"""tdlint — AST-level linter for this control plane's concurrency invariants.

Generic linters check style; the bugs that actually corrupt this system are
project-specific: a share-ledger write outside the scheduler lock, an intent
journal entry whose `done()` is skipped on one control-flow exit, a step name
the boot reconciler silently skips, backend I/O performed while a scheduler
lock is held. Each of those is a *named rule* here (tools/tdlint/rules.py),
checked lexically over the AST — the direct analog of `go vet` for the Go
reference repo, which this Python rebuild never had.

Intentional exceptions are annotated in the source with a pragma the linter
honors and counts:

    # tdlint: disable=<rule>[,<rule>...] [-- free-text reason]

placed on the offending line, the line above it, or a function's `def` line
(which suppresses the rule for the whole function). A pragma that suppresses
nothing is reported as stale, and `--stale-strict` (used by `make lint`)
turns that into a failure — a dead annotation documents a contract the
code no longer has.

Run: `python -m tools.tdlint` (from the repo root; `make lint` wraps it).
Exit status 0 = clean, 1 = violations.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Optional

__all__ = ["Violation", "FileCtx", "run", "lint_paths", "DEFAULT_SCOPE"]

_PRAGMA_RE = re.compile(
    r"#\s*tdlint:\s*disable=([A-Za-z0-9_,\- ]+?)(?:\s*--.*)?$")


@dataclass
class Violation:
    path: str          # repo-relative
    line: int
    rule: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class Pragma:
    line: int
    rules: set[str]
    used: int = 0      # violations this pragma suppressed


@dataclass
class FileCtx:
    """One parsed source file plus its pragma map and function spans."""
    path: str                      # absolute
    rel: str                       # repo-relative, '/'-separated
    text: str
    tree: ast.AST
    pragmas: list[Pragma] = field(default_factory=list)
    # (start_line, end_line, header_lines) per function; header_lines is
    # the def line plus the contiguous comment block directly above it, so
    # a pragma in a function's leading comment governs the whole function
    func_spans: list[tuple[int, int, frozenset]] = field(default_factory=list)

    @classmethod
    def load(cls, path: str, root: str) -> Optional["FileCtx"]:
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path, "r", encoding="utf-8") as f:
                text = f.read()
            tree = ast.parse(text, filename=rel)
        except (OSError, SyntaxError):
            return None
        ctx = cls(path=path, rel=rel, text=text, tree=tree)
        for i, line in enumerate(text.splitlines(), 1):
            m = _PRAGMA_RE.search(line)
            if m:
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                ctx.pragmas.append(Pragma(line=i, rules=rules))
        src_lines = text.splitlines()
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                header = {node.lineno}
                i = node.lineno - 1
                while i >= 1 and src_lines[i - 1].lstrip().startswith("#"):
                    header.add(i)
                    i -= 1
                ctx.func_spans.append(
                    (node.lineno, node.end_lineno or node.lineno,
                     frozenset(header)))
        return ctx

    def suppressed(self, v: Violation) -> bool:
        """A pragma covers a violation when it sits on the violating line,
        the line above it, or in the header (def/class line + contiguous
        leading comment block) of an enclosing function or class."""
        header_lines: set = set()
        for s, e, header in self.func_spans:
            if s <= v.line <= e:
                header_lines |= header
        for p in self.pragmas:
            if v.rule not in p.rules:
                continue
            if p.line in (v.line, v.line - 1) or p.line in header_lines:
                p.used += 1
                return True
        return False


# Files the rules reason about: the concurrent control plane. Workload
# runtimes (workloads/, models/, train/serve), the process supervisor
# (backend/process.py, warmpool.py — child-script generators and a
# supervisor loop with its own never-die error policy), and tests are out
# of scope by design (documented in docs/correctness.md).
DEFAULT_SCOPE = (
    "gpu_docker_api_tpu/schedulers/",
    "gpu_docker_api_tpu/services/",
    "gpu_docker_api_tpu/store/",
    "gpu_docker_api_tpu/server/",
    "gpu_docker_api_tpu/backend/guard.py",
    "gpu_docker_api_tpu/backend/base.py",
    "gpu_docker_api_tpu/reconcile.py",
    "gpu_docker_api_tpu/gateway.py",
    "gpu_docker_api_tpu/intents.py",
    "gpu_docker_api_tpu/idempotency.py",
    "gpu_docker_api_tpu/health.py",
    "gpu_docker_api_tpu/regulator.py",
    "gpu_docker_api_tpu/workqueue.py",
    "gpu_docker_api_tpu/events.py",
    "gpu_docker_api_tpu/obs/",
    "gpu_docker_api_tpu/version.py",
    "gpu_docker_api_tpu/xerrors.py",
)


def _in_scope(rel: str, scope: tuple[str, ...]) -> bool:
    return any(rel == s or rel.startswith(s) for s in scope)


def collect_files(root: str, scope: tuple[str, ...] = DEFAULT_SCOPE,
                  ) -> list[FileCtx]:
    ctxs = []
    for prefix in scope:
        path = os.path.join(root, prefix)
        if os.path.isfile(path):
            ctx = FileCtx.load(path, root)
            if ctx is not None:
                ctxs.append(ctx)
        elif os.path.isdir(path):
            for dirpath, _dirs, files in os.walk(path):
                for name in sorted(files):
                    if not name.endswith(".py"):
                        continue
                    ctx = FileCtx.load(os.path.join(dirpath, name), root)
                    if ctx is not None:
                        ctxs.append(ctx)
    ctxs.sort(key=lambda c: c.rel)
    return ctxs


def run(root: str, scope: tuple[str, ...] = DEFAULT_SCOPE,
        rules: Optional[list[str]] = None) -> dict:
    """Lint the repo at `root`. Returns a report dict:
    {"violations": [Violation], "pragmas": {"total": N, "used": N,
    "stale": [(rel, line, rules)]}, "files": N}."""
    from . import rules as rule_mod
    ctxs = collect_files(root, scope)
    active = rule_mod.all_rules(rules)
    violations: list[Violation] = []
    by_rel = {c.rel: c for c in ctxs}
    for rule in active:
        for v in rule.check_repo(root, ctxs):
            ctx = by_rel.get(v.path)
            if ctx is not None and ctx.suppressed(v):
                continue
            violations.append(v)
    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    total = used = 0
    stale = []
    active_names = {r.name for r in active}
    all_names = {r.name for r in rule_mod.RULES}
    for ctx in ctxs:
        for p in ctx.pragmas:
            total += 1
            if p.used:
                used += 1
            elif p.rules <= active_names or (p.rules - all_names):
                # unused is only evidence of staleness when every rule the
                # pragma names actually RAN (a --rules subset must not
                # call the other rules' load-bearing pragmas stale);
                # misspelled rule names are always reported
                stale.append((ctx.rel, p.line, sorted(p.rules)))
    return {
        "violations": violations,
        "pragmas": {"total": total, "used": used, "stale": stale},
        "files": len(ctxs),
        "rules": [r.name for r in active],
    }


def lint_paths(paths: list[str], root: str,
               rules: Optional[list[str]] = None) -> dict:
    """Lint explicit files (the fixture-test entry point): every per-file
    rule runs regardless of the default scope."""
    from . import rules as rule_mod
    ctxs = [c for c in (FileCtx.load(p, root) for p in paths)
            if c is not None]
    active = rule_mod.all_rules(rules)
    violations: list[Violation] = []
    by_rel = {c.rel: c for c in ctxs}
    for rule in active:
        for v in rule.check_files(ctxs, scoped=False):
            ctx = by_rel.get(v.path)
            if ctx is not None and ctx.suppressed(v):
                continue
            violations.append(v)
    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return {"violations": violations, "files": len(ctxs)}
