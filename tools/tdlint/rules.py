"""tdlint rules — each encodes one invariant of this control plane.

| rule            | invariant                                                  |
|-----------------|------------------------------------------------------------|
| unlocked-state  | scheduler/ledger/MVCC/regulator state is only touched under |
|                 | its owning lock; cross-object scheduler state goes through  |
|                 | locked snapshot accessors                                   |
| intent-lifecycle| every intents.begin() reaches done() on all exits           |
| unknown-step    | every journaled op/step name is in the reconciler registry  |
| io-under-lock   | no backend/store I/O while a scheduler/service lock is held |
| unmapped-xerror | every xerrors class maps to an app code; every code used is |
|                 | documented in the generated OpenAPI                         |
| silent-swallow  | no `except Exception` swallows a failure without log/event  |
| untraced-op     | every events.record() op literal and every tdapi_* metric   |
|                 | family name is registered in obs/names.py — telemetry names |
|                 | are API, not scattered string literals                      |
| seqlock-        | nothing that can block (backend op, WAL-backed store write, |
|  discipline     | sleep, open, fsync, futex wait, logging) runs inside the    |
|                 | seqlock publish window — readers spin for its whole length  |
| claim-order     | per-worker claim-ledger writes follow the global fetch_add  |
|                 | (and ledger undo precedes the global release) — the order   |
|                 | that makes a worker SIGKILL under-admit, never double-admit |
| atomic-region   | counter-region words are only ever touched through the      |
|                 | atomic ops, never raw buffer writes via the seqlock-        |
|                 | protected config path                                       |

All checks are lexical (AST). That is deliberately conservative: code that
needs a lock held by its CALLER (e.g. MVCCStore._apply_put) carries a
`# tdlint: disable=unlocked-state` pragma on its def line stating the
contract — the annotation is the documentation.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Iterable, Optional

from . import FileCtx, Violation

# ---------------------------------------------------------------- shared

#: attributes guarded by a lock somewhere in the control plane
GUARDED_ATTRS = frozenset({
    # schedulers (base._lock): chip/core/port ownership + share ledger
    "status", "shares", "cordoned", "used",
    # store/mvcc.py (_lock / _commit_cond)
    "_log", "_rev", "_compacted", "_durable_seq", "_flushing",
    # regulator.py (_cond)
    "_tenants", "_holder", "_global_vt", "vt", "waiting", "yield_flag",
    # idempotency.py (_lock)
    "_claims", "_count", "_replays",
    # regulator module registry (_LOCK)
    "_REGULATORS",
})

#: attributes that ARE locks — `with <x>.<attr>:` marks a guarded region
LOCK_ATTRS = frozenset({
    "_lock", "_cond", "_commit_cond", "_guard", "_name_locks_guard",
    "_dropped_lock", "_stats_lock", "_conns_lock", "_reconcile_lock",
})
#: module-level lock names (regulator._LOCK)
LOCK_NAMES = frozenset({"_LOCK"})

#: contextmanager METHODS that acquire the owning lock for their body —
#: `with <x>._granting(...):` is a guarded region exactly like
#: `with <x>._lock:` (schedulers/tpu.py wraps the lock to observe grant
#: latency after release, keeping histogram work out of the hot section)
LOCK_WRAPPER_METHODS = frozenset({"_granting"})

#: cross-object scheduler state: accessing these on anything but `self`
#: must go through a locked snapshot accessor (owners()/shares_snapshot()/
#: cordoned_snapshot()) — reading another object's raw dict races its
#: writers (dict-changed-size mid-iteration, torn multi-key reads)
XOBJ_ATTRS = frozenset({"status", "shares", "cordoned", "used"})

MUTATING_METHODS = frozenset({
    "update", "pop", "append", "clear", "setdefault", "add", "remove",
    "discard", "difference_update", "extend", "insert", "popitem",
})


def _with_locks(node: ast.With) -> bool:
    for item in node.items:
        e = item.context_expr
        if isinstance(e, ast.Attribute) and e.attr in LOCK_ATTRS:
            return True
        if isinstance(e, ast.Name) and e.id in LOCK_NAMES:
            return True
        if isinstance(e, ast.Call) and isinstance(e.func, ast.Attribute) \
                and e.func.attr in LOCK_WRAPPER_METHODS:
            return True
    return False


def _guarded_target(node: ast.AST) -> Optional[str]:
    """The guarded attr a store-target mutates, if any: `x.status`,
    `x.status[i]`, `x.shares[i][o]` ..."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and node.attr in GUARDED_ATTRS:
        return node.attr
    return None


def _is_self(node: ast.AST) -> bool:
    return isinstance(node, ast.Name) and node.id == "self"


class Rule:
    name = ""
    description = ""
    #: rel-path predicate; None = every scoped file
    def applies(self, rel: str) -> bool:
        return True

    def check_file(self, ctx: FileCtx) -> Iterable[Violation]:
        return ()

    def check_files(self, ctxs: list[FileCtx],
                    scoped: bool = True) -> list[Violation]:
        out: list[Violation] = []
        for ctx in ctxs:
            if scoped and not self.applies(ctx.rel):
                continue
            out.extend(self.check_file(ctx))
        return out

    def check_repo(self, root: str, ctxs: list[FileCtx]) -> list[Violation]:
        return self.check_files(ctxs, scoped=True)


# ---------------------------------------------------------- unlocked-state

class UnlockedState(Rule):
    name = "unlocked-state"
    description = ("guarded state (scheduler bitmaps, share ledger, MVCC "
                   "internals, regulator queue) mutated outside its lock, "
                   "or another object's scheduler state accessed raw")

    def check_file(self, ctx: FileCtx) -> Iterable[Violation]:
        out: list[Violation] = []

        def visit(node: ast.AST, under: bool, in_init: bool) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a nested def/lambda runs later, outside this lock scope
                init = node.name == "__init__"
                for child in node.body:
                    visit(child, False, init)
                return
            if isinstance(node, ast.Lambda):
                return
            if isinstance(node, ast.With):
                under_here = under or _with_locks(node)
                for item in node.items:
                    visit(item.context_expr, under, in_init)
                for child in node.body:
                    visit(child, under_here, in_init)
                return
            if not in_init:
                self._check_node(ctx, node, under, out)
            for child in ast.iter_child_nodes(node):
                visit(child, under, in_init)

        for top in ast.iter_child_nodes(ctx.tree):
            visit(top, False, False)
        return out

    def _check_node(self, ctx: FileCtx, node: ast.AST, under: bool,
                    out: list[Violation]) -> None:
        targets: list[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        elif isinstance(node, ast.Delete):
            targets = list(node.targets)
        for t in targets:
            attr = _guarded_target(t)
            if attr and not under:
                out.append(Violation(
                    ctx.rel, t.lineno, self.name,
                    f"mutation of guarded state '.{attr}' outside its "
                    f"owning lock"))
        if isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Attribute) and f.attr in MUTATING_METHODS):
                attr = _guarded_target(f.value)
                if attr and not under:
                    out.append(Violation(
                        ctx.rel, node.lineno, self.name,
                        f"mutating call '.{attr}.{f.attr}()' outside the "
                        f"owning lock"))
        # cross-object raw access (read OR write): x.tpu.status,
        # x.ports.used. Deliberately NOT gated on `under`: holding your
        # OWN lock never makes another object's state safe to read — the
        # pre-fix health.py probe held the monitor lock while reading
        # tpu.cordoned raw, exactly this bug class
        if (isinstance(node, ast.Attribute) and node.attr in XOBJ_ATTRS
                and not _is_self(node.value)):
            # plain locals named e.g. `status` aliasing a snapshot are fine;
            # only attribute chains reaching INTO another object count
            if isinstance(node.value, ast.Attribute):
                out.append(Violation(
                    ctx.rel, node.lineno, self.name,
                    f"raw access to another object's guarded state "
                    f"'.{node.attr}' — use a locked snapshot accessor "
                    f"(owners()/shares_snapshot()/cordoned_snapshot())"))


# -------------------------------------------------------- intent-lifecycle

def _is_intents_begin(call: ast.Call) -> bool:
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr == "begin"):
        return False
    v = f.value
    if isinstance(v, ast.Attribute):
        return v.attr in ("intents", "journal")
    if isinstance(v, ast.Name):
        return v.id in ("intents", "journal")
    return False


class IntentLifecycle(Rule):
    name = "intent-lifecycle"
    description = ("a function that opens an intent (intents.begin) must "
                   "close it on every exit: done() in an exception handler "
                   "AND on the success path")

    def applies(self, rel: str) -> bool:
        return "/services/" in rel or rel.endswith("app.py")

    def check_file(self, ctx: FileCtx) -> Iterable[Violation]:
        out: list[Violation] = []
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            begins: list[tuple[str, int]] = []
            for node in ast.walk(fn):
                if (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)
                        and _is_intents_begin(node.value)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    begins.append((node.targets[0].id, node.lineno))
            for name, line in begins:
                in_except, on_success = self._done_paths(fn, name)
                if not (in_except and on_success):
                    missing = []
                    if not in_except:
                        missing.append("an exception handler")
                    if not on_success:
                        missing.append("the success path")
                    out.append(Violation(
                        ctx.rel, line, self.name,
                        f"intent '{name}' opened here has no done() on "
                        f"{' or '.join(missing)} — a failure would leave "
                        f"the journal entry open forever"))
        return out

    @staticmethod
    def _done_paths(fn: ast.AST, name: str) -> tuple[bool, bool]:
        in_except = on_success = False

        def visit(node: ast.AST, inside_handler: bool) -> None:
            nonlocal in_except, on_success
            if isinstance(node, ast.Call):
                f = node.func
                if (isinstance(f, ast.Attribute) and f.attr == "done"
                        and isinstance(f.value, ast.Name)
                        and f.value.id == name):
                    if inside_handler:
                        in_except = True
                    else:
                        on_success = True
            for child in ast.iter_child_nodes(node):
                visit(child, inside_handler
                      or isinstance(node, ast.ExceptHandler))

        visit(fn, False)
        return in_except, on_success


# ------------------------------------------------------------ unknown-step

class UnknownStep(Rule):
    name = "unknown-step"
    description = ("every intents.begin() op and intent.step() name must be "
                   "registered in the reconciler (CONSULTED_STEPS / "
                   "INFORMATIONAL_STEPS / the _replay_intent handler table) "
                   "— an unknown one is silently skipped at boot")

    def applies(self, rel: str) -> bool:
        return ("/services/" in rel or rel.endswith("reconcile.py")
                or rel.endswith("intents.py"))

    def check_files(self, ctxs: list[FileCtx],
                    scoped: bool = True) -> list[Violation]:
        known_steps, known_ops = self._registry(ctxs)
        if known_steps is None and known_ops is None:
            return []   # no reconciler in this file set — nothing to check
        out: list[Violation] = []
        for ctx in ctxs:
            if scoped and not self.applies(ctx.rel):
                continue
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if not isinstance(f, ast.Attribute) or not node.args:
                    continue
                arg = node.args[0]
                if not (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)):
                    continue
                if (f.attr == "step" and isinstance(f.value, ast.Name)
                        and f.value.id.startswith("intent")
                        and known_steps is not None
                        and arg.value not in known_steps):
                    out.append(Violation(
                        ctx.rel, node.lineno, self.name,
                        f"step {arg.value!r} is not in the reconciler's "
                        f"step registry (reconcile.KNOWN_STEPS) — it would "
                        f"be silently ignored at boot"))
                if (f.attr == "begin" and _is_intents_begin(node)
                        and known_ops is not None
                        and arg.value not in known_ops):
                    out.append(Violation(
                        ctx.rel, node.lineno, self.name,
                        f"intent op {arg.value!r} has no handler in the "
                        f"reconciler's _replay_intent table — a crash "
                        f"mid-operation would not be replayed"))
        return out

    @staticmethod
    def _registry(ctxs: list[FileCtx]):
        """(known_steps, known_ops) from the reconciler module in `ctxs`:
        the CONSULTED_STEPS/INFORMATIONAL_STEPS set literals plus the dict
        keys of the handler table inside _replay_intent."""
        steps: Optional[set] = None
        ops: Optional[set] = None
        for ctx in ctxs:
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if (isinstance(t, ast.Name) and t.id in
                                ("CONSULTED_STEPS", "INFORMATIONAL_STEPS")):
                            vals = UnknownStep._str_elts(node.value)
                            if vals is not None:
                                steps = (steps or set()) | vals
                if (isinstance(node, ast.FunctionDef)
                        and node.name == "_replay_intent"):
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Dict):
                            keys = {k.value for k in sub.keys
                                    if isinstance(k, ast.Constant)
                                    and isinstance(k.value, str)}
                            if keys:
                                ops = (ops or set()) | keys
        return steps, ops

    @staticmethod
    def _str_elts(node: ast.AST) -> Optional[set]:
        if isinstance(node, ast.Call) and node.args:   # frozenset({...})
            node = node.args[0]
        if isinstance(node, (ast.Set, ast.List, ast.Tuple)):
            return {e.value for e in node.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)}
        return None


# ----------------------------------------------------------- io-under-lock

#: store methods that hit the WAL (writes); reads are in-memory and fine
STORE_WRITE_METHODS = frozenset({
    "put", "delete", "put_entity_version", "delete_entity_version",
    "delete_entity_versions", "compact", "maintain",
})
STORE_RECEIVERS = frozenset({"client", "_client"})


class IoUnderLock(Rule):
    name = "io-under-lock"
    description = ("blocking backend/store I/O (backend ops, WAL-backed "
                   "store writes, sleeps, file opens) inside a `with "
                   "<lock>:` block — holding a hot lock across I/O "
                   "serializes every other writer behind the disk/substrate")

    def applies(self, rel: str) -> bool:
        # the MVCC store IS the I/O layer: its WAL writes under its own
        # lock are the group-commit design, not a smell
        if rel.endswith(("store/mvcc.py", "store/native.py")):
            return False
        return True

    def check_file(self, ctx: FileCtx) -> Iterable[Violation]:
        out: list[Violation] = []

        def visit(node: ast.AST, under: bool) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                body = node.body if not isinstance(node, ast.Lambda) else []
                for child in body:
                    visit(child, False)   # runs later / other thread
                return
            if isinstance(node, ast.With):
                # items acquire left to right: item i's context expr runs
                # BEFORE its own lock is taken but AFTER items 0..i-1's —
                # `with open(p) as f, self._lock:` must not flag the open,
                # `with self._lock, open(p) as f:` must
                running = under
                for item in node.items:
                    visit(item.context_expr, running)
                    e = item.context_expr
                    if ((isinstance(e, ast.Attribute)
                         and e.attr in LOCK_ATTRS)
                            or (isinstance(e, ast.Name)
                                and e.id in LOCK_NAMES)):
                        running = True
                for child in node.body:
                    visit(child, running)
                return
            if under and isinstance(node, ast.Call):
                what = self._blocking_call(node)
                if what:
                    out.append(Violation(
                        ctx.rel, node.lineno, self.name,
                        f"{what} while holding a lock"))
            for child in ast.iter_child_nodes(node):
                visit(child, under)

        for top in ast.iter_child_nodes(ctx.tree):
            visit(top, False)
        return out

    @staticmethod
    def _blocking_call(node: ast.Call) -> Optional[str]:
        f = node.func
        if isinstance(f, ast.Attribute):
            v = f.value
            if isinstance(v, ast.Attribute) and v.attr == "backend":
                return f"backend op '.backend.{f.attr}()'"
            if (isinstance(v, ast.Attribute) and v.attr in STORE_RECEIVERS
                    and f.attr in STORE_WRITE_METHODS):
                return f"store write '.{v.attr}.{f.attr}()'"
            if (isinstance(v, ast.Name) and v.id == "time"
                    and f.attr == "sleep"):
                return "time.sleep()"
            if (isinstance(v, ast.Name) and v.id == "os"
                    and f.attr in ("fsync", "replace")):
                return f"os.{f.attr}()"
        if isinstance(f, ast.Name) and f.id == "open":
            return "open()"
        return None


# --------------------------------------------------------- unmapped-xerror

class UnmappedXerror(Rule):
    name = "unmapped-xerror"
    description = ("every xerrors class must be explicitly caught in the "
                   "route layer (server/app.py) so it maps to a stable app "
                   "code; every code used must appear in the generated "
                   "OpenAPI document")

    def applies(self, rel: str) -> bool:
        return rel.endswith(("xerrors.py", "app.py", "codes.py"))

    def check_files(self, ctxs: list[FileCtx],
                    scoped: bool = True) -> list[Violation]:
        xerr = next((c for c in ctxs if c.rel.endswith("xerrors.py")), None)
        apps = [c for c in ctxs if c.rel.endswith("app.py")]
        if xerr is None or not apps:
            return []
        handled: set[str] = set()
        for app in apps:
            for node in ast.walk(app.tree):
                if isinstance(node, ast.ExceptHandler) and node.type:
                    for t in ([node.type] if not isinstance(node.type, ast.Tuple)
                              else list(node.type.elts)):
                        if isinstance(t, ast.Attribute):
                            handled.add(t.attr)
                        elif isinstance(t, ast.Name):
                            handled.add(t.id)
        out: list[Violation] = []
        for node in xerr.tree.body:
            if not isinstance(node, ast.ClassDef) or not node.bases:
                continue
            if node.name == "XError" or not node.name.endswith("Error"):
                continue
            if node.name not in handled:
                out.append(Violation(
                    xerr.rel, node.lineno, self.name,
                    f"{node.name} is never caught in the route layer — it "
                    f"falls into the catch-all and surfaces as a generic "
                    f"op-failed code"))
        return out

    def check_repo(self, root: str, ctxs: list[FileCtx]) -> list[Violation]:
        out = self.check_files(ctxs, scoped=True)
        codes = next((c for c in ctxs if c.rel.endswith("server/codes.py")),
                     None)
        spec_path = os.path.join(root, "api", "openapi.json")
        if codes is None or not os.path.exists(spec_path):
            return out
        try:
            with open(spec_path, "r", encoding="utf-8") as f:
                spec_text = json.dumps(json.load(f))
        except (OSError, json.JSONDecodeError):
            return out
        for node in ast.walk(codes.tree):
            if not isinstance(node, ast.ClassDef) or node.name != "ResCode":
                continue
            for stmt in node.body:
                if (isinstance(stmt, ast.Assign)
                        and isinstance(stmt.value, ast.Constant)
                        and isinstance(stmt.value.value, int)
                        and isinstance(stmt.targets[0], ast.Name)):
                    name = stmt.targets[0].id
                    value = stmt.value.value
                    if f"{value} {name}" not in spec_text:
                        out.append(Violation(
                            codes.rel, stmt.lineno, self.name,
                            f"app code {value} ({name}) is not documented "
                            f"in api/openapi.json — regenerate with `make "
                            f"apidoc`"))
        return out


# ---------------------------------------------------------- silent-swallow

LOGGING_METHODS = frozenset({
    "exception", "warning", "error", "info", "debug", "critical", "log",
    "record",   # events.record
})


class SilentSwallow(Rule):
    name = "silent-swallow"
    description = ("`except Exception` (or bare except) whose body neither "
                   "re-raises nor logs nor emits an event — a mutation-path "
                   "failure disappears without a trace")

    def check_file(self, ctx: FileCtx) -> Iterable[Violation]:
        out: list[Violation] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node.type):
                continue
            if not self._body_surfaces(node):
                out.append(Violation(
                    ctx.rel, node.lineno, self.name,
                    "broad except swallows the failure silently — raise, "
                    "log.exception(), or events.record() it"))
        return out

    @staticmethod
    def _is_broad(t: Optional[ast.AST]) -> bool:
        if t is None:
            return True     # bare except
        names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
        for n in names:
            if isinstance(n, ast.Name) and n.id == "Exception":
                return True
            if isinstance(n, ast.Attribute) and n.attr == "Exception":
                return True
        return False

    @staticmethod
    def _body_surfaces(handler: ast.ExceptHandler) -> bool:
        for node in ast.walk(handler):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr in LOGGING_METHODS:
                    return True
        return False


# ------------------------------------------------------------- untraced-op

#: registry-method and constructor names that declare a metric family
METRIC_FACTORY_METHODS = frozenset({"counter", "gauge", "histogram"})
METRIC_CLASS_NAMES = frozenset({"Counter", "Gauge", "Histogram"})
#: event-log receivers: `events.record(...)`, `self.events.record(...)`,
#: `self._events.record(...)` (workqueue holds a private handle)
EVENT_RECEIVERS = frozenset({"events", "_events"})


class UntracedOp(Rule):
    name = "untraced-op"
    description = ("every events.record() op string literal and every "
                   "tdapi_* metric family name must be registered in "
                   "obs/names.py (EVENT_OPS / METRIC_NAMES) — dashboards "
                   "and grep pipelines treat telemetry names as API, so an "
                   "ad-hoc literal is an undocumented API surface")

    def check_files(self, ctxs: list[FileCtx],
                    scoped: bool = True) -> list[Violation]:
        event_ops, metric_names = self._catalog(ctxs)
        if event_ops is None and metric_names is None:
            return []   # no catalog in this file set — nothing to check
        out: list[Violation] = []
        for ctx in ctxs:
            if scoped and not self.applies(ctx.rel):
                continue
            if ctx.rel.endswith("obs/names.py"):
                continue   # the catalog itself is not a call site
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                # the name may arrive positionally or as a keyword —
                # events.record(op=f"{m} {p}") is the http.py idiom, so
                # the keyword form must not bypass the catalog gate
                pos = node.args[0] if node.args else None
                kws = {k.arg: k.value for k in node.keywords if k.arg}
                arg = pos if pos is not None else \
                    kws.get("op", kws.get("name"))
                if not (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)):
                    continue   # computed ops (f"{method} {path}",
                               # f"breaker.{state}") are skipped by design
                f = node.func
                if (event_ops is not None and self._is_events_record(f)
                        and arg.value not in event_ops):
                    out.append(Violation(
                        ctx.rel, node.lineno, self.name,
                        f"event op {arg.value!r} is not registered in the "
                        f"telemetry catalog (obs/names.py EVENT_OPS) — "
                        f"register it or reuse a registered op"))
                if (metric_names is not None
                        and arg.value.startswith("tdapi_")
                        and self._is_metric_decl(f)
                        and arg.value not in metric_names):
                    out.append(Violation(
                        ctx.rel, node.lineno, self.name,
                        f"metric family {arg.value!r} is not registered in "
                        f"the telemetry catalog (obs/names.py METRIC_NAMES) "
                        f"— register it or reuse a registered family"))
        return out

    @staticmethod
    def _is_events_record(f: ast.AST) -> bool:
        if not (isinstance(f, ast.Attribute) and f.attr == "record"):
            return False
        v = f.value
        if isinstance(v, ast.Attribute):
            return v.attr in EVENT_RECEIVERS
        if isinstance(v, ast.Name):
            return v.id in EVENT_RECEIVERS
        return False

    @staticmethod
    def _is_metric_decl(f: ast.AST) -> bool:
        if isinstance(f, ast.Attribute):
            return (f.attr in METRIC_FACTORY_METHODS
                    or f.attr in METRIC_CLASS_NAMES)
        if isinstance(f, ast.Name):
            return f.id in METRIC_CLASS_NAMES
        return False

    @staticmethod
    def _catalog(ctxs: list[FileCtx]):
        """(event_ops, metric_names) from whichever file in `ctxs` assigns
        the EVENT_OPS / METRIC_NAMES set literals (obs/names.py in repo
        runs; any catalog-bearing fixture in tests)."""
        ops: Optional[set] = None
        metrics: Optional[set] = None
        for ctx in ctxs:
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Assign):
                    continue
                for t in node.targets:
                    if not isinstance(t, ast.Name):
                        continue
                    if t.id == "EVENT_OPS":
                        vals = UnknownStep._str_elts(node.value)
                        if vals is not None:
                            ops = (ops or set()) | vals
                    elif t.id == "METRIC_NAMES":
                        vals = UnknownStep._str_elts(node.value)
                        if vals is not None:
                            metrics = (metrics or set()) | vals
        return ops, metrics


# ------------------------------------------------- shm-protocol rules
#
# PR 13's cross-process protocols (server/workers.py) put router state
# beyond both the GIL and every in-process lock tdlint's older rules
# reason about. These three rules encode the shm segment's discipline
# lexically, the same way unlocked-state encodes the lock discipline;
# tdcheck (tools/tdcheck) is the dynamic half of the same defense.
# PR 15's metric shards (obs/shm_metrics.py) are a second segment under
# the SAME discipline: seqlock-discipline and atomic-region cover both.

#: the shm-segment modules the lexical shm rules reason about
SHM_MODULES = ("server/workers.py", "obs/shm_metrics.py")

#: offset-helper names addressing the lock-free COUNTER region — cells
#: that must only ever be touched through the atomic ops. The _sh_*
#: helpers address the metric-shard segment's counter/histogram words
#: (obs/shm_metrics.py); its recorder-ring payload helpers
#: (_sh_ring_slot_off) are deliberately NOT here — ring payload bytes
#: are raw-written by contract (torn entries are skippable).
COUNTER_OFF_HELPERS = frozenset({
    "_gw_cnt_off", "_rep_cnt_off", "_wk_claim_off", "_wk_queued_off",
    "_wk_off",
    "_sh_gw_off", "_sh_cnt_off", "_sh_lat_off", "_sh_qw_off",
    # PR 18 KV-affinity sketch cells (gen|occ|sketch words): written
    # ONLY through the native shm_cells_publish CAS path — a raw-buffer
    # write here is exactly the racy store atomic-region exists to catch
    "_rep_kv_off",
    # PR 19 latency-digest cells (gen|count|ewma_us|p95_us): same
    # contract — every access goes through publish_replica_lat /
    # read_replica_lat over the CAS path, never a raw buffer store
    "_rep_lat_off",
})
COUNTER_OFF_NAMES = frozenset({"CNT_OFF", "WK_OFF", "SH_CNT_OFF"})
#: the seqlock epoch word: a named offset constant (workers.py roster
#: epoch) or a per-slot epoch-offset helper (shm_metrics.py per-gateway
#: shard epochs)
EPOCH_NAME = "HDR_OFF_EPOCH"
EPOCH_OFF_HELPERS = frozenset({"_sh_epoch_off"})


def _exact_helper_call(node: ast.AST,
                       aliases: dict[str, str]) -> Optional[str]:
    """The offset-helper a call expression (or a one-step variable alias
    of one) resolves to, if any. Deliberately EXACT: `_rep_cnt_off(g, r)
    + 8` (the errors cell) is arithmetic on a helper, not the inflight
    cell itself, and is not matched."""
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            and node.func.id in COUNTER_OFF_HELPERS):
        return node.func.id
    if isinstance(node, ast.Name):
        return aliases.get(node.id)
    return None


def _offset_aliases(fn: ast.AST) -> dict[str, str]:
    """name -> helper for simple `x = _helper(...)` assignments in fn."""
    aliases: dict[str, str] = {}
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Name)
                and node.value.func.id in COUNTER_OFF_HELPERS):
            aliases[node.targets[0].id] = node.value.func.id
    return aliases


def _mentions_counter_offset(node: ast.AST,
                             aliases: dict[str, str]) -> bool:
    """Whether ANY part of an offset expression reaches into the counter
    region (helpers, region constants, or aliases of either)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and (
                sub.id in COUNTER_OFF_NAMES or sub.id in aliases):
            return True
        if (isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name)
                and sub.func.id in COUNTER_OFF_HELPERS):
            return True
    return False


class SeqlockDiscipline(Rule):
    name = "seqlock-discipline"
    description = ("blocking work (backend op, store write, spool write, "
                   "sleep, open, fsync, futex wait, logging) inside the "
                   "seqlock publish window — every reader spins for the "
                   "window's whole duration, and a crash inside it parks "
                   "the epoch odd")

    def applies(self, rel: str) -> bool:
        return rel.endswith(SHM_MODULES)

    @staticmethod
    def _is_epoch_store(node: ast.AST) -> bool:
        """`<x>.store(HDR_OFF_EPOCH, ...)` or
        `<x>.store(_sh_epoch_off(g), ...)` — a window's closing store."""
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "store" and node.args):
            return False
        arg = node.args[0]
        if isinstance(arg, ast.Name) and arg.id == EPOCH_NAME:
            return True
        return (isinstance(arg, ast.Call)
                and isinstance(arg.func, ast.Name)
                and arg.func.id in EPOCH_OFF_HELPERS)

    def check_file(self, ctx: FileCtx) -> Iterable[Violation]:
        out: list[Violation] = []
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for node in ast.walk(fn):
                # the publish window is the try-block whose FINALLY
                # closes the epoch (stores to HDR_OFF_EPOCH)
                if not isinstance(node, ast.Try) or not node.finalbody:
                    continue
                closes = any(self._is_epoch_store(sub)
                             for stmt in node.finalbody
                             for sub in ast.walk(stmt))
                if not closes:
                    continue
                for stmt in node.body:
                    for sub in ast.walk(stmt):
                        if not isinstance(sub, ast.Call):
                            continue
                        what = self._blocking_in_window(sub)
                        if what:
                            out.append(Violation(
                                ctx.rel, sub.lineno, self.name,
                                f"{what} inside the seqlock publish "
                                f"window — readers spin (and a crash "
                                f"here parks the epoch odd) for its "
                                f"whole duration"))
        return out

    @staticmethod
    def _blocking_in_window(node: ast.Call) -> Optional[str]:
        what = IoUnderLock._blocking_call(node)
        if what:
            return what
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in ("futex_wait", "wait"):
                return f"blocking '.{f.attr}()'"
            if f.attr in ("write", "flush"):
                # spooling/telemetry file I/O (RotatingWriter.write,
                # SpanSpool flushes, raw file handles): a disk stall
                # inside the window stalls every reader with it
                return f"spool/file I/O '.{f.attr}()'"
            if f.attr == "ring_note":
                # recorder-ring appends serialize JSON and memcpy the
                # payload — telemetry work that belongs outside the
                # window, like every other spooling write
                return "recorder ring write '.ring_note()'"
            if (isinstance(f.value, ast.Name) and f.value.id == "log"):
                return f"logging call 'log.{f.attr}()'"
        return None


class ClaimOrder(Rule):
    name = "claim-order"
    description = ("per-worker claim-ledger writes must FOLLOW the global "
                   "fetch_add (and ledger undo must precede the global "
                   "release): the order that makes a worker SIGKILL "
                   "between the two under-admit briefly instead of ever "
                   "double-admitting")

    def applies(self, rel: str) -> bool:
        return rel.endswith("server/workers.py")

    def check_file(self, ctx: FileCtx) -> Iterable[Violation]:
        out: list[Violation] = []
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            aliases = _offset_aliases(fn)
            ops: list[tuple[int, str, str]] = []   # (line, cell, op)
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.args):
                    continue
                meth = node.func.attr
                if meth == "add":
                    op = "add"
                elif meth == "dec_floor0":
                    op = "dec"
                elif (meth == "store" and len(node.args) >= 2
                      and isinstance(node.args[1], ast.Constant)
                      and node.args[1].value == 0):
                    op = "zero"
                else:
                    continue
                helper = _exact_helper_call(node.args[0], aliases)
                if helper == "_wk_claim_off":
                    ops.append((node.lineno, "ledger", op))
                elif helper == "_rep_cnt_off":
                    ops.append((node.lineno, "global", op))
            for line, cell, op in ops:
                if cell != "ledger":
                    continue
                if op == "add" and not any(
                        c == "global" and o == "add" and ln < line
                        for ln, c, o in ops):
                    out.append(Violation(
                        ctx.rel, line, self.name,
                        "claims-ledger increment with no earlier global "
                        "fetch_add in this function — a SIGKILL between "
                        "the two would make reconcile free capacity that "
                        "was never claimed (double-admit)"))
                elif op == "dec" and not any(
                        c == "global" and o in ("dec", "zero") and ln > line
                        for ln, c, o in ops):
                    out.append(Violation(
                        ctx.rel, line, self.name,
                        "claims-ledger undo with no later global release "
                        "in this function — the undo must come FIRST so "
                        "a SIGKILL between the two under-admits instead "
                        "of double-freeing at reconcile"))
                elif op == "zero" and not any(
                        c == "global" and o in ("dec", "zero")
                        for ln, c, o in ops):
                    out.append(Violation(
                        ctx.rel, line, self.name,
                        "claims-ledger cell zeroed without the matching "
                        "global counter accounting in this function"))
        return out


class AtomicRegion(Rule):
    name = "atomic-region"
    description = ("counter-region words written through a raw buffer "
                   "path (pack_into / slice assignment) instead of the "
                   "atomic ops — a seqlock-path write to a counter word "
                   "is a plain racy store that can wipe concurrent "
                   "fetch_adds")

    def applies(self, rel: str) -> bool:
        return rel.endswith(SHM_MODULES)

    def check_file(self, ctx: FileCtx) -> Iterable[Violation]:
        out: list[Violation] = []
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            aliases = _offset_aliases(fn)
            for node in ast.walk(fn):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "pack_into"
                        and len(node.args) >= 3
                        and _mentions_counter_offset(node.args[2],
                                                     aliases)):
                    out.append(Violation(
                        ctx.rel, node.lineno, self.name,
                        "struct.pack_into targeting a counter-region "
                        "offset — counter words are atomic-ops-only "
                        "(a raw store races concurrent fetch_adds)"))
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if (isinstance(t, ast.Subscript)
                                and self._is_buf(t.value)
                                and _mentions_counter_offset(t.slice,
                                                             aliases)):
                            out.append(Violation(
                                ctx.rel, t.lineno, self.name,
                                "raw buffer slice assignment into the "
                                "counter region — counter words are "
                                "atomic-ops-only"))
        return out

    @staticmethod
    def _is_buf(node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in ("buf", "view")
        if isinstance(node, ast.Attribute):
            return node.attr == "buf"
        return False


# ----------------------------------------------------------------- registry

RULES: list[Rule] = [
    UnlockedState(),
    IntentLifecycle(),
    UnknownStep(),
    IoUnderLock(),
    UnmappedXerror(),
    SilentSwallow(),
    UntracedOp(),
    SeqlockDiscipline(),
    ClaimOrder(),
    AtomicRegion(),
]


def all_rules(names: Optional[list[str]] = None) -> list[Rule]:
    if names is None:
        return list(RULES)
    by_name = {r.name: r for r in RULES}
    unknown = [n for n in names if n not in by_name]
    if unknown:
        raise ValueError(f"unknown rule(s): {unknown} "
                         f"(known: {sorted(by_name)})")
    return [by_name[n] for n in names]
