"""CLI: `python -m tools.tdlint [--root DIR] [--rules a,b]
[--stale-strict] [files...]`.

With no file arguments, lints the control-plane scope (tools.tdlint
DEFAULT_SCOPE) of the repo at --root (default: cwd). With files, lints
exactly those (the seeded-violation fixture path). Exit 1 on violations.
`--stale-strict` also fails on stale pragmas (a pragma that suppresses
nothing is a dead annotation whose stated contract no longer holds) —
only meaningful on full-rule runs; `make lint` uses it.
"""

from __future__ import annotations

import argparse
import sys

from . import DEFAULT_SCOPE, lint_paths, run
from .rules import RULES


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tdlint")
    ap.add_argument("files", nargs="*", help="explicit files to lint")
    ap.add_argument("--root", default=".", help="repo root (default: cwd)")
    ap.add_argument("--rules", default="",
                    help="comma-separated subset of rules to run")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--stale-strict", action="store_true",
                    help="exit nonzero when any pragma is stale")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in RULES:
            print(f"{r.name:18s} {r.description}")
        return 0

    rules = [r.strip() for r in args.rules.split(",") if r.strip()] or None
    if args.files:
        report = lint_paths(args.files, args.root, rules)
    else:
        report = run(args.root, DEFAULT_SCOPE, rules)

    for v in report["violations"]:
        print(v.format())
    n = len(report["violations"])
    pragmas = report.get("pragmas")
    summary = f"tdlint: {n} violation(s) in {report['files']} file(s)"
    stale = []
    if pragmas is not None:
        summary += (f"; {pragmas['total']} pragma(s), "
                    f"{pragmas['used']} honored")
        stale = pragmas["stale"]
        for rel, line, rls in stale:
            print(f"{rel}:{line}: [pragma] stale pragma "
                  f"(suppresses nothing): {','.join(rls)}")
    print(summary)
    if n:
        return 1
    if args.stale_strict and stale:
        print(f"tdlint: --stale-strict: {len(stale)} stale pragma(s)")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
