"""CLI: `python -m tools.tdcheck [--model a,b] [--mode exhaustive|random]
[--schedules N] [--seed N] [--preemptions N] [--replay SCHED]`.

Default: every model, exhaustive within the context bounds. Exit 0 =
every invariant held on every explored schedule; exit 1 prints the
violation with its replayable schedule. `--prove-mutants` instead runs
each checker against its seeded-broken twin and FAILS if any checker
stays silent (the liveness gate `make lint` relies on).

A worker-tier-incapable host (no Linux SO_REUSEPORT / native shm core)
can still check the WAL twin; the shm-backed models report skipped.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tdcheck")
    ap.add_argument("--model",
                    default="seqlock,claim,wal,lease,fedwatch,promote",
                    help="comma-separated subset of: seqlock, claim, wal, "
                    "lease, fedwatch, promote")
    ap.add_argument("--mode", default="exhaustive",
                    choices=["exhaustive", "random"])
    ap.add_argument("--schedules", type=int, default=2000,
                    help="schedule cap (exhaustive) / draw count (random)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--preemptions", type=int, default=2,
                    help="context bound: forced switches per schedule")
    ap.add_argument("--replay", default="",
                    help="replay one schedule (the failure report's "
                    "'k:p,k:p,...' string) against --model")
    ap.add_argument("--variant", default="",
                    help="which sweep pass the schedule came from "
                    "(seqlock: torn|heal; claim: no-kill|kill) — the "
                    "failure report's reproduce line includes it; "
                    "defaults to the kill pass")
    ap.add_argument("--prove-mutants", action="store_true",
                    help="run each checker against its seeded-broken "
                    "twin; fail unless every checker fires")
    args = ap.parse_args(argv)

    from gpu_docker_api_tpu.server import workers

    from .models import MUTANTS, SWEEPS
    from .sched import InvariantViolation, ReplayStrategy, parse_schedule

    names = [m.strip() for m in args.model.split(",") if m.strip()]
    unknown = [m for m in names if m not in SWEEPS]
    if unknown:
        print(f"tdcheck: unknown model(s) {unknown} "
              f"(known: {sorted(SWEEPS)})", file=sys.stderr)
        return 2
    shm_ok = workers.available()

    if args.replay:
        if len(names) != 1:
            print("tdcheck: --replay needs exactly one --model",
                  file=sys.stderr)
            return 2
        from .models import (
            ClaimModel, FedWatchModel, LeaseModel, PromoteModel,
            SeqlockModel, WalModel, run_model,
        )
        schedule = parse_schedule(args.replay)
        strat = ReplayStrategy(schedule)
        m = names[0]
        try:
            # each variant reconstructs the exact model shape + bounds
            # its sweep pass ran — a mismatched process set would
            # desynchronize the replay
            if m == "seqlock":
                if args.variant == "torn":
                    run_model(lambda s: SeqlockModel(s, heal=False),
                              strat, kills=0,
                              preemptions=args.preemptions)
                else:
                    run_model(lambda s: SeqlockModel(s, heal=True),
                              strat, kills=1, preemptions=0)
            elif m == "claim":
                if args.variant == "no-kill":
                    run_model(lambda s: ClaimModel(s, daemon=False),
                              strat, kills=0,
                              preemptions=args.preemptions)
                else:
                    run_model(lambda s: ClaimModel(s), strat, kills=1,
                              preemptions=0)
            elif m == "lease":
                if args.variant == "no-kill":
                    run_model(lambda s: LeaseModel(s), strat, kills=0,
                              preemptions=args.preemptions)
                else:
                    run_model(lambda s: LeaseModel(s), strat, kills=1,
                              preemptions=0)
            elif m == "fedwatch":
                if args.variant == "no-kill":
                    run_model(lambda s: FedWatchModel(s), strat, kills=0,
                              preemptions=args.preemptions)
                else:
                    run_model(lambda s: FedWatchModel(s), strat, kills=1,
                              preemptions=0)
            elif m == "promote":
                if args.variant == "no-kill":
                    run_model(lambda s: PromoteModel(s), strat, kills=0,
                              preemptions=args.preemptions)
                else:
                    run_model(lambda s: PromoteModel(s), strat, kills=1,
                              preemptions=0)
            else:
                run_model(lambda s: WalModel(s), strat, kills=1,
                          crash_all=True, preemptions=args.preemptions)
        except InvariantViolation as v:
            print(v.format())
            return 1
        print("tdcheck: replay completed, invariants held")
        return 0

    kw = dict(mode=args.mode, max_schedules=args.schedules,
              seed=args.seed, preemptions=args.preemptions)
    rc = 0
    for m in names:
        if m in ("seqlock", "claim") and not shm_ok:
            print(f"tdcheck: {m}: SKIPPED (no Linux SO_REUSEPORT / "
                  f"native shm-atomics core)")
            continue
        if args.prove_mutants:
            try:
                MUTANTS[m](**kw)
            except InvariantViolation as v:
                print(f"tdcheck: {m}: checker LIVE — fired on its "
                      f"seeded mutant ({v.message.splitlines()[0]})")
            else:
                print(f"tdcheck: {m}: checker DEAD — the seeded mutant "
                      f"survived the sweep", file=sys.stderr)
                rc = 1
            continue
        try:
            stats = SWEEPS[m](**kw)
        except InvariantViolation as v:
            print(v.format(), file=sys.stderr)
            rc = 1
            continue
        print(f"tdcheck: {m}: {stats['schedules']} schedule(s) "
              f"[{args.mode}], {stats['killed_runs']} with injected "
              f"kill(s), all invariants held "
              f"(digest {stats['digest'][:12]})")
    return rc


if __name__ == "__main__":
    sys.exit(main())
