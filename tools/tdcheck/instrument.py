"""The yield-point seam over the real protocol code.

Lockwatch's trick, re-aimed: instead of patching `threading` lock
factories, `InstrumentedState` subclasses the REAL
`server.workers.SharedRouterState` and wraps every shared-memory
operation (atomic load/store/add/cas-dec, futex wait/wake) with a
scheduler yield point BEFORE the op executes — so the explorer can
preempt, or kill, a logical process between any two shared accesses,
exactly where a real cross-process race or SIGKILL would land.
`install_seams` additionally hooks the in-window publish seam
(`workers._publish_yield`) and defuses `time.sleep` inside the module
(a parked reader must yield to the scheduler, not stall the whole
single-baton world).

Granularity notes (deliberate):
- `dec_floor0`'s internal load/CAS retry loop executes as ONE yield op.
  The loop is self-contained lock-free code whose correctness does not
  depend on mid-loop interleaving with the protocols under test; op
  granularity keeps the schedule tree small enough to sweep.
- `futex_wait` yields and returns "timed out" immediately. Spurious
  wakeups are within the futex contract, so every caller already
  re-checks its condition in a loop — under the explorer that loop IS
  the park/retry behaviour, with the scheduler deciding who runs.

Every op is reported to the model hook with the scheduler's current
process attribution, which is how the claim model knows precisely
whether a kill landed inside the fetch_add→ledger window.
"""

from __future__ import annotations

import contextlib
from typing import Callable, Optional

from gpu_docker_api_tpu.server import workers

from .sched import Scheduler

#: op log entry: (proc_name, op, offset, value_or_result)
OpNote = tuple[Optional[str], str, int, int]


class InstrumentedState(workers.SharedRouterState):
    """A real SharedRouterState (real segment, real native atomics)
    whose every shm op is a scheduler yield point."""

    def __init__(self, sched: Scheduler,
                 note: Optional[Callable[[OpNote], None]] = None):
        super().__init__(create=True)
        self._sched = sched
        self._note = note

    def _yield(self, op: str, off: int) -> None:
        self._sched.yield_point((op, off))

    def _log(self, op: str, off: int, val: int) -> None:
        if self._note is not None:
            self._note((self._sched.current, op, off, val))

    # ---- instrumented ops ------------------------------------------------

    def load(self, off: int) -> int:
        self._yield("load", off)
        v = super().load(off)
        self._log("load", off, v)
        return v

    def store(self, off: int, v: int) -> None:
        self._yield("store", off)
        super().store(off, v)
        self._log("store", off, v)

    def add(self, off: int, d: int) -> int:
        self._yield("add", off)
        v = super().add(off, d)
        self._log("add", off, v)
        return v

    def dec_floor0(self, off: int) -> None:
        self._yield("dec", off)
        super().dec_floor0(off)
        self._log("dec", off, 0)

    def futex_wait(self, off: int, expected: int, timeout_s: float) -> None:
        # park = yield; the caller's retry loop re-checks under the
        # scheduler's control, so no real blocking ever happens
        self._yield("futex_wait", off)
        self._log("futex_wait", off, expected)

    def futex_wake_all(self, off: int) -> None:
        self._yield("futex_wake", off)
        super().futex_wake_all(off)
        self._log("futex_wake", off, 0)


class BrokenSeqlockState(InstrumentedState):
    """Seeded mutant: drops the odd-epoch store that opens the publish
    window, so config bytes land under an even (read-admissible) epoch —
    the classic forgotten-seqlock bug. The torn-roster checker must
    catch this (its liveness proof)."""

    def store(self, off: int, v: int) -> None:
        if off == workers.HDR_OFF_EPOCH and v % 2 == 1:
            self._yield("store", off)   # keep the schedule shape
            self._log("store-dropped", off, v)
            return
        super().store(off, v)


@contextlib.contextmanager
def install_seams(sched: Scheduler):
    """Arm the module-level seams for one exploration run: the publish
    in-window yield hook and a scheduler-cooperative time.sleep."""
    prev_hook = workers._publish_yield
    prev_sleep = workers.time.sleep

    def coop_sleep(s: float) -> None:
        # `workers.time` is the global time module: only modeled threads
        # may be descheduled instead of sleeping — anything else in the
        # process (pytest timers, watchdogs) keeps the real sleep
        if sched.current is None:
            prev_sleep(s)
        else:
            sched.yield_point(("sleep", 0))

    workers._publish_yield = lambda g: sched.yield_point(("pub", g))
    workers.time.sleep = coop_sleep
    try:
        yield
    finally:
        workers._publish_yield = prev_hook
        workers.time.sleep = prev_sleep
