"""tdcheck — deterministic interleaving explorer for the cross-process
protocols.

PR 13 moved the data plane's hottest state out of the GIL and into a
hand-rolled cross-process protocol: a seqlock roster twin, lock-free
atomic claim counters with undo-on-overshoot, futex wakeups, and a C++
leader/follower WAL group commit. None of PR 8's correctness suite sees
any of it — tdlint reasons about `threading` locks, lockwatch patches
in-process lock factories. tdcheck is the missing layer: a cooperative
scheduler runs N logical processes over the REAL protocol code (the
yield-point seam instruments `SharedRouterState`'s shm ops and the
seqlock publish window, the same factory-patching trick lockwatch uses
for locks), systematically enumerates schedules, and injects a SIGKILL
at every yield point. Each invariant checker is proven LIVE on a
seeded-broken mutant twin, like tdlint's rule fixtures.

Checked protocols (tools/tdcheck/models.py):

1. **seqlock publish/read** — a reader never acts on a torn roster, and
   a writer crash mid-publish (epoch parked odd) is healed by the 250ms
   republish rather than wedging readers forever.
2. **claim/undo/reconcile** — no schedule ever admits past a replica's
   advertised slots, and `reconcile_worker` after a SIGKILL restores
   exact counter accounting (the "ledger incremented only after the
   global claim" ordering, previously asserted only in prose).
3. **WAL group commit** — `Commit(seq)` returning implies the record's
   batch was flushed, across leader handoff and crash-at-any-step.
   Checked on a pure-Python twin of the C++ state machine
   (native/mvcc_store.cc), cross-validated against the real core by the
   subprocess kill sweep in tests/test_tdcheck.py.

Exploration (tools/tdcheck/sched.py) is CHESS-style iterative context
bounding: the base schedule runs each process to completion; exhaustive
mode enumerates every placement of up to `preemptions` forced switches
plus up to `kills` crash injections (exhaustive for small bounds —
the 2-writer/1-reader seqlock and 2-worker claim models are swept
completely); beyond the bounds, randomized mode draws schedules from a
seeded RNG, and every failure report carries the exact schedule so
`--replay` reproduces it deterministically.

Run: `python -m tools.tdcheck` (all models, quick budget; `make
verify-tdcheck` wraps the pytest sweep). Exit 0 = every invariant held
on every explored schedule.
"""

from __future__ import annotations

from .sched import (  # noqa: F401  (re-exports: the package API)
    ExhaustiveStrategy, InvariantViolation, RandomStrategy, ReplayStrategy,
    RunResult, Scheduler, explore,
)

__all__ = [
    "Scheduler", "RunResult", "InvariantViolation", "explore",
    "ExhaustiveStrategy", "RandomStrategy", "ReplayStrategy",
]
