"""The cooperative scheduler + schedule-space exploration.

Model checking real code needs real call stacks, so each logical process
is a Python thread — but only ONE ever runs at a time: the controller
hands a baton to the chosen process, which runs until its next yield
point (every instrumented shm op, seqlock seam hit, or cooperative-lock
step) and parks. Determinism follows: the modeled code takes no other
scheduling input, so a schedule (the sequence of controller choices) is
a complete replay key.

Crash semantics are SIGKILL's, not an exception's: a killed process is
simply never scheduled again — its thread stays parked at the yield
point, its shared-memory footprint frozen exactly as a killed worker
process would leave it. `finally:` blocks must NOT run (a real SIGKILL
skips them); they are only unwound at teardown, AFTER the run's
invariants have been checked against the frozen state, so the cleanup
they perform lands on state nobody will read again.

Exploration is CHESS-style iterative context bounding (Musuvathi &
Qadeer): the base schedule runs the current process until it finishes,
and the exhaustive driver enumerates every placement of up to
`preemptions` voluntary switches and up to `kills` injected crashes.
Small protocol models (a handful of processes, tens of yield points)
are swept completely; a fairness cap bounds spin loops (a process that
has run `fair_cap` consecutive steps is descheduled for one step for
free) so retry loops cannot eat the whole budget.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Callable, Iterator, Optional

#: a schedule choice: ("run", proc) | ("kill", proc) | ("crash", "*")
Choice = tuple[str, str]


class _Abort(BaseException):
    """Teardown unwind signal. BaseException so the modeled code's
    `except Exception` handlers never swallow it."""


class InvariantViolation(AssertionError):
    """An invariant checker fired. Carries everything needed to replay
    the failing schedule deterministically: the schedule, and the model
    VARIANT it ran under (a torn-pass schedule replayed against the
    kill-variant model would desynchronize on the extra process)."""

    def __init__(self, model: str, message: str,
                 schedule: Optional[list[Choice]] = None,
                 seed: Optional[int] = None,
                 variant: Optional[str] = None):
        self.model = model
        self.message = message
        self.schedule = list(schedule or [])
        self.seed = seed
        self.variant = variant
        super().__init__(message)

    def __str__(self) -> str:   # dynamic: variant is annotated post-raise
        return self.format()

    def format(self) -> str:
        out = f"[{self.model}] {self.message}"
        if self.schedule:
            sched = ",".join(f"{k}:{p}" for k, p in self.schedule)
            var = f" --variant {self.variant}" if self.variant else ""
            out += f"\n  replay schedule: {sched}"
            out += (f"\n  reproduce: python -m tools.tdcheck "
                    f"--model {self.model}{var} --replay '{sched}'")
        if self.seed is not None:
            out += f"\n  seed: {self.seed}"
        return out


@dataclass
class RunResult:
    """One schedule's outcome."""
    schedule: list[Choice]
    steps: int
    completed: bool            # every live process ran to the end
    wedged: bool               # hit max_steps with processes still live
    killed: list[str] = field(default_factory=list)
    crashed: bool = False      # global crash injected (WAL model)
    error: Optional[BaseException] = None   # modeled-code exception


class _Proc:
    __slots__ = ("name", "fn", "thread", "go", "paused", "done", "killed",
                 "abort", "error", "tag", "killable", "started",
                 "last_run")

    def __init__(self, name: str, fn: Callable[[], None], killable: bool):
        self.name = name
        self.fn = fn
        self.thread: Optional[threading.Thread] = None
        self.go = threading.Event()
        self.paused = threading.Event()
        self.done = False
        self.killed = False
        self.abort = False
        self.error: Optional[BaseException] = None
        self.tag = ("start",)
        self.killable = killable
        self.started = False
        self.last_run = 0


class Scheduler:
    """One schedule's execution engine. Build, spawn(), run(), teardown().

    `kills` bounds injected per-process crashes; `crash_all=True` offers
    a whole-process-group crash instead (the WAL model: every thread of
    the C++ store dies together). `preemptions` bounds forced switches
    away from a still-runnable process — the context bound that keeps
    exhaustive exploration tractable.
    """

    #: join timeout for modeled threads — generous; modeled code never
    #: blocks outside a yield point by construction
    JOIN_S = 20.0

    def __init__(self, strategy: "Strategy", max_steps: int = 400,
                 preemptions: int = 2, kills: int = 0,
                 crash_all: bool = False, fair_cap: int = 16,
                 starve_cap: int = 24):
        self.strategy = strategy
        self.max_steps = max_steps
        self.preempt_budget = preemptions
        self.kill_budget = kills
        self.crash_all = crash_all
        self.fair_cap = fair_cap
        self.starve_cap = starve_cap
        self.procs: dict[str, _Proc] = {}
        self.trace: list[Choice] = []
        self.steps = 0
        self.crashed = False
        self._by_tid: dict[int, _Proc] = {}
        self._teardown = False
        self._last: Optional[_Proc] = None
        self._consec = 0
        self.step_hook: Optional[Callable[[], None]] = None
        #: called with the RunResult after the loop but BEFORE teardown:
        #: killed processes are still frozen at their yield points, so
        #: frozen-state invariant checks see exactly what a post-SIGKILL
        #: reconciler would (teardown unwinds `finally:` blocks, which
        #: would "clean up" the very state under test)
        self.end_hook: Optional[Callable[[RunResult], None]] = None

    # ---- process-side API ------------------------------------------------

    def yield_point(self, tag: tuple = ()) -> None:
        """Called by instrumented ops from modeled threads. Parks the
        thread and hands the baton back to the controller. A no-op on
        unregistered threads (model setup runs inline on the controller)
        and during teardown unwind."""
        p = self._by_tid.get(threading.get_ident())
        if p is None or self._teardown:
            return
        p.tag = tag
        p.paused.set()
        p.go.wait()
        p.go.clear()
        if p.abort:
            p.abort = False
            raise _Abort()

    @property
    def current(self) -> Optional[str]:
        """Name of the process whose thread is asking (attribution for
        model op logs)."""
        p = self._by_tid.get(threading.get_ident())
        return p.name if p is not None else None

    # ---- controller-side API ---------------------------------------------

    def spawn(self, name: str, fn: Callable[[], None],
              killable: bool = True) -> None:
        self.procs[name] = _Proc(name, fn, killable)

    def _body(self, p: _Proc) -> None:
        # ident is only assigned once the thread runs — register here,
        # before the first baton wait, so yield_point can attribute ops
        self._by_tid[threading.get_ident()] = p
        p.go.wait()
        p.go.clear()
        try:
            if not p.abort:
                p.fn()
        except _Abort:
            pass
        except BaseException as e:  # noqa: BLE001 — surfaced on RunResult
            p.error = e
        finally:
            p.done = True
            p.paused.set()

    def _step(self, p: _Proc) -> None:
        p.paused.clear()
        p.go.set()
        if not p.paused.wait(self.JOIN_S):
            raise RuntimeError(
                f"tdcheck: process {p.name!r} did not reach a yield point "
                f"within {self.JOIN_S}s — modeled code blocked outside "
                f"the instrumented seam?")

    def _options(self) -> list[Choice]:
        runnable = [p for p in self.procs.values()
                    if not p.done and not p.killed]
        # weak fairness: only fair schedules are enumerated. A runnable
        # process starved past starve_cap steps is force-run — otherwise
        # the DFS "finds" livelocks no real scheduler produces (two spin
        # loops taking turns forever while the lock holder never runs)
        starved = [p for p in runnable
                   if self.steps - p.last_run > self.starve_cap]
        if starved:
            starved.sort(key=lambda p: p.last_run)
            return [("run", starved[0].name)]
        last = self._last
        last_runnable = (last is not None and not last.done
                         and not last.killed)
        opts: list[Choice] = []
        if last_runnable and self._consec < self.fair_cap:
            opts.append(("run", last.name))
        others = [p for p in runnable if p is not last]
        # switching away from a runnable process costs a preemption;
        # switching after it finished/was killed (or hit the fairness
        # cap) is free
        free_switch = (not last_runnable or self._consec >= self.fair_cap)
        if free_switch or self.preempt_budget > 0:
            opts.extend(("run", p.name) for p in others)
        if self.kill_budget > 0:
            if self.crash_all:
                # whole-process-group crash (the WAL model: every thread
                # of the store's process dies together) — per-process
                # kills would model a thread dying alone, which SIGKILL
                # cannot do
                if runnable:
                    opts.append(("crash", "*"))
            elif last_runnable and last.killable:
                # kill is offered only for the process that JUST ran —
                # at the yield point it is parked on. Killing a parked
                # process any number of steps later leaves its own state
                # identical, so those schedules are duplicates; this
                # prunes them and enumerates crash points along each
                # process's own execution, one per yield point
                opts.append(("kill", last.name))
        if not opts and runnable:
            # fairness cap descheduled the only runnable process with no
            # one to switch to: let it keep running
            opts.append(("run", runnable[0].name))
        return opts

    def run(self) -> RunResult:
        for p in self.procs.values():
            p.thread = threading.Thread(target=self._body, args=(p,),
                                        name=f"tdcheck-{p.name}",
                                        daemon=True)
            p.thread.start()
            p.started = True
        try:
            result = self._loop()
            if self.end_hook is not None and result.error is None:
                self.end_hook(result)
            return result
        finally:
            self.teardown()

    def _loop(self) -> RunResult:
        while True:
            runnable = [p for p in self.procs.values()
                        if not p.done and not p.killed]
            err = next((p.error for p in self.procs.values()
                        if p.error is not None), None)
            if err is not None or not runnable or self.crashed:
                return RunResult(
                    schedule=self.trace, steps=self.steps,
                    completed=all(p.done and not p.killed
                                  for p in self.procs.values()),
                    wedged=False, crashed=self.crashed,
                    killed=[p.name for p in self.procs.values()
                            if p.killed],
                    error=err)
            if self.steps >= self.max_steps:
                return RunResult(
                    schedule=self.trace, steps=self.steps, completed=False,
                    wedged=True, crashed=False,
                    killed=[p.name for p in self.procs.values()
                            if p.killed])
            opts = self._options()
            choice = self.strategy.choose(self.steps, opts)
            self.trace.append(choice)
            kind, who = choice
            if kind == "crash":
                self.kill_budget -= 1
                for p in self.procs.values():
                    if not p.done:
                        p.killed = True
                self.crashed = True
                self.steps += 1
                continue
            p = self.procs[who]
            if kind == "kill":
                self.kill_budget -= 1
                p.killed = True
                self.steps += 1
                if self._last is p:
                    self._last = None
                continue
            if self._last is not None and p is not self._last:
                # a switch only costs preemption budget when CONTINUING
                # was among the offered options (fairness-forced and
                # after-block switches are free)
                if ("run", self._last.name) in opts:
                    self.preempt_budget -= 1
                self._consec = 0
            self._consec = self._consec + 1 if p is self._last else 1
            self._last = p
            p.last_run = self.steps
            self._step(p)
            self.steps += 1
            if self.step_hook is not None:
                self.step_hook()

    def teardown(self) -> None:
        """Release every parked thread (killed, descheduled, or
        budget-stranded): unwind with _Abort so `finally:` cleanup runs
        against the now-discarded state, then join."""
        self._teardown = True
        for p in self.procs.values():
            if p.started and not p.done:
                p.abort = True
                p.go.set()
        for p in self.procs.values():
            if p.thread is not None:
                p.thread.join(timeout=self.JOIN_S)

# ---------------------------------------------------------------- strategies

class Strategy:
    def choose(self, step: int, options: list[Choice]) -> Choice:
        raise NotImplementedError


class ExhaustiveStrategy(Strategy):
    """Follow a forced prefix of option indices, then always pick option
    0; record the option count at every step so the driver can branch."""

    def __init__(self, prefix: tuple[int, ...] = ()):
        self.prefix = prefix
        self.taken: list[int] = []
        self.counts: list[int] = []

    def choose(self, step: int, options: list[Choice]) -> Choice:
        i = len(self.taken)
        idx = self.prefix[i] if i < len(self.prefix) else 0
        if idx >= len(options):
            # the prefix outran this path's options (a shorter run than
            # the sibling it branched from) — clamp; the driver dedups
            idx = 0
        self.taken.append(idx)
        self.counts.append(len(options))
        return options[idx]


class RandomStrategy(Strategy):
    """Seeded uniform choice — deterministic given the seed."""

    def __init__(self, seed: int):
        self.seed = seed
        self.rng = random.Random(seed)

    def choose(self, step: int, options: list[Choice]) -> Choice:
        return options[self.rng.randrange(len(options))]


class ReplayStrategy(Strategy):
    """Replay a recorded schedule by VALUE (robust to option reordering);
    past the recorded suffix, fall back to option 0."""

    def __init__(self, schedule: list[Choice]):
        self.schedule = list(schedule)
        self._i = 0

    def choose(self, step: int, options: list[Choice]) -> Choice:
        if self._i < len(self.schedule):
            want = self.schedule[self._i]
            self._i += 1
            if want in options:
                return want
        return options[0]


# ---------------------------------------------------------------- the driver

def explore(run_once: Callable[[Strategy], RunResult],
            mode: str = "exhaustive", max_schedules: int = 4000,
            seed: int = 0) -> Iterator[RunResult]:
    """Yield RunResults over the schedule space.

    exhaustive: stateless DFS — re-run from scratch for every branch of
    the choice tree (same-prefix runs replay identically because the
    models are deterministic). Terminates when the frontier empties
    (full sweep within the Scheduler's bounds) or max_schedules is hit.

    random: max_schedules draws from a seeded RNG; schedule i uses seed
    `seed + i` so any single failing draw is reproducible alone.
    """
    if mode == "random":
        for i in range(max_schedules):
            try:
                yield run_once(RandomStrategy(seed + i))
            except InvariantViolation as v:
                if v.seed is None:
                    v.seed = seed + i   # this draw alone reproduces it
                raise
        return
    frontier: list[tuple[int, ...]] = [()]
    ran = 0
    while frontier and ran < max_schedules:
        prefix = frontier.pop()
        strat = ExhaustiveStrategy(prefix)
        result = run_once(strat)
        ran += 1
        taken = tuple(strat.taken)
        for i in range(len(prefix), len(strat.counts)):
            for alt in range(1, strat.counts[i]):
                frontier.append(taken[:i] + (alt,))
        yield result


def parse_schedule(text: str) -> list[Choice]:
    """Inverse of the failure report's `k:p,k:p,...` schedule string."""
    out: list[Choice] = []
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        kind, _, who = part.partition(":")
        out.append((kind, who))
    return out
