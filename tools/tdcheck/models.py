"""The three checked protocols, their invariants, and their seeded-broken
mutant twins.

Each model wires REAL protocol code (or, for the C++ group commit, a
line-for-line Python twin of native/mvcc_store.cc's state machine) into
the cooperative scheduler and states its invariants as code. Every
checker is proven LIVE by a mutant twin that reintroduces the bug class
the checker exists to catch — a checker that cannot fail its mutant is
decoration, exactly like a tdlint rule without its bad fixture.

Invariant catalog (docs/correctness.md carries the prose version):

seqlock (real `SharedRouterState.publish` / `read_roster`):
  S1  a reader never parses a torn roster — every observed roster is
      bytewise one of the published ones.
  S2  a writer SIGKILLed inside the publish window (epoch parked odd)
      does not wedge readers forever: the daemon's heal republish
      recovers them (exercised against the real publish, which must be
      re-enterable from a crashed-odd epoch).

claim/undo/reconcile (real `WorkerRouter._try_claim` / `_release` /
`SharedRouterState.reconcile_worker`):
  C1  no schedule admits past a replica's advertised slots (live
      concurrently-held claims <= slots, at every admission).
  C2  after any SIGKILL + reconcile, the global inflight counter equals
      the live outstanding claims plus, per killed worker, a surplus
      that is EXACTLY the worker's (global ops) - (ledger ops) imbalance
      at the kill point — i.e. reconcile's arithmetic is exact, and the
      only reachable discrepancy is the documented one-op window where
      the counter reads HIGH (brief under-admit). It must never read
      LOW: a negative imbalance means the ledger ran ahead of the
      global fetch_add and reconcile would free capacity that was never
      claimed — the double-admit direction the "ledger only after
      global claim" ordering exists to prevent.

WAL group commit (Python twin of native/mvcc_store.cc Append/Commit):
  W1  Commit(seq) returning implies the record's batch was flushed: a
      crash at ANY yield point never loses an acked record, across
      leader handoff (a follower acked by another leader's flush).
  W2  the flushed stream is strictly ordered and duplicate-free.

federation leases (real `FleetArbiter` / `FleetMember.heartbeat_once`,
federation.py — the member's crash seams are the production
fed.after_acquire / fed.after_takeover crashpoints, so every injected
kill lands in a window the crash sweep also exercises):
  L1  at most one live member believes it owns a resource, at every
      observable store state (a steal from a LIVE-leased holder is the
      split-brain this catches).
  L2  bounded heal: after a member SIGKILL at any yield point, one lease
      expiry plus the surviving members' heartbeats re-grant EVERY
      resource to a live-leased member and the believed sets match the
      grant table (a leaked grant that nobody can steal is the
      stuck-ownership direction).

federation watch (real `WatchedStore` + `WatchHub`, federation.py):
  FW1 an informer consuming the hub across a mid-stream consumer kill +
      cursor-resume (the takeover handoff) applies a strictly-increasing
      revision sequence — zero duplicated revisions — and its final
      cache equals the store's watched state — zero dropped revisions.

promote-on-loss (real `FleetMember.heartbeat_once` promote hook +
`WatchedStore`/`WatchHub`, with the StandbyReplicator apply contract as
an in-model twin — replication.py, docs/durability.md §promote):
  R1  no revision acknowledged at-or-below the replicated horizon at
      promote time is lost: the promoted store's record is at least as
      new as the last ack the horizon covers (a replicator that skips
      an event while advancing its horizon is the seeded lie).
  R2  at most one promoted lineage: across every kill placement and
      standby race, the set of members that promote a resource never
      exceeds one — the takeover steal's single-winner epoch is the
      fence (a member that promotes after LOSING the steal is the
      seeded break).
"""

from __future__ import annotations

import hashlib
import json
from typing import Callable, Optional

from gpu_docker_api_tpu import federation, replication
from gpu_docker_api_tpu.server import workers
from gpu_docker_api_tpu.store.mvcc import MVCCStore

from .instrument import BrokenSeqlockState, InstrumentedState, install_seams
from .sched import (
    InvariantViolation, RunResult, Scheduler, Strategy, explore,
)

# ---------------------------------------------------------------- harness


def run_model(factory: Callable[[Scheduler], "Model"], strategy: Strategy,
              *, max_steps: int = 400, preemptions: int = 2,
              kills: int = 0, crash_all: bool = False,
              fair_cap: int = 8, starve_cap: int = 16) -> RunResult:
    """One schedule: build the model, run it, check its invariants.
    Raises InvariantViolation (with the replayable schedule) on any
    failure; returns the RunResult otherwise."""
    sched = Scheduler(strategy, max_steps=max_steps,
                      preemptions=preemptions, kills=kills,
                      crash_all=crash_all, fair_cap=fair_cap,
                      starve_cap=starve_cap)
    model = factory(sched)
    sched.end_hook = model.finish
    try:
        with install_seams(sched):
            result = sched.run()
        err = result.error
        if isinstance(err, InvariantViolation):
            raise InvariantViolation(err.model, err.message,
                                     schedule=result.schedule)
        if err is not None:
            raise InvariantViolation(
                model.name, f"modeled code raised {err!r}",
                schedule=result.schedule) from err
        model.check(result)
        return result
    finally:
        model.close()


class Model:
    name = "model"

    def __init__(self, sched: Scheduler):
        self.sched = sched

    def violation(self, message: str) -> InvariantViolation:
        return InvariantViolation(self.name, message,
                                  schedule=self.sched.trace)

    def finish(self, result: RunResult) -> None:
        """Frozen-state checks — runs BEFORE teardown unwind."""

    def check(self, result: RunResult) -> None:
        """Result-shape checks — runs after teardown."""

    def close(self) -> None:
        pass


# ---------------------------------------------------------------- seqlock

ROSTER_A = [{"name": "alpha", "maxQueue": 4, "deadlineMs": 1000,
             "replicas": [{"port": 1, "slots": 1, "ready": True},
                          {"port": 2, "slots": 2, "ready": True}]}]
ROSTER_B = [{"name": "alpha", "maxQueue": 9, "deadlineMs": 9000,
             "replicas": [{"port": 9, "slots": 9, "ready": True}]}]


def _shape(gw: Optional[dict]) -> Optional[tuple]:
    if gw is None:
        return None
    return (gw["maxQueue"], gw["deadlineMs"],
            tuple((r["port"], r["slots"]) for r in gw["replicas"]))


SHAPE_A = _shape({"maxQueue": 4, "deadlineMs": 1000,
                  "replicas": [{"port": 1, "slots": 1},
                               {"port": 2, "slots": 2}]})
SHAPE_B = _shape({"maxQueue": 9, "deadlineMs": 9000,
                  "replicas": [{"port": 9, "slots": 9}]})


class PublisherGate:
    """The seqlock's single-writer contract, as model harness: in the
    real tier every publish runs on ONE daemon watchdog thread, so two
    publishes never interleave (tdcheck demonstrated that concurrent
    publishers DO tear the roster — the protocol's documented contract,
    now machine-checked rather than assumed). A KILLED holder models a
    crashed daemon: its successor (the heal republish, or a federation
    peer taking over the segment lease) reclaims the gate and publishes
    over whatever epoch parity the corpse left behind — which is exactly
    the crashed-odd re-entry path `publish` must handle."""

    def __init__(self, sched: Scheduler):
        self.sched = sched
        self.owner: Optional[str] = None

    def acquire(self) -> None:
        while True:
            self.sched.yield_point(("gate", 0))
            own = self.owner
            if own is None or self.sched.procs[own].killed:
                self.owner = self.sched.current
                return

    def release(self) -> None:
        self.owner = None


class SeqlockModel(Model):
    """2 writers / 1 reader over the REAL publish/read_roster, plus (in
    the kill sweep) the daemon's heal republish as a fourth process.
    Writers serialize through the PublisherGate (the single-writer
    contract); the reader and kill injection interleave freely."""

    name = "seqlock"

    def __init__(self, sched: Scheduler, heal: bool = False,
                 state_cls: type = InstrumentedState):
        super().__init__(sched)
        self.heal = heal
        self.st = state_cls(sched)
        # setup runs inline on the controller thread (yield points are
        # no-ops there): slot 0 is already named, so modeled publishes
        # never take the slot-identity-change branch and its ~170 cell
        # zeroes — the seqlock window itself is what's under test
        self.st.publish(ROSTER_A)
        self.gate = PublisherGate(sched)
        self.observed: list[tuple] = []
        sched.spawn("w0", self._writer_fn(ROSTER_A))
        sched.spawn("w1", self._writer_fn(ROSTER_B))
        sched.spawn("reader", self._reader, killable=False)
        if heal:
            sched.spawn("heal", self._healer, killable=False)

    def _writer_fn(self, roster: list[dict]) -> Callable[[], None]:
        def fn() -> None:
            self.gate.acquire()
            self.st.publish(roster)
            self.gate.release()
        return fn

    def _reader(self) -> None:
        _, roster = self.st.read_roster()
        self.observed.append(_shape(roster.get("alpha")))

    def _healer(self) -> None:
        # the daemon's 250ms heal republish: fires once the writers have
        # settled (done or killed), like the watchdog tick after a crash
        procs = self.sched.procs
        while not all(procs[w].done or procs[w].killed
                      for w in ("w0", "w1")):
            self.sched.yield_point(("heal-wait", 0))
        if any(procs[w].killed for w in ("w0", "w1")):
            self.gate.acquire()
            self.st.publish(ROSTER_B)
            self.gate.release()

    def check(self, result: RunResult) -> None:
        for shape in self.observed:
            if shape not in (SHAPE_A, SHAPE_B):
                raise self.violation(
                    f"S1 torn roster parsed by a reader: {shape!r} is "
                    f"neither published roster")
        if result.wedged:
            raise self.violation(
                "S2 reader wedged: the heal republish did not recover "
                "readers from a crashed publish"
                if self.heal else
                "run exceeded its step budget (no heal process in this "
                "variant — check bounds)")

    def close(self) -> None:
        self.st.close(unlink=True)


# ------------------------------------------------------- claim/reconcile

CLAIM_ROSTER = [{"name": "g", "maxQueue": 8, "deadlineMs": 60000,
                 "replicas": [{"port": 7001, "slots": 1, "ready": True}]}]


class ClaimModel(Model):
    """2 workers × 2 claim/hold/release iterations against ONE advertised
    slot, with the daemon's watchdog reconciling any killed worker
    mid-run. Uses the real WorkerRouter claim path (or a seeded-broken
    mutant of it)."""

    name = "claim"

    ITERS = 2
    SLOTS = 1

    def __init__(self, sched: Scheduler,
                 router_cls: type = workers.WorkerRouter,
                 daemon: bool = True):
        super().__init__(sched)
        self.st = InstrumentedState(sched, note=self._note)
        self.st.publish(CLAIM_ROSTER)     # inline setup: no yields
        self.rep_off = workers._rep_cnt_off(0, 0)
        self.wk_offs = {workers._wk_claim_off(w, 0, 0): w
                        for w in range(2)}
        self.ops: dict[str, list[tuple[str, int]]] = {}
        self.outstanding: dict[str, int] = {}   # proc -> held claims
        self.reconciled: set[int] = set()
        self.names = {"k0": 0, "k1": 1}
        for name, widx in self.names.items():
            router = router_cls(self.st, widx)
            gw = router._gateway("g")       # inline prewarm
            sched.spawn(name, self._worker_fn(name, router, gw))
        if daemon:
            # the watchdog only matters once a worker can die — the
            # no-kill sweep leaves it out to keep the tree small
            sched.spawn("daemon", self._daemon, killable=False)

    # ---- op attribution (the claim-window oracle) ------------------------

    def _note(self, note) -> None:
        proc, op, off, _val = note
        if proc is None or op not in ("add", "dec"):
            return
        if off == self.rep_off or off in self.wk_offs:
            self.ops.setdefault(proc, []).append((op, off))

    def _imbalance(self, proc: str) -> int:
        """(global ops) - (ledger ops) net for one worker's op log: how
        far the global counter over-counts this worker relative to its
        reconcile-visible ledger. >0 = counter reads high after
        reconcile (safe, brief under-admit); <0 = ledger ran AHEAD of
        the global claim — the double-admit direction."""
        g = led = 0
        for op, off in self.ops.get(proc, ()):
            d = 1 if op == "add" else -1
            if off == self.rep_off:
                g += d
            else:
                led += d
        return g - led

    # ---- processes -------------------------------------------------------

    def _worker_fn(self, name: str, router, gw) -> Callable[[], None]:
        def fn() -> None:
            for _ in range(self.ITERS):
                c = router._try_claim(gw)
                if c is None:
                    self.sched.yield_point(("retry", 0))
                    continue
                live = sum(n for p, n in self.outstanding.items()
                           if not self.sched.procs[p].killed)
                if live + 1 > self.SLOTS:
                    raise self.violation(
                        f"C1 double admit: {name} claimed slot while "
                        f"{live} live claim(s) already held "
                        f"(slots={self.SLOTS})")
                self.outstanding[name] = self.outstanding.get(name, 0) + 1
                self.sched.yield_point(("hold", 0))
                self.outstanding[name] -= 1
                router._release(c)
        return fn

    def _daemon(self) -> None:
        procs = self.sched.procs
        while not all(procs[n].done or procs[n].killed
                      for n in self.names):
            self.sched.yield_point(("watchdog", 0))
            self._reconcile_dead()
        self._reconcile_dead()

    def _reconcile_dead(self) -> None:
        for name, widx in self.names.items():
            if self.sched.procs[name].killed and widx not in self.reconciled:
                self.reconciled.add(widx)
                self.st.reconcile_worker(widx)
                self._check_accounting(f"after reconcile of {name}")

    # ---- invariants ------------------------------------------------------

    def _check_accounting(self, when: str) -> None:
        live = sum(n for p, n in self.outstanding.items()
                   if not self.sched.procs[p].killed)
        surplus = 0
        for name, widx in self.names.items():
            if self.sched.procs[name].killed and widx in self.reconciled:
                imb = self._imbalance(name)
                if imb < 0:
                    raise self.violation(
                        f"C2 {when}: {name}'s claim ledger ran AHEAD of "
                        f"its global fetch_add (imbalance {imb}) — "
                        f"reconcile freed capacity that was never "
                        f"claimed (double-admit direction)")
                surplus += imb
        counter = self.st.lib.shm_load(self.st.base + self.rep_off)
        if counter != live + surplus:
            raise self.violation(
                f"C2 {when}: inflight counter {counter} != live "
                f"outstanding {live} + characterized kill-window "
                f"surplus {surplus} — reconcile accounting is not exact")

    def finish(self, result: RunResult) -> None:
        # frozen state: reconcile any worker the daemon didn't get to
        # (killed on the last step), then the exactness check
        self._reconcile_dead()
        self._check_accounting("at end of schedule")
        for widx in self.reconciled:
            led = self.st.lib.shm_load(
                self.st.base + workers._wk_claim_off(widx, 0, 0))
            if led != 0:
                raise self.violation(
                    f"C2 reconciled worker {widx}'s ledger cell is "
                    f"{led}, not zeroed")

    def check(self, result: RunResult) -> None:
        if result.wedged:
            raise self.violation("claim run exceeded its step budget")

    def close(self) -> None:
        self.st.close(unlink=True)


class BrokenClaimRouter(workers.WorkerRouter):
    """Seeded mutant: increments the per-worker claims ledger BEFORE the
    global fetch_add — the exact ordering bug the prose in workers.py
    warns about. A kill between the two makes reconcile subtract a claim
    that never landed globally, freeing someone else's held slot."""

    def _try_claim(self, gw, avoid=frozenset()):
        st = self.state
        g = gw["slot"]
        ready = [(st.load(workers._rep_cnt_off(g, r["idx"])), r)
                 for r in gw["replicas"]
                 if r["ready"] and r["port"] and r["idx"] not in avoid]
        ready.sort(key=lambda t: t[0])
        for _, r in ready:
            off = workers._rep_cnt_off(g, r["idx"])
            wk = workers._wk_claim_off(self.widx, g, r["idx"])
            st.add(wk, 1)                       # BUG: ledger first
            if st.add(off, 1) <= r["slots"]:
                if st.load(workers._gw_cnt_off(g)) != gw["gen"]:
                    st.dec_floor0(off)
                    st.dec_floor0(wk)
                    continue
                return workers._Claim(g, r["idx"], gw["gen"], r["port"])
            st.dec_floor0(off)
            st.dec_floor0(wk)
        return None


# -------------------------------------------------------- WAL group commit

class CoopLock:
    """A mutex in the cooperative world: acquire spins on a yield point
    (the scheduler decides who wins), release is immediate. Only used by
    the WAL twin — crashes there are whole-process (crash_all), so a
    dead owner can never strand a waiter."""

    __slots__ = ("sched", "tag", "owner")

    def __init__(self, sched: Scheduler, tag: str):
        self.sched = sched
        self.tag = tag
        self.owner: Optional[str] = None

    def acquire(self) -> None:
        while True:
            self.sched.yield_point(("lock", self.tag))
            if self.owner is None:
                self.owner = self.sched.current or "<main>"
                return

    def release(self) -> None:
        self.owner = None

    def __enter__(self) -> "CoopLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class WalTwin:
    """Pure-Python twin of native/mvcc_store.cc's leader/follower group
    commit: Append under mu_, Commit blocks until a flush leader has
    written the record's sequence; the leader swaps the pending buffer
    out under mu_, writes it under wal_mu_ WITHOUT holding mu_, then
    marks durable_seq_ under commit_mu_. The cv wait is modeled as
    release-yield-reacquire (spurious wakes are within the contract).
    Cross-validated against the real core by the subprocess kill sweep
    in tests/test_tdcheck.py."""

    def __init__(self, sched: Scheduler):
        self.sched = sched
        self.mu = CoopLock(sched, "mu")
        self.wal_mu = CoopLock(sched, "wal_mu")
        self.commit_mu = CoopLock(sched, "commit_mu")
        self.pending: list[int] = []     # appended, not yet written
        self.filebuf: list[int] = []     # written, not yet fsynced
        self.disk: list[int] = []        # durable
        self.seq = 0
        self.durable = 0
        self.flushing = False
        self.flushes = 0

    def put(self) -> int:
        with self.mu:
            self.seq += 1
            s = self.seq
            self.pending.append(s)
        self.commit(s)
        return s

    def commit(self, s: int) -> None:
        self.commit_mu.acquire()
        try:
            while self.durable < s:
                if self.flushing:
                    # follower: park until the leader marks durable
                    self.commit_mu.release()
                    self.sched.yield_point(("cv-wait", 0))
                    self.commit_mu.acquire()
                    continue
                self.flushing = True
                self.commit_mu.release()
                target = self._flush()
                self.commit_mu.acquire()
                self.flushing = False
                if target > self.durable:
                    self.durable = target
                    self.flushes += 1
        finally:
            self.commit_mu.release()

    def _flush(self) -> int:
        with self.wal_mu:
            with self.mu:
                target = self.seq        # the batch's durable horizon,
                batch = self.pending     # captured AT the swap
                self.pending = []
            self._write(batch)
        return target

    def _write(self, batch: list[int]) -> None:
        for s in batch:
            self.sched.yield_point(("fwrite", s))
            self.filebuf.append(s)       # fwrite: in the stdio buffer
        if batch:
            self.sched.yield_point(("fsync", 0))
            self.disk.extend(self.filebuf)   # fflush+fsync: durable
            self.filebuf.clear()


class BrokenWalTwin(WalTwin):
    """Seeded mutant: the leader reads the durable target AFTER the file
    write — records appended while the flush was on the wire are marked
    durable without ever being written, so their Commit returns and a
    crash loses an acked record."""

    def _flush(self) -> int:
        with self.wal_mu:
            with self.mu:
                batch = self.pending
                self.pending = []
            self._write(batch)
            with self.mu:
                target = self.seq        # BUG: post-write horizon
        return target


class WalModel(Model):
    name = "wal"

    WRITERS = 2
    PUTS = 2

    def __init__(self, sched: Scheduler, twin_cls: type = WalTwin):
        super().__init__(sched)
        self.twin = twin_cls(sched)
        self.acked: list[int] = []
        for i in range(self.WRITERS):
            sched.spawn(f"p{i}", self._writer_fn())

    def _writer_fn(self) -> Callable[[], None]:
        def fn() -> None:
            for _ in range(self.PUTS):
                s = self.twin.put()
                # no yield between commit-return and the ack record: the
                # ack IS the return, same step
                self.acked.append(s)
        return fn

    def finish(self, result: RunResult) -> None:
        disk = self.twin.disk
        on_disk = set(disk)
        for s in self.acked:
            if s not in on_disk:
                raise self.violation(
                    f"W1 Commit({s}) returned but the record is not in "
                    f"the flushed stream {disk} — an acked record would "
                    f"be lost by this crash")
        if sorted(on_disk) != disk or len(on_disk) != len(disk):
            raise self.violation(
                f"W2 flushed stream is not strictly ordered and "
                f"duplicate-free: {disk}")
        if result.completed and not result.crashed:
            want = list(range(1, self.twin.seq + 1))
            if disk != want:
                raise self.violation(
                    f"W1 clean completion but flushed stream {disk} != "
                    f"{want}")

    def check(self, result: RunResult) -> None:
        if result.wedged:
            raise self.violation("wal run exceeded its step budget")


# ------------------------------------------------------- federation lease

#: two names chosen so the two-member ring splits them (rs/r2 -> m0,
#: rs/r0 -> m1) while a lone member owns both — the interleavings where
#: one member acquires a name before the other joins, and the ring
#: reassigns it on the join, are exactly where a broken arbiter splits
#: ownership
LEASE_RESOURCES = ("r2", "r0")


class BrokenFleetArbiter(federation.FleetArbiter):
    """Seeded mutant for L1: acquire skips the holder-lease-liveness
    check — any ring owner 'steals' a grant from a LIVE member, who
    keeps believing it owns the resource. Split-brain by construction."""

    def acquire(self, resource, name, member):
        with self._lock:
            now = self.clock()
            live = self._sweep_expired(now)
            if member not in live:
                raise federation.LeaseError("no-lease", f"{member} dead")
            owner = federation.HashRing.owner_of(f"{resource}/{name}",
                                                 live)
            if owner != member:
                raise federation.LeaseError("not-owner", f"-> {owner}",
                                            owner=owner or "")
            gk = federation.grant_key(resource, name)
            kv = self.store.get(gk)
            prev = json.loads(kv.value) if kv is not None else None
            # BUG: no prev["holder"] in live refusal — live holders are
            # stolen from exactly like expired ones
            doc = {"resource": resource, "name": name, "holder": member,
                   "epoch": (prev or {}).get("epoch", 0) + 1}
            self.store.put(gk, json.dumps(doc))
            doc = dict(doc)
            doc["stolenFrom"] = (prev or {}).get("holder", "")
            return doc


class NoExpiryFleetArbiter(federation.FleetArbiter):
    """Seeded mutant for L2: the expiry sweep never expires anything, so
    a SIGKILLed member's lease pins its grants forever — no survivor can
    steal, ownership never heals."""

    def _sweep_expired(self, now):
        return self._leases()      # BUG: every lease is forever live


class LeaseModel(Model):
    """Two FleetMembers working the REAL arbiter + member protocol over
    an in-memory MVCC store, on a logical clock. Each member joins, races
    to acquire both names through the ring, then heartbeats; the healer
    (the surviving daemons' watchdog cadence, not killable) waits for the
    members to settle, expires the dead by advancing the clock past the
    TTL, and drives the survivors' heartbeats — which must fence, rejoin,
    re-derive, and steal every orphan. L1 is checked at EVERY scheduler
    step via step_hook; L2 at the frozen end state."""

    name = "lease"

    TTL = 10.0

    def __init__(self, sched: Scheduler,
                 arbiter_cls: type = federation.FleetArbiter):
        super().__init__(sched)
        self.now = 0.0
        self.store = MVCCStore()
        self.arbiter = arbiter_cls(self.store, ttl=self.TTL,
                                   clock=lambda: self.now)
        self.members: dict[str, federation.FleetMember] = {}
        for m in ("m0", "m1"):
            member = federation.FleetMember(
                m, self.arbiter,
                crash_seam=lambda tag: sched.yield_point(("seam", tag)))
            self.members[m] = member
            sched.spawn(m, self._member_fn(member))
        sched.spawn("healer", self._healer, killable=False)
        sched.step_hook = self._check_l1

    def _member_fn(self, member) -> Callable[[], None]:
        def fn() -> None:
            member.join()
            self.sched.yield_point(("joined", 0))
            for r in LEASE_RESOURCES:
                try:
                    member.ensure_owned("rs", r)
                except federation.LeaseError:
                    pass        # not ours on the current ring — clean loss
                self.sched.yield_point(("acq", 0))
            member.heartbeat_once()
        return fn

    def _healer(self) -> None:
        procs = self.sched.procs
        while not all(procs[m].done or procs[m].killed
                      for m in self.members):
            self.sched.yield_point(("heal-wait", 0))
        if not any(procs[m].killed for m in self.members):
            return
        # the arbiter's clock passes the dead member's expiry; survivors'
        # next beats fence (their own leases expired too), rejoin, and
        # sweep the orphans. Two beats: the first may spend its pass
        # rejoining, the second must converge.
        self.now += self.TTL + 1.0
        for _ in range(2):
            for m, member in self.members.items():
                if not procs[m].killed:
                    member.heartbeat_once()

    # ---- invariants ------------------------------------------------------

    def _check_l1(self) -> None:
        for r in LEASE_RESOURCES:
            holders = [m for m, member in self.members.items()
                       if ("rs", r) in member.owned
                       and not self.sched.procs[m].killed]
            if len(holders) > 1:
                raise self.violation(
                    f"L1 split brain: {holders} both believe they own "
                    f"rs/{r}")

    def finish(self, result: RunResult) -> None:
        self._check_l1()
        live_procs = {m for m in self.members
                      if not self.sched.procs[m].killed}
        if not live_procs:
            return      # whole fleet dead: nothing to heal with
        # L2 is about GRANTS healing: every grant row a dead member left
        # behind must have been stolen by a live ring owner within one
        # expiry + two heartbeat rounds, and every surviving grant must
        # be believed by its holder. (A name the dead member never
        # acquired has no grant to heal — it is reacquired on demand by
        # the next ensure_owned, not by the takeover sweep.)
        leases = {d["member"] for d in self.arbiter.members()}
        for g in self.arbiter.grants():
            holder = g["holder"]
            rid = (g["resource"], g["name"])
            if holder not in live_procs or holder not in leases:
                raise self.violation(
                    f"L2 heal incomplete: {g['resource']}/{g['name']} "
                    f"still granted to {holder!r} (live procs "
                    f"{sorted(live_procs)}, live leases {sorted(leases)}) "
                    f"after expiry + 2 heartbeat rounds")
            if rid not in self.members[holder].owned:
                raise self.violation(
                    f"L2 grant/belief split: {g['resource']}/{g['name']} "
                    f"granted to {holder} but the member does not "
                    f"believe it")

    def check(self, result: RunResult) -> None:
        if result.wedged:
            raise self.violation("lease run exceeded its step budget")


# ------------------------------------------------------- federation watch

class BrokenWatchHubDup(federation.WatchHub):
    """Seeded mutant for FW1 (duplicate direction): resume returns
    events with revision >= cursor — the last-applied event is delivered
    again after every reconnect."""

    def _since_locked(self, revision, resource):
        if revision < self.floor:
            raise federation.WatchCompactedError(revision, self.floor)
        return [e for e in self._ring
                if e["revision"] >= revision        # BUG: off-by-one
                and (not resource or e["resource"] == resource)]


class BrokenWatchHubDrop(federation.WatchHub):
    """Seeded mutant for FW1 (drop direction): resume skips the first
    pending event — a takeover resume silently loses one revision."""

    def _since_locked(self, revision, resource):
        if revision < self.floor:
            raise federation.WatchCompactedError(revision, self.floor)
        return [e for e in self._ring
                if e["revision"] > revision + 1     # BUG: skips one
                and (not resource or e["resource"] == resource)]


class FedWatchModel(Model):
    """One writer mutating watched keys through the REAL WatchedStore;
    one killable consumer applying hub events to an informer cache with
    an atomically-updated cursor; one resume consumer (the informer
    reconnected against the takeover survivor, not killable) that drains
    from the shared cursor once the first consumer settles. FW1 at the
    frozen end state."""

    name = "fedwatch"

    KEYS = ("c0", "c1")

    def __init__(self, sched: Scheduler,
                 hub_cls: type = federation.WatchHub):
        super().__init__(sched)
        self.hub = hub_cls(capacity=64)
        self.store = federation.WatchedStore(MVCCStore(), self.hub)
        self.cache: dict[str, dict] = {}
        self.applied: list[int] = []
        self.cursor = self.store.revision
        sched.spawn("writer", self._writer)
        sched.spawn("consumer", self._consumer_fn(resume=False))
        sched.spawn("resume", self._consumer_fn(resume=True),
                    killable=False)

    def _writer(self) -> None:
        base = "/tpu-docker-api/apis/v1/containers"
        self.store.put(f"{base}/{self.KEYS[0]}", "v1")
        self.sched.yield_point(("put", 0))
        self.store.put(f"{base}/{self.KEYS[1]}", "v1")
        self.sched.yield_point(("put", 1))
        self.store.put(f"{base}/{self.KEYS[0]}", "v2")
        self.sched.yield_point(("put", 2))
        self.store.delete(f"{base}/{self.KEYS[1]}")

    def _drain(self) -> bool:
        """Apply every pending event; cache+applied+cursor move together
        between yield points (the informer's apply is one critical
        section — a kill lands before or after an apply, never inside)."""
        evts = self.hub.events_since(self.cursor, resource="containers")
        for e in evts:
            if e["type"] == "delete":
                self.cache.pop(e["name"], None)
            else:
                self.cache[e["name"]] = {"value": e["value"],
                                         "modRevision": e["revision"]}
            self.applied.append(e["revision"])
            self.cursor = e["revision"]
            self.sched.yield_point(("apply", e["revision"]))
        return bool(evts)

    def _consumer_fn(self, resume: bool) -> Callable[[], None]:
        def fn() -> None:
            procs = self.sched.procs
            if resume:
                # the reconnected informer takes over only after the
                # first consumer is gone — one live consumer per cursor,
                # which is the informer contract (the resume happens
                # AGAINST the surviving daemon, not alongside the dying
                # one)
                while not (procs["consumer"].done
                           or procs["consumer"].killed):
                    self.sched.yield_point(("wait-handoff", 0))
            upstream = ("writer", "consumer") if resume else ("writer",)
            while True:
                progressed = self._drain()
                if not progressed and all(procs[u].done or procs[u].killed
                                          for u in upstream):
                    if not self._drain():     # settled: one final sweep
                        return
                self.sched.yield_point(("poll", int(resume)))
        return fn

    def finish(self, result: RunResult) -> None:
        for prev, cur in zip(self.applied, self.applied[1:]):
            if cur <= prev:
                raise self.violation(
                    f"FW1 duplicated/reordered revision: applied "
                    f"sequence {self.applied} is not strictly increasing")
        if self.sched.procs["writer"].killed:
            # store state is still well-defined (kills land at yield
            # points, never inside a put) — the cache must match it
            pass
        want = {}
        prefix = "/tpu-docker-api/apis/v1/containers/"
        for kv in self.store.range(prefix):
            want[kv.key[len(prefix):]] = {"value": kv.value,
                                          "modRevision": kv.mod_revision}
        got = {k: v for k, v in self.cache.items()}
        if got != want:
            raise self.violation(
                f"FW1 dropped revision: informer cache {got} != watched "
                f"store state {want} after the resume consumer settled")

    def check(self, result: RunResult) -> None:
        if result.wedged:
            raise self.violation("fedwatch run exceeded its step budget")


# --------------------------------------------------------- promote-on-loss

#: the one acked-write key the promote model replicates and promotes
PROMOTE_RESOURCE = ("containers", "c0")


class ReplicaTwin:
    """The StandbyReplicator's apply contract over the in-model hub:
    drain watch events in revision order into a replica store at the
    peer's EXACT revisions (put_at/delete_at), horizon = highest drained
    revision. The HTTP transport the real replicator rides is
    integration-tested (tests/test_durability.py); what the model checks
    is the contract the promote path leans on: the replica is a prefix
    of the watchable history through `horizon`."""

    def __init__(self, hub: federation.WatchHub, replica: MVCCStore):
        self.hub = hub
        self.replica = replica
        self.horizon = 0

    def apply_filter(self, evts: list[dict]) -> list[dict]:
        return evts

    def drain(self) -> bool:
        evts = self.hub.events_since(self.horizon)
        for e in self.apply_filter(evts):
            key = replication.resource_key(e["resource"], e["name"])
            if e["type"] == "delete":
                self.replica.delete_at(key, e["revision"])
            else:
                self.replica.put_at(key, e["value"], e["revision"])
        if evts:
            self.horizon = max(self.horizon, evts[-1]["revision"])
        return bool(evts)


class BrokenReplicaSkip(ReplicaTwin):
    """Seeded mutant for R1: drops one event but still advances the
    horizon past it — the replicated-horizon promise is a lie by one
    revision, and a promote at that horizon loses an acked write."""

    def __init__(self, hub, replica):
        super().__init__(hub, replica)
        self._dropped = False

    def apply_filter(self, evts):
        if evts and not self._dropped:
            self._dropped = True
            return evts[1:]     # BUG: horizon still reaches evts[-1]
        return evts


class BrokenPromoteMember(federation.FleetMember):
    """Seeded mutant for R2: the takeover sweep promotes even when the
    arbiter refused the steal (and skips the ring check so two standbys
    both try) — the single-winner acquire IS the fence this discards,
    so two members install two lineages of the dead daemon's records."""

    def heartbeat_once(self) -> dict:
        try:
            out = self.arbiter.renew(self.member_id)
        except federation.LeaseError as e:
            if e.reason != "no-lease":
                raise
            self.fence()
            out = self.join()
        live = set(out["members"])
        grants = self.arbiter.grants()
        self.owned = {(g["resource"], g["name"]) for g in grants
                      if g["holder"] == self.member_id}
        adopted = []
        for g in grants:
            rid = (g["resource"], g["name"])
            if g["holder"] in live or rid in self.owned:
                continue
            try:
                self.arbiter.acquire(g["resource"], g["name"],
                                     self.member_id)
            except federation.LeaseError:
                pass    # BUG: lost the steal race — promote anyway
            self.crash_seam("fed.after_takeover")
            self.owned.add(rid)
            adopted.append(f"{g['resource']}/{g['name']}")
            if self.promote is not None:
                self.promote(g["resource"], g["name"])
                self.crash_seam("fed.after_promote")
        return {"adopted": adopted}


class PromoteModel(Model):
    """Promote-on-loss over the REAL protocol pieces: a killable primary
    (FleetMember seat + WatchedStore feeding a WatchHub) writes acked
    revisions to its granted resource; a replica twin drains the hub in
    order (the StandbyReplicator apply contract); two standbys — real
    FleetMembers with the production promote hook shape — wait out the
    primary, expire its lease, and race heartbeat_once to steal the
    orphan grant and install the replica's record behind the steal's
    fencing epoch. The injected SIGKILL enumerates every yield point of
    the primary, crash seams included.

    R1  no acked revision at-or-below the horizon-at-promote is lost:
        the promoted store's record is at least as new as the last ack
        the horizon covers.
    R2  at most one promoted lineage: the promoters set never exceeds
        one member (the arbiter's single-winner steal is the fence).
    """

    name = "promote"

    TTL = 10.0
    ACKS = ("v1", "v2", "v3")

    def __init__(self, sched: Scheduler,
                 replica_cls: type = ReplicaTwin,
                 member_cls: type = federation.FleetMember):
        super().__init__(sched)
        self.now = 0.0
        self.astore = MVCCStore()       # the arbiter's table (survives)
        self.arbiter = federation.FleetArbiter(self.astore, ttl=self.TTL,
                                               clock=lambda: self.now)
        self.hub = federation.WatchHub(capacity=64)
        self.pstore = federation.WatchedStore(MVCCStore(), self.hub)
        self.repl = replica_cls(self.hub, MVCCStore())
        self.acked: list[tuple[int, str]] = []
        self.promotes: list[tuple[str, str, str, int]] = []
        self._expired = False
        seam = lambda tag: sched.yield_point(("seam", tag))  # noqa: E731
        self.primary = federation.FleetMember("primary", self.arbiter,
                                              crash_seam=seam)
        self.stores: dict[str, MVCCStore] = {}
        self.standbys: dict[str, federation.FleetMember] = {}
        for m in ("s0", "s1"):
            self.stores[m] = MVCCStore()
            self.standbys[m] = member_cls(
                m, self.arbiter, promote=self._promote_hook(m),
                crash_seam=seam)
        sched.spawn("primary", self._primary)
        sched.spawn("repl", self._replicator, killable=False)
        for m in ("s0", "s1"):
            sched.spawn(m, self._standby_fn(m), killable=False)

    def _promote_hook(self, m: str) -> Callable[[str, str], None]:
        def hook(resource: str, name: str) -> None:
            # mirror of App._fleet_promote: install the replica's copy
            # only when the local store lacks the key (idempotent —
            # a crash between promote and adopt re-runs it harmlessly)
            self.promotes.append((m, resource, name, self.repl.horizon))
            key = replication.resource_key(resource, name)
            kv = self.repl.replica.get(key)
            if kv is not None and self.stores[m].get(key) is None:
                self.stores[m].put(key, kv.value)
        return hook

    def _primary(self) -> None:
        self.primary.join()
        self.sched.yield_point(("joined", 0))
        try:
            self.primary.ensure_owned(*PROMOTE_RESOURCE)
        except federation.LeaseError:
            return      # not ours on this ring — nothing to write
        key = replication.resource_key(*PROMOTE_RESOURCE)
        for i, v in enumerate(self.ACKS):
            rev = self.pstore.put(key, v)
            # the put returned: the write is acked to the client AND in
            # the hub (WatchedStore feeds it under the same lock) — a
            # kill can land after this step, never between the two
            self.acked.append((rev, v))
            self.sched.yield_point(("ack", i))

    def _replicator(self) -> None:
        procs = self.sched.procs
        while True:
            progressed = self.repl.drain()
            if not progressed and (procs["primary"].done
                                   or procs["primary"].killed):
                if not self.repl.drain():   # settled: one final sweep
                    return
            self.sched.yield_point(("drain", 0))

    def _standby_fn(self, m: str) -> Callable[[], None]:
        member = self.standbys[m]

        def fn() -> None:
            procs = self.sched.procs
            while not (procs["primary"].done or procs["primary"].killed):
                self.sched.yield_point(("standby-wait", 0))
            if not procs["primary"].killed:
                return      # clean exit: nothing to take over
            # the replica settles first: promote's promise is relative
            # to the horizon at promote time whatever it is, but the
            # acceptance scenario is the drained standby
            while not procs["repl"].done:
                self.sched.yield_point(("repl-wait", 0))
            if not self._expired:
                self._expired = True
                self.now += self.TTL + 1.0
            member.join()
            self.sched.yield_point(("sjoined", 0))
            # two beats, same convergence bound as the lease model: the
            # first may spend its pass rejoining, the second must settle
            for _ in range(2):
                member.heartbeat_once()
                self.sched.yield_point(("sbeat", 0))
        return fn

    # ---- invariants ------------------------------------------------------

    @staticmethod
    def _idx(value: str) -> int:
        return int(value[1:])       # "v3" -> 3

    def finish(self, result: RunResult) -> None:
        promoters = {m for (m, _, _, _) in self.promotes}
        if len(promoters) > 1:
            raise self.violation(
                f"R2 double promote: {sorted(promoters)} each installed "
                f"a lineage of {'/'.join(PROMOTE_RESOURCE)} — the steal "
                f"fence admitted two winners")
        for m, resource, name, horizon in self.promotes:
            covered = [v for (rev, v) in self.acked if rev <= horizon]
            if not covered:
                continue
            key = replication.resource_key(resource, name)
            got = self.stores[m].get(key)
            if got is None or self._idx(got.value) < self._idx(covered[-1]):
                raise self.violation(
                    f"R1 acked revision lost: horizon at promote was "
                    f"{horizon}, which covers ack {covered[-1]!r}, but "
                    f"{m}'s promoted store has "
                    f"{got.value if got else None!r} for {key}")

    def check(self, result: RunResult) -> None:
        if result.wedged:
            raise self.violation("promote run exceeded its step budget")


# ---------------------------------------------------------------- sweeps

def _annotating(variant: str, run_once):
    """Stamp any escaping InvariantViolation with the pass's variant so
    its reproduce line reconstructs the SAME model shape."""
    def wrapped(strategy: Strategy) -> RunResult:
        try:
            return run_once(strategy)
        except InvariantViolation as v:
            v.variant = variant
            raise
    return wrapped


def _tally(stats: dict, res: RunResult) -> None:
    stats["schedules"] += 1
    stats["killed_runs"] += bool(res.killed)
    stats["_digest"].update(repr(res.schedule).encode())


def _seal(stats: dict) -> dict:
    stats["digest"] = stats.pop("_digest").hexdigest()
    return stats


def _new_stats(model: str) -> dict:
    return {"model": model, "schedules": 0, "killed_runs": 0,
            "_digest": hashlib.sha256()}


def sweep_seqlock(mode: str = "exhaustive", max_schedules: int = 4000,
                  seed: int = 0, preemptions: int = 2,
                  state_cls: type = InstrumentedState) -> dict:
    """Two passes: the torn-read sweep (no kills, full preemption bound)
    and the kill+heal sweep (1 injected writer SIGKILL + the daemon's
    republish). The kill pass runs at preemption bound 0: the kill
    placement is itself the enumerated disturbance — every yield point
    of every writer gets a crash — and the fairness cap still forces
    reader/healer interleaving through the recovery, which keeps the
    pass's tree fully sweepable."""
    stats = _new_stats("seqlock")

    def torn(strategy: Strategy) -> RunResult:
        return run_model(lambda s: SeqlockModel(s, heal=False,
                                                state_cls=state_cls),
                         strategy, preemptions=preemptions, kills=0)

    def heal(strategy: Strategy) -> RunResult:
        return run_model(lambda s: SeqlockModel(s, heal=True,
                                                state_cls=state_cls),
                         strategy, preemptions=0, kills=1)

    for run_once in (_annotating("torn", torn), _annotating("heal", heal)):
        for res in explore(run_once, mode=mode,
                           max_schedules=max_schedules, seed=seed):
            _tally(stats, res)
    return _seal(stats)


def sweep_claim(mode: str = "exhaustive", max_schedules: int = 4000,
                seed: int = 0, preemptions: int = 2,
                router_cls: type = workers.WorkerRouter) -> dict:
    stats = _new_stats("claim")

    def no_kill(strategy: Strategy) -> RunResult:
        return run_model(lambda s: ClaimModel(s, router_cls=router_cls,
                                              daemon=False),
                         strategy, preemptions=preemptions, kills=0)

    def kill(strategy: Strategy) -> RunResult:
        # preemption bound 0 for the same reason as the seqlock kill
        # pass: the enumerated disturbance is the kill point itself
        return run_model(lambda s: ClaimModel(s, router_cls=router_cls),
                         strategy, preemptions=0, kills=1)

    for run_once in (_annotating("no-kill", no_kill),
                     _annotating("kill", kill)):
        for res in explore(run_once, mode=mode,
                           max_schedules=max_schedules, seed=seed):
            _tally(stats, res)
    return _seal(stats)


def sweep_wal(mode: str = "exhaustive", max_schedules: int = 4000,
              seed: int = 0, preemptions: int = 2,
              twin_cls: type = WalTwin) -> dict:
    stats = _new_stats("wal")

    def run_once(strategy: Strategy) -> RunResult:
        return run_model(lambda s: WalModel(s, twin_cls=twin_cls),
                         strategy, preemptions=preemptions, kills=1,
                         crash_all=True)

    for res in explore(run_once, mode=mode,
                       max_schedules=max_schedules, seed=seed):
        _tally(stats, res)
    return _seal(stats)


def sweep_lease(mode: str = "exhaustive", max_schedules: int = 4000,
                seed: int = 0, preemptions: int = 2,
                arbiter_cls: type = federation.FleetArbiter) -> dict:
    """Two passes, same shape as claim: the no-kill pass explores
    acquire/join/ring-change interleavings at the preemption bound; the
    kill pass injects one member SIGKILL at every yield point (the
    production crash seams included) with the kill placement as the
    enumerated disturbance."""
    stats = _new_stats("lease")

    def no_kill(strategy: Strategy) -> RunResult:
        return run_model(lambda s: LeaseModel(s, arbiter_cls=arbiter_cls),
                         strategy, preemptions=preemptions, kills=0)

    def kill(strategy: Strategy) -> RunResult:
        return run_model(lambda s: LeaseModel(s, arbiter_cls=arbiter_cls),
                         strategy, preemptions=0, kills=1)

    for run_once in (_annotating("no-kill", no_kill),
                     _annotating("kill", kill)):
        for res in explore(run_once, mode=mode,
                           max_schedules=max_schedules, seed=seed):
            _tally(stats, res)
    return _seal(stats)


def sweep_fedwatch(mode: str = "exhaustive", max_schedules: int = 4000,
                   seed: int = 0, preemptions: int = 2,
                   hub_cls: type = federation.WatchHub) -> dict:
    stats = _new_stats("fedwatch")

    def no_kill(strategy: Strategy) -> RunResult:
        return run_model(lambda s: FedWatchModel(s, hub_cls=hub_cls),
                         strategy, preemptions=preemptions, kills=0)

    def kill(strategy: Strategy) -> RunResult:
        return run_model(lambda s: FedWatchModel(s, hub_cls=hub_cls),
                         strategy, preemptions=0, kills=1)

    for run_once in (_annotating("no-kill", no_kill),
                     _annotating("kill", kill)):
        for res in explore(run_once, mode=mode,
                           max_schedules=max_schedules, seed=seed):
            _tally(stats, res)
    return _seal(stats)


def sweep_promote(mode: str = "exhaustive", max_schedules: int = 4000,
                  seed: int = 0, preemptions: int = 2,
                  replica_cls: type = ReplicaTwin,
                  member_cls: type = federation.FleetMember) -> dict:
    """Two passes, same shape as lease: the no-kill pass explores
    writer/replicator interleavings (no takeover fires — the clean-exit
    baseline); the kill pass injects one primary SIGKILL at every yield
    point — acks, crash seams, and the replicator's drain windows are
    the enumerated disturbance — and the standbys' takeover + promote
    must satisfy R1/R2 on every placement."""
    stats = _new_stats("promote")

    def no_kill(strategy: Strategy) -> RunResult:
        return run_model(lambda s: PromoteModel(s, replica_cls=replica_cls,
                                                member_cls=member_cls),
                         strategy, preemptions=preemptions, kills=0)

    def kill(strategy: Strategy) -> RunResult:
        return run_model(lambda s: PromoteModel(s, replica_cls=replica_cls,
                                                member_cls=member_cls),
                         strategy, preemptions=0, kills=1)

    for run_once in (_annotating("no-kill", no_kill),
                     _annotating("kill", kill)):
        for res in explore(run_once, mode=mode,
                           max_schedules=max_schedules, seed=seed):
            _tally(stats, res)
    return _seal(stats)


SWEEPS = {"seqlock": sweep_seqlock, "claim": sweep_claim, "wal": sweep_wal,
          "lease": sweep_lease, "fedwatch": sweep_fedwatch,
          "promote": sweep_promote}

MUTANTS = {
    "seqlock": lambda **kw: sweep_seqlock(state_cls=BrokenSeqlockState,
                                          **kw),
    "claim": lambda **kw: sweep_claim(router_cls=BrokenClaimRouter, **kw),
    "wal": lambda **kw: sweep_wal(twin_cls=BrokenWalTwin, **kw),
    # the CLI gate proves one mutant per model; the L2 (NoExpiry) and
    # drop-direction watch mutants are proven in tests/test_federation.py,
    # the R2 (BrokenPromoteMember) mutant in tests/test_durability.py
    "lease": lambda **kw: sweep_lease(arbiter_cls=BrokenFleetArbiter,
                                      **kw),
    "fedwatch": lambda **kw: sweep_fedwatch(hub_cls=BrokenWatchHubDup,
                                            **kw),
    "promote": lambda **kw: sweep_promote(replica_cls=BrokenReplicaSkip,
                                          **kw),
}
