"""The three checked protocols, their invariants, and their seeded-broken
mutant twins.

Each model wires REAL protocol code (or, for the C++ group commit, a
line-for-line Python twin of native/mvcc_store.cc's state machine) into
the cooperative scheduler and states its invariants as code. Every
checker is proven LIVE by a mutant twin that reintroduces the bug class
the checker exists to catch — a checker that cannot fail its mutant is
decoration, exactly like a tdlint rule without its bad fixture.

Invariant catalog (docs/correctness.md carries the prose version):

seqlock (real `SharedRouterState.publish` / `read_roster`):
  S1  a reader never parses a torn roster — every observed roster is
      bytewise one of the published ones.
  S2  a writer SIGKILLed inside the publish window (epoch parked odd)
      does not wedge readers forever: the daemon's heal republish
      recovers them (exercised against the real publish, which must be
      re-enterable from a crashed-odd epoch).

claim/undo/reconcile (real `WorkerRouter._try_claim` / `_release` /
`SharedRouterState.reconcile_worker`):
  C1  no schedule admits past a replica's advertised slots (live
      concurrently-held claims <= slots, at every admission).
  C2  after any SIGKILL + reconcile, the global inflight counter equals
      the live outstanding claims plus, per killed worker, a surplus
      that is EXACTLY the worker's (global ops) - (ledger ops) imbalance
      at the kill point — i.e. reconcile's arithmetic is exact, and the
      only reachable discrepancy is the documented one-op window where
      the counter reads HIGH (brief under-admit). It must never read
      LOW: a negative imbalance means the ledger ran ahead of the
      global fetch_add and reconcile would free capacity that was never
      claimed — the double-admit direction the "ledger only after
      global claim" ordering exists to prevent.

WAL group commit (Python twin of native/mvcc_store.cc Append/Commit):
  W1  Commit(seq) returning implies the record's batch was flushed: a
      crash at ANY yield point never loses an acked record, across
      leader handoff (a follower acked by another leader's flush).
  W2  the flushed stream is strictly ordered and duplicate-free.
"""

from __future__ import annotations

import hashlib
from typing import Callable, Optional

from gpu_docker_api_tpu.server import workers

from .instrument import BrokenSeqlockState, InstrumentedState, install_seams
from .sched import (
    InvariantViolation, RunResult, Scheduler, Strategy, explore,
)

# ---------------------------------------------------------------- harness


def run_model(factory: Callable[[Scheduler], "Model"], strategy: Strategy,
              *, max_steps: int = 400, preemptions: int = 2,
              kills: int = 0, crash_all: bool = False,
              fair_cap: int = 8, starve_cap: int = 16) -> RunResult:
    """One schedule: build the model, run it, check its invariants.
    Raises InvariantViolation (with the replayable schedule) on any
    failure; returns the RunResult otherwise."""
    sched = Scheduler(strategy, max_steps=max_steps,
                      preemptions=preemptions, kills=kills,
                      crash_all=crash_all, fair_cap=fair_cap,
                      starve_cap=starve_cap)
    model = factory(sched)
    sched.end_hook = model.finish
    try:
        with install_seams(sched):
            result = sched.run()
        err = result.error
        if isinstance(err, InvariantViolation):
            raise InvariantViolation(err.model, err.message,
                                     schedule=result.schedule)
        if err is not None:
            raise InvariantViolation(
                model.name, f"modeled code raised {err!r}",
                schedule=result.schedule) from err
        model.check(result)
        return result
    finally:
        model.close()


class Model:
    name = "model"

    def __init__(self, sched: Scheduler):
        self.sched = sched

    def violation(self, message: str) -> InvariantViolation:
        return InvariantViolation(self.name, message,
                                  schedule=self.sched.trace)

    def finish(self, result: RunResult) -> None:
        """Frozen-state checks — runs BEFORE teardown unwind."""

    def check(self, result: RunResult) -> None:
        """Result-shape checks — runs after teardown."""

    def close(self) -> None:
        pass


# ---------------------------------------------------------------- seqlock

ROSTER_A = [{"name": "alpha", "maxQueue": 4, "deadlineMs": 1000,
             "replicas": [{"port": 1, "slots": 1, "ready": True},
                          {"port": 2, "slots": 2, "ready": True}]}]
ROSTER_B = [{"name": "alpha", "maxQueue": 9, "deadlineMs": 9000,
             "replicas": [{"port": 9, "slots": 9, "ready": True}]}]


def _shape(gw: Optional[dict]) -> Optional[tuple]:
    if gw is None:
        return None
    return (gw["maxQueue"], gw["deadlineMs"],
            tuple((r["port"], r["slots"]) for r in gw["replicas"]))


SHAPE_A = _shape({"maxQueue": 4, "deadlineMs": 1000,
                  "replicas": [{"port": 1, "slots": 1},
                               {"port": 2, "slots": 2}]})
SHAPE_B = _shape({"maxQueue": 9, "deadlineMs": 9000,
                  "replicas": [{"port": 9, "slots": 9}]})


class PublisherGate:
    """The seqlock's single-writer contract, as model harness: in the
    real tier every publish runs on ONE daemon watchdog thread, so two
    publishes never interleave (tdcheck demonstrated that concurrent
    publishers DO tear the roster — the protocol's documented contract,
    now machine-checked rather than assumed). A KILLED holder models a
    crashed daemon: its successor (the heal republish, or a federation
    peer taking over the segment lease) reclaims the gate and publishes
    over whatever epoch parity the corpse left behind — which is exactly
    the crashed-odd re-entry path `publish` must handle."""

    def __init__(self, sched: Scheduler):
        self.sched = sched
        self.owner: Optional[str] = None

    def acquire(self) -> None:
        while True:
            self.sched.yield_point(("gate", 0))
            own = self.owner
            if own is None or self.sched.procs[own].killed:
                self.owner = self.sched.current
                return

    def release(self) -> None:
        self.owner = None


class SeqlockModel(Model):
    """2 writers / 1 reader over the REAL publish/read_roster, plus (in
    the kill sweep) the daemon's heal republish as a fourth process.
    Writers serialize through the PublisherGate (the single-writer
    contract); the reader and kill injection interleave freely."""

    name = "seqlock"

    def __init__(self, sched: Scheduler, heal: bool = False,
                 state_cls: type = InstrumentedState):
        super().__init__(sched)
        self.heal = heal
        self.st = state_cls(sched)
        # setup runs inline on the controller thread (yield points are
        # no-ops there): slot 0 is already named, so modeled publishes
        # never take the slot-identity-change branch and its ~170 cell
        # zeroes — the seqlock window itself is what's under test
        self.st.publish(ROSTER_A)
        self.gate = PublisherGate(sched)
        self.observed: list[tuple] = []
        sched.spawn("w0", self._writer_fn(ROSTER_A))
        sched.spawn("w1", self._writer_fn(ROSTER_B))
        sched.spawn("reader", self._reader, killable=False)
        if heal:
            sched.spawn("heal", self._healer, killable=False)

    def _writer_fn(self, roster: list[dict]) -> Callable[[], None]:
        def fn() -> None:
            self.gate.acquire()
            self.st.publish(roster)
            self.gate.release()
        return fn

    def _reader(self) -> None:
        _, roster = self.st.read_roster()
        self.observed.append(_shape(roster.get("alpha")))

    def _healer(self) -> None:
        # the daemon's 250ms heal republish: fires once the writers have
        # settled (done or killed), like the watchdog tick after a crash
        procs = self.sched.procs
        while not all(procs[w].done or procs[w].killed
                      for w in ("w0", "w1")):
            self.sched.yield_point(("heal-wait", 0))
        if any(procs[w].killed for w in ("w0", "w1")):
            self.gate.acquire()
            self.st.publish(ROSTER_B)
            self.gate.release()

    def check(self, result: RunResult) -> None:
        for shape in self.observed:
            if shape not in (SHAPE_A, SHAPE_B):
                raise self.violation(
                    f"S1 torn roster parsed by a reader: {shape!r} is "
                    f"neither published roster")
        if result.wedged:
            raise self.violation(
                "S2 reader wedged: the heal republish did not recover "
                "readers from a crashed publish"
                if self.heal else
                "run exceeded its step budget (no heal process in this "
                "variant — check bounds)")

    def close(self) -> None:
        self.st.close(unlink=True)


# ------------------------------------------------------- claim/reconcile

CLAIM_ROSTER = [{"name": "g", "maxQueue": 8, "deadlineMs": 60000,
                 "replicas": [{"port": 7001, "slots": 1, "ready": True}]}]


class ClaimModel(Model):
    """2 workers × 2 claim/hold/release iterations against ONE advertised
    slot, with the daemon's watchdog reconciling any killed worker
    mid-run. Uses the real WorkerRouter claim path (or a seeded-broken
    mutant of it)."""

    name = "claim"

    ITERS = 2
    SLOTS = 1

    def __init__(self, sched: Scheduler,
                 router_cls: type = workers.WorkerRouter,
                 daemon: bool = True):
        super().__init__(sched)
        self.st = InstrumentedState(sched, note=self._note)
        self.st.publish(CLAIM_ROSTER)     # inline setup: no yields
        self.rep_off = workers._rep_cnt_off(0, 0)
        self.wk_offs = {workers._wk_claim_off(w, 0, 0): w
                        for w in range(2)}
        self.ops: dict[str, list[tuple[str, int]]] = {}
        self.outstanding: dict[str, int] = {}   # proc -> held claims
        self.reconciled: set[int] = set()
        self.names = {"k0": 0, "k1": 1}
        for name, widx in self.names.items():
            router = router_cls(self.st, widx)
            gw = router._gateway("g")       # inline prewarm
            sched.spawn(name, self._worker_fn(name, router, gw))
        if daemon:
            # the watchdog only matters once a worker can die — the
            # no-kill sweep leaves it out to keep the tree small
            sched.spawn("daemon", self._daemon, killable=False)

    # ---- op attribution (the claim-window oracle) ------------------------

    def _note(self, note) -> None:
        proc, op, off, _val = note
        if proc is None or op not in ("add", "dec"):
            return
        if off == self.rep_off or off in self.wk_offs:
            self.ops.setdefault(proc, []).append((op, off))

    def _imbalance(self, proc: str) -> int:
        """(global ops) - (ledger ops) net for one worker's op log: how
        far the global counter over-counts this worker relative to its
        reconcile-visible ledger. >0 = counter reads high after
        reconcile (safe, brief under-admit); <0 = ledger ran AHEAD of
        the global claim — the double-admit direction."""
        g = led = 0
        for op, off in self.ops.get(proc, ()):
            d = 1 if op == "add" else -1
            if off == self.rep_off:
                g += d
            else:
                led += d
        return g - led

    # ---- processes -------------------------------------------------------

    def _worker_fn(self, name: str, router, gw) -> Callable[[], None]:
        def fn() -> None:
            for _ in range(self.ITERS):
                c = router._try_claim(gw)
                if c is None:
                    self.sched.yield_point(("retry", 0))
                    continue
                live = sum(n for p, n in self.outstanding.items()
                           if not self.sched.procs[p].killed)
                if live + 1 > self.SLOTS:
                    raise self.violation(
                        f"C1 double admit: {name} claimed slot while "
                        f"{live} live claim(s) already held "
                        f"(slots={self.SLOTS})")
                self.outstanding[name] = self.outstanding.get(name, 0) + 1
                self.sched.yield_point(("hold", 0))
                self.outstanding[name] -= 1
                router._release(c)
        return fn

    def _daemon(self) -> None:
        procs = self.sched.procs
        while not all(procs[n].done or procs[n].killed
                      for n in self.names):
            self.sched.yield_point(("watchdog", 0))
            self._reconcile_dead()
        self._reconcile_dead()

    def _reconcile_dead(self) -> None:
        for name, widx in self.names.items():
            if self.sched.procs[name].killed and widx not in self.reconciled:
                self.reconciled.add(widx)
                self.st.reconcile_worker(widx)
                self._check_accounting(f"after reconcile of {name}")

    # ---- invariants ------------------------------------------------------

    def _check_accounting(self, when: str) -> None:
        live = sum(n for p, n in self.outstanding.items()
                   if not self.sched.procs[p].killed)
        surplus = 0
        for name, widx in self.names.items():
            if self.sched.procs[name].killed and widx in self.reconciled:
                imb = self._imbalance(name)
                if imb < 0:
                    raise self.violation(
                        f"C2 {when}: {name}'s claim ledger ran AHEAD of "
                        f"its global fetch_add (imbalance {imb}) — "
                        f"reconcile freed capacity that was never "
                        f"claimed (double-admit direction)")
                surplus += imb
        counter = self.st.lib.shm_load(self.st.base + self.rep_off)
        if counter != live + surplus:
            raise self.violation(
                f"C2 {when}: inflight counter {counter} != live "
                f"outstanding {live} + characterized kill-window "
                f"surplus {surplus} — reconcile accounting is not exact")

    def finish(self, result: RunResult) -> None:
        # frozen state: reconcile any worker the daemon didn't get to
        # (killed on the last step), then the exactness check
        self._reconcile_dead()
        self._check_accounting("at end of schedule")
        for widx in self.reconciled:
            led = self.st.lib.shm_load(
                self.st.base + workers._wk_claim_off(widx, 0, 0))
            if led != 0:
                raise self.violation(
                    f"C2 reconciled worker {widx}'s ledger cell is "
                    f"{led}, not zeroed")

    def check(self, result: RunResult) -> None:
        if result.wedged:
            raise self.violation("claim run exceeded its step budget")

    def close(self) -> None:
        self.st.close(unlink=True)


class BrokenClaimRouter(workers.WorkerRouter):
    """Seeded mutant: increments the per-worker claims ledger BEFORE the
    global fetch_add — the exact ordering bug the prose in workers.py
    warns about. A kill between the two makes reconcile subtract a claim
    that never landed globally, freeing someone else's held slot."""

    def _try_claim(self, gw, avoid=frozenset()):
        st = self.state
        g = gw["slot"]
        ready = [(st.load(workers._rep_cnt_off(g, r["idx"])), r)
                 for r in gw["replicas"]
                 if r["ready"] and r["port"] and r["idx"] not in avoid]
        ready.sort(key=lambda t: t[0])
        for _, r in ready:
            off = workers._rep_cnt_off(g, r["idx"])
            wk = workers._wk_claim_off(self.widx, g, r["idx"])
            st.add(wk, 1)                       # BUG: ledger first
            if st.add(off, 1) <= r["slots"]:
                if st.load(workers._gw_cnt_off(g)) != gw["gen"]:
                    st.dec_floor0(off)
                    st.dec_floor0(wk)
                    continue
                return workers._Claim(g, r["idx"], gw["gen"], r["port"])
            st.dec_floor0(off)
            st.dec_floor0(wk)
        return None


# -------------------------------------------------------- WAL group commit

class CoopLock:
    """A mutex in the cooperative world: acquire spins on a yield point
    (the scheduler decides who wins), release is immediate. Only used by
    the WAL twin — crashes there are whole-process (crash_all), so a
    dead owner can never strand a waiter."""

    __slots__ = ("sched", "tag", "owner")

    def __init__(self, sched: Scheduler, tag: str):
        self.sched = sched
        self.tag = tag
        self.owner: Optional[str] = None

    def acquire(self) -> None:
        while True:
            self.sched.yield_point(("lock", self.tag))
            if self.owner is None:
                self.owner = self.sched.current or "<main>"
                return

    def release(self) -> None:
        self.owner = None

    def __enter__(self) -> "CoopLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class WalTwin:
    """Pure-Python twin of native/mvcc_store.cc's leader/follower group
    commit: Append under mu_, Commit blocks until a flush leader has
    written the record's sequence; the leader swaps the pending buffer
    out under mu_, writes it under wal_mu_ WITHOUT holding mu_, then
    marks durable_seq_ under commit_mu_. The cv wait is modeled as
    release-yield-reacquire (spurious wakes are within the contract).
    Cross-validated against the real core by the subprocess kill sweep
    in tests/test_tdcheck.py."""

    def __init__(self, sched: Scheduler):
        self.sched = sched
        self.mu = CoopLock(sched, "mu")
        self.wal_mu = CoopLock(sched, "wal_mu")
        self.commit_mu = CoopLock(sched, "commit_mu")
        self.pending: list[int] = []     # appended, not yet written
        self.filebuf: list[int] = []     # written, not yet fsynced
        self.disk: list[int] = []        # durable
        self.seq = 0
        self.durable = 0
        self.flushing = False
        self.flushes = 0

    def put(self) -> int:
        with self.mu:
            self.seq += 1
            s = self.seq
            self.pending.append(s)
        self.commit(s)
        return s

    def commit(self, s: int) -> None:
        self.commit_mu.acquire()
        try:
            while self.durable < s:
                if self.flushing:
                    # follower: park until the leader marks durable
                    self.commit_mu.release()
                    self.sched.yield_point(("cv-wait", 0))
                    self.commit_mu.acquire()
                    continue
                self.flushing = True
                self.commit_mu.release()
                target = self._flush()
                self.commit_mu.acquire()
                self.flushing = False
                if target > self.durable:
                    self.durable = target
                    self.flushes += 1
        finally:
            self.commit_mu.release()

    def _flush(self) -> int:
        with self.wal_mu:
            with self.mu:
                target = self.seq        # the batch's durable horizon,
                batch = self.pending     # captured AT the swap
                self.pending = []
            self._write(batch)
        return target

    def _write(self, batch: list[int]) -> None:
        for s in batch:
            self.sched.yield_point(("fwrite", s))
            self.filebuf.append(s)       # fwrite: in the stdio buffer
        if batch:
            self.sched.yield_point(("fsync", 0))
            self.disk.extend(self.filebuf)   # fflush+fsync: durable
            self.filebuf.clear()


class BrokenWalTwin(WalTwin):
    """Seeded mutant: the leader reads the durable target AFTER the file
    write — records appended while the flush was on the wire are marked
    durable without ever being written, so their Commit returns and a
    crash loses an acked record."""

    def _flush(self) -> int:
        with self.wal_mu:
            with self.mu:
                batch = self.pending
                self.pending = []
            self._write(batch)
            with self.mu:
                target = self.seq        # BUG: post-write horizon
        return target


class WalModel(Model):
    name = "wal"

    WRITERS = 2
    PUTS = 2

    def __init__(self, sched: Scheduler, twin_cls: type = WalTwin):
        super().__init__(sched)
        self.twin = twin_cls(sched)
        self.acked: list[int] = []
        for i in range(self.WRITERS):
            sched.spawn(f"p{i}", self._writer_fn())

    def _writer_fn(self) -> Callable[[], None]:
        def fn() -> None:
            for _ in range(self.PUTS):
                s = self.twin.put()
                # no yield between commit-return and the ack record: the
                # ack IS the return, same step
                self.acked.append(s)
        return fn

    def finish(self, result: RunResult) -> None:
        disk = self.twin.disk
        on_disk = set(disk)
        for s in self.acked:
            if s not in on_disk:
                raise self.violation(
                    f"W1 Commit({s}) returned but the record is not in "
                    f"the flushed stream {disk} — an acked record would "
                    f"be lost by this crash")
        if sorted(on_disk) != disk or len(on_disk) != len(disk):
            raise self.violation(
                f"W2 flushed stream is not strictly ordered and "
                f"duplicate-free: {disk}")
        if result.completed and not result.crashed:
            want = list(range(1, self.twin.seq + 1))
            if disk != want:
                raise self.violation(
                    f"W1 clean completion but flushed stream {disk} != "
                    f"{want}")

    def check(self, result: RunResult) -> None:
        if result.wedged:
            raise self.violation("wal run exceeded its step budget")


# ---------------------------------------------------------------- sweeps

def _annotating(variant: str, run_once):
    """Stamp any escaping InvariantViolation with the pass's variant so
    its reproduce line reconstructs the SAME model shape."""
    def wrapped(strategy: Strategy) -> RunResult:
        try:
            return run_once(strategy)
        except InvariantViolation as v:
            v.variant = variant
            raise
    return wrapped


def _tally(stats: dict, res: RunResult) -> None:
    stats["schedules"] += 1
    stats["killed_runs"] += bool(res.killed)
    stats["_digest"].update(repr(res.schedule).encode())


def _seal(stats: dict) -> dict:
    stats["digest"] = stats.pop("_digest").hexdigest()
    return stats


def _new_stats(model: str) -> dict:
    return {"model": model, "schedules": 0, "killed_runs": 0,
            "_digest": hashlib.sha256()}


def sweep_seqlock(mode: str = "exhaustive", max_schedules: int = 4000,
                  seed: int = 0, preemptions: int = 2,
                  state_cls: type = InstrumentedState) -> dict:
    """Two passes: the torn-read sweep (no kills, full preemption bound)
    and the kill+heal sweep (1 injected writer SIGKILL + the daemon's
    republish). The kill pass runs at preemption bound 0: the kill
    placement is itself the enumerated disturbance — every yield point
    of every writer gets a crash — and the fairness cap still forces
    reader/healer interleaving through the recovery, which keeps the
    pass's tree fully sweepable."""
    stats = _new_stats("seqlock")

    def torn(strategy: Strategy) -> RunResult:
        return run_model(lambda s: SeqlockModel(s, heal=False,
                                                state_cls=state_cls),
                         strategy, preemptions=preemptions, kills=0)

    def heal(strategy: Strategy) -> RunResult:
        return run_model(lambda s: SeqlockModel(s, heal=True,
                                                state_cls=state_cls),
                         strategy, preemptions=0, kills=1)

    for run_once in (_annotating("torn", torn), _annotating("heal", heal)):
        for res in explore(run_once, mode=mode,
                           max_schedules=max_schedules, seed=seed):
            _tally(stats, res)
    return _seal(stats)


def sweep_claim(mode: str = "exhaustive", max_schedules: int = 4000,
                seed: int = 0, preemptions: int = 2,
                router_cls: type = workers.WorkerRouter) -> dict:
    stats = _new_stats("claim")

    def no_kill(strategy: Strategy) -> RunResult:
        return run_model(lambda s: ClaimModel(s, router_cls=router_cls,
                                              daemon=False),
                         strategy, preemptions=preemptions, kills=0)

    def kill(strategy: Strategy) -> RunResult:
        # preemption bound 0 for the same reason as the seqlock kill
        # pass: the enumerated disturbance is the kill point itself
        return run_model(lambda s: ClaimModel(s, router_cls=router_cls),
                         strategy, preemptions=0, kills=1)

    for run_once in (_annotating("no-kill", no_kill),
                     _annotating("kill", kill)):
        for res in explore(run_once, mode=mode,
                           max_schedules=max_schedules, seed=seed):
            _tally(stats, res)
    return _seal(stats)


def sweep_wal(mode: str = "exhaustive", max_schedules: int = 4000,
              seed: int = 0, preemptions: int = 2,
              twin_cls: type = WalTwin) -> dict:
    stats = _new_stats("wal")

    def run_once(strategy: Strategy) -> RunResult:
        return run_model(lambda s: WalModel(s, twin_cls=twin_cls),
                         strategy, preemptions=preemptions, kills=1,
                         crash_all=True)

    for res in explore(run_once, mode=mode,
                       max_schedules=max_schedules, seed=seed):
        _tally(stats, res)
    return _seal(stats)


SWEEPS = {"seqlock": sweep_seqlock, "claim": sweep_claim, "wal": sweep_wal}

MUTANTS = {
    "seqlock": lambda **kw: sweep_seqlock(state_cls=BrokenSeqlockState,
                                          **kw),
    "claim": lambda **kw: sweep_claim(router_cls=BrokenClaimRouter, **kw),
    "wal": lambda **kw: sweep_wal(twin_cls=BrokenWalTwin, **kw),
}
