"""Developer tooling (not shipped with the daemon). tools.tdlint is the
project-specific concurrency-invariant linter (`make lint`)."""
