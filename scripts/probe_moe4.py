"""Chip probe 4: can the MoE routing/dispatch machinery tax be cut?

probe_moe3 put the per-layer-microbatch tax at routing ~2.1 ms +
gathers ~2.2 ms fwd (+1.3 grad) against ~2 ms of expert matmul — the
documented floor behind 28.2% active-FLOPs MFU (BASELINE.md). This
probe times drop-in replacements for each term in isolation, same
chain-timer discipline as probe_moe3 (output feeds next input; clock
stopped on a host fetch):

  route_topk / route_2max   — lax.top_k(probs, 2) vs two-pass masked max
                              (k=2 needs no sort network)
  cumsum / cumsum_blocked   — capacity ranking: jnp.cumsum over [K*T, E]
                              vs two-level blocked scan (within-block
                              tril matmul on the MXU + tiny cross-block
                              cumsum — converts a length-8192 serial
                              scan into G=16 block sums)
  gath_take / gath_onehot   — slot->token row gather vs dispatch by
                              [C_sub, T] one-hot matmul per expert

Usage: python scripts/probe_moe4.py
"""

import json
import sys
import time

sys.path.insert(0, ".")

INNER = 32
REPS = 3


def chain_timer(step, x0):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def chain(x):
        def body(c, _):
            return step(c), None
        c, _ = jax.lax.scan(body, x, None, length=INNER)
        return jnp.sum(jax.tree.leaves(c)[0].astype(jnp.float32))

    float(chain(x0))
    best = float("inf")
    for _ in range(REPS):
        t0 = time.perf_counter()
        float(chain(x0))
        best = min(best, time.perf_counter() - t0)
    return best / INNER


def blocked_cumsum(flat, block: int = 512):
    """Inclusive cumsum along axis 0 of [N, E] via two-level blocks:
    within-block prefix sums ride a [B, B] tril MATMUL (MXU work, no
    serial scan), block offsets come from one tiny cumsum over N/B
    block totals."""
    import jax.numpy as jnp

    n, e = flat.shape
    g = n // block
    x = flat.reshape(g, block, e).astype(jnp.float32)
    tril = jnp.tril(jnp.ones((block, block), jnp.float32))
    within = jnp.einsum("ab,gbe->gae", tril, x)          # [G, B, E]
    totals = within[:, -1, :]                            # [G, E]
    offs = jnp.cumsum(totals, axis=0) - totals           # exclusive [G, E]
    return (within + offs[:, None, :]).reshape(n, e)


def main():
    import jax
    import jax.numpy as jnp

    from gpu_docker_api_tpu.models.moe import MoEConfig

    c = MoEConfig.moe_1b()
    t, d, e = 4096, c.d_model, c.n_experts
    k = c.top_k
    cap = c.capacity(t)
    key = jax.random.key(0)
    ht = jax.random.normal(key, (t, d), jnp.bfloat16)
    router = jax.random.normal(key, (d, e), jnp.float32) * 0.02

    out = {"t": t, "cap": cap, "inner": INNER}

    # -- routing: top_k vs two-pass max ------------------------------------
    def route_topk(h):
        logits = h.astype(jnp.float32) @ router
        probs = jax.nn.softmax(logits, -1)
        g, i = jax.lax.top_k(probs, k)
        return h + ((probs + jnp.sum(g) + jnp.sum(i))
                    @ router.T).astype(h.dtype) * 1e-3

    def route_2max(h):
        logits = h.astype(jnp.float32) @ router
        probs = jax.nn.softmax(logits, -1)
        i1 = jnp.argmax(probs, -1)
        g1 = jnp.max(probs, -1)
        masked = probs.at[jnp.arange(t), i1].set(-jnp.inf)
        i2 = jnp.argmax(masked, -1)
        g2 = jnp.max(masked, -1)
        g = jnp.stack([g1, g2], -1)
        i = jnp.stack([i1, i2], -1)
        return h + ((probs + jnp.sum(g) + jnp.sum(i))
                    @ router.T).astype(h.dtype) * 1e-3

    out["route_topk_ms"] = round(chain_timer(route_topk, ht) * 1e3, 3)
    out["route_2max_ms"] = round(chain_timer(route_2max, ht) * 1e3, 3)

    # -- capacity ranking: cumsum vs blocked tril matmul -------------------
    gate_idx = jax.random.randint(key, (t, k), 0, e, jnp.int32)
    onehot = jax.nn.one_hot(gate_idx, e, dtype=jnp.int32)
    flat0 = onehot.transpose(1, 0, 2).reshape(t * k, e)

    def cs_base(f):
        pos = jnp.cumsum(f, axis=0) * f - 1
        return f + (jnp.sum(pos) % 2).astype(f.dtype)  # data dep, no drift

    def cs_blocked(f):
        pos = (blocked_cumsum(f).astype(jnp.int32)) * f - 1
        return f + (jnp.sum(pos) % 2).astype(f.dtype)

    out["cumsum_ms"] = round(chain_timer(cs_base, flat0) * 1e3, 3)
    out["cumsum_blocked_ms"] = round(chain_timer(cs_blocked, flat0) * 1e3, 3)
    # correctness cross-check
    a = jnp.cumsum(flat0, axis=0)
    b = blocked_cumsum(flat0).astype(jnp.int32)
    assert bool(jnp.all(a == b)), "blocked cumsum mismatch"

    # -- dispatch gather vs one-hot matmul dispatch ------------------------
    from gpu_docker_api_tpu.models.moe import capacity_positions
    pos = capacity_positions(onehot)
    keep = pos < cap
    flat_slot = jnp.where(keep, gate_idx * cap + pos, e * cap)
    gv = jax.random.uniform(key, (t, k), jnp.float32)

    def gath_take(h):
        tok = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[:, None],
                               flat_slot.shape)
        slot_tok = jnp.full((e * cap,), t, jnp.int32).at[
            flat_slot.reshape(-1)].set(tok.reshape(-1), mode="drop")
        hp = jnp.concatenate([h, jnp.zeros((1, d), h.dtype)], 0)
        xe = jnp.take(hp, slot_tok, axis=0)
        back = jnp.take(xe, jnp.where(keep, flat_slot, 0), axis=0)
        w = (gv * keep.astype(jnp.float32))[..., None]
        return jnp.sum(back.astype(jnp.float32) * w, 1).astype(h.dtype)

    out["gath_take_fwd_ms"] = round(chain_timer(gath_take, ht) * 1e3, 3)
    g_fn = jax.grad(lambda h: jnp.sum(gath_take(h).astype(jnp.float32)))
    out["gath_take_fwdgrad_ms"] = round(chain_timer(g_fn, ht) * 1e3, 3)

    # one-hot dispatch as [E*C, T] x [T, D] matmul (the einsum path's
    # dispatch HALF only, to see whether take or matmul wins per-term)
    def gath_onehot(h):
        slot_oh = (jax.nn.one_hot(flat_slot[:, 0], e * cap, dtype=h.dtype)
                   + jax.nn.one_hot(flat_slot[:, 1], e * cap,
                                    dtype=h.dtype))          # [T, E*C]
        xe = jnp.einsum("ts,td->sd", slot_oh, h)
        w = (gv * keep.astype(jnp.float32))
        back = jnp.einsum("sd,ts->td", xe.astype(jnp.float32),
                          slot_oh.astype(jnp.float32) * w[:, 0:1])
        return back.astype(h.dtype)

    out["gath_onehot_fwd_ms"] = round(
        chain_timer(gath_onehot, ht) * 1e3, 3)

    print(json.dumps(out))


if __name__ == "__main__":
    main()
