"""Chip probe: long-context (16k/32k) train-step MFU, pair-stack A/B
(VERDICT r4 weak #4 / next #4).

TDAPI_FLASH_PAIR_STACK is read at module import, so each arm runs in its
own process:

    TDAPI_FLASH_PAIR_STACK=32 python scripts/probe_long.py 16384
    TDAPI_FLASH_PAIR_STACK=1  python scripts/probe_long.py 16384
    python scripts/probe_long.py 32768

stack=1 reproduces round 3's one-pair-per-launch ladder (~19% MFU on the
attention term at S=16k); stack=32 is the round-4 rewrite whose effect
was never published.
"""

import dataclasses
import json
import os
import sys

sys.path.insert(0, ".")


def main():
    import bench
    from gpu_docker_api_tpu.models.llama import LlamaConfig
    from gpu_docker_api_tpu.train import TrainConfig

    seq = int(sys.argv[1]) if len(sys.argv) > 1 else 16384
    cfg = dataclasses.replace(LlamaConfig.llama_250m(), max_seq_len=seq)
    tc = TrainConfig(remat_policy="full") if seq > 16384 else None
    rec = bench._mfu_one(f"llama_250m_s{seq // 1024}k", cfg, batch=1,
                         seq=seq, K=2, tc=tc)
    rec["pair_stack"] = int(os.environ.get("TDAPI_FLASH_PAIR_STACK", "32"))
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
