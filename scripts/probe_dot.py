"""Standalone int8-vs-bf16 dot microbench probe (VERDICT r4 next #2).

bench.py's dot_tfs row timed ONE call per dtype after warmup; through the
axon tunnel that is one RTT-sized sample, and across rounds it produced
contradictory records (bf16 28 TF/s vs int8 71 TF/s — with prose claiming
the reverse ordering). This probe is the adjudicator: K timed calls per
dtype, interleaved A/B/A/B to cancel drift, a long device-side scan per
call (the tunnel-timing discipline: one dispatch, clock stopped on a host
fetch of the result), run from N fresh processes by the shell wrapper.

Usage:  python scripts/probe_dot.py            # one process, prints JSON
        for i in 1 2 3; do python scripts/probe_dot.py; done
"""

import json
import time

import jax
import jax.numpy as jnp

M = 4096
SCAN = 64          # dots per timed dispatch — amortizes dispatch/RTT
REPS = 5           # timed dispatches per dtype (best + spread reported)


def make_chain(dtype, pref):
    a = jax.random.normal(jax.random.key(7), (M, M),
                          jnp.bfloat16).astype(dtype)
    w = jax.random.normal(jax.random.key(8), (M, M),
                          jnp.bfloat16).astype(dtype)

    @jax.jit
    def chain(x):
        def body(c, _):
            o = jax.lax.dot_general(
                c, w, (((1,), (0,)), ((), ())),
                preferred_element_type=pref)
            return o.astype(dtype), None
        c, _ = jax.lax.scan(body, x, None, length=SCAN)
        return jnp.sum(c.astype(jnp.float32))

    float(chain(a))            # compile + first-run
    return lambda: float(chain(a))


def main():
    bf16 = make_chain(jnp.bfloat16, jnp.float32)
    i8 = make_chain(jnp.int8, jnp.int32)
    times = {"bf16": [], "int8": []}
    for _ in range(REPS):      # interleaved: drift hits both arms alike
        for name, fn in (("bf16", bf16), ("int8", i8)):
            t0 = time.perf_counter()
            fn()
            times[name].append((time.perf_counter() - t0) / SCAN)

    def tfs(ts):
        return round(2 * M ** 3 / min(ts) / 1e12, 1)

    def spread(ts):
        return round((max(ts) - min(ts)) / min(ts), 3)

    out = {
        "platform": jax.devices()[0].platform,
        "device": jax.devices()[0].device_kind,
        "m": M, "scan": SCAN, "reps": REPS,
        "dot_tflops_bf16": tfs(times["bf16"]),
        "dot_tflops_int8_i32": tfs(times["int8"]),
        "int8_over_bf16": round(min(times["bf16"]) / min(times["int8"]), 2),
        "spread_bf16": spread(times["bf16"]),
        "spread_int8": spread(times["int8"]),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
