"""Chip probe 3: isolate the MoE machinery tax term by term.

probe_moe2 put the dense same-active-FLOPs twin at 52% MFU / 353 ms and
the gather-dispatch MoE at 28.2% / 652 ms — ~300 ms of tax. This probe
times the candidate terms in isolation. Tunnel discipline: each timed
dispatch is a jitted chain of `inner` iterations whose OUTPUT FEEDS THE
NEXT INPUT (defeats loop-invariant hoisting; amortizes the ~50-60 ms
tunnel RTT), clock stopped on a host fetch.

  bmm / flat — per-expert batched einsum [E,C,D]x[E,D,F]x[E,F,D] vs the
               flat matmul pair of identical FLOPs (grouped-matmul MXU
               efficiency)
  gath       — dispatch gather + combine gather-sum, fwd and grad (the
               grad of a gather is a scatter-add)
  route      — router matmul + top_k + capacity cumsum, fwd and grad

Usage: python scripts/probe_moe3.py
"""

import json
import sys
import time

sys.path.insert(0, ".")

INNER = 32
REPS = 3


def chain_timer(step, x0):
    """step: x -> x (same shape/dtype). Returns best per-iteration s."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def chain(x):
        def body(c, _):
            return step(c), None
        c, _ = jax.lax.scan(body, x, None, length=INNER)
        return jnp.sum(jax.tree.leaves(c)[0].astype(jnp.float32))

    float(chain(x0))                       # compile + first-run
    best = float("inf")
    for _ in range(REPS):
        t0 = time.perf_counter()
        float(chain(x0))
        best = min(best, time.perf_counter() - t0)
    return best / INNER


def main():
    import jax
    import jax.numpy as jnp

    from gpu_docker_api_tpu.models.moe import MoEConfig, capacity_positions

    c = MoEConfig.moe_1b()
    t, d, f, e = 4096, c.d_model, c.d_ff, c.n_experts   # microbatch T
    cap = c.capacity(t)
    key = jax.random.key(0)
    ht = jax.random.normal(key, (t, d), jnp.bfloat16)
    we1 = jax.random.normal(key, (e, d, f), jnp.bfloat16) * 0.02
    we2 = jax.random.normal(key, (e, f, d), jnp.bfloat16) * 0.02
    wf1 = jax.random.normal(key, (d, f), jnp.bfloat16) * 0.02
    wf2 = jax.random.normal(key, (f, d), jnp.bfloat16) * 0.02
    xe0 = jax.random.normal(key, (e, cap, d), jnp.bfloat16)

    out = {"t": t, "cap": cap, "inner": INNER}
    flops_pair = 2 * 2 * e * cap * d * f   # two matmuls per iteration

    s = chain_timer(lambda x: jnp.einsum(
        "ecf,efd->ecd", jnp.einsum("ecd,edf->ecf", x, we1),
        we2).astype(jnp.bfloat16), xe0)
    out["bmm_tflops"] = round(flops_pair / s / 1e12, 1)
    s = chain_timer(lambda x: ((x @ wf1) @ wf2).astype(jnp.bfloat16),
                    xe0.reshape(e * cap, d))
    out["flat_tflops"] = round(flops_pair / s / 1e12, 1)

    # gather dispatch + combine, fwd and grad, chained through [T, D]
    gate_idx = jax.random.randint(key, (t, c.top_k), 0, e, jnp.int32)
    onehot = jax.nn.one_hot(gate_idx, e, dtype=jnp.int32)
    pos = capacity_positions(onehot)
    keep = pos < cap
    flat_slot = jnp.where(keep, gate_idx * cap + pos, e * cap)
    gv = jax.random.uniform(key, (t, c.top_k), jnp.float32)

    def gath(h):
        tok = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[:, None],
                               flat_slot.shape)
        slot_tok = jnp.full((e * cap,), t, jnp.int32).at[
            flat_slot.reshape(-1)].set(tok.reshape(-1), mode="drop")
        hp = jnp.concatenate([h, jnp.zeros((1, d), h.dtype)], 0)
        xe = jnp.take(hp, slot_tok, axis=0)              # dispatch
        back = jnp.take(xe, jnp.where(keep, flat_slot, 0), axis=0)
        w = (gv * keep.astype(jnp.float32))[..., None]
        return jnp.sum(back.astype(jnp.float32) * w, 1).astype(h.dtype)

    out["gather_fwd_ms"] = round(chain_timer(gath, ht) * 1e3, 3)
    g_fn = jax.grad(lambda h: jnp.sum(gath(h).astype(jnp.float32)))
    out["gather_fwdgrad_ms"] = round(chain_timer(g_fn, ht) * 1e3, 3)

    # routing, fwd and grad, chained through [T, D]
    router = jax.random.normal(key, (d, e), jnp.float32) * 0.02

    def route(h):
        logits = h.astype(jnp.float32) @ router
        probs = jax.nn.softmax(logits, -1)
        g, i = jax.lax.top_k(probs, c.top_k)
        oh = jax.nn.one_hot(i, e, dtype=jnp.int32)
        p = capacity_positions(oh)
        # feed outputs back into h: full data dependency, tiny extra cost
        return (h + ((probs + jnp.sum(g) + jnp.sum(p))
                     @ router.T).astype(h.dtype) * 1e-3)

    out["route_fwd_ms"] = round(chain_timer(route, ht) * 1e3, 3)
    r_fn = jax.grad(lambda h: jnp.sum(route(h).astype(jnp.float32)))
    out["route_fwdgrad_ms"] = round(chain_timer(r_fn, ht) * 1e3, 3)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
