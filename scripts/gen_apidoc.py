"""Generate docs/api.md — the human-readable API reference — from
api/openapi.json (VERDICT r4 missing #1: the reference ships a rendered
2,597-line API guide, `/root/reference/api/gpu-docker-api-en.md`,
alongside its machine spec; this renders ours from ours).

The spec is the single source of truth (scripts/gen_openapi.py generates
it from the live Router + DTOs; test_openapi pins route coverage and
regeneration-match), so this document can never drift from the server:
CI regenerates both and fails on diff (`make apidoc`).

Usage: python scripts/gen_apidoc.py [--check]
"""

from __future__ import annotations

import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

METHOD_ORDER = {"get": 0, "post": 1, "patch": 2, "put": 3, "delete": 4}


def _ref_name(ref: str) -> str:
    return ref.rsplit("/", 1)[-1]


def _type_str(schema: dict) -> str:
    """Compact human type for a schema node (refs become links)."""
    if not schema:
        return "any"
    if "$ref" in schema:
        name = _ref_name(schema["$ref"])
        return f"[{name}](#schema-{name.lower()})"
    t = schema.get("type", "object")
    if t == "array":
        return f"array of {_type_str(schema.get('items', {}))}"
    if t == "object" and "additionalProperties" in schema:
        ap = schema["additionalProperties"]
        if isinstance(ap, dict):
            return f"map of {_type_str(ap)}"
        return "object"
    if "enum" in schema:
        return " \\| ".join(f"`{v}`" for v in schema["enum"])
    return t


def _cell(text) -> str:
    return str(text).replace("|", "\\|").replace("\n", " ")


def _schema_table(name: str, schema: dict, out: list) -> None:
    out.append(f'### <a id="schema-{name.lower()}"></a>{name}\n')
    if schema.get("description"):
        out.append(schema["description"] + "\n")
    props = schema.get("properties")
    if not props:
        out.append(f"Type: {_type_str(schema)}\n")
        return
    required = set(schema.get("required", []))
    out.append("| field | type | required | default | description |")
    out.append("|---|---|---|---|---|")
    for fname, fs in props.items():
        default = fs.get("default", "")
        default = f"`{json.dumps(default)}`" if default != "" else ""
        out.append(
            f"| `{fname}` | {_type_str(fs)} "
            f"| {'yes' if fname in required else ''} | {default} "
            f"| {_cell(fs.get('description', ''))} |")
    out.append("")


def _example_block(media: dict, out: list) -> None:
    if "example" in media:
        out.append("```json")
        out.append(json.dumps(media["example"], indent=2))
        out.append("```")


def generate(spec: dict) -> str:
    info = spec["info"]
    out: list[str] = [
        f"# {info['title']} — API reference",
        "",
        f"Version {info['version']}. "
        "GENERATED from [`api/openapi.json`](../api/openapi.json) by "
        "`scripts/gen_apidoc.py` — edit the handlers/DTOs and run "
        "`make apidoc`, not this file.",
        "",
        info.get("description", "").strip(),
        "",
        "Every response is HTTP 200 with the envelope "
        "`{\"code\": N, \"msg\": \"...\", \"data\": ...}`; `code` carries "
        "the app-level result (200 success; the [error code "
        "table](#error-codes) otherwise). Auth: when the daemon runs "
        "with `APIKEY`, send `Authorization: Bearer <key>` "
        "(403 envelope otherwise).",
        "",
        "The `gateway` operations are the inference serving tier "
        "(router + CoW-clone autoscaler) — the model, routing/shedding "
        "policy, autoscale knobs and bench methodology live in "
        "[serving.md](serving.md).",
        "",
    ]
    # group operations by tag
    by_tag: dict[str, list] = {}
    for path, methods in spec["paths"].items():
        for method, op in methods.items():
            tag = (op.get("tags") or ["misc"])[0]
            by_tag.setdefault(tag, []).append((path, method, op))
    tags = [t["name"] for t in spec.get("tags", [])] or sorted(by_tag)
    # an operation tagged outside the declared tag list must not vanish
    # from the rendered document — append undeclared tags at the end
    tags += sorted(t for t in by_tag if t not in tags)

    out.append("## Contents\n")
    for tag in tags:
        ops = sorted(by_tag.get(tag, []),
                     key=lambda e: (e[0], METHOD_ORDER.get(e[1], 9)))
        out.append(f"- **{tag}**")
        for path, method, op in ops:
            oid = op.get("operationId", f"{method}-{path}")
            out.append(f"  - [`{method.upper()} {path}`](#{oid.lower()}) — "
                       f"{op.get('summary', '')}")
    out.append("")

    for tag in tags:
        tag_info = next((t for t in spec.get("tags", [])
                         if t["name"] == tag), {})
        out.append(f"## {tag}\n")
        if tag_info.get("description"):
            out.append(tag_info["description"] + "\n")
        ops = sorted(by_tag.get(tag, []),
                     key=lambda e: (e[0], METHOD_ORDER.get(e[1], 9)))
        for path, method, op in ops:
            oid = op.get("operationId", f"{method}-{path}")
            out.append(f'### <a id="{oid.lower()}"></a>'
                       f"{op.get('summary', oid)}\n")
            out.append(f"`{method.upper()} {path}`\n")
            if op.get("description"):
                out.append(op["description"] + "\n")
            params = op.get("parameters", [])
            if params:
                out.append("| parameter | in | type | required | "
                           "description |")
                out.append("|---|---|---|---|---|")
                for p in params:
                    while "$ref" in p:   # shared params (traceparent)
                        sec, nm = p["$ref"].rsplit("/", 2)[-2:]
                        p = spec["components"][sec][nm]
                    out.append(
                        f"| `{p['name']}` | {p['in']} "
                        f"| {_type_str(p.get('schema', {}))} "
                        f"| {'yes' if p.get('required') else ''} "
                        f"| {_cell(p.get('description', ''))} |")
                out.append("")
            body = op.get("requestBody")
            if body:
                schema = body["content"]["application/json"]["schema"]
                out.append(f"Request body: {_type_str(schema)}\n")
            resp = op["responses"]["200"]
            media = resp.get("content", {}).get("application/json", {})
            schema = media.get("schema", {})
            data = {}
            for part in schema.get("allOf", []):
                data = part.get("properties", {}).get("data", data)
            if data:
                out.append(f"Response `data`: {_type_str(data)}\n")
            _example_block(media, out)
            out.append("")

    out.append("## Schemas\n")
    for name, schema in spec["components"]["schemas"].items():
        _schema_table(name, schema, out)

    # error-code appendix from the live table (wire-compatible with the
    # reference's internal/routers/code.go)
    from gpu_docker_api_tpu.server.codes import ResCode
    out.append('## <a id="error-codes"></a>Error codes\n')
    out.append("App-level codes in the envelope's `code` field "
               "(wire-compatible with the reference):\n")
    out.append("| code | name | message |")
    out.append("|---|---|---|")
    for rc in sorted(ResCode, key=lambda r: r.value):
        out.append(f"| {rc.value} | `{rc.name}` | {_cell(rc.msg)} |")
    out.append("")
    return "\n".join(out) + "\n"


def main() -> int:
    spec = json.load(open(os.path.join(ROOT, "api", "openapi.json")))
    text = generate(spec)
    target = os.path.join(ROOT, "docs", "api.md")
    if "--check" in sys.argv:
        try:
            current = open(target).read()
        except FileNotFoundError:
            current = None
        if current != text:
            print("docs/api.md is stale — run: python scripts/gen_apidoc.py")
            return 1
        print("docs/api.md is up to date")
        return 0
    open(target, "w").write(text)
    print(f"wrote {target} ({len(text.splitlines())} lines)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
