"""Chip probe: moe_1b train-step MFU with the gather-dispatch path
(VERDICT r4 weak #5 / next #5), plus a capacity-factor A/B.

Round-4 record (einsum dispatch): 764 ms/step, 24% active-FLOPs MFU.
The dispatch/combine one-hot einsums cost O(T·E·C·D) FLOPs/layer —
arithmetic puts them at ~the expert matmuls themselves at T=4096 — so
the gather path should roughly halve the MoE-side step time.

Usage: python scripts/probe_moe.py [cf ...]   (default: 1.25 1.0)
"""

import json
import sys

sys.path.insert(0, ".")


def main():
    import dataclasses

    import bench
    from gpu_docker_api_tpu.models.moe import MoEConfig
    from gpu_docker_api_tpu.train import TrainConfig

    cfs = [float(x) for x in sys.argv[1:]] or [1.25, 1.0]
    out = {}
    for cf in cfs:
        cfg = dataclasses.replace(MoEConfig.moe_1b(), capacity_factor=cf)
        rec = bench._mfu_one(f"moe_1b_cf{cf}", cfg, batch=8, seq=2048,
                             K=4, tc=TrainConfig(accum_steps=4))
        out[f"cf{cf}"] = rec
        print(json.dumps({f"cf{cf}": rec}), flush=True)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
