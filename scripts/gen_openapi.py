#!/usr/bin/env python3
"""Generate api/openapi.json — the FULL-schema API document.

The reference ships a 4,761-line generated OpenAPI file with complete
request/response schemas per endpoint (reference
api/gpu-docker-api-en.openapi.json); this repo's spec is generated too, from
this script, so the document can't rot apart from the handlers: the schemas
below mirror dtos.py (wire DTOs), services/replicaset.py `_run_response` /
`get_container_info` / `get_container_history`, services/volume.py,
schedulers/*.get_status, and events.py — each schema cites its source. A
typed client can be generated from it (gpu_docker_api_tpu/client.py builds
one at runtime and tests/test_openapi.py drives the live server with it).

Run: python scripts/gen_openapi.py   (writes api/openapi.json)
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def ref(name: str) -> dict:
    return {"$ref": f"#/components/schemas/{name}"}


def obj(props: dict, required: list | None = None, desc: str = "",
        additional=None) -> dict:
    out: dict = {"type": "object", "properties": props}
    if required:
        out["required"] = required
    if desc:
        out["description"] = desc
    if additional is not None:
        out["additionalProperties"] = additional
    return out


def arr(items: dict, desc: str = "") -> dict:
    out: dict = {"type": "array", "items": items}
    if desc:
        out["description"] = desc
    return out


def s(desc: str = "", **kw) -> dict:
    out: dict = {"type": "string"}
    if desc:
        out["description"] = desc
    out.update(kw)
    return out


def i(desc: str = "", **kw) -> dict:
    out: dict = {"type": "integer"}
    if desc:
        out["description"] = desc
    out.update(kw)
    return out


def b(desc: str = "") -> dict:
    out: dict = {"type": "boolean"}
    if desc:
        out["description"] = desc
    return out


def envelope(data_schema: dict | None, example_data=None,
             desc: str = "") -> dict:
    """Every endpoint answers HTTP 200 with the {code, msg, data} envelope;
    app-level errors ride `code` (server/codes.py table)."""
    data = data_schema if data_schema is not None else {"nullable": True}
    schema = {
        "allOf": [ref("Envelope"),
                  {"type": "object", "properties": {"data": data}}]}
    content: dict = {"schema": schema}
    if example_data is not None:
        content["example"] = {"code": 200, "msg": "Success",
                              "data": example_data}
    return {"200": {
        "description": desc or "Envelope (code 200 on success; app error "
                               "codes otherwise — see Envelope.code)",
        "content": {"application/json": content}}}


def op(op_id: str, summary: str, responses: dict, body: dict | None = None,
       params: list | None = None, tags: list | None = None,
       desc: str = "") -> dict:
    out: dict = {"operationId": op_id, "summary": summary,
                 "responses": responses}
    if desc:
        out["description"] = desc
    if body is not None:
        out["requestBody"] = {"required": True, "content": {
            "application/json": {"schema": body}}}
    if params:
        out["parameters"] = params
    if tags:
        out["tags"] = tags
    return out


NAME_PARAM = {"name": "name", "in": "path", "required": True,
              "schema": {"type": "string"},
              "description": "replicaSet / volume base name (unversioned; "
                             "must not contain '-')"}

# Attached to EVERY mutating operation (post-processing in build_spec):
# exactly-once retry semantics (server/app.py middleware + idempotency.py)
IDEM_PARAM = {
    "name": "Idempotency-Key", "in": "header", "required": False,
    "schema": {"type": "string"},
    "description": "Client-chosen key making this mutation safe to "
                   "retry: the server persists the response and replays "
                   "it on duplicates (Idempotency-Replayed: true) "
                   "instead of re-executing — across daemon crashes too "
                   "(the boot reconciler settles the cache together with "
                   "the interrupted mutation). Reusing a key with a "
                   "different request is rejected (envelope code 1000); "
                   "a duplicate racing the original answers HTTP 409 + "
                   "Retry-After."}

# Attached to the version-guarded mutations (IF_MATCH_OPS below)
IF_MATCH_PARAM = {
    "name": "If-Match", "in": "header", "required": False,
    "schema": {"type": "integer", "minimum": 0},
    "description": "Optimistic-concurrency precondition: the mutation "
                   "only proceeds if the target's current version equals "
                   "this value (checked under the per-name mutation "
                   "lock). On mismatch: HTTP 412, envelope code 412, "
                   "current version in X-Current-Version and "
                   "data.currentVersion."}

IF_MATCH_OPS = {"patchReplicaSet", "rollbackReplicaSet", "stopReplicaSet",
                "restartReplicaSet", "deleteReplicaSet", "patchVolumeSize",
                "deleteVolume"}

RESP_429 = {"description":
            "Shed by the mutation admission gate before any state was "
            "touched (envelope code 429) — too many in-flight mutations; "
            "retry after the Retry-After header."}
RESP_412 = {"description":
            "If-Match version precondition failed (envelope code 412); "
            "X-Current-Version carries the current version."}
RESP_409 = {"description":
            "A request with the same Idempotency-Key is currently "
            "executing; retry shortly for its stored result."}

CHIP_PARAM = {"name": "id", "in": "path", "required": True,
              "schema": {"type": "integer", "minimum": 0},
              "description": "Global chip index (see /resources/tpus)"}

GW_PARAM = {"name": "name", "in": "path", "required": True,
            "schema": {"type": "string"},
            "description": "Gateway name (no '-'; replicas are "
                           "replicaSets named {name}r{idx})"}

#: data-plane operations: NOT wrapped by the mutation gate / idempotency
#: middleware server-side, so the exactly-once surface must not be
#: documented on them (their 429 is the GATEWAY's own admission shed)
DATA_PLANE_OPS = {"gatewayGenerate"}

MEMBER_PARAM = {"name": "member", "in": "path", "required": True,
                "schema": {"type": "string"},
                "description": "Fleet member id (the daemon's "
                               "--fleet-member)"}

#: fleet-plane operations: registered raw (server/fleet.py) — they bypass
#: the mutation gate and idempotency middleware because they ARE the
#: coordination substrate those layers would sit on (a heartbeat that can
#: be shed by the admission gate expires its own lease). Retries are safe
#: by protocol instead: join/renew/acquire are idempotent per holder,
#: release/leave tolerate repeats.
FLEET_OPS = {"fleetJoin", "fleetRenew", "fleetLeave", "fleetAcquire",
             "fleetRelease"}

# Attached to EVERY operation (post-processing in build_spec): W3C Trace
# Context ingress (obs/trace.py; the shipped client stamps one per call)
TRACEPARENT_PARAM = {
    "name": "traceparent", "in": "header", "required": False,
    "schema": {"type": "string",
               "pattern": "^[0-9a-f]{2}-[0-9a-f]{32}-[0-9a-f]{16}-"
                          "[0-9a-f]{2}$"},
    "description": "W3C Trace Context (level 1). When present, the "
                   "request's ingress span joins the caller's trace id "
                   "instead of minting a fresh one — a caller spanning "
                   "several control planes can stitch the traces. "
                   "Malformed values never fail the request; the trace "
                   "just restarts here. The full span tree is served at "
                   "GET /api/v1/traces/{traceId}."}


def build_codes_desc() -> str:
    from gpu_docker_api_tpu.server.codes import ResCode
    rows = [f"{c.value} {c.name}" for c in ResCode]
    return ("Application status code (wire-compatible with the reference's "
            "internal/routers/code.go table): " + "; ".join(rows))


def build_spec() -> dict:
    run_example = {
        "imageName": "python", "replicaSetName": "train",
        "tpuCount": 4, "cpuCount": 8, "memory": "16GB",
        "binds": [{"src": "data-1", "dest": "/data"}],
        "env": ["JAX_COMPILATION_CACHE_DIR=/tmp/jax-cache"],
        "cmd": ["python", "-m",
                "gpu_docker_api_tpu.workloads.train_llama"],
        "containerPorts": ["8000"],
    }
    run_resp_example = {
        "name": "train-1", "version": 1, "tpuChips": [0, 1, 2, 3],
        "tpuShares": 0, "priority": "",
        "meshPlan": {"dp": 1, "fsdp": 2, "pp": 1, "ep": 1, "tp": 2,
                     "sp": 1},
        "cpuset": "0-7", "portBindings": {"8000": 40001},
    }
    spec_example = {
        "image": "python", "env": ["TPU_VISIBLE_CHIPS=0,1,2,3"],
        "cmd": ["python", "-c", "import jax"],
        "binds": ["data-1:/data"], "cpuset": "0-7", "cpu_count": 8,
        "memory_bytes": 17179869184, "shm_bytes": 274877906944,
        "rootfs_quota": "30G", "restart_policy": "unless-stopped",
        "port_bindings": {"8000": 40001}, "tpu_chips": [0, 1, 2, 3],
        "tpu_env": {"TPU_VISIBLE_CHIPS": "0,1,2,3"},
        "devices": ["/dev/accel0"],
    }

    schemas = {
        "Envelope": obj(
            {"code": i(build_codes_desc()),
             "msg": s("Human-readable status"),
             "data": {"nullable": True,
                      "description": "Operation payload (endpoint-specific; "
                                     "null on errors and bare acks)"},
             "traceId": s("W3C trace id of the request — present on ERROR "
                          "envelopes (code != 200) when tracing is armed, "
                          "so a failed call is greppable server-side: "
                          "GET /api/v1/traces/{traceId} shows exactly "
                          "where the mutation failed")},
            required=["code", "msg"],
            desc="Every endpoint answers HTTP 200 with this envelope "
                 "(server/http.py); failures ride the `code` field."),
        "Bind": obj(
            {"src": s("Volume base name OR host path"),
             "dest": s("Mount point inside the container")},
            desc="Volume/host-dir mount (dtos.Bind; wire format of the "
                 "reference models/container.go Bind)"),
        "MeshPlan": obj(
            {a: {"type": "integer", "minimum": 1, "default": 1,
                 "description": d}
             for a, d in [
                 ("dp", "pure data parallelism (outermost axis)"),
                 ("fsdp", "fully-sharded data parallelism (ZeRO-3)"),
                 ("pp", "pipeline stages (granted as adjacent sub-mesh "
                        "slabs along one axis)"),
                 ("ep", "expert parallelism (MoE)"),
                 ("tp", "tensor (megatron) parallelism — innermost with "
                        "sp: placed on contiguous ICI links, inside one "
                        "host where possible"),
                 ("sp", "sequence/context parallelism (ring/Ulysses)")]},
            additional=False,
            desc="Gang parallelism plan: chips per mesh axis, outermost "
                 "(dp) to innermost (sp). The product MUST equal the "
                 "request's tpuCount (app error 1000 otherwise, also when "
                 "no sub-box of the slice topology can host the factors "
                 "ICI-contiguously). The scheduler grants an "
                 "ICI-contiguous sub-mesh shaped for these factors and "
                 "stamps TDAPI_MESH_PLAN into the container env so the "
                 "workload builds exactly this mesh "
                 "(docs/gang.md)."),
        "ContainerRun": obj(
            {"imageName": s("Image to run (required)"),
             "replicaSetName": s("Base name (required; no '-'; versions "
                                 "are named {name}-{v})"),
             "meshPlan": ref("MeshPlan"),
             "tpuCount": {
                 "type": "number", "minimum": 0, "multipleOf": 0.25,
                 "description":
                     "Whole ICI-contiguous chips (1, 2, ...), or a "
                     "FRACTIONAL share of one chip (exactly 0.25, 0.5 "
                     "or 0.75 — any other fraction, including values "
                     "like 1.5, is rejected with app error 1000: counts "
                     "above 1 must be whole). Fractional tenants "
                     "co-locate on a share-split chip and time-slice it "
                     "through the per-chip regulator by share weight "
                     "(gpuCount accepted as a legacy alias). App error "
                     "1026 when no chip has enough free share "
                     "capacity."},
             "gpuCount": {"type": "number", "minimum": 0,
                          "description": "Legacy alias for tpuCount"},
             "priority": s("Regulator class for fractional co-tenancy: "
                           "'latency' streams preempt 'best_effort' "
                           "co-tenants at decode-chunk boundaries "
                           "('' = best_effort)",
                           enum=["", "latency", "best_effort"]),
             "cpuCount": i("CPU cores to pin (cpuset)", minimum=0),
             "memory": s("Memory limit, e.g. '16GB' (units KB/MB/GB/TB)"),
             "binds": arr(ref("Bind")),
             "env": arr(s(), "KEY=VALUE environment entries"),
             "cmd": arr(s(), "Container entrypoint command"),
             "containerPorts": arr(s(), "Container ports; each gets a "
                                        "host port from the port "
                                        "scheduler"),
             "profile": obj(
                 {}, additional={"type": "number"},
                 desc="Per-generation relative throughput, e.g. "
                      "{\"v4\": 1.0, \"v5e\": 0.3} — how much a chip of "
                      "each generation is worth to THIS workload. Used "
                      "by the placement policy layer to score candidate "
                      "boxes on a mixed fleet (docs/scheduling.md); "
                      "unset generations fall back to fitted step-time "
                      "observations, then the generation baselines. "
                      "Ignored when no --placement-policy is "
                      "configured.")},
            required=["imageName", "replicaSetName"],
            desc="POST /api/v1/replicaSet body (dtos.ContainerRun; "
                 "reference models/container.go ContainerRun)"),
        "TpuPatch": obj({"tpuCount": {"type": "number", "minimum": 0,
                                      "multipleOf": 0.25,
                                      "description": "Whole chips, or "
                                      "exactly 0.25/0.5/0.75 (counts "
                                      "above 1 must be whole; else app "
                                      "error 1000)"},
                         "meshPlan": ref("MeshPlan"),
                         "gpuCount": {"type": "number", "minimum": 0,
                                      "description": "Legacy alias"}},
                        desc="TPU re-grant. On a gang replicaSet a "
                             "tpuCount/meshPlan change is a RESHARD: the "
                             "workload is quiesce-checkpointed at an "
                             "exact step, a new plan-shaped sub-mesh is "
                             "granted, and the restarted version resumes "
                             "the checkpoint under the new mesh (zero "
                             "lost steps when the workload honors the "
                             "quiesce contract; plain stop-and-replay "
                             "fallback otherwise). meshPlan requires "
                             "tpuCount; omitting meshPlan on a count "
                             "change resets a gang set to the trivial "
                             "plan."),
        "CpuPatch": obj({"cpuCount": i(minimum=0)}),
        "MemoryPatch": obj({"memory": s("e.g. '32GB'")}),
        "VolumePatch": obj({"oldBind": ref("Bind"),
                            "newBind": ref("Bind")}),
        "PatchRequest": obj(
            {"tpuPatch": ref("TpuPatch"), "gpuPatch": ref("TpuPatch"),
             "cpuPatch": ref("CpuPatch"),
             "memoryPatch": ref("MemoryPatch"),
             "volumePatch": ref("VolumePatch")},
            desc="PATCH /api/v1/replicaSet/{name} body (dtos.PatchRequest)"
                 " — at least one sub-patch; rolling replacement creates "
                 "version {name}-{v+1}"),
        "RollbackRequest": obj({"version": i("Target version (>= 0)",
                                             minimum=0)},
                               required=["version"]),
        "ContainerExecute": obj(
            {"workDir": s("Working directory inside the container"),
             "cmd": arr(s(), "Command to exec")},
            desc="POST .../execute body (dtos.ContainerExecute)"),
        "ContainerCommit": obj({"newImageName": s("required")},
                               required=["newImageName"]),
        "VolumeCreate": obj(
            {"name": s("Base name (no '-', no leading '/')"),
             "size": s("e.g. '20GB'; empty = unbounded"),
             "tier": s("Storage tier ('' = default/local-SSD; e.g. 'nfs' "
                       "when the operator configured one)")},
            required=["name"],
            desc="POST /api/v1/volumes body (dtos.VolumeCreate + tier)"),
        "VolumeSize": obj({"size": s("New size, e.g. '40GB'")},
                          required=["size"]),
        "ContainerSpec": obj(
            {"image": s(), "env": arr(s()), "cmd": arr(s()),
             "binds": arr(s(), "'src:dest' strings"),
             "cpuset": s("Pinned cores, e.g. '0-7'"),
             "cpu_count": i(), "memory_bytes": i(), "shm_bytes": i(),
             "rootfs_quota": s(), "restart_policy": s(),
             "port_bindings": obj({}, additional=i(),
                                  desc="containerPort -> hostPort"),
             "tpu_chips": arr(i(), "Granted global chip indices"),
             "tpu_shares": i("Fractional grant: share quanta (of 4) held "
                             "on tpu_chips[0]; 0 = whole-chip grant"),
             "priority": s("Regulator class ('' | 'latency' | "
                           "'best_effort')"),
             "tpu_env": obj({}, additional=s(),
                            desc="TPU env injected into the container "
                                 "(TPU_VISIBLE_CHIPS etc.; gang grants "
                                 "add TDAPI_MESH_PLAN)"),
             "mesh_plan": obj({}, additional=i(),
                              desc="Granted gang plan as axis factors; "
                                   "{} = trivial/no plan"),
             "devices": arr(s(), "/dev/accel* passthrough")},
            desc="Substrate-facing creation spec (dtos.ContainerSpec; the "
                 "reference stores docker Config+HostConfig here)"),
        "StoredContainerInfo": obj(
            {"version": i(), "createTime": s(),
             "containerName": s("Versioned name {rs}-{version}"),
             "spec": ref("ContainerSpec"),
             "resourcesReleased": b("Whether the grants were returned to "
                                    "the pool (stop sets this)")},
            desc="Persisted container version (dtos.StoredContainerInfo; "
                 "reference EtcdContainerInfo)"),
        "StoredVolumeInfo": obj(
            {"version": i(), "createTime": s(),
             "volumeName": s("Versioned name {name}-{version}"),
             "size": s(), "tier": s()},
            desc="Persisted volume version (dtos.StoredVolumeInfo)"),
        "RunResponse": obj(
            {"name": s("Versioned container name"), "version": i(),
             "tpuChips": arr(i()),
             "tpuShares": i("Share quanta (of 4) held on tpuChips[0]; "
                            "0 = whole-chip grant"),
             "priority": s("Regulator class for fractional co-tenancy"),
             "meshPlan": ref("MeshPlan"),
             "cpuset": s(),
             "portBindings": obj({}, additional=i())},
            desc="run/patch/rollback/restart payload "
                 "(services/replicaset.py _run_response). meshPlan is the "
                 "granted gang shape (all-1s for non-gang sets)."),
        "ExecuteResponse": obj({"output": s("Captured stdout+stderr")}),
        "CommitResponse": obj({"imageId": s(), "imageName": s()}),
        "ContainerInfo": obj(
            {"version": i(), "createTime": s(), "containerName": s(),
             "running": {"type": "boolean", "nullable": True,
                         "description": "null in degraded read-only mode "
                                        "(breaker open: live state "
                                        "unknown)"},
             "paused": {"type": "boolean", "nullable": True},
             "resourcesReleased": b(),
             "degraded": b("Present/true when the answer came from the "
                           "store alone (substrate circuit open)"),
             "meshPlan": ref("MeshPlan"),
             "spec": ref("ContainerSpec"),
             "multihost": obj(
                 {}, additional=obj({}, additional=s()),
                 desc="Present when the grant spans TPU-VM hosts: "
                      "workerId -> env the worker's container needs so "
                      "the libtpu processes form one slice "
                      "(topology.multihost_env)")},
            desc="GET replicaSet info payload "
                 "(services/replicaset.py get_container_info)"),
        "ContainerHistoryItem": obj(
            {"version": i(), "createTime": s(),
             "status": ref("StoredContainerInfo")}),
        "VolumeCreateResponse": obj(
            {"name": s("Versioned volume name"), "version": i(),
             "mountpoint": s(), "size": s()}),
        "VolumeInfo": obj(
            {"version": i(), "createTime": s(), "volumeName": s(),
             "size": s(), "tier": s(), "mountpoint": s(),
             "usedBytes": {"type": "integer", "nullable": True,
                           "description": "null in degraded read-only "
                                          "mode (breaker open)"},
             "degraded": b("Present/true when served from the store "
                           "alone (substrate circuit open)")},
            desc="GET volume info payload (services/volume.py)"),
        "VolumeHistoryItem": obj(
            {"version": i(), "createTime": s(),
             "status": ref("StoredVolumeInfo")}),
        "TpuChip": obj(
            {"index": i("Global chip index"), "id": s(),
             "device": s("/dev/accel* path"),
             "coord": arr(i(), "ICI mesh coordinate"),
             "used": b("Whole-granted OR share-split"),
             "owner": s("Whole-chip granting replicaSet ('' = free or "
                        "share-split)"),
             "cordoned": b("Excluded from placement (health monitor or "
                           "operator cordon)"),
             "shares": obj({}, additional=i(),
                           desc="Fractional co-tenants: replicaSet -> "
                                "share quanta held (sums to <= 4)"),
             "freeShares": i("Share quanta still grantable on this chip "
                             "(0 when cordoned or whole-granted)")}),
        "TpuTopology": obj(
            {"acceleratorType": s("e.g. 'v5p-8'"), "generation": s(),
             "shape": arr(i(), "ICI mesh shape"), "wraparound": b(),
             "workerId": i(), "numWorkers": i(), "chipsPerHost": i(),
             "iciConnected": b()},
            desc="topology.Topology.serialize()"),
        "TpuStatus": obj(
            {"topology": ref("TpuTopology"), "chips": arr(ref("TpuChip")),
             "freeCount": {
                 "type": "number",
                 "description":
                     "ALLOCATABLE capacity in chip units, fractional "
                     "share capacity included (a half-shared chip "
                     "contributes its remaining quarters); integer when "
                     "no chip is share-split"},
             "freeShares": i("Total share quanta grantable to fractional "
                             "requests (4 = one whole free chip)"),
             "cordoned": arr(i(), "Cordoned chip indices")},
            desc="GET /resources/tpus payload (schedulers/tpu.py "
                 "get_status; reference GetGpuStatus)"),
        "CpuStatus": obj(
            {"totalCount": i(), "usedCount": i(),
             "usedCores": arr(i())}),
        "PortStatus": obj(
            {"range": arr(i(), "[start, end]"), "availableCount": i(),
             "usedPortSet": arr(i())}),
        "Event": obj(
            {"ts": {"type": "number", "description": "Unix seconds"},
             "op": s("Operation, e.g. 'replicaSet.run'"),
             "target": s(), "code": i("App code the op returned"),
             "durationMs": {"type": "number"}, "requestId": s(),
             "seq": i("Monotonic per-daemon sequence — the SSE event id; "
                      "pass the last seen value as Last-Event-ID (or "
                      "?lastEventId=) to resume a ?follow=1 stream from "
                      "the ring"),
             "traceId": s("Trace the event was recorded under (absent "
                          "when no traced request was on the recording "
                          "thread) — links this row to its span tree at "
                          "GET /api/v1/traces/{traceId}")},
            desc="Operation event (events.py record)"),
        "WatchEvent": obj(
            {"revision": i("MVCC revision the mutation committed at — "
                           "the SSE event id and the exact resume "
                           "point"),
             "resource": s("e.g. 'containers', 'gateways', "
                           "'fleet.grants'"),
             "name": s(), "type": s(enum=["put", "delete"]),
             "value": s("Stored JSON document (null on delete)",
                        nullable=True)},
            desc="One watched store mutation (federation.py WatchHub)"),
        "WatchItem": obj(
            {"name": s(), "value": s("Stored JSON document"),
             "modRevision": i()},
            desc="One resource row in a list snapshot"),
        "FleetMemberInfo": obj(
            {"member": s(), "addr": s("Advertised HOST:PORT for "
                                      "re-routing"),
             "epoch": i("Lease generation — bumps on every rejoin"),
             "ttlRemaining": {"type": "number"}},
            desc="One live fleet lease (federation.py FleetArbiter)"),
        "FleetGrant": obj(
            {"resource": s(), "name": s(),
             "holder": s("Member that owns this resource"),
             "epoch": i("Fencing token — bumps on every ownership "
                        "CHANGE (steal/takeover), never on the "
                        "holder's idempotent re-acquire"),
             "stolenFrom": s("Previous holder when this acquire was a "
                             "takeover steal; empty otherwise"),
             "modRevision": i()},
            desc="One row of the fleet grant table"),
        "FleetLease": obj(
            {"member": s(), "ttl": {"type": "number"},
             "epoch": i(), "members": arr(s(), "Live members, sorted")},
            desc="Join/renew response: the lease plus the live "
                 "membership the caller's hash ring must be computed "
                 "over"),
        "SpanEvent": obj(
            {"name": s("Point-in-time marker: an intent step name, "
                       "'retry', 'failed', or 'breaker.rejected'"),
             "t": {"type": "number",
                   "description": "Milliseconds since the span started"}},
            desc="Point-in-time marker inside a span (obs/trace.py); "
                 "extra keys carry marker-specific detail (retry attempt "
                 "+ backoffMs, breaker state, step sync flag)",
            additional=True),
        "Span": obj(
            {"traceId": s("32-hex W3C trace id"),
             "spanId": s("16-hex span id"),
             "parentId": s("Parent span id (null on the ingress root; an "
                           "id OUTSIDE the trace's span set when the "
                           "caller supplied a traceparent)",
                           nullable=True),
             "op": s("Stage name: '<METHOD> <route>' (ingress), 'svc.*', "
                     "'intent.*', 'backend.*', 'sched.*', 'store.*', "
                     "'copy.*', 'workqueue.apply', 'reconcile.*'"),
             "target": s("ReplicaSet/volume name the stage acted on"),
             "start": {"type": "number", "description": "Unix seconds"},
             "durationMs": {"type": "number"},
             "status": s("'ok', 'committed', or the exception class name "
                         "the stage died with"),
             "attrs": obj({}, additional=True,
                          desc="Stage attributes (granted chips, copy "
                               "bytes/mode, app code, ...)"),
             "events": arr(ref("SpanEvent"))},
            desc="One timed stage of a trace (obs/trace.py). In the "
                 "`tree` view each span additionally carries `children`, "
                 "sorted by start time."),
        "TraceSummary": obj(
            {"traceId": s(), "rootOp": s("The ingress root's op, e.g. "
                                         "'POST /api/v1/replicaSet'"),
             "target": s(), "start": {"type": "number"},
             "durationMs": {"type": "number"},
             "status": s(), "spanCount": i()},
            desc="Finished-trace summary (GET /api/v1/traces rows, "
                 "slowest first)"),
        "Trace": obj(
            {"traceId": s(), "rootOp": s(), "target": s(),
             "durationMs": {"type": "number"}, "status": s(),
             "spans": arr(ref("Span"), "Flat span list, finish order"),
             "tree": arr(ref("Span"),
                         "Spans nested by parentId (children sorted by "
                         "start); reconciler resumes of a crashed "
                         "mutation appear as additional roots on the "
                         "same trace")},
            desc="One full trace: every recorded span plus the assembled "
                 "span tree"),
        "TraceStats": obj(
            {"retained": i("Traces currently held in the ring "
                           "(keep-slowest retention)"),
             "spansTotal": i(), "dropped": i("Traces FIFO-evicted")},
            desc="Trace-collector self-observation (obs/trace.py)"),
        "ChipHealth": obj(
            {"index": i("Global chip index"), "device": s(),
             "failureScore": i("Consecutive failed probes (presence or "
                               "flap evidence); resets on success"),
             "healthy": b(), "cordoned": b()},
            desc="Per-chip probe state (health.py)"),
        "HealthReport": obj(
            {"status": s("'ok' or 'degraded'",
                         enum=["ok", "degraded"]),
             "substrate": obj({"reachable": b("backend.ping()")}),
             "chips": arr(ref("ChipHealth")),
             "cordoned": arr(i()),
             "flapping": obj(
                 {}, additional=i(),
                 desc="container -> restart count >= flap threshold"),
             "probes": i("Probe cycles run so far"),
             "lastProbeAt": {"type": "number",
                             "description": "Unix seconds"},
             "running": b("Background prober active")},
            desc="Substrate health probe report (health.py report)"),
        "BreakerState": obj(
            {"state": s(enum=["closed", "half_open", "open"]),
             "consecutiveFailures": i(), "threshold": i(),
             "cooldownSec": {"type": "number"}},
            desc="Backend circuit-breaker state (backend/guard.py); null "
                 "when the daemon runs unguarded"),
        "WorkerPostmortem": obj(
            {"worker": i("Worker slot index"),
             "pid": {"type": "integer", "nullable": True,
                     "description": "Dead process's pid"},
             "at": {"type": "number", "description": "Unix seconds of "
                                                     "the reap"},
             "reclaimedClaims": i("Replica slot claims the watchdog "
                                  "subtracted back (reconcile)"),
             "claimDelta": obj(
                 {}, additional=obj(
                     {"claims": i("Held replica-slot claims"),
                      "queued": i("Held admission-queue tickets")}),
                 desc="gateway -> what the dead worker still held"),
             "recorder": arr(
                 obj({}, additional=True),
                 "Final flight-recorder segment, read from the dead "
                 "worker's shared-memory ring (survives SIGKILL — no "
                 "handler ran in the worker); oldest first, bounded")},
            desc="Postmortem bundle the watchdog captures when reaping "
                 "a dead data-plane worker (server/workers.py); also "
                 "surfaced as a gateway.worker_postmortem event"),
        "WorkersBlock": obj(
            {"count": i("Configured worker processes"),
             "port": i("SO_REUSEPORT data-plane port"),
             "alive": i(), "respawns": i(),
             "reclaimedClaims": i("Total claims reconciled from dead "
                                  "workers"),
             "telemetry": b("Cross-process telemetry plane armed "
                            "(shm metric shards + span spooling + "
                            "flight recorder; obs/shm_metrics.py)"),
             "postmortems": arr(ref("WorkerPostmortem"),
                                "Recent dead-worker bundles, oldest "
                                "first (bounded ring)"),
             "gateways": obj(
                 {}, additional=obj(
                     {"requestsTotal": i(), "shedTotal": i(),
                      "queued": i(), "inflight": i(),
                      "affinityHits": i(), "affinityTokens": i(),
                      "hedges": i(), "hedgeWins": i(),
                      "retryBudgetExhausted": i()}),
                 desc="Per-gateway data-plane counters from the shared "
                      "segment")},
            desc="Multi-process data-plane tier status "
                 "(server/workers.py describe); null when the tier is "
                 "off (TDAPI_GW_WORKERS unset/0)"),
        "Healthz": obj(
            {"status": s(enum=["ok", "degraded"]),
             "health": ref("HealthReport"),
             "breaker": {"allOf": [ref("BreakerState")],
                         "nullable": True},
             "workqueue": obj({"pending": i(), "dropped": i()}),
             "workers": {"allOf": [ref("WorkersBlock")],
                         "nullable": True},
             "gateways": obj(
                 {}, additional=obj(
                     {"tailTolerance": ref("TailToleranceBlock")}),
                 desc="Per-gateway tail-tolerance posture, keyed by "
                      "gateway name"),
             "reconcileActions": i("Boot reconcile total; non-zero = the "
                                   "previous daemon died dirty"),
             "storeReadOnly": {"type": "string", "nullable": True,
                               "description":
                                   "Read-only latch reason while the WAL "
                                   "cannot be appended (ENOSPC &c; "
                                   "mutations answer 503 + Retry-After "
                                   "until the timed re-probe heals it); "
                                   "null when writable "
                                   "(docs/durability.md)"},
             "replication": {"allOf": [ref("ReplicationBlock")],
                             "nullable": True}},
            desc="GET /api/v1/healthz payload (server/app.py h_healthz)"),
        "ReplicationBlock": obj(
            {"peer": s("host:port of the replicated peer daemon "
                       "(--repl-peer / TDAPI_REPL_PEER)"),
             "horizon": i("Highest peer revision contiguously applied "
                          "to the local replica store"),
             "peerHead": i("Highest peer revision observed on the "
                           "watch stream"),
             "lagRevisions": i("peerHead - horizon (0 = caught up)"),
             "eventsApplied": i("Watch events applied since boot"),
             "resyncs": i("Full relist resyncs after WatchCompacted"),
             "connected": b("True while the watch stream is attached")},
            desc="Warm-standby replication status "
                 "(replication.py StandbyReplicator.describe; "
                 "docs/durability.md); null when no peer is "
                 "configured"),
        "CordonResponse": obj(
            {"cordoned": arr(i(), "Full cordoned set after the change")}),
        "DrainItem": obj(
            {"name": s("replicaSet base name"), "version": i("New version"),
             "fromChips": arr(i()), "toChips": arr(i()),
             "quiesced": b("True when the workload acknowledged the "
                           "checkpoint-now signal and parked with a "
                           "checkpoint at its exact step before the move "
                           "(the zero-loss path; backend quiesce "
                           "contract). False = plain stop-and-replay."),
             "stepsLost": {"type": "integer", "nullable": True,
                           "description":
                               "Training steps the migration forfeited: 0 "
                               "when quiesced (by construction). null when "
                               "not quiesced — unknown to the control "
                               "plane, bounded by the workload's "
                               "--checkpoint-every cadence."}}),
        "DrainResult": obj(
            {"cordoned": arr(i()),
             "drained": arr(ref("DrainItem")),
             "skipped": arr(s(), "Stopped replicaSets (hold no grant; "
                                 "restart re-grants healthy chips)"),
             "failed": obj({}, additional=s(),
                           desc="replicaSet -> error (e.g. not enough "
                                "healthy capacity); the rest of the "
                                "drain proceeds")},
            desc="POST /tpus/drain payload (services/replicaset.py "
                 "drain_cordoned)"),
        "PlacementPool": obj(
            {"name": s("Pool name (the daemon's own slice is its "
                       "generation)"),
             "generation": s("TPU generation, e.g. v4 / v5e / v5p"),
             "acceleratorType": s("e.g. v4-32"),
             "totalChips": i(), "freeChips": i(),
             "freeQuanta": i("Free quarter-chip share quanta"),
             "cordoned": i(), "shareSplit": i("Chips split into shares"),
             "largestFreeBox": i("Largest free ICI-contiguous box — the "
                                 "biggest gang admissible right now"),
             "fragmentation": {"type": "number",
                               "description": "1 - largestFreeBox/"
                                              "freeChips (0 = compact)"}},
            desc="One pool's capacity view (schedulers/tpu.py "
                 "capacity_view)"),
        "PlacementStatus": obj(
            {"policy": s("Active scoring objective"),
             "policies": arr(s(), "Known objectives"),
             "policyActive": b("False = scoring surface is up but "
                               "run_container still uses mechanism-layer "
                               "first-fit (no --placement-policy)"),
             "pools": arr(ref("PlacementPool")),
             "declaredProfiles": arr(s(), "Workloads with a declared "
                                          "profile"),
             "fittedProfiles": arr(s(), "Workloads with step-time "
                                        "observations"),
             "scoredTotal": i("Candidate boxes scored since boot"),
             "placementsTotal": i("Scored placements committed")},
            desc="GET /placement payload (placement.py "
                 "FleetModel.describe; docs/scheduling.md)"),
        "DefragStatus": obj(
            {"budgetFloor": i("Migration budget floor (chips moved per "
                              "run <= max(gang size, this); "
                              "TDAPI_DEFRAG_BUDGET)"),
             "pending": i("Fragmentation-blocked gang shapes queued for "
                          "the background loop"),
             "running": b("Background loop thread alive"),
             "runsTotal": i(), "migrationsTotal": i(),
             "movedChipsTotal": i(), "stepsLostTotal": i(),
             "deniedTotal": i(),
             "lastRunMs": {"type": "number"}},
            desc="Defragmenter counters (defrag.py)"),
        "DefragRequest": obj(
            {"tpuCount": i("Gang size in whole chips (required)",
                           minimum=1),
             "meshPlan": ref("MeshPlan")},
            required=["tpuCount"],
            desc="POST /placement/defrag body: the gang shape to open a "
                 "box for"),
        "DefragReport": obj(
            {"n": i("Requested gang size"),
             "opened": b("True = an ICI-contiguous n-chip box is now "
                         "free; re-POST the gang to admit it"),
             "pool": s("Pool whose box was opened (on success)"),
             "box": arr(i(), "The opened chips"),
             "migrations": arr(ref("DrainItem")),
             "movedChips": i("Chips migrated this run (<= budget)"),
             "stepsLost": i("Training steps forfeited across all "
                            "migrations — 0 when every evicted tenant "
                            "quiesced"),
             "denied": s("Refusal reason: not fragmentation-blocked / "
                         "no eviction plan within budget / an eviction "
                         "error")},
            desc="One defrag run's report (defrag.py "
                 "Defragmenter.run_for)"),
        "GatewayCreate": obj(
            {"name": s("Gateway name (required; no '-')"),
             "image": s("Replica image (required)"),
             "cmd": arr(s(), "Replica command — must serve the workload "
                             "HTTP contract (POST /generate, GET "
                             "/healthz with a `batching` block; "
                             "workloads/serve.py or mock_model.py)"),
             "env": arr(s()),
             "tpuCount": {
                 "type": "number", "minimum": 0, "multipleOf": 0.25,
                 "description": "Per-replica chips; a fraction (0.25/0.5/"
                                "0.75) multiplexes several models per "
                                "chip through the share ledger + "
                                "regulator, with one gateway's replicas "
                                "spread across chips (soft "
                                "anti-affinity)"},
             "cpuCount": i(), "memory": s(),
             "priority": s("Regulator class for fractional replicas: "
                           "'' | latency | best_effort"),
             "port": s("containerPort the replica serves on "
                       "(default 8000; a host port is granted per "
                       "replica)"),
             "minReplicas": i("Floor; 0 enables scale-to-zero "
                              "(default 1)"),
             "maxReplicas": i("Ceiling the autoscaler may reach "
                              "(default 4)"),
             "sloMs": {"type": "number",
                       "description": "p99 target the autoscaler "
                                      "defends (default 1000)"},
             "deadlineMs": {"type": "number",
                            "description": "Per-request deadline at the "
                                           "gateway (default 10000)"},
             "maxQueue": i("Admission queue bound — past it requests "
                           "shed 429 immediately (default 64)"),
             "scaleUpQueue": i("Queued-per-ready-replica that triggers "
                               "scale-up (default 4)"),
             "scaleDownIdleS": {"type": "number",
                                "description": "Idle seconds before "
                                               "scaling down (default "
                                               "60)"},
             "slots": i("Assumed per-replica batcher slots until the "
                        "replica's /healthz advertises them (default 4)"),
             "readiness": s("http (poll replica /healthz; default) | "
                            "running (trust substrate run state)"),
             "poolPolicy": s("shared (default; every replica serves both "
                             "phases) | disaggregated (even replica idx = "
                             "prefill pool, odd = decode pool; long-prompt "
                             "requests run the two-phase KV handoff; "
                             "docs/serving.md §KV-aware routing)")},
            required=["name", "image"],
            desc="POST /api/v1/gateways body (gateway.GatewayConfig)"),
        "GatewayReplica": obj(
            {"name": s("Replica replicaSet name ({gateway}r{idx})"),
             "container": s("Current versioned container"),
             "hostPort": i(), "state": s("starting | ready | stopping | "
                                         "stopped | failed"),
             "slots": i("Batcher slots the gateway admits against"),
             "inflight": i(), "chips": arr(i()), "failures": i(),
             "role": s("shared | prefill | decode (idx parity under "
                       "poolPolicy=disaggregated)"),
             "kvOcc": i("Prefix-cache blocks the replica last "
                        "advertised (X-TDAPI-KV-Occ fold)"),
             "probation": b("In the tail-tolerance probation set "
                            "(score-penalized; serves trickle probes "
                            "toward re-admission)")}),
        "TailToleranceBlock": obj(
            {"ejectEnabled": b("TDAPI_GW_EJECT != 0"),
             "hedgeEnabled": b("TDAPI_GW_HEDGE != 0"),
             "retryBudgetEnabled": b("TDAPI_GW_RETRY_BUDGET != 0"),
             "probation": obj(
                 {}, additional=obj(
                     {"kind": s("latency (gray-failure ejection) | "
                                "failed (transport-strike heal)"),
                      "passes": i("Consecutive trickle-probe passes "
                                  "toward re-admission")}),
                 desc="Replicas currently in probation, keyed by name"),
             "ejections": i("Replicas ejected by the latency outlier "
                            "detector (cumulative)"),
             "probationPasses": i("Probation re-admissions (cumulative)"),
             "hedges": i("Hedged requests fired"),
             "hedgeWins": i("Hedges whose duplicate finished first"),
             "retryBudgetExhausted": i("Forwards shed 503 because the "
                                       "retry budget ran dry"),
             "retryTokens": {"type": "number",
                             "description": "Current retry-budget token "
                                            "level (capacity 16)"},
             "fleetMedianMs": {"type": "number", "nullable": True,
                               "description":
                                   "Healthy-fleet median windowed p95 "
                                   "(the ejection threshold's base); "
                                   "null before enough samples"}},
            desc="Per-gateway tail-tolerance posture: kill-switch "
                 "state, probation roster, ejection/hedge/retry-budget "
                 "counters (docs/serving.md §Tail tolerance)"),
        "GatewayStatus": obj(
            {"name": s(), "config": ref("GatewayCreate"),
             "replicas": arr(ref("GatewayReplica")),
             "readyReplicas": i(), "queueDepth": i(), "inflight": i(),
             "p99Ms": {"type": "number", "nullable": True,
                       "description": "Rolling 30s p99 (the autoscaler's "
                                      "SLO signal); null before traffic"},
             "requestsTotal": i(), "shedTotal": i(),
             "scaleUps": i(), "scaleDowns": i(),
             "affinityHits": i("Requests the KV sketch steered off the "
                               "bare least-queued pick"),
             "affinityTokens": i("Prefill tokens those hits predicted "
                                 "saved"),
             "kvHandoffs": i("Completed prefill->decode disaggregated "
                             "handoffs"),
             "tailTolerance": ref("TailToleranceBlock"),
             "lastScaleReadyMs": {
                 "type": "number", "nullable": True,
                 "description": "Last scale trigger -> replica READY "
                                "latency (the CoW-clone fast path vs "
                                "~1.9s cold start)"}},
            desc="Live gateway status (gateway.Gateway.describe)"),
        "GatewayScale": obj({"replicas": i("Target live replicas "
                                           "(0..maxReplicas)")},
                            required=["replicas"]),
        "GenerateRequest": obj(
            {"tokens": arr(arr(i()), "Prompt token ids [batch, len]"),
             "max_new": i("Tokens to generate (default 16)"),
             "temperature": {"type": "number"},
             "top_k": i(), "top_p": {"type": "number"}},
            required=["tokens"],
            desc="The serving workload's /generate body, relayed "
                 "verbatim to a replica"),
        "GenerateResponse": obj(
            {"tokens": arr(arr(i()), "Generated streams [batch, len]")}),
        "ReconcileReport": obj(
            {"intentsReplayed": arr(s("kind:target:op")),
             "opsCompleted": arr(s()),
             "orphanContainersRemoved": arr(s()),
             "containersRecreated": arr(s()),
             "containersStarted": arr(s()),
             "containersAdopted": arr(s()),
             "layersCopied": i(),
             "grantsFreed": obj({"tpu": i(), "cpu": i(), "ports": i()}),
             "grantsRemarked": obj({"tpu": i(), "cpu": i(), "ports": i()}),
             "versionFixes": i(),
             "orphanVolumesRemoved": arr(s()),
             "volumesMigrated": i(),
             "droppedReplayed": i(),
             "idempotency": obj(
                 {"finalized": i("in_progress records whose intent "
                                 "rolled forward (retries replay)"),
                  "dropped": i("records of unwound/never-started "
                               "mutations (retries re-execute)"),
                  "expired": i("TTL-expired records swept")},
                 desc="Idempotency-cache settlement (idempotency.py "
                      "reconcile_boot)"),
             "actions": i("Total corrective actions; 0 = clean boot")},
            desc="Boot-time crash-recovery report (reconcile.py)"),
    }

    v1 = "/api/v1"
    paths = {
        "/ping": {"get": op(
            "ping", "Health check", envelope(None, None), tags=["meta"])},
        f"{v1}/replicaSet": {"post": op(
            "runReplicaSet",
            "Create + start a container under a new replicaSet",
            envelope(ref("RunResponse"), run_resp_example),
            body=ref("ContainerRun"), tags=["replicaSet"],
            desc="Grants tpuCount ICI-contiguous chips (or a fractional "
                 "share of one chip when tpuCount < 1), cpuCount cores, "
                 "and one host port per containerPort, then starts "
                 "version 1 ({name}-1) on the substrate. App errors: "
                 "1001 exists, 1013/1014/1015 not enough tpu/cpu/port, "
                 "1026 fractional share capacity oversubscribed.")},
        f"{v1}/replicaSet/{{name}}": {
            "get": op("getReplicaSet", "Current-version info",
                      envelope(obj({"info": ref("ContainerInfo")})),
                      params=[NAME_PARAM], tags=["replicaSet"]),
            "patch": op(
                "patchReplicaSet",
                "Lift TPU/CPU/memory/volume config via rolling "
                "replacement",
                envelope(ref("RunResponse"), run_resp_example),
                body=ref("PatchRequest"), params=[NAME_PARAM],
                tags=["replicaSet"],
                desc="Creates version {name}-{v+1}; the writable layer is "
                     "copied; the old container stops BEFORE the new one "
                     "starts (TPU chips are exclusive). A tpuPatch "
                     "prefers sub-meshes containing the current grant. On "
                     "a gang replicaSet a tpuCount/meshPlan change is a "
                     "live RESHARD (quiesce-checkpoint -> plan-shaped "
                     "re-grant -> resume under the new mesh; docs/"
                     "gang.md)."),
            "delete": op("deleteReplicaSet",
                         "Stop, release grants, delete all versions",
                         envelope(None), params=[NAME_PARAM],
                         tags=["replicaSet"])},
        f"{v1}/replicaSet/{{name}}/rollback": {"patch": op(
            "rollbackReplicaSet", "Roll back to a stored version",
            envelope(ref("RunResponse"), run_resp_example),
            body=ref("RollbackRequest"), params=[NAME_PARAM],
            tags=["replicaSet"],
            desc="Re-runs the stored spec as a NEW version (the reference "
                 "semantics: rollback is re-create, so history stays "
                 "append-only)")},
        f"{v1}/replicaSet/{{name}}/stop": {"patch": op(
            "stopReplicaSet", "Stop + release chip/core/port grants",
            envelope(None), params=[NAME_PARAM], tags=["replicaSet"])},
        f"{v1}/replicaSet/{{name}}/restart": {"patch": op(
            "restartReplicaSet", "Restart (re-grants released resources)",
            envelope(ref("RunResponse"), run_resp_example),
            params=[NAME_PARAM], tags=["replicaSet"])},
        f"{v1}/replicaSet/{{name}}/pause": {"patch": op(
            "pauseReplicaSet", "SIGSTOP the container processes",
            envelope(None), params=[NAME_PARAM], tags=["replicaSet"])},
        f"{v1}/replicaSet/{{name}}/continue": {"patch": op(
            "continueReplicaSet", "SIGCONT after pause",
            envelope(None), params=[NAME_PARAM], tags=["replicaSet"])},
        f"{v1}/replicaSet/{{name}}/execute": {"post": op(
            "executeReplicaSet", "Exec a command inside the container",
            envelope(ref("ExecuteResponse"), {"output": "hello\n"}),
            body=ref("ContainerExecute"), params=[NAME_PARAM],
            tags=["replicaSet"])},
        f"{v1}/replicaSet/{{name}}/commit": {"post": op(
            "commitReplicaSet", "Commit the container to a new image",
            envelope(ref("CommitResponse")),
            body=ref("ContainerCommit"), params=[NAME_PARAM],
            tags=["replicaSet"])},
        f"{v1}/replicaSet/{{name}}/history": {"get": op(
            "replicaSetHistory", "All stored versions, newest first",
            envelope(obj({"history": arr(ref("ContainerHistoryItem"))})),
            params=[NAME_PARAM], tags=["replicaSet"])},
        f"{v1}/volumes": {"post": op(
            "createVolume", "Create a versioned volume",
            envelope(ref("VolumeCreateResponse"),
                     {"name": "data-1", "version": 1,
                      "mountpoint": "/var/lib/tdapi/volumes/data-1",
                      "size": "20GB"}),
            body=ref("VolumeCreate"), tags=["volume"])},
        f"{v1}/volumes/{{name}}": {
            "get": op("getVolume", "Current-version info",
                      envelope(obj({"info": ref("VolumeInfo")})),
                      params=[NAME_PARAM], tags=["volume"]),
            "delete": op(
                "deleteVolume", "Delete the volume",
                envelope(None),
                params=[NAME_PARAM,
                        {"name": "noall", "in": "query", "required": False,
                         "schema": {"type": "boolean"},
                         "description": "Keep history versions; delete "
                                        "only the current one"}],
                tags=["volume"])},
        f"{v1}/volumes/{{name}}/size": {"patch": op(
            "patchVolumeSize",
            "Scale the volume (new version; data migrated; shrink "
            "guarded by used bytes)",
            envelope(ref("VolumeCreateResponse")),
            body=ref("VolumeSize"), params=[NAME_PARAM], tags=["volume"])},
        f"{v1}/volumes/{{name}}/history": {"get": op(
            "volumeHistory", "All stored versions, newest first",
            envelope(obj({"history": arr(ref("VolumeHistoryItem"))})),
            params=[NAME_PARAM], tags=["volume"])},
        f"{v1}/resources/tpus": {"get": op(
            "resourceTpus", "Chip inventory + ICI topology",
            envelope(obj({"tpus": ref("TpuStatus")})), tags=["resource"])},
        f"{v1}/resources/gpus": {"get": op(
            "resourceGpus", "Legacy alias of /resources/tpus",
            envelope(obj({"tpus": ref("TpuStatus")})), tags=["resource"])},
        f"{v1}/resources/cpus": {"get": op(
            "resourceCpus", "Core inventory",
            envelope(obj({"cpus": ref("CpuStatus")})), tags=["resource"])},
        f"{v1}/resources/ports": {"get": op(
            "resourcePorts", "Host-port pool",
            envelope(obj({"ports": ref("PortStatus")})),
            tags=["resource"])},
        f"{v1}/events": {"get": op(
            "events", "Recent operation events (bounded ring), or — with "
            "?follow=1 — a live Server-Sent Events stream",
            {"200": {
                "description":
                    "Envelope with the ring snapshot — or, with "
                    "?follow=1, a close-delimited text/event-stream: "
                    "each event goes out as `id: <seq>` + `data: <Event "
                    "JSON>`; `: heartbeat` comment frames mark idle "
                    "intervals. Reconnect with Last-Event-ID (or "
                    "?lastEventId=) to resume from the ring — a resume "
                    "point older than the ring's tail first yields an "
                    "`event: gap` frame (data: {firstRetained}) so the "
                    "client KNOWS records were lost, then the retained "
                    "suffix; the shipped client.follow_events() raises "
                    "a typed EventGapError there. Subscribe instead of "
                    "polling.",
                "content": {
                    "application/json": {"schema": {
                        "allOf": [ref("Envelope"), {
                            "type": "object", "properties": {
                                "data": obj(
                                    {"events": arr(ref("Event"))})}}]}},
                    "text/event-stream": {
                        "schema": {"type": "string"}}}}},
            params=[{"name": "limit", "in": "query", "required": False,
                     "schema": {"type": "integer", "minimum": 0}},
                    {"name": "target", "in": "query", "required": False,
                     "schema": {"type": "string"},
                     "description": "Filter by event target name"},
                    {"name": "follow", "in": "query", "required": False,
                     "schema": {"type": "string"},
                     "description": "Set to 1 to stream new events as "
                                    "Server-Sent Events instead of "
                                    "answering a snapshot"},
                    {"name": "heartbeat", "in": "query", "required": False,
                     "schema": {"type": "number", "minimum": 0.05},
                     "description": "Idle-heartbeat cadence in seconds "
                                    "(follow=1 only; default 15)"},
                    {"name": "lastEventId", "in": "query",
                     "required": False,
                     "schema": {"type": "integer", "minimum": 0},
                     "description": "Resume point (follow=1 only): "
                                    "stream ring events with seq greater "
                                    "than this, then live ones"},
                    {"name": "Last-Event-ID", "in": "header",
                     "required": False,
                     "schema": {"type": "integer", "minimum": 0},
                     "description": "Header form of lastEventId (what an "
                                    "EventSource reconnect sends)"}],
            tags=["meta"])},
        f"{v1}/watch": {"get": op(
            "watch", "Per-resource list+watch on MVCC store revisions — "
            "with ?list=1 an atomic snapshot, otherwise a revision-"
            "ordered Server-Sent Events stream",
            {"200": {
                "description":
                    "With ?list=1: envelope {resource, revision, items} "
                    "— an atomic snapshot plus the exact revision to "
                    "pass back as fromRevision, the list half of "
                    "list+watch (client.Informer does both). Otherwise "
                    "a close-delimited text/event-stream: every store "
                    "mutation under the resource goes out as `id: "
                    "<revision>` + `data: <WatchEvent JSON>` in strict "
                    "revision order with no gaps or duplicates "
                    "(model-checked invariant FW1, tools/tdcheck); `: "
                    "heartbeat` comments mark idle intervals. Resume "
                    "with fromRevision= or Last-Event-ID. A resume "
                    "point the ring has compacted past is REFUSED "
                    "before streaming (envelope code 1036, data.floor) "
                    "— relist, then watch from the snapshot revision; a "
                    "fromRevision ahead of the store's head is refused "
                    "the same way (code 1036 with data.head: a "
                    "revision from another daemon's store, e.g. after "
                    "fleet takeover moved the client to a different "
                    "member). If compaction overtakes an attached slow "
                    "consumer mid-stream, the stream emits one `event: "
                    "gap` frame and closes; the client must relist.",
                "content": {
                    "application/json": {"schema": {
                        "allOf": [ref("Envelope"), {
                            "type": "object", "properties": {
                                "data": obj(
                                    {"resource": s(),
                                     "revision": i(
                                         "Store revision the snapshot "
                                         "is consistent at — watch "
                                         "from here"),
                                     "items": arr(ref("WatchItem"))})}}]}},
                    "text/event-stream": {
                        "schema": {"type": "string"}}}}},
            params=[{"name": "resource", "in": "query", "required": True,
                     "schema": {"type": "string"},
                     "description": "Store subtree to watch: "
                                    "'containers', 'gateways', 'volumes' "
                                    "... or the fleet planes "
                                    "'fleet.grants' / 'fleet.leases'"},
                    {"name": "list", "in": "query", "required": False,
                     "schema": {"type": "string"},
                     "description": "Set to 1 for the atomic snapshot "
                                    "instead of the stream"},
                    {"name": "fromRevision", "in": "query",
                     "required": False,
                     "schema": {"type": "integer", "minimum": 0},
                     "description": "Stream mutations with revision "
                                    "strictly greater than this "
                                    "(default: now — live tail only)"},
                    {"name": "heartbeat", "in": "query", "required": False,
                     "schema": {"type": "number", "minimum": 0.05},
                     "description": "Idle-heartbeat cadence in seconds "
                                    "(default 15)"},
                    {"name": "Last-Event-ID", "in": "header",
                     "required": False,
                     "schema": {"type": "integer", "minimum": 0},
                     "description": "Header form of fromRevision (what "
                                    "an EventSource reconnect sends)"}],
            tags=["meta"],
            desc="The federation wire: fleet members watch "
                 "'fleet.grants' to mirror ownership, informers keep "
                 "caches warm across daemon takeover "
                 "(docs/federation.md). Revisions are per-daemon; after "
                 "redirecting to a new member, relist rather than "
                 "resuming with the old daemon's revision.")},
        f"{v1}/traces": {"get": op(
            "traces", "Finished-trace summaries, slowest first "
            "(keep-slowest retention: the ring pins its slowest traces "
            "past FIFO eviction)",
            envelope(obj({"traces": arr(ref("TraceSummary")),
                          "stats": ref("TraceStats")})),
            params=[{"name": "op", "in": "query", "required": False,
                     "schema": {"type": "string"},
                     "description": "Root-op substring filter, e.g. "
                                    "'PATCH' or '/replicaSet'"},
                    {"name": "minDurationMs", "in": "query",
                     "required": False,
                     "schema": {"type": "number", "minimum": 0},
                     "description": "Only traces at least this slow"},
                    {"name": "limit", "in": "query", "required": False,
                     "schema": {"type": "integer", "minimum": 0}}],
            tags=["meta"],
            desc="Every REST mutation yields a trace: ingress -> service "
                 "-> intent steps -> scheduler grant -> backend ops "
                 "(retries/breaker rejections as span events) -> store "
                 "writes, async write-behind stages included. Events and "
                 "error envelopes carry traceId, linking them here.")},
        f"{v1}/traces/{{traceId}}": {"get": op(
            "trace", "One full trace: flat span list + assembled span "
            "tree",
            envelope(obj({"trace": ref("Trace")})),
            params=[{"name": "traceId", "in": "path", "required": True,
                     "schema": {"type": "string",
                                "pattern": "^[0-9a-f]{32}$"},
                     "description": "From a traceparent this client "
                                    "sent, an error envelope, an event "
                                    "row, or the /traces listing"}],
            tags=["meta"],
            desc="App error 1000 when the id is unknown (evicted or "
                 "never seen). A crash-recovered mutation's trace also "
                 "carries the boot reconciler's replay spans — the "
                 "intent journal preserves the original request's trace "
                 "identity across the crash.")},
        f"{v1}/healthz": {"get": op(
            "healthz", "Substrate health: chip presence, reachability, "
            "flap detection, breaker state",
            envelope(ref("Healthz")),
            params=[{"name": "probe", "in": "query", "required": False,
                     "schema": {"type": "boolean"},
                     "description": "Run a fresh probe cycle inline "
                                    "instead of answering from the last "
                                    "background cycle"}],
            tags=["meta"],
            desc="status='degraded' when the substrate is unreachable, "
                 "any chip is failing or cordoned, a container is "
                 "flapping, or the breaker is not closed. With the "
                 "multi-process data-plane tier on (TDAPI_GW_WORKERS>0) "
                 "the `workers` block carries per-gateway data-plane "
                 "counters and the recent dead-worker POSTMORTEM "
                 "bundles (flight-recorder segment + claim-reconcile "
                 "delta).")},
        f"{v1}/tpus/{{id}}/cordon": {"post": op(
            "cordonTpu", "Exclude a chip from all future placements",
            envelope(ref("CordonResponse"), {"cordoned": [3]}),
            params=[CHIP_PARAM], tags=["resource"],
            desc="A cordoned chip that is currently granted keeps its "
                 "owner — cordon never kills a workload; POST "
                 "/tpus/drain migrates them off. Persisted: a restart "
                 "cannot resurrect the chip as allocatable.")},
        f"{v1}/tpus/{{id}}/uncordon": {"post": op(
            "uncordonTpu", "Return a cordoned chip to the allocatable "
            "pool",
            envelope(ref("CordonResponse"), {"cordoned": []}),
            params=[CHIP_PARAM], tags=["resource"])},
        f"{v1}/tpus/drain": {"post": op(
            "drainTpus", "Migrate every replicaSet holding a cordoned "
            "chip onto healthy chips",
            envelope(ref("DrainResult")), tags=["resource"],
            desc="Each migration is an intent-journaled rolling "
                 "replacement (crash mid-drain reconciles at boot). "
                 "Workloads that opted into the quiesce contract (spec "
                 "env TDAPI_QUIESCE=1, SIGUSR1 handler) are asked to "
                 "checkpoint-now and park before the stop, making the "
                 "move zero-loss (per-item quiesced/stepsLost report "
                 "it); on timeout the drain falls back to a plain stop. "
                 "Per-replicaSet failures are reported in `failed` and "
                 "do not abort the rest — re-POSTing is idempotent: "
                 "already-migrated sets are skipped, failed ones "
                 "retried. App error 503 when the backend circuit is "
                 "open.")},
        f"{v1}/placement": {"get": op(
            "getPlacement", "Placement policy, per-pool capacity + "
            "fragmentation views, and defragmenter counters",
            envelope(obj({"placement": ref("PlacementStatus"),
                          "defrag": ref("DefragStatus")})),
            tags=["resource"],
            desc="The heterogeneity-aware placement surface "
                 "(docs/scheduling.md): which scoring objective is "
                 "active (--placement-policy / TDAPI_PLACEMENT_POLICY; "
                 "policyActive false = mechanism-layer first-fit), each "
                 "pool's largest free ICI-contiguous box and "
                 "fragmentation ratio, and the defragmenter's "
                 "run/migration/denial counters.")},
        f"{v1}/placement/defrag": {"post": op(
            "runDefrag", "Synchronously open an ICI-contiguous box for "
            "a fragmentation-blocked gang shape",
            envelope(obj({"defrag": ref("DefragReport")})),
            body=ref("DefragRequest"), tags=["resource"],
            desc="The operator-driven twin of the background defrag "
                 "loop: if the shape is geometry- and capacity-feasible "
                 "but no free box exists, the cheapest set of small "
                 "tenants is migrated off a candidate box via the "
                 "quiesce -> CoW-move -> re-grant ladder (hard avoid on "
                 "the box), under the migration budget. Idempotent: "
                 "re-POSTing after a crash or partial run re-diagnoses "
                 "live state and finishes the eviction; a shape that is "
                 "not fragmentation-blocked is a clean deny, never a "
                 "migration storm. App error 503 when the backend "
                 "circuit is open.")},
        f"{v1}/reconcile": {"get": op(
            "reconcile", "Crash-recovery report from the boot-time "
            "reconciler; ?run=1 performs a fresh pass (admin; quiesce "
            "mutations first)",
            envelope(obj({"reconcile": ref("ReconcileReport")})),
            params=[{"name": "run", "in": "query", "required": False,
                     "schema": {"type": "string"},
                     "description": "Set to 1 to run a fresh pass"}],
            tags=["meta"])},
        f"{v1}/gateways": {
            "post": op(
                "createGateway",
                "Create an inference gateway (router + autoscaler) "
                "fronting N model replicas",
                envelope(obj({"gateway": ref("GatewayStatus")})),
                body=ref("GatewayCreate"), tags=["gateway"],
                desc="Starts minReplicas replicas immediately (each an "
                     "ordinary replicaSet named {gateway}r{idx}, "
                     "intent-journaled), then runs the autoscaler "
                     "control loop: scale-up clones a warm replica's "
                     "writable layer (CoW reflink ladder) so a new "
                     "replica is serving well under the cold-start "
                     "time; idle gateways scale down to minReplicas "
                     "(0 = scale-to-zero; the first request wakes one "
                     "replica back through the warm pool). Fractional "
                     "tpuCount multiplexes several gateways' small "
                     "models per chip via the share ledger + regulator. "
                     "App errors: 1030 exists, 1013/1026 capacity."),
            "get": op("listGateways", "All gateways with live status",
                      envelope(obj({"gateways":
                                    arr(ref("GatewayStatus"))})),
                      tags=["gateway"])},
        f"{v1}/gateways/{{name}}": {
            "get": op("getGateway", "Live gateway status",
                      envelope(obj({"gateway": ref("GatewayStatus")})),
                      params=[GW_PARAM], tags=["gateway"]),
            "delete": op("deleteGateway",
                         "Stop the autoscaler, delete every replica, "
                         "drop the gateway",
                         envelope(None), params=[GW_PARAM],
                         tags=["gateway"])},
        f"{v1}/gateways/{{name}}/scale": {"patch": op(
            "scaleGateway", "Manually scale to exactly N live replicas",
            envelope(obj({"gateway": ref("GatewayStatus")})),
            body=ref("GatewayScale"), params=[GW_PARAM],
            tags=["gateway"],
            desc="Bounded by the configured maxReplicas; the autoscaler "
                 "keeps managing afterwards (an idle gateway scales "
                 "back down). Scale mutations are intent-journaled.")},
        f"{v1}/gateways/{{name}}/generate": {"post": op(
            "gatewayGenerate",
            "DATA PLANE: route one generate request through the "
            "gateway's continuous-batching router",
            envelope(ref("GenerateResponse"),
                     {"tokens": [[1, 2, 3, 7, 9]]}),
            body=ref("GenerateRequest"),
            params=[GW_PARAM,
                    {"name": "stream", "in": "query", "required": False,
                     "schema": {"type": "string"},
                     "description":
                         "Present: relay the replica's body as a "
                         "close-delimited stream (StreamingResponse) "
                         "instead of a buffered reply"},
                    {"name": "X-TDAPI-Priority", "in": "header",
                     "required": False,
                     "schema": {"type": "string",
                                "enum": ["", "high", "latency"]},
                     "description":
                         "Admission class: high/latency requests drain "
                         "through a strict-priority FIFO ahead of "
                         "best-effort traffic — an SLO-bound stream "
                         "keeps its p99 through a burst (the gateway "
                         "twin of the regulator's latency class)"}],
            tags=["gateway"],
            desc="Admitted when a ready replica has a free batcher slot "
                 "(least-queued routing KV-affinity-scored: replicas "
                 "advertising a Bloom-sketch hit on the prompt's prefix "
                 "win queue ties, never a shorter queue; FIFO "
                 "admission); bypasses the mutation gate and idempotency "
                 "middleware — serving traffic is not a control "
                 "mutation. Sheds HTTP 429 + Retry-After when the "
                 "gateway queue is full, HTTP 504 (envelope 504) when "
                 "the per-request deadline passes before a slot frees; "
                 "both feed the autoscaler. The replica's envelope is "
                 "relayed verbatim. Under poolPolicy=disaggregated, "
                 "non-streamed prompts past TDAPI_GW_DISAGG_PROMPT "
                 "tokens run the two-phase prefill->decode KV handoff "
                 "(X-TDAPI-Phase / X-TDAPI-KV-Key / X-TDAPI-KV-Source "
                 "replica headers; docs/serving.md §KV-aware routing), "
                 "falling back to the shared path on any miss.")},
        f"{v1}/fleet/lease": {"post": op(
            "fleetJoin", "Join the fleet (or rejoin after expiry): "
            "acquire this member's TTL lease",
            envelope(ref("FleetLease"),
                     {"member": "b", "ttl": 5.0, "epoch": 1,
                      "members": ["a", "b"]}),
            body=obj({"member": s("Member id (--fleet-member)"),
                      "addr": s("Advertised HOST:PORT other daemons "
                                "redirect writes to")},
                     required=["member"]),
            tags=["fleet"],
            desc="Rejoining after one's own lease expired bumps the "
                 "lease epoch; the member fences first (drops every "
                 "believed-owned resource) and re-acquires through the "
                 "grant table, so a paused-and-resumed daemon can never "
                 "act on stale ownership. Raw route: bypasses the "
                 "mutation gate and idempotency middleware (a heartbeat "
                 "that can be shed expires its own lease).")},
        f"{v1}/fleet/lease/{{member}}/renew": {"post": op(
            "fleetRenew", "Heartbeat: extend the lease TTL",
            envelope(ref("FleetLease")),
            params=[MEMBER_PARAM], tags=["fleet"],
            desc="Runs at TTL/3 from FleetMember.start(). Envelope code "
                 "1038 with data.reason='no-lease' once the lease has "
                 "already expired — the member must rejoin (fence + "
                 "fresh epoch), not keep renewing.")},
        f"{v1}/fleet/lease/{{member}}": {"delete": op(
            "fleetLeave", "Leave the fleet: release the lease and every "
            "grant this member holds",
            envelope(obj({"member": s(),
                          "released": arr(s(), "Grant keys freed, "
                                          "'resource:name'")})),
            params=[MEMBER_PARAM], tags=["fleet"],
            desc="Graceful shutdown path (daemon stop). The freed "
                 "slices are re-acquired by the surviving members' next "
                 "heartbeat sweep — same machinery as crash takeover, "
                 "minus the TTL wait.")},
        f"{v1}/fleet/members": {"get": op(
            "fleetMembers", "Live fleet membership",
            envelope(obj({"members": arr(ref("FleetMemberInfo")),
                          "ttl": {"type": "number",
                                  "description": "Configured lease TTL "
                                                 "(seconds)"}})),
            tags=["fleet"],
            desc="Reading membership lazily sweeps expired leases "
                 "first, so the answer never lists a dead member as "
                 "live. The member set is the hash-ring input: "
                 "ownership of a resource is owner_of(key, members) — "
                 "derived, never stored (docs/federation.md).")},
        f"{v1}/fleet/grants": {
            "get": op(
                "fleetGrants", "The grant table: which member owns "
                "which resource slice",
                envelope(obj({"grants": arr(ref("FleetGrant"))})),
                tags=["fleet"],
                desc="Grant epochs are fencing tokens: takeover bumps "
                     "them, so a stale holder's writes are detectable. "
                     "Watchable live via GET /api/v1/watch?resource="
                     "fleet.grants — model-checked invariant L1: at "
                     "most one live holder per resource at every "
                     "instant (tools/tdcheck LeaseModel)."),
            "post": op(
                "fleetAcquire", "Acquire (or take over) ownership of "
                "one resource",
                envelope(ref("FleetGrant"),
                         {"resource": "containers", "name": "rs0",
                          "holder": "a", "epoch": 2,
                          "stolenFrom": "b", "modRevision": 41}),
                body=obj({"resource": s(), "name": s(),
                          "member": s("Acquiring member — must hold a "
                                      "live lease and own the key on "
                                      "the current hash ring")},
                         required=["resource", "name", "member"]),
                tags=["fleet"],
                desc="Refusals are typed in the envelope: code 1038 "
                     "data.reason='no-lease' (caller's lease expired), "
                     "'not-owner' (hash ring places the key "
                     "elsewhere), or 'held' with data.owner/"
                     "data.ownerAddr (another member's lease is still "
                     "live — redirect the write there; this is also "
                     "code 1037 on the fenced mutation routes). "
                     "Stealing succeeds only once the holder's lease "
                     "expired, bumping the grant epoch; a concurrent "
                     "steal race has exactly one winner, the loser "
                     "gets the clean 'held' refusal. The holder's own "
                     "re-acquire is idempotent and does NOT bump the "
                     "epoch.")},
        f"{v1}/fleet/grants/release": {"post": op(
            "fleetRelease", "Release one grant this member holds",
            envelope(obj({"released": b("Whether a grant was removed "
                                        "(repeat releases answer "
                                        "false)")})),
            body=obj({"resource": s(), "name": s(),
                      "member": s("Releasing member — must be the "
                                  "current holder")},
                     required=["resource", "name", "member"]),
            tags=["fleet"],
            desc="Used when a resource is deleted or its ring slice "
                 "moved after membership change. Releasing a grant "
                 "held by someone else is refused (code 1038).")},
        "/metrics": {"get": op(
            "metrics", "Prometheus text exposition",
            {"200": {"description": "text/plain; version=0.0.4",
                     "content": {"text/plain": {
                         "schema": {"type": "string"}}}}},
            tags=["meta"])},
        "/openapi.json": {"get": op(
            "openapi", "This document",
            {"200": {"description": "OpenAPI 3.0 JSON",
                     "content": {"application/json": {
                         "schema": {"type": "object"}}}}},
            tags=["meta"])},
    }

    # every mutating operation gets the exactly-once surface: the
    # Idempotency-Key header, the 429 shed response, and (for mutations of
    # a named, versioned resource) the If-Match precondition + 412
    # every operation accepts a W3C traceparent (obs/trace.py ingress) —
    # one shared components/parameters definition, $ref'd per op, so the
    # 12-line header description isn't duplicated ~20 times in the spec
    for path_item in paths.values():
        for o in path_item.values():
            o.setdefault("parameters", []).append(
                {"$ref": "#/components/parameters/traceparent"})
    for path_item in paths.values():
        for method, o in path_item.items():
            if method not in ("post", "patch", "delete"):
                continue
            if o["operationId"] in FLEET_OPS:
                # raw coordination routes: no gate, no idempotency cache
                continue
            if o["operationId"] in DATA_PLANE_OPS:
                # the gateway's own shed/deadline responses, not the
                # mutation gate's
                o["responses"]["429"] = {
                    "description": "Gateway admission queue full — shed "
                                   "before waiting; retry after "
                                   "Retry-After."}
                o["responses"]["504"] = {
                    "description": "Per-request deadline passed before a "
                                   "replica slot freed (envelope code "
                                   "504); the autoscaler is adding "
                                   "capacity — retry."}
                continue
            o.setdefault("parameters", []).append(dict(IDEM_PARAM))
            o["responses"]["429"] = dict(RESP_429)
            o["responses"]["409"] = dict(RESP_409)
            if o["operationId"] in IF_MATCH_OPS:
                o["parameters"].append(dict(IF_MATCH_PARAM))
                o["responses"]["412"] = dict(RESP_412)

    return {
        "openapi": "3.0.3",
        "info": {
            "title": "tpu-docker-api",
            "version": "0.15.0",
            "description":
                "TPU-native container-orchestration REST API. Same "
                "surface as gpu-docker-api (reference "
                "api/gpu-docker-api-en.openapi.json) with the NVIDIA "
                "substrate replaced by an ICI-topology-aware TPU chip "
                "allocator. Every response is HTTP 200 with an envelope "
                "{code, msg, data} — with these exceptions (chosen so "
                "load balancers and generic clients react without "
                "parsing the envelope): 503 + Retry-After when the "
                "substrate circuit breaker is open (reads keep serving "
                "from the state store in degraded read-only mode), 412 "
                "when an If-Match version precondition fails, 429 + "
                "Retry-After when the mutation admission gate sheds "
                "under overload, and 409 when a duplicate "
                "Idempotency-Key races its original. Mutations are "
                "exactly-once under retry when stamped with an "
                "Idempotency-Key header (see that parameter). "
                "Authentication: optional static bearer token (APIKEY "
                "env) via the Authorization header; 403 envelope when "
                "it mismatches. Generated by scripts/gen_openapi.py — "
                "do not edit by hand.",
        },
        "servers": [{"url": "http://localhost:2378"}],
        "tags": [{"name": "replicaSet"}, {"name": "volume"},
                 {"name": "resource"}, {"name": "gateway"},
                 {"name": "fleet",
                  "description": "Federated control plane: TTL leases, "
                                 "hash-ring resource ownership, "
                                 "takeover (docs/federation.md)"},
                 {"name": "meta"}],
        "security": [{"bearer": []}],
        "paths": paths,
        "components": {
            "securitySchemes": {
                "bearer": {"type": "http", "scheme": "bearer",
                           "description": "Static APIKEY; no-op when the "
                                          "server runs without one"}},
            "parameters": {"traceparent": dict(TRACEPARENT_PARAM)},
            "schemas": schemas,
        },
    }


def main() -> None:
    spec = build_spec()
    # optional output override keeps CHECKS side-effect free (the
    # regeneration test writes to a temp path and diffs)
    out = (sys.argv[1] if len(sys.argv) > 1
           else os.path.join(REPO, "api", "openapi.json"))
    with open(out, "w", encoding="utf-8") as f:
        json.dump(spec, f, indent=1, sort_keys=False)
        f.write("\n")
    n_paths = len(spec["paths"])
    n_ops = sum(len(v) for v in spec["paths"].values())
    n_schemas = len(spec["components"]["schemas"])
    print(f"wrote {out}: {n_paths} paths, {n_ops} operations, "
          f"{n_schemas} schemas")


if __name__ == "__main__":
    main()
