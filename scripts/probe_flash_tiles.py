"""Chip probe: asymmetric flash tiles.

_auto_block (round 2) picked SQUARE tiles (256/512). But the per-block
VPU epilogue splits into terms with different tile scaling: the exp of
every score is invariant (O(S^2) transcendentals no blocking removes),
while the acc/l RESCALE work is O(S^2 * d / blk_k) — it shrinks as kv
blocks grow, independent of blk_q. Square tiles never probed that axis:
this sweeps (blk_q, blk_k) over the public flash_attention overrides,
fwd (inference path) and fwd+bwd (training path), S=2048/4096, causal.
Chain discipline: N calls per timing with the output feeding the next
query (nothing CSE'd/overlapped), clock stopped on a host fetch.

Usage: python scripts/probe_flash_tiles.py
"""

import json
import sys
import time

sys.path.insert(0, ".")

REPS = 3


def main():
    import jax
    import jax.numpy as jnp

    from gpu_docker_api_tpu.ops.attention import flash_attention

    b, h, d = 4, 8, 128
    key = jax.random.key(0)

    for s, chain in ((1024, 64), (2048, 32), (4096, 16)):
        q = jax.random.normal(key, (b, s, h, d), jnp.bfloat16)
        k = jax.random.normal(key, (b, s, h, d), jnp.bfloat16)
        v = jax.random.normal(key, (b, s, h, d), jnp.bfloat16)
        flops = 4 * b * h * s * s * d / 2          # causal fwd

        tiles = [(128, 128), (256, 256), (512, 512),
                 (256, 512), (256, 1024), (512, 1024),
                 (128, 1024), (512, 2048), (256, 2048)]
        for bq, bk in tiles:
            if bq > s or bk > s:
                continue

            @jax.jit
            def fwd_chain(q0):
                def body(c, _):
                    o = flash_attention(c, k, v, causal=True,
                                        blk_q=bq, blk_k=bk)
                    return o, None
                c, _ = jax.lax.scan(body, q0, None, length=chain)
                return jnp.sum(c.astype(jnp.float32))

            @jax.jit
            def bwd_chain(q0):
                def body(c, _):
                    g = jax.grad(lambda qq: jnp.sum(flash_attention(
                        qq, k, v, causal=True, blk_q=bq,
                        blk_k=bk).astype(jnp.float32)))(c)
                    return g.astype(jnp.bfloat16), None
                c, _ = jax.lax.scan(body, q0, None, length=chain)
                return jnp.sum(c.astype(jnp.float32))

            row = {"s": s, "bq": bq, "bk": bk}
            try:
                float(fwd_chain(q))
                best = float("inf")
                for _ in range(REPS):
                    t0 = time.perf_counter()
                    float(fwd_chain(q))
                    best = min(best, time.perf_counter() - t0)
                row["fwd_ms"] = round(best / chain * 1e3, 3)
                row["fwd_tflops"] = round(flops / (best / chain) / 1e12, 1)
            except Exception as e:
                row["fwd_err"] = str(e)[:120]
            try:
                float(bwd_chain(q))
                best = float("inf")
                for _ in range(REPS):
                    t0 = time.perf_counter()
                    float(bwd_chain(q))
                    best = min(best, time.perf_counter() - t0)
                row["fwdbwd_ms"] = round(best / chain * 1e3, 3)
            except Exception as e:
                row["bwd_err"] = str(e)[:120]
            print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
