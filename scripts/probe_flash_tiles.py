"""Chip probe: asymmetric flash tiles.

_auto_block (round 2) picked SQUARE tiles (256/512). But the per-block
VPU epilogue splits into terms with different tile scaling: the exp of
every score is invariant (O(S^2) transcendentals no blocking removes),
while the acc/l RESCALE work is O(S^2 * d / blk_k) — it shrinks as kv
blocks grow, independent of blk_q. Square tiles never probed that axis.

TWO phases, because the pallas arm's ABSOLUTE rate is epoch-bimodal
through the axon tunnel (22.7 vs 58.9 TF/s for the identical
kernel+shape 40 min apart, XLA arm steady — BASELINE.md flash row):

1. sweep — each tile timed on its own chained scan (output feeds the
   next query; one host fetch stops the clock). Orients the search,
   but rows from different minutes are not comparable across epochs.
2. interleaved A/B — the ADJUDICATOR: candidate and baseline tiles
   alternate A B A B within one process, best-of-5 per arm, ratio
   reported. This is the phase the _auto_block/BASELINE.md numbers
   come from (1.38x/1.68x/1.25x fwd at S=1024/2048/4096 for
   (512,1024) over the old auto; 1.06-1.13x grad; s1024 grad wash).

Usage: python scripts/probe_flash_tiles.py
"""

import json
import sys
import time

sys.path.insert(0, ".")

REPS = 3


def main():
    import jax
    import jax.numpy as jnp

    from gpu_docker_api_tpu.ops.attention import flash_attention

    b, h, d = 4, 8, 128
    key = jax.random.key(0)

    for s, chain in ((1024, 64), (2048, 32), (4096, 16)):
        q = jax.random.normal(key, (b, s, h, d), jnp.bfloat16)
        k = jax.random.normal(key, (b, s, h, d), jnp.bfloat16)
        v = jax.random.normal(key, (b, s, h, d), jnp.bfloat16)
        flops = 4 * b * h * s * s * d / 2          # causal fwd

        tiles = [(128, 128), (256, 256), (512, 512),
                 (256, 512), (256, 1024), (512, 1024),
                 (128, 1024), (512, 2048), (256, 2048)]
        for bq, bk in tiles:
            if bq > s or bk > s:
                continue

            @jax.jit
            def fwd_chain(q0):
                def body(c, _):
                    o = flash_attention(c, k, v, causal=True,
                                        blk_q=bq, blk_k=bk)
                    return o, None
                c, _ = jax.lax.scan(body, q0, None, length=chain)
                return jnp.sum(c.astype(jnp.float32))

            @jax.jit
            def bwd_chain(q0):
                def body(c, _):
                    g = jax.grad(lambda qq: jnp.sum(flash_attention(
                        qq, k, v, causal=True, blk_q=bq,
                        blk_k=bk).astype(jnp.float32)))(c)
                    return g.astype(jnp.bfloat16), None
                c, _ = jax.lax.scan(body, q0, None, length=chain)
                return jnp.sum(c.astype(jnp.float32))

            row = {"s": s, "bq": bq, "bk": bk}
            try:
                float(fwd_chain(q))
                best = float("inf")
                for _ in range(REPS):
                    t0 = time.perf_counter()
                    float(fwd_chain(q))
                    best = min(best, time.perf_counter() - t0)
                row["fwd_ms"] = round(best / chain * 1e3, 3)
                row["fwd_tflops"] = round(flops / (best / chain) / 1e12, 1)
            except Exception as e:
                row["fwd_err"] = str(e)[:120]
            try:
                float(bwd_chain(q))
                best = float("inf")
                for _ in range(REPS):
                    t0 = time.perf_counter()
                    float(bwd_chain(q))
                    best = min(best, time.perf_counter() - t0)
                row["fwdbwd_ms"] = round(best / chain * 1e3, 3)
            except Exception as e:
                row["bwd_err"] = str(e)[:120]
            print(json.dumps(row), flush=True)

    # ---- phase 2: interleaved A/B (the adjudicator) -------------------
    def make(bq, bk, chain, grad, k, v):
        @jax.jit
        def run(q0):
            def body(c, _):
                if grad:
                    g = jax.grad(lambda qq: jnp.sum(flash_attention(
                        qq, k, v, causal=True, blk_q=bq,
                        blk_k=bk).astype(jnp.float32)))(c)
                    return g.astype(jnp.bfloat16), None
                return flash_attention(c, k, v, causal=True,
                                       blk_q=bq, blk_k=bk), None
            c, _ = jax.lax.scan(body, q0, None, length=chain)
            return jnp.sum(c.astype(jnp.float32))
        return run

    # bases are round 2's auto tiles per path; candidate is the tall-kv
    # (512,1024) that _auto_block now defaults to
    cases = [
        (1024, 256, False, (256, 256)), (1024, 256, True, (512, 512)),
        (2048, 128, False, (256, 256)), (2048, 128, True, (512, 512)),
        (4096, 64, False, (512, 512)), (4096, 64, True, (512, 512)),
    ]
    for s, chain, grad, (base_q, base_k) in cases:
        q = jax.random.normal(key, (b, s, h, d), jnp.bfloat16)
        k = jax.random.normal(key, (b, s, h, d), jnp.bfloat16)
        v = jax.random.normal(key, (b, s, h, d), jnp.bfloat16)
        bq, bk = 512, min(1024, s)
        base = make(base_q, base_k, chain, grad, k, v)
        cand = make(bq, bk, chain, grad, k, v)
        float(base(q))
        float(cand(q))              # compiles outside the timing
        ta, tb = [], []
        for _ in range(5):
            t0 = time.perf_counter()
            float(base(q))
            ta.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            float(cand(q))
            tb.append(time.perf_counter() - t0)
        print(json.dumps({
            "ab": True, "s": s, "grad": grad,
            "base": [base_q, base_k], "cand": [bq, bk],
            "base_ms": round(min(ta) / chain * 1e3, 3),
            "cand_ms": round(min(tb) / chain * 1e3, 3),
            "cand_over_base": round(min(ta) / min(tb), 3),
        }), flush=True)


if __name__ == "__main__":
    main()
