"""Chip probe 2: moe_1b time breakdown (VERDICT r4 next #5's "where does
the time go") by ablation:

- fwd:    forward-only loss (no backward) — fwd/bwd split
- nrm:    remat off (backward without recompute) — remat tax
- dense:  a dense twin with the SAME active FLOPs per token
          (d_ff = top_k * expert d_ff) under identical accounting —
          everything above its time is the MoE machinery tax
          (routing, gathers, capacity padding, per-expert batching)

All arms use capacity_factor 1.25 (the quality default) unless given.
Usage: python scripts/probe_moe2.py
"""

import dataclasses
import json
import sys
import time

sys.path.insert(0, ".")


def main():
    import jax
    import jax.numpy as jnp

    import bench
    from gpu_docker_api_tpu.models.llama import LlamaConfig
    from gpu_docker_api_tpu.models.moe import MoEConfig
    from gpu_docker_api_tpu.models.moe import init_params as moe_init
    from gpu_docker_api_tpu.models.moe import moe_forward
    from gpu_docker_api_tpu.train import TrainConfig

    out = {}
    mcfg = MoEConfig.moe_1b()

    # dense twin: same layers/d_model/heads, d_ff = top_k * 2560 = 5120
    dcfg = LlamaConfig(
        vocab_size=mcfg.vocab_size, d_model=mcfg.d_model,
        n_layers=mcfg.n_layers, n_heads=mcfg.n_heads,
        n_kv_heads=mcfg.n_kv_heads, d_ff=mcfg.top_k * mcfg.d_ff,
        max_seq_len=mcfg.max_seq_len)
    out["dense_twin"] = bench._mfu_one("dense_twin_d1024_ff5120", dcfg,
                                       batch=8, seq=2048, K=4,
                                       tc=TrainConfig(accum_steps=4))
    print(json.dumps({"dense_twin": out["dense_twin"]}), flush=True)

    # remat off (microbatch activations must fit without recompute)
    try:
        out["no_remat"] = bench._mfu_one(
            "moe_1b_noremat", mcfg, batch=8, seq=2048, K=4,
            tc=TrainConfig(accum_steps=4, remat=False))
    except Exception as e:  # noqa: BLE001 — likely OOM
        out["no_remat"] = {"error": f"{type(e).__name__}: {e}"[:300]}
    print(json.dumps({"no_remat": out["no_remat"]}), flush=True)

    # forward-only: mean CE + router loss, jitted, K timed reps (same
    # tunnel discipline: one scan, fetch at the end)
    params = moe_init(mcfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (8, 2048), 0,
                                mcfg.vocab_size, jnp.int32)

    def fwd_loss(p, toks):
        logits, raux = moe_forward(p, toks[:, :-1], mcfg)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        ce = -jnp.mean(jnp.take_along_axis(
            logp, toks[:, 1:, None], axis=-1))
        return ce + raux

    @jax.jit
    def k_fwd(p, toks):
        def body(c, _):
            return c + fwd_loss(p, toks), None
        s, _ = jax.lax.scan(body, jnp.zeros(()), None, length=4)
        return s

    float(k_fwd(params, tokens))          # compile
    t0 = time.perf_counter()
    float(k_fwd(params, tokens))
    fwd_ms = (time.perf_counter() - t0) / 4 * 1e3
    out["fwd_only_ms"] = round(fwd_ms, 2)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
