#!/usr/bin/env bash
# Wipe all control-plane state for a clean rerun (reference parity:
# scripts/reset.sh, which deleted the etcd prefix + ./merges). The store is
# embedded here, so reset = remove the state dir (WAL, events, backend
# rootfs/volumes/images/logs).
set -euo pipefail

STATE_DIR="${1:-./tpu-docker-api-state}"

if pgrep -f "gpu_docker_api_tpu.cli" > /dev/null 2>&1; then
    echo "refusing to reset while a tpu-docker-api daemon is running" >&2
    exit 1
fi

rm -rf "$STATE_DIR"
echo "reset: removed $STATE_DIR"
