"""pytest plugin (loaded via -p before capture starts): force the suite onto
the 8-device virtual CPU platform. The image's TPU plugin binds the backend
at interpreter startup, so the env must be set before python launches —
when it isn't, re-exec pytest with the right environment."""
import os
import subprocess
import sys

_WANT = {
    "JAX_PLATFORMS": "cpu",
    "JAX_PLATFORM_NAME": "cpu",
    "JAX_ENABLE_X64": "0",
    # XLA's C++ W-level logging must be visible: the SPMD-reshard regression
    # test asserts on a stderr warning, which TF_CPP_MIN_LOG_LEVEL>=2 would
    # silence into a vacuous pass. The level is read at process init, so it
    # has to be set here (pre-exec), not in the test.
    "TF_CPP_MIN_LOG_LEVEL": "0",
}

def _ensure_env() -> None:
    need = any(os.environ.get(k) != v for k, v in _WANT.items())
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        need = True
        flags = (flags + " --xla_force_host_platform_device_count=8").strip()
    # The TPU-tunnel sitecustomize keys off this var; with it set, every
    # backend init dials the tunnel (jax_platforms is forced to "axon,cpu"),
    # and a wedged tunnel hangs the whole CPU suite. Drop it.
    if "PALLAS_AXON_POOL_IPS" in os.environ:
        need = True
    if need and os.environ.get("_TDAPI_TEST_REEXEC") != "1":
        env = dict(os.environ)
        env.update(_WANT)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["XLA_FLAGS"] = flags
        env["_TDAPI_TEST_REEXEC"] = "1"  # one retry only — never loop
        ret = subprocess.run(
            [sys.executable, "-m", "pytest", *sys.argv[1:]], env=env).returncode
        os._exit(ret)

_ensure_env()
