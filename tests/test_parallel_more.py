"""MoE expert parallelism, pipeline parallelism, and Ulysses sequence
parallelism on the 8-device virtual CPU mesh — the pp/ep/sp axes of the
dryrun contract (the reference has none of these; SURVEY §2 checklist +
§5.7/5.8 obligations)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gpu_docker_api_tpu.models import FAMILIES, family_for
from gpu_docker_api_tpu.models.llama import (
    LlamaConfig, init_params as llama_init, llama_forward,
)
from gpu_docker_api_tpu.models.moe import (
    MoEConfig, capacity_positions, init_params as moe_init, moe_block,
    moe_forward,
)
from gpu_docker_api_tpu.ops.attention import reference_attention
from gpu_docker_api_tpu.parallel.mesh import MeshPlan, make_mesh
from gpu_docker_api_tpu.parallel.pipeline import pipeline_forward, pipeline_trunk
from gpu_docker_api_tpu.parallel.ulysses import ulysses_attention
from gpu_docker_api_tpu.train import Trainer, TrainConfig, param_specs

# slow tier: long-compile / multi-process e2e — quick CI runs
# -m 'not slow' (<3 min); the full suite stays the default
pytestmark = pytest.mark.slow


# ---- model family registry -------------------------------------------------

def test_family_registry_dispatch():
    assert family_for(LlamaConfig.tiny()).name == "llama"
    assert family_for(MoEConfig.tiny()).name == "moe"
    assert FAMILIES["moe"].returns_extra_loss
    with pytest.raises(TypeError):
        family_for(object())


# ---- MoE -------------------------------------------------------------------

@pytest.fixture(scope="module")
def moe_tiny():
    cfg = MoEConfig.tiny()
    return cfg, moe_init(cfg, jax.random.key(0))


def test_moe_forward_shapes_and_finite(moe_tiny):
    cfg, params = moe_tiny
    toks = jax.random.randint(jax.random.key(1), (2, 32), 0,
                              cfg.vocab_size, dtype=jnp.int32)
    logits, router_loss = moe_forward(params, toks, cfg)
    assert logits.shape == (2, 32, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert bool(jnp.all(jnp.isfinite(logits)))
    assert float(router_loss) > 0.0


def test_moe_block_generous_capacity_routes_all(moe_tiny):
    """With capacity_factor high enough that nothing drops, the block output
    equals the explicit per-token top-k mixture computed densely."""
    cfg, params = moe_tiny
    cfg = dataclasses.replace(cfg, capacity_factor=8.0)
    layer = jax.tree.map(lambda a: a[0], params["layers"])
    x = jax.random.normal(jax.random.key(2), (2, 16, cfg.d_model), jnp.float32)

    out, aux, z = moe_block(x, layer, cfg)

    from gpu_docker_api_tpu.models.llama import rms_norm
    h = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    logits = h.astype(jnp.float32) @ layer["router"]
    probs = jax.nn.softmax(logits, axis=-1)
    gv, gi = jax.lax.top_k(probs, cfg.top_k)
    gv = gv / jnp.sum(gv, axis=-1, keepdims=True)
    dense = jnp.zeros_like(x)
    for e in range(cfg.n_experts):
        ge = jax.nn.silu(h @ layer["we1"][e]) * (h @ layer["we3"][e])
        ye = ge @ layer["we2"][e]
        w = jnp.sum(jnp.where(gi == e, gv, 0.0), axis=-1)
        dense = dense + w[..., None] * ye
    np.testing.assert_allclose(np.asarray(out), np.asarray(x + dense),
                               atol=1e-4, rtol=1e-4)
    assert float(aux) > 0 and float(z) >= 0


def test_moe_tiny_capacity_drops_tokens_residual_passthrough(moe_tiny):
    """With capacity clamped to the top_k minimum, most tokens overflow:
    dropped tokens must pass through as the EXACT residual identity (their
    combine weight is zero), and at most n_experts*cap token rows may be
    touched at all."""
    cfg, params = moe_tiny
    cfg = dataclasses.replace(cfg, capacity_factor=1e-9)  # cap clamps to top_k
    cap = cfg.capacity(8)
    layer = jax.tree.map(lambda a: a[0], params["layers"])
    x = jax.random.normal(jax.random.key(3), (1, 8, cfg.d_model), jnp.float32)
    out, _, _ = moe_block(x, layer, cfg)
    assert bool(jnp.all(jnp.isfinite(out)))
    changed = jnp.any(out != x, axis=-1)  # [1, 8] rows an expert touched
    n_changed = int(jnp.sum(changed))
    # every slot that exists can host one token; nothing else may move
    assert n_changed <= cfg.n_experts * cap
    # and the dropped rows are bit-exact passthrough (already implied by
    # `changed` using exact inequality — assert explicitly for clarity)
    mask = ~np.asarray(changed)[0]          # [8] dropped-token rows
    np.testing.assert_array_equal(np.asarray(out)[0][mask],
                                  np.asarray(x)[0][mask])


def test_moe_capacity_priority_is_k_major():
    """A token's top-1 pick must win a capacity slot over another token's
    k=1 spillover, regardless of token order: token 0 picks expert A as its
    SECOND choice, token 1 picks A FIRST — with cap=1, token 1 keeps A."""
    # experts: A=0, B=1, C=2.  gate_idx[t] = (k0 pick, k1 pick)
    gate_idx = jnp.array([[1, 0],    # token 0: B first, A spillover
                          [0, 2]])   # token 1: A FIRST, C spillover
    onehot = jax.nn.one_hot(gate_idx, 3, dtype=jnp.int32)
    pos = capacity_positions(onehot)
    # token 1's k=0 pick of A outranks token 0's k=1 pick of A
    assert pos[1, 0] == 0 and pos[0, 1] == 1
    assert pos[0, 0] == 0 and pos[1, 1] == 0


def test_moe_ep_sharded_training_loss_decreases(moe_tiny):
    cfg, _ = moe_tiny
    plan = MeshPlan(fsdp=1, ep=4, tp=2)
    tr = Trainer.create(cfg, plan, tc=TrainConfig(learning_rate=1e-2))
    state = tr.init(jax.random.key(0))
    # expert weights actually sharded over ep
    we1_sh = state["params"]["layers"]["we1"].sharding
    assert "ep" in we1_sh.spec[1]  # leading axis is n_layers, then experts
    toks = jax.random.randint(jax.random.key(4), (8, 32), 0,
                              cfg.vocab_size, dtype=jnp.int32)
    toks = tr.shard_batch(toks)
    losses = []
    for _ in range(4):
        state, m = tr.step(state, toks)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    assert all(np.isfinite(losses))


# ---- pipeline --------------------------------------------------------------

@pytest.fixture(scope="module")
def llama_tiny():
    cfg = LlamaConfig.tiny()
    return cfg, llama_init(cfg, jax.random.key(0))


def test_pipeline_forward_matches_sequential(llama_tiny):
    cfg, params = llama_tiny
    toks = jax.random.randint(jax.random.key(5), (4, 32), 0,
                              cfg.vocab_size, dtype=jnp.int32)
    ref = llama_forward(params, toks, cfg)
    mesh = make_mesh(MeshPlan(pp=2, tp=2, fsdp=2))
    out = jax.jit(lambda p, t: pipeline_forward(
        p, t, cfg, mesh, n_microbatches=2))(params, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_pipeline_microbatch_validation(llama_tiny):
    cfg, params = llama_tiny
    mesh = make_mesh(MeshPlan(pp=2, fsdp=4))
    toks = jax.random.randint(jax.random.key(6), (3, 16), 0,
                              cfg.vocab_size, dtype=jnp.int32)
    with pytest.raises(ValueError, match="divisible"):
        pipeline_forward(params, toks, cfg, mesh, n_microbatches=2)


def test_pipeline_trunk_pp1_is_plain_scan(llama_tiny):
    cfg, params = llama_tiny
    mesh = make_mesh(MeshPlan(fsdp=8))
    x = jax.random.normal(jax.random.key(7), (2, 16, cfg.d_model),
                          jnp.float32)
    # identity layer: the point is the pp=1 fast path (plain scan, no ring)
    out = pipeline_trunk(params["layers"], x,
                         lambda h, layer: h, mesh, n_microbatches=2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_pipelined_train_step_loss_decreases(llama_tiny):
    cfg, _ = llama_tiny
    plan = MeshPlan(pp=2, tp=2, fsdp=2)
    tr = Trainer.create(cfg, plan,
                        tc=TrainConfig(learning_rate=1e-2, n_microbatches=2))
    state = tr.init(jax.random.key(0))
    # layer stacks sharded over pp on the leading (n_layers) axis
    specs = param_specs(cfg, pipelined=True)
    assert specs["layers"]["wq"][0] == "pp"
    assert state["params"]["layers"]["wq"].sharding.spec[0] == "pp"
    toks = jax.random.randint(jax.random.key(8), (8, 32), 0,
                              cfg.vocab_size, dtype=jnp.int32)
    toks = tr.shard_batch(toks)
    losses = []
    for _ in range(4):
        state, m = tr.step(state, toks)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]


# ---- ulysses ---------------------------------------------------------------

def _qkv(b=2, s=64, h=8, hkv=4, d=16):
    return (jax.random.normal(jax.random.key(11), (b, s, h, d), jnp.float32),
            jax.random.normal(jax.random.key(12), (b, s, hkv, d), jnp.float32),
            jax.random.normal(jax.random.key(13), (b, s, hkv, d), jnp.float32))


def test_ulysses_matches_reference_sp_only():
    q, k, v = _qkv()
    ref = reference_attention(q, k, v, causal=True)
    mesh = make_mesh(MeshPlan(sp=4, fsdp=2))
    out = jax.jit(lambda q, k, v: ulysses_attention(q, k, v, mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


def test_ulysses_with_tp_sharded_heads():
    q, k, v = _qkv()
    ref = reference_attention(q, k, v, causal=True)
    mesh = make_mesh(MeshPlan(sp=2, tp=2, fsdp=2))
    out = jax.jit(lambda q, k, v: ulysses_attention(q, k, v, mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


def test_ulysses_gqa_kv_replication():
    """Hkv < sp: KV heads replicate up to the group size before the a2a."""
    q, k, v = _qkv(hkv=2)
    ref = reference_attention(q, k, v, causal=True)
    mesh = make_mesh(MeshPlan(sp=4, fsdp=2))
    out = jax.jit(lambda q, k, v: ulysses_attention(q, k, v, mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


def test_ulysses_rejects_indivisible_heads():
    q, k, v = _qkv(h=6, hkv=6)
    mesh = make_mesh(MeshPlan(sp=4, fsdp=2))
    with pytest.raises(ValueError, match="divide"):
        ulysses_attention(q, k, v, mesh)


def test_llama_forward_ulysses_matches_dense(llama_tiny):
    cfg, params = llama_tiny
    ucfg = dataclasses.replace(cfg, sp_attn="ulysses")
    toks = jax.random.randint(jax.random.key(14), (4, 32), 0,
                              cfg.vocab_size, dtype=jnp.int32)
    ref = llama_forward(params, toks, cfg)
    mesh = make_mesh(MeshPlan(sp=2, tp=2, fsdp=2))
    with mesh:
        out = llama_forward(params, toks, ucfg, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_moe_step_compiles_without_involuntary_reshards(capfd):
    """VERDICT r1 #3: the ep-sharded MoE train step must compile with zero
    '[SPMD] Involuntary full rematerialization' warnings — each one is a
    full activation reshard every step on a real mesh. Fixed by the
    fully-determined qkv/embed activation pins (models/llama.py) and the
    vocab-parallel embed spec (parallel/mesh.py)."""
    import os
    import tempfile

    import jax
    import jax.numpy as jnp

    from gpu_docker_api_tpu.models.moe import MoEConfig
    from gpu_docker_api_tpu.parallel.mesh import MeshPlan
    from gpu_docker_api_tpu.train import TrainConfig, Trainer

    config = MoEConfig.tiny()
    trainer = Trainer.create(config, MeshPlan(fsdp=2, ep=2, tp=2),
                             tc=TrainConfig(remat=True))
    state = trainer.init(jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, 64), 0,
                                config.vocab_size, dtype=jnp.int32)
    tokens = trainer.shard_batch(tokens)

    # Fail-closed preconditions: the warning must be loggable (W-level C++
    # logs enabled — pytest_force_cpu pins TF_CPP_MIN_LOG_LEVEL=0 pre-exec)
    # and a real compile must happen (a compilation-cache hit skips the SPMD
    # partitioner entirely and would pass vacuously).
    assert os.environ.get("TF_CPP_MIN_LOG_LEVEL", "0") in ("", "0", "1")
    # XLA's SPMD partitioner logs from C++ directly to fd 2; capture it
    # across the compile with a dup2 swap (pytest's capfd alone misses
    # output written before its read, so read the file ourselves).
    with tempfile.TemporaryFile() as tmp:
        saved = os.dup(2)
        cache_was = jax.config.jax_enable_compilation_cache
        try:
            os.dup2(tmp.fileno(), 2)
            jax.config.update("jax_enable_compilation_cache", False)
            with trainer.mesh:
                trainer._step_fn.lower(state, tokens).compile()
        finally:
            jax.config.update("jax_enable_compilation_cache", cache_was)
            os.dup2(saved, 2)
            os.close(saved)
        tmp.seek(0)
        stderr = tmp.read().decode(errors="replace")
    assert "Involuntary full rematerialization" not in stderr, stderr[-2000:]


def test_pipeline_loss_matches_sequential(llama_tiny):
    """pipeline_loss (lm_head + CE OUTSIDE the pp region, on the pp-sharded
    trunk output — see pipeline.py design note) must equal the sequential
    loss exactly — same math, different schedule."""
    from gpu_docker_api_tpu.parallel.pipeline import pipeline_loss
    from gpu_docker_api_tpu.train import loss_fn
    cfg, params = llama_tiny
    mesh = make_mesh(MeshPlan(fsdp=2, pp=2, tp=2))
    toks = jax.random.randint(jax.random.key(3), (8, 32), 0, cfg.vocab_size,
                              dtype=jnp.int32)
    ref = loss_fn(params, toks, cfg)                 # sequential, no mesh
    with mesh:
        out = jax.jit(lambda p, t: pipeline_loss(
            p, t, cfg, mesh, n_microbatches=4))(params, toks)
    np.testing.assert_allclose(float(out), float(ref), atol=1e-5, rtol=1e-5)


def test_pipeline_loss_no_output_broadcast(llama_tiny):
    """VERDICT r1 weak #4: training must not psum the [M, b, S, D] output
    buffer around the pp ring. Compiled HLO of the pipelined loss may only
    contain small cross-replica collectives (the scalar loss psum, grad
    reductions of [b,S]-sized stats) — never an all-reduce the size of the
    full activation buffer."""
    import re
    from gpu_docker_api_tpu.parallel.pipeline import pipeline_loss
    cfg, params = llama_tiny
    mesh = make_mesh(MeshPlan(pp=2, fsdp=2, tp=2))
    b, s, d = 8, 32, cfg.d_model
    toks = jax.random.randint(jax.random.key(3), (b, s), 0, cfg.vocab_size,
                              dtype=jnp.int32)
    with mesh:
        compiled = (jax.jit(lambda p, t: pipeline_loss(
            p, t, cfg, mesh, n_microbatches=4))
            .lower(params, toks).compile())
    hlo = compiled.as_text()
    buffer_elems = 4 * (b // 4) * s * d              # [M, b/M, S, D]
    for line in hlo.splitlines():
        if " all-reduce(" not in line and " all-reduce-start(" not in line:
            continue
        # result type is everything between '=' and 'all-reduce'; it may be
        # a TUPLE (the all-reduce combiner batches several operands) — check
        # every element shape, not just the first
        restype = line.split("=", 1)[1].split("all-reduce", 1)[0]
        for m in re.finditer(r"[a-z0-9]+\[([0-9,]*)\]", restype):
            dims = [int(x) for x in m.group(1).split(",") if x]
            elems = 1
            for x in dims:
                elems *= x
            assert elems < buffer_elems, (
                f"full-buffer all-reduce survived: {line.strip()}")


def test_pipeline_layers_divisibility_error(llama_tiny):
    """ADVICE r1: n_layers % pp must fail loudly, not as an opaque sharding
    error (tiny has 2 layers; pp=4 over 8 devices cannot split them)."""
    from gpu_docker_api_tpu.parallel.pipeline import pipeline_forward
    cfg, params = llama_tiny
    mesh = make_mesh(MeshPlan(pp=4, tp=2))
    toks = jax.random.randint(jax.random.key(3), (8, 32), 0, cfg.vocab_size,
                              dtype=jnp.int32)
    with pytest.raises(ValueError, match="not divisible by pp"):
        with mesh:
            pipeline_forward(params, toks, cfg, mesh, n_microbatches=4)


# -------------------------------------------------- interleaved schedule
# VERDICT r1 weak #4 (full ask): the Megatron-style virtual-stage schedule
# must match sequential numerics exactly and waste measurably fewer ticks
# than GPipe at the same (pp, M).

def _tiny4():
    cfg = dataclasses.replace(LlamaConfig.tiny(), n_layers=4)
    return cfg, llama_init(cfg, jax.random.key(0))


def test_interleaved_loss_matches_sequential():
    from gpu_docker_api_tpu.parallel.pipeline import pipeline_loss
    from gpu_docker_api_tpu.train import loss_fn
    cfg, params = _tiny4()
    mesh = make_mesh(MeshPlan(fsdp=2, pp=2, tp=2))
    toks = jax.random.randint(jax.random.key(3), (8, 32), 0, cfg.vocab_size,
                              dtype=jnp.int32)
    ref = loss_fn(params, toks, cfg)                 # sequential, no mesh
    with mesh:
        out = jax.jit(lambda p, t: pipeline_loss(
            p, t, cfg, mesh, n_microbatches=4, virtual_stages=2))(
                params, toks)
    np.testing.assert_allclose(float(out), float(ref), atol=1e-5, rtol=1e-5)


def test_interleaved_grads_match_sequential():
    from gpu_docker_api_tpu.parallel.pipeline import pipeline_loss
    from gpu_docker_api_tpu.train import loss_fn
    cfg, params = _tiny4()
    mesh = make_mesh(MeshPlan(fsdp=2, pp=2, tp=2))
    toks = jax.random.randint(jax.random.key(7), (4, 32), 0, cfg.vocab_size,
                              dtype=jnp.int32)
    g_ref = jax.grad(lambda p: loss_fn(p, toks, cfg))(params)
    with mesh:
        g = jax.jit(jax.grad(lambda p: pipeline_loss(
            p, toks, cfg, mesh, n_microbatches=2, virtual_stages=2)))(params)
    flat_g = jax.tree.leaves(g)
    flat_r = jax.tree.leaves(g_ref)
    for a, b in zip(flat_g, flat_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)


def test_interleaved_fewer_wasted_ticks():
    """Step-time proxy: ticks x per-tick depth (the schedules share per-tick
    math; only the count and chunk size differ). Interleaving must cut the
    bubble by exactly v."""
    from gpu_docker_api_tpu.parallel.pipeline import schedule_work_units
    pp, m = 2, 8
    useful = m / pp
    gpipe = schedule_work_units(pp, m, v=1)
    inter = schedule_work_units(pp, m, v=2)
    assert inter < gpipe
    # bubble halves: (pp-1)/m -> (pp-1)/(m*v)
    np.testing.assert_allclose(gpipe - useful, (pp - 1) / pp)
    np.testing.assert_allclose(inter - useful, (pp - 1) / (2 * pp))


def test_interleaved_divisibility_errors():
    from gpu_docker_api_tpu.parallel.pipeline import pipeline_loss
    cfg, params = _tiny4()
    mesh = make_mesh(MeshPlan(fsdp=2, pp=2, tp=2))
    toks = jax.random.randint(jax.random.key(3), (6, 32), 0, cfg.vocab_size,
                              dtype=jnp.int32)
    with mesh:
        # m=3 not divisible by pp=2 under interleaving
        with pytest.raises(ValueError, match="groups of pp"):
            pipeline_loss(params, toks, cfg, mesh, n_microbatches=3,
                          virtual_stages=2)
        # n_layers=4 not divisible by pp*v=2*4
        with pytest.raises(ValueError, match="pp\\*virtual_stages"):
            pipeline_loss(params, toks[:, :32], cfg, mesh, n_microbatches=2,
                          virtual_stages=4)


def test_trainer_interleaved_step():
    """Full sharded train step with the interleaved schedule: loss drops."""
    from gpu_docker_api_tpu.train import TrainConfig, Trainer
    cfg, _ = _tiny4()
    tc = TrainConfig(learning_rate=1e-2, n_microbatches=4, virtual_stages=2)
    trainer = Trainer.create(cfg, MeshPlan(fsdp=2, pp=2, tp=2), tc=tc)
    state = trainer.init(jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (8, 32), 0, cfg.vocab_size,
                              dtype=jnp.int32)
    toks = trainer.shard_batch(toks)
    losses = []
    for _ in range(5):
        state, m = trainer.step(state, toks)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]


def test_pipeline_bf16_grads_compile():
    """bf16 models through the pipelined loss must compile and differentiate
    on XLA:CPU — the bf16 cotangent psum of the replicated microbatch input
    used to CHECK-crash AllReducePromotion (caught by the round-2 workload
    CLI drive, never by the f32-only tests)."""
    import dataclasses
    cfg = dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.bfloat16,
                              n_layers=4)
    params = llama_init(cfg, jax.random.key(0))
    mesh = make_mesh(MeshPlan(fsdp=2, pp=2, tp=2))
    toks = jax.random.randint(jax.random.key(3), (8, 32), 0, cfg.vocab_size,
                              dtype=jnp.int32)
    from gpu_docker_api_tpu.parallel.pipeline import pipeline_loss
    for v in (1, 2):
        with mesh:
            g = jax.jit(jax.grad(lambda p: pipeline_loss(
                p, toks, cfg, mesh, n_microbatches=4, virtual_stages=v)))(
                    params)
        assert all(bool(jnp.all(jnp.isfinite(x.astype(jnp.float32))))
                   for x in jax.tree.leaves(g))


# -------------------------------------------------- pipelined MoE

def test_pipelined_moe_logits_match_sequential(moe_tiny):
    """With generous capacity (nothing drops), per-token expert outputs are
    independent of batch makeup, so the pipelined MoE logits must equal the
    sequential forward exactly; the router loss is the mean over microbatch
    statistic pools (documented semantics), so only approximately equal."""
    cfg, params = moe_tiny
    cfg = dataclasses.replace(cfg, capacity_factor=8.0)
    mesh = make_mesh(MeshPlan(pp=2, ep=2, tp=2))
    toks = jax.random.randint(jax.random.key(3), (8, 32), 0, cfg.vocab_size,
                              dtype=jnp.int32)
    ref_logits, ref_rl = moe_forward(params, toks, cfg)
    with mesh:
        logits, rl = jax.jit(lambda p, t: pipeline_forward(
            p, t, cfg, mesh, n_microbatches=4))(params, toks)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               atol=1e-5, rtol=1e-5)
    # per-microbatch routing statistics differ from full-batch ones, but
    # the normalization must be right (a sum over microbatches would be ~4x)
    assert float(rl) == pytest.approx(float(ref_rl), rel=1.0)
    assert float(rl) > 0


def test_pipelined_moe_training_loss_decreases(moe_tiny):
    """Full sharded train step with pp x ep x tp on the MoE family — the
    composition loss_fn refused before round 2."""
    cfg, _ = moe_tiny
    tc = TrainConfig(learning_rate=1e-2, n_microbatches=2)
    tr = Trainer.create(cfg, MeshPlan(pp=2, ep=2, tp=2), tc=tc)
    state = tr.init(jax.random.key(0))
    toks = jax.random.randint(jax.random.key(4), (8, 32), 0,
                              cfg.vocab_size, dtype=jnp.int32)
    toks = tr.shard_batch(toks)
    losses = []
    for _ in range(4):
        state, m = tr.step(state, toks)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    assert all(np.isfinite(losses))


def test_pipelined_moe_interleaved_matches_sequential(moe_tiny):
    """Interleaved schedule (v=2) + MoE: the per-lap aux masking must not
    double-count or drop a chunk-visit — logits exact under generous
    capacity, router loss normalized like the sequential path."""
    cfg, _ = moe_tiny
    cfg = dataclasses.replace(cfg, capacity_factor=8.0, n_layers=4)
    params = moe_init(cfg, jax.random.key(0))
    mesh = make_mesh(MeshPlan(pp=2, ep=2, tp=2))
    toks = jax.random.randint(jax.random.key(5), (8, 32), 0, cfg.vocab_size,
                              dtype=jnp.int32)
    ref_logits, ref_rl = moe_forward(params, toks, cfg)
    with mesh:
        logits, rl = jax.jit(lambda p, t: pipeline_forward(
            p, t, cfg, mesh, n_microbatches=4, virtual_stages=2))(
                params, toks)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               atol=1e-5, rtol=1e-5)
    assert float(rl) == pytest.approx(float(ref_rl), rel=1.0)
    assert float(rl) > 0


# -------------------------------------------------- pp x sp composition

def test_pipeline_with_sequence_parallel_matches_sequential(llama_tiny):
    """pp x sp: the trunk goes manual over both axes — activations flow
    sequence-sharded through the pipeline ring while K/V rotate the sp ring
    inside each stage (ring attention body). Exact vs sequential."""
    cfg, params = llama_tiny
    toks = jax.random.randint(jax.random.key(9), (8, 32), 0,
                              cfg.vocab_size, dtype=jnp.int32)
    ref = llama_forward(params, toks, cfg)
    mesh = make_mesh(MeshPlan(pp=2, sp=2, tp=2))
    with mesh:
        out = jax.jit(lambda p, t: pipeline_forward(
            p, t, cfg, mesh, n_microbatches=4))(params, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_pipeline_sp_interleaved_train_step():
    """pp x sp x tp with the interleaved schedule: full train step, loss
    drops — every axis of the mesh exercised in one program."""
    from gpu_docker_api_tpu.train import TrainConfig, Trainer
    cfg = dataclasses.replace(LlamaConfig.tiny(), n_layers=4)
    tc = TrainConfig(learning_rate=1e-2, n_microbatches=2, virtual_stages=2)
    tr = Trainer.create(cfg, MeshPlan(pp=2, sp=2, tp=2), tc=tc)
    state = tr.init(jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (8, 32), 0, cfg.vocab_size,
                              dtype=jnp.int32)
    toks = tr.shard_batch(toks)
    losses = []
    for _ in range(4):
        state, m = tr.step(state, toks)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    assert all(np.isfinite(losses))


def test_pipelined_moe_with_sp_matches_sequential(moe_tiny):
    """pp x sp for MoE (VERDICT r2 hole #3): the trunk goes manual over
    {pp, sp} with each sp rank routing its own sequence shard's tokens.
    With capacity generous enough that no pool drops (capacity decisions
    are the ONLY pool-size-dependent part of routing), logits are exact
    vs sequential. The router aux sees per-(microbatch, sp-shard) token
    pools — one more pool split with the same documented microbatched-MoE
    semantics — so it is close to, not equal to, the full-batch
    statistic; when capacity binds, drop decisions differ the same way."""
    cfg, params = moe_tiny
    cfg = dataclasses.replace(cfg, capacity_factor=8.0)
    toks = jax.random.randint(jax.random.key(3), (8, 32), 0, cfg.vocab_size,
                              dtype=jnp.int32)
    ref_logits, ref_aux = moe_forward(params, toks, cfg)
    mesh = make_mesh(MeshPlan(pp=2, sp=2, tp=2))
    with mesh:
        logits, aux = jax.jit(lambda p, t: pipeline_forward(
            p, t, cfg, mesh, n_microbatches=2))(params, toks)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               atol=2e-4, rtol=2e-4)
    # aux: same order of magnitude, finite (pool-split statistic)
    assert np.isfinite(float(aux))
    np.testing.assert_allclose(float(aux), float(ref_aux), rtol=0.5)


def test_pipelined_moe_with_sp_trains(moe_tiny):
    """End-to-end train steps on the pp x sp x tp mesh for MoE: loss
    finite and decreasing through the composed trunk."""
    from gpu_docker_api_tpu.train import TrainConfig, Trainer
    cfg, _ = moe_tiny
    tc = TrainConfig(learning_rate=1e-2, n_microbatches=2)
    tr = Trainer.create(cfg, MeshPlan(pp=2, sp=2, tp=2), tc=tc)
    state = tr.init(jax.random.key(0))
    toks = tr.shard_batch(
        jax.random.randint(jax.random.key(1), (8, 32), 0, cfg.vocab_size,
                           dtype=jnp.int32))
    losses = []
    for _ in range(4):
        state, m = tr.step(state, toks)
        losses.append(float(m["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_pipeline_sp_requires_pp(llama_tiny):
    """Misuse fails with an actionable error, not an unbound-axis
    NameError: sp>1 with pp=1 points at the non-pipelined path."""
    cfg, params = llama_tiny
    toks = jax.random.randint(jax.random.key(2), (8, 32), 0,
                              cfg.vocab_size, dtype=jnp.int32)
    with pytest.raises(ValueError, match="non-pipelined"):
        pipeline_forward(params, toks, cfg,
                         make_mesh(MeshPlan(sp=2, tp=2, fsdp=2)),
                         n_microbatches=2)


def test_pipeline_ulysses_matches_sequential(llama_tiny):
    """pp x sp with the Ulysses strategy: all-to-all head scatter runs
    inside the manual {pp, sp} region — exact vs sequential, same as the
    ring path."""
    cfg, params = llama_tiny
    cfg = dataclasses.replace(cfg, sp_attn="ulysses")
    toks = jax.random.randint(jax.random.key(11), (8, 32), 0,
                              cfg.vocab_size, dtype=jnp.int32)
    ref = llama_forward(params, toks, cfg)
    mesh = make_mesh(MeshPlan(pp=2, sp=2, tp=2))
    with mesh:
        out = jax.jit(lambda p, t: pipeline_forward(
            p, t, cfg, mesh, n_microbatches=4))(params, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_moe_with_sequence_parallel_trains(moe_tiny):
    """Non-pipelined MoE composes with sp through auto-SPMD: ring attention
    over the sp axis, dispatch/combine einsums resharded by the compiler."""
    cfg, _ = moe_tiny
    tr = Trainer.create(cfg, MeshPlan(sp=2, ep=2, tp=2),
                        tc=TrainConfig(learning_rate=1e-2))
    state = tr.init(jax.random.key(0))
    toks = tr.shard_batch(jax.random.randint(
        jax.random.key(4), (8, 32), 0, cfg.vocab_size, jnp.int32))
    losses = []
    for _ in range(3):
        state, m = tr.step(state, toks)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] and all(np.isfinite(losses))
