"""Llama model + attention ops correctness (8-device virtual CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gpu_docker_api_tpu.models.llama import (
    LlamaConfig, count_params, init_params, llama_forward, param_kinds,
)
from gpu_docker_api_tpu.ops.attention import flash_attention, reference_attention


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    return cfg, params


def test_forward_shape_and_finite(tiny):
    cfg, params = tiny
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)
    logits = llama_forward(params, tokens, cfg, impl="xla")
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert bool(jnp.isfinite(logits).all())


def test_causality(tiny):
    """Changing a future token must not change past logits."""
    cfg, params = tiny
    t1 = jax.random.randint(jax.random.key(2), (1, 16), 0, cfg.vocab_size)
    t2 = t1.at[0, 10].set((t1[0, 10] + 1) % cfg.vocab_size)
    l1 = llama_forward(params, t1, cfg, impl="xla")
    l2 = llama_forward(params, t2, cfg, impl="xla")
    np.testing.assert_allclose(l1[0, :10], l2[0, :10], atol=1e-5)
    assert not np.allclose(l1[0, 10:], l2[0, 10:])


def test_param_kinds_tree_matches(tiny):
    cfg, params = tiny
    kinds = param_kinds(cfg)
    # same tree structure
    jax.tree.map(lambda p, k: None, params, kinds)
    assert count_params(params) > 0


def test_gqa_reference_matches_full_mha():
    """GQA with repeated KV == MHA on the expanded tensors."""
    key = jax.random.key(0)
    b, s, h, hkv, d = 2, 32, 4, 2, 16
    q = jax.random.normal(key, (b, s, h, d))
    k = jax.random.normal(jax.random.key(1), (b, s, hkv, d))
    v = jax.random.normal(jax.random.key(2), (b, s, hkv, d))
    out = reference_attention(q, k, v, causal=True)
    k_full = jnp.repeat(k, h // hkv, axis=2)
    v_full = jnp.repeat(v, h // hkv, axis=2)
    out_full = reference_attention(q, k_full, v_full, causal=True)
    np.testing.assert_allclose(out, out_full, atol=1e-6)


def test_flash_matches_reference_cpu_interpret():
    """The pallas kernel's numerics vs the XLA oracle (interpret mode runs
    the kernel on CPU). GQA shape: 4 heads over 2 KV heads."""
    b, s, h, hkv, d = 1, 256, 4, 2, 128
    q = jax.random.normal(jax.random.key(0), (b, s, h, d), jnp.float32)
    k = jax.random.normal(jax.random.key(1), (b, s, hkv, d), jnp.float32)
    v = jax.random.normal(jax.random.key(2), (b, s, hkv, d), jnp.float32)
    ref = reference_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-3, rtol=2e-3)


def test_flash_noncausal_matches_reference():
    b, s, h, d = 1, 128, 2, 128
    q = jax.random.normal(jax.random.key(3), (b, s, h, d), jnp.float32)
    k = jax.random.normal(jax.random.key(4), (b, s, h, d), jnp.float32)
    v = jax.random.normal(jax.random.key(5), (b, s, h, d), jnp.float32)
    ref = reference_attention(q, k, v, causal=False)
    out = flash_attention(q, k, v, causal=False, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-3, rtol=2e-3)


def test_flagship_config_param_counts():
    """The full-size configs must match their published parameter counts
    (Llama-3-8B = 8.03B, Mixtral-8x7B = 46.7B) — verified abstractly via
    eval_shape, nothing materializes. Guards against silent config drift
    (a wrong d_ff or head count changes the billions digit)."""
    from gpu_docker_api_tpu.models.llama import LlamaConfig, count_params
    from gpu_docker_api_tpu.models.llama import init_params as llama_ip
    from gpu_docker_api_tpu.models.moe import MoEConfig
    from gpu_docker_api_tpu.models.moe import init_params as moe_ip

    def count(cfg, init):
        # count_params works on eval_shape output: ShapeDtypeStruct has .size
        return count_params(jax.eval_shape(
            lambda: init(cfg, jax.random.key(0))))

    assert count(LlamaConfig.llama3_8b(), llama_ip) == pytest.approx(
        8.03e9, rel=0.005)
    assert count(MoEConfig.mixtral_8x7b(), moe_ip) == pytest.approx(
        46.7e9, rel=0.005)
    # the single-v5e MFU flagship (50.0% measured round 3): ~1.07B
    assert count(LlamaConfig.llama_1b(), llama_ip) == pytest.approx(
        1.075e9, rel=0.01)


def test_auto_dispatch_respects_measured_crossover(monkeypatch):
    """The auto dispatcher must not pick the measured-slower impl
    (VERDICT r2 weak #2): the round-3 interleaved v5e sweep has flash
    winning from S=1024 on both paths; below that (unmeasured) XLA is
    the conservative default, as for any kernel-unfriendly shape."""
    import importlib
    # the ops package re-exports the `attention` FUNCTION under the same
    # name as the module, so attribute-style imports resolve to it
    attn_mod = importlib.import_module("gpu_docker_api_tpu.ops.attention")

    calls = []
    monkeypatch.setattr(attn_mod, "_on_tpu", lambda: True)
    monkeypatch.setattr(attn_mod, "flash_attention",
                        lambda *a, **k: calls.append("flash"))
    monkeypatch.setattr(attn_mod, "reference_attention",
                        lambda *a, **k: calls.append("xla"))

    def q(s):
        return jnp.zeros((1, s, 2, 128), jnp.bfloat16)

    for s, want in ((512, "xla"), (1024, "flash"), (2048, "flash"),
                    (1000, "xla")):     # 1000: unaligned stays XLA too
        calls.clear()
        attn_mod.attention(q(s), q(s), q(s), impl="auto")
        assert calls == [want], (s, calls)
    # the grad path (train.loss_fn) crosses over a tier earlier: flash's
    # backward avoids the [S, S] rematerialization, measured 1.23x at 1024
    for s, want in ((512, "xla"), (1024, "flash"), (2048, "flash")):
        calls.clear()
        attn_mod.attention(q(s), q(s), q(s), impl="auto_grad")
        assert calls == [want], (s, calls)
    # explicit impl always wins over the crossover
    calls.clear()
    attn_mod.attention(q(1024), q(1024), q(1024), impl="flash")
    assert calls == ["flash"]
    with pytest.raises(ValueError, match="impl"):
        attn_mod.attention(q(128), q(128), q(128), impl="bogus")


def test_moe_gather_einsum_dispatch_agree():
    """The two expert-dispatch paths (one-hot einsums for ep-sharded
    meshes, slot->token gathers for single-shard) must implement the
    SAME routing semantics: identical capacity ranking, identical
    drops, identical renormalized gate weighting. Forced-tight capacity
    so real drops occur in the comparison."""
    import numpy as np

    from gpu_docker_api_tpu.models.moe import (
        MoEConfig, _moe_experts_einsum, _moe_experts_gather,
        capacity_positions, init_params)

    cfg = MoEConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    layer = jax.tree.map(lambda p: p[0], params["layers"])
    t = 96
    ht = jax.random.normal(jax.random.key(1), (t, cfg.d_model),
                           jnp.float32)
    logits = ht @ layer["router"]
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, cfg.top_k)
    gate_vals = gate_vals / jnp.sum(gate_vals, axis=-1, keepdims=True)
    onehot = jax.nn.one_hot(gate_idx, cfg.n_experts, dtype=jnp.int32)
    pos = capacity_positions(onehot)
    cap = max(2, cfg.capacity(t) // 2)      # tight: force real drops
    keep = pos < cap
    assert not bool(jnp.all(keep)), "capacity must actually drop tokens"

    def pin(arr, spec):
        return arr

    a = _moe_experts_einsum(ht, layer, cfg, gate_idx, gate_vals, keep,
                            pos, cap, pin)
    b = _moe_experts_gather(ht, layer, cfg, gate_idx, gate_vals, keep,
                            pos, cap, pin)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-5)
