import os

import pytest

from gpu_docker_api_tpu.utils.file import (
    copy_dir, dir_size, from_bytes, move_dir_contents, to_bytes, valid_size_unit,
)


def test_to_bytes():
    assert to_bytes("1KB") == 1024
    assert to_bytes("30GB") == 30 * 1024 ** 3
    assert to_bytes("2TB") == 2 * 1024 ** 4
    assert to_bytes("1.5MB") == int(1.5 * 1024 ** 2)
    assert to_bytes(" 10mb ") == 10 * 1024 ** 2


def test_to_bytes_rejects_garbage():
    # the reference's ToBytes silently returns 0 here (utils/file.go:23-46)
    for bad in ("10XB", "GB", "", "10", "xGB"):
        with pytest.raises(ValueError):
            to_bytes(bad)


def test_from_bytes_roundtrip():
    # regression for reference bug 2 (SURVEY): rollback labelled MB counts as GB
    for s in ("1KB", "512MB", "30GB", "2TB"):
        assert to_bytes(from_bytes(to_bytes(s))) == to_bytes(s)
    assert from_bytes(30 * 1024 ** 3) == "30GB"


def test_valid_size_unit():
    assert valid_size_unit("20GB")
    assert valid_size_unit("1.5tb")
    assert not valid_size_unit("20G")
    assert not valid_size_unit("GB")


def test_dir_size_and_copy(tmp_path):
    src = tmp_path / "src"
    (src / "sub").mkdir(parents=True)
    (src / "a.bin").write_bytes(b"x" * 1000)
    (src / "sub" / "b.bin").write_bytes(b"y" * 500)
    os.symlink("a.bin", src / "link")
    assert dir_size(str(src)) == 1500

    dest = tmp_path / "dest"
    copy_dir(str(src), str(dest))
    assert (dest / "a.bin").read_bytes() == b"x" * 1000
    assert (dest / "sub" / "b.bin").read_bytes() == b"y" * 500
    assert os.path.islink(dest / "link")


def test_dir_size_dedupes_hardlinks(tmp_path):
    # a hardlinked file occupies ONE set of blocks; billing it per link
    # over-charged quota checks (the shrink guard refused legitimate sizes)
    d = tmp_path / "vol"
    d.mkdir()
    (d / "orig.bin").write_bytes(b"h" * 2048)
    os.link(d / "orig.bin", d / "hard1.bin")
    (d / "sub").mkdir()
    os.link(d / "orig.bin", d / "sub" / "hard2.bin")
    (d / "plain.bin").write_bytes(b"p" * 100)
    assert dir_size(str(d)) == 2048 + 100


def test_move_dir_contents(tmp_path):
    src = tmp_path / "old"
    src.mkdir()
    (src / "data.txt").write_text("hello")
    dest = tmp_path / "new"
    move_dir_contents(str(src), str(dest))
    assert (dest / "data.txt").read_text() == "hello"
    assert not any(src.iterdir())


def test_from_bytes_exact_roundtrip_odd_sizes():
    # non-unit-aligned byte counts must still round-trip exactly
    for n in (1535450955, 1023, 1025, 7 * 1024 ** 3 + 13):
        assert to_bytes(from_bytes(n)) == n
