"""Differentiable flash attention: the pallas backward kernels (dq, dk/dv
with GQA group accumulation) must match reference_attention's gradients.
Run in interpreter mode on CPU; the same kernels compile for TPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gpu_docker_api_tpu.ops.attention import (
    flash_attention, reference_attention,
)

# slow tier: long-compile / multi-process e2e — quick CI runs
# -m 'not slow' (<3 min); the full suite stays the default
pytestmark = pytest.mark.slow


def _grads(b, s, h, hkv, d, causal, blk=64):
    q = jax.random.normal(jax.random.key(1), (b, s, h, d), jnp.float32)
    k = jax.random.normal(jax.random.key(2), (b, s, hkv, d), jnp.float32)
    v = jax.random.normal(jax.random.key(3), (b, s, hkv, d), jnp.float32)
    cot = jax.random.normal(jax.random.key(4), (b, s, h, d), jnp.float32)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) * cot)

    flash = loss(lambda q, k, v: flash_attention(
        q, k, v, causal=causal, blk_q=blk, blk_k=blk, interpret=True))
    ref = loss(lambda q, k, v: reference_attention(q, k, v, causal=causal))
    gf = jax.grad(flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
    return gf, gr


@pytest.mark.parametrize("case", [
    dict(b=2, s=128, h=4, hkv=4, d=32, causal=True),    # MHA causal
    dict(b=2, s=128, h=4, hkv=2, d=32, causal=True),    # GQA causal
    dict(b=1, s=128, h=8, hkv=2, d=16, causal=False),   # GQA full
    dict(b=1, s=256, h=4, hkv=2, d=32, causal=True),    # multi kv-block
])
def test_flash_grads_match_reference(case):
    gf, gr = _grads(**case)
    for name, a, b_ in zip(("dq", "dk", "dv"), gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=2e-3, rtol=2e-3, err_msg=name)


def test_train_step_through_flash_path():
    """A whole model loss differentiates through the flash kernel (this was
    impossible before custom_vjp — grad through pallas_call has no rule)."""
    from gpu_docker_api_tpu.models.llama import LlamaConfig, init_params
    from gpu_docker_api_tpu.train import loss_fn

    # 128-seq so blocks divide; flash forced via impl
    cfg = LlamaConfig(vocab_size=128, d_model=64, n_layers=1, n_heads=4,
                      n_kv_heads=2, d_ff=128, max_seq_len=128,
                      dtype=jnp.float32)
    params = init_params(cfg, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (1, 128), 0, 128,
                              dtype=jnp.int32)

    # interpret-mode flash inside the full CE loss. (importlib, not plain
    # `import a.b.attention`: the package re-exports an `attention` FUNCTION
    # that shadows the submodule attribute)
    import importlib
    att = importlib.import_module("gpu_docker_api_tpu.ops.attention")
    orig = att.flash_attention

    def interp_flash(q, k, v, causal=True, **kw):
        return orig(q, k, v, causal=causal, interpret=True)

    att.flash_attention = interp_flash
    try:
        val, grads = jax.value_and_grad(
            lambda p: loss_fn(p, toks, cfg, impl="flash"))(params)
    finally:
        att.flash_attention = orig
    assert bool(jnp.isfinite(val))
    flat = jax.tree.leaves(grads)
    assert all(bool(jnp.all(jnp.isfinite(g))) for g in flat)
    assert any(float(jnp.max(jnp.abs(g))) > 0 for g in flat)
