"""Differentiable flash attention: the pallas backward kernels (dq, dk/dv
with GQA group accumulation) must match reference_attention's gradients.
Run in interpreter mode on CPU; the same kernels compile for TPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gpu_docker_api_tpu.ops.attention import (
    flash_attention, reference_attention,
)

# slow tier: long-compile / multi-process e2e — quick CI runs
# -m 'not slow' (<3 min); the full suite stays the default
pytestmark = pytest.mark.slow


def _grads(b, s, h, hkv, d, causal, blk=64):
    q = jax.random.normal(jax.random.key(1), (b, s, h, d), jnp.float32)
    k = jax.random.normal(jax.random.key(2), (b, s, hkv, d), jnp.float32)
    v = jax.random.normal(jax.random.key(3), (b, s, hkv, d), jnp.float32)
    cot = jax.random.normal(jax.random.key(4), (b, s, h, d), jnp.float32)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) * cot)

    flash = loss(lambda q, k, v: flash_attention(
        q, k, v, causal=causal, blk_q=blk, blk_k=blk, interpret=True))
    ref = loss(lambda q, k, v: reference_attention(q, k, v, causal=causal))
    gf = jax.grad(flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
    return gf, gr


@pytest.mark.parametrize("case", [
    dict(b=2, s=128, h=4, hkv=4, d=32, causal=True),    # MHA causal
    dict(b=2, s=128, h=4, hkv=2, d=32, causal=True),    # GQA causal
    dict(b=1, s=128, h=8, hkv=2, d=16, causal=False),   # GQA full
    dict(b=1, s=256, h=4, hkv=2, d=32, causal=True),    # multi kv-block
])
def test_flash_grads_match_reference(case):
    gf, gr = _grads(**case)
    for name, a, b_ in zip(("dq", "dk", "dv"), gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=2e-3, rtol=2e-3, err_msg=name)


def test_train_step_through_flash_path():
    """A whole model loss differentiates through the flash kernel (this was
    impossible before custom_vjp — grad through pallas_call has no rule)."""
    from gpu_docker_api_tpu.models.llama import LlamaConfig, init_params
    from gpu_docker_api_tpu.train import loss_fn

    # 128-seq so blocks divide; flash forced via impl
    cfg = LlamaConfig(vocab_size=128, d_model=64, n_layers=1, n_heads=4,
                      n_kv_heads=2, d_ff=128, max_seq_len=128,
                      dtype=jnp.float32)
    params = init_params(cfg, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (1, 128), 0, 128,
                              dtype=jnp.int32)

    # interpret-mode flash inside the full CE loss. (importlib, not plain
    # `import a.b.attention`: the package re-exports an `attention` FUNCTION
    # that shadows the submodule attribute)
    import importlib
    att = importlib.import_module("gpu_docker_api_tpu.ops.attention")
    orig = att.flash_attention

    def interp_flash(q, k, v, causal=True, **kw):
        return orig(q, k, v, causal=causal, interpret=True)

    att.flash_attention = interp_flash
    try:
        val, grads = jax.value_and_grad(
            lambda p: loss_fn(p, toks, cfg, impl="flash"))(params)
    finally:
        att.flash_attention = orig
    assert bool(jnp.isfinite(val))
    flat = jax.tree.leaves(grads)
    assert all(bool(jnp.all(jnp.isfinite(g))) for g in flat)
    assert any(float(jnp.max(jnp.abs(g))) > 0 for g in flat)


# ---- long-sequence chunked flash (blockwise_attention) ---------------------

def _bw_qkv(key, s, b=1, h=2, hkv=2, d=16):
    ks = jax.random.split(key, 3)
    return (jax.random.normal(ks[0], (b, s, h, d), jnp.float32),
            jax.random.normal(ks[1], (b, s, hkv, d), jnp.float32),
            jax.random.normal(ks[2], (b, s, hkv, d), jnp.float32))


@pytest.mark.parametrize("window", [0, 10])
def test_blockwise_matches_reference(window):
    """Chunk-pair decomposition == single reference attention, causal
    and windowed (with the banded boundary pair), fwd AND grads — the
    path sequences past the single-call VMEM ceiling take."""
    from gpu_docker_api_tpu.ops.attention import blockwise_attention

    q, k, v = _bw_qkv(jax.random.key(0), s=64)
    want = reference_attention(q, k, v, causal=True, window=window)
    got = blockwise_attention(q, k, v, causal=True, window=window,
                              chunk=16, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)

    def loss_b(q, k, v):
        return jnp.sum(blockwise_attention(
            q, k, v, causal=True, window=window, chunk=16,
            interpret=True).astype(jnp.float32) ** 2)

    def loss_r(q, k, v):
        return jnp.sum(reference_attention(
            q, k, v, causal=True, window=window).astype(jnp.float32) ** 2)

    g1 = jax.grad(loss_b, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b2 in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b2),
                                   rtol=3e-3, atol=3e-3)


def test_blockwise_noncausal_matches_reference():
    from gpu_docker_api_tpu.ops.attention import blockwise_attention

    q, k, v = _bw_qkv(jax.random.key(1), s=48)
    want = reference_attention(q, k, v, causal=False)
    got = blockwise_attention(q, k, v, causal=False, chunk=16,
                              interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_blockwise_gqa_and_single_chunk_fallback():
    from gpu_docker_api_tpu.ops.attention import blockwise_attention

    q, k, v = _bw_qkv(jax.random.key(2), s=32, h=4, hkv=2)
    want = reference_attention(q, k, v, causal=True)
    got = blockwise_attention(q, k, v, causal=True, chunk=16,
                              interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
    # s <= chunk falls back to one kernel call
    got1 = blockwise_attention(q, k, v, causal=True, chunk=64,
                               interpret=True)
    np.testing.assert_allclose(np.asarray(got1), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_blockwise_window_larger_than_chunk():
    """window > chunk: past chunks wholly inside the window run the
    flash pair, only the boundary chunk uses the banded einsum — and the
    result still equals the reference."""
    from gpu_docker_api_tpu.ops.attention import blockwise_attention

    q, k, v = _bw_qkv(jax.random.key(3), s=96)
    for window in (40, 60, 96):       # spans 2-6 chunks of 16
        want = reference_attention(q, k, v, causal=True, window=window)
        got = blockwise_attention(q, k, v, causal=True, window=window,
                                  chunk=16, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=3e-4, atol=3e-4)


def test_auto_long_seq_dispatch(monkeypatch):
    """Past the single-call ceiling, auto routes divisible lengths to
    the chunk decomposition and non-decomposable ones to XLA — never to
    the known-OOM single call."""
    import importlib
    attn_mod = importlib.import_module("gpu_docker_api_tpu.ops.attention")

    calls = []
    monkeypatch.setattr(attn_mod, "_on_tpu", lambda: True)
    monkeypatch.setattr(attn_mod, "flash_attention",
                        lambda *a, **k: calls.append("flash"))
    monkeypatch.setattr(attn_mod, "blockwise_attention",
                        lambda *a, **k: calls.append("blockwise"))
    monkeypatch.setattr(attn_mod, "reference_attention",
                        lambda *a, **k: calls.append("xla"))

    def qq(s):
        return jnp.zeros((1, s, 2, 128), jnp.bfloat16)

    cases = [
        # grad path: single to 4096, blockwise past, xla if indivisible
        ("auto_grad", 4096, "flash"), ("auto_grad", 8192, "blockwise"),
        ("auto_grad", 2048 * 5, "blockwise"),
        ("auto_grad", 4096 + 1024, "blockwise"),   # 5120 = 2.5 chunks?
        # fwd path: single to 8192
        ("auto", 8192, "flash"), ("auto", 16384, "blockwise"),
    ]
    # 5120 % 2048 != 0 -> xla, fix expectation
    cases[3] = ("auto_grad", 4096 + 1024, "xla")
    for impl, s, want in cases:
        calls.clear()
        attn_mod.attention(qq(s), qq(s), qq(s), impl=impl)
        assert calls == [want], (impl, s, calls)


@pytest.mark.parametrize("fast", [False, True], ids=["f32", "bf16mxu"])
@pytest.mark.parametrize("causal", [True, False])
def test_flash_bf16_mxu_path_matches_reference(causal, fast, monkeypatch):
    """bf16 inputs through BOTH kernel precisions: the default f32 path
    and the TDAPI_FLASH_BF16_MXU fast path (operands stay bf16 into the
    dots, f32 accumulation; default-off after the v5e A/B measured no
    gain — kept for chips where the f32 matmul rate binds, so its
    numerics must stay pinned). The flag is read at import, so the test
    monkeypatches the module constant. QK^T products are exact (bf16
    mantissa pairs fit f32); the p/ds second-dot operands round to bf16,
    the same precision the bf16 output cast imposes anyway."""
    import importlib
    attn_mod = importlib.import_module("gpu_docker_api_tpu.ops.attention")
    monkeypatch.setattr(attn_mod, "FLASH_BF16_MXU", fast)
    b, s, h, hkv, d = 2, 128, 4, 2, 32
    q = jax.random.normal(jax.random.key(1), (b, s, h, d), jnp.bfloat16)
    k = jax.random.normal(jax.random.key(2), (b, s, hkv, d), jnp.bfloat16)
    v = jax.random.normal(jax.random.key(3), (b, s, hkv, d), jnp.bfloat16)
    cot = jax.random.normal(jax.random.key(4), (b, s, h, d), jnp.float32)

    out = flash_attention(q, k, v, causal=causal, blk_q=64, blk_k=64,
                          interpret=True)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=2e-2, rtol=2e-2)

    def loss(fn):
        return lambda q, k, v: jnp.sum(
            fn(q, k, v).astype(jnp.float32) * cot)

    gf = jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, causal=causal, blk_q=64, blk_k=64, interpret=True)),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss(lambda q, k, v: reference_attention(
        q, k, v, causal=causal)), argnums=(0, 1, 2))(q, k, v)
    for name, a, b_ in zip(("dq", "dk", "dv"), gf, gr):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b_, np.float32),
            atol=6e-2, rtol=6e-2, err_msg=name)
