"""Speculative decoding (infer.speculative_generate): the greedy-case
guarantee is that the output equals the target-only greedy stream for ANY
draft model — the draft changes speed, never content."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gpu_docker_api_tpu.infer import generate, speculative_generate
from gpu_docker_api_tpu.models.llama import LlamaConfig, init_params


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny()
    target = init_params(cfg, jax.random.key(0))
    # a DIFFERENT random-init draft: worst-case proposals (near-zero
    # acceptance) — exactness must hold regardless
    draft = init_params(cfg, jax.random.key(42))
    prompt = jax.random.randint(jax.random.key(1), (1, 8), 0,
                                cfg.vocab_size, jnp.int32)
    return cfg, target, draft, prompt


def test_exact_match_with_bad_draft(setup):
    cfg, target, draft, prompt = setup
    want = np.asarray(generate(target, prompt, cfg, max_new=12))
    got, stats = speculative_generate(target, draft, prompt, cfg, cfg,
                                      max_new=12, gamma=4)
    np.testing.assert_array_equal(np.asarray(got), want)
    assert int(stats["rounds"]) >= 1


def test_exact_match_with_perfect_draft_and_fewer_rounds(setup):
    """Draft == target: every proposal accepted, so each round emits
    gamma+1 tokens — rounds ~ max_new/(gamma+1), and the a==gamma
    cache-fill path is exercised every round."""
    cfg, target, _, prompt = setup
    want = np.asarray(generate(target, prompt, cfg, max_new=12))
    got, stats = speculative_generate(target, target, prompt, cfg, cfg,
                                      max_new=12, gamma=3)
    np.testing.assert_array_equal(np.asarray(got), want)
    # 12 tokens: first + rounds*(<=4); perfect acceptance -> 3 rounds
    assert int(stats["rounds"]) == 3
    assert int(stats["accepted"]) == 3 * 3     # a == gamma every round


@pytest.mark.parametrize("gamma", [1, 2, 5])
def test_exact_across_gamma(setup, gamma):
    cfg, target, draft, prompt = setup
    want = np.asarray(generate(target, prompt, cfg, max_new=9))
    got, _ = speculative_generate(target, draft, prompt, cfg, cfg,
                                  max_new=9, gamma=gamma)
    np.testing.assert_array_equal(np.asarray(got), want)


def test_heterogeneous_draft_config(setup):
    """The draft may be a different architecture entirely (that's the
    point); only the vocab must match."""
    cfg, target, _, prompt = setup
    small = LlamaConfig(vocab_size=cfg.vocab_size, d_model=32, n_layers=1,
                        n_heads=2, n_kv_heads=1, d_ff=64, max_seq_len=128,
                        dtype=jnp.float32)
    draft = init_params(small, jax.random.key(7))
    want = np.asarray(generate(target, prompt, cfg, max_new=10))
    got, _ = speculative_generate(target, draft, prompt, cfg, small,
                                  max_new=10, gamma=4)
    np.testing.assert_array_equal(np.asarray(got), want)


def test_rejects_batch(setup):
    cfg, target, draft, _ = setup
    prompt = jnp.zeros((2, 4), jnp.int32)
    with pytest.raises(ValueError):
        speculative_generate(target, draft, prompt, cfg, cfg, max_new=4)


def test_speculative_with_kv_quant(setup):
    """kv_quant must flow into BOTH caches (a --kv-quant server's greedy
    path keeps the int8 cache); the stream matches the target's own
    kv-quant greedy stream."""
    cfg, target, draft, prompt = setup
    want = np.asarray(generate(target, prompt, cfg, max_new=10,
                               kv_quant=True))
    got, _ = speculative_generate(target, draft, prompt, cfg, cfg,
                                  max_new=10, gamma=4, kv_quant=True)
    np.testing.assert_array_equal(np.asarray(got), want)
