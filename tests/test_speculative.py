"""Speculative decoding (infer.speculative_generate): the greedy-case
guarantee is that the output equals the target-only greedy stream for ANY
draft model — the draft changes speed, never content."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gpu_docker_api_tpu.infer import generate, speculative_generate
from gpu_docker_api_tpu.models.llama import LlamaConfig, init_params

# slow tier: long-compile / multi-process e2e — quick CI runs
# -m 'not slow' (<3 min); the full suite stays the default
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny()
    target = init_params(cfg, jax.random.key(0))
    # a DIFFERENT random-init draft: worst-case proposals (near-zero
    # acceptance) — exactness must hold regardless
    draft = init_params(cfg, jax.random.key(42))
    prompt = jax.random.randint(jax.random.key(1), (1, 8), 0,
                                cfg.vocab_size, jnp.int32)
    return cfg, target, draft, prompt


def test_exact_match_with_bad_draft(setup):
    cfg, target, draft, prompt = setup
    want = np.asarray(generate(target, prompt, cfg, max_new=12))
    got, stats = speculative_generate(target, draft, prompt, cfg, cfg,
                                      max_new=12, gamma=4)
    np.testing.assert_array_equal(np.asarray(got), want)
    assert int(stats["rounds"]) >= 1


def test_exact_match_with_perfect_draft_and_fewer_rounds(setup):
    """Draft == target: every proposal accepted, so each round emits
    gamma+1 tokens — rounds ~ max_new/(gamma+1), and the a==gamma
    cache-fill path is exercised every round."""
    cfg, target, _, prompt = setup
    want = np.asarray(generate(target, prompt, cfg, max_new=12))
    got, stats = speculative_generate(target, target, prompt, cfg, cfg,
                                      max_new=12, gamma=3)
    np.testing.assert_array_equal(np.asarray(got), want)
    # 12 tokens: first + rounds*(<=4); perfect acceptance -> 3 rounds
    assert int(stats["rounds"]) == 3
    assert int(stats["accepted"]) == 3 * 3     # a == gamma every round


@pytest.mark.parametrize("gamma", [1, 2, 5])
def test_exact_across_gamma(setup, gamma):
    cfg, target, draft, prompt = setup
    want = np.asarray(generate(target, prompt, cfg, max_new=9))
    got, _ = speculative_generate(target, draft, prompt, cfg, cfg,
                                  max_new=9, gamma=gamma)
    np.testing.assert_array_equal(np.asarray(got), want)


def test_heterogeneous_draft_config(setup):
    """The draft may be a different architecture entirely (that's the
    point); only the vocab must match."""
    cfg, target, _, prompt = setup
    small = LlamaConfig(vocab_size=cfg.vocab_size, d_model=32, n_layers=1,
                        n_heads=2, n_kv_heads=1, d_ff=64, max_seq_len=128,
                        dtype=jnp.float32)
    draft = init_params(small, jax.random.key(7))
    want = np.asarray(generate(target, prompt, cfg, max_new=10))
    got, _ = speculative_generate(target, draft, prompt, cfg, small,
                                  max_new=10, gamma=4)
    np.testing.assert_array_equal(np.asarray(got), want)


def test_rejects_batch(setup):
    cfg, target, draft, _ = setup
    prompt = jnp.zeros((2, 4), jnp.int32)
    with pytest.raises(ValueError):
        speculative_generate(target, draft, prompt, cfg, cfg, max_new=4)


def test_speculative_with_kv_quant(setup):
    """kv_quant must flow into BOTH caches (a --kv-quant server's greedy
    path keeps the int8 cache); the stream matches the target's own
    kv-quant greedy stream."""
    cfg, target, draft, prompt = setup
    want = np.asarray(generate(target, prompt, cfg, max_new=10,
                               kv_quant=True))
    got, _ = speculative_generate(target, draft, prompt, cfg, cfg,
                                  max_new=10, gamma=4, kv_quant=True)
    np.testing.assert_array_equal(np.asarray(got), want)


# ---- rejection-sampling speculative decoding (temperature > 0) -------------

@pytest.fixture(scope="module")
def sampling_setup():
    # tiny vocab so exact marginals are enumerable and the statistical
    # test has power at a few hundred samples
    cfg = LlamaConfig(vocab_size=16, d_model=32, n_layers=2, n_heads=2,
                      n_kv_heads=1, d_ff=64, max_seq_len=64,
                      dtype=jnp.float32)
    target = init_params(cfg, jax.random.key(0))
    # a draft with a SHARP, very different q (random tiny inits are all
    # near-uniform over 16 tokens, which would give the distribution test
    # no power): scale its head so q concentrates where p doesn't
    draft = init_params(cfg, jax.random.key(42))
    draft = dict(draft, lm_head=draft["lm_head"] * 8.0)
    prompt = jnp.array([[3, 7, 1, 9]], jnp.int32)
    return cfg, target, draft, prompt


def test_sampling_deterministic_per_key(sampling_setup):
    cfg, target, draft, prompt = sampling_setup
    a, _ = speculative_generate(target, draft, prompt, cfg, cfg,
                                max_new=8, gamma=3, temperature=0.8,
                                key=jax.random.key(5))
    b, _ = speculative_generate(target, draft, prompt, cfg, cfg,
                                max_new=8, gamma=3, temperature=0.8,
                                key=jax.random.key(5))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sampling_distribution_matches_target_exactly(sampling_setup):
    """Rejection-sampling guarantee (Leviathan et al.): the emitted-token
    marginal equals the TARGET-only sampling distribution for ANY draft.
    Compare the empirical marginal of the first round-emitted token (the
    accepted-or-resampled one) against the analytically exact target
    marginal; a broken acceptance rule would pull it toward the (very
    different) draft distribution."""
    from gpu_docker_api_tpu.infer import prefill, init_cache

    cfg, target, draft, prompt = sampling_setup
    temp = 0.9

    def dist(logits):
        return np.asarray(jax.nn.softmax(logits / temp, axis=-1))[0]

    # exact marginal of token[1]: sum_t0 p(t0) * p(.|prompt,t0)
    logits0, _ = prefill(target, prompt,
                         init_cache(cfg, 1, 32), cfg)
    p0 = dist(logits0)
    exact = np.zeros(cfg.vocab_size)
    for t0 in range(cfg.vocab_size):
        if p0[t0] < 1e-9:
            continue
        ext = jnp.concatenate(
            [prompt, jnp.array([[t0]], jnp.int32)], axis=1)
        lg, _ = prefill(target, ext, init_cache(cfg, 1, 32), cfg)
        exact += p0[t0] * dist(lg)

    n = 600
    counts = np.zeros(cfg.vocab_size)
    for i in range(n):
        toks, _ = speculative_generate(
            target, draft, prompt, cfg, cfg, max_new=2, gamma=3,
            temperature=temp, key=jax.random.key(1000 + i))
        counts[int(toks[0, 1])] += 1
    tv = 0.5 * np.abs(counts / n - exact).sum()
    assert tv < 0.15, f"TV {tv:.3f} vs exact target marginal (n={n})"
    # power check: the draft's own marginal must be far from the target's
    # (otherwise this test couldn't catch draft contamination)
    lgd, _ = prefill(draft, prompt, init_cache(cfg, 1, 32), cfg)
    assert 0.5 * np.abs(dist(lgd) - p0).sum() > 0.3


def test_sampling_with_filters_and_kv_quant_runs(sampling_setup):
    cfg, target, draft, prompt = sampling_setup
    toks, stats = speculative_generate(
        target, draft, prompt, cfg, cfg, max_new=10, gamma=4,
        temperature=0.7, top_k=8, top_p=0.9, kv_quant=True,
        key=jax.random.key(2))
    out = np.asarray(toks)
    assert out.shape == (1, 10)
    assert (out >= 0).all() and (out < cfg.vocab_size).all()
    assert int(stats["rounds"]) >= 1


def test_sampling_accepts_everything_with_identical_draft(sampling_setup):
    """draft == target: min(1, p/q) = 1, so every proposal is accepted and
    rounds ~ max_new/(gamma+1) — the speedup survives sampling."""
    cfg, target, _, prompt = sampling_setup
    gamma, max_new = 4, 15
    _, stats = speculative_generate(
        target, target, prompt, cfg, cfg, max_new=max_new, gamma=gamma,
        temperature=1.0, key=jax.random.key(3))
    assert int(stats["rounds"]) <= -(-max_new // (gamma + 1)) + 1
    assert int(stats["accepted"]) >= int(stats["rounds"]) * gamma * 0.9
