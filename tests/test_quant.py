"""Int8 quantization (ops/quant.py): roundtrip error bounds, qmatmul
equivalences, and the quantized end-to-end inference path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gpu_docker_api_tpu.infer import generate, init_cache, prefill
from gpu_docker_api_tpu.models.llama import LlamaConfig, init_params
from gpu_docker_api_tpu.ops.quant import (
    QTensor, dequantize, is_quantized, qmatmul, quantize, quantize_params,
)


def test_quantize_roundtrip_error_bound():
    w = jax.random.normal(jax.random.key(0), (64, 48), jnp.float32)
    qt = quantize(w)
    assert qt.q.dtype == jnp.int8
    assert qt.s.shape == (48,)
    # symmetric per-channel: |error| <= scale/2 per element
    err = np.abs(np.asarray(dequantize(qt, jnp.float32)) - np.asarray(w))
    assert (err <= np.asarray(qt.s)[None, :] * 0.5 + 1e-6).all()


def test_quantize_stacked_layers_axis():
    w = jax.random.normal(jax.random.key(1), (3, 16, 8), jnp.float32)
    qt = quantize(w)
    assert qt.s.shape == (3, 8)          # per-layer, per-out-channel
    err = np.abs(np.asarray(dequantize(qt, jnp.float32)) - np.asarray(w))
    assert (err <= np.asarray(qt.s)[:, None, :] * 0.5 + 1e-6).all()


def test_qmatmul_dense_passthrough():
    x = jax.random.normal(jax.random.key(2), (4, 16), jnp.float32)
    w = jax.random.normal(jax.random.key(3), (16, 8), jnp.float32)
    np.testing.assert_allclose(np.asarray(qmatmul(x, w)),
                               np.asarray(x @ w), rtol=1e-6)


def test_qmatmul_w8_equals_dequantized_matmul():
    """Output-side scaling must be numerically equivalent to dequantizing
    the weight first (the scale factors out of the contraction)."""
    x = jax.random.normal(jax.random.key(4), (4, 32), jnp.float32)
    w = jax.random.normal(jax.random.key(5), (32, 16), jnp.float32)
    qt = quantize(w, "w8")
    got = np.asarray(qmatmul(x, qt))
    want = np.asarray(x @ dequantize(qt, jnp.float32))
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)
    # and both are close to the dense product
    np.testing.assert_allclose(got, np.asarray(x @ w), rtol=0.15, atol=0.15)


def test_qmatmul_w8a8_close_to_dense():
    x = jax.random.normal(jax.random.key(6), (4, 64), jnp.float32)
    w = jax.random.normal(jax.random.key(7), (64, 16), jnp.float32)
    qt = quantize(w, "w8a8")
    got = np.asarray(qmatmul(x, qt))
    want = np.asarray(x @ w)
    # dynamic 8-bit on both sides: ~1% relative error on gaussian data
    assert np.abs(got - want).max() / np.abs(want).max() < 0.05


def test_qtensor_is_a_pytree_through_jit():
    w = jax.random.normal(jax.random.key(8), (16, 8), jnp.float32)
    qt = quantize(w)
    out = jax.jit(lambda q: qmatmul(jnp.ones((2, 16), jnp.float32), q))(qt)
    assert out.shape == (2, 8)
    leaves, treedef = jax.tree.flatten(qt)
    assert len(leaves) == 2              # q + s; mode rides the treedef
    qt2 = jax.tree.unflatten(treedef, leaves)
    assert qt2.mode == qt.mode


@pytest.mark.parametrize("mode", ["w8", "w8a8"])
def test_quantized_prefill_logits_close(mode):
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    qparams = quantize_params(params, mode)
    assert is_quantized(qparams) and not is_quantized(params)
    toks = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)
    dense_logits, _ = prefill(params, toks, init_cache(cfg, 2, 32), cfg)
    q_logits, _ = prefill(qparams, toks, init_cache(cfg, 2, 32), cfg)
    d, q = np.asarray(dense_logits), np.asarray(q_logits)
    # logits track the dense model closely relative to their spread
    assert np.abs(q - d).max() / (np.abs(d).max() + 1e-9) < 0.08
    # and the top-1 token mostly survives quantization
    agree = (d.argmax(-1) == q.argmax(-1)).mean()
    assert agree >= 0.5, f"top-1 agreement {agree}"


def test_quantized_generate_runs_greedy():
    cfg = LlamaConfig.tiny()
    params = quantize_params(init_params(cfg, jax.random.key(0)), "w8")
    prompt = jax.random.randint(jax.random.key(2), (2, 8), 0, cfg.vocab_size)
    out = generate(params, prompt, cfg, max_new=6)
    assert out.shape == (2, 6)
    assert (np.asarray(out) >= 0).all()
    assert (np.asarray(out) < cfg.vocab_size).all()


def test_quantize_params_rejects_unknown_mode():
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    with pytest.raises(ValueError):
        quantize_params(params, "int4")


# ---- int8 KV cache ----

def test_kv_cache_quantized_shapes():
    cfg = LlamaConfig.tiny()
    cache = init_cache(cfg, 2, 32, quantized=True)
    assert cache["k"].dtype == jnp.int8
    assert cache["ks"].shape == cache["k"].shape[:-1] + (1,)
    assert cache["ks"].dtype == jnp.float32


def test_kv_quant_prefill_decode_close_to_dense():
    """Quantized-cache prefill+decode must track the dense cache closely:
    the int8 error is per-token bounded by the per-token-per-head scale."""
    from gpu_docker_api_tpu.infer import decode_step
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(3), (2, 16), 0, cfg.vocab_size)
    ld, cd = prefill(params, toks, init_cache(cfg, 2, 32), cfg)
    lq, cq = prefill(params, toks, init_cache(cfg, 2, 32, quantized=True),
                     cfg)
    d, q = np.asarray(ld), np.asarray(lq)
    assert np.abs(q - d).max() / (np.abs(d).max() + 1e-9) < 0.08
    # a decode step on top of the quantized cache stays close too
    nxt = jnp.argmax(ld, axis=-1).astype(jnp.int32)
    ld2, _ = decode_step(params, nxt, cd, cfg)
    lq2, _ = decode_step(params, nxt, cq, cfg)
    d2, q2 = np.asarray(ld2), np.asarray(lq2)
    assert np.abs(q2 - d2).max() / (np.abs(d2).max() + 1e-9) < 0.1


def test_kv_quant_generate_runs_and_first_token_matches():
    """The first generated token comes straight off the prefill logits,
    whose int8-cache error is bounded (see the prefill test) — unlike
    full-stream agreement, which drifts chaotically after one argmax flip
    on a random-init model and would be platform-flaky to assert."""
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(4), (2, 8), 0, cfg.vocab_size)
    dense = np.asarray(generate(params, prompt, cfg, max_new=8))
    kv8 = np.asarray(generate(params, prompt, cfg, max_new=8,
                              kv_quant=True))
    assert dense.shape == kv8.shape == (2, 8)
    assert (kv8 >= 0).all() and (kv8 < cfg.vocab_size).all()
    assert (dense[:, 0] == kv8[:, 0]).all()


def test_kv_quant_composes_with_w8_weights():
    cfg = LlamaConfig.tiny()
    params = quantize_params(init_params(cfg, jax.random.key(0)), "w8")
    prompt = jax.random.randint(jax.random.key(5), (1, 8), 0, cfg.vocab_size)
    out = generate(params, prompt, cfg, max_new=4, kv_quant=True)
    assert out.shape == (1, 4)
    assert (np.asarray(out) >= 0).all()


# ---- MoE expert-bank quantization ----

def test_quantize_moe_expert_banks():
    from gpu_docker_api_tpu.models.moe import MoEConfig
    from gpu_docker_api_tpu.models.moe import init_params as moe_init

    cfg = MoEConfig.tiny()
    params = moe_init(cfg, jax.random.key(0))
    qp = quantize_params(params, "w8")
    we1 = qp["layers"]["we1"]
    assert isinstance(we1, QTensor) and we1.q.dtype == jnp.int8
    # [L, E, d, f] -> scales per layer, expert, out-channel
    assert we1.s.shape == params["layers"]["we1"].shape[:2] + (
        params["layers"]["we1"].shape[-1],)
    assert not isinstance(qp["layers"]["router"], QTensor)   # router dense


@pytest.mark.slow
def test_quantized_moe_prefill_close_and_generate_runs():
    from gpu_docker_api_tpu.models.moe import MoEConfig
    from gpu_docker_api_tpu.models.moe import init_params as moe_init

    cfg = MoEConfig.tiny()
    params = moe_init(cfg, jax.random.key(0))
    qp = quantize_params(params, "w8")
    toks = jax.random.randint(jax.random.key(1), (2, 12), 0, cfg.vocab_size)
    d, _ = prefill(params, toks, init_cache(cfg, 2, 32), cfg)
    q, _ = prefill(qp, toks, init_cache(cfg, 2, 32), cfg)
    d, q = np.asarray(d), np.asarray(q)
    assert np.abs(q - d).max() / (np.abs(d).max() + 1e-9) < 0.1
    out = generate(qp, toks[:1, :6], cfg, max_new=4)
    assert out.shape == (1, 4)


def test_qeinsum_rejects_unsupported_scale_layouts():
    """qeinsum's output-side scale assumes an [E, in, out] bank feeding an
    [E, ..., out] output; any other layout must fail loudly instead of
    silently mis-scaling (ADVICE r2 low)."""
    from gpu_docker_api_tpu.ops.quant import qeinsum

    bank = quantize(jax.random.normal(jax.random.key(0), (2, 8, 4)), "w8")
    a = jax.random.normal(jax.random.key(1), (2, 3, 8))
    out = qeinsum("ecd,edf->ecf", a, bank)           # the supported shape
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(jnp.einsum("ecd,edf->ecf", a, dequantize(bank, a.dtype))),
        rtol=3e-5)  # both sides are f32 einsums; contraction-order noise
    # layer-stacked bank that scan didn't unstack
    bank4 = quantize(
        jax.random.normal(jax.random.key(2), (3, 2, 8, 4)), "w8")
    with pytest.raises(ValueError, match="scale layout"):
        qeinsum("lecd,ledf->lecf", jnp.zeros((3, 2, 3, 8)), bank4)
    # output not ending with the bank's out axis
    with pytest.raises(ValueError, match="scale layout"):
        qeinsum("ecd,edf->efc", a, bank)
    # output not led by the bank's expert axis
    with pytest.raises(ValueError, match="scale layout"):
        qeinsum("ecd,edf->cef", a, bank)


@pytest.mark.slow
def test_quantize_params_streaming_matches_on_device():
    """Host-side per-leaf streaming quantization (the llama3_8b-on-16GB
    serving path) produces the same numerics as the all-on-device
    quantize: identical greedy streams."""
    from gpu_docker_api_tpu.ops.quant import quantize_params_streaming

    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    prompt = jnp.array([[5, 9, 2, 7]], jnp.int32)
    want = np.asarray(generate(
        jax.jit(lambda p: quantize_params(p, "w8"))(params),
        prompt, cfg, 8))[0].tolist()
    host = jax.tree.map(np.asarray, params)        # "host-loaded" tree
    qs = quantize_params_streaming(host, "w8")
    assert is_quantized(qs)
    got = np.asarray(generate(qs, prompt, cfg, 8))[0].tolist()
    assert got == want
