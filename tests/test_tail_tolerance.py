"""Tail-tolerant serving sweep (`tail` marker; make verify-tail).

Four layers:

- PRIMITIVES on injected clocks (no threads, no sleeps): the latency
  digest's shm cell round-trip, the pure ejection decision (outlier
  threshold, min-count gate, the <=50%-of-fleet cap under all-slow
  fleets), the probation state machine (eject -> trickle probes -> N
  consecutive passes re-admit, a failure resets the streak), the
  deterministic worker-tier probe window, the hedge delay/token bucket,
  and the retry budget;
- GATEWAY integration on an injected transport: the ejection tick moves
  the outlier into probation and the picker penalizes it, a
  transport-strike FAILED replica heals back to READY through the same
  probe path WITHOUT a scale cycle, hedged requests race first-wins with
  the loser's slot released, and the retry budget sheds long before the
  deadline;
- WIRE: budget exhaustion answers HTTP 503 + Retry-After over live REST;
- WORKER-TIER PARITY over shm: the stateless tier's recomputed eject set
  equals tailtolerance.eject_set over the same published digest cells
  (the decision both tiers share), and its hedge/budget counters land on
  the gateway's shared-memory words.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request

import pytest

from gpu_docker_api_tpu import tailtolerance, xerrors
from gpu_docker_api_tpu.gateway import (
    FAILED, READY, Gateway, GatewayConfig, Replica,
)
from gpu_docker_api_tpu.tailtolerance import (
    HedgePolicy, LatencyDigest, LocalLatencyStore, ProbationTracker,
    RetryBudget, eject_set, fold_cells, trickle_allow,
)

pytestmark = pytest.mark.tail


# ------------------------------------------------------------ primitives

def test_latency_digest_fold_and_cell_roundtrip():
    d = LatencyDigest()
    d.observe(10.0)
    # the first sample seeds both estimates
    assert d.ewma_ms == 10.0 and d.p95_ms == 10.0 and d.count == 1
    for _ in range(50):
        d.observe(10.0)
    # steady traffic: the p95 estimate stays near the service time
    assert 0.0 <= d.p95_ms <= 30.0
    p95_before = d.p95_ms
    for _ in range(20):
        d.observe(500.0)
    # a latency regression drives the estimate up fast (19x step)
    assert d.p95_ms > p95_before * 3
    cells = d.to_cells()
    back = LatencyDigest.from_cells(cells)
    assert back.count == d.count
    assert back.ewma_ms == pytest.approx(d.ewma_ms, abs=0.001)
    assert back.p95_ms == pytest.approx(d.p95_ms, abs=0.001)
    # fold_cells from nothing = first observation
    c = fold_cells(None, 7.0)
    assert LatencyDigest.from_cells(c).ewma_ms == pytest.approx(7.0)


def test_eject_set_outlier_threshold_and_gates():
    fast = [("a", 10.0, 100), ("b", 12.0, 100), ("c", 11.0, 100)]
    # a 3x-median outlier ejects; the healthy rows don't
    assert eject_set(fast + [("d", 400.0, 100)], fleet=4) == {"d"}
    # under the min-count gate the outlier has no standing
    assert eject_set(fast + [("d", 400.0, 3)], fleet=4) == set()
    # a single-row "fleet" has nothing to be an outlier of
    assert eject_set([("a", 1000.0, 100)], fleet=1) == set()
    # sub-floor latencies never eject, whatever the ratio
    tiny = [("a", 0.01, 100), ("b", 0.01, 100), ("c", 1.0, 100)]
    assert eject_set(tiny, fleet=3) == set()


def test_eject_cap_never_exceeded_under_all_slow_fleet():
    """The <=50%-of-fleet cap: iterate ejection ticks over a fleet where
    EVERY replica degrades, feeding each tick's result back as `already`
    — probation membership must never pass int(cap * fleet)."""
    n = 8
    cap_abs = int(n * tailtolerance.EJECT_CAP)
    in_probation: set = set()
    # a rolling brownout: two more replicas degrade every tick, so an
    # uncapped detector would eventually eject everyone — exactly the
    # availability collapse the cap exists to prevent
    for tick in range(10):
        n_degraded = min(2 * (tick + 1), n)
        stats = [(f"r{i}", 5000.0 + i if i < n_degraded else 10.0, 100)
                 for i in range(n) if f"r{i}" not in in_probation]
        out = eject_set(stats, already=frozenset(in_probation), fleet=n)
        in_probation |= out
        assert len(in_probation) <= cap_abs, (tick, in_probation)
    assert len(in_probation) == cap_abs      # the cap BINDS, not just holds
    # and at the cap, further ticks eject nobody
    stats = [(f"r{i}", 9000.0, 100) for i in range(n)
             if f"r{i}" not in in_probation]
    assert eject_set(stats, already=frozenset(in_probation),
                     fleet=n) == set()


def test_probation_state_machine_on_injected_clock():
    clock = [100.0]
    p = ProbationTracker(now=lambda: clock[0])
    assert p.eject("a", kind="latency") is True
    assert p.eject("a") is False             # idempotent entry
    assert p.contains("a") and p.kind("a") == "latency"
    # freshly ejected: the replica just proved itself slow; no probe yet
    assert not p.probe_due("a")
    clock[0] += tailtolerance.PROBE_INTERVAL_S
    assert p.probe_due("a")
    p.note_probe("a")
    assert not p.probe_due("a")              # interval restarts per probe
    # N-1 passes then a failure: the streak resets, membership holds
    for _ in range(tailtolerance.PROBE_PASSES - 1):
        assert p.verdict("a", ok=True) is False
    assert p.verdict("a", ok=False) is False
    assert p.contains("a")
    # N consecutive passes re-admit (entry gone)
    for i in range(tailtolerance.PROBE_PASSES):
        readmitted = p.verdict("a", ok=True)
        assert readmitted is (i == tailtolerance.PROBE_PASSES - 1)
    assert not p.contains("a")
    # prune drops members whose replica left the eligible set
    p.eject("gone")
    p.prune({"kept"})
    assert len(p) == 0


def test_trickle_allow_deterministic_across_workers():
    rows = [3, 5, 9]
    w = tailtolerance.WORKER_PROBE_WINDOW_S
    sp = tailtolerance.WORKER_PROBE_SPACING
    # inside an open window: every worker (same now) picks the SAME row
    now_open = (sp * 7) * w + 0.01
    picked = trickle_allow(rows, now_open)
    assert picked in rows
    assert all(trickle_allow(rows, now_open + dt) == picked
               for dt in (0.0, w * 0.4, w * 0.9))
    # between windows: nobody probes (spacing-1 of every spacing windows)
    assert trickle_allow(rows, (sp * 7 + 1) * w + 0.01) is None
    # successive open windows rotate through the rows
    seen = {trickle_allow(rows, (sp * i) * w + 0.01) for i in range(6)}
    assert seen == set(rows)
    assert trickle_allow([], now_open) is None


def test_hedge_policy_delay_and_token_bucket():
    clock = [0.0]
    h = HedgePolicy(now=lambda: clock[0])
    # no basis: too few samples, or a single-replica fleet
    assert h.delay_s(lambda: {}) is None
    clock[0] += HedgePolicy.REFRESH_S
    assert h.delay_s(lambda: {0: (100, 10.0, 20.0)}) is None
    clock[0] += HedgePolicy.REFRESH_S
    snap = {0: (50, 10.0, 20.0), 1: (50, 12.0, 40.0)}
    # delay = FACTOR x median p95, in seconds
    assert h.delay_s(lambda: snap) == pytest.approx(
        30.0 * HedgePolicy.FACTOR / 1e3)
    # cached within REFRESH_S: a changed snapshot is not consulted
    assert h.delay_s(lambda: {}) == pytest.approx(
        30.0 * HedgePolicy.FACTOR / 1e3)
    # bucket: BURST takes, then dry until fed; put_back refunds
    for _ in range(int(HedgePolicy.BURST)):
        assert h.take()
    assert not h.peek() and not h.take()
    h.put_back()
    assert h.take()
    for _ in range(int(1.0 / HedgePolicy.RATE)):
        h.feed()                             # ~20 successes = 1 token
    assert h.take()


def test_retry_budget_spends_and_refills():
    b = RetryBudget(capacity=3.0, refill=0.5)
    assert [b.try_retry() for _ in range(4)] == [True, True, True, False]
    b.success()
    assert not b.try_retry()                 # 0.5 < a whole token
    b.success()
    assert b.try_retry() and not b.try_retry()
    # refill never climbs past capacity
    for _ in range(100):
        b.success()
    assert b.tokens == pytest.approx(3.0)


# ---------------------------------------------- gateway on injected transport

def _bare_gateway(transport, **cfg_kw) -> Gateway:
    kw = dict(name="g", image="img", deadlineMs=2000, maxQueue=8)
    kw.update(cfg_kw)
    cfg = GatewayConfig(**kw)
    return Gateway(cfg, services=None, intents=None, transport=transport)


def _ready_replica(name, idx, port, slots=2) -> Replica:
    r = Replica(name, idx)
    r.state = READY
    r.slots = slots
    r.host_port = port
    return r


def _seed_digests(gw, rows, ms=10.0, n=20):
    for row in rows:
        for _ in range(n):
            gw.lat_store.fold(row, ms)


def test_gateway_ejection_tick_penalizes_outlier_and_probes_readmit():
    """_eval_eject moves the slow replica into probation; the picker
    then avoids it while healthy capacity exists, routes a trickle probe
    when one comes due, and N fast probe completions re-admit it with
    its gray-era digest history dropped."""
    ports = []

    def transport(port, method, path, body, timeout):
        ports.append(port)
        return 200, b'{"code":200,"msg":"ok","data":{}}'

    gw = _bare_gateway(transport)
    clock = [1000.0]
    gw.probation = ProbationTracker(now=lambda: clock[0])
    gw.replicas = {"a": _ready_replica("a", 0, 1001),
                   "b": _ready_replica("b", 1, 1002),
                   "c": _ready_replica("c", 2, 1003)}
    _seed_digests(gw, rows=(0, 1), ms=10.0)
    _seed_digests(gw, rows=(2,), ms=800.0)   # the gray replica (row 2)
    gw._eval_eject()
    assert gw.probation.contains("c") and gw.probation.kind("c") == "latency"
    assert gw.ejections == 1
    assert gw._fleet_median_ms == pytest.approx(10.0, rel=0.5)
    # re-running the tick is idempotent (already-counted, no re-eject)
    gw._eval_eject()
    assert gw.ejections == 1
    # routing: penalized — requests avoid "c" while a/b have slots
    for _ in range(6):
        gw.forward(b"{}")
    assert 1003 not in ports
    # a due probe on the idle ejected replica wins the pick outright
    clock[0] += tailtolerance.PROBE_INTERVAL_S + 0.01
    gw.forward(b"{}")
    assert ports[-1] == 1003
    # two more due probes (fast completions under the 3x-median bar,
    # via the floor since median is ~10ms) re-admit and reset the row
    for _ in range(tailtolerance.PROBE_PASSES - 1):
        clock[0] += tailtolerance.PROBE_INTERVAL_S + 0.01
        gw.forward(b"{}")
    assert not gw.probation.contains("c")
    assert gw.probation_passes == 1
    assert 2 not in gw.lat_store.snapshot()  # gray-era history dropped


def test_gateway_failed_replica_heals_without_scale_cycle():
    """The PR 19 regression fix: a transport-strike FAILED replica used
    to be terminal until an autoscaler stop/start recycled it. It now
    heals through the probation probe path — back to READY with zero
    scale events."""
    dead = [True]
    calls = []

    def transport(port, method, path, body, timeout):
        calls.append(port)
        if port == 1001 and dead[0]:
            raise ConnectionRefusedError("replica gone")
        return 200, b'{"code":200,"msg":"ok","data":{}}'

    gw = _bare_gateway(transport)
    clock = [5000.0]
    gw.probation = ProbationTracker(now=lambda: clock[0])
    gw.replicas = {"sick": _ready_replica("sick", 0, 1001, slots=4),
                   "live": _ready_replica("live", 1, 1002, slots=4)}
    for _ in range(Gateway.MAX_FAILURES + 1):
        status, _ = gw.forward(b"{}")
        assert status == 200
    assert gw.replicas["sick"].state is FAILED
    assert gw.probation.kind("sick") == "failed"
    # FAILED no longer serves (and is not probed before its interval)
    calls.clear()
    gw.forward(b"{}")
    assert 1001 not in calls
    # the replica recovers; due probes route to it and heal it
    dead[0] = False
    for _ in range(tailtolerance.PROBE_PASSES):
        clock[0] += tailtolerance.PROBE_INTERVAL_S + 0.01
        gw.forward(b"{}")
    assert gw.replicas["sick"].state is READY
    assert not gw.probation.contains("sick")
    assert gw.scale_ups == 0 and gw.scale_downs == 0
    # and it serves plain traffic again
    calls.clear()
    for _ in range(8):
        gw.forward(b"{}")
    assert 1001 in calls


def test_gateway_hedge_first_wins_and_loser_slot_released():
    """The primary outlives the digest-derived hedge delay; the
    duplicate on the other replica finishes first and wins, the hedge
    counters move, and BOTH slots are back (release-on-completion)."""
    release_slow = threading.Event()

    def transport(port, method, path, body, timeout):
        if port == 1001:
            release_slow.wait(5)
            return 200, b'{"code":200,"msg":"slow","data":{}}'
        return 200, b'{"code":200,"msg":"fast","data":{}}'

    gw = _bare_gateway(transport, deadlineMs=8000)
    gw.replicas = {"a": _ready_replica("a", 0, 1001),
                   "b": _ready_replica("b", 1, 1002)}
    _seed_digests(gw, rows=(0, 1), ms=10.0)  # hedge delay ~= 15ms
    status, payload = gw.forward(b"{}")
    assert status == 200 and b"fast" in payload
    assert gw.hedges == 1 and gw.hedge_wins == 1
    release_slow.set()
    deadline = time.time() + 5
    while time.time() < deadline:
        with gw._cond:
            if all(r.inflight == 0 for r in gw.replicas.values()):
                break
        time.sleep(0.01)
    with gw._cond:
        assert all(r.inflight == 0 for r in gw.replicas.values())


def test_gateway_hedge_bucket_empty_no_duplicate():
    """A drained hedge token bucket means NO duplicate dispatches — the
    ~5% added-load cap is the bucket, so an empty bucket must degrade to
    plain forwarding, not queue hedges."""
    seen = []

    def transport(port, method, path, body, timeout):
        seen.append(port)
        time.sleep(0.05)                     # well past the ~15ms delay
        return 200, b'{"code":200,"msg":"ok","data":{}}'

    gw = _bare_gateway(transport, deadlineMs=8000)
    gw.replicas = {"a": _ready_replica("a", 0, 1001),
                   "b": _ready_replica("b", 1, 1002)}
    _seed_digests(gw, rows=(0, 1), ms=10.0)
    while gw.hedge.take():
        pass                                 # drain the bucket
    status, _ = gw.forward(b"{}")
    assert status == 200
    assert gw.hedges == 0 and len(seen) == 1


def test_gateway_retry_budget_sheds_long_before_deadline():
    """Replicas hard-down with a LONG deadline: the old behavior retried
    until the deadline; the budget sheds as soon as the bucket drains,
    and the counter moves."""
    attempts = []

    def transport(port, method, path, body, timeout):
        attempts.append(port)
        raise ConnectionRefusedError("down")

    gw = _bare_gateway(transport, deadlineMs=60000)
    gw.retry_budget = RetryBudget(capacity=3.0, refill=0.1)
    gw.replicas = {"a": _ready_replica("a", 0, 1001, slots=4),
                   "b": _ready_replica("b", 1, 1002, slots=4)}
    t0 = time.monotonic()
    with pytest.raises(xerrors.GatewayRetryBudgetError) as ei:
        gw.forward(b"{}")
    assert time.monotonic() - t0 < 5.0       # nowhere near the 60s deadline
    assert len(attempts) == 4                # first try + 3 budgeted retries
    assert gw.retry_budget_exhausted == 1
    assert ei.value.retry_after > 0


def test_gateway_describe_tail_block_and_kill_switches(monkeypatch):
    gw = _bare_gateway(lambda *a: (200, b"{}"))
    d = gw.describe()
    tail = d["tailTolerance"]
    assert tail["ejectEnabled"] and tail["hedgeEnabled"]
    assert tail["retryBudgetEnabled"]
    assert tail["ejections"] == 0 and tail["hedges"] == 0
    assert tail["retryTokens"] == pytest.approx(RetryBudget.CAPACITY)
    # kill switches: TDAPI_GW_*=0 disables each policy independently
    monkeypatch.setenv(tailtolerance.EJECT_ENV, "0")
    monkeypatch.setenv(tailtolerance.HEDGE_ENV, "0")
    monkeypatch.setenv(tailtolerance.RETRY_BUDGET_ENV, "0")
    gw2 = _bare_gateway(lambda *a: (200, b"{}"))
    t2 = gw2.describe()["tailTolerance"]
    assert not (t2["ejectEnabled"] or t2["hedgeEnabled"]
                or t2["retryBudgetEnabled"])
    # with ejection off, _eval_eject never moves anyone
    gw2.replicas = {"a": _ready_replica("a", 0, 1001),
                    "b": _ready_replica("b", 1, 1002)}
    _seed_digests(gw2, rows=(0,), ms=10.0)
    _seed_digests(gw2, rows=(1,), ms=900.0)
    gw2._eval_eject()
    assert len(gw2.probation) == 0 and gw2.ejections == 0


def test_tail_catalog_registration():
    from gpu_docker_api_tpu.obs.names import EVENT_OPS, METRIC_NAMES
    assert {"gateway.ejected", "gateway.probation_pass",
            "gateway.hedged"} <= EVENT_OPS
    assert {"tdapi_gateway_ejections_total",
            "tdapi_gateway_hedges_total",
            "tdapi_gateway_hedge_wins_total",
            "tdapi_gateway_retry_budget_exhausted_total"} <= METRIC_NAMES


# ------------------------------------------------------------------- wire

def test_budget_exhaustion_answers_503_with_retry_after(tmp_path):
    """Over live REST: a browned-out gateway answers 503 + Retry-After
    (bounded shed), never an unbounded retry loop."""
    from gpu_docker_api_tpu.server.app import App
    from gpu_docker_api_tpu.topology import make_topology

    app = App(state_dir=str(tmp_path / "state"), backend="mock",
              addr="127.0.0.1:0", port_range=(46400, 46500),
              topology=make_topology("v4-16"), api_key="", cpu_cores=8,
              store_maint_records=0)
    app.start()
    try:
        app.gateways.create(GatewayConfig(
            name="gw", image="img", cmd=["serve"], minReplicas=2,
            maxReplicas=2, readiness="running", scaleDownIdleS=3600,
            deadlineMs=60000, maxQueue=16))
        gw = app.gateways.get("gw")
        deadline = time.time() + 10
        while time.time() < deadline and sum(
                1 for r in gw.replicas.values()
                if r.state is READY) < 2:
            time.sleep(0.02)

        def transport(port, method, path, body, timeout):
            raise ConnectionRefusedError("brownout")

        gw._transport = transport
        gw.retry_budget = RetryBudget(capacity=2.0, refill=0.1)
        req = urllib.request.Request(
            f"http://{app.address}/api/v1/gateways/gw/generate",
            method="POST", data=b'{"tokens": [[1]]}',
            headers={"Content-Type": "application/json"})
        t0 = time.monotonic()
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert time.monotonic() - t0 < 10.0
        err = ei.value
        assert err.code == 503
        assert err.headers.get("Retry-After") is not None
        body = json.loads(err.read())
        assert body["code"] == 503
        # /healthz surfaces the tail-tolerance block per gateway
        hz = json.loads(urllib.request.urlopen(
            f"http://{app.address}/api/v1/healthz", timeout=10).read())
        tail = hz["data"]["gateways"]["gw"]["tailTolerance"]
        assert tail["retryBudgetExhausted"] >= 1
    finally:
        app.stop()


# --------------------------------------------- worker-tier parity over shm

workers = pytest.importorskip("gpu_docker_api_tpu.server.workers")

needs_workers = pytest.mark.skipif(
    not workers.available(),
    reason="worker tier unavailable (no Linux SO_REUSEPORT / native core)")


@pytest.fixture()
def state():
    st = workers.SharedRouterState(create=True)
    yield st
    st.close(unlink=True)


def _publish(st, n_reps, slots=2, name="g", deadline_ms=3000):
    st.publish([{"name": name, "maxQueue": 16, "deadlineMs": deadline_ms,
                 "replicas": [{"port": 1001 + i, "slots": slots,
                               "ready": True} for i in range(n_reps)]}])


@needs_workers
def test_worker_tier_eject_parity_with_shared_decision(state, monkeypatch):
    """Both tiers run tailtolerance.eject_set over the same shm digest
    cells. Fold a fleet with one gray row through the shm store, then
    assert the worker router's recomputed eject set, the shm-backed
    store's snapshot-driven decision (what a daemon gateway bound to the
    tier would compute), and the pure function over raw cell reads all
    agree. The trickle-probe carve-out is pinned separately
    (test_trickle_allow_deterministic_across_workers) — silenced here so
    an open probe window can't race the equality."""
    monkeypatch.setattr(tailtolerance, "trickle_allow",
                        lambda rows, now, **kw: None)
    _publish(state, 4)
    for r in range(4):
        for _ in range(20):
            state.fold_replica_lat(0, r, 700.0 if r == 3 else 10.0)
    # the pure decision over raw cell reads
    stats = []
    for r in range(4):
        cells = state.read_replica_lat(0, r)
        assert cells is not None
        stats.append((r, cells[2] / 1e3, cells[0]))
    want = tailtolerance.eject_set(stats, fleet=4)
    assert want == {3}
    # worker tier: the router's recomputed probation
    router = workers.WorkerRouter(state, 0,
                                  transport=lambda *a: (200, b"{}"))
    _, roster = state.read_roster()
    assert router._ejected(roster["g"]) == want
    # daemon tier: ShmLatencyStore.snapshot over the SAME cells feeds
    # the same eject_set call gateway._eval_eject makes
    shm_store = workers.ShmLatencyStore(state, "g")
    snap = shm_store.snapshot()
    gw_stats = [(row, snap[row][2], snap[row][0]) for row in sorted(snap)]
    assert tailtolerance.eject_set(gw_stats, fleet=4) == want
    # and the penalty is live: traffic avoids the gray replica while
    # healthy slots exist
    seen = []

    def transport(port, method, path, body, timeout):
        seen.append(port)
        return 200, b'{"code":200,"msg":"ok","data":{}}'

    router2 = workers.WorkerRouter(state, 0, transport=transport)
    for _ in range(6):
        router2.forward("g", b"{}")
    assert 1004 not in seen


@needs_workers
def test_worker_tier_hedge_and_budget_counters_on_shm(state):
    """The worker router's hedge increments the gateway's shared-memory
    hedge words (daemon-visible), the duplicate wins first, and a
    drained retry budget sheds GatewayRetryBudgetError with the shm
    exhaustion counter bumped."""
    release_slow = threading.Event()

    def transport(port, method, path, body, timeout):
        if port == 1001:
            release_slow.wait(5)
            return 200, b'{"code":200,"msg":"slow","data":{}}'
        return 200, b'{"code":200,"msg":"fast","data":{}}'

    _publish(state, 2, deadline_ms=8000)
    for r in range(2):
        for _ in range(20):
            state.fold_replica_lat(0, r, 10.0)   # hedge delay ~= 15ms
    router = workers.WorkerRouter(state, 0, transport=transport)
    status, payload = router.forward("g", b"{}")
    assert status == 200 and b"fast" in payload
    release_slow.set()
    c = state.gateway_counters(0)
    assert c["hedges"] == 1 and c["hedgeWins"] == 1
    deadline = time.time() + 5
    while time.time() < deadline and sum(c["inflight"]) != 0:
        time.sleep(0.01)
        c = state.gateway_counters(0)
    assert sum(c["inflight"]) == 0           # loser's claim released
    # retry budget: hard-down replicas shed once the bucket drains
    def down(port, method, path, body, timeout):
        raise ConnectionRefusedError("down")

    _publish(state, 2, deadline_ms=60000)
    router2 = workers.WorkerRouter(state, 0, transport=down)
    router2._budgets[0] = RetryBudget(capacity=2.0, refill=0.1)
    t0 = time.monotonic()
    with pytest.raises(xerrors.GatewayRetryBudgetError):
        router2.forward("g", b"{}")
    assert time.monotonic() - t0 < 10.0
    assert state.gateway_counters(0)["retryBudgetExhausted"] == 1
