"""Heterogeneity-aware placement + defragmenter sweep.

Model half: the pure objective algebra (max-throughput routes by profile,
cost prefers cheap generations, finish-time fairness discounts full
pools), the declared <- fitted <- baseline profile merge, and a seeded
randomized churn over a mixed v4-32 + v5e-8 ``FleetModel`` asserting the
scored place/claim pipeline never double-grants or leaks.

Live half: on a real ``App``, a seeded churn of run/patch/stop/delete is
driven into the canonical fragmentation-blocked state (free chips
suffice, no free box), then the defragmenter must restore the largest
contiguous box, the previously-infeasible gang must admit, every
migration must be quiesced with stepsLost == 0 (tenants opt in via
TDAPI_QUIESCE=1), and the final bitmap must exactly match the store —
zero leaks.

`make verify-placement` runs exactly this marker.
"""

import json
import random

import pytest

from gpu_docker_api_tpu import faults, xerrors
from gpu_docker_api_tpu.defrag import Defragmenter
from gpu_docker_api_tpu.dtos import (
    ContainerRun, PatchRequest, StoredContainerInfo, TpuPatch)
from gpu_docker_api_tpu.meshplan import PlanSpec
from gpu_docker_api_tpu.placement import (
    POLICIES, Candidate, FleetModel, obj_cost, obj_finish_time_fairness,
    obj_first_fit, obj_max_throughput)
from gpu_docker_api_tpu.schedulers import TpuScheduler
from gpu_docker_api_tpu.schedulers.base import FREE
from gpu_docker_api_tpu.server.app import App
from gpu_docker_api_tpu.server.codes import ResCode
from gpu_docker_api_tpu.server.http import Request
from gpu_docker_api_tpu.topology import make_topology

pytestmark = pytest.mark.placement

GANG_PLAN = {"dp": 2, "fsdp": 2, "tp": 2}      # 8 chips


@pytest.fixture(autouse=True)
def _disarmed():
    faults.disarm_all()
    faults.disarm_faults()
    yield
    faults.disarm_all()
    faults.disarm_faults()


def make_fleet(policy="max_throughput"):
    return FleetModel({
        "v4": TpuScheduler(topology=make_topology("v4-32")),    # 16 chips
        "v5e": TpuScheduler(topology=make_topology("v5e-8")),   # 8 chips
    }, policy=policy)


def make_app(tmp_path, policy="max_throughput"):
    return App(state_dir=str(tmp_path / "state"), backend="mock",
               addr="127.0.0.1:0", port_range=(48000, 48100),
               topology=make_topology("v4-32"), api_key="", cpu_cores=16,
               store_maint_records=0, placement_policy=policy)


def stored_containers(app):
    app.wq.join()
    return {kv.key.rsplit("/", 1)[1]: StoredContainerInfo.deserialize(kv.value)
            for kv in app.client.range("containers")}


def assert_no_leaks(app):
    """Scheduler bitmap == stored specs, both directions."""
    stored = stored_containers(app)
    exp = {}
    for name, info in stored.items():
        if info.resourcesReleased:
            continue
        for c in info.spec.tpu_chips:
            exp[c] = name
    got = {c: o for c, o in app.tpu.status.items() if o is not FREE}
    assert got == exp, f"bitmap {got} != store {exp}"


# ---- objective algebra (pure functions over snapshots) ----

def test_max_throughput_routes_by_profile():
    fleet = make_fleet()
    snap = fleet.snapshot()
    cands = fleet.candidates_for(2)
    assert {c.pool for c in cands} == {"v4", "v5e"}
    embed = {"profile": {"v4": 1.0, "v5e": 0.2}, "n": 2}
    dense = {"profile": {"v4": 0.5, "v5e": 1.5}, "n": 2}
    best_e = max(cands, key=lambda c: obj_max_throughput(snap, c, embed))
    best_d = max(cands, key=lambda c: obj_max_throughput(snap, c, dense))
    assert best_e.pool == "v4" and best_d.pool == "v5e"


def test_cost_prefers_cheap_generation_for_flat_profile():
    fleet = make_fleet()
    snap = fleet.snapshot()
    cands = fleet.candidates_for(2)
    ctx = {"profile": {"v4": 1.0, "v5e": 1.0}, "n": 2}
    best = max(cands, key=lambda c: obj_cost(snap, c, ctx))
    assert best.pool == "v5e"          # same throughput at 0.37x the cost


def test_fairness_discounts_nearly_full_pool():
    fleet = make_fleet()
    # fill v4 down to 2 free chips: the fast pool has no headroom left
    fleet.pools["v4"].apply(14, "hog")
    snap = fleet.snapshot()
    cands = fleet.candidates_for(2)
    ctx = {"profile": {"v4": 1.0, "v5e": 0.9}, "n": 2}
    best_thr = max(cands, key=lambda c: obj_max_throughput(snap, c, ctx))
    best_fair = max(cands,
                    key=lambda c: obj_finish_time_fairness(snap, c, ctx))
    assert best_thr.pool == "v4"       # raw throughput still says v4
    assert best_fair.pool == "v5e"     # fairness routes around the queue


def test_first_fit_policy_reproduces_naive_pick():
    fleet = make_fleet(policy="first_fit")
    pool, chips = fleet.place(2, "w0")
    # deterministic tiebreak: lexically-first pool, lowest chips
    assert pool == "v4" and chips == [0, 1]


def test_objectives_are_pure():
    """Objectives must not touch schedulers: scoring a synthetic candidate
    against a synthetic snapshot works with no pools at all."""
    from gpu_docker_api_tpu.placement import FleetSnapshot, PoolView
    snap = FleetSnapshot(pools=(PoolView(
        name="x", generation="v4", accelerator_type="v4-32",
        total_chips=16, free_chips=16, free_quanta=64, cordoned=0,
        share_split=0, largest_free_box=16, fragmentation=0.0),))
    cand = Candidate(pool="x", generation="v4", chips=(0, 1), dims=(2, 1, 1),
                     span=1, surface=10, ext_free=6, host_splits=0)
    ctx = {"profile": {}, "n": 2}
    for name, obj in sorted(POLICIES.items()):
        s1, s2 = obj(snap, cand, ctx), obj(snap, cand, ctx)
        assert s1 == s2, name          # deterministic, side-effect free
    assert obj_first_fit(snap, cand, ctx) == 0.0


# ---- profile merge: baselines <- fitted <- declared ----

def test_profile_defaults_to_generation_baselines():
    fleet = make_fleet()
    prof = fleet.profile_for("w")
    assert set(prof) == {"v4", "v5e"}
    assert prof["v4"] == 1.0 and prof["v5e"] == pytest.approx(0.72)


def test_single_generation_observations_never_perturb_baselines():
    fleet = make_fleet()
    for _ in range(8):
        fleet.observe_step_time("w", "v4", 100.0)
    assert fleet.profile_for("w")["v4"] == 1.0     # no cross-gen ratio yet


def test_cross_generation_fit_reanchors_ratios():
    fleet = make_fleet()
    for _ in range(4):
        fleet.observe_step_time("w", "v4", 100.0)   # 10 steps/s
        fleet.observe_step_time("w", "v5e", 50.0)   # 20 steps/s
    prof = fleet.profile_for("w")
    # anchored at v5e (tie on samples -> lexically-max generation): the
    # baseline frame keeps v5e at 0.72 and scales v4 by the observed ratio
    assert prof["v5e"] == pytest.approx(0.72)
    assert prof["v4"] == pytest.approx(0.36)


def test_declared_profile_wins_over_fitted():
    fleet = make_fleet()
    for _ in range(4):
        fleet.observe_step_time("w", "v4", 100.0)
        fleet.observe_step_time("w", "v5e", 50.0)
    fleet.declare_profile("w", {"v4": 3.0})
    prof = fleet.profile_for("w")
    assert prof["v4"] == 3.0 and prof["v5e"] == pytest.approx(0.72)


# ---- place: score -> claim commit path ----

def test_place_commits_scored_winner_and_counts():
    fleet = make_fleet()
    pool, chips = fleet.place(2, "dense",
                              profile={"v4": 0.5, "v5e": 1.5})
    assert pool == "v5e" and len(chips) == 2
    assert fleet.pools["v5e"].status[chips[0]] == "dense"
    assert fleet.placements_total == 1 and fleet.scored_total > 0
    d = fleet.describe()
    assert d["policy"] == "max_throughput"
    assert {p["name"] for p in d["pools"]} == {"v4", "v5e"}


def test_place_raises_when_no_pool_fits():
    fleet = make_fleet()
    with pytest.raises(xerrors.TpuNotEnoughError):
        fleet.place(32, "huge")


def test_place_respects_mesh_plan_geometry():
    fleet = make_fleet()
    plan = PlanSpec.from_json(GANG_PLAN)
    pool, chips = fleet.place(8, "gang", plan=plan)
    assert len(chips) == 8
    assert fleet.pools[pool].topology.is_connected(chips)


# ---- randomized churn: mixed-fleet placement invariants ----

def test_churn_mixed_fleet_never_double_grants_or_leaks():
    rng = random.Random(20)
    fleet = make_fleet()
    profiles = [None, {"v4": 1.0, "v5e": 0.3}, {"v4": 0.4, "v5e": 1.2}]
    live = {}                           # owner -> (pool, chips)
    seq = 0
    for _ in range(120):
        if live and rng.random() < 0.4:
            owner = rng.choice(sorted(live))
            pool, chips = live.pop(owner)
            fleet.pools[pool].restore(chips, owner)
        else:
            seq += 1
            owner = f"w{seq}"
            try:
                pool, chips = fleet.place(
                    rng.choice([1, 1, 2, 4]), owner,
                    profile=rng.choice(profiles),
                    policy=rng.choice(sorted(POLICIES)))
            except xerrors.TpuNotEnoughError:
                continue
            live[owner] = (pool, chips)
        # invariant: each pool's bitmap is exactly the live grants
        for pname, sched in fleet.pools.items():
            exp = {c: o for o, (p, cs) in live.items()
                   for c in cs if p == pname}
            got = {c: o for c, o in sched.status.items() if o is not FREE}
            assert got == exp
    for owner, (pool, chips) in live.items():
        fleet.pools[pool].restore(chips, owner)
    for sched in fleet.pools.values():
        cv = sched.capacity_view()
        assert cv["freeChips"] == cv["totalChips"]
        assert cv["largestFreeBox"] == cv["totalChips"]   # contiguity back
        assert cv["fragmentation"] == 0.0


# ---- defragmenter unit guards (model-level, no migrations needed) ----

def _blocked_fleet():
    """Single v4-32 pool with free chips {0..3, 12..15}: 8 free, no free
    8-box (every 8-box crosses the occupied middle), one-chip tenants."""
    fleet = FleetModel(
        {"v4": TpuScheduler(topology=make_topology("v4-32"))})
    sched = fleet.pools["v4"]
    for i in range(16):
        sched.claim([i], f"t{i}")
    for i in (0, 1, 2, 3, 12, 13, 14, 15):
        sched.restore([i], f"t{i}")
    cv = sched.capacity_view()
    assert cv["freeChips"] == 8 and cv["largestFreeBox"] < 8, cv
    return fleet


def test_defrag_diagnose_flags_fragmentation_blocked_pool():
    fleet = _blocked_fleet()
    d = Defragmenter(fleet, replicasets=None)
    blocked = d.diagnose(8, PlanSpec.from_json(GANG_PLAN))
    assert [b["pool"] for b in blocked] == ["v4"]
    assert d.diagnose(4) == []          # a free 4-box exists
    assert d.diagnose(16) == []         # genuinely out of capacity


def test_defrag_eviction_plan_is_cheapest_and_budgeted():
    fleet = _blocked_fleet()
    d = Defragmenter(fleet, replicasets=None)
    plan = d.plan_eviction("v4", 8, PlanSpec.from_json(GANG_PLAN))
    assert plan is not None
    assert plan["movedChips"] == 4      # 4 one-chip tenants off the box
    assert len(plan["evict"]) == 4
    # a budget below the cheapest plan denies instead of thrashing
    tight = Defragmenter(fleet, replicasets=None, budget=3)
    assert tight.plan_eviction("v4", 8) is None


def test_defrag_respects_federation_ownership():
    fleet = _blocked_fleet()
    d = Defragmenter(fleet, replicasets=None, owns=lambda name: False)
    assert d.plan_eviction("v4", 8) is None     # peers' tenants: hands off


# ---- live churn: defrag restores contiguity, gang admits, zero loss ----

def test_churn_then_defrag_admits_gang_with_zero_loss(tmp_path):
    rng = random.Random(7)
    app = make_app(tmp_path)
    try:
        seq = 0
        live = []
        for _ in range(40):
            op = rng.choice(["run", "run", "run", "stop", "delete", "patch"])
            if op == "run" or not live:
                seq += 1
                name = f"c{seq}"
                try:
                    app.replicasets.run_container(ContainerRun(
                        imageName="img", replicaSetName=name,
                        tpuCount=rng.choice([1, 1, 2]),
                        env=["TDAPI_QUIESCE=1"]))
                except xerrors.TpuNotEnoughError:
                    continue
                live.append(name)
            elif op == "stop":
                app.replicasets.stop_container(live.pop(
                    rng.randrange(len(live))))
            elif op == "delete":
                app.replicasets.delete_container(live.pop(
                    rng.randrange(len(live))))
            else:
                try:
                    app.replicasets.patch_container(
                        rng.choice(live), PatchRequest(
                            tpuPatch=TpuPatch(tpuCount=rng.choice([1, 2]))))
                except (xerrors.TpuNotEnoughError,
                        xerrors.NoPatchRequiredError):
                    continue
            assert_no_leaks(app)
        # drive into the canonical fragmentation-blocked state: clear the
        # churn survivors, fill with 16 one-chip quiesce-enabled tenants,
        # free the outer z-slabs (chips 0-3 and 12-15)
        for name in live:
            app.replicasets.delete_container(name)
        for i in range(16):
            app.replicasets.run_container(ContainerRun(
                imageName="img", replicaSetName=f"t{i}", tpuCount=1,
                env=["TDAPI_QUIESCE=1"]))
        owner_of = {c: o for c, o in app.tpu.status.items() if o}
        for c in (0, 1, 2, 3, 12, 13, 14, 15):
            app.replicasets.delete_container(owner_of[c])
        cv = app.tpu.capacity_view()
        assert cv["freeChips"] == 8 and cv["largestFreeBox"] < 8, cv
        plan = PlanSpec.from_json(GANG_PLAN)
        with pytest.raises(xerrors.TpuNotEnoughError):
            app.replicasets.run_container(ContainerRun(
                imageName="img", replicaSetName="gang", tpuCount=8,
                meshPlan=GANG_PLAN))
        rep = app.defrag.run_for(8, plan)
        assert rep["opened"], rep
        # every migration quiesced at its exact step: zero training loss
        assert rep["migrations"], "defrag must have moved tenants"
        for item in rep["migrations"]:
            assert item["quiesced"] is True
            assert item["stepsLost"] == 0
        assert rep["movedChips"] <= 8   # within the n-chip budget
        # contiguity restored: the largest free box fits the gang again
        assert app.tpu.capacity_view()["largestFreeBox"] >= 8
        app.replicasets.run_container(ContainerRun(
            imageName="img", replicaSetName="gang", tpuCount=8,
            meshPlan=GANG_PLAN, env=["TDAPI_QUIESCE=1"]))
        gang = stored_containers(app)["gang"]
        assert len(gang.spec.tpu_chips) == 8
        assert app.tpu.topology.is_connected(list(gang.spec.tpu_chips))
        assert_no_leaks(app)
        # a second run on the now-satisfied shape is a clean deny, not a
        # migration storm
        rep2 = app.defrag.run_for(8, plan)
        assert rep2["denied"] == "not fragmentation-blocked"
    finally:
        app.stop()


def test_run_container_notes_infeasible_gang_for_background_defrag(tmp_path):
    app = make_app(tmp_path)
    try:
        for i in range(16):
            app.replicasets.run_container(ContainerRun(
                imageName="img", replicaSetName=f"t{i}", tpuCount=1))
        owner_of = {c: o for c, o in app.tpu.status.items() if o}
        for c in (0, 1, 2, 3, 12, 13, 14, 15):
            app.replicasets.delete_container(owner_of[c])
        req = Request("POST", "/api/v1/containers/run", {},
                      json.dumps({"imageName": "img",
                                  "replicaSetName": "gang",
                                  "tpuCount": 8,
                                  "meshPlan": GANG_PLAN}).encode(), {}, {})
        resp = app.h_run(req)
        assert int(resp.code) == int(ResCode.ContainerTpuNotEnough)
        assert app.defrag.describe()["pending"] == 1
    finally:
        app.stop()


def test_http_placement_surface_and_client_helpers(tmp_path):
    from gpu_docker_api_tpu.client import ApiClient
    app = make_app(tmp_path)
    app.start()
    c = ApiClient("127.0.0.1", app.server.port)
    try:
        st = c.placement_status()
        assert st["policy"] == "max_throughput"
        assert st["policyActive"] is True
        assert st["pools"][0]["largestFreeBox"] == 16
        assert c.defrag_status()["runsTotal"] == 0
        for i in range(16):
            app.replicasets.run_container(ContainerRun(
                imageName="img", replicaSetName=f"t{i}", tpuCount=1,
                env=["TDAPI_QUIESCE=1"]))
        owner_of = {ch: o for ch, o in app.tpu.status.items() if o}
        for ch in (0, 1, 2, 3, 12, 13, 14, 15):
            app.replicasets.delete_container(owner_of[ch])
        st = c.placement_status()
        assert st["pools"][0]["freeChips"] == 8
        assert st["pools"][0]["largestFreeBox"] < 8
        assert st["pools"][0]["fragmentation"] > 0
        rep = c.run_defrag(8, GANG_PLAN)
        assert rep["opened"] is True and rep["stepsLost"] == 0
        assert c.defrag_status()["runsTotal"] == 1
        assert c.placement_status()["pools"][0]["largestFreeBox"] >= 8
    finally:
        c.close()
        app.stop()
