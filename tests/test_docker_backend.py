"""DockerBackend against a fake dockerd: a real HTTP server on a real unix
socket, replaying an in-memory docker API (VERDICT r1 item 4 — the docker
adapter must execute without dockerd in the image).

Covers the full adapter surface: payload rendering (TPU devices + vfio,
libtpu ro-bind, lxcfs /proc virtualization binds, StorageOpt rootfs quota,
port bindings, env merge), lifecycle endpoints, exec with the 8-byte framed
stream, inspect mapping, and volumes with driver-opts quota.
"""

import json
import socketserver
import threading
from http.server import BaseHTTPRequestHandler
from urllib.parse import urlparse, parse_qs

import pytest

from gpu_docker_api_tpu.backend import docker as docker_mod
from gpu_docker_api_tpu.backend.docker import DockerBackend, DockerError
from gpu_docker_api_tpu.dtos import ContainerSpec


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    # the fake's state lives on the server object
    @property
    def fake(self):
        return self.server.fake

    def log_message(self, *a):  # silence
        pass

    def address_string(self):  # unix socket has no peer address
        return "uds"

    def _body(self):
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length) if length else b""
        try:
            return json.loads(raw) if raw else None
        except json.JSONDecodeError:
            return raw

    def _send(self, code, payload=b"", ctype="application/json"):
        if isinstance(payload, (dict, list)):
            payload = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _route(self, method):
        u = urlparse(self.path)
        path = u.path
        q = {k: v[0] for k, v in parse_qs(u.query).items()}
        body = self._body()
        self.fake.requests.append((method, path, q, body))
        handler = self.fake.route(method, path, q, body)
        if handler is None:
            self._send(404, {"message": f"not found: {method} {path}"})
        else:
            code, payload = handler
            self._send(code, payload)

    def do_GET(self):
        self._route("GET")

    def do_POST(self):
        self._route("POST")

    def do_DELETE(self):
        self._route("DELETE")


class FakeDockerd:
    """Minimal in-memory docker engine behind a unix socket."""

    def __init__(self, sock_path: str):
        self.requests: list = []
        self.containers: dict[str, dict] = {}
        self.volumes: dict[str, dict] = {}
        self.execs: dict[str, dict] = {}
        self._n = 0

        class _Server(socketserver.ThreadingUnixStreamServer):
            daemon_threads = True
            allow_reuse_address = True

        self.server = _Server(sock_path, _Handler)
        self.server.fake = self
        self.thread = threading.Thread(target=self.server.serve_forever,
                                       daemon=True)
        self.thread.start()

    def close(self):
        self.server.shutdown()
        self.server.server_close()

    # ---- routing ----

    def route(self, method, path, q, body):
        if path == "/_ping":
            return 200, b"OK"
        parts = [p for p in path.split("/") if p]  # ['v1.41', 'containers', ..]
        if parts[0].startswith("v1."):
            parts = parts[1:]
        if parts[0] == "containers":
            return self._containers(method, parts, q, body)
        if parts[0] == "exec" and len(parts) == 3:
            return self._exec_start_or_json(method, parts[1], parts[2], body)
        if parts[0] == "volumes":
            return self._volumes(method, parts, body)
        if parts[0] == "commit":
            name = q.get("container", "")
            if name not in self.containers:
                return 404, {"message": "no such container"}
            return 201, {"Id": f"sha256:{name}-committed"}
        return None

    def _containers(self, method, parts, q, body):
        if parts[1:] == ["create"]:
            name = q.get("name", f"anon{self._n}")
            if name in self.containers:
                return 409, {"message": f"Conflict: {name} already in use"}
            self._n += 1
            self.containers[name] = {
                "spec": body, "running": False, "paused": False,
                "exit_code": 0, "id": f"id{self._n:04d}"}
            return 201, {"Id": self.containers[name]["id"]}
        if parts[1:] == ["json"]:  # list
            return 200, [{"Names": [f"/{n}"]} for n in sorted(self.containers)]
        name = parts[1]
        c = self.containers.get(name)
        if c is None:
            return 404, {"message": f"No such container: {name}"}
        rest = parts[2:]
        if method == "DELETE":
            if c["running"] and q.get("force") != "true":
                return 409, {"message": "container is running"}
            del self.containers[name]
            return 204, b""
        if rest == ["json"]:
            return 200, {
                "State": {"Running": c["running"], "Paused": c["paused"],
                          "ExitCode": c["exit_code"], "Pid": 4321},
                "GraphDriver": {"Data": {"UpperDir": f"/var/overlay/{name}/diff"}},
            }
        if rest == ["start"]:
            c["running"] = True
            return 204, b""
        if rest == ["stop"]:
            c["running"] = False
            c["exit_code"] = 137
            return 204, b""
        if rest == ["pause"]:
            c["paused"] = True
            return 204, b""
        if rest == ["restart"]:
            c["running"], c["paused"] = True, False
            return 204, b""
        if rest == ["exec"]:
            self._n += 1
            eid = f"exec{self._n:04d}"
            self.execs[eid] = {"cmd": body.get("Cmd", []), "exit": 0}
            return 201, {"Id": eid}
        return None

    def _exec_start_or_json(self, method, eid, op, body):
        e = self.execs.get(eid)
        if e is None:
            return 404, {"message": "no such exec"}
        if op == "start":
            # docker's multiplexed stream: stdout frame + stderr frame
            out = (" ".join(e["cmd"]) + "\n").encode()
            frame = b"\x01\x00\x00\x00" + len(out).to_bytes(4, "big") + out
            err = b"warn\n"
            frame += b"\x02\x00\x00\x00" + len(err).to_bytes(4, "big") + err
            return 200, frame
        if op == "json":
            return 200, {"ExitCode": e["exit"]}
        return None

    def _volumes(self, method, parts, body):
        if parts[1:] == ["create"]:
            name = body["Name"]
            self.volumes[name] = {"opts": body.get("DriverOpts") or {}}
            return 201, {"Name": name, "Mountpoint": f"/var/volumes/{name}/_data",
                         "Options": self.volumes[name]["opts"]}
        name = parts[1]
        v = self.volumes.get(name)
        if v is None:
            return 404, {"message": f"no such volume: {name}"}
        if method == "DELETE":
            del self.volumes[name]
            return 204, b""
        return 200, {"Name": name, "Mountpoint": "", "Options": v["opts"]}


@pytest.fixture
def fake(tmp_path):
    sock = str(tmp_path / "docker.sock")
    f = FakeDockerd(sock)
    yield f
    f.close()


@pytest.fixture
def backend(fake, tmp_path):
    return DockerBackend(str(tmp_path / "state"), socket_path=fake.server.server_address)


def _spec(**kw):
    d = dict(image="ubuntu:22.04", cmd=["sleep", "30"], env=["FOO=bar"])
    d.update(kw)
    return ContainerSpec(**d)


def test_ping_on_init(fake, backend):
    assert ("GET", "/_ping", {}, None) in fake.requests


def test_create_payload_rendering(fake, backend, tmp_path, monkeypatch):
    # fake host features: vfio groups, libtpu, lxcfs
    vfio = tmp_path / "vfio"
    vfio.mkdir()
    (vfio / "0").touch()
    (vfio / "vfio").touch()
    libtpu = tmp_path / "libtpu.so"
    libtpu.touch()
    lxcfs = tmp_path / "lxcfs"
    (lxcfs / "proc").mkdir(parents=True)
    for f in ("cpuinfo", "meminfo", "uptime"):
        (lxcfs / "proc" / f).touch()
    monkeypatch.setattr(docker_mod, "DEV_VFIO_GLOB", f"{vfio}/*")
    monkeypatch.setattr(docker_mod, "LIBTPU_CANDIDATES", (str(libtpu),))
    monkeypatch.setattr(docker_mod, "LXCFS_DIR", str(lxcfs))

    backend.create("rs-1", _spec(
        devices=["/dev/accel0", "/dev/accel1"],
        tpu_env={"TPU_VISIBLE_CHIPS": "0,1", "TPU_WORKER_ID": "0"},
        binds=["/data:/data"],
        port_bindings={8080: 40001},
        rootfs_quota="30G",
        shm_bytes=256 * 1024 ** 3,
        cpuset="0-3",
        memory_bytes=2 * 1024 ** 3,
        restart_policy="unless-stopped",
    ))
    create = next(r for r in fake.requests if r[1].endswith("/containers/create"))
    assert create[2]["name"] == "rs-1"
    body = create[3]
    assert body["Image"] == "ubuntu:22.04"
    assert "FOO=bar" in body["Env"]
    assert "TPU_VISIBLE_CHIPS=0,1" in body["Env"]
    hc = body["HostConfig"]
    paths = [d["PathOnHost"] for d in hc["Devices"]]
    assert "/dev/accel0" in paths and "/dev/accel1" in paths
    assert str(vfio / "0") in paths and str(vfio / "vfio") in paths
    assert all(d["CgroupPermissions"] == "rwm" for d in hc["Devices"])
    assert f"{libtpu}:{libtpu}:ro" in hc["Binds"]
    assert "/data:/data" in hc["Binds"]
    # lxcfs /proc virtualization (reference replicaset.go:33-40)
    assert f"{lxcfs}/proc/cpuinfo:/proc/cpuinfo:rw" in hc["Binds"]
    assert f"{lxcfs}/proc/meminfo:/proc/meminfo:rw" in hc["Binds"]
    # swaps wasn't materialized on this "host" -> not bound
    assert not any("swaps" in b for b in hc["Binds"])
    assert hc["StorageOpt"] == {"size": "30G"}
    assert hc["ShmSize"] == 256 * 1024 ** 3
    assert hc["PortBindings"] == {"8080/tcp": [{"HostPort": "40001"}]}
    assert hc["CpusetCpus"] == "0-3"
    assert hc["Memory"] == 2 * 1024 ** 3
    assert hc["RestartPolicy"] == {"Name": "unless-stopped"}
    assert body["ExposedPorts"] == {"8080/tcp": {}}


def test_lifecycle_endpoints(fake, backend):
    backend.create("rs-1", _spec())
    backend.start("rs-1")
    assert backend.inspect("rs-1").running
    backend.pause("rs-1")
    assert backend.inspect("rs-1").paused
    backend.restart_inplace("rs-1")
    st = backend.inspect("rs-1")
    assert st.running and not st.paused
    backend.stop("rs-1")
    st = backend.inspect("rs-1")
    assert not st.running and st.exit_code == 137
    with pytest.raises(DockerError):
        backend.create("rs-1", _spec())  # 409 conflict
    backend.remove("rs-1", force=True)
    assert not backend.inspect("rs-1").exists


def test_inspect_maps_upperdir_and_pid(fake, backend):
    backend.create("rs-1", _spec())
    backend.start("rs-1")
    st = backend.inspect("rs-1")
    assert st.upper_dir == "/var/overlay/rs-1/diff"
    assert st.pid == 4321


def test_exec_demux_and_exit_code(fake, backend):
    backend.create("rs-1", _spec())
    backend.start("rs-1")
    code, out = backend.execute("rs-1", ["echo", "hi"], workdir="/app")
    assert code == 0
    assert "echo hi" in out and "warn" in out  # stdout + stderr demuxed
    ex = next(r for r in fake.requests if r[1].endswith("/exec") and r[0] == "POST")
    assert ex[3]["Cmd"] == ["echo", "hi"]
    assert ex[3]["WorkingDir"] == "/app"


def test_remove_running_requires_force(fake, backend):
    backend.create("rs-1", _spec())
    backend.start("rs-1")
    with pytest.raises(DockerError):
        backend.remove("rs-1", force=False)
    backend.remove("rs-1", force=True)


def test_list_names_prefix(fake, backend):
    for n in ("foo-1", "foo-2", "bar-1"):
        backend.create(n, _spec())
    assert backend.list_names("foo-") == ["foo-1", "foo-2"]


def test_commit(fake, backend):
    backend.create("rs-1", _spec())
    digest = backend.commit("rs-1", "myimg:v2")
    assert digest.startswith("sha256:")
    c = next(r for r in fake.requests if r[1].endswith("/commit"))
    assert c[2] == {"container": "rs-1", "repo": "myimg", "tag": "v2"}


def test_volume_quota_opts(fake, backend):
    v = backend.volume_create("vol", size_bytes=20 * 1024 ** 3)
    assert v.exists and v.driver_opts == {"size": str(20 * 1024 ** 3)}
    got = backend.volume_inspect("vol")
    assert got.exists and got.size_limit_bytes == 20 * 1024 ** 3
    backend.volume_remove("vol")
    assert not backend.volume_inspect("vol").exists


def test_missing_container_404(fake, backend):
    assert not backend.inspect("nope").exists
    with pytest.raises(DockerError) as ei:
        backend.start("nope")
    assert ei.value.status == 404
