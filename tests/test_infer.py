"""KV-cache autoregressive decoding: greedy decode must match the
full-forward oracle token-for-token for both model families (static-shape
cache, one compiled decode step, scan-driven loop)."""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from gpu_docker_api_tpu.infer import decode_step, generate, init_cache, prefill
from gpu_docker_api_tpu.models.llama import (
    LlamaConfig, init_params as llama_init, llama_forward,
)
from gpu_docker_api_tpu.models.moe import (
    MoEConfig, init_params as moe_init, moe_forward,
)


@pytest.fixture(scope="module")
def llama():
    cfg = LlamaConfig.tiny()
    return cfg, llama_init(cfg, jax.random.key(0))


def _prompt(cfg, b=2, t=8):
    return jax.random.randint(jax.random.key(1), (b, t), 0,
                              cfg.vocab_size, dtype=jnp.int32)


def test_generate_matches_full_forward_oracle(llama):
    cfg, params = llama
    prompt = _prompt(cfg)
    seq, oracle = prompt, []
    for _ in range(6):
        logits = llama_forward(params, seq, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        oracle.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    out = generate(params, prompt, cfg, max_new=6)
    assert out.shape == (2, 6)
    assert bool(jnp.all(out == jnp.stack(oracle, axis=1)))


def test_generate_moe_matches_oracle():
    # generous capacity so routing drops nothing — decode (1 token/step) and
    # full forward (T tokens) then agree exactly
    cfg = dataclasses.replace(MoEConfig.tiny(), capacity_factor=8.0)
    params = moe_init(cfg, jax.random.key(0))
    prompt = _prompt(cfg)
    seq, oracle = prompt, []
    for _ in range(5):
        logits, _ = moe_forward(params, seq, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        oracle.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    out = generate(params, prompt, cfg, max_new=5)
    assert bool(jnp.all(out == jnp.stack(oracle, axis=1)))


def test_prefill_then_decode_steps(llama):
    cfg, params = llama
    prompt = _prompt(cfg)
    cache = init_cache(cfg, 2, 16)
    logits, cache = prefill(params, prompt, cache, cfg)
    assert logits.shape == (2, cfg.vocab_size)
    assert int(cache["length"]) == 8
    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits2, cache = decode_step(params, tok, cache, cfg)
    assert logits2.shape == (2, cfg.vocab_size)
    assert int(cache["length"]) == 9
    # prefill last-position logits equal the plain forward's
    full = llama_forward(params, prompt, cfg)
    assert bool(jnp.allclose(logits, full[:, -1], atol=1e-4))


def test_cache_overflow_raises(llama):
    """dynamic_update_slice clamps OOB writes — the API must refuse instead
    of silently corrupting the newest cache entry."""
    cfg, params = llama
    prompt = _prompt(cfg)                       # 8 tokens
    cache = init_cache(cfg, 2, 9)               # room for prompt + 1
    logits, cache = prefill(params, prompt, cache, cfg)
    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    _, cache = decode_step(params, tok, cache, cfg)   # fills slot 9/9
    with pytest.raises(ValueError, match="overflow"):
        decode_step(params, tok, cache, cfg)
    with pytest.raises(ValueError, match="overflow"):
        prefill(params, prompt, init_cache(cfg, 2, 4), cfg)


def test_generate_max_new_one(llama):
    cfg, params = llama
    prompt = _prompt(cfg)
    out = generate(params, prompt, cfg, max_new=1)
    full = llama_forward(params, prompt, cfg)
    assert bool(jnp.all(
        out[:, 0] == jnp.argmax(full[:, -1], axis=-1).astype(jnp.int32)))


def test_generate_sampling_respects_temperature(llama):
    cfg, params = llama
    prompt = _prompt(cfg)
    g1 = generate(params, prompt, cfg, max_new=4, temperature=1.0,
                  key=jax.random.key(7))
    g2 = generate(params, prompt, cfg, max_new=4, temperature=1.0,
                  key=jax.random.key(8))
    assert g1.shape == g2.shape == (2, 4)
    # different keys should (overwhelmingly) differ somewhere
    assert not bool(jnp.all(g1 == g2))
    # same key reproduces
    g3 = generate(params, prompt, cfg, max_new=4, temperature=1.0,
                  key=jax.random.key(7))
    assert bool(jnp.all(g1 == g3))
