"""KV-cache autoregressive decoding: greedy decode must match the
full-forward oracle token-for-token for both model families (static-shape
cache, one compiled decode step, scan-driven loop)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gpu_docker_api_tpu.infer import decode_step, generate, init_cache, prefill
from gpu_docker_api_tpu.models.llama import (
    LlamaConfig, init_params as llama_init, llama_forward,
)
from gpu_docker_api_tpu.models.moe import (
    MoEConfig, init_params as moe_init, moe_forward,
)


@pytest.fixture(scope="module")
def llama():
    cfg = LlamaConfig.tiny()
    return cfg, llama_init(cfg, jax.random.key(0))


def _prompt(cfg, b=2, t=8):
    return jax.random.randint(jax.random.key(1), (b, t), 0,
                              cfg.vocab_size, dtype=jnp.int32)


@pytest.mark.slow
def test_generate_matches_full_forward_oracle(llama):
    cfg, params = llama
    prompt = _prompt(cfg)
    seq, oracle = prompt, []
    for _ in range(6):
        logits = llama_forward(params, seq, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        oracle.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    out = generate(params, prompt, cfg, max_new=6)
    assert out.shape == (2, 6)
    assert bool(jnp.all(out == jnp.stack(oracle, axis=1)))


@pytest.mark.slow
def test_generate_moe_matches_oracle():
    # generous capacity so routing drops nothing — decode (1 token/step) and
    # full forward (T tokens) then agree exactly
    cfg = dataclasses.replace(MoEConfig.tiny(), capacity_factor=8.0)
    params = moe_init(cfg, jax.random.key(0))
    prompt = _prompt(cfg)
    seq, oracle = prompt, []
    for _ in range(5):
        logits, _ = moe_forward(params, seq, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        oracle.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    out = generate(params, prompt, cfg, max_new=5)
    assert bool(jnp.all(out == jnp.stack(oracle, axis=1)))


def test_prefill_then_decode_steps(llama):
    cfg, params = llama
    prompt = _prompt(cfg)
    cache = init_cache(cfg, 2, 16)
    logits, cache = prefill(params, prompt, cache, cfg)
    assert logits.shape == (2, cfg.vocab_size)
    assert int(cache["length"]) == 8
    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits2, cache = decode_step(params, tok, cache, cfg)
    assert logits2.shape == (2, cfg.vocab_size)
    assert int(cache["length"]) == 9
    # prefill last-position logits equal the plain forward's
    full = llama_forward(params, prompt, cfg)
    assert bool(jnp.allclose(logits, full[:, -1], atol=1e-4))


def test_cache_overflow_raises(llama):
    """dynamic_update_slice clamps OOB writes — the API must refuse instead
    of silently corrupting the newest cache entry."""
    cfg, params = llama
    prompt = _prompt(cfg)                       # 8 tokens
    cache = init_cache(cfg, 2, 9)               # room for prompt + 1
    logits, cache = prefill(params, prompt, cache, cfg)
    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    _, cache = decode_step(params, tok, cache, cfg)   # fills slot 9/9
    with pytest.raises(ValueError, match="overflow"):
        decode_step(params, tok, cache, cfg)
    with pytest.raises(ValueError, match="overflow"):
        prefill(params, prompt, init_cache(cfg, 2, 4), cfg)


def test_generate_max_new_one(llama):
    cfg, params = llama
    prompt = _prompt(cfg)
    out = generate(params, prompt, cfg, max_new=1)
    full = llama_forward(params, prompt, cfg)
    assert bool(jnp.all(
        out[:, 0] == jnp.argmax(full[:, -1], axis=-1).astype(jnp.int32)))


def test_generate_sampling_respects_temperature(llama):
    cfg, params = llama
    prompt = _prompt(cfg)
    g1 = generate(params, prompt, cfg, max_new=4, temperature=1.0,
                  key=jax.random.key(7))
    g2 = generate(params, prompt, cfg, max_new=4, temperature=1.0,
                  key=jax.random.key(8))
    assert g1.shape == g2.shape == (2, 4)
    # different keys should (overwhelmingly) differ somewhere
    assert not bool(jnp.all(g1 == g2))
    # same key reproduces
    g3 = generate(params, prompt, cfg, max_new=4, temperature=1.0,
                  key=jax.random.key(7))
    assert bool(jnp.all(g1 == g3))


def test_attend_cached_never_reads_past_frontier():
    """Length-aware decode contract (VERDICT r1 weak #5): blocks beyond the
    causal frontier are never read. Poison the unused cache region with NaN
    — a full-S_max attend would propagate it (0 * NaN = NaN in the value
    einsum); the blockwise loop must stay finite."""
    import math
    from gpu_docker_api_tpu.infer import _attend_cached, _block_for, blocks_used

    b, h, hkv, d, s_max = 2, 4, 2, 16, 64
    blk = _block_for(s_max)
    assert blk > 1                      # 64 is a power of two
    key = jax.random.key(0)
    kq, kk, kv = jax.random.split(key, 3)
    pos, t = 5, 1                       # frontier at 6 -> one block of 32? blk=64->1
    q = jax.random.normal(kq, (b, t, h, d))
    k_all = jax.random.normal(kk, (b, s_max, hkv, d))
    v_all = jax.random.normal(kv, (b, s_max, hkv, d))
    used = int(blocks_used(pos, t, blk)) * blk
    poison = jnp.full((b, s_max - used, hkv, d), jnp.nan)
    k_pois = k_all.at[:, used:].set(poison) if used < s_max else k_all
    v_pois = v_all.at[:, used:].set(poison) if used < s_max else v_all
    out = jax.jit(_attend_cached)(q, k_pois, v_pois, jnp.int32(pos))
    assert bool(jnp.all(jnp.isfinite(out)))

    # numerics: blockwise result == dense masked reference
    qf = q.astype(jnp.float32) / math.sqrt(d)
    kf = jnp.repeat(k_all.astype(jnp.float32), h // hkv, axis=2)
    vf = jnp.repeat(v_all.astype(jnp.float32), h // hkv, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", qf, kf)
    rows = pos + jnp.arange(t)
    cols = jnp.arange(s_max)
    scores = jnp.where((cols[None, :] <= rows[:, None])[None, None],
                       scores, -jnp.inf)
    ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(scores, axis=-1), vf)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_blocks_used_proportional_to_length():
    """The attend loop's trip count — hence FLOPs — grows with the prefix,
    not with S_max."""
    from gpu_docker_api_tpu.infer import _block_for, blocks_used
    s_max = 4096
    blk = _block_for(s_max)
    assert blk == 128
    assert int(blocks_used(jnp.int32(0), 1, blk)) == 1
    assert int(blocks_used(jnp.int32(127), 1, blk)) == 1
    assert int(blocks_used(jnp.int32(128), 1, blk)) == 2
    assert int(blocks_used(jnp.int32(4000), 1, blk)) == 32   # ~len/blk << 4096/blk
    # odd S_max degrades to a smaller power-of-two block, never breaks
    assert _block_for(96) == 32 and _block_for(7) == 1


def test_decode_step_donates_cache(llama):
    """ADVICE r1: the public decode path must update the cache buffers in
    place (donated), not copy [L,B,S_max,Hkv,D] every token."""
    from gpu_docker_api_tpu.infer import decode_step, init_cache, prefill
    cfg, params = llama
    cache = init_cache(cfg, 1, 32)
    prompt = jnp.array([[5, 7, 11]], dtype=jnp.int32)
    _, cache = prefill(params, prompt, cache, cfg)
    k_before = cache["k"]
    tok = jnp.array([3], dtype=jnp.int32)
    _, cache2 = decode_step(params, tok, cache, cfg)
    # donation invalidates the input buffer
    assert k_before.is_deleted()
    assert cache2["host_length"] == 4


# -------------------------------------------------- sampling filters

def test_top_k_filter_keeps_k_highest():
    from gpu_docker_api_tpu.infer import _filter_top_k
    logits = jnp.array([[1.0, 5.0, 3.0, 2.0, 4.0]])
    out = _filter_top_k(logits, 2)
    assert bool(jnp.isfinite(out[0, 1])) and bool(jnp.isfinite(out[0, 4]))
    assert not bool(jnp.isfinite(out[0, 0]))
    assert not bool(jnp.isfinite(out[0, 2]))
    assert not bool(jnp.isfinite(out[0, 3]))


def test_top_p_filter_nucleus():
    from gpu_docker_api_tpu.infer import _filter_top_p
    # probs ~ [0.643, 0.236, 0.087, 0.032, ...]: nucleus(0.7) = {0} until
    # cumulative BEFORE a token reaches p — token 1 enters at 0.643 < 0.7
    logits = jnp.log(jnp.array([[0.643, 0.236, 0.087, 0.022, 0.012]]))
    out = _filter_top_p(logits, 0.7)
    assert bool(jnp.isfinite(out[0, 0]))
    assert bool(jnp.isfinite(out[0, 1]))
    assert not bool(jnp.isfinite(out[0, 2]))
    # the top token ALWAYS survives even with tiny p
    out1 = _filter_top_p(logits, 1e-6)
    assert bool(jnp.isfinite(out1[0, 0]))
    assert not bool(jnp.isfinite(out1[0, 1]))


def test_generate_sampled_tokens_respect_top_k():
    """With top_k=1, sampling at any temperature IS greedy."""
    cfg = LlamaConfig.tiny()
    params = llama_init(cfg, jax.random.key(0))
    prompt = jnp.array([[5, 9, 2, 7]], jnp.int32)
    greedy = generate(params, prompt, cfg, 5, temperature=0.0)
    topk1 = generate(params, prompt, cfg, 5, temperature=1.3, top_k=1,
                     key=jax.random.key(42))
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(topk1))
