"""Speculative decoding INSIDE the continuous batcher (serve._Batcher with
draft=): per-slot draft proposals, one shared multi-token verify forward,
per-row acceptance + cache rollback. The contract mirrors the standalone
path (test_speculative.py): greedy rows emit EXACTLY the target-only greedy
stream for any draft; sampling rows keep exact target statistics via
per-row rejection sampling. This closes VERDICT r3 weak #5 (the
`--batch-slots and --draft-config both claim the decode step` refusal).

Every stream-equality test (and the distribution test) runs twice:
kv_block=0 (dense slot cache) and kv_block=8 (PAGED pool — paging.
paged_verify writes each row's gamma+1 verify tokens through its page
table, across block boundaries; VERDICT r4 next #3). Plus paged-only
pins: in-flight prefix sharing under spec (shared blocks are never
verify-written) and verify overshoot at the max_len boundary (admission's
spec_pad headroom keeps overshoot out of the scratch block)."""

import threading
from concurrent.futures import ThreadPoolExecutor

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gpu_docker_api_tpu.infer import generate
from gpu_docker_api_tpu.models.llama import LlamaConfig, init_params
from gpu_docker_api_tpu.workloads.serve import _Batcher

# slow tier: many tiny-model compiles (draft + verify + accept programs)
pytestmark = pytest.mark.slow

# run dense and paged variants of every stream-equality test
DENSE_PAGED = pytest.mark.parametrize("kv_block", [0, 8],
                                      ids=["dense", "paged"])


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny()
    target = init_params(cfg, jax.random.key(0))
    # a DIFFERENT random-init draft: worst-case proposals (near-zero
    # acceptance) — exactness must hold regardless
    draft = init_params(cfg, jax.random.key(42))
    return cfg, target, draft


def solo(params, cfg, prompt_row, n, **kw):
    return np.asarray(generate(params, prompt_row[None, :], cfg,
                               max_new=n, **kw))[0]


def run_batch(b, prompts, max_new, **submit_kw):
    """Submit all prompts concurrently; close the batcher FIRST on exit
    (workers stuck in done.wait() are only woken by _fail_all)."""
    ex = ThreadPoolExecutor(len(prompts))
    try:
        futs = [ex.submit(b.submit, p, max_new, **submit_kw)
                for p in prompts]
        return [f.result(timeout=180) for f in futs]
    finally:
        b.close()
        ex.shutdown(wait=True)


def prompts_for(cfg, lens, seed0=1):
    return [jax.random.randint(jax.random.key(seed0 + i), (ln,), 0,
                               cfg.vocab_size, jnp.int32)
            for i, ln in enumerate(lens)]


@DENSE_PAGED
def test_greedy_streams_bit_exact_with_bad_draft(setup, kv_block):
    """Three concurrent greedy streams through the speculative batcher
    must equal their solo target-only greedy streams exactly — the draft
    (worst-case: a different random init) changes speed, never content."""
    cfg, target, draft = setup
    prompts = prompts_for(cfg, [6, 9, 5])
    want = [solo(target, cfg, p, 12) for p in prompts]
    b = _Batcher(cfg, target, slots=3, max_len=64, kv_block=kv_block,
                 draft=(cfg, draft), gamma=4)
    got = run_batch(b, prompts, 12)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g, w)
    assert b.spec_rounds >= 1
    assert b.spec_emitted >= 3 * 11         # all but the arm token


@DENSE_PAGED
def test_perfect_draft_accepts_everything(setup, kv_block):
    """draft == target: every proposal accepted, each round emits
    gamma+1 tokens per row — and the a==gamma draft-cache fill path runs
    every round. Stream still bit-exact."""
    cfg, target, _ = setup
    gamma = 3
    (p,) = prompts_for(cfg, [7])
    want = solo(target, cfg, p, 13)
    b = _Batcher(cfg, target, slots=1, max_len=64, kv_block=kv_block,
                 draft=(cfg, target), gamma=gamma)
    (got,) = run_batch(b, [p], 13)
    np.testing.assert_array_equal(got, want)
    # 13 tokens = 1 (arm) + 12 from rounds of gamma+1=4 -> 3 rounds
    assert b.spec_rounds == 3
    assert b.spec_accepted == 3 * gamma


@DENSE_PAGED
@pytest.mark.parametrize("gamma", [1, 2, 5])
def test_exact_across_gamma(setup, gamma, kv_block):
    cfg, target, draft = setup
    prompts = prompts_for(cfg, [6, 8], seed0=11)
    want = [solo(target, cfg, p, 9) for p in prompts]
    b = _Batcher(cfg, target, slots=2, max_len=64, kv_block=kv_block,
                 draft=(cfg, draft), gamma=gamma)
    got = run_batch(b, prompts, 9)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g, w)


@DENSE_PAGED
def test_staggered_admission_joins_between_spec_rounds(setup, kv_block):
    """A request admitted mid-run must not disturb the running stream,
    and must itself be exact — continuous batching's contract, now under
    speculative rounds."""
    cfg, target, draft = setup
    p0, p1 = prompts_for(cfg, [5, 7], seed0=21)
    want0, want1 = solo(target, cfg, p0, 16), solo(target, cfg, p1, 8)
    b = _Batcher(cfg, target, slots=2, max_len=64, kv_block=kv_block,
                 draft=(cfg, draft), gamma=4)
    ex = ThreadPoolExecutor(2)
    try:
        f0 = ex.submit(b.submit, p0, 16)
        # wait until the first stream is mid-decode, then join
        while b.spec_rounds < 1 and not f0.done():
            threading.Event().wait(0.01)
        f1 = ex.submit(b.submit, p1, 8)
        got0, got1 = f0.result(timeout=180), f1.result(timeout=180)
    finally:
        b.close()
        ex.shutdown(wait=True)
    np.testing.assert_array_equal(got0, want0)
    np.testing.assert_array_equal(got1, want1)


@DENSE_PAGED
def test_spec_with_kv_quant(setup, kv_block):
    """int8 slot caches (BOTH models) compose with speculative rounds;
    exactness is against the kv_quant solo stream (same numerics)."""
    cfg, target, draft = setup
    prompts = prompts_for(cfg, [6, 9], seed0=31)
    want = [solo(target, cfg, p, 10, kv_quant=True) for p in prompts]
    b = _Batcher(cfg, target, slots=2, max_len=64, kv_quant=True,
                 kv_block=kv_block, draft=(cfg, draft), gamma=3)
    got = run_batch(b, prompts, 10)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g, w)


@DENSE_PAGED
def test_spec_with_chunked_prefill(setup, kv_block):
    """Chunked prefill feeds target AND draft caches piecewise; arming
    waits for both, then spec rounds produce the exact stream."""
    cfg, target, draft = setup
    prompts = prompts_for(cfg, [13, 6], seed0=41)
    want = [solo(target, cfg, p, 8) for p in prompts]
    b = _Batcher(cfg, target, slots=2, max_len=64, prefill_chunk=4,
                 kv_block=kv_block, draft=(cfg, draft), gamma=3)
    got = run_batch(b, prompts, 8)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g, w)


@DENSE_PAGED
def test_spec_with_prefix_cache(setup, kv_block):
    """Prefix reuse restores the TARGET's KV; the draft prefills the full
    prompt (it has no prefix store). Streams stay exact and the second
    identical prompt hits the prefix cache."""
    cfg, target, draft = setup
    (p,) = prompts_for(cfg, [12], seed0=51)
    want = solo(target, cfg, p, 8)
    b = _Batcher(cfg, target, slots=1, max_len=64, prefix_cache=2,
                 kv_block=kv_block, draft=(cfg, draft), gamma=3)
    try:
        got1 = b.submit(p, 8)
        got2 = b.submit(p, 8)
    finally:
        b.close()
    np.testing.assert_array_equal(got1, want)
    np.testing.assert_array_equal(got2, want)
    assert b.prefix_hits >= 1


@DENSE_PAGED
def test_mixed_greedy_and_sampling_rows(setup, kv_block):
    """A sampling row joins the batch: greedy rows must stay bit-exact
    (their acceptance never looks at the sampling machinery), and the
    sampled stream must be valid tokens of full length."""
    cfg, target, draft = setup
    pg, ps = prompts_for(cfg, [6, 7], seed0=61)
    want = solo(target, cfg, pg, 12)
    b = _Batcher(cfg, target, slots=2, max_len=64, kv_block=kv_block,
                 draft=(cfg, draft), gamma=4, seed=7)
    ex = ThreadPoolExecutor(2)
    try:
        fg = ex.submit(b.submit, pg, 12)
        fs = ex.submit(b.submit, ps, 12, temperature=0.9, top_k=8)
        got_g, got_s = fg.result(timeout=180), fs.result(timeout=180)
    finally:
        b.close()
        ex.shutdown(wait=True)
    np.testing.assert_array_equal(got_g, want)
    assert len(got_s) == 12
    assert all(0 <= t < cfg.vocab_size for t in got_s)


@DENSE_PAGED
def test_sampling_reproducible_with_seed(setup, kv_block):
    """One sampled stream, fixed batcher seed: the spec-round keys fold a
    deterministic step counter, so a rerun reproduces the stream."""
    cfg, target, draft = setup
    (p,) = prompts_for(cfg, [6], seed0=71)

    def once():
        b = _Batcher(cfg, target, slots=1, max_len=64,
                     kv_block=kv_block, draft=(cfg, draft), gamma=3,
                     seed=123)
        try:
            return b.submit(p, 10, temperature=0.8)
        finally:
            b.close()

    assert once() == once()


@DENSE_PAGED
def test_sampling_distribution_matches_target(kv_block):
    """The batcher's rejection sampling preserves the target-only
    marginal (same guarantee the standalone path proves): the SECOND
    emitted token — always produced by a spec round (accepted draft
    proposal or residual resample) — must match the analytically exact
    target marginal, for a draft whose own marginal is far away.

    Same statistical design as test_speculative.py's distribution test:
    16-token vocab (tiny's 256-token near-uniform distributions put the
    n=600 sampling-noise TV floor at ~0.26, above any useful threshold)
    and a sharpened draft head so the test has power against draft
    contamination."""
    from gpu_docker_api_tpu.infer import init_cache, prefill

    cfg = LlamaConfig(vocab_size=16, d_model=32, n_layers=2, n_heads=2,
                      n_kv_heads=1, d_ff=64, max_seq_len=64,
                      dtype=jnp.float32)
    target = init_params(cfg, jax.random.key(0))
    draft = init_params(cfg, jax.random.key(42))
    draft = dict(draft, lm_head=draft["lm_head"] * 8.0)
    temp = 0.9
    prompt = jnp.array([3, 7, 1, 9], jnp.int32)

    def dist(logits):
        return np.asarray(jax.nn.softmax(logits / temp, axis=-1))[0]

    logits0, _ = prefill(target, prompt[None], init_cache(cfg, 1, 32), cfg)
    p0 = dist(logits0)
    exact = np.zeros(cfg.vocab_size)
    for t0 in range(cfg.vocab_size):
        if p0[t0] < 1e-9:
            continue
        ext = jnp.concatenate([prompt[None],
                               jnp.array([[t0]], jnp.int32)], axis=1)
        lg, _ = prefill(target, ext, init_cache(cfg, 1, 32), cfg)
        exact += p0[t0] * dist(lg)

    n = 600
    counts = np.zeros(cfg.vocab_size)
    b = _Batcher(cfg, target, slots=1, max_len=64, kv_block=kv_block,
                 draft=(cfg, draft), gamma=3, seed=9)
    try:
        for _ in range(n):
            out = b.submit(prompt, 2, temperature=temp)
            counts[out[1]] += 1
    finally:
        b.close()
    tv = 0.5 * np.abs(counts / n - exact).sum()
    assert tv < 0.15, f"TV {tv:.3f} vs exact target marginal (n={n})"
    # power check: the draft's own marginal must be far from the target's
    lgd, _ = prefill(draft, prompt[None], init_cache(cfg, 1, 32), cfg)
    assert 0.5 * np.abs(dist(lgd) - p0).sum() > 0.3


def test_paged_spec_inflight_share_stays_exact(setup):
    """In-batch zero-copy prefix sharing UNDER speculative rounds: two
    identical prompts, chunked prefill so the second admission parks on
    the first's write frontier and shares its full prompt blocks. Both
    streams must equal the solo target-only stream bit-exactly — any
    verify write into a shared block would corrupt the donor's KV and
    diverge its stream (the safety claim, pinned by equality)."""
    cfg, target, draft = setup
    (p,) = prompts_for(cfg, [28], seed0=81)
    want = solo(target, cfg, p, 10)
    b = _Batcher(cfg, target, slots=2, max_len=64, kv_block=8,
                 prefill_chunk=4, draft=(cfg, draft), gamma=3)
    ex = ThreadPoolExecutor(2)
    try:
        f0 = ex.submit(b.submit, p, 10)
        # admit the follower while the donor is mid-prefill (7 chunks):
        # the second admission MUST take the in-flight sharing path
        while not any(s is not None for s in b.slots) and not f0.done():
            threading.Event().wait(0.005)
        f1 = ex.submit(b.submit, p, 10)
        got = [f0.result(timeout=180), f1.result(timeout=180)]
    finally:
        b.close()
        ex.shutdown(wait=True)
    for g in got:
        np.testing.assert_array_equal(g, want)
    # no --prefix-cache here: hits can only come from the in-flight
    # donor path — sharing really happened (not a vacuous pass)
    assert b.prefix_hits >= 1


def test_paged_spec_verify_overshoot_at_budget_boundary(setup):
    """Two rows at the FULL token budget (prompt + max_new == max_len):
    their final verify rounds overshoot past max_len, which must land in
    each row's reserved spec_pad blocks — not fall through the page
    table to the shared scratch block, where the two rows' overshoots
    would collide and corrupt each other's verify logits. Bit-equality
    to the solo streams pins it."""
    cfg, target, draft = setup
    prompts = prompts_for(cfg, [8, 8], seed0=91)
    want = [solo(target, cfg, p, 24) for p in prompts]
    b = _Batcher(cfg, target, slots=2, max_len=32, kv_block=8,
                 draft=(cfg, draft), gamma=4)
    got = run_batch(b, prompts, 24)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g, w)


def test_vocab_mismatch_refused(setup):
    import dataclasses
    cfg, target, draft = setup
    dcfg = dataclasses.replace(cfg, vocab_size=cfg.vocab_size + 1)
    with pytest.raises(ValueError, match="vocab"):
        _Batcher(cfg, target, slots=1, max_len=64, draft=(dcfg, draft))


def test_paged_spec_kitchen_sink_composition(setup):
    """EVERY serving feature at once: paged pool + speculative rounds +
    int8 KV (both models) + prefix store + chunked prefill. Two rounds
    of an identical prompt: the second admission reuses stored prefix
    blocks zero-copy while spec rounds verify-write through page tables
    in int8. Streams must equal the kv_quant solo reference bit-exactly."""
    cfg, target, draft = setup
    (p,) = prompts_for(cfg, [17], seed0=101)
    want = solo(target, cfg, p, 9, kv_quant=True)
    b = _Batcher(cfg, target, slots=2, max_len=64, kv_block=8,
                 kv_quant=True, prefix_cache=2, prefill_chunk=4,
                 draft=(cfg, draft), gamma=3)
    try:
        got1 = b.submit(p, 9)
        got2 = b.submit(p, 9)
    finally:
        b.close()
    np.testing.assert_array_equal(got1, want)
    np.testing.assert_array_equal(got2, want)
    assert b.prefix_hits >= 1          # the store path actually fired
    assert b.spec_rounds >= 2
