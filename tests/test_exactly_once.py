"""Exactly-once mutation sweep (`make verify-retry`).

Every mutating endpoint is driven under the three delivery hazards the
tentpole defends against:

1. **duplicate key** — the same request sent twice under one
   `Idempotency-Key` must produce exactly one state change (store
   revision and version maps unchanged by the duplicate; the duplicate
   gets the stored response with `Idempotency-Replayed: true`);
2. **dropped response** — the server executes but the client sees a
   connection error (faults.py `drop_response`); the keyed retry must
   replay, not re-execute;
3. **overload** — a gate forced full must shed the request with HTTP 429
   + Retry-After and exactly ZERO state change.

Plus: crash-between-attempts through the crashpoint harness (the boot
reconciler settles the result cache together with the interrupted
mutation), If-Match races (exactly one winner, the loser gets 412 and no
grant), graceful drain, TTL sweeping, and the client-side satellites
(close() across threads, retry/replay stats).

Invariants after every case mirror the crash/fault sweeps: scheduler
bitmaps == non-released stored specs, no open intents, reconcile
fixpoint.
"""

import http.client
import json
import threading
import time

import pytest

from gpu_docker_api_tpu import faults, xerrors
from gpu_docker_api_tpu.client import ApiClient, ApiError
from gpu_docker_api_tpu.dtos import StoredContainerInfo
from gpu_docker_api_tpu.faults import InjectedCrash
from gpu_docker_api_tpu.server.app import App, MutationGate
from gpu_docker_api_tpu.server.http import Request
from gpu_docker_api_tpu.topology import make_topology

pytestmark = pytest.mark.retry

N_CHIPS = 16      # v4-32 single host
N_CORES = 16


@pytest.fixture(autouse=True)
def _disarmed():
    faults.disarm_all()
    faults.disarm_faults()
    yield
    faults.disarm_all()
    faults.disarm_faults()


def make_app(tmp_path, **kw):
    kw.setdefault("backend", "mock")
    kw.setdefault("topology", make_topology("v4-32"))
    return App(state_dir=str(tmp_path / "state"), addr="127.0.0.1:0",
               port_range=(47000, 47100), api_key="", cpu_cores=N_CORES,
               store_maint_records=0, **kw)


def call(app, method, path, body=None, headers=None, timeout=10):
    conn = http.client.HTTPConnection("127.0.0.1", app.server.port,
                                      timeout=timeout)
    payload = json.dumps(body) if body is not None else None
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    conn.request(method, path, payload, hdrs)
    resp = conn.getresponse()
    raw = resp.read()
    out_headers = dict(resp.getheaders())
    conn.close()
    return resp.status, out_headers, json.loads(raw) if raw else None


def direct(app, method, path, body=None, headers=None):
    """Drive the full middleware chain (gate -> idempotency -> handler)
    without HTTP — for crash cases, where the handler thread 'dies' with
    InjectedCrash and a socket would just add teardown noise."""
    handler, params = app.server.router.resolve(method, path)
    assert handler is not None, (method, path)
    req = Request(method, path, {},
                  json.dumps(body).encode() if body is not None else b"",
                  dict(headers or {}), params, client_addr="test")
    return handler(req)


# ------------------------------------------------------------ invariants

def stored_containers(app):
    app.wq.join()
    return {kv.key.rsplit("/", 1)[1]: StoredContainerInfo.deserialize(kv.value)
            for kv in app.client.range("containers")}


def assert_no_leaks(app):
    stored = stored_containers(app)
    exp_tpu, exp_cpu, exp_ports = {}, {}, {}
    for name, info in stored.items():
        if info.resourcesReleased:
            continue
        for c in info.spec.tpu_chips:
            exp_tpu[c] = name
        for c in app.cpu._cores(info.spec.cpuset):
            exp_cpu[c] = name
        for p in info.spec.port_bindings.values():
            exp_ports[int(p)] = name
    assert {i: o for i, o in app.tpu.status.items()
            if o not in (None, "")} == exp_tpu
    assert {i: o for i, o in app.cpu.status.items()
            if o not in (None, "")} == exp_cpu
    assert dict(app.ports.used) == exp_ports
    assert app.intents.open_intents() == []
    settle = app.reconciler.run()
    assert sum(settle["grantsFreed"].values()) == 0, settle
    assert sum(settle["grantsRemarked"].values()) == 0, settle
    rerun = app.reconciler.run()
    assert rerun["actions"] == 0, f"re-reconcile not a no-op: {rerun}"
    return stored


# ------------------------------------------------------- endpoint table

def setup_demo(app):
    app.replicasets.run_container(__import__(
        "gpu_docker_api_tpu.dtos", fromlist=["ContainerRun"]).ContainerRun(
        imageName="img", replicaSetName="demo", tpuCount=2, cpuCount=2,
        containerPorts=["8888"]))


def setup_demo_v2(app):
    from gpu_docker_api_tpu.dtos import PatchRequest, TpuPatch
    setup_demo(app)
    app.replicasets.patch_container(
        "demo", PatchRequest(tpuPatch=TpuPatch(tpuCount=4)))


def setup_vol(app):
    app.volumes.create_volume("vol", "16MB")


def setup_cordoned(app):
    setup_demo(app)
    chips = stored_containers(app)["demo"].spec.tpu_chips
    app.tpu.cordon([chips[0]])


# every mutating endpoint: (id, setup, method, path, body)
ENDPOINTS = [
    ("run", None, "POST", "/api/v1/replicaSet",
     {"imageName": "i", "replicaSetName": "fresh", "tpuCount": 1,
      "cpuCount": 1, "containerPorts": ["80"]}),
    ("patch", setup_demo, "PATCH", "/api/v1/replicaSet/demo",
     {"tpuPatch": {"tpuCount": 4}}),
    ("rollback", setup_demo_v2, "PATCH",
     "/api/v1/replicaSet/demo/rollback", {"version": 1}),
    ("stop", setup_demo, "PATCH", "/api/v1/replicaSet/demo/stop", None),
    ("restart", setup_demo, "PATCH",
     "/api/v1/replicaSet/demo/restart", None),
    ("pause", setup_demo, "PATCH", "/api/v1/replicaSet/demo/pause", None),
    ("continue", setup_demo, "PATCH",
     "/api/v1/replicaSet/demo/continue", None),
    ("execute", setup_demo, "POST", "/api/v1/replicaSet/demo/execute",
     {"cmd": ["echo", "hi"]}),
    ("commit", setup_demo, "POST", "/api/v1/replicaSet/demo/commit",
     {"newImageName": "snap:v1"}),
    ("delete", setup_demo, "DELETE", "/api/v1/replicaSet/demo", None),
    ("volCreate", None, "POST", "/api/v1/volumes",
     {"name": "vol", "size": "16MB"}),
    ("volPatch", setup_vol, "PATCH", "/api/v1/volumes/vol/size",
     {"size": "32MB"}),
    ("volDelete", setup_vol, "DELETE", "/api/v1/volumes/vol", None),
    ("cordon", None, "POST", "/api/v1/tpus/0/cordon", None),
    ("uncordon", None, "POST", "/api/v1/tpus/0/uncordon", None),
    ("drain", setup_cordoned, "POST", "/api/v1/tpus/drain", None),
]

IDS = [e[0] for e in ENDPOINTS]


def _state_fingerprint(app):
    """Everything a duplicate must not change: store revision, version
    maps, scheduler ownership."""
    app.wq.join()
    return (app.store.revision,
            app.container_versions.items(), app.volume_versions.items(),
            dict(app.tpu.status), dict(app.cpu.status),
            dict(app.ports.used))


@pytest.mark.parametrize("ep,setup,method,path,body", ENDPOINTS, ids=IDS)
def test_duplicate_key_sweep(ep, setup, method, path, body, tmp_path):
    """Acceptance: the same mutation delivered twice under one key
    produces exactly one state change and one version bump; the duplicate
    replays the stored response byte-for-byte."""
    app = make_app(tmp_path)
    if setup is not None:
        setup(app)
    app.start()
    try:
        key = f"dup-{ep}"
        status1, hdrs1, out1 = call(app, method, path, body,
                                    headers={"Idempotency-Key": key})
        assert out1["code"] == 200, (ep, out1)
        assert "Idempotency-Replayed" not in hdrs1
        fp = _state_fingerprint(app)
        status2, hdrs2, out2 = call(app, method, path, body,
                                    headers={"Idempotency-Key": key})
        assert hdrs2.get("Idempotency-Replayed") == "true", (ep, hdrs2)
        assert status2 == status1 and out2 == out1, ep
        assert _state_fingerprint(app) == fp, \
            f"{ep}: duplicate changed state"
        # key reused with a DIFFERENT request: rejected, still no change
        _, _, out3 = call(app, "POST", "/api/v1/replicaSet",
                          {"imageName": "i", "replicaSetName": "other"},
                          headers={"Idempotency-Key": key})
        assert out3["code"] == 1000, out3
        assert _state_fingerprint(app) == fp
        assert_no_leaks(app)
    finally:
        app.stop()


@pytest.mark.parametrize("ep,setup,method,path,body", ENDPOINTS, ids=IDS)
def test_dropped_response_sweep(ep, setup, method, path, body, tmp_path):
    """Acceptance: the server executes but the response never arrives
    (injected drop_response). The keyed retry replays the stored outcome —
    the mutation lands exactly once."""
    app = make_app(tmp_path)
    if setup is not None:
        setup(app)
    app.start()
    try:
        key = f"drop-{ep}"
        faults.arm_fault(f"{method} {path}:drop_response")
        with pytest.raises((ConnectionError, http.client.HTTPException,
                            OSError)):
            call(app, method, path, body,
                 headers={"Idempotency-Key": key})
        faults.disarm_faults()
        fp = _state_fingerprint(app)   # the mutation DID happen
        status, hdrs, out = call(app, method, path, body,
                                 headers={"Idempotency-Key": key})
        assert out["code"] == 200, (ep, out)
        assert hdrs.get("Idempotency-Replayed") == "true", ep
        assert _state_fingerprint(app) == fp, \
            f"{ep}: retry after dropped response re-executed"
        assert_no_leaks(app)
    finally:
        app.stop()


@pytest.mark.parametrize("ep,setup,method,path,body", ENDPOINTS, ids=IDS)
def test_overload_shed_sweep(ep, setup, method, path, body, tmp_path):
    """Acceptance: with the gate full, every mutating endpoint sheds with
    429 + Retry-After BEFORE touching any state."""
    app = make_app(tmp_path)
    if setup is not None:
        setup(app)
    app.start()
    try:
        fp = _state_fingerprint(app)
        # fill the gate from a fake foreign client so the request under
        # test is shed at the semaphore, not the per-client cap
        app.gate.max_inflight = 1
        app.gate.max_waiting = 0
        assert app.gate.acquire("hog") is None
        try:
            status, hdrs, out = call(app, method, path, body)
            assert status == 429, (ep, status, out)
            assert out["code"] == 429, (ep, out)
            assert int(hdrs["Retry-After"]) >= 1, ep
            assert _state_fingerprint(app) == fp, \
                f"{ep}: shed request touched state"
        finally:
            app.gate.release("hog")
        # gate free again: the same request goes through
        _, _, out = call(app, method, path, body)
        assert out["code"] == 200, (ep, out)
        assert_no_leaks(app)
    finally:
        app.stop()


# ------------------------------------------------- crash between attempts

# crashpoint -> (setup, method, path, body, expectation after retry)
# "replay": the intent rolled FORWARD at boot — the retry must replay,
# not re-execute. "reexecute": the intent was unwound — the retry is a
# fresh execution and must succeed against the restored state.
CRASH_CASES = [
    ("run.after_grant", None, "POST", "/api/v1/replicaSet",
     {"imageName": "i", "replicaSetName": "fresh", "tpuCount": 2},
     "reexecute"),
    ("run.after_start", None, "POST", "/api/v1/replicaSet",
     {"imageName": "i", "replicaSetName": "fresh", "tpuCount": 2},
     "reexecute"),
    # pre-'created' crashes: NOTHING committed — finalizing these as
    # success would fabricate a mutation that never happened
    ("rollback.after_grant", setup_demo_v2, "PATCH",
     "/api/v1/replicaSet/demo/rollback", {"version": 1}, "reexecute"),
    ("restart.after_grant", setup_demo, "PATCH",
     "/api/v1/replicaSet/demo/restart", None, "reexecute"),
    ("replace.after_create", setup_demo, "PATCH",
     "/api/v1/replicaSet/demo", {"tpuPatch": {"tpuCount": 4}}, "replay"),
    ("replace.after_copy", setup_demo, "PATCH",
     "/api/v1/replicaSet/demo", {"tpuPatch": {"tpuCount": 4}}, "replay"),
    ("replace.after_start_new", setup_demo, "PATCH",
     "/api/v1/replicaSet/demo", {"tpuPatch": {"tpuCount": 4}}, "replay"),
    ("stop.after_backend_stop", setup_demo, "PATCH",
     "/api/v1/replicaSet/demo/stop", None, "replay"),
    ("delete.after_remove", setup_demo, "DELETE",
     "/api/v1/replicaSet/demo", None, "replay"),
]


@pytest.mark.parametrize("cp,setup,method,path,body,expect", CRASH_CASES,
                         ids=[c[0] for c in CRASH_CASES])
def test_crash_between_attempts(cp, setup, method, path, body, expect,
                                tmp_path):
    """Acceptance: attempt 1 dies at a crashpoint (client saw nothing);
    the daemon reboots; attempt 2 arrives with the same key. The boot
    reconciler settled BOTH the mutation and its cache entry, so the key
    observes exactly one state change either way."""
    app = make_app(tmp_path)
    if setup is not None:
        setup(app)
    key = f"crash-{cp}"
    faults.arm(cp)
    with pytest.raises(InjectedCrash):
        direct(app, method, path, body, headers={"Idempotency-Key": key})
    faults.disarm_all()
    # abandon like a daemon death (test_crash_recovery protocol)
    app.wq.close()
    app.store.close()
    app.events.close()
    app2 = make_app(tmp_path, backend=app.backend)
    resp = direct(app2, method, path, body,
                  headers={"Idempotency-Key": key})
    payload = json.loads(resp.payload())
    assert payload["code"] == 200, (cp, payload)
    if expect == "replay":
        assert resp.headers.get("Idempotency-Replayed") == "true", cp
    else:
        assert "Idempotency-Replayed" not in resp.headers, cp
    stored = assert_no_leaks(app2)
    if cp.startswith("run."):
        # exactly one run: version 1, not 2
        assert stored["fresh"].version == 1
    elif cp == "rollback.after_grant":
        # re-executed rollback: exactly one new version on top of v2
        assert stored["demo"].version == 3
        assert len(stored["demo"].spec.tpu_chips) == 2   # v1's count
    elif cp == "restart.after_grant":
        assert stored["demo"].version == 2
    elif cp.startswith("replace."):
        # exactly one replace: version 2, linear history [2, 1]
        assert stored["demo"].version == 2
        assert len(stored["demo"].spec.tpu_chips) == 4
        versions = [v for v, _ in
                    app2.client.entity_versions("containers", "demo")]
        assert versions == [1, 2]
    elif cp.startswith("stop."):
        assert stored["demo"].resourcesReleased
    elif cp.startswith("delete."):
        assert "demo" not in stored


def test_crash_after_commit_before_response_store(tmp_path):
    """The nastiest window: the service COMMITTED (intent.done ran) but
    the daemon died before the middleware stored the response. The
    executed marker — written before the intent key cleared — makes the
    boot reconciler finalize the key, so the retry replays instead of
    double-applying."""
    from gpu_docker_api_tpu import idempotency as idem_mod
    from gpu_docker_api_tpu.dtos import PatchRequest, TpuPatch

    app = make_app(tmp_path)
    setup_demo(app)
    key = "late-crash"
    body = json.dumps({"tpuPatch": {"tpuCount": 4}}).encode()
    fp = idem_mod.fingerprint("PATCH", "/api/v1/replicaSet/demo", body, {})
    state, _ = app.idempotency.begin(key, fp)
    assert state == idem_mod.NEW
    with idem_mod.context(key):
        app.replicasets.patch_container(
            "demo", PatchRequest(tpuPatch=TpuPatch(tpuCount=4)))
    # daemon dies HERE: response never stored (no finish() call)
    app.wq.close()
    app.store.close()
    app.events.close()
    app2 = make_app(tmp_path, backend=app.backend)
    assert app2.last_reconcile["idempotency"]["finalized"] == 1
    resp = direct(app2, "PATCH", "/api/v1/replicaSet/demo",
                  {"tpuPatch": {"tpuCount": 4}},
                  headers={"Idempotency-Key": key})
    assert json.loads(resp.payload())["code"] == 200
    assert resp.headers.get("Idempotency-Replayed") == "true"
    stored = assert_no_leaks(app2)
    assert stored["demo"].version == 2      # exactly ONE bump, not two
    versions = [v for v, _ in
                app2.client.entity_versions("containers", "demo")]
    assert versions == [1, 2]


def test_crash_mid_drain_keyed_retry_reexecutes(tmp_path):
    """Drain journals one intent PER replicaSet: completing one migration
    must not finalize the whole keyed request as success — the retry
    re-executes and finishes the remaining migrations."""
    from gpu_docker_api_tpu.dtos import ContainerRun

    app = make_app(tmp_path)
    for name in ("aa", "bb"):
        app.replicasets.run_container(ContainerRun(
            imageName="img", replicaSetName=name, tpuCount=2))
    stored = stored_containers(app)
    app.tpu.cordon([stored["aa"].spec.tpu_chips[0],
                    stored["bb"].spec.tpu_chips[0]])
    key = "drain-key"
    faults.arm("replace.after_copy")     # dies migrating the FIRST set
    with pytest.raises(InjectedCrash):
        direct(app, "POST", "/api/v1/tpus/drain", None,
               headers={"Idempotency-Key": key})
    faults.disarm_all()
    app.wq.close()
    app.store.close()
    app.events.close()
    app2 = make_app(tmp_path, backend=app.backend)
    # the key was dropped, not finalized: the retry RE-EXECUTES
    resp = direct(app2, "POST", "/api/v1/tpus/drain", None,
                  headers={"Idempotency-Key": key})
    payload = json.loads(resp.payload())
    assert payload["code"] == 200
    assert "Idempotency-Replayed" not in resp.headers
    stored = assert_no_leaks(app2)
    cordoned = set(app2.tpu.cordoned)
    for name, info in stored.items():
        assert not set(info.spec.tpu_chips) & cordoned, \
            f"{name} still on cordoned chips after keyed drain retry"


def test_query_string_part_of_fingerprint(tmp_path):
    """?noall turns a volume delete into a different operation: reusing
    the key without it must be rejected, not replayed."""
    app = make_app(tmp_path)
    setup_vol(app)
    app.start()
    try:
        _, _, out = call(app, "DELETE", "/api/v1/volumes/vol?noall",
                         headers={"Idempotency-Key": "qk"})
        assert out["code"] == 200
        _, _, out = call(app, "DELETE", "/api/v1/volumes/vol",
                         headers={"Idempotency-Key": "qk"})
        assert out["code"] == 1000, out     # mismatch, not a replay
        assert_no_leaks(app)
    finally:
        app.stop()


# ------------------------------------------------------ If-Match / races

def test_if_match_precondition(tmp_path):
    app = make_app(tmp_path)
    setup_demo(app)
    app.start()
    try:
        # wrong version: 412 + current version, no state change
        status, hdrs, out = call(app, "PATCH", "/api/v1/replicaSet/demo",
                                 {"tpuPatch": {"tpuCount": 4}},
                                 headers={"If-Match": "7"})
        assert status == 412 and out["code"] == 412, out
        assert hdrs["X-Current-Version"] == "1"
        assert out["data"]["currentVersion"] == 1
        assert stored_containers(app)["demo"].version == 1
        # matching version: proceeds
        status, _, out = call(app, "PATCH", "/api/v1/replicaSet/demo",
                              {"tpuPatch": {"tpuCount": 4}},
                              headers={"If-Match": "1"})
        assert out["code"] == 200, out
        assert out["data"]["version"] == 2
        # garbage If-Match is a client error, not a 500
        _, _, out = call(app, "PATCH", "/api/v1/replicaSet/demo/stop",
                         body=None, headers={"If-Match": "abc"})
        assert out["code"] == 1000
        # stop honors it too (and quoted etags parse)
        status, _, out = call(app, "PATCH", "/api/v1/replicaSet/demo/stop",
                              body=None, headers={"If-Match": '"2"'})
        assert out["code"] == 200
        assert_no_leaks(app)
    finally:
        app.stop()


def test_racing_patches_one_winner(tmp_path):
    """Satellite: two concurrent patches, both based on version 1, both
    sending If-Match: 1 — exactly one wins, the loser gets 412 under the
    name lock, zero leaked grants, linear version history."""
    app = make_app(tmp_path)
    setup_demo(app)
    app.start()
    results = []
    barrier = threading.Barrier(2)

    def racer(count):
        barrier.wait()
        status, hdrs, out = call(app, "PATCH", "/api/v1/replicaSet/demo",
                                 {"tpuPatch": {"tpuCount": count}},
                                 headers={"If-Match": "1"})
        results.append((status, out["code"],
                        hdrs.get("X-Current-Version")))

    try:
        threads = [threading.Thread(target=racer, args=(n,))
                   for n in (3, 4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        codes = sorted(c for _, c, _ in results)
        assert codes == [200, 412], results
        loser = next(r for r in results if r[1] == 412)
        assert loser[2] == "2"          # told the version that beat it
        stored = assert_no_leaks(app)
        assert stored["demo"].version == 2
        versions = [v for v, _ in
                    app.client.entity_versions("containers", "demo")]
        assert versions == [1, 2]       # linear: no forked/ghost version
    finally:
        app.stop()


def test_volume_if_match(tmp_path):
    app = make_app(tmp_path)
    setup_vol(app)
    with pytest.raises(xerrors.PreconditionFailedError) as ei:
        app.volumes.patch_volume_size("vol", "32MB", if_match=9)
    assert ei.value.current == 1
    app.volumes.patch_volume_size("vol", "32MB", if_match=1)
    with pytest.raises(xerrors.PreconditionFailedError):
        app.volumes.delete_volume("vol", if_match=1)
    app.volumes.delete_volume("vol", if_match=2)
    assert_no_leaks(app)
    app.stop()


# ------------------------------------------------------ overload details

def test_per_client_fairness(tmp_path):
    """One address hogging the gate is shed at its cap while another
    client still gets through."""
    gate = MutationGate(max_inflight=8, max_waiting=8, per_client=2)
    assert gate.acquire("10.0.0.1") is None
    assert gate.acquire("10.0.0.1") is None
    assert gate.acquire("10.0.0.1") == "per_client"     # over the cap
    assert gate.acquire("10.0.0.2") is None             # others unaffected
    gate.release("10.0.0.1")
    assert gate.acquire("10.0.0.1") is None             # slot freed
    d = gate.describe()
    assert d["shedTotal"] == 1 and d["shedByReason"]["per_client"] == 1
    assert d["inflight"] == 3       # 2x .1 admitted, 1 released, .2, .1


def test_gate_fifo_no_barging(tmp_path):
    """Newcomers must not steal a freed slot from parked waiters: the
    queue is FIFO, so the oldest waiter is admitted first and a sustained
    arrival stream cannot starve the queue into timeout sheds."""
    gate = MutationGate(max_inflight=2, max_waiting=4, wait_timeout=2.0)
    assert gate.acquire("a") is None
    assert gate.acquire("b") is None
    results = {}

    def waiter(name):
        results[name] = gate.acquire(name)

    t1 = threading.Thread(target=waiter, args=("w1",))
    t1.start()
    time.sleep(0.05)
    t2 = threading.Thread(target=waiter, args=("w2",))
    t2.start()
    time.sleep(0.05)
    gate.release("a")               # ONE slot: must go to w1, the head
    t1.join(2)
    assert not t1.is_alive() and results.get("w1") is None
    assert "w2" not in results      # w2 still parked behind the full gate
    gate.release("b")
    t2.join(2)
    assert results.get("w2") is None
    gate.release("w1")
    gate.release("w2")
    assert gate.describe()["shedTotal"] == 0


def test_gate_queue_timeout_and_watermark(tmp_path):
    gate = MutationGate(max_inflight=1, max_waiting=1, wait_timeout=0.05)
    assert gate.acquire("a") is None
    t = threading.Thread(target=lambda: gate.acquire("b"))  # queues, times out
    t.start()
    time.sleep(0.01)
    assert gate.acquire("c") == "queue_full"            # watermark hit
    t.join()
    d = gate.describe()
    assert d["shedByReason"]["queue_timeout"] == 1
    assert d["shedByReason"]["queue_full"] == 1
    gate.release("a")
    assert gate.acquire("b") is None


def test_overload_metrics_exported(tmp_path):
    app = make_app(tmp_path)
    app.start()
    try:
        app.gate.max_inflight, app.gate.max_waiting = 1, 0
        assert app.gate.acquire("hog") is None
        status, _, out = call(app, "POST", "/api/v1/volumes",
                              {"name": "v", "size": "1MB"})
        assert status == 429
        app.gate.release("hog")
        conn = http.client.HTTPConnection("127.0.0.1", app.server.port,
                                          timeout=10)
        conn.request("GET", "/metrics")
        text = conn.getresponse().read().decode()
        conn.close()
        assert "tdapi_mutations_shed_total 1" in text
        assert "tdapi_mutations_inflight 0" in text
        assert "tdapi_idempotency_records" in text
        shed = [e for e in app.events.recent()
                if e["op"] == "admission.shed"]
        assert shed and shed[0]["code"] == 429
    finally:
        app.stop()


def test_duplicate_in_flight_409(tmp_path):
    """A duplicate arriving while the original is still executing answers
    409 (neither executes twice nor fabricates a result)."""
    app = make_app(tmp_path)
    app.start()
    release = threading.Event()
    entered = threading.Event()
    orig_create = app.backend.create

    def slow_create(name, spec):
        entered.set()
        release.wait(5)
        return orig_create(name, spec)

    app.backend.create = slow_create
    body = {"imageName": "i", "replicaSetName": "slow", "tpuCount": 1}
    first = []

    def runner():
        first.append(call(app, "POST", "/api/v1/replicaSet", body,
                          headers={"Idempotency-Key": "k-slow"}))

    try:
        t = threading.Thread(target=runner)
        t.start()
        assert entered.wait(5)
        status, hdrs, out = call(app, "POST", "/api/v1/replicaSet", body,
                                 headers={"Idempotency-Key": "k-slow"})
        assert status == 409 and out["code"] == 409, out
        assert hdrs["Retry-After"] == "1"
        release.set()
        t.join()
        assert first[0][2]["code"] == 200
        # now the duplicate replays
        _, hdrs, out = call(app, "POST", "/api/v1/replicaSet", body,
                            headers={"Idempotency-Key": "k-slow"})
        assert out["code"] == 200
        assert hdrs.get("Idempotency-Replayed") == "true"
        assert_no_leaks(app)
    finally:
        release.set()
        app.stop()


def test_error_outcomes_not_cached(tmp_path):
    """Failed mutations changed nothing (services unwind), so their
    responses are NOT cached: a retry under the same key re-executes
    instead of replaying a possibly-transient failure for the TTL."""
    app = make_app(tmp_path)
    app.start()
    try:
        body = {"imageName": "i", "replicaSetName": "big", "tpuCount": 99}
        _, h1, o1 = call(app, "POST", "/api/v1/replicaSet", body,
                         headers={"Idempotency-Key": "err-key"})
        assert o1["code"] == 1013        # not enough chips
        assert app.idempotency.record_count() == 0   # claim dropped
        # the retry re-executes — here with capacity that now fits
        body["tpuCount"] = 2
        _, h2, o2 = call(app, "POST", "/api/v1/replicaSet", body,
                         headers={"Idempotency-Key": "err-key"})
        assert o2["code"] == 200, o2
        assert "Idempotency-Replayed" not in h2
        assert_no_leaks(app)
    finally:
        app.stop()


def test_client_polls_in_flight_conflict(tmp_path):
    """A keyed retry racing its still-executing original (client-side
    timeout, server still working) gets 409 and POLLS for the stored
    result per Retry-After instead of surfacing a terminal error."""
    app = make_app(tmp_path)
    app.start()
    orig_create = app.backend.create

    def slow_create(name, spec):
        time.sleep(0.6)
        return orig_create(name, spec)

    app.backend.create = slow_create
    try:
        c = ApiClient("127.0.0.1", app.server.port, timeout=0.25,
                      retry_backoff=0.01)
        run = c.runReplicaSet(body={"imageName": "x",
                                    "replicaSetName": "racy",
                                    "tpuCount": 1})
        assert run["name"] == "racy-1"
        st = c.stats()
        assert st["replays"] >= 1        # answered from the result cache
        assert st["mutationRetries"] >= 1
        app.backend.create = orig_create
        stored = assert_no_leaks(app)
        assert stored["racy"].version == 1   # exactly one execution
    finally:
        app.backend.create = orig_create
        app.stop()


# ------------------------------------------------------- graceful drain

def test_stop_drains_inflight_mutation(tmp_path):
    """ApiServer.stop() must let an in-flight mutation finish and deliver
    its response instead of resetting the socket mid-write."""
    app = make_app(tmp_path)
    app.start()
    release = threading.Event()
    entered = threading.Event()
    orig_create = app.backend.create

    def slow_create(name, spec):
        entered.set()
        release.wait(5)
        return orig_create(name, spec)

    app.backend.create = slow_create
    result = []

    def runner():
        result.append(call(app, "POST", "/api/v1/replicaSet",
                           {"imageName": "i", "replicaSetName": "drainme",
                            "tpuCount": 1}))

    t = threading.Thread(target=runner)
    t.start()
    assert entered.wait(5)
    stopper = threading.Thread(target=app.stop)
    stopper.start()
    time.sleep(0.05)            # stop() is now draining
    release.set()
    t.join(10)
    stopper.join(15)
    assert result, "in-flight request was cut off by stop()"
    status, hdrs, out = result[0]
    assert out["code"] == 200, out
    assert hdrs.get("Connection") == "close"     # told to re-connect


# --------------------------------------------------------- TTL lifecycle

def test_idempotency_ttl_and_boot_sweep(tmp_path):
    from gpu_docker_api_tpu.idempotency import NEW, REPLAY, IdempotencyCache

    app = make_app(tmp_path)
    cache = IdempotencyCache(app.client, ttl=0.05)
    state, _ = cache.begin("k1", "fp")
    assert state == NEW
    cache.finish("k1", 200, 200, b'{"code": 200}')
    assert cache.begin("k1", "fp")[0] == REPLAY
    time.sleep(0.08)
    assert cache.begin("k1", "fp")[0] == NEW    # expired: fresh claim
    cache.finish("k1", 200, 200, b'{"code": 200}')
    time.sleep(0.08)
    assert cache.sweep() >= 1                   # maintenance path
    # boot sweep: an in_progress record with NO intent outcome (crashed
    # before any side effect) is dropped so the retry re-executes
    app.idempotency.begin("orphan", "fp")
    app.wq.close()
    app.store.close()
    app.events.close()
    app2 = make_app(tmp_path, backend=app.backend)
    assert app2.last_reconcile["idempotency"]["dropped"] == 1
    from gpu_docker_api_tpu.idempotency import NEW as NEW2
    assert app2.idempotency.begin("orphan", "fp")[0] == NEW2
    app2.idempotency.abandon("orphan")
    app2.stop()


# ------------------------------------------------------ client satellites

def test_client_close_releases_all_threads(tmp_path):
    app = make_app(tmp_path)
    app.start()
    try:
        c = ApiClient("127.0.0.1", app.server.port)
        c.ping()
        ready = threading.Barrier(4)

        def worker():
            c.ping()
            ready.wait(5)

        threads = [threading.Thread(target=worker) for _ in range(3)]
        for t in threads:
            t.start()
        ready.wait(5)
        for t in threads:
            t.join()
        with c._conns_lock:
            pooled = list(c._conns)
        assert len(pooled) == 4         # one socket per thread
        c.close()
        assert all(conn.sock is None for conn in pooled), \
            "close() left another thread's socket open"
        with c._conns_lock:
            assert not c._conns
        assert c.ping() == {"status": "pong"}   # lazily re-pools
    finally:
        app.stop()


def test_client_disables_idempotency_for_old_server_spec(tmp_path):
    """Against a daemon whose spec doesn't advertise Idempotency-Key,
    the client must fall back to never retrying mutations — a resend
    there would double-apply."""
    import copy

    app = make_app(tmp_path)
    app.start()
    try:
        c = ApiClient("127.0.0.1", app.server.port)
        assert c.idempotency is True
        old_spec = copy.deepcopy(c.spec)
        for methods in old_spec["paths"].values():
            for op in methods.values():
                if isinstance(op, dict) and "parameters" in op:
                    op["parameters"] = [
                        p for p in op["parameters"]
                        if p.get("name") != "Idempotency-Key"]
        old = ApiClient("127.0.0.1", app.server.port, spec=old_spec)
        assert old.idempotency is False
    finally:
        app.stop()


def test_client_transparent_retry_on_dropped_response(tmp_path):
    """End to end: the server drops the response; the client's keyed
    retry machinery absorbs it; the mutation lands exactly once and the
    stats surface what happened."""
    app = make_app(tmp_path)
    app.start()
    try:
        c = ApiClient("127.0.0.1", app.server.port, retry_backoff=0.01)
        faults.arm_fault("POST /api/v1/replicaSet:drop_response")
        run = c.runReplicaSet(body={"imageName": "x",
                                    "replicaSetName": "once",
                                    "tpuCount": 2})
        assert run["name"] == "once-1"
        st = c.stats()
        assert st["mutationRetries"] + st["staleRetries"] >= 1
        assert st["replays"] == 1
        stored = assert_no_leaks(app)
        assert stored["once"].version == 1
        # client-side If-Match plumbing rides the generated methods
        with pytest.raises(ApiError) as ei:
            c.patchReplicaSet(name="once",
                              body={"tpuPatch": {"tpuCount": 1}},
                              if_match=9)
        assert ei.value.code == 412
        out = c.patchReplicaSet(name="once",
                                body={"tpuPatch": {"tpuCount": 1}},
                                if_match=1)
        assert out["version"] == 2
    finally:
        faults.disarm_faults()
        app.stop()
