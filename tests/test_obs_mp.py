"""Cross-process telemetry plane sweep (`obs` marker, worker-tier half).

Four layers:

- SHARD MATH: the shm metric shards' fixed-bucket histograms
  (obs/shm_metrics.py) must aggregate to exactly what the in-process
  python Histogram computes for the same observations — bucket layout
  mirroring is the merge's correctness condition;
- SEQLOCK: a scrape racing a shard reset (gateway slot reassigned) must
  see the full pre-reset totals or all-zeros, never a torn mix; the
  recorder ring tolerates torn slots by skipping them;
- CRASH: SIGKILL a worker mid-request — the watchdog's postmortem
  bundle carries the dead worker's shm flight-recorder segment and the
  claim-reconcile delta, surfaced as a `gateway.worker_postmortem`
  event and in the /healthz workers block;
- LIVE REST: with a real worker tier, the daemon's /metrics covers
  worker-served requests under the SAME families as in-process serving
  (metric-family parity), and GET /api/v1/traces/{id} returns the
  stitched client -> worker admit/route -> replica trace.
"""

from __future__ import annotations

import http.client
import json
import threading
import time

import pytest

from gpu_docker_api_tpu.events import EventLog
from gpu_docker_api_tpu.obs import metrics as obs_metrics
from gpu_docker_api_tpu.obs import shm_metrics
from gpu_docker_api_tpu.obs import trace
from gpu_docker_api_tpu.obs.recorder import FlightRecorder
from gpu_docker_api_tpu.obs.spool import SpanSpool, SpoolTailer
from gpu_docker_api_tpu.obs.trace import TraceCollector

workers = pytest.importorskip("gpu_docker_api_tpu.server.workers")
from test_workers import FakeManager, StubReplica, data_call, rep  # noqa: E402

pytestmark = [
    pytest.mark.obs,
    pytest.mark.skipif(not workers.available(),
                       reason="worker tier unavailable "
                              "(no Linux SO_REUSEPORT / native core)"),
]


@pytest.fixture()
def shards():
    st = shm_metrics.MetricShards(create=True)
    yield st
    st.close(unlink=True)


# ------------------------------------------------------------ shard math

def test_shard_aggregation_matches_python_histogram(shards):
    """Observations spread across shards must sum to exactly the python
    Histogram's view of the same values — including boundary values (the
    le-cumulative contract) and the overflow cell."""
    h = obs_metrics.Histogram("t_lat", buckets=shm_metrics.LAT_BUCKETS_MS)
    values = [0.3, 1.0, 1.0001, 7.5, 25.0, 999.0, 2500.0, 99999.0,
              12.5, 0.0]
    for i, v in enumerate(values):
        h.observe(v)
        shards.observe_latency(i % 3, 0, v)     # 3 shards, one gateway
    agg = shards.aggregate(0)["lat"]
    snap = h.snapshot()
    # cumulative per-bucket equality
    cum = 0
    for bound, n in zip(shm_metrics.LAT_BUCKETS_MS, agg["buckets"]):
        cum += n
        assert cum == snap["buckets"][bound], bound
    assert cum + agg["buckets"][-1] == snap["inf"]
    assert agg["count"] == snap["count"] == len(values)
    # sums agree to the shard's integer-microsecond resolution
    assert abs(agg["sumMs"] - snap["sum"]) < 1e-2


def test_histogram_extern_merges_shard_cells(shards):
    """set_extern: shard data merges into the SAME family in-process
    observations land in — render and snapshot both see the union."""
    h = obs_metrics.Histogram("t_gw", labels=("gateway",),
                              buckets=shm_metrics.LAT_BUCKETS_MS)
    h.observe(5.0, gateway="g")
    shards.observe_latency(0, 0, 5.0)
    shards.observe_latency(1, 0, 700.0)

    def extern():
        lat = shards.aggregate(0)["lat"]
        return {("g",): (lat["buckets"], lat["sumMs"], lat["count"])}

    h.set_extern(extern)
    snap = h.snapshot(gateway="g")
    assert snap["count"] == 3
    assert abs(snap["sum"] - 710.0) < 1e-2
    text = "\n".join(h.render())
    assert 't_gw_count{gateway="g"} 3' in text
    # clearing the hook restores the in-process-only view
    h.set_extern(None)
    assert h.snapshot(gateway="g")["count"] == 1


def test_counter_parity_families_present_without_workers(tmp_path):
    """Family parity, static half: an App with the worker tier OFF still
    declares every tdapi_gw_worker_* family (and the gateway families),
    so dashboards built against either serving mode see the same family
    set — values are just zero/empty."""
    from gpu_docker_api_tpu.server.app import App
    from gpu_docker_api_tpu.topology import make_topology

    app = App(state_dir=str(tmp_path / "s"), backend="mock",
              addr="127.0.0.1:0", topology=make_topology("v5p-8"),
              api_key="", cpu_cores=4, store_maint_records=0)
    try:
        text = app.metrics.render() + obs_metrics.REGISTRY.render()
        for fam in ("tdapi_gw_workers_alive",
                    "tdapi_gw_worker_respawns_total",
                    "tdapi_gw_worker_requests_total",
                    "tdapi_gw_worker_shed_total",
                    "tdapi_gw_worker_deadline_total",
                    "tdapi_gw_worker_retries_total",
                    "tdapi_gw_worker_queue_wait_ms",
                    "tdapi_gateway_request_duration_ms"):
            assert f"# TYPE {fam} " in text, fam
        app.events.record("tpu.cordon", target="0")   # mirror check
    finally:
        app.stop()
    # the daemon's own flight recorder flushed on graceful stop (the
    # SIGTERM/atexit half of the recorder contract), mirroring events
    blob = json.loads((tmp_path / "s" / "recorder-daemon.json")
                      .read_text())
    kinds = {e["k"] for e in blob["entries"]}
    assert "stop" in kinds and "event" in kinds


# ------------------------------------------------------------- seqlock

def test_scrape_during_reset_never_torn(shards):
    """A reset (slot reassignment zeroing every shard's cells) racing a
    scrape: the aggregate is the FULL pre-reset picture or all-zeros —
    a mixed read (some shards zeroed, some not; count without matching
    sum) is exactly the torn read the per-gateway seqlock exists to
    prevent."""
    K = 48
    V = 10.0

    def populate():
        for i in range(K):
            s = i % 4
            shards.inc(s, 0, shm_metrics.C_REQUESTS)
            shards.observe_latency(s, 0, V)

    bad: list = []
    for _ in range(60):
        populate()                       # quiescent: no reader racing
        results: list = []

        def read_many():
            for _ in range(15):
                results.append(shards.aggregate(0))

        t = threading.Thread(target=read_many)
        t.start()
        shards.reset_gateway(0)
        t.join(10)
        for a in results:
            c = a["lat"]["count"]
            req = sum(w["requests"] for w in a["perWorker"])
            if (c, req) not in ((K, K), (0, 0)) \
                    or abs(a["lat"]["sumMs"] - c * V) > 1e-2:
                bad.append((c, req, a["lat"]["sumMs"]))
    assert not bad, f"torn scrapes: {bad[:5]}"


def test_ring_roundtrip_truncation_and_torn_slot(shards):
    """Ring entries round-trip oldest-first and wrap; an oversized entry
    is truncated (and skipped on parse failure); a manually-torn slot is
    skipped rather than crashing the postmortem read."""
    for i in range(shm_metrics.RING_SLOTS + 5):
        shards.ring_note(0, {"k": "e", "i": i})
    got = shards.read_ring(0)
    assert len(got) == shm_metrics.RING_SLOTS
    assert got[0]["i"] == 5 and got[-1]["i"] == shm_metrics.RING_SLOTS + 4
    # oversized payload truncates -> unparseable -> skipped, not raised
    shards.ring_note(1, {"k": "big", "pad": "x" * 4096})
    assert shards.read_ring(1) == []
    # torn slot: garbage bytes with a plausible length word
    shards.ring_note(2, {"k": "ok"})
    off = shm_metrics._sh_ring_slot_off(2, 1)
    shards.shm.buf[off + 8:off + 8 + 4] = b"\xff\xfe\x00{"
    shards.store(off, 4)
    shards.add(shm_metrics._sh_ring_off(2), 1)
    assert [e["k"] for e in shards.read_ring(2)] == ["ok"]


def test_flight_recorder_ring_sink_and_flush(shards, tmp_path):
    """FlightRecorder mirrors notes into the shm ring (the SIGKILL
    survivor) and flushes its in-memory ring to the postmortem file on
    graceful exit; a broken sink never fails note()."""
    rec = FlightRecorder(capacity=32, sink=shards.ring_writer(3))
    for i in range(4):
        rec.note("req", gw="g", i=i)
    assert [e["i"] for e in shards.read_ring(3)] == [0, 1, 2, 3]
    path = str(tmp_path / "rec.json")
    assert rec.flush_to(path)
    blob = json.loads(open(path).read())
    assert blob["notesTotal"] == 4
    assert [e["k"] for e in blob["entries"]] == ["req"] * 4
    broken = FlightRecorder(sink=lambda e: (_ for _ in ()).throw(
        RuntimeError("segment gone")))
    broken.note("still", fine=True)      # must not raise
    assert broken.dump()[-1]["k"] == "still"


# ------------------------------------------------------ spool -> merge

def test_span_spool_merges_into_collector(tmp_path):
    """Worker-side spans spooled to spans-<pid>.jsonl merge into a
    daemon TraceCollector with trace identity, root finalization, and
    partial-line tolerance."""
    spool_dir = tmp_path / "spans"
    spool_dir.mkdir()
    spool = SpanSpool(str(spool_dir / "spans-123.jsonl"))
    tid = trace.new_trace_id()
    parent = trace.format_traceparent(tid, trace.new_span_id())
    with trace.root_span(spool, "POST /x/:name/generate",
                         traceparent=parent, target="g"):
        with trace.span("gateway.admit", target="g"):
            pass
        with trace.span("gateway.forward", target="g") as fsp:
            fsp.event("replica.queue_wait", ms=1.5)
    spool.close()

    traces = TraceCollector(None)
    tailer = SpoolTailer(str(spool_dir), traces)
    merged = tailer.poll()
    assert merged == 3
    t = traces.get(tid)
    assert t is not None and t["status"] == "ok"
    ops = {s["op"] for s in t["spans"]}
    assert {"POST /x/:name/generate", "gateway.admit",
            "gateway.forward"} <= ops
    fwd = next(s for s in t["spans"] if s["op"] == "gateway.forward")
    assert fwd["events"][0] == {"name": "replica.queue_wait",
                                "t": fwd["events"][0]["t"], "ms": 1.5}
    # a torn tail line (worker died mid-write) parks until completed
    with open(spool_dir / "spans-123.jsonl", "a") as f:
        f.write('{"traceId": "')
    assert tailer.poll() == 0
    with open(spool_dir / "spans-123.jsonl", "a") as f:
        f.write(f'{tid}", "spanId": "{trace.new_span_id()}", '
                f'"op": "late", "start": 0, "durationMs": 1}}\n')
    assert tailer.poll() == 1


# ------------------------------------------------- crash: postmortem

@pytest.fixture()
def stub():
    s = StubReplica()
    yield s
    s.close()


def test_sigkill_mid_request_yields_postmortem(stub, tmp_path):
    """SIGKILL the only worker while it holds the replica's slot: the
    watchdog's reap must surface a gateway.worker_postmortem event whose
    bundle carries the shm flight-recorder segment (the in-flight
    request is visible in it — no handler ever ran in the worker) and
    the claim-reconcile delta."""
    events = EventLog(None)
    traces = TraceCollector(None)
    mgr = FakeManager([{"name": "g", "maxQueue": 8, "deadlineMs": 4000,
                        "replicas": [rep(stub.port, slots=1)]}])
    tier = workers.WorkerTier(mgr, n=1, events=events, traces=traces,
                              spool_dir=str(tmp_path / "spans"))
    tier.start()
    try:
        deadline = time.time() + 15
        out = {}
        while time.time() < deadline:
            try:
                _, _, out = data_call(tier.port)
                if out.get("code") == 200:
                    break
            except OSError:
                time.sleep(0.05)
        assert out.get("code") == 200, out
        stub.hold.clear()
        # a client-traced request: ring entries per request are gated on
        # the traceparent (untraced hot-path cost), so the postmortem's
        # recorder segment names exactly the traffic an operator can
        # also look up by trace id
        tp = trace.format_traceparent(trace.new_trace_id(),
                                      trace.new_span_id())
        t = threading.Thread(
            target=lambda: data_call(tier.port, timeout=3,
                                     headers={"traceparent": tp}))
        t.start()
        deadline = time.time() + 5
        while time.time() < deadline and stub.inflight == 0:
            time.sleep(0.02)
        assert stub.inflight == 1
        tier.procs[0].kill()
        t.join(10)
        stub.hold.set()
        deadline = time.time() + 10
        while time.time() < deadline and not tier.postmortems:
            time.sleep(0.05)
        assert tier.postmortems, "watchdog never captured a postmortem"
        pm = tier.postmortems[-1]
        assert pm["worker"] == 0
        assert pm["reclaimedClaims"] >= 1
        assert pm["claimDelta"].get("g", {}).get("claims", 0) >= 1
        kinds = [e.get("k") for e in pm["recorder"]]
        assert "req" in kinds, kinds     # the in-flight request survived
        assert "boot" in kinds or len(kinds) >= 1
        evts = [e for e in events.recent(limit=50)
                if e["op"] == "gateway.worker_postmortem"]
        assert evts and evts[-1]["target"] == "worker-0"
        assert evts[-1]["reclaimed"] >= 1
        assert tier.describe()["postmortems"]
    finally:
        tier.stop()


def test_respawn_preserves_cumulative_counters(stub):
    """A worker respawn must not reset its shard (counters are
    cumulative per SLOT): totals stay monotonic across the kill, so a
    scrape during respawn never sees the data plane's history vanish."""
    mgr = FakeManager([{"name": "g", "maxQueue": 8, "deadlineMs": 4000,
                        "replicas": [rep(stub.port, slots=2)]}])
    tier = workers.WorkerTier(mgr, n=1)
    tier.start()
    try:
        deadline = time.time() + 15
        served = 0
        while time.time() < deadline and served < 5:
            try:
                _, _, out = data_call(tier.port)
                if out.get("code") == 200:
                    served += 1
            except OSError:
                time.sleep(0.05)
        assert served == 5
        before = tier.per_worker_counts()["g"][0]["requests"]
        assert before >= 5
        tier.procs[0].kill()
        deadline = time.time() + 10
        while time.time() < deadline and tier.respawns < 1:
            time.sleep(0.05)
        assert tier.per_worker_counts()["g"][0]["requests"] >= before
        deadline = time.time() + 10
        out = {}
        while time.time() < deadline:
            try:
                _, _, out = data_call(tier.port)
                if out.get("code") == 200:
                    break
            except OSError:
                time.sleep(0.05)
        assert out.get("code") == 200
        assert tier.per_worker_counts()["g"][0]["requests"] > before
    finally:
        tier.stop()


# --------------------------------------------- live REST e2e (slowish)

class TelemetryStubReplica(StubReplica):
    """StubReplica speaking the full telemetry contract: traceparent
    echo + X-TDAPI-Queue-Wait-Ms on responses (mock_model/serve.py
    parity) — what the worker stitches into its forward span."""

    def __init__(self):
        super().__init__()
        self.srv.RequestHandlerClass = self._wrap(
            self.srv.RequestHandlerClass)

    @staticmethod
    def _wrap(base):
        class H(base):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0) or 0)
                self.rfile.read(n)
                body = b'{"code":200,"msg":"ok","data":{"tokens":[[1]]}}'
                try:
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(body)))
                    tp = self.headers.get("traceparent")
                    if tp:
                        self.send_header("traceparent", tp)
                    self.send_header("X-TDAPI-Queue-Wait-Ms", "2.25")
                    self.end_headers()
                    self.wfile.write(body)
                except OSError:
                    pass
        return H


def _api(port, method, path, body=None, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=15)
    try:
        payload = json.dumps(body).encode() if body is not None else b""
        conn.request(method, path, payload,
                     {"Content-Type": "application/json",
                      **(headers or {})})
        resp = conn.getresponse()
        raw = resp.read()
        try:
            return json.loads(raw)
        except ValueError:
            return {"raw": raw.decode("utf-8", "replace")}
    finally:
        conn.close()


@pytest.fixture()
def telemetry_app(tmp_path):
    from gpu_docker_api_tpu.gateway import READY, GatewayConfig
    from gpu_docker_api_tpu.server.app import App
    from gpu_docker_api_tpu.topology import make_topology

    replica = TelemetryStubReplica()
    app = App(state_dir=str(tmp_path / "state"), backend="mock",
              addr="127.0.0.1:0", port_range=(47200, 47300),
              topology=make_topology("v5p-8"), api_key="", cpu_cores=8,
              store_maint_records=0, gw_workers=4)
    app.start()
    try:
        assert app.workers is not None
        app.gateways.create(GatewayConfig(
            name="gw", image="img", cmd=["serve"],
            minReplicas=1, maxReplicas=2, readiness="running",
            scaleDownIdleS=3600, deadlineMs=4000, maxQueue=16))
        gw = app.gateways.get("gw")
        deadline = time.time() + 10
        while time.time() < deadline and not any(
                r.state is READY for r in gw.replicas.values()):
            time.sleep(0.05)
        with gw._cond:
            for r in gw.replicas.values():
                r.host_port = replica.port
        app.workers.poke()
        deadline = time.time() + 15
        out = {}
        while time.time() < deadline:
            try:
                _, _, out = data_call(app.workers.port, name="gw")
                if out.get("code") == 200:
                    break
            except OSError:
                pass
            time.sleep(0.05)
        assert out.get("code") == 200, out
        yield app, replica
    finally:
        app.stop()
        replica.close()


def test_e2e_trace_daemon_worker_replica(telemetry_app):
    """The acceptance walk: a data-plane request with a client
    traceparent, served by a WORKER process, shows up at the daemon's
    GET /api/v1/traces/{id} as the stitched chain — worker ingress root
    honoring the client trace id, admit + forward children, and the
    replica's queue-wait as a span event on the forward."""
    app, _ = telemetry_app
    tid = trace.new_trace_id()
    parent = trace.format_traceparent(tid, trace.new_span_id())
    _, _, out = data_call(app.workers.port, name="gw",
                          headers={"traceparent": parent})
    assert out.get("code") == 200, out
    t = None
    deadline = time.time() + 10
    while time.time() < deadline:
        got = _api(app.server.port, "GET", f"/api/v1/traces/{tid}")
        if got.get("code") == 200:
            t = got["data"]["trace"]
            if len(t["spans"]) >= 3:
                break
        time.sleep(0.1)
    assert t is not None, "worker spans never merged into the daemon"
    ops = {s["op"] for s in t["spans"]}
    assert "POST /api/v1/gateways/:name/generate" in ops
    assert "gateway.admit" in ops and "gateway.forward" in ops
    fwd = next(s for s in t["spans"] if s["op"] == "gateway.forward")
    evs = {e["name"]: e for e in fwd.get("events", [])}
    assert "replica.queue_wait" in evs
    assert evs["replica.queue_wait"]["ms"] == 2.25
    # the tree hangs together: admit/forward nest under the worker root
    root = next(s for s in t["spans"]
                if s["op"] == "POST /api/v1/gateways/:name/generate")
    assert fwd["parentId"] == root["spanId"]
    # and the summary list knows the trace
    lst = _api(app.server.port, "GET",
               "/api/v1/traces?op=generate&limit=10")
    assert any(r["traceId"] == tid
               for r in lst["data"]["traces"])


def test_metric_family_parity_and_truthful_latency(telemetry_app):
    """Family parity, dynamic half: worker-served requests land in the
    SAME tdapi_gateway_* families the in-process path feeds — the
    duration family's count covers worker traffic, the gw_worker_*
    families attribute it per worker, and /healthz carries the workers
    block."""
    app, _ = telemetry_app
    for _ in range(6):
        _, _, out = data_call(app.workers.port, name="gw")
        assert out.get("code") == 200
    deadline = time.time() + 5
    text = ""
    while time.time() < deadline:
        text = _api(app.server.port, "GET", "/metrics")["raw"]
        if 'tdapi_gateway_request_duration_ms_count{gateway="gw"}' in text:
            count = int([
                ln for ln in text.splitlines()
                if ln.startswith(
                    'tdapi_gateway_request_duration_ms_count'
                    '{gateway="gw"}')][0].split()[-1])
            if count >= 7:
                break
        time.sleep(0.1)
    assert 'tdapi_gateway_request_duration_ms_count{gateway="gw"}' in text
    count = int([ln for ln in text.splitlines()
                 if ln.startswith('tdapi_gateway_request_duration_ms_'
                                  'count{gateway="gw"}')][0].split()[-1])
    assert count >= 7            # the fixture's probe + our 6
    assert 'tdapi_gateway_requests_total{gateway="gw"}' in text
    # per-worker attribution exists and sums to at least our traffic
    wk_lines = [ln for ln in text.splitlines()
                if ln.startswith("tdapi_gw_worker_requests_total{")]
    assert wk_lines
    assert sum(int(ln.split()[-1]) for ln in wk_lines) >= 7
    assert "tdapi_gw_workers_alive 4" in text
    # queue-wait histogram is fed
    assert 'tdapi_gw_worker_queue_wait_ms_count{gateway="gw"}' in text
    # healthz workers block: telemetry armed, postmortems list present
    hz = _api(app.server.port, "GET", "/api/v1/healthz")["data"]
    assert hz["workers"]["telemetry"] is True
    assert hz["workers"]["postmortems"] == []
    # family parity with the workers-off mode is pinned by
    # test_counter_parity_families_present_without_workers — here the
    # worker-mode exposition must carry the same family declarations
    for fam in ("tdapi_gw_worker_shed_total",
                "tdapi_gw_worker_deadline_total",
                "tdapi_gw_worker_retries_total"):
        assert f"# TYPE {fam} " in text, fam
