"""Fractional-chip multi-tenancy sweep (`mt` marker; make verify-mt).

Three layers, matching the feature's structure:

1. scheduler share-ledger invariants (schedulers/tpu.py): no
   oversubscription under concurrent applies, whole/fractional mixing,
   exact owner-checked release, serialize/restore round-trip, cordon
   exclusion;
2. service plumbing (services/replicaset.py): grant lifecycle through
   run/patch/stop/restart/delete, failure unwind, drain of co-tenants
   with zero leaked shares, crash-mid-replace reconcile;
3. the per-chip concurrency regulator (regulator.py): weighted time
   sharing, latency-class preemption with bounded stall, preempt events,
   and the REST/metrics surface.
"""

import json
import threading
import time

import pytest

from gpu_docker_api_tpu import regulator as regmod
from gpu_docker_api_tpu import xerrors
from gpu_docker_api_tpu.backend import MockBackend
from gpu_docker_api_tpu.dtos import ContainerRun, PatchRequest, TpuPatch
from gpu_docker_api_tpu.schedulers import (
    SHARE_QUANTA, CpuScheduler, PortScheduler, TpuScheduler, parse_tpu_count,
)
from gpu_docker_api_tpu.services import ReplicaSetService
from gpu_docker_api_tpu.store import MVCCStore, StateClient
from gpu_docker_api_tpu.topology import make_topology
from gpu_docker_api_tpu.version import MergeMap, VersionMap
from gpu_docker_api_tpu.workqueue import WorkQueue

pytestmark = pytest.mark.mt


# ---------------------------------------------------------------- scheduler

def test_parse_tpu_count():
    assert parse_tpu_count(2) == (2, 0)
    assert parse_tpu_count(4.0) == (4, 0)
    assert parse_tpu_count(0) == (0, 0)
    assert parse_tpu_count(0.25) == (0, 1)
    assert parse_tpu_count(0.5) == (0, 2)
    assert parse_tpu_count(0.75) == (0, 3)
    for bad in (-1, -0.25, 0.3, 1.5, 2.25):
        with pytest.raises(ValueError):
            parse_tpu_count(bad)


def test_fractional_packing_and_freecount():
    s = TpuScheduler(topology=make_topology("v4-16"))       # 8 chips
    a = s.apply_shares(2, "a")
    b = s.apply_shares(2, "b")
    assert a == b                                   # packed onto one chip
    c = s.apply_shares(3, "c")
    assert c != a                                   # no room left on a
    st = s.get_status()
    assert st["freeCount"] == 6.25                  # 6 whole + 1 quantum
    assert st["freeShares"] == 25
    chip = next(ch for ch in st["chips"] if ch["index"] == a)
    assert chip["shares"] == {"a": 2, "b": 2}
    assert chip["used"] and chip["owner"] == ""
    assert chip["freeShares"] == 0


def test_no_oversubscription_under_concurrent_applies():
    s = TpuScheduler(topology=make_topology("v4-16"))       # 8 chips = 32 q
    granted: list[tuple[str, int]] = []
    lock = threading.Lock()

    def worker(i):
        for j in range(8):
            owner = f"t{i}-{j}"
            try:
                chip = s.apply_shares(3, owner)
            except xerrors.TpuOversubscribedError:
                continue
            with lock:
                granted.append((owner, chip))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # every chip's ledger within capacity, and the ledger exactly matches
    # the successful grants
    for chip, owners in s.shares.items():
        assert sum(owners.values()) <= SHARE_QUANTA
    assert len(granted) == sum(
        1 for owners in s.shares.values() for _ in owners)
    for owner, chip in granted:
        assert s.shares[chip][owner] == 3


def test_whole_and_fractional_mixing():
    s = TpuScheduler(topology=make_topology("v4-16"))
    chip = s.apply_shares(2, "frac")
    whole = s.apply(4, "whole")
    assert chip not in whole                # shared chip invisible to whole
    # whole-granted chips invisible to fractional placement: 8 chips,
    # 4 whole-granted, chip has 2/4 used -> exactly 14 quanta left
    for _ in range(14):
        c = s.apply_shares(1, "more")
        assert c not in whole
    with pytest.raises(xerrors.TpuOversubscribedError):
        s.apply_shares(1, "flood")
    # the oversubscribed error is still a TpuNotEnoughError for
    # share-unaware callers
    assert issubclass(xerrors.TpuOversubscribedError,
                      xerrors.TpuNotEnoughError)


def test_release_exact_and_owner_checked():
    s = TpuScheduler(topology=make_topology("v4-16"))
    chip = s.apply_shares(2, "a")
    s.apply_shares(2, "b")
    # wrong owner / wrong chip: no-ops
    assert s.restore_shares(chip, 2, "ghost") == 0
    assert s.restore_shares(chip + 1, 2, "a") == 0
    assert s.shares[chip] == {"a": 2, "b": 2}
    # over-release clamps to the holding; double release frees nothing
    assert s.restore_shares(chip, 99, "a") == 2
    assert s.restore_shares(chip, 2, "a") == 0
    assert s.shares[chip] == {"b": 2}
    assert s.restore_shares(chip, 2, "b") == 2
    assert chip not in s.shares
    assert s.get_status()["freeCount"] == 8


def test_serialize_restore_roundtrip():
    store = MVCCStore()
    client = StateClient(store)
    wq = WorkQueue(client)
    wq.start()
    try:
        s = TpuScheduler(client, wq, topology=make_topology("v4-16"))
        chip = s.apply_shares(3, "a")
        s.apply_shares(1, "b")
        s.apply(2, "whole")
        s.cordon([7])
        wq.join()
        s.flush()
        s2 = TpuScheduler(client, wq)
        assert s2.shares == s.shares
        assert s2.status == s.status
        assert s2.cordoned == s.cordoned
        assert s2.get_status()["freeCount"] == s.get_status()["freeCount"]
        # restored ledger still enforces capacity
        with pytest.raises(xerrors.TpuOversubscribedError):
            s2.apply_shares(1, "c", prefer=chip)    # prefer ignored: full
            for _ in range(64):
                s2.apply_shares(3, "c")
    finally:
        wq.close()


def test_cordon_excludes_shared_chips():
    s = TpuScheduler(topology=make_topology("v4-16"))
    chip = s.apply_shares(1, "a")
    s.cordon([chip])
    # remaining quanta of a cordoned chip are not allocatable
    st = s.get_status()
    assert st["freeCount"] == 7
    assert next(c for c in st["chips"]
                if c["index"] == chip)["freeShares"] == 0
    c2 = s.apply_shares(1, "b")
    assert c2 != chip
    # the existing tenant keeps its shares (cordon never yanks)
    assert s.shares[chip] == {"a": 1}


# ------------------------------------------------------------------ service

@pytest.fixture()
def world(tmp_path):
    store = MVCCStore()
    client = StateClient(store)
    wq = WorkQueue(client)
    wq.start()
    backend = MockBackend(str(tmp_path / "state"))
    tpu = TpuScheduler(client, wq, topology=make_topology("v4-16"))
    cpu = CpuScheduler(client, wq, core_count=16)
    ports = PortScheduler(client, wq, port_range=(42000, 42100), seed=11)
    rs = ReplicaSetService(backend, client, wq, tpu, cpu, ports,
                           VersionMap("containerVersionMap", client, wq),
                           MergeMap(client, wq))
    yield rs, backend, tpu, wq, client
    wq.close()


def _run_frac(rs, name, count=0.5, priority="best_effort"):
    return rs.run_container(ContainerRun(
        imageName="ubuntu:22.04", replicaSetName=name, tpuCount=count,
        priority=priority))


def test_run_fractional_co_tenants(world):
    rs, backend, tpu, wq, _ = world
    r1 = _run_frac(rs, "hi", 0.5, "latency")
    r2 = _run_frac(rs, "lo", 0.5)
    assert r1["tpuShares"] == 2 and r1["priority"] == "latency"
    assert r1["tpuChips"] == r2["tpuChips"]          # co-located
    st = backend.inspect("hi-1")
    assert "TDAPI_TPU_SHARES=2" in st.spec.env
    assert "TDAPI_PRIORITY=latency" in st.spec.env
    assert st.spec.tpu_env.get("TPU_VISIBLE_CHIPS")
    assert tpu.get_status()["freeCount"] == 7


def test_patch_transitions_and_unwind(world):
    rs, backend, tpu, wq, _ = world
    _run_frac(rs, "t", 0.5)
    chip = rs._stored_info("t").spec.tpu_chips[0]
    # fraction -> fraction (same chip preferred when capacity allows:
    # 2 held + 1 new = 3 <= 4, so the resize stays put)
    r = rs.patch_container("t", PatchRequest(tpuPatch=TpuPatch(0.25)))
    assert r["tpuShares"] == 1 and r["tpuChips"] == [chip]
    assert tpu.shares[chip] == {"t": 1}
    # fraction -> whole
    r = rs.patch_container("t", PatchRequest(tpuPatch=TpuPatch(2)))
    assert r["tpuShares"] == 0 and len(r["tpuChips"]) == 2
    assert tpu.shares == {}
    # whole -> fraction
    r = rs.patch_container("t", PatchRequest(tpuPatch=TpuPatch(0.75)))
    assert r["tpuShares"] == 3
    assert tpu.shares[r["tpuChips"][0]] == {"t": 3}
    assert tpu.get_status()["freeCount"] == 7.25
    # failed patch (impossible whole count) leaves the ledger untouched
    with pytest.raises(xerrors.TpuNotEnoughError):
        rs.patch_container("t", PatchRequest(tpuPatch=TpuPatch(64)))
    assert tpu.shares[r["tpuChips"][0]] == {"t": 3}
    assert tpu.get_status()["freeCount"] == 7.25


def test_stop_restart_delete_release_exact(world):
    rs, backend, tpu, wq, _ = world
    _run_frac(rs, "a", 0.25)
    _run_frac(rs, "b", 0.5)
    chip = rs._stored_info("a").spec.tpu_chips[0]
    rs.stop_container("a")
    assert tpu.shares[chip] == {"b": 2}             # exact release, b kept
    rs.restart_container("a")
    assert tpu.shares[chip]["a"] == 1               # re-granted (packed)
    rs.delete_container("a")
    rs.delete_container("b")
    assert tpu.shares == {}
    assert tpu.get_status()["freeCount"] == 8


def test_drain_migrates_co_tenants_zero_leaked_shares(world):
    rs, backend, tpu, wq, _ = world
    for n in ("t1", "t2", "t3"):
        _run_frac(rs, n, 0.25)
    _run_frac(rs, "big", 0.75)                      # second chip
    chip = rs._stored_info("t1").spec.tpu_chips[0]
    tpu.cordon([chip])
    res = rs.drain_cordoned()
    moved = {d["name"] for d in res["drained"]}
    assert {"t1", "t2", "t3"} <= moved
    assert not res["failed"]
    # zero leaked shares: cordoned chip's ledger empty, every tenant's
    # quanta intact elsewhere, totals conserved
    assert chip not in tpu.shares
    total = sum(q for owners in tpu.shares.values()
                for q in owners.values())
    assert total == 1 + 1 + 1 + 3
    for d in res["drained"]:
        assert chip not in d["toChips"]


def test_crash_mid_replace_reconciles_shares(world, monkeypatch):
    from gpu_docker_api_tpu import faults
    from gpu_docker_api_tpu.intents import IntentJournal
    from gpu_docker_api_tpu.reconcile import Reconciler
    rs, backend, tpu, wq, client = world
    _run_frac(rs, "t", 0.5)
    _run_frac(rs, "peer", 0.25)
    chip = rs._stored_info("t").spec.tpu_chips[0]
    faults.arm("replace.after_create")
    try:
        with pytest.raises(faults.InjectedCrash):
            rs.patch_container("t", PatchRequest(tpuPatch=TpuPatch(0.75)))
    finally:
        faults.disarm_all()
    # daemon died mid-replace: replay intents + cross-check on a fresh
    # reconciler; the ledger must settle to exactly the stored records
    # (t back at 0.5 on its chip, peer untouched, no orphan quanta)
    rec = Reconciler(backend, client, wq, tpu,
                     CpuScheduler(client, wq, core_count=16),
                     PortScheduler(client, wq, port_range=(42000, 42100)),
                     VersionMap("containerVersionMap", client, wq),
                     VersionMap("volumeVersionMap", client, wq),
                     MergeMap(client, wq), IntentJournal(client),
                     replicasets=rs)
    rec.run()
    rs.invalidate("t")
    info = rs._stored_info("t")
    # the replace settles forward (new record persisted before the crash)
    # or unwinds — either way the ledger must EXACTLY match the surviving
    # records: t's quanta where its record says, peer untouched, not one
    # orphan quantum anywhere
    assert info.spec.tpu_shares in (2, 3)
    t_chip = info.spec.tpu_chips[0]
    assert tpu.shares[t_chip]["t"] == info.spec.tpu_shares
    assert tpu.shares[chip]["peer"] == 1
    total = sum(q for owners in tpu.shares.values() for q in owners.values())
    assert total == info.spec.tpu_shares + 1


# ---------------------------------------------------------------- regulator

class _EventSink:
    def __init__(self):
        self.events = []

    def record(self, op, **kw):
        self.events.append((op, kw))


def test_weighted_sharing_converges():
    reg = regmod.ChipRegulator(0)
    a = reg.register("a", weight=3)
    b = reg.register("b", weight=1)
    stop = time.monotonic() + 0.6

    def run(t):
        while time.monotonic() < stop:
            with t.slice(tokens=1):
                time.sleep(0.002)

    threads = [threading.Thread(target=run, args=(t,)) for t in (a, b)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # chip time under saturation converges to the 3:1 weight ratio;
    # generous window for scheduler jitter
    ratio = a.busy_seconds / max(b.busy_seconds, 1e-9)
    assert 1.8 < ratio < 5.0, ratio
    assert reg.chunks_total == a.chunks + b.chunks


def test_latency_preempts_best_effort_bounded_stall():
    sink = _EventSink()
    reg = regmod.ChipRegulator(3, events=sink)
    be = reg.register("be", weight=4)
    hi = reg.register("hi", weight=1, priority="latency")
    chunk_s = 0.05
    saw_yield = []

    def holder():
        with be.slice():
            time.sleep(chunk_s)
            saw_yield.append(be.should_yield())

    th = threading.Thread(target=holder)
    th.start()
    time.sleep(0.01)                    # holder mid-chunk
    t0 = time.perf_counter()
    with hi.slice():
        waited = time.perf_counter() - t0
    th.join()
    # bounded stall: the latency tenant waited at most the in-flight
    # chunk (+ scheduler slack), never a full round of co-tenants
    assert waited < chunk_s + 0.05, waited
    assert saw_yield == [True]          # holder was told to yield
    assert be.preempted == 1
    assert reg.preempt_total == 1
    assert [e for e in sink.events if e[0] == "regulator.preempt"]
    # flag clears with the release
    assert not be.should_yield()


def test_latency_class_skips_the_queue():
    reg = regmod.ChipRegulator(0)
    be1 = reg.register("be1", weight=2)
    be2 = reg.register("be2", weight=2)
    hi = reg.register("hi", weight=1, priority="latency")
    order = []
    lock = threading.Lock()

    def run(t, n):
        for _ in range(n):
            with t.slice():
                with lock:
                    order.append(t.name)
                time.sleep(0.004)

    threads = [threading.Thread(target=run, args=(t, 10))
               for t in (be1, be2)]
    for t in threads:
        t.start()
    time.sleep(0.01)
    run(hi, 5)
    for t in threads:
        t.join()
    # every hi admission happened before the best-effort queue drained:
    # hi never waited behind more than the chunk in flight
    last_hi = max(i for i, n in enumerate(order) if n == "hi")
    assert last_hi < len(order) - 1, order


def test_single_tenant_uncontended():
    reg = regmod.ChipRegulator(0)
    t = reg.register("solo", weight=4)
    for _ in range(100):
        with t.slice(tokens=1):
            pass
    assert t.chunks == 100 and t.tokens == 100
    assert reg.queue_depth() == 0
    assert not t.should_yield()


def test_duplicate_names_never_displace_a_tenant():
    """Two tenants registering the same label must BOTH stay admittable
    — a silent dict replace would strand the displaced tenant's
    acquire() forever (its serving loop would deadlock)."""
    reg = regmod.ChipRegulator(0)
    a = reg.register("tenant-v1", weight=2)
    b = reg.register("tenant-v1", weight=2)
    assert a is not b
    done = []

    def run(t):
        for _ in range(5):
            with t.slice():
                time.sleep(0.001)
        done.append(t)

    threads = [threading.Thread(target=run, args=(t,)) for t in (a, b)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert len(done) == 2               # neither deadlocked
    assert a.chunks == 5 and b.chunks == 5
    a.unregister()
    assert len(reg.describe()["tenants"]) == 1


def test_registry_and_snapshot():
    regmod.reset()
    try:
        r0 = regmod.for_chip(0)
        assert regmod.for_chip(0) is r0
        t = r0.register("x", weight=2)
        with t.slice(tokens=3):
            pass
        snap = regmod.snapshot()
        assert any(r["chip"] == 0 and r["chunksTotal"] == 1
                   and r["tenants"][0]["tokens"] == 3 for r in snap)
    finally:
        regmod.reset()


def test_batcher_ticks_through_regulator():
    """serve._Batcher issues its device chunks through a tenant slice:
    two tiny batchers sharing one regulator both complete, and the
    regulator accounts their chunks."""
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp
    from gpu_docker_api_tpu.models.llama import LlamaConfig, init_params
    from gpu_docker_api_tpu.workloads.serve import _Batcher

    regmod.reset()
    try:
        config = LlamaConfig.tiny()
        params = init_params(config, jax.random.key(0))
        reg = regmod.for_chip(0)
        hi = reg.register("hi", weight=2, priority="latency")
        lo = reg.register("lo", weight=2)
        b_hi = _Batcher(config, params, slots=2, max_len=64,
                        regulator=hi, seed=0)
        b_lo = _Batcher(config, params, slots=2, max_len=64,
                        regulator=lo, seed=0, decode_chunk=4)
        try:
            prompt = jnp.ones((8,), jnp.int32)
            outs = []

            def ask(b):
                outs.append(b.submit(prompt, 12))

            threads = [threading.Thread(target=ask, args=(b,))
                       for b in (b_hi, b_lo) for _ in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert len(outs) == 4
            assert all(len(o) == 12 for o in outs)
        finally:
            b_hi.close()
            b_lo.close()
        d = reg.describe()
        by = {t["name"]: t for t in d["tenants"]}
        assert by["hi"]["chunks"] > 0 and by["lo"]["chunks"] > 0
        assert d["queueDepth"] == 0
    finally:
        regmod.reset()


# ------------------------------------------------------------- REST surface

@pytest.fixture()
def app(tmp_path):
    from gpu_docker_api_tpu.server.app import App
    regmod.reset()
    a = App(state_dir=str(tmp_path / "state"), backend="mock",
            addr="127.0.0.1:0", port_range=(43000, 43100),
            topology=make_topology("v4-32"), api_key="", cpu_cores=16)
    a.start()
    yield a
    a.stop()
    regmod.reset()


def _call(app, method, path, body=None):
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", app.server.port,
                                      timeout=10)
    payload = json.dumps(body) if body is not None else None
    conn.request(method, path, payload,
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    raw = resp.read()
    conn.close()
    return resp.status, json.loads(raw) if raw else None


def test_api_fractional_run_freecount_and_oversubscription(app):
    # fractional run via the wire format
    _, body = _call(app, "POST", "/api/v1/replicaSet", {
        "imageName": "img", "replicaSetName": "frac", "tpuCount": 0.5,
        "priority": "latency"})
    assert body["code"] == 200, body
    assert body["data"]["tpuShares"] == 2
    assert body["data"]["priority"] == "latency"
    chip = body["data"]["tpuChips"][0]
    # freeCount reports allocatable SHARES in chip units (the small fix:
    # fractional capacity visible to clients)
    _, body = _call(app, "GET", "/api/v1/resources/tpus")
    tpus = body["data"]["tpus"]
    assert tpus["freeCount"] == 15.5
    assert tpus["freeShares"] == 62
    assert tpus["chips"][chip]["shares"] == {"frac": 2}
    # invalid fraction and invalid priority are client errors
    for bad in ({"tpuCount": 0.3}, {"tpuCount": 1.5},
                {"priority": "urgent"}):
        req = {"imageName": "img", "replicaSetName": "bad", "tpuCount": 1}
        req.update(bad)
        _, body = _call(app, "POST", "/api/v1/replicaSet", req)
        assert body["code"] == 1000, (bad, body)
    # fill the fleet's shares, then expect the oversubscribed code
    for i in range(1000):
        _, body = _call(app, "POST", "/api/v1/replicaSet", {
            "imageName": "img", "replicaSetName": f"f{i}",
            "tpuCount": 0.75})
        if body["code"] != 200:
            break
    assert body["code"] == 1026, body


def test_api_metrics_export_shares_and_regulator(app):
    _, body = _call(app, "POST", "/api/v1/replicaSet", {
        "imageName": "img", "replicaSetName": "frac", "tpuCount": 0.25})
    assert body["code"] == 200
    chip = body["data"]["tpuChips"][0]
    # exercise a regulator so its gauges exist
    t = regmod.for_chip(chip).register("frac", weight=1)
    with t.slice(tokens=4):
        pass
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", app.server.port,
                                      timeout=10)
    conn.request("GET", "/metrics")
    text = conn.getresponse().read().decode()
    conn.close()
    assert f'tdapi_tpu_shares_allocated{{chip="{chip}"}} 1' in text
    assert "tdapi_tpu_shares_allocated_total 1" in text
    assert "tdapi_tpu_shares_allocatable 63" in text
    assert "tdapi_tpu_shares_utilization" in text
    assert f'tdapi_regulator_chunks_total{{chip="{chip}"}} 1' in text
    assert f'tdapi_regulator_queue_depth{{chip="{chip}"}} 0' in text
    assert f'tdapi_regulator_preemptions_total{{chip="{chip}"}} 0' in text


def test_api_regulator_preempt_event_lands_on_event_log(app):
    reg = regmod.for_chip(0)
    be = reg.register("be", weight=4)
    hi = reg.register("hi", weight=1, priority="latency")

    def holder():
        with be.slice():
            time.sleep(0.03)

    th = threading.Thread(target=holder)
    th.start()
    time.sleep(0.005)
    with hi.slice():
        pass
    th.join()
    _, body = _call(app, "GET", "/api/v1/events?limit=50")
    ops = [e["op"] for e in body["data"]["events"]]
    assert "regulator.preempt" in ops
