"""Tier-1-safe control-plane throughput smoke (`make verify-perf`).

Floors are DELIBERATELY generous — an order of magnitude under the numbers
a loaded dev machine produces (bench.py's scheduling extra records 250+
chips/sec at concurrency 16; the floors here are 25) — so this can run in
the default tier on any CI box without flaking, while still catching the
failure mode that matters: a regression that re-serializes the hot path
(per-record WAL flushes, per-request TCP setup, O(op) scheduler dumps)
costs 3-10x, which no amount of machine noise hides behind a 10x margin.
"""

from __future__ import annotations

import http.client
import json
import threading
import time

import pytest

pytestmark = pytest.mark.perf

CHIPS_PER_RS = 4
FLOOR_CHIPS_PER_SEC = 25        # bench records ~10x this; see module doc
FLOOR_STORE_OPS_PER_SEC = 2000  # store_bench records ~10x this
FLOOR_REGULATOR_OPS_PER_SEC = 20000   # uncontended slices run ~100x this
CEIL_REGULATOR_OVERHEAD_PCT = 30      # bench records ~1%; criterion is 5
CEIL_OBS_OVERHEAD_PCT = 30            # bench records ~1-2%; criterion is 5


@pytest.fixture()
def app(tmp_path):
    from gpu_docker_api_tpu.server.app import App
    from gpu_docker_api_tpu.topology import make_topology

    a = App(state_dir=str(tmp_path / "state"), backend="mock",
            addr="127.0.0.1:0", topology=make_topology("v4-128"),
            api_key="", cpu_cores=16)
    a.start()
    yield a
    a.stop()


def _cycle(conn, name: str) -> None:
    for method, path, body in (
            ("POST", "/api/v1/replicaSet",
             {"imageName": "x", "replicaSetName": name,
              "tpuCount": CHIPS_PER_RS}),
            ("DELETE", f"/api/v1/replicaSet/{name}", None)):
        conn.request(method, path,
                     json.dumps(body) if body is not None else None,
                     {"Content-Type": "application/json"})
        out = json.loads(conn.getresponse().read())
        assert out.get("code") == 200, out


def test_scheduling_throughput_floor(app):
    """Full REST stack on the mock substrate, 4 keep-alive clients: the
    control plane must schedule comfortably more than FLOOR chips/sec."""
    conc, per_client = 4, 6
    warm = http.client.HTTPConnection("127.0.0.1", app.server.port, timeout=30)
    _cycle(warm, "warm")
    warm.close()
    errs: list = []

    def client(cid: int) -> None:
        conn = http.client.HTTPConnection("127.0.0.1", app.server.port,
                                          timeout=30)
        try:
            for j in range(per_client):
                _cycle(conn, f"perf{cid}x{j}")
        except Exception as e:  # noqa: BLE001
            errs.append(f"client {cid}: {e!r}")
        finally:
            conn.close()

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(conc)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    assert not errs, errs
    chips_per_sec = conc * per_client * CHIPS_PER_RS / dt
    assert chips_per_sec >= FLOOR_CHIPS_PER_SEC, (
        f"control-plane throughput collapsed: {chips_per_sec:.1f} chips/sec "
        f"< floor {FLOOR_CHIPS_PER_SEC} (was the hot path re-serialized?)")


def test_clone_tree_beats_serial_seed_copy(tmp_path):
    """The copy fast path (utils/copyfast.py) must not fall behind the
    serial seed walk it replaced. Fixture: 96 x 256 KB files (24 MB) —
    enough files that the pool's parallelism (sendfile/copy_file_range
    release the GIL) shows, small enough for any CI box. The margin is
    DELIBERATELY generous (fast path may take up to 1.5x the serial walk's
    time before this fails): the target failure mode is a rewrite that
    re-serializes or re-buffers the copy path into a 3-10x regression, not
    machine noise."""
    import os
    import shutil

    from gpu_docker_api_tpu.utils.copyfast import clone_tree

    src = tmp_path / "layer"
    src.mkdir()
    blob = os.urandom(256 * 1024)
    for i in range(96):
        sub = src / f"d{i % 8}"
        sub.mkdir(exist_ok=True)
        (sub / f"f{i}.bin").write_bytes(blob)

    def serial_seed_copy(s: str, d: str) -> None:
        # the pre-copyfast copy_dir: recursive scandir + copy2, one file
        # at a time (utils/file.py at the seed)
        os.makedirs(d, exist_ok=True)
        for entry in os.scandir(s):
            dp = os.path.join(d, entry.name)
            if entry.is_dir():
                serial_seed_copy(entry.path, dp)
            else:
                shutil.copy2(entry.path, dp, follow_symlinks=False)

    # warm the page cache so the comparison is copy-path, not disk; the
    # two sides are timed INTERLEAVED (serial, fast, serial, fast) with
    # best-of per side, so a load spike on a busy CI box hits both rather
    # than deciding the verdict
    serial_seed_copy(str(src), str(tmp_path / "warm"))
    t_serial = float("inf")
    t_fast = float("inf")
    stats = None
    for i in range(2):
        t0 = time.perf_counter()
        serial_seed_copy(str(src), str(tmp_path / f"serial{i}"))
        t_serial = min(t_serial, time.perf_counter() - t0)
        t0 = time.perf_counter()
        stats = clone_tree(str(src), str(tmp_path / f"fast{i}"))
        t_fast = min(t_fast, time.perf_counter() - t0)

    assert stats.files == 96 and stats.bytes == 96 * 256 * 1024
    assert (tmp_path / "fast0" / "d0" / "f0.bin").read_bytes() == blob
    assert t_fast <= t_serial * 2.0, (
        f"copy fast path regressed: clone_tree {t_fast:.3f}s vs serial "
        f"seed walk {t_serial:.3f}s (floor: 2.0x — generous; the target "
        f"failure is a 3-10x re-serialization) — was the pool or the "
        f"copy ladder re-serialized?")


def test_store_put_throughput_floor(tmp_path):
    """WAL-backed store writes (group-commit path, 4 concurrent writers)
    must stay comfortably above FLOOR ops/sec on both engines."""
    from gpu_docker_api_tpu.store import native_available, open_store

    engines = ["python"] + (["native"] if native_available() else [])
    for engine in engines:
        s = open_store(wal_path=str(tmp_path / f"perf-{engine}.wal"),
                       engine=engine)
        n, conc = 500, 4
        errs: list = []

        def writer(wid: int, store=s) -> None:
            try:
                for j in range(n):
                    store.put(f"/perf/{wid}/k{j % 50}", f"v{j}")
            except Exception as e:  # noqa: BLE001
                errs.append(repr(e))

        threads = [threading.Thread(target=writer, args=(i,))
                   for i in range(conc)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        s.close()
        assert not errs, errs
        ops = conc * n / dt
        assert ops >= FLOOR_STORE_OPS_PER_SEC, (
            f"{engine} store puts collapsed: {ops:.0f} ops/sec < "
            f"floor {FLOOR_STORE_OPS_PER_SEC}")


def test_regulator_single_tenant_overhead_floor():
    """The co-tenancy regulator on a DEDICATED stream (one tenant, no
    contention) must be nearly free: a raw acquire/release floor, plus a
    bounded overhead ratio on a simulated decode stream whose chunks cost
    ~1ms — the single-tenant case every non-shared serving loop pays.
    Acceptance pins <= 5% in bench; the floor here is 30% so a loaded CI
    box cannot flake while a regression to per-chunk locking/IO still
    trips it."""
    from gpu_docker_api_tpu import regulator as regmod

    reg = regmod.ChipRegulator(0)
    t = reg.register("solo", weight=4)

    # raw admission throughput
    n = 2000
    t0 = time.perf_counter()
    for _ in range(n):
        with t.slice(tokens=1):
            pass
    ops = n / (time.perf_counter() - t0)
    assert ops >= FLOOR_REGULATOR_OPS_PER_SEC, (
        f"regulator admission collapsed: {ops:.0f} slices/sec < "
        f"floor {FLOOR_REGULATOR_OPS_PER_SEC}")

    # overhead on a chunked stream (chunk ~= 1ms of device work)
    def spin(seconds: float) -> None:
        end = time.perf_counter() + seconds
        while time.perf_counter() < end:
            pass

    chunks, chunk_s = 150, 0.001
    t0 = time.perf_counter()
    for _ in range(chunks):
        spin(chunk_s)
    raw = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(chunks):
        with t.slice(tokens=8):
            spin(chunk_s)
    reg_t = time.perf_counter() - t0
    overhead = (reg_t - raw) / raw * 100
    assert overhead <= CEIL_REGULATOR_OVERHEAD_PCT, (
        f"single-tenant regulator overhead {overhead:.1f}% > "
        f"{CEIL_REGULATOR_OVERHEAD_PCT}% ceiling (raw {raw:.4f}s, "
        f"regulated {reg_t:.4f}s)")


def test_obs_overhead_ceiling(app):
    """Tracing + histograms armed (the default) vs disarmed through the
    full REST stack. Disarm flips BOTH halves (trace.set_enabled +
    metrics.set_enabled) so the delta prices the whole obs layer, not
    just spans. Estimator matches bench.py's: per-round armed/disarmed
    ratios (arms adjacent in time, so this container's 2x throughput
    drift cancels within a round), order alternated per round, cleanest
    round wins — noise only inflates a ratio, a real obs tax shows in
    every round. bench.py's c16 sweep pins the real number (criterion
    <= 5%); the ceiling here is 30% so a loaded CI box cannot flake while
    a regression to per-span syscalls or synchronous serialization still
    trips it. Note every OTHER floor in this module already runs with
    tracing armed — that is the 'floors stay green' half of the
    acceptance."""
    from gpu_docker_api_tpu.obs import metrics as obs_metrics
    from gpu_docker_api_tpu.obs import trace

    def _arm(on: bool) -> None:
        trace.set_enabled(on)
        obs_metrics.set_enabled(on)

    conn = http.client.HTTPConnection("127.0.0.1", app.server.port,
                                      timeout=30)
    _cycle(conn, "obswarm")

    def run(tag: str, n: int = 16) -> float:
        t0 = time.perf_counter()
        for j in range(n):
            _cycle(conn, f"{tag}x{j}")
        return n / (time.perf_counter() - t0)

    armed, disarmed = [], []
    try:
        for rnd in range(4):
            order = ((False, disarmed, "off"), (True, armed, "on")) \
                if rnd % 2 == 0 else \
                ((True, armed, "on"), (False, disarmed, "off"))
            for on, acc, tag in order:
                _arm(on)
                acc.append(run(f"obs{tag}{rnd}"))
    finally:
        _arm(True)
    conn.close()
    overhead = min(max(0.0, (1.0 - a / d) * 100)
                   for a, d in zip(armed, disarmed))
    assert overhead <= CEIL_OBS_OVERHEAD_PCT, (
        f"obs overhead {overhead:.1f}% > {CEIL_OBS_OVERHEAD_PCT}% ceiling "
        f"(per-round armed {[round(x, 1) for x in armed]}/s vs disarmed "
        f"{[round(x, 1) for x in disarmed]}/s)")


FLOOR_NATIVE_PUT_MANY_PER_SEC = 20000   # native batched+fsync runs ~20x this
FLOOR_NATIVE_SPEEDUP_BATCHED = 1.2      # bench records ~3-5x; criterion 1.5


def test_native_batched_fsync_puts_beat_python(tmp_path):
    """The store_native_speedup criterion's tier-1 shadow: batched puts
    with fsync ON through the native core must beat the python engine
    (both group-commit, so the delta is the per-record python-side cost
    the core eliminates). Floors are generous — the target regression is
    the core quietly losing its batch commit (per-record flush/fsync
    again), which costs 5-20x. Skips when the core isn't built."""
    from gpu_docker_api_tpu.store import native_available, open_store

    if not native_available():
        pytest.skip("native core not built")

    def run(engine):
        s = open_store(wal_path=str(tmp_path / f"bm-{engine}.wal"),
                       engine=engine, fsync=True)
        best = 0.0
        try:
            for _ in range(2):               # best-of-2 (noisy CI box)
                t0 = time.perf_counter()
                for b in range(4):
                    s.put_many([(f"/bm/k{i % 50}", f"v{b}-{i}")
                                for i in range(250)])
                best = max(best, 1000 / (time.perf_counter() - t0))
        finally:
            s.close()
        return best

    native = run("native")
    python = run("python")
    assert native >= FLOOR_NATIVE_PUT_MANY_PER_SEC, (
        f"native batched fsync puts collapsed: {native:.0f} ops/sec < "
        f"floor {FLOOR_NATIVE_PUT_MANY_PER_SEC} (did the core lose its "
        f"group commit?)")
    assert native >= python * FLOOR_NATIVE_SPEEDUP_BATCHED, (
        f"native no longer beats python on batched durable puts: "
        f"{native:.0f} vs {python:.0f} ops/sec (criterion 1.5x; floor "
        f"{FLOOR_NATIVE_SPEEDUP_BATCHED}x)")


def test_native_box_search_not_a_pessimization():
    """topology_alloc.cc's keep-it verdict, pinned: at v4-128 scale the
    memo-gated native box search must not be slower than the pure-python
    candidate scan it accelerates (generous 1.5x margin — the target
    failure is the gate breaking so every call pays native marshalling
    AND the python scan, or the core itself regressing). Skips when the
    core isn't built."""
    import random
    from unittest import mock

    from gpu_docker_api_tpu._native import load
    from gpu_docker_api_tpu.schedulers.tpu import TpuScheduler
    from gpu_docker_api_tpu.topology import TpuTopology

    if load("topoalloc") is None:
        pytest.skip("native core not built")
    # single-worker mesh: the native path applies to every size
    topo = TpuTopology("v4-128", "v4", (4, 4, 4), chips_per_host=64)
    sched = TpuScheduler(None, topology=topo)
    rng = random.Random(11)
    for i in rng.sample(range(64), 24):
        sched.status[i] = "x"
    free = {i for i, o in sched.status.items() if o is None}
    sizes = (1, 2, 4, 8)
    for n in sizes:
        sched._box_candidates(n)             # warm the memo for both arms

    def sweep():
        for n in sizes:
            sched._find_box(n, free)

    t_native = t_python = float("inf")
    for _ in range(3):                       # interleaved best-of (noise)
        t0 = time.perf_counter()
        for _ in range(30):
            sweep()
        t_native = min(t_native, time.perf_counter() - t0)
        with mock.patch.object(sched, "_native_find_box",
                               return_value=None):
            t0 = time.perf_counter()
            for _ in range(30):
                sweep()
            t_python = min(t_python, time.perf_counter() - t0)
    assert t_native <= t_python * 1.5, (
        f"native-assisted box search is a pessimization: {t_native:.4f}s "
        f"vs python-only {t_python:.4f}s at v4-128 — is the memo gate "
        f"broken?")


FLOOR_WORKER_TIER_RPS = 150   # one worker + stub replica runs ~10-30x this


def test_worker_tier_throughput_floor():
    """The multi-process data plane end-to-end (real SO_REUSEPORT worker
    process, shared-memory claims, stub replica): a generous floor that
    catches the tier re-serializing (e.g. per-request roster reads going
    seqlock-retry-bound, per-request connection setup, futex storms).
    Skips when the tier is unavailable."""
    import http.server
    import socketserver

    try:
        from gpu_docker_api_tpu.server import workers
    except ImportError:
        pytest.skip("worker tier module unavailable")
    if not workers.available():
        pytest.skip("worker tier unavailable")

    class H(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # without NODELAY the stub's header/body segments wait out the
        # worker's delayed ACK (~40ms) and the floor measures Nagle
        disable_nagle_algorithm = True

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0) or 0)
            self.rfile.read(n)
            body = b'{"code":200,"msg":"ok","data":{}}'
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    rep = socketserver.ThreadingTCPServer(("127.0.0.1", 0), H)
    rep.daemon_threads = True
    threading.Thread(target=rep.serve_forever, daemon=True).start()
    rport = rep.server_address[1]

    class Mgr:
        on_change = None

        def router_states(self):
            return [{"name": "g", "maxQueue": 64, "deadlineMs": 10000,
                     "replicas": [{"port": rport, "slots": 16,
                                   "ready": True}]}]

        def get(self, name):
            raise KeyError(name)

    tier = workers.WorkerTier(Mgr(), n=1)
    tier.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", tier.port,
                                          timeout=10)
        deadline = time.time() + 15
        while time.time() < deadline:       # worker boot
            try:
                conn.request("POST", "/api/v1/gateways/g/generate", b"{}",
                             {"Content-Type": "application/json"})
                if json.loads(conn.getresponse().read()).get(
                        "code") == 200:
                    break
            except OSError:
                conn.close()
                conn = http.client.HTTPConnection("127.0.0.1", tier.port,
                                                  timeout=10)
                time.sleep(0.05)
        n = 150
        best = 0.0
        for _ in range(2):
            t0 = time.perf_counter()
            for _ in range(n):
                conn.request("POST", "/api/v1/gateways/g/generate", b"{}",
                             {"Content-Type": "application/json"})
                out = json.loads(conn.getresponse().read())
                assert out.get("code") == 200, out
            best = max(best, n / (time.perf_counter() - t0))
        conn.close()
        assert best >= FLOOR_WORKER_TIER_RPS, (
            f"worker-tier data plane collapsed: {best:.0f} rps < floor "
            f"{FLOOR_WORKER_TIER_RPS}")
    finally:
        tier.stop()
        rep.shutdown()


FLOOR_ROUTER_FWD_PER_SEC = 5000       # uncontended forwards run ~10-15x this
FLOOR_ROUTER_CONTENDED_PER_SEC = 500  # 4-thread GIL-bound runs ~10x this


def test_gateway_router_admit_floor():
    """The gateway router's claim/forward/release path on an injected
    no-op transport: admission (FIFO fast path + least-queued pick) must
    stay far from re-serializing — a per-request connection setup, a
    sleep in the claim loop, or ticket-chained notify_all on the
    uncontended path all cost 10x+, which the generous floors catch."""
    import threading as _threading

    from gpu_docker_api_tpu.gateway import (
        READY, Gateway, GatewayConfig, Replica,
    )

    def transport(port, method, path, body, timeout):
        return 200, b'{"code":200,"msg":"ok","data":{}}'

    gw = Gateway(GatewayConfig(name="g", image="i", deadlineMs=5000,
                               maxQueue=512),
                 services=None, intents=None, transport=transport)
    for i in range(2):
        r = Replica(f"r{i}", i)
        r.state = READY
        r.slots = 8
        r.host_port = 1000 + i
        gw.replicas[r.name] = r

    n = 4000
    best = 0.0
    for _ in range(2):                      # best-of-2 (noisy container)
        t0 = time.perf_counter()
        for _ in range(n):
            gw.forward(b"{}")
        best = max(best, n / (time.perf_counter() - t0))
    assert best >= FLOOR_ROUTER_FWD_PER_SEC, (
        f"router admit throughput {best:.0f}/s < "
        f"{FLOOR_ROUTER_FWD_PER_SEC}/s floor")

    per_thread, workers = 1500, 4
    t0 = time.perf_counter()

    def worker():
        for _ in range(per_thread):
            gw.forward(b"{}")

    threads = [_threading.Thread(target=worker) for _ in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    rate = workers * per_thread / (time.perf_counter() - t0)
    assert rate >= FLOOR_ROUTER_CONTENDED_PER_SEC, (
        f"contended router throughput {rate:.0f}/s < "
        f"{FLOOR_ROUTER_CONTENDED_PER_SEC}/s floor")
