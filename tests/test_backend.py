"""Backend seam tests: mock and process substrates through the Backend ABC."""

import functools
import os
import subprocess
import sys
import time

import pytest

from gpu_docker_api_tpu.backend import MockBackend, ProcessBackend, make_backend
from gpu_docker_api_tpu.dtos import ContainerSpec


@functools.lru_cache(maxsize=1)
def _rlimit_data_enforced() -> bool:
    """RLIMIT_DATA covers private writable mappings only on kernel >= 4.7;
    older kernels (and some sandboxes) limit just brk, so a big bytearray
    sails past the limit. Probe instead of parsing uname — containers lie."""
    probe = ("import resource; "
             "resource.setrlimit(resource.RLIMIT_DATA, (50 * 1024 * 1024,) * 2); "
             "b = bytearray(200 * 1024 * 1024)")
    try:
        rc = subprocess.run([sys.executable, "-c", probe],
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL, timeout=60).returncode
    except (OSError, subprocess.TimeoutExpired):
        return False
    return rc != 0


def _require_rlimit_data():
    """Call-time skip (a decorator would run the 200MB probe subprocess at
    collection of EVERY pytest invocation, deselected runs included)."""
    if not _rlimit_data_enforced():
        pytest.skip("kernel cannot enforce RLIMIT_DATA on mappings (needs >= 4.7)")


@pytest.fixture(params=["mock", "process"])
def backend(request, tmp_path):
    b = make_backend(request.param, str(tmp_path / "state"))
    yield b
    b.close()


def _spec(**kw):
    d = dict(image="", cmd=["sleep", "30"], env=["FOO=bar"])
    d.update(kw)
    return ContainerSpec(**d)


def test_create_start_inspect_stop(backend):
    backend.create("rs-1", _spec())
    st = backend.inspect("rs-1")
    assert st.exists and not st.running
    backend.start("rs-1")
    st = backend.inspect("rs-1")
    assert st.running
    assert st.upper_dir and os.path.isdir(st.upper_dir)
    backend.stop("rs-1")
    assert not backend.inspect("rs-1").running
    backend.remove("rs-1")
    assert not backend.inspect("rs-1").exists


def test_duplicate_create_rejected(backend):
    backend.create("rs-1", _spec())
    with pytest.raises(RuntimeError):
        backend.create("rs-1", _spec())


def test_list_names_prefix(backend):
    backend.create("foo-1", _spec())
    backend.create("foo-2", _spec())
    backend.create("bar-1", _spec())
    assert backend.list_names("foo-") == ["foo-1", "foo-2"]


def test_remove_running_requires_force(backend):
    backend.create("rs-1", _spec())
    backend.start("rs-1")
    with pytest.raises(RuntimeError):
        backend.remove("rs-1", force=False)
    backend.remove("rs-1", force=True)
    assert not backend.inspect("rs-1").exists


def test_volumes(backend):
    v = backend.volume_create("vol", size_bytes=1024 ** 2)
    assert v.exists and os.path.isdir(v.mountpoint)
    with open(os.path.join(v.mountpoint, "data.bin"), "wb") as f:
        f.write(b"z" * 2048)
    got = backend.volume_inspect("vol")
    assert got.used_bytes == 2048
    with pytest.raises(RuntimeError):
        backend.volume_create("vol")
    backend.volume_remove("vol")
    assert not backend.volume_inspect("vol").exists


# ---- process-backend-specific behavior ----

def test_process_exec_real_output(tmp_path):
    b = ProcessBackend(str(tmp_path / "s"))
    b.create("rs-1", _spec(env=["GREETING=hello"]))
    b.start("rs-1")
    code, out = b.execute("rs-1", ["sh", "-c", "echo $GREETING world"])
    assert code == 0
    assert "hello world" in out
    b.close()


def test_process_tpu_env_injection(tmp_path):
    b = ProcessBackend(str(tmp_path / "s"))
    b.create("rs-1", _spec(tpu_env={"TPU_VISIBLE_CHIPS": "0,1"}))
    b.start("rs-1")
    code, out = b.execute("rs-1", ["sh", "-c", "echo chips=$TPU_VISIBLE_CHIPS"])
    assert "chips=0,1" in out
    b.close()


def test_process_pause_continue(tmp_path):
    b = ProcessBackend(str(tmp_path / "s"))
    b.create("rs-1", _spec(cmd=["sleep", "30"]))
    b.start("rs-1")
    b.pause("rs-1")
    assert b.inspect("rs-1").paused
    b.restart_inplace("rs-1")
    st = b.inspect("rs-1")
    assert st.running and not st.paused
    b.close()


def test_process_binds_symlinked(tmp_path):
    data = tmp_path / "data"
    data.mkdir()
    (data / "weights.bin").write_bytes(b"W" * 10)
    b = ProcessBackend(str(tmp_path / "s"))
    b.create("rs-1", _spec(binds=[f"{data}:/root/foo-tmp"]))
    b.start("rs-1")
    code, out = b.execute("rs-1", ["sh", "-c", "cat $CONTAINER_ROOT/root/foo-tmp/weights.bin"])
    assert code == 0 and "WWWWWWWWWW" in out
    b.close()


def test_process_commit_and_seed(tmp_path):
    b = ProcessBackend(str(tmp_path / "s"))
    b.create("rs-1", _spec())
    b.start("rs-1")
    b.execute("rs-1", ["sh", "-c", "echo state > $CONTAINER_ROOT/file.txt"])
    b.commit("rs-1", "myimage:v1")
    b.create("rs-2", _spec(image="myimage:v1", cmd=["sleep", "30"]))
    b.start("rs-2")
    code, out = b.execute("rs-2", ["sh", "-c", "cat $CONTAINER_ROOT/file.txt"])
    assert code == 0 and "state" in out
    b.close()


def test_process_stop_terminates(tmp_path):
    b = ProcessBackend(str(tmp_path / "s"))
    b.create("rs-1", _spec(cmd=["sleep", "300"]))
    b.start("rs-1")
    pid = b.inspect("rs-1").pid
    assert pid is not None
    t0 = time.time()
    b.stop("rs-1", timeout=5)
    assert time.time() - t0 < 5
    st = b.inspect("rs-1")
    assert not st.running and st.exit_code is not None
    b.close()


def test_mock_exec_canned(tmp_path):
    b = MockBackend(str(tmp_path / "s"))
    b.create("rs-1", _spec())
    code, out = b.execute("rs-1", ["echo", "hi"])
    assert code == 1  # not running
    b.start("rs-1")
    code, out = b.execute("rs-1", ["echo", "hi"])
    assert code == 0 and "echo hi" in out


def test_docker_demux_frames():
    from gpu_docker_api_tpu.backend.docker import _demux_stream
    frame = b"\x01\x00\x00\x00\x00\x00\x00\x05hello" + b"\x02\x00\x00\x00\x00\x00\x00\x06 world"
    assert _demux_stream(frame) == "hello world"
    assert _demux_stream(b"plain tty output") == "plain tty output"


def test_process_port_grant_env(tmp_path):
    """The process substrate can't NAT like docker: granted host ports are
    exported so workloads bind them directly (serving workload contract).
    PORT = the FIRST-DECLARED container port, not the lexicographically
    smallest ("10001" < "8080" as strings)."""
    b = ProcessBackend(str(tmp_path / "s"))
    b.create("rs-1", _spec(port_bindings={"8080": 40123, "10001": 40456}))
    b.start("rs-1")
    code, out = b.execute(
        "rs-1", ["sh", "-c", "echo p=$PORT a=$HOST_PORT_8080 b=$HOST_PORT_10001"])
    assert code == 0
    assert "p=40123 a=40123 b=40456" in out
    b.close()


def test_process_port_env_daemon_port_does_not_leak(tmp_path, monkeypatch):
    """A PORT in the daemon's own environment must not override the grant;
    a PORT in the spec's env must."""
    monkeypatch.setenv("PORT", "1234")  # the daemon's own (e.g. PaaS) PORT
    b = ProcessBackend(str(tmp_path / "s"))
    b.create("rs-1", _spec(port_bindings={"8000": 40999}))
    b.start("rs-1")
    code, out = b.execute("rs-1", ["sh", "-c", "echo p=$PORT"])
    assert "p=40999" in out
    b.create("rs-2", _spec(port_bindings={"8000": 40999},
                           env=["PORT=7777"]))
    b.start("rs-2")
    code, out = b.execute("rs-2", ["sh", "-c", "echo p=$PORT"])
    assert "p=7777" in out
    b.close()


def test_process_memory_limit_enforced(tmp_path):
    """memory_bytes is a real RLIMIT_DATA, not bookkeeping: a workload
    allocating past its grant dies; the same workload under no limit
    succeeds."""
    _require_rlimit_data()
    alloc = "import sys; b = bytearray(400 * 1024 * 1024); print('ok')"
    b = ProcessBackend(str(tmp_path / "s"))
    b.create("fat", _spec(cmd=["python3", "-c", alloc],
                          memory_bytes=200 * 1024 * 1024))
    b.start("fat")
    b._get("fat").popen.wait(timeout=60)
    assert b.inspect("fat").exit_code != 0
    b.create("ok", _spec(cmd=["python3", "-c", alloc]))
    b.start("ok")
    b._get("ok").popen.wait(timeout=60)
    assert b.inspect("ok").exit_code == 0
    b.close()


def test_process_volume_quota_persisted(tmp_path):
    """The quota survives inspect (overlay2-XFS size= analog, service-level
    guarded) and never pollutes the volume's own contents/usage."""
    b = ProcessBackend(str(tmp_path / "s"))
    v = b.volume_create("vol", size_bytes=5 * 1024 ** 2)
    with open(os.path.join(v.mountpoint, "d.bin"), "wb") as f:
        f.write(b"x" * 1024)
    got = b.volume_inspect("vol")
    assert got.size_limit_bytes == 5 * 1024 ** 2
    assert got.used_bytes == 1024
    b.volume_remove("vol")
    assert not b.volume_inspect("vol").exists
    # recreating without a quota must not inherit the old one
    v2 = b.volume_create("vol")
    assert b.volume_inspect("vol").size_limit_bytes == 0
    b.close()


def test_process_volume_named_like_quota_dir(tmp_path):
    """Quota metadata lives in its own namespace: a volume named '.quotas'
    is just a volume, and removing it can't wipe other volumes' quotas."""
    b = ProcessBackend(str(tmp_path / "s"))
    b.volume_create("vol", size_bytes=1024)
    v = b.volume_create(".quotas")
    assert v.exists
    b.volume_remove(".quotas")
    assert b.volume_inspect("vol").size_limit_bytes == 1024
    b.close()


def test_process_exec_shares_memory_limit(tmp_path):
    """docker exec runs inside the container's -m cgroup; exec here gets
    the same RLIMIT_DATA as the main process."""
    _require_rlimit_data()
    b = ProcessBackend(str(tmp_path / "s"))
    b.create("rs-1", _spec(cmd=["sleep", "30"],
                           memory_bytes=200 * 1024 * 1024))
    b.start("rs-1")
    code, out = b.execute(
        "rs-1", ["python3", "-c",
                 "b = bytearray(400 * 1024 * 1024); print('survived')"])
    assert code != 0 and "survived" not in out
    b.close()
