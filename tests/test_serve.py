"""The serving workload: token-level generation over HTTP with the
control-plane envelope, checkpoint loading (including interleaved grouped
layouts), single-flight KV-cache decode."""

import http.client
import json
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gpu_docker_api_tpu.models.llama import LlamaConfig, init_params
from gpu_docker_api_tpu.workloads.serve import (
    _handler_for, _maybe_ungroup, _Server,
)


@pytest.fixture(scope="module")
def served():
    from http.server import ThreadingHTTPServer
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    srv = _Server(cfg, params)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0),
                                _handler_for(srv, "llama/tiny"))
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield cfg, params, httpd.server_address[1]
    httpd.shutdown()
    httpd.server_close()


def _call(port, method, path, body=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request(method, path,
                 json.dumps(body) if body is not None else None,
                 {"Content-Type": "application/json"})
    out = json.loads(conn.getresponse().read())
    conn.close()
    return out


def test_healthz(served):
    cfg, _, port = served
    out = _call(port, "GET", "/healthz")
    assert out["code"] == 200
    assert out["data"]["model"] == "llama/tiny"
    assert out["data"]["vocab"] == cfg.vocab_size
    assert out["data"]["params"] > 0


def test_generate_greedy_matches_direct(served):
    cfg, params, port = served
    prompt = [[5, 9, 2, 7], [1, 3, 3, 8]]
    out = _call(port, "POST", "/generate",
                {"tokens": prompt, "max_new": 6, "temperature": 0.0})
    assert out["code"] == 200, out
    got = out["data"]["tokens"]
    from gpu_docker_api_tpu.infer import generate
    want = generate(params, jnp.asarray(prompt, jnp.int32), cfg, 6,
                    temperature=0.0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_generate_bad_requests(served):
    _, _, port = served
    assert _call(port, "POST", "/generate", {})["code"] == 400
    assert _call(port, "POST", "/generate",
                 {"tokens": [[99999]], "max_new": 2})["code"] == 400
    assert _call(port, "POST", "/generate",
                 {"tokens": [[1, 2]], "max_new": 0})["code"] == 400
    assert _call(port, "POST", "/nope", {})["code"] == 404
    assert _call(port, "GET", "/nope")["code"] == 404


def test_maybe_ungroup_roundtrip():
    """Grouped (interleaved-checkpoint) layer layouts are detected by their
    two extra leading dims and converted back to the canonical stack."""
    import dataclasses
    from gpu_docker_api_tpu.parallel.pipeline import group_layers
    cfg = dataclasses.replace(LlamaConfig.tiny(), n_layers=4)
    params = init_params(cfg, jax.random.key(0))
    grouped = dict(params)
    grouped["layers"] = group_layers(params["layers"], pp=2, v=2)
    back = _maybe_ungroup(grouped, cfg)
    for a, b in zip(jax.tree.leaves(back), jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # canonical params pass through untouched
    same = _maybe_ungroup(params, cfg)
    assert jax.tree.leaves(same)[0] is jax.tree.leaves(params)[0]


@pytest.mark.slow
def test_generate_sampling_params_over_http(served):
    """The REST surface accepts top_k/top_p, and top_k=1 at any temperature
    is greedy (proves the kwargs actually reach generate)."""
    cfg, params, port = served
    prompt = [[5, 9, 2, 7]]
    greedy = _call(port, "POST", "/generate",
                   {"tokens": prompt, "max_new": 5})["data"]["tokens"]
    topk1 = _call(port, "POST", "/generate",
                  {"tokens": prompt, "max_new": 5, "temperature": 1.5,
                   "top_k": 1, "top_p": 0.9})
    assert topk1["code"] == 200, topk1
    assert topk1["data"]["tokens"] == greedy


def test_generate_sampling_validation(served):
    _, _, port = served
    base = {"tokens": [[1, 2]], "max_new": 2}
    assert _call(port, "POST", "/generate",
                 {**base, "top_p": 0.0})["code"] == 400
    assert _call(port, "POST", "/generate",
                 {**base, "top_p": 1.5})["code"] == 400
    assert _call(port, "POST", "/generate",
                 {**base, "top_k": -1})["code"] == 400
    assert _call(port, "POST", "/generate",
                 {**base, "temperature": -1.0})["code"] == 400
    assert _call(port, "POST", "/generate",
                 {**base, "temperature": 99.0})["code"] == 400


@pytest.mark.slow
def test_continuous_batching_concurrent_requests():
    """Three concurrent greedy requests through the batcher (2 slots, so
    one waits for a free slot) must each equal their solo greedy stream —
    admission mid-decode must not disturb running rows."""
    from gpu_docker_api_tpu.infer import generate
    from gpu_docker_api_tpu.workloads.serve import _Batcher

    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    srv = _Server(cfg, params)
    srv.batcher = _Batcher(cfg, params, slots=2, max_len=64)
    try:
        prompts = [
            jax.random.randint(jax.random.key(i), (4 + 3 * i,), 0,
                               cfg.vocab_size) for i in range(3)
        ]
        want = [np.asarray(generate(params, p[None], cfg, max_new=5))[0]
                for p in prompts]
        got = [None] * 3

        def ask(i):
            got[i] = srv.generate(np.asarray(prompts[i])[None].tolist(),
                                  max_new=5, temperature=0.0)[0]

        threads = [threading.Thread(target=ask, args=(i,)) for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        for i in range(3):
            np.testing.assert_array_equal(got[i], want[i])
    finally:
        srv.batcher.close()


def test_batcher_rejects_overlong_request():
    from gpu_docker_api_tpu.workloads.serve import _Batcher

    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    b = _Batcher(cfg, params, slots=1, max_len=16)
    try:
        with pytest.raises(ValueError):
            b.submit(jnp.zeros((14,), jnp.int32), 8)
    finally:
        b.close()


def test_batcher_crash_releases_waiters(monkeypatch):
    """A dying scheduler thread must fail pending submits, not hang them
    (restarts=0 pins the no-retry behavior; restart path tested below)."""
    from gpu_docker_api_tpu.workloads import serve as serve_mod
    from gpu_docker_api_tpu.workloads.serve import _Batcher

    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    b = _Batcher(cfg, params, slots=1, max_len=32, restarts=0)
    import gpu_docker_api_tpu.batching as batching_mod

    def boom(*a, **k):
        raise RuntimeError("injected device failure")

    monkeypatch.setattr(batching_mod, "slot_prefill", boom)
    with pytest.raises(RuntimeError, match="batcher"):
        b.submit(jnp.zeros((4,), jnp.int32), 4)
    # once the scheduler thread has fully unwound, submits fail fast
    # instead of hanging (mid-teardown they may race to 'batcher failed'
    # via _fail_all's queue drain — also a fast failure, hence the join)
    b.thread.join(timeout=10)
    with pytest.raises(RuntimeError, match="unavailable"):
        b.submit(jnp.zeros((4,), jnp.int32), 4)


@pytest.mark.slow
def test_batcher_restarts_after_transient_crash(monkeypatch):
    """One transient device error fails the in-flight request but the
    scheduler rebuilds its cache and keeps serving (ADVICE r2 medium)."""
    from gpu_docker_api_tpu.workloads.serve import _Batcher
    import gpu_docker_api_tpu.batching as batching_mod

    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    real = batching_mod.slot_prefill
    fails = {"n": 1}

    def flaky(*a, **k):
        if fails["n"]:
            fails["n"] -= 1
            raise RuntimeError("transient XLA error")
        return real(*a, **k)

    monkeypatch.setattr(batching_mod, "slot_prefill", flaky)
    b = _Batcher(cfg, params, slots=1, max_len=32)
    try:
        with pytest.raises(RuntimeError, match="batcher"):
            b.submit(jnp.zeros((4,), jnp.int32), 4)
        # the restarted scheduler serves the next request normally and
        # matches the direct greedy stream
        prompt = jnp.array([5, 9, 2, 7], jnp.int32)
        deadline = 50
        out = None
        for _ in range(deadline):
            try:
                out = b.submit(prompt, 4)
                break
            except RuntimeError:
                import time
                time.sleep(0.1)
        assert out is not None, "batcher never came back after restart"
        from gpu_docker_api_tpu.infer import generate
        want = np.asarray(generate(params, prompt[None], cfg, 4)).tolist()[0]
        assert out == want
        assert b.alive
    finally:
        b.close()


def test_batcher_restart_budget_exhausts(monkeypatch):
    """A persistent fault must not retry forever: after the restart budget
    the batcher stays dead and submits fail fast."""
    from gpu_docker_api_tpu.workloads.serve import _Batcher
    import gpu_docker_api_tpu.batching as batching_mod

    def boom(*a, **k):
        raise RuntimeError("persistent device failure")

    monkeypatch.setattr(batching_mod, "slot_prefill", boom)
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    b = _Batcher(cfg, params, slots=1, max_len=32, restarts=2)
    # a submit landing inside the restart window raises without reaching
    # the scheduler, so crashes aren't 1:1 with submits — drive until the
    # budget is actually spent and the thread exits
    import time
    for _ in range(40):
        with pytest.raises(RuntimeError, match="batcher"):
            b.submit(jnp.zeros((4,), jnp.int32), 4)
        if not b.thread.is_alive():
            break
        time.sleep(0.05)
    b.thread.join(timeout=10)
    assert not b.thread.is_alive()
    assert not b.alive
    with pytest.raises(RuntimeError, match="unavailable"):
        b.submit(jnp.zeros((4,), jnp.int32), 4)


@pytest.mark.slow
def test_server_batching_accepts_sampling_rejects_multirow():
    """With --batch-slots active, single-row requests (greedy OR
    sampling) ride the batcher; only multi-row batches are refused
    (they would race the batcher for HBM — ADVICE r2 low)."""
    from gpu_docker_api_tpu.workloads.serve import _Batcher

    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    srv = _Server(cfg, params)
    srv.batcher = _Batcher(cfg, params, slots=1, max_len=32)
    try:
        with pytest.raises(ValueError, match="continuous-batching"):
            srv.generate([[1, 2, 3], [4, 5, 6]], 4, temperature=0.0)
        out = srv.generate([[1, 2, 3]], 4, temperature=0.0)
        assert len(out) == 1 and len(out[0]) == 4
        out = srv.generate([[1, 2, 3]], 4, temperature=0.9, top_k=8)
        assert len(out) == 1 and len(out[0]) == 4
        assert all(0 <= t < cfg.vocab_size for t in out[0])
    finally:
        srv.batcher.close()


@pytest.mark.slow
def test_batcher_sampling_row_does_not_perturb_greedy():
    """A sampling request decoding alongside a greedy one must leave the
    greedy stream EXACTLY its solo stream (per-row pick isolation)."""
    from gpu_docker_api_tpu.infer import generate
    from gpu_docker_api_tpu.workloads.serve import _Batcher

    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    b = _Batcher(cfg, params, slots=2, max_len=64, seed=7)
    try:
        gp = jnp.array([5, 9, 2, 7], jnp.int32)
        want = np.asarray(generate(params, gp[None], cfg, 10))[0].tolist()
        out = [None, None]

        def greedy():
            out[0] = b.submit(gp, 10)

        def sampled():
            out[1] = b.submit(jnp.array([1, 3, 3, 8], jnp.int32), 10,
                              temperature=1.0, top_k=16)

        ts = [threading.Thread(target=greedy),
              threading.Thread(target=sampled)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        assert out[0] == want
        assert len(out[1]) == 10
        assert all(0 <= t < cfg.vocab_size for t in out[1])
    finally:
        b.close()


@pytest.mark.slow
def test_batcher_sampling_deterministic_per_seed():
    from gpu_docker_api_tpu.workloads.serve import _Batcher

    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    prompt = jnp.array([5, 9, 2, 7], jnp.int32)

    def run(seed):
        b = _Batcher(cfg, params, slots=1, max_len=32, seed=seed)
        try:
            return b.submit(prompt, 12, temperature=1.5)
        finally:
            b.close()

    a, b2, c = run(11), run(11), run(12)
    assert a == b2                      # same seed, same stream
    # different seed: 12 high-temperature tokens colliding across two
    # independent key chains is ~impossible — a real equality here means
    # the seed is being ignored
    assert a != c


def test_rowwise_pick_semantics():
    from gpu_docker_api_tpu.batching import rowwise_pick

    key = jax.random.key(0)
    logits = jax.random.normal(jax.random.key(1), (3, 32)) * 3.0
    temps = jnp.array([0.0, 1.0, 1.0], jnp.float32)
    # row 0 greedy; row 1 top_k=1 == greedy at ANY temperature; row 2
    # top_k=4 must land inside its top-4 set
    tks = jnp.array([0, 1, 4], jnp.int32)
    tps = jnp.array([1.0, 1.0, 1.0], jnp.float32)
    greedy = np.asarray(jnp.argmax(logits, axis=-1))
    for i in range(20):
        out = np.asarray(rowwise_pick(logits, temps, tks, tps,
                                      jax.random.fold_in(key, i)))
        assert out[0] == greedy[0]
        assert out[1] == greedy[1]
        top4 = set(np.asarray(jax.lax.top_k(logits[2], 4)[1]).tolist())
        assert int(out[2]) in top4
    # top_p tiny -> only the argmax survives the nucleus
    tps = jnp.array([1.0, 1.0, 1e-6], jnp.float32)
    tks = jnp.array([0, 0, 0], jnp.int32)
    out = np.asarray(rowwise_pick(logits, temps, tks, tps, key))
    assert out[2] == greedy[2]


def test_prefill_tick_round_robin_is_fair():
    """Chunked prefill must rotate across slots: a parked prefill in a
    high slot is not starved by lower-index slots (ADVICE r2 low)."""
    from gpu_docker_api_tpu.workloads.serve import _Batcher

    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    b = _Batcher(cfg, params, slots=3, max_len=32, prefill_chunk=4)
    b._stop = True
    b.thread.join(timeout=10)
    fed = []
    b._prefill_piece = lambda i, item, piece, first: fed.append(i)
    for i in range(3):
        b.slots[i] = {"chunks": [jnp.zeros((4,), jnp.int32)] * 8,
                      "done": threading.Event()}
    for _ in range(6):
        assert b._prefill_tick()
    assert fed == [0, 1, 2, 0, 1, 2]


def test_batcher_close_fails_fast():
    from gpu_docker_api_tpu.workloads.serve import _Batcher

    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    b = _Batcher(cfg, params, slots=1, max_len=32)
    b.close()
    with pytest.raises(RuntimeError, match="unavailable"):
        b.submit(jnp.zeros((4,), jnp.int32), 2)


def test_healthz_reports_batching_stats():
    import urllib.request
    from http.server import ThreadingHTTPServer
    from gpu_docker_api_tpu.workloads.serve import _Batcher

    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    srv = _Server(cfg, params)
    srv.batcher = _Batcher(cfg, params, slots=3, max_len=32)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _handler_for(srv, "t"))
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        port = httpd.server_address[1]
        r = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10).read())
        b = r["data"]["batching"]
        assert b["slots"] == 3 and b["active"] == 0 and b["alive"] is True
    finally:
        httpd.shutdown()
        httpd.server_close()
        srv.batcher.close()


@pytest.mark.slow
def test_chunked_prefill_streams_exact():
    """Chunked prefill (pieces interleaved with decode for other slots)
    must produce the same greedy streams as whole-prompt prefill."""
    from gpu_docker_api_tpu.infer import generate
    from gpu_docker_api_tpu.workloads.serve import _Batcher

    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    b = _Batcher(cfg, params, slots=2, max_len=64, prefill_chunk=4)
    try:
        # a long prompt (chunked into 4-token pieces, last piece ragged)
        # and a short one running concurrently
        p_long = jax.random.randint(jax.random.key(10), (18,), 0,
                                    cfg.vocab_size)
        p_short = jax.random.randint(jax.random.key(11), (3,), 0,
                                     cfg.vocab_size)
        want_long = np.asarray(generate(params, p_long[None], cfg,
                                        max_new=5))[0]
        want_short = np.asarray(generate(params, p_short[None], cfg,
                                         max_new=5))[0]
        got = {}

        def ask(name, p):
            got[name] = b.submit(jnp.asarray(p), 5)

        ts = [threading.Thread(target=ask, args=("long", p_long)),
              threading.Thread(target=ask, args=("short", p_short))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        np.testing.assert_array_equal(got["long"], want_long)
        np.testing.assert_array_equal(got["short"], want_short)
    finally:
        b.close()


@pytest.mark.slow
def test_batcher_composes_with_w8_weights():
    """--quantize w8 --batch-slots: the slot decode runs through qmatmul,
    so int8 weights serve batched exactly like they serve solo."""
    from gpu_docker_api_tpu.infer import generate
    from gpu_docker_api_tpu.ops.quant import quantize_params
    from gpu_docker_api_tpu.workloads.serve import _Batcher

    cfg = LlamaConfig.tiny()
    params = quantize_params(init_params(cfg, jax.random.key(0)), "w8")
    b = _Batcher(cfg, params, slots=1, max_len=32)
    try:
        p = jax.random.randint(jax.random.key(12), (6,), 0, cfg.vocab_size)
        want = np.asarray(generate(params, p[None], cfg, max_new=4))[0]
        got = b.submit(jnp.asarray(p), 4)
        np.testing.assert_array_equal(got, want)
    finally:
        b.close()


def test_batcher_rejects_empty_prompt():
    from gpu_docker_api_tpu.workloads.serve import _Batcher

    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    b = _Batcher(cfg, params, slots=1, max_len=16, prefill_chunk=4)
    try:
        with pytest.raises(ValueError, match="empty"):
            b.submit(jnp.zeros((0,), jnp.int32), 4)
    finally:
        b.close()


@pytest.mark.slow
def test_prefix_cache_reuses_kv_and_streams_exact():
    """Second request sharing a 16-token prefix must restore the stored KV
    (only the suffix prefills) and still produce its exact solo stream."""
    from gpu_docker_api_tpu.infer import generate
    from gpu_docker_api_tpu.workloads.serve import _Batcher

    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    b = _Batcher(cfg, params, slots=1, max_len=64, prefix_cache=4)
    try:
        base = jax.random.randint(jax.random.key(20), (16,), 0,
                                  cfg.vocab_size)
        p1 = jnp.concatenate([base, jnp.array([5, 9], jnp.int32)])
        p2 = jnp.concatenate([base, jnp.array([7, 1, 3], jnp.int32)])
        want1 = np.asarray(generate(params, p1[None], cfg, max_new=4))[0]
        want2 = np.asarray(generate(params, p2[None], cfg, max_new=4))[0]
        got1 = b.submit(p1, 4)
        assert b.prefix_hits == 0
        got2 = b.submit(p2, 4)
        assert b.prefix_hits == 1                 # p1's KV prefix reused
        np.testing.assert_array_equal(got1, want1)
        np.testing.assert_array_equal(got2, want2)
        # identical prompt resubmitted: restore covers all but the last
        # token, stream still exact
        got1b = b.submit(p1, 4)
        assert b.prefix_hits == 2
        np.testing.assert_array_equal(got1b, want1)
    finally:
        b.close()


@pytest.mark.slow
def test_prefix_cache_composes_with_chunked_prefill():
    from gpu_docker_api_tpu.infer import generate
    from gpu_docker_api_tpu.workloads.serve import _Batcher

    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    b = _Batcher(cfg, params, slots=2, max_len=64, prefill_chunk=4,
                 prefix_cache=2)
    try:
        base = jax.random.randint(jax.random.key(21), (12,), 0,
                                  cfg.vocab_size)
        p1 = jnp.concatenate([base, jnp.array([2], jnp.int32)])
        p2 = jnp.concatenate([base, jnp.array([8, 4, 6, 1, 9], jnp.int32)])
        want2 = np.asarray(generate(params, p2[None], cfg, max_new=5))[0]
        b.submit(p1, 2)
        got2 = b.submit(p2, 5)
        assert b.prefix_hits == 1
        np.testing.assert_array_equal(got2, want2)
    finally:
        b.close()


def test_prefix_cache_lru_eviction():
    from gpu_docker_api_tpu.workloads.serve import _Batcher

    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    b = _Batcher(cfg, params, slots=1, max_len=64, prefix_cache=2)
    try:
        for seed in range(4):                     # distinct 10-token prompts
            p = jax.random.randint(jax.random.key(30 + seed), (10,), 0,
                                   cfg.vocab_size)
            b.submit(p, 2)
        assert len(b._prefixes) == 2              # LRU-bounded
    finally:
        b.close()


def test_batcher_submit_validates_sampling_params():
    from gpu_docker_api_tpu.workloads.serve import _Batcher

    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    b = _Batcher(cfg, params, slots=1, max_len=32)
    try:
        with pytest.raises(ValueError, match="top_p"):
            b.submit(jnp.zeros((4,), jnp.int32), 4, temperature=1.0,
                     top_p=0.0)
        with pytest.raises(ValueError, match="top_p"):
            # passes an f64 range check but rounds to 0.0f on the f32
            # sampling vectors — must be rejected, not silently empty
            # the nucleus
            b.submit(jnp.zeros((4,), jnp.int32), 4, temperature=1.0,
                     top_p=1e-46)
        with pytest.raises(ValueError, match="temperature"):
            b.submit(jnp.zeros((4,), jnp.int32), 4, temperature=-1.0)
        with pytest.raises(ValueError, match="top_k"):
            b.submit(jnp.zeros((4,), jnp.int32), 4, top_k=-3)
        # a huge-but-valid top_k means "no filter"; it must clamp to
        # vocab (int32 wire/vector safety), not crash the scheduler
        out = b.submit(jnp.zeros((4,), jnp.int32), 2, temperature=0.5,
                       top_k=2**31)
        assert len(out) == 2
    finally:
        b.close()


@pytest.mark.slow
def test_host_load_serving_over_http():
    """--host-load --quantize w8: the model inits on HOST and streams
    int8 to the device, then serves normally — the llama3-8B-on-16GB
    path, exercised end-to-end at tiny scale."""
    import re
    import subprocess
    import sys
    import time
    import urllib.error
    import urllib.request

    # --port 0 lets serve pick a free port itself (no bind race); it
    # prints the bound address on startup
    proc = subprocess.Popen(
        [sys.executable, "-m", "gpu_docker_api_tpu.workloads.serve",
         "--family", "llama", "--config", "tiny", "--quantize", "w8",
         "--host-load", "--host", "127.0.0.1", "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        port = None
        deadline = time.time() + 180
        while time.time() < deadline and port is None:
            line = proc.stdout.readline()
            assert line or proc.poll() is None, "server died before binding"
            m = re.search(r"serving .* on 127\.0\.0\.1:(\d+)", line)
            if m:
                port = int(m.group(1))
        assert port is not None, "never saw the bound address"
        out = None
        last_err = None
        while time.time() < deadline:
            try:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/generate",
                    data=json.dumps({"tokens": [[5, 9, 2, 7]],
                                     "max_new": 6}).encode(),
                    headers={"Content-Type": "application/json"})
                out = json.loads(urllib.request.urlopen(
                    req, timeout=30).read())
                break
            except urllib.error.HTTPError as e:
                # the server answered: a 4xx/5xx is a real failure, not
                # a not-ready state — surface it instead of spinning
                raise AssertionError(
                    f"/generate failed: {e.code} "
                    f"{e.read().decode(errors='replace')[:500]}")
            except Exception as e:           # not up yet
                last_err = e
                time.sleep(1)
        assert out is not None and out["code"] == 200, (out, last_err)
        # matches the in-process streamed-quantized oracle exactly
        from gpu_docker_api_tpu.infer import generate
        from gpu_docker_api_tpu.ops.quant import (
            quantize_params_streaming,
        )
        cfg = LlamaConfig.tiny()
        qs = quantize_params_streaming(
            jax.tree.map(np.asarray, init_params(cfg, jax.random.key(0))),
            "w8")
        want = np.asarray(generate(
            qs, jnp.array([[5, 9, 2, 7]], jnp.int32), cfg, 6))[0].tolist()
        assert out["data"]["tokens"][0] == want
    finally:
        proc.kill()
        proc.wait(timeout=10)
