from gpu_docker_api_tpu.topology import Chip, TpuTopology, make_topology


def test_known_shapes():
    t = make_topology("v5p-8")
    assert t.shape == (2, 2, 1)
    assert t.num_chips == 4
    assert [c.device_path for c in t.chips] == [f"/dev/accel{i}" for i in range(4)]

    t8 = make_topology("v5e-8")
    assert t8.shape == (2, 4, 1)
    assert t8.num_chips == 8


def test_unknown_type_most_cubic():
    t = make_topology("v5p-64")  # 32 chips
    assert t.num_chips == 32
    x, y, z = t.shape
    assert x * y * z == 32
    assert max(t.shape) <= 8  # cubic-ish, not a line


def test_neighbors_mesh():
    t = make_topology("v4-32")  # 2x2x4
    corner = t.at((0, 0, 0))
    assert sorted(n.coord for n in t.neighbors(corner)) == [(0, 0, 1), (0, 1, 0), (1, 0, 0)]
    mid = t.at((0, 0, 2))
    assert len(t.neighbors(mid)) == 4


def test_neighbors_torus_wrap():
    t = TpuTopology("v4-32", "v4", (2, 2, 4), wraparound=True)
    corner = t.at((0, 0, 0))
    coords = sorted(n.coord for n in t.neighbors(corner))
    assert (0, 0, 3) in coords  # wrap along z (size 4 > 2)
    # size-2 axes don't produce duplicate wrap links
    assert len(coords) == len(set(coords))


def test_connectivity():
    t = make_topology("v4-32")
    assert t.is_connected([0, 1])          # (0,0,0)-(1,0,0)
    assert not t.is_connected([0, 3])      # (0,0,0) vs (1,1,0): diagonal
    assert t.is_connected([0, 1, 3])       # path through (1,0,0)


def test_sub_boxes_prefers_compact():
    t = make_topology("v4-32")  # 2x2x4
    first_dims = next(iter(t.sub_boxes(4)))[1]
    # any surface-area-8 slab (2x2 in some plane) beats the 1x1x4 line (SA 9)
    a, b, c = first_dims
    assert a * b + b * c + a * c == 8
    dims_order = [d for _, d in t.sub_boxes(4)]
    assert dims_order[-1] == (1, 1, 4) or (1, 1, 4) not in dims_order[:1]


def test_visible_chips_env():
    t = make_topology("v5p-8")
    env = t.visible_chips_env([0, 1])
    assert env["TPU_VISIBLE_CHIPS"] == "0,1"
    assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,1,1"
    assert env["TPU_PROCESS_BOUNDS"] == "1,1,1"
    assert env["TPU_ACCELERATOR_TYPE"] == "v5p-8"
    env4 = t.visible_chips_env([0, 1, 2, 3])
    assert env4["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,2,1"


# ---------------------------------------------------- workers / multi-host

def test_worker_mapping_v5p_16():
    topo = make_topology("v5p-16")        # 8 chips, 4 per host -> 2 workers
    assert topo.num_workers == 2 and topo.chips_per_host == 4
    assert [topo.worker_of(i) for i in range(8)] == [0, 0, 0, 0, 1, 1, 1, 1]
    assert topo.worker_chips(1) == [4, 5, 6, 7]
    assert topo.workers_spanned([0, 1, 4]) == [0, 1]


def test_worker_mapping_v5e_single_host():
    topo = make_topology("v5e-8")         # 8 chips, 8 per host -> 1 worker
    assert topo.num_workers == 1 and topo.chips_per_host == 8
    assert topo.workers_spanned(list(range(8))) == [0]


def test_multihost_env_full_slice():
    topo = make_topology("v5p-16")
    envs = topo.multihost_env(list(range(8)))
    assert sorted(envs) == [0, 1]
    e0, e1 = envs[0], envs[1]
    # local device indices per host
    assert e0["TPU_VISIBLE_CHIPS"] == "0,1,2,3"
    assert e1["TPU_VISIBLE_CHIPS"] == "0,1,2,3"
    assert e0["TPU_WORKER_ID"] == "0" and e1["TPU_WORKER_ID"] == "1"
    assert e0["CLOUD_TPU_TASK_ID"] == "0" and e1["CLOUD_TPU_TASK_ID"] == "1"
    # identical full per-host boxes -> process bounds declared
    assert e0["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,2,1"
    assert e0["TPU_PROCESS_BOUNDS"] == "1,1,2"
    # coordination mesh wiring
    assert e0["TPU_PROCESS_ADDRESSES"] == e1["TPU_PROCESS_ADDRESSES"]
    assert e0["TPU_PROCESS_ADDRESSES"].count(":8476") == 2
    assert e0["TPU_WORKER_HOSTNAMES"] == "worker-0,worker-1"


def test_multihost_env_ragged_grant_omits_bounds():
    topo = make_topology("v5p-16")
    # 3 chips on worker 0, 4 on worker 1: shapes differ -> no bounds env
    envs = topo.multihost_env([0, 1, 2, 4, 5, 6, 7])
    assert "TPU_CHIPS_PER_PROCESS_BOUNDS" not in envs[0]
    assert "TPU_PROCESS_ADDRESSES" in envs[0]


def test_serialize_roundtrip_carries_workers():
    topo = make_topology("v5p-16")
    d = topo.serialize()
    assert d["numWorkers"] == 2 and d["chipsPerHost"] == 4


# -------------------------------------------------- device-node probe
# VERDICT r1 weak #7: the /dev/accel* fallback must be exact for the
# standard host configs and explicit (never a guessed 3D box) otherwise.

def _probe(tmp_path, monkeypatch, n_nodes, acc_type=None):
    import gpu_docker_api_tpu.topology as T
    for i in range(n_nodes):
        (tmp_path / f"accel{i}").touch()
    monkeypatch.setattr(T, "ACCEL_GLOB", str(tmp_path / "accel[0-9]*"))
    if acc_type is None:
        monkeypatch.delenv("TPU_ACCELERATOR_TYPE", raising=False)
    else:
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", acc_type)
    return T.discover_topology()


def test_probe_single_chip(tmp_path, monkeypatch):
    topo = _probe(tmp_path, monkeypatch, 1)
    assert topo.num_chips == 1 and topo.generation == "v5e"


def test_probe_four_chips(tmp_path, monkeypatch):
    topo = _probe(tmp_path, monkeypatch, 4)
    assert topo.num_chips == 4 and topo.shape == (2, 2, 1)


def test_probe_eight_chips(tmp_path, monkeypatch):
    topo = _probe(tmp_path, monkeypatch, 8)
    assert topo.num_chips == 8 and topo.shape == (2, 4, 1)


def test_probe_two_chips_no_adjacency_claims(tmp_path, monkeypatch):
    """2 local chips (non-standard count): the chips are numbered but NO
    ICI adjacency is asserted (which links exist depends on which chips of
    the real mesh these are), and env never declares process bounds."""
    topo = _probe(tmp_path, monkeypatch, 2)
    assert topo.shape == (2, 1, 1)
    assert topo.chips_per_host == 2
    assert topo.num_workers == 1
    assert not topo.ici_connected
    assert topo.neighbors(topo.chip(0)) == []
    assert not topo.is_connected([0, 1])
    env = topo.visible_chips_env([0, 1])
    assert env["TPU_VISIBLE_CHIPS"] == "0,1"
    assert "TPU_CHIPS_PER_PROCESS_BOUNDS" not in env
    assert "TPU_PROCESS_BOUNDS" not in env


def test_probe_odd_count_numbering_only(tmp_path, monkeypatch):
    topo = _probe(tmp_path, monkeypatch, 6)
    assert topo.shape == (6, 1, 1) and topo.num_chips == 6
    assert not topo.ici_connected


def test_probe_env_overrides_nodes(tmp_path, monkeypatch):
    """TPU_ACCELERATOR_TYPE beats device-node counting."""
    topo = _probe(tmp_path, monkeypatch, 2, acc_type="v5p-8")
    assert topo.generation == "v5p" and topo.num_chips == 4


def test_probe_bad_env_type_raises(tmp_path, monkeypatch):
    """A typo'd accelerator type must fail loudly, not become a guess."""
    import pytest
    with pytest.raises(ValueError):
        _probe(tmp_path, monkeypatch, 2, acc_type="warp9")
