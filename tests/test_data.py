"""Data pipeline: memmap token files, deterministic batch streams, device
prefetch."""

import numpy as np
import pytest

from gpu_docker_api_tpu.data import (
    Prefetcher, SyntheticDataset, TokenFileDataset, make_dataset,
)


@pytest.fixture
def token_file(tmp_path):
    toks = np.arange(10_000, dtype=np.uint16) % 311
    path = tmp_path / "corpus.bin"
    toks.tofile(path)
    return str(path), toks


def test_memmap_crops_match_file(token_file):
    path, toks = token_file
    ds = TokenFileDataset(path, batch=4, seq=32, seed=7)
    b = ds.batch_at(0)
    assert b.shape == (4, 32) and b.dtype == np.int32
    # every row is a contiguous crop of the file
    for row in b:
        matches = [s for s in range(len(toks) - 32)
                   if np.array_equal(toks[s:s + 32], row)]
        assert matches, "row is not a contiguous crop"


def test_deterministic_and_step_varying(token_file):
    path, _ = token_file
    a = TokenFileDataset(path, batch=2, seq=16, seed=1).batch_at(5)
    b = TokenFileDataset(path, batch=2, seq=16, seed=1).batch_at(5)
    c = TokenFileDataset(path, batch=2, seq=16, seed=1).batch_at(6)
    d = TokenFileDataset(path, batch=2, seq=16, seed=2).batch_at(5)
    np.testing.assert_array_equal(a, b)     # resume replays exactly
    assert not np.array_equal(a, c)         # steps differ
    assert not np.array_equal(a, d)         # seeds differ


def test_process_streams_disjoint(token_file):
    path, _ = token_file
    p0 = TokenFileDataset(path, batch=2, seq=16, seed=1, process_id=0)
    p1 = TokenFileDataset(path, batch=2, seq=16, seed=1, process_id=1)
    assert not np.array_equal(p0.batch_at(0), p1.batch_at(0))


def test_file_too_small_raises(tmp_path):
    path = tmp_path / "tiny.bin"
    np.arange(10, dtype=np.uint16).tofile(path)
    with pytest.raises(ValueError, match="tokens"):
        TokenFileDataset(str(path), batch=1, seq=32)


def test_u32_suffix_dtype(tmp_path):
    toks = (np.arange(1000, dtype=np.uint32) * 70001) % 100_000
    path = tmp_path / "big_vocab.u32"
    toks.tofile(path)
    ds = TokenFileDataset(str(path), batch=1, seq=8)
    assert int(ds.batch_at(0).max()) < 100_000
    assert ds.n_tokens == 1000


def test_negative_seed_works():
    ds = SyntheticDataset(vocab_size=50, batch=2, seq=8, seed=-1)
    assert ds.batch_at(0).shape == (2, 8)


def test_synthetic_bounds_and_determinism():
    ds = SyntheticDataset(vocab_size=50, batch=3, seq=8, seed=4)
    a = ds.batch_at(2)
    assert a.shape == (3, 8) and a.min() >= 0 and a.max() < 50
    np.testing.assert_array_equal(
        a, SyntheticDataset(50, 3, 8, seed=4).batch_at(2))


def test_make_dataset_dispatch(token_file, tmp_path):
    path, _ = token_file
    assert isinstance(make_dataset("", 99, 1, 8), SyntheticDataset)
    assert isinstance(make_dataset(path, 99, 1, 8), TokenFileDataset)
    with pytest.raises(FileNotFoundError):
        make_dataset(str(tmp_path / "nope.bin"), 99, 1, 8)


def test_prefetcher_preserves_order_and_values(token_file):
    path, _ = token_file
    ds = TokenFileDataset(path, batch=2, seq=16, seed=3)
    placed = []

    def place(b):
        placed.append(True)
        return b * 2            # stand-in for device_put

    pf = Prefetcher(ds.iter_from(0), place, depth=2)
    got = [next(pf) for _ in range(5)]
    pf.close()
    for step, g in enumerate(got):
        np.testing.assert_array_equal(g, ds.batch_at(step) * 2)


def test_prefetcher_close_joins_blocked_producer():
    def endless():
        i = 0
        while True:
            yield np.full((2, 2), i)
            i += 1

    pf = Prefetcher(endless(), place=lambda b: b, depth=1)
    next(pf)
    pf.close()                   # producer blocked on a full queue must exit
    assert not pf._thread.is_alive()


def test_final_token_reachable(tmp_path):
    """Off-by-one guard: with exactly seq+1 tokens there are two valid
    crops; both (and thus the final token) must be drawable."""
    toks = np.arange(9, dtype=np.uint16)          # seq=8 -> starts {0, 1}
    path = tmp_path / "edge.bin"
    toks.tofile(path)
    ds = TokenFileDataset(str(path), batch=64, seq=8, seed=0)
    seen_last = any(8 in ds.batch_at(s) for s in range(20))
    assert seen_last, "token N-1 never sampled — exclusive-high off-by-one"


def test_out_of_vocab_fails_loudly(token_file):
    path, _ = token_file                           # ids up to 310
    ds = TokenFileDataset(path, batch=4, seq=16, vocab_size=256)
    with pytest.raises(ValueError, match="vocab"):
        for s in range(50):
            ds.batch_at(s)


def test_prefetcher_propagates_producer_error(token_file):
    path, _ = token_file
    ds = TokenFileDataset(path, batch=2, seq=16)

    def bad_place(b):
        raise RuntimeError("device on fire")

    pf = Prefetcher(ds.iter_from(0), bad_place, depth=2)
    with pytest.raises(RuntimeError, match="device on fire"):
        next(pf)
    pf.close()
