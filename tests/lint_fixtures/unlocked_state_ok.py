"""Clean twin for `unlocked-state`: same shape, mutations under the lock,
cross-object reads through the locked snapshot accessor."""
import threading


class GoodScheduler:
    def __init__(self):
        self._lock = threading.RLock()
        self.status = {}

    def grant(self, idx, owner):
        with self._lock:
            self.status[idx] = owner

    def owners(self):
        with self._lock:
            return dict(self.status)

    def free_count(self, other):
        return len(other.tpu.owners())
