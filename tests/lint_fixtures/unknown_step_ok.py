"""Clean twin for `unknown-step`: every op and step name is registered."""


class GoodService:
    def run(self, name):
        intent = self.intents.begin("container.run", name)
        intent.step("granted")
        intent.step("created")
        intent.done(committed=True)

    def replace(self, name):
        intent = self.intents.begin("container.replace", name)
        intent.step("stopped", sync=False)
        intent.done()
