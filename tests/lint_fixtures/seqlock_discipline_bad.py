"""Seeded violations for the seqlock-discipline rule: blocking work
(store write, sleep, logging) inside the seqlock publish window — the
try-block whose finally closes the epoch."""

import time

HDR_OFF_EPOCH = 16


class State:
    def publish(self, states):
        epoch = self.load(HDR_OFF_EPOCH)
        odd = epoch + 1 if epoch % 2 == 0 else epoch
        self.store(HDR_OFF_EPOCH, odd)
        try:
            for st in states:
                self.client.put("/roster", st)     # store write in window
                time.sleep(0.01)                   # sleep in window
                log.warning("published %s", st)    # logging in window
        finally:
            self.store(HDR_OFF_EPOCH, odd + 1)
