"""Clean twin for `silent-swallow`: the broad except logs before moving
on (re-raising or events.record() would equally satisfy the rule)."""
import logging

log = logging.getLogger(__name__)


def cleanup(backend, name):
    try:
        backend.remove(name)
    except Exception:
        log.exception("cleanup: removing %s failed", name)
