"""Clean twin for `unmapped-xerror`: every error class is caught in the
route layer (api_ok/app.py)."""


class XError(Exception):
    pass


class HandledError(XError):
    pass


class AlsoHandledError(XError):
    pass
