"""Route layer for the `unmapped-xerror` clean corpus: every xerrors
class maps to a stable app code."""
from . import xerrors


def run_handler(req):
    try:
        return do_run(req)
    except xerrors.HandledError:
        return {"code": 1001}
    except (xerrors.AlsoHandledError, ValueError):
        return {"code": 1002}
