"""Clean twin: the same publish shape with every blocking call OUTSIDE
the seqlock window — persisted before, logged after the close."""

import time

HDR_OFF_EPOCH = 16


class State:
    def publish(self, states):
        self.client.put("/roster", states)         # before the window
        epoch = self.load(HDR_OFF_EPOCH)
        odd = epoch + 1 if epoch % 2 == 0 else epoch
        self.store(HDR_OFF_EPOCH, odd)
        try:
            for st in states:
                self.write_conf(st)                # plain memory writes
        finally:
            self.store(HDR_OFF_EPOCH, odd + 1)
        time.sleep(0.01)                           # after the close
        log.warning("published %d gateways", len(states))
