"""Seeded violation for `silent-swallow`: a broad except whose body leaves
no trace — a mutation-path failure disappears."""


def cleanup(backend, name):
    try:
        backend.remove(name)
    except Exception:                     # VIOLATION: swallowed silently
        pass
