"""Clean twin of atomic_region_lat_bad.py: the digest cells only ever
move through the native CAS publish/read entry points (the gen word
fences the group), exactly how workers.py publish_replica_lat /
read_replica_lat access them."""

CNT_OFF = 4096
LAT_CELL_WORDS = 3


def _rep_cnt_off(g, r):
    return CNT_OFF + (g * 16 + r) * 12 * 8


def _rep_lat_off(g, r):
    return _rep_cnt_off(g, r) + 8 * 8


class State:
    def good_publish(self, g, r, vals):
        self.lib.shm_cells_publish(self.base + _rep_lat_off(g, r),
                                   self.base + _rep_lat_off(g, r) + 8,
                                   vals, LAT_CELL_WORDS)

    def good_read(self, g, r, out):
        return self.lib.shm_cells_read(self.base + _rep_lat_off(g, r),
                                       self.base + _rep_lat_off(g, r) + 8,
                                       out, LAT_CELL_WORDS)
