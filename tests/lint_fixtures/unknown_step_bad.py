"""Seeded violations for `unknown-step`: a step name the reconciler has
never heard of (silently skipped at boot) and an intent op with no replay
handler (a crash mid-operation would never be replayed)."""


class BadService:
    def run(self, name):
        intent = self.intents.begin("container.run", name)
        intent.step("granted")
        intent.step("warped")                          # VIOLATION: step
        intent.done(committed=True)

    def teleport(self, name):
        intent = self.intents.begin("container.teleport", name)  # VIOLATION
        intent.done()
