"""Miniature reconciler registry the `unknown-step` rule reads: the step
sets plus the handler table inside _replay_intent, exactly the shapes
gpu_docker_api_tpu/reconcile.py declares."""

CONSULTED_STEPS = frozenset({"created", "copied"})
INFORMATIONAL_STEPS = frozenset({"granted", "stopped"})


def _replay_intent(rec, report):
    handler = {
        "container.run": None,
        "container.replace": None,
    }.get(rec.op)
    return handler
