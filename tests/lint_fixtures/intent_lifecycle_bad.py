"""Seeded violation for `intent-lifecycle`: the intent is closed on the
success path but a failure between begin() and done() leaves the journal
entry open forever (no done() in any exception handler)."""


class BadService:
    def run(self, name):
        intent = self.intents.begin("container.run", name)   # VIOLATION
        self.backend.create(name, {})
        intent.done(committed=True)
