"""Clean twin of atomic_region_shm_bad.py: shard counter words go
through the atomic ops; raw buffer writes only touch the recorder-ring
payload region (whose helper is deliberately outside the counter set —
torn ring entries are skippable by contract)."""

SH_CNT_OFF = 144


def _sh_cnt_off(s, g, c):
    return SH_CNT_OFF + (s * 16 + g) * 36 * 8 + c * 8


def _sh_ring_slot_off(s, i):
    return 40000 + s * 16000 + i * 256


class Shards:
    def good_counter(self, s, g):
        self.add(_sh_cnt_off(s, g, 0), 1)
        self.store(_sh_cnt_off(s, g, 1), 0)

    def good_ring_payload(self, s, i, payload):
        off = _sh_ring_slot_off(s, i)
        self.store(off, 0)
        self.shm.buf[off + 8:off + 8 + len(payload)] = payload
        self.store(off, len(payload))
