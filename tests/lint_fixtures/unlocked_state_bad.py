"""Seeded violations for `unlocked-state`: a guarded-attr mutation outside
the owning lock, a raw cross-object read of another scheduler's map, and —
the subtler variant — a cross-object read made under the reader's OWN lock
(holding your lock never makes someone else's state safe)."""
import threading


class BadScheduler:
    def __init__(self):
        self._lock = threading.RLock()
        self.status = {}

    def grant(self, idx, owner):
        self.status[idx] = owner          # VIOLATION: mutation, no lock

    def free_count(self, other):
        return len(other.tpu.status)      # VIOLATION: raw cross-object read

    def probe(self, other):
        with self._lock:
            # VIOLATION: own lock held, but other.tpu.cordoned is guarded
            # by the OTHER object's lock (the pre-fix health.py bug)
            return 3 in other.tpu.cordoned
