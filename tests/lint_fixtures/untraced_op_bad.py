"""Seeded untraced-op violations: ad-hoc event op literals and
unregistered tdapi_* metric families, in positional AND keyword form
(5 violations expected)."""


class Service:
    def __init__(self, events, registry):
        self.events = events
        self._events = events
        self.registry = registry

    def mutate(self):
        # unregistered op through the public handle
        self.events.record("container.teleported", code=200)
        # ... and through a private one (the workqueue idiom)
        self._events.record("rogue.drop", target="x")
        # keyword form must not bypass the gate (the http.py idiom)
        self.events.record(op="rogue.keyword", code=200)
        # registered op: fine
        self.events.record("replace.copied", code=200)

    def instruments(self):
        # unregistered metric family
        self.registry.gauge("tdapi_teleports_total", typ="counter")
        # ... keyword form likewise
        self.registry.counter(name="tdapi_rogue_kw_total")
        # registered family: fine
        self.registry.histogram("tdapi_http_request_duration_ms")
        # non-tdapi name handed to an unrelated .counter() API: not ours
        self.registry.counter("widget_spins")
