"""Clean twin: config-region raw writes (that's what the seqlock
protects) and counter-region access through the atomic ops only."""

import struct

CONF_OFF = 64
CNT_OFF = 512


def _gw_conf_off(g):
    return CONF_OFF + g * 456


def _gw_cnt_off(g):
    return CNT_OFF + g * 64


class State:
    def publish(self, buf, name):
        off = _gw_conf_off(0)
        buf[off:off + 48] = name.ljust(48, b"\0")        # config region
        struct.pack_into("<q", buf, off + 48, 4)         # config words
        self.store(_gw_cnt_off(0), 0)                    # atomic op: fine
        self.add(_gw_cnt_off(0) + 8, 1)                  # atomic op: fine
