"""Seeded violations for the latency-digest half of atomic-region: a
replica's digest cells (gen | count | ewma_us | p95_us, addressed via
_rep_lat_off) written through raw buffer paths instead of the native
shm_cells_publish CAS path — a plain store tears against a concurrent
folder and hands both router tiers a corrupt gray-failure signal."""

import struct

CNT_OFF = 4096


def _rep_cnt_off(g, r):
    return CNT_OFF + (g * 16 + r) * 12 * 8


def _rep_lat_off(g, r):
    return _rep_cnt_off(g, r) + 8 * 8


class State:
    def bad_pack(self, g, r):
        struct.pack_into("<q", self.shm.buf, _rep_lat_off(g, r), 3)

    def bad_slice(self, g, r):
        off = _rep_lat_off(g, r)
        self.shm.buf[off + 8:off + 16] = b"\x00" * 8
