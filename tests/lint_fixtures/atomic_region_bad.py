"""Seeded violations for the atomic-region rule: counter-region words
written through the raw buffer path (pack_into / slice assignment) the
seqlock config writes use — a plain racy store over live fetch_adds."""

import struct

CNT_OFF = 512


def _gw_cnt_off(g):
    return CNT_OFF + g * 64


class State:
    def publish(self, buf):
        struct.pack_into("<q", buf, _gw_cnt_off(0) + 8, 0)   # raw pack
        off = _gw_cnt_off(1)
        buf[off:off + 8] = b"\0" * 8                         # aliased slice
        self.shm.buf[CNT_OFF:CNT_OFF + 8] = b"\0" * 8        # region const
