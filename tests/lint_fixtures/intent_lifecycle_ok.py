"""Clean twin for `intent-lifecycle`: done() reached on the success path
AND from the unwind handler."""


class GoodService:
    def run(self, name):
        intent = self.intents.begin("container.run", name)
        try:
            self.backend.create(name, {})
        except Exception:
            intent.done()
            raise
        intent.done(committed=True)
