"""Clean twin: the documented ordering — global fetch_add before the
ledger increment, ledger undo before the global release, and bulk
zeroing that accounts both sides."""


def _rep_cnt_off(g, r):
    return 512 + g * 64 + r * 16


def _wk_claim_off(w, g, r):
    return 4096 + w * 256 + g * 16 + r * 8


class Router:
    def try_claim(self, st, g, r, slots):
        off = _rep_cnt_off(g, r)
        if st.add(off, 1) <= slots:                # global claim first
            st.add(_wk_claim_off(0, g, r), 1)      # then the ledger
            return True
        st.dec_floor0(off)                         # overshoot undo
        return False

    def release(self, st, g, r):
        st.dec_floor0(_wk_claim_off(0, g, r))      # ledger undone first
        st.dec_floor0(_rep_cnt_off(g, r))          # then the global free

    def reconcile(self, st, g, r):
        st.dec_floor0(_rep_cnt_off(g, r))
        st.store(_wk_claim_off(0, g, r), 0)        # zero, both accounted
