"""Clean twin of seqlock_discipline_shm_bad.py: the shard epoch window
holds nothing but atomic stores; the spooling happens before/after."""


def _sh_epoch_off(g):
    return 16 + g * 8


def _sh_gw_off(s, g):
    return 144 + (s * 16 + g) * 36 * 8


class Shards:
    def reset_gateway(self, g):
        self._w.write("about to reset\n")            # outside the window
        epoch = self.load(_sh_epoch_off(g))
        odd = epoch + 1 if epoch % 2 == 0 else epoch
        self.store(_sh_epoch_off(g), odd)
        try:
            for s in range(8):
                base = _sh_gw_off(s, g)
                for w in range(36):
                    self.store(base + w * 8, 0)      # atomics only
        finally:
            self.store(_sh_epoch_off(g), odd + 1)
        self._w.flush()                              # outside the window
