"""Fixture telemetry catalog for the untraced-op rule tests — the shape
obs/names.py has in the real tree (the rule matches on the EVENT_OPS /
METRIC_NAMES assignments, not on the filename)."""

EVENT_OPS = frozenset({
    "replace.copied",
    "reconcile",
})

METRIC_NAMES = frozenset({
    "tdapi_tpu_chips",
    "tdapi_http_request_duration_ms",
})
