"""Seeded violations for the metric-shard half of atomic-region: shard
counter/histogram words written through raw buffer paths instead of the
native atomic ops — a plain store races (and can wipe) a worker's
concurrent fetch_adds into the same cell."""

import struct

SH_CNT_OFF = 144


def _sh_cnt_off(s, g, c):
    return SH_CNT_OFF + (s * 16 + g) * 36 * 8 + c * 8


def _sh_lat_off(s, g):
    return _sh_cnt_off(s, g, 4)


class Shards:
    def bad_pack(self, s, g):
        struct.pack_into("<q", self.shm.buf, _sh_cnt_off(s, g, 0), 7)

    def bad_slice(self, s, g):
        off = _sh_lat_off(s, g)
        self.shm.buf[off:off + 8] = b"\x00" * 8
