"""Seeded violations for the metric-shard half of seqlock-discipline:
spooling I/O (writer write/flush, recorder-ring append) inside the
per-gateway shard epoch window — a disk stall in the window stalls every
scrape spinning on the epoch, and a crash parks it odd."""


def _sh_epoch_off(g):
    return 16 + g * 8


class Shards:
    def reset_gateway(self, g):
        epoch = self.load(_sh_epoch_off(g))
        odd = epoch + 1 if epoch % 2 == 0 else epoch
        self.store(_sh_epoch_off(g), odd)
        try:
            self._w.write("resetting\n")             # spool write in window
            self._w.flush()                          # spool flush in window
            self.recorder.ring_note({"k": "reset"})  # ring append in window
        finally:
            self.store(_sh_epoch_off(g), odd + 1)
