"""Clean twin of untraced_op_bad.py: every op/metric literal is in the
fixture catalog (names_catalog.py); computed names are skipped by design
(HTTP request events, breaker.{state})."""


class Service:
    def __init__(self, events, registry):
        self.events = events
        self.registry = registry

    def mutate(self, method, path):
        self.events.record("replace.copied", code=200)
        self.events.record("reconcile", code=200)
        # computed op: the rule skips non-literals by design — one event
        # name per route would be unbounded
        self.events.record(f"{method} {path}", code=200)

    def instruments(self):
        self.registry.gauge("tdapi_tpu_chips", labels=("state",))
        self.registry.histogram("tdapi_http_request_duration_ms")
