"""Route layer for the `unmapped-xerror` bad corpus: handles only
HandledError."""
from . import xerrors


def run_handler(req):
    try:
        return do_run(req)
    except xerrors.HandledError:
        return {"code": 1001}
