"""Seeded violation for `unmapped-xerror`: OrphanedError is never caught
in the route layer (api_bad/app.py), so it would fall into the catch-all
and surface as a generic op-failed code."""


class XError(Exception):
    pass


class HandledError(XError):
    pass


class OrphanedError(XError):              # VIOLATION: no route catches it
    pass
