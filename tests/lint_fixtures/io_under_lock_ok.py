"""Clean twin for `io-under-lock`: state flip under the lock, substrate
call outside it."""
import threading


class GoodService:
    def __init__(self):
        self._lock = threading.Lock()

    def stop(self, name):
        with self._lock:
            self.running = False
        self.backend.stop(name)
