"""Pragma fixtures: each would-be violation below is suppressed by a
`# tdlint: disable=<rule>` pragma in one of the three honored positions —
same line, line above, and function header (def line or its contiguous
leading comment block). test_tdlint asserts this file lints clean with
every pragma counted as used."""
import threading


class PragmaScheduler:
    def __init__(self):
        self._lock = threading.RLock()
        self.status = {}

    def same_line(self, idx):
        self.status[idx] = "x"    # tdlint: disable=unlocked-state -- demo

    def line_above(self, idx):
        # tdlint: disable=unlocked-state -- demo: pragma on the line above
        self.status[idx] = "y"

    # tdlint: disable=unlocked-state -- demo: header pragma covers the body
    def whole_function(self, idx):
        self.status[idx] = "z"
        del self.status[idx]
