"""Seeded violations for the claim-order rule: the per-worker claims
ledger written on the WRONG side of the global counter op — a SIGKILL
between the two makes reconcile free capacity that was never claimed."""


def _rep_cnt_off(g, r):
    return 512 + g * 64 + r * 16


def _wk_claim_off(w, g, r):
    return 4096 + w * 256 + g * 16 + r * 8


class BrokenRouter:
    def try_claim(self, st, g, r, slots):
        st.add(_wk_claim_off(0, g, r), 1)          # ledger BEFORE global
        if st.add(_rep_cnt_off(g, r), 1) <= slots:
            return True
        st.dec_floor0(_rep_cnt_off(g, r))
        st.dec_floor0(_wk_claim_off(0, g, r))      # undo AFTER global
        return False

    def release(self, st, g, r):
        st.dec_floor0(_rep_cnt_off(g, r))          # global freed first
        st.dec_floor0(_wk_claim_off(0, g, r))      # ledger undone last
