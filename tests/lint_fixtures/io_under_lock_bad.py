"""Seeded violation for `io-under-lock`: a backend op dispatched while a
lock is held — every other writer queues behind the substrate."""
import threading


class BadService:
    def __init__(self):
        self._lock = threading.Lock()

    def stop(self, name):
        with self._lock:
            self.backend.stop(name)       # VIOLATION: backend op under lock
            self.running = False
