"""Environment sanity: the assumptions every other test file builds on.
A failure here means the suite's results are meaningless, not that the
framework is broken — check these FIRST when debugging a red run."""

import os
import shutil
import sys


def test_jax_on_virtual_cpu_mesh():
    """The whole suite must run on the 8-device virtual CPU platform —
    if the axon TPU plugin grabs the backend, sharding tests are
    meaningless."""
    import jax
    assert jax.default_backend() == "cpu"
    assert jax.device_count() == 8


def test_axon_tunnel_neutralized():
    """pytest_force_cpu must have cleared the tunnel env BEFORE jax import:
    a wedged tunnel otherwise hangs every test at backend init (observed
    round 2: even JAX_PLATFORMS=cpu hangs while the plugin registers)."""
    assert not os.environ.get("PALLAS_AXON_POOL_IPS", "")
    assert os.environ.get("JAX_PLATFORMS", "cpu").startswith("cpu")


def test_required_packages_importable():
    """Everything the framework imports must come from the baked image —
    a missing package should fail HERE with a clear name, not mid-suite."""
    import importlib
    for mod in ("jax", "flax", "optax", "orbax.checkpoint", "chex",
                "einops", "numpy"):
        importlib.import_module(mod)


def test_native_toolchain_present():
    """make/g++ build the C++ cores; the suite rebuilds them when stale."""
    assert shutil.which("g++"), "g++ missing — native cores can't build"
    assert shutil.which("make"), "make missing"


def test_native_store_lib_loadable():
    """The committed/built libmvccstore must match the current C ABI — a
    stale build otherwise surfaces as confusing ctypes symbol errors in
    whatever store test imports it first (observed round 2: undefined
    symbol mvcc_maintain after a source-only commit)."""
    from gpu_docker_api_tpu._native import load
    lib = load("mvccstore")
    if lib is not None:  # missing lib is allowed (pure-python fallback)
        for sym in ("mvcc_open", "mvcc_put", "mvcc_put_many",
                    "mvcc_get_fast", "mvcc_range_fast", "mvcc_maintain",
                    "mvcc_wal_flushes"):
            assert hasattr(lib, sym), f"stale native build: no {sym}"


def test_python_version_floor():
    """f-string/dataclass/typing usage assumes >= 3.10."""
    assert sys.version_info >= (3, 10)


def test_repo_layout_contracts():
    """Files the driver depends on every round must exist at the repo root."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for f in ("bench.py", "__graft_entry__.py", "Makefile", "pytest.ini"):
        assert os.path.exists(os.path.join(root, f)), f
