def test_jax_on_virtual_cpu_mesh():
    """The whole suite must run on the 8-device virtual CPU platform —
    if the axon TPU plugin grabs the backend, sharding tests are meaningless."""
    import jax
    assert jax.default_backend() == "cpu"
    assert jax.device_count() == 8
