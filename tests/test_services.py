"""ReplicaSet + Volume service state-machine tests over the mock backend."""

import os

import pytest

from gpu_docker_api_tpu import xerrors
from gpu_docker_api_tpu.backend import MockBackend
from gpu_docker_api_tpu.dtos import (
    Bind, ContainerRun, MemoryPatch, PatchRequest, TpuPatch, VolumePatch,
)
from gpu_docker_api_tpu.schedulers import CpuScheduler, PortScheduler, TpuScheduler
from gpu_docker_api_tpu.services import ReplicaSetService, VolumeService
from gpu_docker_api_tpu.store import MVCCStore, StateClient
from gpu_docker_api_tpu.topology import make_topology
from gpu_docker_api_tpu.version import MergeMap, VersionMap
from gpu_docker_api_tpu.workqueue import WorkQueue


@pytest.fixture()
def world(tmp_path):
    store = MVCCStore()
    client = StateClient(store)
    wq = WorkQueue(client)
    wq.start()
    backend = MockBackend(str(tmp_path / "state"))
    tpu = TpuScheduler(client, wq, topology=make_topology("v4-32"))
    cpu = CpuScheduler(client, wq, core_count=16)
    ports = PortScheduler(client, wq, port_range=(42000, 42100), seed=11)
    rs = ReplicaSetService(backend, client, wq, tpu, cpu, ports,
                           VersionMap("containerVersionMap", client, wq),
                           MergeMap(client, wq))
    vol = VolumeService(backend, client, wq,
                        VersionMap("volumeVersionMap", client, wq))
    yield rs, vol, backend, tpu, cpu, ports, wq, client
    wq.close()


def _run(rs, name="demo", tpus=2, cpus=2, ports=1, **kw):
    return rs.run_container(ContainerRun(
        imageName="ubuntu:22.04", replicaSetName=name, tpuCount=tpus,
        cpuCount=cpus, memory="8GB",
        containerPorts=["8888"] if ports else [], **kw))


# ------------------------------------------------------------------- run

def test_run_container(world):
    rs, _, backend, tpu, cpu, ports, wq, client = world
    resp = _run(rs)
    assert resp["name"] == "demo-1"
    assert len(resp["tpuChips"]) == 2
    assert resp["cpuset"] == "0,1"
    assert "8888" in resp["portBindings"]
    st = backend.inspect("demo-1")
    assert st.running
    assert st.spec.tpu_env["TPU_VISIBLE_CHIPS"]
    assert any(e == "CONTAINER_VERSION=1" for e in st.spec.env)
    wq.join()
    assert client.get("containers", "demo") is not None


def test_run_duplicate_rejected(world):
    rs = world[0]
    _run(rs)
    with pytest.raises(xerrors.ContainerExistedError):
        _run(rs)


def test_run_resource_rollback_on_shortage(world):
    rs, _, _, tpu, cpu, ports, _, _ = world
    with pytest.raises(xerrors.TpuNotEnoughError):
        _run(rs, name="big", tpus=64)
    # nothing leaked
    assert tpu.get_status()["freeCount"] == 16
    assert cpu.get_status()["usedCount"] == 0


def test_run_zero_tpu_smoke(world):
    # BASELINE config 1: 0-chip container
    rs, _, backend, tpu, *_ = world
    resp = _run(rs, name="smoke", tpus=0, cpus=0, ports=0)
    assert resp["tpuChips"] == []
    assert tpu.get_status()["freeCount"] == 16
    assert backend.inspect("smoke-1").running


# ----------------------------------------------------------------- patch

def test_patch_tpu_1_to_4(world):
    # BASELINE config 3: patch 1 -> 4 chips, rolling replacement
    rs, _, backend, tpu, *_ = world
    _run(rs, name="train", tpus=1)
    resp = rs.patch_container("train", PatchRequest(tpuPatch=TpuPatch(4)))
    assert resp["name"] == "train-2"
    assert len(resp["tpuChips"]) == 4
    assert tpu.topology.is_connected(resp["tpuChips"])
    assert not backend.inspect("train-1").exists       # old deleted
    assert backend.inspect("train-2").running
    assert tpu.get_status()["freeCount"] == 12


def test_patch_copies_writable_layer(world):
    rs, _, backend, *_ = world
    _run(rs, name="data")
    # simulate workload state in the old container's writable layer
    upper = backend.inspect("data-1").upper_dir
    with open(os.path.join(upper, "ckpt.bin"), "w") as f:
        f.write("step-42")
    rs.patch_container("data", PatchRequest(memoryPatch=MemoryPatch("16GB")))
    new_upper = backend.inspect("data-2").upper_dir
    with open(os.path.join(new_upper, "ckpt.bin")) as f:
        assert f.read() == "step-42"


def test_patch_no_change_raises(world):
    rs = world[0]
    _run(rs, tpus=2)
    with pytest.raises(xerrors.NoPatchRequiredError):
        rs.patch_container("demo", PatchRequest())
    with pytest.raises(xerrors.NoPatchRequiredError):
        rs.patch_container("demo", PatchRequest(tpuPatch=TpuPatch(2)))
    with pytest.raises(xerrors.NoPatchRequiredError):
        rs.patch_container("demo", PatchRequest(memoryPatch=MemoryPatch("8GB")))


def test_patch_shortage_keeps_old_running(world):
    rs, _, backend, tpu, *_ = world
    _run(rs, name="a", tpus=2)
    _run(rs, name="b", tpus=12)
    with pytest.raises(xerrors.TpuNotEnoughError):
        rs.patch_container("a", PatchRequest(tpuPatch=TpuPatch(8)))
    # old container untouched, resources re-marked
    assert backend.inspect("a-1").running
    assert tpu.get_status()["freeCount"] == 2


def test_patch_ports_regranted(world):
    rs, _, backend, _, _, ports, _, _ = world
    r1 = _run(rs, name="p")
    old_port = r1["portBindings"]["8888"]
    r2 = rs.patch_container("p", PatchRequest(memoryPatch=MemoryPatch("1GB")))
    assert "8888" in r2["portBindings"]
    st = ports.get_status()
    assert old_port not in st["usedPortSet"]  # old port released
    assert r2["portBindings"]["8888"] in st["usedPortSet"]


def test_patch_volume_bind_swap(world):
    rs, vol, backend, *_ = world
    v1 = vol.create_volume("data", "1GB")
    v2 = vol.create_volume("bigdata", "2GB")
    rs.run_container(ContainerRun(
        imageName="x", replicaSetName="j",
        binds=[Bind(v1["name"], "/root/foo-tmp")]))
    rs.patch_container("j", PatchRequest(volumePatch=VolumePatch(
        oldBind=Bind(v1["name"], "/root/foo-tmp"),
        newBind=Bind(v2["name"], "/root/foo-tmp"))))
    st = backend.inspect("j-2")
    assert st.spec.binds == [f"{v2['name']}:/root/foo-tmp"]


# -------------------------------------------------------------- rollback

def test_rollback_forward_writes(world):
    rs, _, backend, tpu, *_ = world
    _run(rs, name="r", tpus=1)
    rs.patch_container("r", PatchRequest(tpuPatch=TpuPatch(4)))
    resp = rs.rollback_container("r", 1)
    assert resp["version"] == 3            # append-only history
    assert len(resp["tpuChips"]) == 1      # back to v1 shape
    assert tpu.get_status()["freeCount"] == 15
    hist = rs.get_container_history("r")
    assert [h["version"] for h in hist] == [3, 2, 1]


def test_rollback_same_version_rejected(world):
    rs = world[0]
    _run(rs, name="r")
    with pytest.raises(xerrors.NoRollbackRequiredError):
        rs.rollback_container("r", 1)


def test_rollback_missing_version(world):
    rs = world[0]
    _run(rs, name="r")
    rs.patch_container("r", PatchRequest(memoryPatch=MemoryPatch("1GB")))
    with pytest.raises(xerrors.VersionNotFoundError):
        rs.rollback_container("r", 99)


# ------------------------------------------- stop / restart / pause / exec

def test_stop_releases_resources(world):
    rs, _, backend, tpu, cpu, ports, _, _ = world
    _run(rs, name="s", tpus=4, cpus=4)
    rs.stop_container("s")
    assert not backend.inspect("s-1").running
    assert tpu.get_status()["freeCount"] == 16
    assert cpu.get_status()["usedCount"] == 0
    assert ports.get_status()["usedPortSet"] == []


def test_restart_stopped_is_new_version(world):
    rs, _, backend, tpu, *_ = world
    _run(rs, name="s", tpus=2)
    rs.stop_container("s")
    resp = rs.restart_container("s")
    assert resp["name"] == "s-2"
    assert len(resp["tpuChips"]) == 2
    assert backend.inspect("s-2").running
    assert not backend.inspect("s-1").exists
    assert tpu.get_status()["freeCount"] == 14


def test_restart_running_keeps_grant(world):
    rs, _, backend, tpu, *_ = world
    r1 = _run(rs, name="s", tpus=2)
    resp = rs.restart_container("s")
    assert resp["tpuChips"] == r1["tpuChips"]  # identical ICI region
    assert backend.inspect("s-2").running


def test_pause_continue(world):
    rs, _, backend, *_ = world
    _run(rs, name="pz")
    rs.pause_container("pz")
    assert backend.inspect("pz-1").paused
    rs.startup_container("pz")
    st = backend.inspect("pz-1")
    assert st.running and not st.paused


def test_execute_and_commit(world):
    rs, _, backend, *_ = world
    _run(rs, name="e")
    out = rs.execute_container("e", ["echo", "hello"])
    assert "echo hello" in out
    img = rs.commit_container("e", "snap:v1")
    assert img.startswith("sha256:")


# ---------------------------------------------------------------- delete

def test_delete_clears_everything(world):
    rs, _, backend, tpu, _, _, wq, client = world
    _run(rs, name="d", tpus=2)
    rs.delete_container("d")
    assert not backend.inspect("d-1").exists
    assert tpu.get_status()["freeCount"] == 16
    with pytest.raises(xerrors.NotExistInStoreError):
        rs.get_container_info("d")
    with pytest.raises(xerrors.NotExistInStoreError):
        rs.get_container_history("d")
    # name is reusable and restarts at version 1
    resp = _run(rs, name="d")
    assert resp["name"] == "d-1"


# ---------------------------------------------------------------- volumes

def test_volume_create_patch_grow(world):
    _, vol, backend, *_ = world
    v = vol.create_volume("vol", "1GB")
    assert v["name"] == "vol-1"
    mp = v["mountpoint"]
    with open(os.path.join(mp, "data.bin"), "wb") as f:
        f.write(b"d" * 4096)
    out = vol.patch_volume_size("vol", "2GB")
    assert out["name"] == "vol-2"
    # data migrated
    with open(os.path.join(out["mountpoint"], "data.bin"), "rb") as f:
        assert len(f.read()) == 4096
    info = vol.get_volume_info("vol")
    assert info["volumeName"] == "vol-2" and info["size"] == "2GB"
    hist = vol.get_volume_history("vol")
    assert [h["version"] for h in hist] == [2, 1]


def test_volume_shrink_guard(world):
    _, vol, *_ = world
    v = vol.create_volume("vol", "1GB")
    with open(os.path.join(v["mountpoint"], "big.bin"), "wb") as f:
        f.write(b"x" * (2 * 1024))  # 2KB used
    with pytest.raises(xerrors.VolumeSizeUsedGreaterThanReducedError):
        vol.patch_volume_size("vol", "1KB")
    # shrink above used is fine
    out = vol.patch_volume_size("vol", "500MB")
    assert out["size"] == "500MB"


def test_volume_duplicate_and_delete(world):
    _, vol, backend, *_ = world
    vol.create_volume("vol", "1GB")
    with pytest.raises(xerrors.VolumeExistedError):
        vol.create_volume("vol", "1GB")
    vol.delete_volume("vol")
    with pytest.raises(xerrors.NotExistInStoreError):
        vol.get_volume_info("vol")
    vol.create_volume("vol", "1GB")  # name free again


def test_volume_same_size_no_patch(world):
    _, vol, *_ = world
    vol.create_volume("vol", "1GB")
    with pytest.raises(xerrors.NoPatchRequiredError):
        vol.patch_volume_size("vol", "1GB")


# ------------------------------------------------- xla compile-cache inject

def test_xla_cache_env_injected(world, tmp_path):
    rs, *_ = world
    rs.xla_cache_dir = str(tmp_path / "xla-cache")
    _run(rs, "cached")
    info = rs.get_container_info("cached")
    env = info["spec"]["env"]
    assert f"JAX_COMPILATION_CACHE_DIR={rs.xla_cache_dir}" in env
    assert "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=0" in env
    bind = f"{rs.xla_cache_dir}:{rs.xla_cache_dir}"
    assert bind in info["spec"]["binds"]


def test_xla_cache_user_override_wins(world, tmp_path):
    rs, *_ = world
    rs.xla_cache_dir = str(tmp_path / "xla-cache")
    _run(rs, "custom", env=["JAX_COMPILATION_CACHE_DIR=/my/own"])
    env = rs.get_container_info("custom")["spec"]["env"]
    assert "JAX_COMPILATION_CACHE_DIR=/my/own" in env
    assert not any(e.startswith(
        f"JAX_COMPILATION_CACHE_DIR={rs.xla_cache_dir}") for e in env)


def test_xla_cache_survives_patch_without_duplication(world, tmp_path):
    rs, *_ = world
    rs.xla_cache_dir = str(tmp_path / "xla-cache")
    _run(rs, "patched")
    rs.patch_container("patched", PatchRequest(tpuPatch=TpuPatch(tpuCount=4)))
    spec = rs.get_container_info("patched")["spec"]
    cache_envs = [e for e in spec["env"]
                  if e.startswith("JAX_COMPILATION_CACHE_DIR=")]
    assert cache_envs == [f"JAX_COMPILATION_CACHE_DIR={rs.xla_cache_dir}"]
    bind = f"{rs.xla_cache_dir}:{rs.xla_cache_dir}"
    assert spec["binds"].count(bind) == 1


# ----------------------------------------------------- multi-host plan

def test_info_exposes_multihost_launch_plan(world):
    rs, *_ = world
    _run(rs, "big", tpus=8)               # v4-32 world: spans 2 of 4 workers
    info = rs.get_container_info("big")
    plan = info["multihost"]
    assert len(plan) == 2
    for rank, (w, env) in enumerate(sorted(plan.items(), key=lambda x: int(x[0]))):
        assert env["TPU_WORKER_ID"] == w
        assert env["CLOUD_TPU_TASK_ID"] == str(rank)
        assert "TPU_PROCESS_ADDRESSES" in env
    _run(rs, "small", tpus=2)
    assert "multihost" not in rs.get_container_info("small")


# -------------------------------------------------- volume tiers

def test_volume_tiers_end_to_end(tmp_path):
    """SURVEY §7.7: the local-SSD/NFS data-disk split. A volume created on a
    configured tier lands under that tier's root, reports its tier, keeps
    it across a scale-up (data migrates in-tier), and unknown tiers fail
    with the configuration hint."""
    from gpu_docker_api_tpu.server.app import App
    from gpu_docker_api_tpu.topology import make_topology
    nfs = tmp_path / "fake-nfs"
    nfs.mkdir()
    app = App(state_dir=str(tmp_path / "state"), backend="process",
              addr="127.0.0.1:0", topology=make_topology("v5p-8"),
              api_key="", cpu_cores=4, volume_tiers={"nfs": str(nfs)})
    app.start()
    try:
        out = app.volumes.create_volume("shared", "1GB", tier="nfs")
        assert out["mountpoint"].startswith(str(nfs))
        info = app.volumes.get_volume_info("shared")
        assert info["tier"] == "nfs"
        # default tier volumes stay under the state dir
        local = app.volumes.create_volume("scratch", "1GB")
        assert not local["mountpoint"].startswith(str(nfs))
        # scale-up keeps the tier and migrates data in-tier
        import os
        with open(os.path.join(out["mountpoint"], "w.bin"), "wb") as f:
            f.write(b"D" * 64)
        scaled = app.volumes.patch_volume_size("shared", "2GB")
        assert scaled["mountpoint"].startswith(str(nfs))
        assert open(os.path.join(scaled["mountpoint"], "w.bin"), "rb").read() \
            == b"D" * 64
        assert app.volumes.get_volume_info("shared")["tier"] == "nfs"
        # unknown tier: actionable error
        import pytest as _pt
        with _pt.raises(ValueError, match="--volume-tier"):
            app.volumes.create_volume("bad", "1GB", tier="warpfs")
    finally:
        app.stop()


# ------------------------------------------------- name-lock lifecycle

def test_delete_drops_name_lock_entry(world):
    """Satellite regression: _name_locks used to grow one entry per
    replicaSet name FOREVER (never removed on delete) — a create/delete
    churn leaked a lock object per name."""
    rs = world[0]
    for i in range(5):
        _run(rs, name=f"churn{i}", tpus=1, cpus=0, ports=0)
    assert len(rs._name_locks) == 5
    for i in range(5):
        rs.delete_container(f"churn{i}")
    assert rs._name_locks == {}
    # recreating a deleted name works and re-registers exactly one lock
    _run(rs, name="churn0", tpus=1, cpus=0, ports=0)
    assert set(rs._name_locks) == {"churn0"}
    rs.delete_container("churn0")
    assert rs._name_locks == {}


def test_name_lock_waiter_survives_delete(world):
    """A thread blocked on a name's mutex while that name is deleted must
    proceed safely on the FRESH lock entry (mutual exclusion preserved,
    no deadlock, no KeyError)."""
    import threading

    rs = world[0]
    _run(rs, name="victim", tpus=1, cpus=0, ports=0)
    in_delete = threading.Event()
    release_delete = threading.Event()
    real_join = rs.wq.join

    def slow_join(*a, **kw):
        in_delete.set()
        release_delete.wait(5)
        return real_join(*a, **kw)

    rs.wq.join = slow_join          # widen the window while delete holds the lock
    results = []

    def create_again():
        in_delete.wait(5)
        rs.wq.join = real_join      # only the first (delete) call is slowed
        release_delete.set()
        try:
            results.append(_run(rs, name="victim", tpus=1, cpus=0, ports=0))
        except Exception as e:  # noqa: BLE001
            results.append(e)

    t = threading.Thread(target=create_again)
    t.start()
    rs.delete_container("victim")
    t.join(10)
    assert not t.is_alive()
    assert results and not isinstance(results[0], Exception), results
    assert results[0]["name"] == "victim-1"   # fresh lifecycle, version 1
    rs.delete_container("victim")
    assert "victim" not in rs._name_locks
