"""Mesh sharding, ring attention, and the sharded train step on the
8-device virtual CPU mesh (the driver's dryrun uses the same mechanism)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gpu_docker_api_tpu.models.llama import LlamaConfig, init_params
from gpu_docker_api_tpu.ops.attention import reference_attention
from gpu_docker_api_tpu.parallel.mesh import (
    MeshPlan, best_tp_for, make_mesh, param_sharding_rules,
    validate_plan_for_topology,
)
from gpu_docker_api_tpu.parallel.ring import ring_attention
from gpu_docker_api_tpu.train import Trainer, TrainConfig, loss_fn


def test_mesh_plan_auto():
    p = MeshPlan.auto(8, tp=2)
    assert p.size == 8 and p.fsdp == 4 and p.tp == 2
    with pytest.raises(ValueError):
        MeshPlan.auto(8, tp=3)
    assert best_tp_for(8) == 8
    assert best_tp_for(12, max_tp=8) == 4


def test_make_mesh_axes():
    mesh = make_mesh(MeshPlan(dp=1, fsdp=2, tp=2, sp=2))
    assert mesh.shape == {"dp": 1, "fsdp": 2, "pp": 1, "ep": 1,
                          "tp": 2, "sp": 2}
    with pytest.raises(ValueError):
        make_mesh(MeshPlan(dp=3))


def test_plan_topology_validation():
    assert validate_plan_for_topology(MeshPlan(fsdp=2, tp=2, sp=2), (2, 2, 2))
    assert not validate_plan_for_topology(MeshPlan(fsdp=1, tp=1), (2, 2, 2))


def test_ring_attention_matches_reference():
    mesh = make_mesh(MeshPlan(dp=1, fsdp=1, tp=1, sp=8))
    b, s, h, hkv, d = 2, 64, 4, 2, 32
    q = jax.random.normal(jax.random.key(0), (b, s, h, d), jnp.float32)
    k = jax.random.normal(jax.random.key(1), (b, s, hkv, d), jnp.float32)
    v = jax.random.normal(jax.random.key(2), (b, s, hkv, d), jnp.float32)
    ref = reference_attention(q, k, v, causal=True)
    with mesh:
        out = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


def test_ring_attention_noncausal():
    mesh = make_mesh(MeshPlan(dp=1, fsdp=2, tp=1, sp=4))
    b, s, h, d = 2, 32, 2, 16
    q = jax.random.normal(jax.random.key(3), (b, s, h, d), jnp.float32)
    k = jax.random.normal(jax.random.key(4), (b, s, h, d), jnp.float32)
    v = jax.random.normal(jax.random.key(5), (b, s, h, d), jnp.float32)
    ref = reference_attention(q, k, v, causal=False)
    with mesh:
        out = ring_attention(q, k, v, mesh, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


def test_sharded_params_placement():
    cfg = LlamaConfig.tiny()
    mesh = make_mesh(MeshPlan(dp=1, fsdp=2, tp=2, sp=2))
    trainer = Trainer.create(cfg, MeshPlan(dp=1, fsdp=2, tp=2, sp=2))
    state = trainer.init(jax.random.key(0))
    embed = state["params"]["embed"]
    # embed [V, D] vocab-parallel (("tp","fsdp"), None) -> V/(2*2) x D
    shard_shapes = {s.data.shape for s in embed.addressable_shards}
    assert shard_shapes == {(cfg.vocab_size // 4, cfg.d_model)}
    # optimizer moments shard like their params
    leaves = jax.tree.leaves(state["opt_state"],
                             is_leaf=lambda x: hasattr(x, "sharding"))
    assert any(
        getattr(l, "shape", ()) == embed.shape and l.sharding == embed.sharding
        for l in leaves if hasattr(l, "sharding"))


@pytest.mark.slow
def test_train_step_loss_decreases():
    cfg = LlamaConfig.tiny()
    trainer = Trainer.create(
        cfg, MeshPlan(dp=2, fsdp=2, tp=2, sp=1),
        tc=TrainConfig(learning_rate=1e-2, remat=False))
    state = trainer.init(jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(7), (4, 32), 0, cfg.vocab_size)
    tokens = trainer.shard_batch(tokens)
    losses = []
    for _ in range(5):
        state, metrics = trainer.step(state, tokens)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]  # memorizing one batch must reduce loss
    assert all(np.isfinite(losses))
    assert int(state["step"]) == 5


def test_train_step_with_remat_matches():
    cfg = LlamaConfig.tiny()
    tokens = jax.random.randint(jax.random.key(8), (2, 16), 0, cfg.vocab_size)
    params = init_params(cfg, jax.random.key(0))
    base = loss_fn(params, tokens, cfg)
    rematted = jax.checkpoint(
        lambda p: loss_fn(p, tokens, cfg))(params)
    np.testing.assert_allclose(float(base), float(rematted), rtol=1e-6)


@pytest.mark.parametrize("family", ["llama", "moe"])
@pytest.mark.slow
def test_remat_policies_identical_numerics(family):
    """Per-layer remat ("full" min-HBM and "dots" save-matmul-outputs) must
    not change the step's loss or gradients vs no remat — rematerialization
    is a scheduling choice, never a numerics one. MoE is the riskier
    target: its scan body carries (x, aux_sum, z_sum) with router losses
    crossing the remat boundary."""
    if family == "moe":
        from gpu_docker_api_tpu.models.moe import MoEConfig
        cfg = MoEConfig.tiny()
    else:
        cfg = LlamaConfig.tiny()
    tokens = jax.random.randint(jax.random.key(9), (4, 32), 0, cfg.vocab_size)
    outs = {}
    for label, tc in {
        "none": TrainConfig(remat=False),
        "full": TrainConfig(remat=True, remat_policy="full"),
        "dots": TrainConfig(remat=True, remat_policy="dots"),
    }.items():
        trainer = Trainer.create(cfg, MeshPlan(dp=2, fsdp=2, tp=2, sp=1),
                                 tc=tc)
        state = trainer.init(jax.random.key(0))
        _, m = trainer.step(state, trainer.shard_batch(tokens))
        outs[label] = (float(m["loss"]), float(m["grad_norm"]))
    np.testing.assert_allclose(outs["full"], outs["none"], rtol=2e-5)
    np.testing.assert_allclose(outs["dots"], outs["none"], rtol=2e-5)


def test_param_specs_layer_axis_unsharded():
    """Layer-stacked params: the scan axis must be None; fsdp/tp land on the
    matrix axes (regression: specs were written for 2-D weights)."""
    from gpu_docker_api_tpu.train import param_specs
    cfg = LlamaConfig.tiny()
    specs = param_specs(cfg)
    assert specs["layers"]["wq"] == jax.sharding.PartitionSpec(None, "fsdp", "tp")
    assert specs["layers"]["wo"] == jax.sharding.PartitionSpec(None, "tp", "fsdp")
    assert specs["embed"] == jax.sharding.PartitionSpec(("tp", "fsdp"), None)
    # placement: wq [L, D, kq] shards D over fsdp, kq over tp
    trainer = Trainer.create(cfg, MeshPlan(dp=1, fsdp=2, tp=2, sp=2))
    state = trainer.init(jax.random.key(0))
    wq = state["params"]["layers"]["wq"]
    kq = cfg.n_heads * (cfg.d_model // cfg.n_heads)
    assert {s.data.shape for s in wq.addressable_shards} == {
        (cfg.n_layers, cfg.d_model // 2, kq // 2)}


def test_opt_state_sharding_matches_by_path():
    """wq and wo have identical shapes with transposed specs — moments must
    match their own param's sharding (regression: shape-keyed match)."""
    cfg = LlamaConfig.tiny()
    trainer = Trainer.create(cfg, MeshPlan(dp=1, fsdp=2, tp=2, sp=2))
    state = trainer.init(jax.random.key(0))
    params = state["params"]
    # find the adam moments subtree (mirrors the param tree)
    from jax.tree_util import tree_flatten_with_path
    flat = tree_flatten_with_path(state["opt_state"])[0]
    mu_wo = [l for p, l in flat
             if "'wo'" in "".join(str(x) for x in p) and ".mu" in "".join(str(x) for x in p)]
    assert mu_wo, "no mu found for wo"
    assert mu_wo[0].sharding == params["layers"]["wo"].sharding
    mu_wq = [l for p, l in flat
             if "'wq'" in "".join(str(x) for x in p) and ".mu" in "".join(str(x) for x in p)]
    assert mu_wq[0].sharding == params["layers"]["wq"].sharding
    assert params["layers"]["wq"].sharding != params["layers"]["wo"].sharding


def test_forward_uses_ring_under_sp_mesh():
    """llama_forward with an sp>1 mesh must produce the same numbers as the
    unsharded forward (ring attention wiring regression)."""
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(9), (2, 32), 0, cfg.vocab_size)
    from gpu_docker_api_tpu.models.llama import llama_forward
    base = llama_forward(params, tokens, cfg, impl="xla")
    mesh = make_mesh(MeshPlan(dp=1, fsdp=1, tp=1, sp=8))
    with mesh:
        sharded = llama_forward(params, tokens, cfg, impl="xla", mesh=mesh)
    np.testing.assert_allclose(np.asarray(base), np.asarray(sharded),
                               atol=2e-4, rtol=2e-4)


@pytest.mark.slow
def test_grad_accumulation_matches_full_batch():
    """accum_steps slices must reproduce the full-batch step: same loss,
    same post-update params (tiny config is f32, so exact to fp tolerance)."""
    cfg = LlamaConfig.tiny()
    tokens = jax.random.randint(jax.random.key(11), (8, 32), 0,
                                cfg.vocab_size)
    outs = {}
    for label, a in {"full": 1, "accum4": 4}.items():
        tr = Trainer.create(cfg, MeshPlan(dp=2, fsdp=2, tp=2, sp=1),
                            tc=TrainConfig(remat=False, accum_steps=a))
        st = tr.init(jax.random.key(0))
        st2, m = tr.step(st, tr.shard_batch(tokens))
        outs[label] = (float(m["loss"]),
                       np.asarray(jax.device_get(
                           jax.tree.leaves(st2["params"])[0])))
    np.testing.assert_allclose(outs["accum4"][0], outs["full"][0],
                               rtol=2e-5)
    np.testing.assert_allclose(outs["accum4"][1], outs["full"][1],
                               rtol=1e-4, atol=1e-5)


def test_grad_accumulation_rejects_indivisible_batch():
    cfg = LlamaConfig.tiny()
    tr = Trainer.create(cfg, MeshPlan(dp=2, fsdp=2, tp=2, sp=1),
                        tc=TrainConfig(remat=False, accum_steps=3))
    st = tr.init(jax.random.key(0))
    toks = tr.shard_batch(jax.random.randint(jax.random.key(12), (8, 32), 0,
                                             cfg.vocab_size))
    with pytest.raises(ValueError, match="divisible"):
        tr.step(st, toks)


@pytest.mark.slow
def test_lr_schedule_warmup_cosine():
    """make_schedule: 0 at step 0, peak at warmup end, min ratio at the
    decay horizon; bare TrainConfig stays a plain constant."""
    from gpu_docker_api_tpu.train import make_schedule
    tc = TrainConfig(learning_rate=1e-3, warmup_steps=10, decay_steps=90,
                     min_lr_ratio=0.1)
    sched = make_schedule(tc)
    np.testing.assert_allclose(float(sched(0)), 0.0, atol=1e-9)
    np.testing.assert_allclose(float(sched(10)), 1e-3, rtol=1e-6)
    np.testing.assert_allclose(float(sched(100)), 1e-4, rtol=1e-5)
    assert float(sched(55)) < 1e-3
    assert make_schedule(TrainConfig(learning_rate=2e-4)) == 2e-4
    # schedule actually drives the optimizer: a warmup step at step 0 is a no-op
    cfg = LlamaConfig.tiny()
    tr = Trainer.create(cfg, MeshPlan(dp=2, fsdp=2, tp=2, sp=1),
                        tc=TrainConfig(remat=False, warmup_steps=5,
                                       decay_steps=50))
    st = tr.init(jax.random.key(0))
    p0 = np.asarray(jax.device_get(jax.tree.leaves(st["params"])[0]))
    st2, _ = tr.step(st, tr.shard_batch(
        jax.random.randint(jax.random.key(13), (4, 32), 0, cfg.vocab_size)))
    p1 = np.asarray(jax.device_get(jax.tree.leaves(st2["params"])[0]))
    np.testing.assert_allclose(p1, p0, atol=1e-7)   # lr(0) == 0
