"""Race stress sweep (`races` marker; make verify-races) + regression
tests for the concurrency findings tdlint/lockwatch flagged in existing
code.

The stress harness fires randomized concurrent run/patch/stop/restart/
delete/drain/fractional-grant mixes from many threads against one world
while a checker thread continuously asserts the scheduler's cross-map
invariants on ONE consistent locked snapshot:

- per-chip share-ledger sum never exceeds SHARE_QUANTA;
- bitmap/ledger disjointness: a whole-owned chip never carries share
  entries (share-split chips are invisible to whole placement, and vice
  versa);
- share quanta are always 1..SHARE_QUANTA with real owners.

Only domain errors (xerrors.XError — not-enough, oversubscribed, existed,
no-patch-required...) are expected under contention; any OTHER exception
(KeyError, RuntimeError: dict changed size during iteration — the classic
torn-read crash) fails the sweep. At the end every replicaSet is deleted
and the harness asserts zero leaked grants across all three schedulers.

Regression tests (the genuine pre-existing findings this PR fixed):

1. health.py probed the substrate while holding the monitor lock — a hung
   device node parked /healthz's report() behind a dead backend
   (lockwatch: lock held across backend op).
2. reconcile.py iterated LIVE scheduler dicts from the runtime
   `?run=1` path while request threads grant concurrently (tdlint:
   unlocked-state cross-object access).
3. reconcile.py silently skipped intent records whose op/step this build
   doesn't know — version drift cleared a half-done mutation without a
   trace (tdlint: unknown-step is the static half; the runtime half now
   surfaces on the report and the event log).
"""

import random
import threading
import time

import pytest

from gpu_docker_api_tpu import xerrors
from gpu_docker_api_tpu.backend import MockBackend
from gpu_docker_api_tpu.dtos import ContainerRun, PatchRequest, TpuPatch
from gpu_docker_api_tpu.health import HealthMonitor
from gpu_docker_api_tpu.intents import IntentJournal
from gpu_docker_api_tpu.reconcile import KNOWN_STEPS, Reconciler
from gpu_docker_api_tpu.schedulers import (
    SHARE_QUANTA, CpuScheduler, PortScheduler, TpuScheduler,
)
from gpu_docker_api_tpu.services import ReplicaSetService
from gpu_docker_api_tpu.store import MVCCStore, StateClient
from gpu_docker_api_tpu.topology import make_topology
from gpu_docker_api_tpu.version import MergeMap, VersionMap
from gpu_docker_api_tpu.workqueue import WorkQueue

pytestmark = pytest.mark.races


@pytest.fixture()
def world(tmp_path):
    store = MVCCStore()
    client = StateClient(store)
    wq = WorkQueue(client)
    wq.start()
    backend = MockBackend(str(tmp_path / "state"))
    tpu = TpuScheduler(client, wq, topology=make_topology("v4-16"))
    cpu = CpuScheduler(client, wq, core_count=64)
    ports = PortScheduler(client, wq, port_range=(43000, 43400), seed=7)
    rs = ReplicaSetService(backend, client, wq, tpu, cpu, ports,
                           VersionMap("containerVersionMap", client, wq),
                           MergeMap(client, wq))
    yield rs, backend, tpu, cpu, ports, wq, client
    wq.close()


def _check_invariants(snap) -> list:
    """Invariant assertions over ONE locked snapshot (tpu.snapshot())."""
    bad = []
    for chip, owners in snap["shares"].items():
        total = sum(owners.values())
        if total > SHARE_QUANTA:
            bad.append(f"chip {chip} ledger oversubscribed: {owners}")
        for owner, q in owners.items():
            if not owner or not (1 <= q <= SHARE_QUANTA):
                bad.append(f"chip {chip} bogus grant {owner!r}={q}")
        if owners and snap["status"].get(chip) is not None:
            bad.append(
                f"chip {chip} both whole-owned by "
                f"{snap['status'][chip]!r} and share-split: {owners}")
    return bad


# ------------------------------------------------------------ the sweep

@pytest.mark.parametrize("seed", [11, 23])
def test_concurrent_mutation_stress(world, seed):
    rs, backend, tpu, cpu, ports, wq, _client = world
    n_workers, n_ops = 6, 22
    unexpected: list = []
    invariant_violations: list = []
    stop_checking = threading.Event()

    def checker():
        while not stop_checking.is_set():
            invariant_violations.extend(_check_invariants(tpu.snapshot()))
            if invariant_violations:
                return
            time.sleep(0.002)

    def attempt(fn):
        try:
            fn()
        except (xerrors.XError, ValueError):
            pass                     # domain outcome under contention
        except Exception as e:       # noqa: BLE001 — the race signal
            unexpected.append(f"{type(e).__name__}: {e}")

    def worker(wid):
        rng = random.Random(seed * 100 + wid)
        names = [f"w{wid}a", f"w{wid}b", f"w{wid}c"]
        for _ in range(n_ops):
            name = rng.choice(names)
            roll = rng.random()
            if roll < 0.30:
                count = rng.choice([1, 2, 0.25, 0.5, 0.75])
                attempt(lambda: rs.run_container(ContainerRun(
                    imageName="ubuntu:22.04", replicaSetName=name,
                    tpuCount=count,
                    priority=rng.choice(["", "latency", "best_effort"]))))
            elif roll < 0.50:
                count = rng.choice([1, 2, 0.25, 0.5, 0.75])
                attempt(lambda: rs.patch_container(
                    name, PatchRequest(tpuPatch=TpuPatch(count))))
            elif roll < 0.62:
                attempt(lambda: rs.stop_container(name))
            elif roll < 0.72:
                attempt(lambda: rs.restart_container(name))
            elif roll < 0.82:
                attempt(lambda: rs.delete_container(name))
            elif roll < 0.90:
                # cross-worker read/stop: name-lock + snapshot contention
                other = f"w{(wid + 1) % n_workers}{rng.choice('abc')}"
                attempt(lambda: rs.get_container_info(other))
            elif roll < 0.96:
                attempt(lambda: tpu.get_status())
            else:
                # cordon a chip, drain its tenants, uncordon
                chip = rng.randrange(8)
                def drain_cycle():
                    tpu.cordon([chip])
                    try:
                        rs.drain_cordoned()
                    finally:
                        tpu.uncordon([chip])
                attempt(drain_cycle)

    chk = threading.Thread(target=checker)
    chk.start()
    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive(), "stress worker wedged (deadlock?)"
    stop_checking.set()
    chk.join(timeout=10)

    assert unexpected == []
    assert invariant_violations == []

    # drain everything and prove zero leaked grants anywhere
    for wid in range(n_workers):
        for suffix in "abc":
            try:
                rs.delete_container(f"w{wid}{suffix}")
            except xerrors.XError:
                pass
    wq.join()
    snap = tpu.snapshot()
    assert _check_invariants(snap) == []
    assert all(o is None for o in snap["status"].values()), snap["status"]
    assert snap["shares"] == {}
    assert snap["cordoned"] == set()
    assert cpu.owners() == {} or all(
        o is None for o in cpu.owners().values())
    assert all(o is None for o in ports.owners().values())


# ------------------------------- regression: stress-sweep flake findings

def test_restart_stopped_fractional_under_exhaustion_raises_domain_error(
        world):
    """REGRESSION (stress-sweep worker IndexError, ~1/4 runs at PR 9
    HEAD): restarting a STOPPED fractional replicaSet when share capacity
    has since been exhausted made apply_shares raise — and the unwind
    handler, keyed on the requested quanta instead of the taken grant,
    indexed an empty fresh_tpu list. The domain error must propagate
    clean, with nothing leaked."""
    rs, _backend, tpu, _cpu, _ports, wq, _client = world
    rs.run_container(ContainerRun(imageName="ubuntu:22.04",
                                  replicaSetName="frac", tpuCount=0.25))
    rs.stop_container("frac")       # releases the quanta
    # eat ALL remaining capacity with whole-chip grants
    hogs = tpu.apply(len(tpu.owners()), "hog")
    with pytest.raises(xerrors.TpuNotEnoughError):   # incl. Oversubscribed
        rs.restart_container("frac")
    tpu.restore(hogs, "hog")
    wq.join()
    snap = tpu.snapshot()
    assert snap["shares"] == {}
    assert all(o is None for o in snap["status"].values())


def test_drain_regrant_on_same_chip_releases_old_quanta(world):
    """REGRESSION (stress-sweep share-ledger leak): a drain migration's
    fresh share grant can land back on the SAME chip with the same quanta
    when the cordon snapshot raced an uncordon — the old holding then
    compared equal to the new spec and was treated as an identical
    carryover, never released. The explicit fresh-grant flag releases the
    old quanta exactly once; the ledger ends with only the new grant."""
    rs, _backend, tpu, _cpu, _ports, wq, _client = world
    out = rs.run_container(ContainerRun(imageName="ubuntu:22.04",
                                        replicaSetName="mig",
                                        tpuCount=0.25))
    chip = out["tpuChips"][0]
    assert tpu.shares_snapshot()[chip] == {"mig": 1}
    # simulate the race window: the drain's entry snapshot says the chip
    # is cordoned, but by re-grant time it is not — apply_shares picks the
    # most-loaded chip, which is the SAME one
    orig = tpu.cordoned_snapshot
    tpu.cordoned_snapshot = lambda: {chip}
    try:
        result = rs.drain_cordoned()
    finally:
        tpu.cordoned_snapshot = orig
    assert [d["name"] for d in result["drained"]] == ["mig"]
    wq.join()
    snap = tpu.snapshot()
    total = sum(sum(o.values()) for o in snap["shares"].values())
    assert total == 1, f"leaked share quanta: {snap['shares']}"
    rs.delete_container("mig")
    wq.join()
    assert tpu.snapshot()["shares"] == {}


# ----------------------------------------------- regression: health probe

class _HangableBackend:
    """Health-hook stub whose chip probe can hang forever."""

    def __init__(self):
        self.gate = threading.Event()   # unset = chip_available hangs
        self.gate.set()

    def ping(self):
        return True

    def flap_counts(self):
        return {}

    def chip_available(self, device_path):
        self.gate.wait()                # a dead device node, in effect
        return True


def test_report_not_parked_behind_hung_probe():
    """REGRESSION (lockwatch: lock held across backend op): probing used
    to call backend.chip_available chip-by-chip INSIDE the monitor lock,
    so one hung device node parked report() — served at /healthz, the
    endpoint an operator needs exactly when the substrate is sick. All
    substrate probing now happens before the lock is taken."""
    backend = _HangableBackend()
    tpu = TpuScheduler(topology=make_topology("v4-8"))
    mon = HealthMonitor(backend, tpu, auto_cordon=False)
    mon.probe_once()                    # healthy warm-up cycle
    backend.gate.clear()                # device node wedges
    t = threading.Thread(target=mon.probe_once, daemon=True)
    t.start()
    time.sleep(0.1)                     # prober is now inside the hang
    done = threading.Event()
    out: dict = {}

    def read_report():
        out.update(mon.report())
        done.set()

    threading.Thread(target=read_report, daemon=True).start()
    ok = done.wait(timeout=5)
    backend.gate.set()                  # unwedge before asserting
    t.join(timeout=5)
    assert ok, "report() blocked behind a hung substrate probe"
    assert out["probes"] == 1           # the wedged cycle hadn't landed


# ------------------------------------- regression: live-dict iteration

def test_scheduler_snapshots_safe_under_concurrent_grants():
    """REGRESSION (tdlint: unlocked-state): the runtime reconcile path
    iterated self.tpu.status / .shares / ports.used LIVE while request
    threads grant — a dict mutated mid-iteration raises RuntimeError and
    a torn multi-key read frees the wrong grants. The locked snapshot
    accessors (owners()/shares_snapshot()/cordoned_snapshot()) must stay
    stable under a concurrent grant/release storm."""
    tpu = TpuScheduler(topology=make_topology("v4-32"))     # 16 chips
    errors: list = []
    stop = threading.Event()

    def churn(wid):
        rng = random.Random(wid)
        while not stop.is_set():
            try:
                if rng.random() < 0.5:
                    grant = tpu.apply(rng.choice([1, 2]), f"o{wid}")
                    tpu.restore(grant, f"o{wid}")
                else:
                    q = rng.choice([1, 2])
                    chip = tpu.apply_shares(q, f"s{wid}")
                    tpu.restore_shares(chip, q, f"s{wid}")
            except xerrors.XError:
                pass
            except Exception as e:      # noqa: BLE001
                errors.append(f"churn: {type(e).__name__}: {e}")

    def read_loop():
        while not stop.is_set():
            try:
                for _idx, _owner in tpu.owners().items():
                    pass
                for _chip, owners in tpu.shares_snapshot().items():
                    sum(owners.values())
                tpu.cordoned_snapshot()
                tpu.snapshot()
            except Exception as e:      # noqa: BLE001
                errors.append(f"read: {type(e).__name__}: {e}")

    threads = ([threading.Thread(target=churn, args=(i,)) for i in range(4)]
               + [threading.Thread(target=read_loop) for _ in range(2)])
    for t in threads:
        t.start()
    time.sleep(1.5)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    assert errors == []


def test_owners_returns_snapshot_not_live_map():
    tpu = TpuScheduler(topology=make_topology("v4-8"))
    before = tpu.owners()
    grant = tpu.apply(1, "a")
    assert all(o is None for o in before.values())       # copy, not alias
    assert tpu.owners()[grant[0]] == "a"
    shares_before = tpu.shares_snapshot()
    chip = tpu.apply_shares(2, "b")
    assert chip not in shares_before
    assert tpu.shares_snapshot()[chip] == {"b": 2}


# --------------------------------- regression: unknown intent op / step

def _reconciler(world, events=None):
    rs, backend, tpu, cpu, ports, wq, client = world
    return Reconciler(
        backend, client, wq, tpu, cpu, ports,
        VersionMap("containerVersionMap", client, wq),
        VersionMap("volumeVersionMap", client, wq),
        MergeMap(client, wq), IntentJournal(client),
        events=events, replicasets=rs)


def test_reconcile_surfaces_unknown_intent_op(world):
    """REGRESSION: an intent op this build has no replay handler for
    (journaled by a newer daemon, or corrupt) was logged at debug level
    and silently cleared — the mutation it describes stays half-done with
    zero operator-visible evidence. It now lands on the reconcile report
    (counted as an action) and the event log. Uses the REAL EventLog: a
    stub with a different record() signature once hid a keyword collision
    with its first positional (`op`)."""
    from gpu_docker_api_tpu.events import EventLog
    _rs, _backend, _tpu, _cpu, _ports, wq, client = world
    journal = IntentJournal(client)
    journal.begin("teleport", "ghost-1")
    wq.join()
    events = EventLog()
    report = _reconciler(world, events=events).run()
    assert report["unknownIntentOps"] == ["container:ghost-1:teleport"]
    assert report["actions"] >= 1
    rows = [e for e in events.recent()
            if e["op"] == "reconcile.unknown_op"]
    assert rows and rows[0]["intentOp"] == "teleport"
    assert rows[0]["target"] == "ghost-1"


def test_reconcile_surfaces_unknown_step(world):
    from gpu_docker_api_tpu.events import EventLog
    _rs, _backend, _tpu, _cpu, _ports, wq, client = world
    journal = IntentJournal(client)
    intent = journal.begin("run", "w0x-1")
    intent.step("hyperdrive")           # a step no reconciler branch reads
    assert "hyperdrive" not in KNOWN_STEPS
    wq.join()
    events = EventLog()
    _reconciler(world, events=events).run()
    rows = [e for e in events.recent()
            if e["op"] == "reconcile.unknown_step"]
    assert rows and rows[0]["steps"] == ["hyperdrive"]
    assert rows[0]["intentOp"] == "run"
