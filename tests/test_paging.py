"""Paged KV cache (paging.py): block-pool primitives, batcher
integration, admission control (VERDICT r2 weak #4 / next #6)."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gpu_docker_api_tpu.infer import generate
from gpu_docker_api_tpu.models.llama import LlamaConfig, init_params
from gpu_docker_api_tpu.paging import (
    BlockAllocator, init_paged_cache, paged_decode, paged_prefill,
)
from gpu_docker_api_tpu.workloads.serve import _Batcher


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny()
    return cfg, init_params(cfg, jax.random.key(0))


def _run_slot(cfg, params, cache, slot, prompt, max_new, slots=2):
    """Drive one slot's stream through the paged primitives."""
    logits, cache = paged_prefill(params, prompt, cache, jnp.int32(slot),
                                  cfg)
    toks = [int(jnp.argmax(logits[0]))]
    active = jnp.array([i == slot for i in range(slots)])
    while len(toks) < max_new:
        step = jnp.array([toks[-1] if i == slot else 0
                          for i in range(slots)], jnp.int32)
        logits, cache = paged_decode(params, step, cache, active, cfg)
        toks.append(int(jnp.argmax(logits[slot])))
    return toks, cache


def _pages_for(alloc, blk, n_tokens, max_pages):
    need = -(-n_tokens // blk)
    blocks = alloc.alloc(need)
    row = np.zeros(max_pages, np.int32)
    row[:need] = blocks
    return jnp.array(row), blocks


@pytest.mark.slow
@pytest.mark.parametrize("quantized", [False, True])
def test_paged_stream_matches_generate(tiny, quantized):
    """The paged cache reproduces infer.generate's greedy stream exactly
    (dense pool and int8 pool) — non-contiguous blocks included."""
    cfg, params = tiny
    prompt = jnp.array([[5, 9, 2, 7, 11, 3]], jnp.int32)
    max_new = 8
    want = np.asarray(generate(params, prompt, cfg, max_new,
                               kv_quant=quantized))[0].tolist()
    blk = 4
    cache = init_paged_cache(cfg, n_blocks=16, block_size=blk, slots=2,
                             max_pages=8, quantized=quantized)
    alloc = BlockAllocator(16)
    alloc.alloc(3)     # burn a few so slot pages are NOT contiguous
    row, _ = _pages_for(alloc, blk, prompt.shape[1] + max_new, 8)
    cache["pages"] = cache["pages"].at[1].set(row)
    toks, _ = _run_slot(cfg, params, cache, 1, prompt, max_new)
    assert toks == want


@pytest.mark.slow
def test_pool_memory_is_independent_of_slots_times_max_len(tiny):
    """THE point: cache memory ∝ pool blocks, not slots x max_len. A
    16-slot, 128-token-max batcher with a 9-block pool holds 9x8 = 72
    tokens of KV — 17x less than the dense 16x128; and it still serves
    correctly within that budget."""
    cfg, params = tiny
    blk, pool = 8, 9
    b = _Batcher(cfg, params, slots=16, max_len=128, kv_block=blk,
                 kv_pool_blocks=pool)
    try:
        dense_tokens = 16 * 128
        paged_tokens = pool * blk
        assert b.cache["k"].shape[1] * b.cache["k"].shape[2] == paged_tokens
        assert paged_tokens * 17 <= dense_tokens
        prompt = jnp.array([5, 9, 2, 7], jnp.int32)
        want = np.asarray(generate(params, prompt[None], cfg, 6))[0].tolist()
        assert b.submit(prompt, 6) == want
    finally:
        b.close()


@pytest.mark.slow
def test_paged_batcher_streams_match_dense(tiny):
    """Concurrent streams through the PAGED batcher equal their solo
    greedy streams (the dense batcher's equality contract, unchanged)."""
    cfg, params = tiny
    b = _Batcher(cfg, params, slots=3, max_len=64, kv_block=8)
    try:
        prompts = [jax.random.randint(jax.random.key(i), (4 + 3 * i,), 0,
                                      cfg.vocab_size) for i in range(3)]
        want = [np.asarray(generate(params, p[None], cfg, 5))[0].tolist()
                for p in prompts]
        got = [None] * 3

        def ask(i):
            got[i] = b.submit(prompts[i], 5)

        ts = [threading.Thread(target=ask, args=(i,)) for i in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        assert got == want
    finally:
        b.close()


@pytest.mark.slow
def test_admission_waits_for_free_blocks(tiny):
    """A pool too small for two concurrent requests serializes them:
    the second waits for the first's blocks, then completes correctly —
    admission by free blocks, not by slot count."""
    cfg, params = tiny
    blk = 8
    # pool fits exactly ONE (prompt 4 + max_new 12 -> 2 blocks) + scratch
    b = _Batcher(cfg, params, slots=2, max_len=32, kv_block=blk,
                 kv_pool_blocks=3)
    try:
        prompts = [jnp.array([5, 9, 2, 7], jnp.int32),
                   jnp.array([1, 3, 3, 8], jnp.int32)]
        want = [np.asarray(generate(params, p[None], cfg, 12))[0].tolist()
                for p in prompts]
        got = [None] * 2

        def ask(i):
            got[i] = b.submit(prompts[i], 12)

        ts = [threading.Thread(target=ask, args=(i,)) for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        assert sorted(map(tuple, got)) == sorted(map(tuple, want))
        assert b._alloc.free_blocks == 2      # everything returned
    finally:
        b.close()


def test_oversized_request_rejected_up_front(tiny):
    cfg, params = tiny
    b = _Batcher(cfg, params, slots=1, max_len=64, kv_block=8,
                 kv_pool_blocks=3)
    try:
        with pytest.raises(ValueError, match="never be admitted"):
            b.submit(jnp.zeros((30,), jnp.int32), 20)
    finally:
        b.close()


@pytest.mark.slow
def test_paged_chunked_prefill_stream_exact(tiny):
    cfg, params = tiny
    b = _Batcher(cfg, params, slots=2, max_len=64, kv_block=8,
                 prefill_chunk=4)
    try:
        prompt = jax.random.randint(jax.random.key(9), (11,), 0,
                                    cfg.vocab_size)
        want = np.asarray(generate(params, prompt[None], cfg, 6))[0].tolist()
        assert b.submit(prompt, 6) == want
    finally:
        b.close()


@pytest.mark.slow
def test_paged_prefix_sharing_zero_copy(tiny):
    """Zero-copy prefix reuse: a second request extending a cached
    prompt points its page table at the SHARED blocks (no new blocks
    for the prefix, no data copy) and its stream still exactly equals
    the solo greedy stream."""
    cfg, params = tiny
    blk = 4
    b = _Batcher(cfg, params, slots=2, max_len=64, kv_block=blk,
                 kv_pool_blocks=24, prefix_cache=4)
    try:
        sys_prompt = [5, 9, 2, 7, 11, 3, 1, 4]          # 2 full blocks
        p1 = jnp.array(sys_prompt + [8, 6], jnp.int32)
        p2 = jnp.array(sys_prompt + [2, 13, 10], jnp.int32)
        want1 = np.asarray(generate(params, p1[None], cfg, 6))[0].tolist()
        want2 = np.asarray(generate(params, p2[None], cfg, 6))[0].tolist()

        assert b.submit(p1, 6) == want1
        free_after_1 = b._alloc.free_blocks
        assert b.prefix_hits == 0
        # second request shares the 2-block prefix: allocates blocks for
        # ceil((11+6)/4)=5 pages MINUS the 2 shared -> 3 new
        assert b.submit(p2, 6) == want2
        assert b.prefix_hits == 1
        # everything private returned; the 2 stored blocks stay live
        assert b._alloc.free_blocks == free_after_1
    finally:
        b.close()


@pytest.mark.slow
def test_paged_prefix_eviction_returns_blocks(tiny):
    """The prefix trie has NO count bound — every completed prompt stays
    warm until pool pressure — and pressure eviction drops LRU leaves'
    block references: the pool never leaks."""
    cfg, params = tiny
    b = _Batcher(cfg, params, slots=1, max_len=32, kv_block=4,
                 kv_pool_blocks=12, prefix_cache=1)
    try:
        total = b._alloc.free_blocks
        for seed in range(3):                  # distinct prompts
            p = jax.random.randint(jax.random.key(seed), (8,), 0,
                                   cfg.vocab_size)
            b.submit(p, 4)
        # ALL three prompts stay cached (2 blocks each): eviction is
        # pressure-only, prefix_cache no longer bounds the entry count
        assert b._alloc.free_blocks == total - 6
        assert len(b._trie) == 6
        # pressure: needs ceil((8+24)/4)=8 blocks > 5 free -> LRU leaves
        # evict until the request fits, and their blocks come back
        p = jax.random.randint(jax.random.key(9), (8,), 0,
                               cfg.vocab_size)
        want = np.asarray(generate(params, p[None], cfg, 24))[0].tolist()
        assert b.submit(p, 24) == want
        assert b.prefix_evictions >= 3
    finally:
        b.close()


@pytest.mark.slow
def test_paged_prefix_composes_with_kv_quant(tiny):
    cfg, params = tiny
    b = _Batcher(cfg, params, slots=1, max_len=64, kv_block=4,
                 prefix_cache=2, kv_quant=True)
    try:
        sys_prompt = [5, 9, 2, 7, 11, 3, 1, 4]
        p1 = jnp.array(sys_prompt + [8], jnp.int32)
        p2 = jnp.array(sys_prompt + [2, 13], jnp.int32)
        want2 = np.asarray(generate(params, p2[None], cfg, 6,
                                    kv_quant=True))[0].tolist()
        b.submit(p1, 4)
        assert b.submit(p2, 6) == want2
        assert b.prefix_hits == 1
    finally:
        b.close()


def test_block_allocator_bookkeeping():
    a = BlockAllocator(5)          # blocks 1..4 allocatable
    assert a.free_blocks == 4
    got = a.alloc(3)
    assert len(got) == 3 and 0 not in got
    assert a.alloc(2) is None      # only 1 left
    assert a.free_blocks == 1
    a.free(got)
    assert a.free_blocks == 4
    with pytest.raises(ValueError):
        BlockAllocator(1)


# ---- chunked decode (device-side multi-step scan) --------------------------

@pytest.mark.slow
@pytest.mark.parametrize("paged", [False, True])
def test_decode_chunk_streams_match_generate(tiny, paged):
    """decode_chunk > 1 must not change any stream: K steps per host
    sync, per-row budgets stop rows mid-chunk (K does not divide
    max_new), late-joining requests still correct."""
    cfg, params = tiny
    kw = {"kv_block": 8} if paged else {}
    b = _Batcher(cfg, params, slots=2, max_len=64, decode_chunk=5, **kw)
    try:
        prompts = [jnp.array([5, 9, 2, 7], jnp.int32),
                   jnp.array([1, 3, 3, 8, 2], jnp.int32)]
        new = [12, 7]          # neither divisible by the chunk of 5
        want = [np.asarray(generate(params, p[None], cfg, n))[0].tolist()
                for p, n in zip(prompts, new)]
        got = [None, None]

        def ask(i):
            got[i] = b.submit(prompts[i], new[i])

        ts = [threading.Thread(target=ask, args=(i,)) for i in range(2)]
        ts[0].start()
        import time
        time.sleep(0.3)        # second request joins mid-stream
        ts[1].start()
        for t in ts:
            t.join(timeout=120)
        assert got == want
    finally:
        b.close()


@pytest.mark.slow
def test_decode_multi_primitive_matches_single_steps(tiny):
    """slot_decode_multi == K sequential slot_decode calls exactly,
    including a row whose budget ends mid-chunk."""
    from gpu_docker_api_tpu.batching import (
        init_slot_cache, slot_decode, slot_decode_multi, slot_prefill,
    )

    cfg, params = tiny
    prompts = [jnp.array([[4, 8, 15]], jnp.int32),
               jnp.array([[16, 23, 42]], jnp.int32)]
    K = 6
    remaining = jnp.array([K, 3], jnp.int32)    # row 1 stops after 3

    def prefilled():
        cache = init_slot_cache(cfg, slots=2, max_len=32)
        lg0, cache = slot_prefill(params, prompts[0], cache,
                                  jnp.int32(0), cfg)
        lg1, cache = slot_prefill(params, prompts[1], cache,
                                  jnp.int32(1), cfg)
        toks = jnp.array([int(jnp.argmax(lg0[0])),
                          int(jnp.argmax(lg1[0]))], jnp.int32)
        return toks, cache

    toks, cache = prefilled()
    active = jnp.array([True, True])
    multi, cache_m = slot_decode_multi(params, toks, cache, active,
                                       remaining, cfg, K)
    multi = np.asarray(multi)                   # [K, 2]

    toks, cache = prefilled()
    singles = []
    for t in range(K):
        act = np.array([t < int(remaining[0]), t < int(remaining[1])])
        logits, cache = slot_decode(params, toks, cache,
                                    jnp.array(act), cfg)
        nxt = np.asarray(jnp.argmax(logits, axis=-1))
        toks = jnp.where(jnp.array(act), nxt, toks)
        singles.append(np.asarray(toks))
    # rows within budget agree step by step
    for t in range(K):
        assert multi[t, 0] == singles[t][0]
        if t < 3:
            assert multi[t, 1] == singles[t][1]
    # the stopped row's length froze at its budget
    assert int(cache_m["lengths"][1]) == prompts[1].shape[1] + 3


@pytest.mark.slow
def test_batcher_stress_mixed_traffic(tiny):
    """Robustness hammer: 12 concurrent requests (greedy + sampled,
    varied lengths) through a small paged pool with chunked prefill,
    prefix cache, and chunked decode all on — every greedy stream must
    equal its solo oracle, every sampled stream must be well-formed,
    and the pool must account to zero leaks afterwards."""
    import random

    cfg, params = tiny
    b = _Batcher(cfg, params, slots=3, max_len=64, kv_block=8,
                 kv_pool_blocks=12, prefill_chunk=4, prefix_cache=2,
                 decode_chunk=4, seed=3)
    try:
        rng = random.Random(0)
        sys_prompt = [5, 9, 2, 7, 11, 3, 1, 4]
        jobs = []
        for i in range(12):
            body = [rng.randrange(cfg.vocab_size) for _ in
                    range(rng.randrange(1, 6))]
            prompt = jnp.array(sys_prompt + body, jnp.int32)
            temp = 0.0 if i % 3 else 0.9
            jobs.append((prompt, rng.randrange(3, 9), temp))
        oracles = {}
        for i, (p, n, temp) in enumerate(jobs):
            if temp == 0.0:
                oracles[i] = np.asarray(
                    generate(params, p[None], cfg, n))[0].tolist()
        got = [None] * len(jobs)

        def ask(i):
            p, n, temp = jobs[i]
            got[i] = b.submit(p, n, temperature=temp, top_k=12)

        ts = [threading.Thread(target=ask, args=(i,)) for i in
              range(len(jobs))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=300)
        for i, (p, n, temp) in enumerate(jobs):
            assert got[i] is not None and len(got[i]) == n, (i, got[i])
            if i in oracles:
                assert got[i] == oracles[i], f"greedy stream {i} diverged"
            assert all(0 <= t < cfg.vocab_size for t in got[i])
        # zero block leaks: only trie-indexed prefixes may stay live,
        # and every trie node holds exactly one distinct pool block
        live = (b.kv_pool_blocks - 1) - b._alloc.free_blocks
        assert live == len(b._trie)
    finally:
        b.close()


@pytest.mark.slow
def test_pool_pressure_evicts_stored_prefixes(tiny):
    """Stored prefixes are a cache, not a reservation: a request that
    needs their blocks evicts LRU entries instead of deadlocking behind
    them (pool sized so free blocks alone can't fit the request)."""
    cfg, params = tiny
    b = _Batcher(cfg, params, slots=1, max_len=64, kv_block=4,
                 kv_pool_blocks=8, prefix_cache=4)
    try:
        # store a prefix pinning 2 of the 7 usable blocks
        b.submit(jnp.array([5, 9, 2, 7, 11, 3, 1, 4], jnp.int32), 4)
        assert len(b._trie) == 2
        # needs ceil((9+16)/4)=7 blocks > 5 free -> must evict the store
        p = jax.random.randint(jax.random.key(1), (9,), 0, cfg.vocab_size)
        want = np.asarray(generate(params, p[None], cfg, 16))[0].tolist()
        assert b.submit(p, 16) == want
    finally:
        b.close()


# ---- IN-BATCH prefix sharing (VERDICT r3 next #5) --------------------------
# Concurrent identical/common-prefix prompts share pool blocks AT ADMISSION
# from in-flight slots — no completed/stored prefix required. prefix_cache=0
# in these tests pins the sharing to the in-flight donor path specifically.

@pytest.mark.slow
def test_inbatch_identical_prompts_share_blocks(tiny):
    """4 identical prompts in one burst: the pool only fits them if the
    admissions share the prompt's blocks (4 unshared reservations need 36
    blocks; the pool has 32 usable). All four must run CONCURRENTLY, emit
    the exact solo stream, and the followers' shares must show in
    prefix_hits — with the prefix STORE off."""
    from concurrent.futures import ThreadPoolExecutor
    import time as _time

    cfg, params = tiny
    blk, max_new = 4, 24
    # 9 tokens: a follower may share full blocks of the first len-1=8
    # tokens (the last position always needs its own forward) -> 2 blocks
    prompt = jnp.array([5, 9, 2, 7, 11, 3, 1, 4, 6], jnp.int32)
    want = np.asarray(generate(params, prompt[None], cfg,
                               max_new))[0].tolist()
    # per request: ceil((9+24)/4) = 9 pages; unshared 4x9=36 > 32 usable;
    # shared: leader 9 + 3 followers x (9-2) = 30 <= 32
    b = _Batcher(cfg, params, slots=4, max_len=36, kv_block=blk,
                 kv_pool_blocks=33, prefix_cache=0)
    ex = ThreadPoolExecutor(4)
    try:
        # pay every compile first (full prefill + suffix prefill + decode
        # programs) so the burst below races model-step time, not XLA
        b.submit(prompt, 2)
        ex.submit(b.submit, prompt, 2).result(timeout=120)
        peak = 0
        futs = [ex.submit(b.submit, prompt, max_new) for _ in range(4)]
        # all four must become resident at once — impossible without
        # sharing (32 unshared blocks > 28 usable)
        deadline = _time.time() + 60
        while _time.time() < deadline and not all(f.done() for f in futs):
            peak = max(peak, sum(s is not None for s in b.slots))
            if peak == 4:
                break
            _time.sleep(0.001)
        got = [f.result(timeout=120) for f in futs]
    finally:
        b.close()
        ex.shutdown(wait=True)
    assert peak == 4, f"peak concurrent slots {peak}"
    for g in got:
        assert g == want
    assert b.prefix_hits == 3            # the three burst followers
    # nothing stored (prefix_cache=0): every block back in the pool
    assert b._alloc.free_blocks == 32


@pytest.mark.slow
def test_inbatch_follower_waits_for_mid_prefill_donor(tiny):
    """A follower admitted while its donor is MID chunked prefill must
    not attend unwritten positions: it parks until the donor's write
    frontier passes the shared tokens, then streams exactly."""
    from concurrent.futures import ThreadPoolExecutor

    cfg, params = tiny
    prompt = jax.random.randint(jax.random.key(77), (32,), 0,
                                cfg.vocab_size, jnp.int32)
    want = np.asarray(generate(params, prompt[None], cfg, 6))[0].tolist()
    b = _Batcher(cfg, params, slots=2, max_len=64, kv_block=4,
                 prefill_chunk=2, prefix_cache=0)
    ex = ThreadPoolExecutor(2)
    try:
        f1 = ex.submit(b.submit, prompt, 6)
        f2 = ex.submit(b.submit, prompt, 6)   # admitted mid-prefill
        got1, got2 = f1.result(timeout=120), f2.result(timeout=120)
    finally:
        b.close()
        ex.shutdown(wait=True)
    assert got1 == want and got2 == want
    assert b.prefix_hits == 1


@pytest.mark.slow
def test_inbatch_common_prefix_different_tails(tiny):
    """Different prompts sharing a block-aligned prefix: the follower
    shares only the common FULL blocks and prefills its own tail."""
    from concurrent.futures import ThreadPoolExecutor

    cfg, params = tiny
    sys_prompt = [5, 9, 2, 7, 11, 3, 1, 4]               # 2 full blocks
    p1 = jnp.array(sys_prompt + [8, 6, 12], jnp.int32)
    p2 = jnp.array(sys_prompt + [2, 13], jnp.int32)
    want1 = np.asarray(generate(params, p1[None], cfg, 12))[0].tolist()
    want2 = np.asarray(generate(params, p2[None], cfg, 12))[0].tolist()
    b = _Batcher(cfg, params, slots=2, max_len=32, kv_block=4,
                 prefix_cache=0)
    ex = ThreadPoolExecutor(2)
    try:
        f1 = ex.submit(b.submit, p1, 12)
        f2 = ex.submit(b.submit, p2, 12)
        got1, got2 = f1.result(timeout=120), f2.result(timeout=120)
    finally:
        b.close()
        ex.shutdown(wait=True)
    assert got1 == want1 and got2 == want2
    # sharing direction depends on thread arrival order; either way one
    # follower shared the 2-block system prefix
    assert b.prefix_hits == 1
    assert b._alloc.free_blocks == b.kv_pool_blocks - 1   # no leaks
