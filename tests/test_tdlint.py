"""tdlint self-tests: every rule is proven LIVE against a seeded-violation
fixture (tests/lint_fixtures/) and SILENT on its clean twin; the pragma
machinery (all three placements, used-counting, stale detection) and the
repo gate (`make lint` must exit 0 on the tree as committed) are covered
here too. Runs in the default tier and from `make lint` itself, so a rule
that rots into never-firing fails the build that relies on it."""

import os
import subprocess
import sys

import pytest

from tools import tdlint
from tools.tdlint import lint_paths, run as lint_run

FIXTURES = os.path.join(os.path.dirname(__file__), "lint_fixtures")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint(names, rules):
    paths = [os.path.join(FIXTURES, n) for n in names]
    rep = lint_paths(paths, FIXTURES, rules=rules)
    return rep["violations"]


# ------------------------------------------------------- rule liveness

def test_unlocked_state_fires_and_clean_twin_silent():
    vs = _lint(["unlocked_state_bad.py"], ["unlocked-state"])
    assert len(vs) == 3
    assert {v.rule for v in vs} == {"unlocked-state"}
    assert "mutation of guarded state '.status'" in vs[0].message
    assert "raw access to another object's guarded state" in vs[1].message
    # holding your OWN lock must not exempt reads of ANOTHER object's
    # guarded state (the pre-fix health.py probe pattern)
    assert "raw access to another object's guarded state" in vs[2].message
    assert _lint(["unlocked_state_ok.py"], ["unlocked-state"]) == []


def test_intent_lifecycle_fires_and_clean_twin_silent():
    vs = _lint(["intent_lifecycle_bad.py"], ["intent-lifecycle"])
    assert len(vs) == 1
    assert vs[0].rule == "intent-lifecycle"
    assert "no done() on an exception handler" in vs[0].message
    assert _lint(["intent_lifecycle_ok.py"], ["intent-lifecycle"]) == []


def test_unknown_step_fires_and_clean_twin_silent():
    vs = _lint(["unknown_step_bad.py", "registry.py"], ["unknown-step"])
    assert len(vs) == 2
    msgs = " | ".join(v.message for v in vs)
    assert "'warped' is not in the reconciler's step registry" in msgs
    assert "'container.teleport' has no handler" in msgs
    assert _lint(["unknown_step_ok.py", "registry.py"],
                 ["unknown-step"]) == []


def test_io_under_lock_fires_and_clean_twin_silent():
    vs = _lint(["io_under_lock_bad.py"], ["io-under-lock"])
    assert len(vs) == 1
    assert "backend op '.backend.stop()' while holding a lock" \
        in vs[0].message
    assert _lint(["io_under_lock_ok.py"], ["io-under-lock"]) == []


def test_unmapped_xerror_fires_and_clean_twin_silent():
    vs = _lint([os.path.join("api_bad", "xerrors.py"),
                os.path.join("api_bad", "app.py")], ["unmapped-xerror"])
    assert len(vs) == 1
    assert "OrphanedError is never caught" in vs[0].message
    assert _lint([os.path.join("api_ok", "xerrors.py"),
                  os.path.join("api_ok", "app.py")],
                 ["unmapped-xerror"]) == []


def test_silent_swallow_fires_and_clean_twin_silent():
    vs = _lint(["silent_swallow_bad.py"], ["silent-swallow"])
    assert len(vs) == 1
    assert "swallows the failure silently" in vs[0].message
    assert _lint(["silent_swallow_ok.py"], ["silent-swallow"]) == []


def test_untraced_op_fires_and_clean_twin_silent():
    vs = _lint(["untraced_op_bad.py", "names_catalog.py"], ["untraced-op"])
    assert len(vs) == 5
    msgs = " | ".join(v.message for v in vs)
    assert "'container.teleported' is not registered" in msgs
    assert "'rogue.drop' is not registered" in msgs
    assert "'rogue.keyword' is not registered" in msgs
    assert "'tdapi_teleports_total' is not registered" in msgs
    assert "'tdapi_rogue_kw_total' is not registered" in msgs
    # non-tdapi counter names on unrelated APIs are not ours to police
    assert "widget_spins" not in msgs
    assert _lint(["untraced_op_ok.py", "names_catalog.py"],
                 ["untraced-op"]) == []


def test_seqlock_discipline_fires_and_clean_twin_silent():
    vs = _lint(["seqlock_discipline_bad.py"], ["seqlock-discipline"])
    assert len(vs) == 3
    msgs = " | ".join(v.message for v in vs)
    assert "store write '.client.put()' inside the seqlock publish" in msgs
    assert "time.sleep() inside the seqlock publish window" in msgs
    assert "logging call 'log.warning()'" in msgs
    assert _lint(["seqlock_discipline_ok.py"],
                 ["seqlock-discipline"]) == []


def test_claim_order_fires_and_clean_twin_silent():
    vs = _lint(["claim_order_bad.py"], ["claim-order"])
    assert len(vs) == 3
    msgs = " | ".join(v.message for v in vs)
    assert "no earlier global fetch_add" in msgs
    assert "no later global release" in msgs
    assert _lint(["claim_order_ok.py"], ["claim-order"]) == []


def test_atomic_region_fires_and_clean_twin_silent():
    vs = _lint(["atomic_region_bad.py"], ["atomic-region"])
    assert len(vs) == 3
    msgs = " | ".join(v.message for v in vs)
    assert "struct.pack_into targeting a counter-region offset" in msgs
    assert "raw buffer slice assignment into the counter region" in msgs
    assert _lint(["atomic_region_ok.py"], ["atomic-region"]) == []


def test_shm_rules_scoped_to_shm_modules_only():
    """The shm rules reason about the two shm-segment modules' layout
    discipline; other scoped files must not be walked by them (their
    helper names could collide). claim-order stays workers.py-only —
    the claim ledger does not exist in the metric shards."""
    from tools.tdlint.rules import AtomicRegion, ClaimOrder, \
        SeqlockDiscipline
    for rule in (SeqlockDiscipline(), ClaimOrder(), AtomicRegion()):
        assert rule.applies("gpu_docker_api_tpu/server/workers.py")
        assert not rule.applies("gpu_docker_api_tpu/gateway.py")
        assert not rule.applies("gpu_docker_api_tpu/store/mvcc.py")
    for rule in (SeqlockDiscipline(), AtomicRegion()):
        assert rule.applies("gpu_docker_api_tpu/obs/shm_metrics.py")
    assert not ClaimOrder().applies("gpu_docker_api_tpu/obs/shm_metrics.py")


def test_seqlock_discipline_shm_shard_fires_and_clean_twin_silent():
    """The metric-shard extension: spool write/flush and recorder-ring
    appends inside a shard epoch window (closed via _sh_epoch_off) fire;
    the atomics-only reset with spooling outside the window is silent."""
    vs = _lint(["seqlock_discipline_shm_bad.py"], ["seqlock-discipline"])
    assert len(vs) == 3
    msgs = " | ".join(v.message for v in vs)
    assert "spool/file I/O '.write()'" in msgs
    assert "spool/file I/O '.flush()'" in msgs
    assert "recorder ring write '.ring_note()'" in msgs
    assert _lint(["seqlock_discipline_shm_ok.py"],
                 ["seqlock-discipline"]) == []


def test_atomic_region_shm_shard_fires_and_clean_twin_silent():
    """The metric-shard extension: raw pack_into / slice writes into
    _sh_* counter-region offsets fire; atomic-op writes and raw writes
    into the recorder-ring payload region (helper outside the counter
    set by design) are silent."""
    vs = _lint(["atomic_region_shm_bad.py"], ["atomic-region"])
    assert len(vs) == 2
    msgs = " | ".join(v.message for v in vs)
    assert "struct.pack_into targeting a counter-region offset" in msgs
    assert "raw buffer slice assignment into the counter region" in msgs
    assert _lint(["atomic_region_shm_ok.py"], ["atomic-region"]) == []


def test_atomic_region_lat_digest_fires_and_clean_twin_silent():
    """The PR 19 latency-digest extension: raw pack_into / slice writes
    into _rep_lat_off cell groups fire; the CAS publish/read entry
    points (the only legitimate access path) are silent."""
    vs = _lint(["atomic_region_lat_bad.py"], ["atomic-region"])
    assert len(vs) == 2
    msgs = " | ".join(v.message for v in vs)
    assert "struct.pack_into targeting a counter-region offset" in msgs
    assert "raw buffer slice assignment into the counter region" in msgs
    assert _lint(["atomic_region_lat_ok.py"], ["atomic-region"]) == []


def test_claim_order_ignores_non_inflight_cells():
    """`_rep_cnt_off(...) + 8` is the errors cell, not the inflight
    claim — arithmetic on a helper must not be classified as the global
    claim op (a false 'earlier fetch_add' would mask real reversals)."""
    import textwrap
    import tempfile
    src = textwrap.dedent("""\
        def forward(self, st, g, r):
            st.add(_rep_cnt_off(g, r) + 8, 1)      # errors cell only
            st.add(_wk_claim_off(0, g, r), 1)      # ledger with NO claim
    """)
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "errors_cell.py")
        with open(p, "w") as f:
            f.write(src)
        vs = lint_paths([p], d, rules=["claim-order"])["violations"]
    assert len(vs) == 1
    assert "no earlier global fetch_add" in vs[0].message


def test_untraced_op_without_catalog_is_silent():
    """A file set with no EVENT_OPS/METRIC_NAMES assignment (fixture runs
    of OTHER rules) must not fail — there is no catalog to check against."""
    assert _lint(["untraced_op_bad.py"], ["untraced-op"]) == []


# ------------------------------------------------------------- pragmas

def test_pragma_all_three_placements_honored_and_counted():
    rep = lint_run(FIXTURES, scope=("pragma_usage.py",),
                   rules=["unlocked-state"])
    assert rep["violations"] == []
    assert rep["pragmas"]["total"] == 3
    assert rep["pragmas"]["used"] == 3
    assert rep["pragmas"]["stale"] == []


def test_stale_pragma_reported(tmp_path):
    f = tmp_path / "stale.py"
    f.write_text("# tdlint: disable=unlocked-state -- suppresses nothing\n"
                 "X = 1\n")
    rep = lint_run(str(tmp_path), scope=("stale.py",),
                   rules=["unlocked-state"])
    assert rep["violations"] == []
    assert rep["pragmas"]["total"] == 1
    assert rep["pragmas"]["used"] == 0
    assert rep["pragmas"]["stale"] == [("stale.py", 1, ["unlocked-state"])]


def test_rules_subset_does_not_mark_other_pragmas_stale():
    """`--rules silent-swallow` must not call the unlocked-state pragmas
    in pragma_usage.py stale — their rule never ran this invocation."""
    rep = lint_run(FIXTURES, scope=("pragma_usage.py",),
                   rules=["silent-swallow"])
    assert rep["pragmas"]["stale"] == []
    assert rep["pragmas"]["used"] == 0


def test_misspelled_pragma_rule_always_reported(tmp_path):
    f = tmp_path / "typo.py"
    f.write_text("# tdlint: disable=unlockd-state -- typo'd rule name\n"
                 "X = 1\n")
    rep = lint_run(str(tmp_path), scope=("typo.py",),
                   rules=["silent-swallow"])
    assert rep["pragmas"]["stale"] == [("typo.py", 1, ["unlockd-state"])]


def test_io_under_lock_context_expr_ordering(tmp_path):
    """`with open(p) as f, self._lock:` runs the open BEFORE the lock is
    taken — no violation; the reversed order IS one."""
    ok = tmp_path / "open_then_lock.py"
    ok.write_text("def f(self, p):\n"
                  "    with open(p) as fh, self._lock:\n"
                  "        self.x = fh.read()\n")
    bad = tmp_path / "lock_then_open.py"
    bad.write_text("def f(self, p):\n"
                   "    with self._lock, open(p) as fh:\n"
                   "        self.x = fh.read()\n")
    assert lint_paths([str(ok)], str(tmp_path),
                      rules=["io-under-lock"])["violations"] == []
    vs = lint_paths([str(bad)], str(tmp_path),
                    rules=["io-under-lock"])["violations"]
    assert len(vs) == 1 and "open() while holding a lock" in vs[0].message


def test_pragma_does_not_suppress_other_rules(tmp_path):
    f = tmp_path / "wrong_rule.py"
    f.write_text(
        "def f(backend):\n"
        "    try:\n"
        "        backend.remove('x')\n"
        "    # tdlint: disable=unlocked-state -- wrong rule name\n"
        "    except Exception:\n"
        "        pass\n")
    rep = lint_paths([str(f)], str(tmp_path), rules=["silent-swallow"])
    assert len(rep["violations"]) == 1


def test_stale_strict_cli_fails_on_stale_pragma(tmp_path):
    """`make lint` runs --stale-strict: a pragma whose rule no longer
    fires must FAIL the build, not warn — the stated contract it
    documents no longer matches the code."""
    from tools.tdlint.__main__ import main as tdlint_main
    pkg = tmp_path / "gpu_docker_api_tpu"
    pkg.mkdir()
    (pkg / "health.py").write_text(
        "# tdlint: disable=unlocked-state -- contract long gone\n"
        "X = 1\n")
    assert tdlint_main(["--root", str(tmp_path)]) == 0
    assert tdlint_main(["--root", str(tmp_path), "--stale-strict"]) == 1


# ------------------------------------------------------------ repo gate

def test_unknown_rule_name_rejected():
    with pytest.raises(ValueError, match="unknown rule"):
        from tools.tdlint.rules import all_rules
        all_rules(["no-such-rule"])


def test_repo_lints_clean_via_cli():
    """The committed tree must pass its own linter — the same invocation
    `make lint` runs, minus compileall."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.tdlint", "--root", REPO],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 violation(s)" in proc.stdout


def test_repo_scope_covers_the_concurrent_core():
    ctxs = tdlint.collect_files(REPO)
    rels = {c.rel for c in ctxs}
    for must in ("gpu_docker_api_tpu/schedulers/tpu.py",
                 "gpu_docker_api_tpu/store/mvcc.py",
                 "gpu_docker_api_tpu/services/replicaset.py",
                 "gpu_docker_api_tpu/reconcile.py",
                 "gpu_docker_api_tpu/regulator.py",
                 "gpu_docker_api_tpu/server/app.py",
                 "gpu_docker_api_tpu/obs/names.py",
                 "gpu_docker_api_tpu/obs/trace.py"):
        assert must in rels


def test_live_catalog_matches_emitters():
    """The untraced-op rule reads the REAL obs/names.py when linting the
    repo — a renamed event op or metric family that is still emitted
    under the old name must fail the build. Spot-check that the catalog
    carries both sides' anchor entries."""
    from gpu_docker_api_tpu.obs import names
    assert "replace.copied" in names.EVENT_OPS
    assert "workqueue.drop" in names.EVENT_OPS
    assert "tdapi_http_request_duration_ms" in names.METRIC_NAMES
    assert "tdapi_tpu_chips" in names.METRIC_NAMES
    # every catalogued family is a tdapi_* family — the rule's prefix
    # filter must never skip a catalogued name
    assert all(m.startswith("tdapi_") for m in names.METRIC_NAMES)


def test_live_registry_matches_reconciler():
    """The unknown-step rule reads the REAL reconciler's registry when
    linting the repo — a step written by services but missing from
    reconcile.KNOWN_STEPS must fail the build, not silently pass."""
    from gpu_docker_api_tpu import reconcile
    assert "created" in reconcile.CONSULTED_STEPS
    assert "precopied" in reconcile.INFORMATIONAL_STEPS
    assert reconcile.KNOWN_STEPS == (
        reconcile.CONSULTED_STEPS | reconcile.INFORMATIONAL_STEPS)
