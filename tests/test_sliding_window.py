"""Sliding-window attention: reference/flash/cached-decode agreement."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gpu_docker_api_tpu.infer import generate
from gpu_docker_api_tpu.models.llama import (
    LlamaConfig, init_params, llama_forward,
)

# slow tier: long-compile / multi-process e2e — quick CI runs
# -m 'not slow' (<3 min); the full suite stays the default
pytestmark = pytest.mark.slow
from gpu_docker_api_tpu.ops.attention import (
    flash_attention, reference_attention,
)


def qkv(key, b=2, s=256, h=4, hkv=2, d=128, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    return (jax.random.normal(ks[0], (b, s, h, d), dtype),
            jax.random.normal(ks[1], (b, s, hkv, d), dtype),
            jax.random.normal(ks[2], (b, s, hkv, d), dtype))


def test_reference_window_masks_correctly():
    """Row r must ignore keys <= r - window: moving an out-of-window key
    changes nothing; moving an in-window key does."""
    q, k, v = qkv(jax.random.key(0), s=8, d=16)
    w = reference_attention(q, k, v, causal=True, window=4)
    k2 = k.at[:, 0].set(99.0)                    # key 0: outside row 7's window
    w2 = reference_attention(q, k2, v, causal=True, window=4)
    np.testing.assert_allclose(np.asarray(w[:, 7]), np.asarray(w2[:, 7]),
                               rtol=1e-6)
    assert not np.allclose(np.asarray(w[:, 3]), np.asarray(w2[:, 3]))


def test_window_ge_seq_equals_full_causal():
    q, k, v = qkv(jax.random.key(1), s=64, d=32)
    full = reference_attention(q, k, v, causal=True)
    win = reference_attention(q, k, v, causal=True, window=64)
    np.testing.assert_allclose(np.asarray(win), np.asarray(full), rtol=1e-6)


@pytest.mark.parametrize("window", [64, 128, 200])
def test_flash_window_matches_reference(window):
    q, k, v = qkv(jax.random.key(2))
    want = reference_attention(q, k, v, causal=True, window=window)
    got = flash_attention(q, k, v, causal=True, window=window,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_window_gradients_match_reference():
    q, k, v = qkv(jax.random.key(3), b=1, s=256)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True,
                                           window=96) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, window=96,
                                       interpret=True) ** 2)

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gf):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=2e-4)


def test_windowed_model_generate_matches_full_forward_oracle():
    """The cached decode path (blockwise attend with window + skipped dead
    blocks) must reproduce the un-cached windowed forward's greedy stream."""
    cfg = dataclasses.replace(LlamaConfig.tiny(), sliding_window=6)
    params = init_params(cfg, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(4), (2, 10), 0,
                                cfg.vocab_size, jnp.int32)
    got = np.asarray(generate(params, prompt, cfg, max_new=8))

    seq = prompt
    want = []
    for _ in range(8):
        logits = llama_forward(params, seq, cfg)          # windowed full fwd
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        want.append(np.asarray(nxt))
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, np.stack(want, axis=1))


def test_windowed_training_step_runs():
    from gpu_docker_api_tpu.parallel.mesh import MeshPlan
    from gpu_docker_api_tpu.train import Trainer, TrainConfig

    cfg = dataclasses.replace(LlamaConfig.tiny(), sliding_window=8)
    tr = Trainer.create(cfg, MeshPlan(dp=2, fsdp=2, tp=2, sp=1),
                        tc=TrainConfig(remat=True))
    st = tr.init(jax.random.key(0))
    toks = tr.shard_batch(jax.random.randint(jax.random.key(5), (4, 32), 0,
                                             cfg.vocab_size, jnp.int32))
    st, m = tr.step(st, toks)
    assert np.isfinite(float(m["loss"]))


def test_window_with_sp_raises():
    from gpu_docker_api_tpu.parallel.mesh import MeshPlan
    from gpu_docker_api_tpu.train import Trainer, TrainConfig

    cfg = dataclasses.replace(LlamaConfig.tiny(), sliding_window=8)
    tr = Trainer.create(cfg, MeshPlan(dp=1, fsdp=2, tp=2, sp=2),
                        tc=TrainConfig(remat=False))
    st = tr.init(jax.random.key(0))
    toks = tr.shard_batch(jax.random.randint(jax.random.key(6), (4, 32), 0,
                                             cfg.vocab_size, jnp.int32))
    with pytest.raises(NotImplementedError, match="sliding_window"):
        tr.step(st, toks)


def test_mistral_7b_canned_config():
    """Resolves from the registry; windowed; shapes check out abstractly
    (no 7B init on CPU — eval_shape only)."""
    from gpu_docker_api_tpu.models import named_config, family_for

    cfg = named_config("llama", "mistral_7b")
    assert cfg.sliding_window == 4096
    assert cfg.n_kv_heads == 8 and cfg.d_ff == 14336
    shapes = jax.eval_shape(
        lambda: family_for(cfg).init_params(cfg, jax.random.key(0)))
    n = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(shapes))
    assert 7.0e9 < n < 7.6e9          # ~7.24B params
