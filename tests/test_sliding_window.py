"""Sliding-window attention: reference/flash/cached-decode agreement."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gpu_docker_api_tpu.infer import generate
from gpu_docker_api_tpu.models.llama import (
    LlamaConfig, init_params, llama_forward,
)

# slow tier: long-compile / multi-process e2e — quick CI runs
# -m 'not slow' (<3 min); the full suite stays the default
pytestmark = pytest.mark.slow
from gpu_docker_api_tpu.ops.attention import (
    flash_attention, reference_attention,
)


def qkv(key, b=2, s=256, h=4, hkv=2, d=128, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    return (jax.random.normal(ks[0], (b, s, h, d), dtype),
            jax.random.normal(ks[1], (b, s, hkv, d), dtype),
            jax.random.normal(ks[2], (b, s, hkv, d), dtype))


def test_reference_window_masks_correctly():
    """Row r must ignore keys <= r - window: moving an out-of-window key
    changes nothing; moving an in-window key does."""
    q, k, v = qkv(jax.random.key(0), s=8, d=16)
    w = reference_attention(q, k, v, causal=True, window=4)
    k2 = k.at[:, 0].set(99.0)                    # key 0: outside row 7's window
    w2 = reference_attention(q, k2, v, causal=True, window=4)
    np.testing.assert_allclose(np.asarray(w[:, 7]), np.asarray(w2[:, 7]),
                               rtol=1e-6)
    assert not np.allclose(np.asarray(w[:, 3]), np.asarray(w2[:, 3]))


def test_window_ge_seq_equals_full_causal():
    q, k, v = qkv(jax.random.key(1), s=64, d=32)
    full = reference_attention(q, k, v, causal=True)
    win = reference_attention(q, k, v, causal=True, window=64)
    np.testing.assert_allclose(np.asarray(win), np.asarray(full), rtol=1e-6)


@pytest.mark.parametrize("window", [64, 128, 200])
def test_flash_window_matches_reference(window):
    q, k, v = qkv(jax.random.key(2))
    want = reference_attention(q, k, v, causal=True, window=window)
    got = flash_attention(q, k, v, causal=True, window=window,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_window_gradients_match_reference():
    q, k, v = qkv(jax.random.key(3), b=1, s=256)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True,
                                           window=96) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, window=96,
                                       interpret=True) ** 2)

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gf):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=2e-4)


def test_windowed_model_generate_matches_full_forward_oracle():
    """The cached decode path (blockwise attend with window + skipped dead
    blocks) must reproduce the un-cached windowed forward's greedy stream."""
    cfg = dataclasses.replace(LlamaConfig.tiny(), sliding_window=6)
    params = init_params(cfg, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(4), (2, 10), 0,
                                cfg.vocab_size, jnp.int32)
    got = np.asarray(generate(params, prompt, cfg, max_new=8))

    seq = prompt
    want = []
    for _ in range(8):
        logits = llama_forward(params, seq, cfg)          # windowed full fwd
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        want.append(np.asarray(nxt))
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, np.stack(want, axis=1))


def test_windowed_training_step_runs():
    from gpu_docker_api_tpu.parallel.mesh import MeshPlan
    from gpu_docker_api_tpu.train import Trainer, TrainConfig

    cfg = dataclasses.replace(LlamaConfig.tiny(), sliding_window=8)
    tr = Trainer.create(cfg, MeshPlan(dp=2, fsdp=2, tp=2, sp=1),
                        tc=TrainConfig(remat=True))
    st = tr.init(jax.random.key(0))
    toks = tr.shard_batch(jax.random.randint(jax.random.key(5), (4, 32), 0,
                                             cfg.vocab_size, jnp.int32))
    st, m = tr.step(st, toks)
    assert np.isfinite(float(m["loss"]))


# ---- SWA x sequence parallelism (VERDICT r2 hole #3) -----------------------

def test_windowed_ring_matches_reference():
    """Ring attention with a window == full-sequence windowed reference,
    including GQA, on the 8-device CPU mesh."""
    from gpu_docker_api_tpu.parallel.mesh import MeshPlan, make_mesh
    from gpu_docker_api_tpu.parallel.ring import ring_attention

    mesh = make_mesh(MeshPlan(dp=1, fsdp=1, tp=1, sp=4),
                     jax.devices()[:4])
    q, k, v = qkv(jax.random.key(3), b=2, s=64, h=4, hkv=2, d=16)
    for window in (5, 16, 40, 64):
        with mesh:
            got = ring_attention(q, k, v, mesh, causal=True, impl="xla",
                                 window=window)
        want = reference_attention(q, k, v, causal=True, window=window)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)


def test_windowed_ring_flash_kernels_match_reference():
    """The flash path (windowed pallas diagonal + banded einsum behind
    shards, interpreter mode) agrees with the reference too."""
    from gpu_docker_api_tpu.parallel.mesh import MeshPlan, make_mesh
    from gpu_docker_api_tpu.parallel.ring import ring_attention

    mesh = make_mesh(MeshPlan(dp=1, fsdp=1, tp=1, sp=2),
                     jax.devices()[:2])
    q, k, v = qkv(jax.random.key(4), b=1, s=256, h=2, hkv=2, d=128,
                  dtype=jnp.float32)
    with mesh:
        got = ring_attention(q, k, v, mesh, causal=True, impl="flash",
                             window=100)
    want = reference_attention(q, k, v, causal=True, window=100)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


def test_windowed_ring_gradients_match_reference():
    from gpu_docker_api_tpu.parallel.mesh import MeshPlan, make_mesh
    from gpu_docker_api_tpu.parallel.ring import ring_attention

    mesh = make_mesh(MeshPlan(dp=1, fsdp=1, tp=1, sp=4),
                     jax.devices()[:4])
    q, k, v = qkv(jax.random.key(5), b=1, s=32, h=2, hkv=2, d=8)

    def loss_ring(q, k, v):
        with mesh:
            o = ring_attention(q, k, v, mesh, causal=True, impl="xla",
                               window=10)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        o = reference_attention(q, k, v, causal=True, window=10)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_windowed_ring_skips_out_of_window_rotations():
    """THE payoff: K/V shards wholly outside the window are never
    rotated in — the compiled HLO has fewer collective-permutes than the
    full causal ring (which pays ring-1 hops)."""
    from gpu_docker_api_tpu.parallel.mesh import MeshPlan, make_mesh
    from gpu_docker_api_tpu.parallel.ring import ring_attention

    mesh = make_mesh(MeshPlan(dp=1, fsdp=1, tp=1, sp=8))
    q, k, v = qkv(jax.random.key(6), b=1, s=64, h=2, hkv=2, d=8)

    def count_permutes(window):
        # impl="flash": both the windowed and the full-causal flash
        # bodies UNROLL their hop loop, so the compiled HLO's
        # collective-permute count equals 2 x hops (k and v) — an exact
        # communication-shape assertion (the einsum body hides its hops
        # in a fori_loop, where text counts can't see the trip count)
        def f(q, k, v):
            with mesh:
                return ring_attention(q, k, v, mesh, causal=True,
                                      impl="flash", window=window)
        txt = jax.jit(f).lower(q, k, v).compile().as_text()
        return txt.count(" collective-permute(")

    # s_loc = 8: window=8 sees at most 1 shard back -> 1 hop (2 permutes);
    # the full causal ring rotates ring-1 = 7 times (14 permutes)
    assert count_permutes(8) == 2
    assert count_permutes(0) == 14


def test_windowed_forward_under_sp_matches_single_device():
    """llama_forward with sliding_window on an sp mesh == the same model
    on one device (the guard this replaces used to raise here)."""
    from gpu_docker_api_tpu.parallel.mesh import MeshPlan, make_mesh

    cfg = dataclasses.replace(LlamaConfig.tiny(), sliding_window=8)
    params = init_params(cfg, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(6), (2, 32), 0,
                              cfg.vocab_size, jnp.int32)
    want = llama_forward(params, toks, cfg, impl="xla")
    mesh = make_mesh(MeshPlan(dp=1, fsdp=1, tp=1, sp=4),
                     jax.devices()[:4])
    got = llama_forward(params, toks, cfg, impl="xla", mesh=mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=3e-4, atol=3e-4)


def test_windowed_ulysses_matches_single_device():
    from gpu_docker_api_tpu.parallel.mesh import MeshPlan, make_mesh

    cfg = dataclasses.replace(LlamaConfig.tiny(), sliding_window=8,
                              sp_attn="ulysses")
    params = init_params(cfg, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(7), (2, 32), 0,
                              cfg.vocab_size, jnp.int32)
    want = llama_forward(params, toks, cfg, impl="xla")
    mesh = make_mesh(MeshPlan(dp=1, fsdp=1, tp=1, sp=2),
                     jax.devices()[:2])
    got = llama_forward(params, toks, cfg, impl="xla", mesh=mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=3e-4, atol=3e-4)


def test_windowed_training_step_under_sp_mesh():
    """End-to-end: a windowed model TRAINS on a pp-free sp mesh (the
    combination the r2 guard refused); loss finite and decreasing-ish."""
    from gpu_docker_api_tpu.parallel.mesh import MeshPlan
    from gpu_docker_api_tpu.train import Trainer, TrainConfig

    cfg = dataclasses.replace(LlamaConfig.tiny(), sliding_window=8)
    tr = Trainer.create(cfg, MeshPlan(dp=1, fsdp=2, tp=2, sp=2),
                        tc=TrainConfig(remat=False))
    st = tr.init(jax.random.key(0))
    toks = tr.shard_batch(jax.random.randint(jax.random.key(6), (4, 32), 0,
                                             cfg.vocab_size, jnp.int32))
    losses = []
    for _ in range(4):
        st, metrics = tr.step(st, toks)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_windowed_pipeline_sp_loss_matches_unsharded():
    """pp x sp with a windowed config: pipelined loss == plain loss."""
    from gpu_docker_api_tpu.parallel.mesh import MeshPlan, make_mesh
    from gpu_docker_api_tpu.train import loss_fn

    cfg = dataclasses.replace(LlamaConfig.tiny(), n_layers=4,
                              sliding_window=8)
    params = init_params(cfg, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(8), (4, 32), 0,
                              cfg.vocab_size, jnp.int32)
    want = float(loss_fn(params, toks, cfg, impl="xla", remat=False))
    mesh = make_mesh(MeshPlan(pp=2, sp=2, tp=2))
    with mesh:
        got = float(jax.jit(lambda p, t: loss_fn(
            p, t, cfg, impl="xla", mesh=mesh, n_microbatches=2,
            remat=False))(params, toks))
    np.testing.assert_allclose(got, want, rtol=5e-4)


def test_mistral_7b_canned_config():
    """Resolves from the registry; windowed; shapes check out abstractly
    (no 7B init on CPU — eval_shape only)."""
    from gpu_docker_api_tpu.models import named_config, family_for

    cfg = named_config("llama", "mistral_7b")
    assert cfg.sliding_window == 4096
    assert cfg.n_kv_heads == 8 and cfg.d_ff == 14336
    shapes = jax.eval_shape(
        lambda: family_for(cfg).init_params(cfg, jax.random.key(0)))
    n = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(shapes))
    assert 7.0e9 < n < 7.6e9          # ~7.24B params
