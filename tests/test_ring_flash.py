"""Flash-speed ring attention: flash_attention_lse (differentiable in out
AND lse), partial merging, and the ring body built on them."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gpu_docker_api_tpu.ops.attention import (
    flash_attention_lse, merge_attention_partials, reference_attention,
)

# slow tier: long-compile / multi-process e2e — quick CI runs
# -m 'not slow' (<3 min); the full suite stays the default
pytestmark = pytest.mark.slow
from gpu_docker_api_tpu.parallel.mesh import MeshPlan, make_mesh
from gpu_docker_api_tpu.parallel.ring import (
    _ring_local_flash, ring_attention,
)


def _ref_lse(q, k, v, causal):
    """Oracle logsumexp of the SCALED scores, [B, H, S]."""
    import math
    b, s, h, d = q.shape
    group = h // k.shape[2]
    kf = jnp.repeat(k.astype(jnp.float32), group, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk",
                        q.astype(jnp.float32) / math.sqrt(d), kf)
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, (s, s), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (s, s), 1)
        scores = jnp.where((cols <= rows)[None, None], scores, -jnp.inf)
    return jax.scipy.special.logsumexp(scores, axis=-1)


def qkv(key, b=1, s=256, h=4, hkv=2, d=128):
    ks = jax.random.split(key, 3)
    return (jax.random.normal(ks[0], (b, s, h, d), jnp.float32),
            jax.random.normal(ks[1], (b, s, hkv, d), jnp.float32),
            jax.random.normal(ks[2], (b, s, hkv, d), jnp.float32))


@pytest.mark.parametrize("causal", [True, False])
def test_flash_lse_values(causal):
    q, k, v = qkv(jax.random.key(0))
    out, lse = flash_attention_lse(q, k, v, causal=causal, interpret=True)
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(lse),
                               np.asarray(_ref_lse(q, k, v, causal)),
                               rtol=1e-4, atol=1e-4)


def test_flash_lse_grads_through_both_outputs():
    """The merge differentiates through lse, so the vjp must handle BOTH
    cotangents — compare against an einsum oracle of the same function."""
    q, k, v = qkv(jax.random.key(1))

    def loss_flash(q, k, v):
        out, lse = flash_attention_lse(q, k, v, causal=True,
                                       interpret=True)
        return jnp.sum(out.astype(jnp.float32) ** 2) + jnp.sum(
            jnp.sin(lse))

    def loss_ref(q, k, v):
        out = reference_attention(q, k, v, causal=True)
        lse = _ref_lse(q, k, v, True)
        return jnp.sum(out.astype(jnp.float32) ** 2) + jnp.sum(
            jnp.sin(lse))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4)


def test_merge_partials_equals_joint():
    """Splitting the key set and merging the partials must equal attention
    over the union (non-causal so both halves are visible). Each partial's
    KV length equals the q length (the kernel's contract — exactly the
    ring situation: equal shard sizes)."""
    q, _, _ = qkv(jax.random.key(2), s=256)
    ks = jax.random.split(jax.random.key(12), 4)
    k1 = jax.random.normal(ks[0], (1, 256, 2, 128), jnp.float32)
    k2 = jax.random.normal(ks[1], (1, 256, 2, 128), jnp.float32)
    v1 = jax.random.normal(ks[2], (1, 256, 2, 128), jnp.float32)
    v2 = jax.random.normal(ks[3], (1, 256, 2, 128), jnp.float32)
    o1, l1 = flash_attention_lse(q, k1, v1, causal=False, interpret=True)
    o2, l2 = flash_attention_lse(q, k2, v2, causal=False, interpret=True)
    got = merge_attention_partials([o1, o2], [l1, l2])
    want = reference_attention(
        q, jnp.concatenate([k1, k2], axis=1),
        jnp.concatenate([v1, v2], axis=1), causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_flash_matches_reference(causal):
    mesh = make_mesh(MeshPlan(dp=1, fsdp=1, tp=2, sp=4))
    b, s, h, hkv, d = 2, 512, 4, 2, 128
    q = jax.random.normal(jax.random.key(3), (b, s, h, d), jnp.float32)
    k = jax.random.normal(jax.random.key(4), (b, s, hkv, d), jnp.float32)
    v = jax.random.normal(jax.random.key(5), (b, s, hkv, d), jnp.float32)
    ref = reference_attention(q, k, v, causal=causal)

    from gpu_docker_api_tpu.parallel.mesh import qkv_spec
    local = functools.partial(_ring_local_flash, axis="sp", ring=4,
                              causal=causal, interpret=True)
    spec = qkv_spec(mesh, h, hkv)
    with mesh:
        out = jax.shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                            out_specs=spec, check_vma=False)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_ring_flash_gradients_match_reference_ring():
    """Training through the flash ring: grads vs the einsum ring body."""
    mesh = make_mesh(MeshPlan(dp=1, fsdp=1, tp=2, sp=4))
    b, s, h, d = 1, 512, 2, 128
    q = jax.random.normal(jax.random.key(6), (b, s, h, d), jnp.float32)
    k = jax.random.normal(jax.random.key(7), (b, s, h, d), jnp.float32)
    v = jax.random.normal(jax.random.key(8), (b, s, h, d), jnp.float32)

    from gpu_docker_api_tpu.parallel.mesh import qkv_spec
    spec = qkv_spec(mesh, h, h)

    def make_loss(body):
        def loss(q, k, v):
            with mesh:
                out = jax.shard_map(body, mesh=mesh,
                                    in_specs=(spec, spec, spec),
                                    out_specs=spec, check_vma=False)(q, k, v)
            return jnp.sum(out.astype(jnp.float32) ** 2)
        return loss

    from gpu_docker_api_tpu.parallel.ring import _ring_local
    flash_body = functools.partial(_ring_local_flash, axis="sp", ring=4,
                                   causal=True, interpret=True)
    ref_body = functools.partial(_ring_local, axis="sp", ring=4, causal=True)
    gf = jax.grad(make_loss(flash_body), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(make_loss(ref_body), argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=3e-4, atol=3e-4)


def test_ring_dispatch_flash_flag():
    """impl='flash' forces the flash body even off-TPU (interpret inside);
    the public entry still matches the reference."""
    mesh = make_mesh(MeshPlan(dp=1, fsdp=1, tp=2, sp=4))
    b, s, h, d = 1, 512, 2, 128
    q = jax.random.normal(jax.random.key(9), (b, s, h, d), jnp.float32)
    k = jax.random.normal(jax.random.key(10), (b, s, h, d), jnp.float32)
    v = jax.random.normal(jax.random.key(11), (b, s, h, d), jnp.float32)
    ref = reference_attention(q, k, v, causal=True)
    with mesh:
        out = ring_attention(q, k, v, mesh, causal=True, impl="flash")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)
