"""Process-substrate supervision: restart-policy enforcement and hard
storage quota (VERDICT r2 missing #1/#2).

The reference delegates both to dockerd — `RestartPolicy: unless-stopped`
(/root/reference/internal/services/replicaset.go:73-75) and overlay2-XFS
`size=` quotas (internal/services/volume.go:36-38,
replicaset.go:67-71). The host-process substrate supervises itself: a
daemon-side supervisor thread restarts crashed workloads with backoff,
and sized volumes are loop-mounted ext4 images giving real kernel ENOSPC
(falling back to the advisory service-layer guard where the host can't
mount, e.g. sandboxed CI)."""

import os
import signal
import time

import pytest

from conftest import wait_for
from gpu_docker_api_tpu.backend.process import (
    ProcessBackend, _quota_bytes,
)
from gpu_docker_api_tpu.dtos import ContainerSpec


@pytest.fixture()
def sup(tmp_path):
    b = ProcessBackend(str(tmp_path / "b"), supervise=True,
                       supervise_interval=0.05)
    yield b
    b.close()


def _start(b, name, shell, policy="unless-stopped", quota="30G"):
    spec = ContainerSpec(cmd=["sh", "-c", shell], restart_policy=policy,
                         rootfs_quota=quota)
    b.create(name, spec)
    b.start(name)
    return b.inspect(name)


def _runs(b, name):
    path = os.path.join(b.inspect(name).upper_dir, "runs.txt")
    if not os.path.exists(path):
        return 0
    return len(open(path).read().splitlines())


def test_crashed_container_is_restarted(sup):
    st = _start(sup, "c1", "echo run >> runs.txt; sleep 60")
    wait_for(lambda: _runs(sup, "c1") >= 1, msg="first run")
    os.kill(st.pid, signal.SIGKILL)                 # simulate a crash
    wait_for(lambda: _runs(sup, "c1") >= 2, msg="supervised restart")
    st2 = sup.inspect("c1")
    assert st2.running and st2.pid != st.pid
    # the restart is recorded in the container log
    log = sup._get("c1").log_path
    assert "supervisor: restarting" in open(log).read()


def test_exited_container_restarts_under_unless_stopped(sup):
    # docker semantics: unless-stopped restarts even a clean exit
    _start(sup, "c2", "echo run >> runs.txt; exit 0")
    wait_for(lambda: _runs(sup, "c2") >= 2, msg="restart after exit 0")


def test_explicit_stop_is_terminal(sup):
    _start(sup, "c3", "echo run >> runs.txt; sleep 60")
    wait_for(lambda: _runs(sup, "c3") >= 1, msg="first run")
    sup.stop("c3", timeout=5)
    time.sleep(1.0)                                 # > several poll ticks
    assert not sup.inspect("c3").running
    assert _runs(sup, "c3") == 1


def test_on_failure_policy_ignores_clean_exit(sup):
    _start(sup, "c4", "echo run >> runs.txt; exit 0", policy="on-failure")
    wait_for(lambda: _runs(sup, "c4") >= 1, msg="run")
    time.sleep(1.0)
    assert _runs(sup, "c4") == 1                    # rc 0: no restart
    _start(sup, "c5", "echo run >> runs.txt; exit 3", policy="on-failure")
    wait_for(lambda: _runs(sup, "c5") >= 2, msg="restart after failure")


def test_policy_no_never_restarts(sup):
    _start(sup, "c6", "echo run >> runs.txt; exit 1", policy="no")
    wait_for(lambda: _runs(sup, "c6") >= 1, msg="run")
    time.sleep(1.0)
    assert _runs(sup, "c6") == 1


def test_backoff_forgiveness_resets_restart_count(tmp_path):
    """process.py forgiveness window: a container healthy past
    forgive_after has its restart_count reset, so a much-later crash
    restarts promptly instead of inheriting an escalated backoff."""
    b = ProcessBackend(str(tmp_path / "b"), supervise=True,
                       supervise_interval=0.05, forgive_after=0.3)
    try:
        st = _start(b, "c1", "echo run >> runs.txt; sleep 60")
        wait_for(lambda: _runs(b, "c1") >= 1, msg="first run")
        os.kill(st.pid, signal.SIGKILL)
        wait_for(lambda: _runs(b, "c1") >= 2, msg="first restart")
        p = b._get("c1")
        assert p.restart_count >= 1
        # healthy past the window: the history is forgiven
        wait_for(lambda: p.restart_count == 0, timeout=5,
                 msg="backoff forgiveness")
        # the next crash starts from the minimum backoff, not 2^n
        st2 = b.inspect("c1")
        os.kill(st2.pid, signal.SIGKILL)
        t0 = time.time()
        wait_for(lambda: _runs(b, "c1") >= 3, timeout=5,
                 msg="prompt restart after forgiveness")
        assert time.time() - t0 < 3.0      # base delay is 0.25s, not 30s
        assert b._get("c1").restart_count == 1
    finally:
        b.close()


class _RacingProcs(dict):
    """Simulates remove() winning the race inside _supervise_one's locked
    re-check: the lookup succeeds but the proc's popen is already None."""

    def get(self, key, default=None):
        p = super().get(key, default)
        if p is not None:
            p.popen = None
        return p


def test_supervise_remove_race_guarded(tmp_path):
    """Regression (ISSUE satellite): inside the locked re-check, p.popen
    can be nulled by a concurrent remove(); the old code raised
    AttributeError there — eaten by the supervisor's blanket except, so
    the restart stayed silently pending forever."""
    b = ProcessBackend(str(tmp_path / "b"))      # no supervisor thread
    try:
        spec = ContainerSpec(cmd=["sh", "-c", "exit 1"],
                             restart_policy="always")
        b.create("c1", spec)
        b.start("c1")
        p = b._get("c1")
        p.popen.wait(timeout=10)
        b._supervise_one("c1", p)                # observes death
        assert p.restart_at > 0
        p.restart_at = time.time() - 1           # restart is due NOW
        b._procs = _RacingProcs(b._procs)        # remove() races the lock
        b._supervise_one("c1", p)                # must not raise or restart
        assert p.popen is None
        assert p.restart_count == 0
    finally:
        b.close()


def test_remove_nulls_popen_for_stale_handles(tmp_path):
    """remove() marks the proc dead for any supervisor tick still holding
    the old _Proc — the other half of the race fix."""
    b = ProcessBackend(str(tmp_path / "b"))
    try:
        b.create("c1", ContainerSpec(cmd=["sleep", "30"]))
        b.start("c1")
        p = b._get("c1")
        b.remove("c1", force=True)
        assert p.popen is None
        b._supervise_one("c1", p)                # stale tick: clean no-op
    finally:
        b.close()


def test_rootfs_quota_watchdog_kills_writer(sup):
    st = _start(sup, "c7",
                "dd if=/dev/zero of=big bs=1M count=5 2>/dev/null; sleep 60",
                quota="1MB")
    assert st.running
    wait_for(lambda: not sup.inspect("c7").running, timeout=15,
             msg="quota kill")
    log = open(sup._get("c7").log_path).read()
    assert "storage quota exceeded" in log
    # quota kill is terminal: the restart policy must not resurrect a
    # workload that will immediately breach again
    time.sleep(1.0)
    assert not sup.inspect("c7").running


def test_quota_bytes_accepts_docker_style_units():
    assert _quota_bytes("30G") == 30 * 1024 ** 3
    assert _quota_bytes("30GB") == 30 * 1024 ** 3
    assert _quota_bytes("512MB") == 512 * 1024 ** 2
    assert _quota_bytes("1T") == 1024 ** 4
    assert _quota_bytes("") == 0
    assert _quota_bytes("garbage") == 0


# ---- volume quota: loopback ENOSPC -----------------------------------------

def test_volume_quota_enospc(tmp_path):
    b = ProcessBackend(str(tmp_path / "b"))
    try:
        if not b._loopfs_capable():
            pytest.skip("host can't loop-mount (no CAP_SYS_ADMIN)")
        vs = b.volume_create("q1", size_bytes=16 << 20)
        assert vs.driver_opts["enforced"] is True
        assert os.path.ismount(vs.mountpoint)
        # writing past the quota hits a real kernel ENOSPC
        with pytest.raises(OSError) as ei:
            with open(os.path.join(vs.mountpoint, "big"), "wb") as f:
                chunk = b"\0" * (1 << 20)
                for _ in range(32):
                    f.write(chunk)
                    f.flush()
                    os.fsync(f.fileno())
        assert ei.value.errno == 28                 # ENOSPC
        st = b.volume_inspect("q1")
        assert st.size_limit_bytes == 16 << 20
        assert st.used_bytes > 0
        b.volume_remove("q1")
        assert not os.path.exists(vs.mountpoint)
        assert not os.path.exists(
            os.path.join(b._volimg_dir, "q1.img"))
    finally:
        b.close()


def test_volume_quota_fallback_is_advisory(tmp_path):
    """Where the host can't mount, sized volumes stay plain dirs and the
    quota is advisory (service-layer used-vs-limit guard) — documented,
    tested fallback."""
    b = ProcessBackend(str(tmp_path / "b"))
    try:
        b._loopfs = False                           # force the fallback
        vs = b.volume_create("q2", size_bytes=8 << 20)
        assert vs.driver_opts["enforced"] is False
        assert not os.path.ismount(vs.mountpoint)
        # advisory: the write succeeds; inspect still reports the limit
        with open(os.path.join(vs.mountpoint, "big"), "wb") as f:
            f.write(b"\0" * (12 << 20))
        st = b.volume_inspect("q2")
        assert st.size_limit_bytes == 8 << 20
        assert st.used_bytes >= 12 << 20
    finally:
        b.close()


def test_close_releases_and_restart_remounts(tmp_path):
    b = ProcessBackend(str(tmp_path / "b"))
    if not b._loopfs_capable():
        b.close()
        pytest.skip("host can't loop-mount")
    vs = b.volume_create("q3", size_bytes=16 << 20)
    assert os.path.ismount(vs.mountpoint)
    with open(os.path.join(vs.mountpoint, "ckpt"), "w") as f:
        f.write("step-42")
    b.close()
    assert not os.path.ismount(vs.mountpoint)
    # the image and data survive for a restarted daemon
    assert os.path.exists(os.path.join(b._volimg_dir, "q3.img"))
    # a new backend on the same state dir remounts: data visible again,
    # quota still kernel-enforced
    b2 = ProcessBackend(str(tmp_path / "b"))
    try:
        assert os.path.ismount(vs.mountpoint)
        assert open(os.path.join(vs.mountpoint, "ckpt")).read() == "step-42"
    finally:
        b2.close()


def test_volume_quota_below_loopfs_floor_stays_advisory(tmp_path):
    """A quota smaller than ext4 can enforce must not be reported as
    hard-enforced at a wrong limit."""
    b = ProcessBackend(str(tmp_path / "b"))
    try:
        vs = b.volume_create("q4", size_bytes=1 << 20)
        assert vs.driver_opts["enforced"] is False
        assert not os.path.ismount(vs.mountpoint)
        assert b.volume_inspect("q4").size_limit_bytes == 1 << 20
    finally:
        b.close()
