"""Differential engine parity: one randomized op sequence, two engines.

The python MVCCStore and the C++ core are interchangeable behind
StateClient, which means "same API" is not enough — revisions, version
counters, tombstone semantics, compaction floors, and the WAL bytes all
have to agree, or a daemon that restarts onto the other engine silently
corrupts history. This suite replays one seeded random sequence of
put / put_many / delete / compact / range / history / get_at against both
engines in lockstep, asserting identical observable state after every
op, then closes both and cross-replays each WAL in the OTHER engine.

Skips cleanly when the native core isn't built."""

from __future__ import annotations

import os
import random
import subprocess
import sys

import pytest

from gpu_docker_api_tpu.store import MVCCStore, native_available, open_store

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native core not built")

KEYS = [f"/par/{c}" for c in "abcdefgh"] + ["/other/x", "/par/nested/deep"]


def _observable(s):
    """Everything a client can see: live range + revision + per-key
    history shape."""
    return {
        "rev": s.revision,
        "range": [(kv.key, kv.value, kv.create_revision, kv.mod_revision,
                   kv.version) for kv in s.range("/")],
        "hist": {k: [(kv.value, kv.mod_revision, kv.version)
                     for kv in s.history(k)] for k in KEYS},
    }


def _apply(rng, s, op, args):
    if op == "put":
        return s.put(*args)
    if op == "put_many":
        return s.put_many(args)
    if op == "delete":
        return s.delete(args)
    if op == "compact":
        rev_at, keep = args
        return s.compact(rev_at, keep)
    raise AssertionError(op)


@pytest.mark.parametrize("seed", [7, 1234])
def test_randomized_lockstep_parity(tmp_path, seed):
    rng = random.Random(seed)
    py = open_store(str(tmp_path / "py.wal"), engine="python")
    nat = open_store(str(tmp_path / "nat.wal"), engine="native")
    try:
        for step in range(300):
            roll = rng.random()
            if roll < 0.45:
                op, args = "put", (rng.choice(KEYS),
                                   f"v{step}-{rng.randint(0, 9)}")
            elif roll < 0.6:
                op, args = "put_many", [
                    (rng.choice(KEYS), f"b{step}-{i}")
                    for i in range(rng.randint(0, 5))]
            elif roll < 0.8:
                op, args = "delete", rng.choice(KEYS)
            else:
                # compact to a revision at-or-below current, keeping a
                # random prefix's history
                rev_at = rng.randint(0, py.revision)
                keep = rng.choice([(), ("/par/",), ("/other/",)])
                op, args = "compact", (rev_at, keep)
            out_py = _apply(rng, py, op, args)
            out_nat = _apply(rng, nat, op, args)
            assert out_py == out_nat, (step, op, args)
            if step % 23 == 0:
                assert _observable(py) == _observable(nat), (step, op)
        assert _observable(py) == _observable(nat)
        # get_at_revision parity on a few uncompacted revisions
        for r in range(max(1, py.revision - 5), py.revision + 1):
            for k in KEYS[:4]:
                try:
                    a = py.get_at_revision(k, r)
                    a = None if a is None else (a.value, a.mod_revision)
                    a_err = None
                except ValueError as e:
                    a, a_err = None, str(e)[:9]
                try:
                    b = nat.get_at_revision(k, r)
                    b = None if b is None else (b.value, b.mod_revision)
                    b_err = None
                except ValueError as e:
                    b, b_err = None, str(e)[:9]
                assert (a, a_err) == (b, b_err), (k, r)
    finally:
        py.close()
        nat.close()

    # ---- WAL interop: each engine replays the OTHER's WAL -------------
    nat_of_py = open_store(str(tmp_path / "py.wal"), engine="native")
    py_of_nat = open_store(str(tmp_path / "nat.wal"), engine="python")
    try:
        assert _observable(nat_of_py) == _observable(py_of_nat)
    finally:
        nat_of_py.close()
        py_of_nat.close()


def test_maintain_parity_and_interop(tmp_path):
    """maintain() (compact + WAL rewrite + handle swap) leaves both
    engines observably identical, and the rewritten WALs still replay in
    the other engine."""
    py = open_store(str(tmp_path / "mp.wal"), engine="python")
    nat = open_store(str(tmp_path / "mn.wal"), engine="native")
    for s in (py, nat):
        for i in range(40):
            s.put(f"/m/k{i % 7}", f"v{i}")
        s.delete("/m/k0")
        s.put("/m/k0", "reborn")
        s.maintain(keep_history_prefixes=("/m/k1",))
        s.put("/m/k2", "after-maintain")
    assert _observable(py) == _observable(nat)
    py.close()
    nat.close()
    a = open_store(str(tmp_path / "mp.wal"), engine="native")
    b = open_store(str(tmp_path / "mn.wal"), engine="python")
    try:
        assert _observable(a) == _observable(b)
    finally:
        a.close()
        b.close()


def test_native_fsync_acked_puts_survive_kill(tmp_path):
    """The fsync-honesty acceptance: with the NATIVE engine and fsync on,
    every put/put_many ACKED before an abrupt os._exit death replays —
    in BOTH engines (the WAL the native core fsyncs is the shared
    format). This is the sweep open_store used to dodge by demoting
    fsync=True to the python engine."""
    wal = str(tmp_path / "kill.wal")
    child = (
        "import sys, os, threading\n"
        f"sys.path.insert(0, {os.getcwd()!r})\n"
        "from gpu_docker_api_tpu.store.native import NativeMVCCStore\n"
        f"s = NativeMVCCStore(wal_path={wal!r}, fsync=True)\n"
        "def w(i):\n"
        "    for j in range(20):\n"
        "        s.put(f'/kill/k{i}-{j}', str(j))\n"
        "    s.put_many([(f'/kill/b{i}-{j}', str(j)) for j in range(20)])\n"
        "ts = [threading.Thread(target=w, args=(i,)) for i in range(4)]\n"
        "[t.start() for t in ts]\n"
        "[t.join() for t in ts]\n"
        "print('ACKED', flush=True)\n"
        "os._exit(1)\n"
    )
    out = subprocess.run([sys.executable, "-c", child],
                         capture_output=True, text=True, timeout=60)
    assert "ACKED" in out.stdout, out.stderr
    for engine in ("native", "python"):
        s2 = open_store(wal_path=wal, engine=engine)
        try:
            for i in range(4):
                for j in range(20):
                    assert s2.get(f"/kill/k{i}-{j}").value == str(j), engine
                    assert s2.get(f"/kill/b{i}-{j}").value == str(j), engine
        finally:
            s2.close()


def test_open_store_auto_prefers_native_with_fsync(tmp_path):
    """The factory flip: fsync=True no longer demotes to python."""
    from gpu_docker_api_tpu.store.native import NativeMVCCStore
    s = open_store(str(tmp_path / "auto.wal"), engine="auto", fsync=True)
    try:
        assert isinstance(s, NativeMVCCStore)
        s.put("/x", "1")
        assert s.wal_flushes >= 1        # real counters, not aliases
    finally:
        s.close()
