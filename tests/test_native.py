"""Native C++ core tests: engine parity, WAL interop, allocator equivalence.
(The shared `store` fixture already runs the whole MVCC semantics suite
against both engines.)"""

import random

import pytest

from gpu_docker_api_tpu.store import MVCCStore, native_available, open_store

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native core not built")


def test_wal_python_writes_native_reads(tmp_path):
    wal = str(tmp_path / "w.jsonl")
    py = MVCCStore(wal_path=wal)
    py.put("k", 'payload with "quotes" \\ and\nnewlines\tand unicode é中')
    py.put("k", "v2")
    py.delete("k")
    py.put("k", "v3")
    py.put("other", "x")
    rev = py.revision
    py.close()

    nat = open_store(wal_path=wal, engine="native")
    assert nat.revision == rev
    kv = nat.get("k")
    assert kv.value == "v3" and kv.version == 1
    assert nat.get("other").value == "x"
    assert [k.value for k in nat.history("k")] == ["v3"]
    nat.close()


def test_wal_native_writes_python_reads(tmp_path):
    wal = str(tmp_path / "w.jsonl")
    nat = open_store(wal_path=wal, engine="native")
    tricky = 'json-in-json: {"a": "b\\"c", "n": [1,2]} é 中文 \x07'
    nat.put("k", tricky)
    nat.put("k", "v2")
    nat.compact(nat.revision)
    nat.put("k", "v3")
    rev = nat.revision
    nat.close()

    py = MVCCStore(wal_path=wal)
    assert py.revision == rev
    assert py.get("k").value == "v3"
    with pytest.raises(ValueError):
        py.get_at_revision("k", 1)  # compaction replayed from WAL
    py.close()


def test_native_snapshot_roundtrip(tmp_path):
    nat = open_store(wal_path=str(tmp_path / "a.jsonl"), engine="native")
    nat.put("x", "1")
    nat.put("x", "2")
    nat.put("gone", "z")
    nat.delete("gone")
    snap = str(tmp_path / "snap.jsonl")
    nat.snapshot(snap)
    rev = nat.revision
    nat.close()
    py = MVCCStore(wal_path=snap)  # snapshots replay in either engine
    assert py.revision == rev
    assert [kv.value for kv in py.history("x")] == ["1", "2"]
    assert py.get("gone") is None
    py.close()


def test_find_box_native_matches_python_cost():
    """The native box search must pick placements with the same cost key as
    the Python implementation, over randomized occupancy."""
    from gpu_docker_api_tpu.schedulers.tpu import TpuScheduler
    from gpu_docker_api_tpu.topology import TpuTopology

    def single_worker_topo():
        # the native core only serves single-worker slices (it doesn't score
        # worker spans); pin chips_per_host to the whole mesh
        return TpuTopology("v4-32", "v4", (2, 2, 4), chips_per_host=16)

    rng = random.Random(42)
    for trial in range(30):
        topo = single_worker_topo()
        sched = TpuScheduler(None, topology=topo)
        used = rng.sample(range(16), rng.randint(0, 10))
        for i in used:
            sched.status[i] = "x"
        free = {i for i, o in sched.status.items() if o is None}
        for n in (1, 2, 4):
            if len(free) < n:
                continue
            native = sched._native_find_box(n, free)
            # force the python path
            sched_py = TpuScheduler(None, topology=single_worker_topo())
            sched_py.status = dict(sched.status)
            from unittest import mock
            with mock.patch.object(sched_py, "_native_find_box",
                                   return_value=None):
                python = sched_py._find_box(n, free)
            if python is None:
                assert native == []
            else:
                assert native is not None and native != []
                assert _cost(topo, free, native) == _cost(topo, free, python)


def _cost(topo, free, idx):
    coords = [topo.chip(i).coord for i in idx]
    dims = tuple(max(c[a] for c in coords) - min(c[a] for c in coords) + 1
                 for a in range(3))
    sa = dims[0] * dims[1] + dims[1] * dims[2] + dims[0] * dims[2]
    box = set(idx)
    ext = 0
    for i in idx:
        for nb in topo.neighbors(topo.chip(i)):
            if nb.index not in box and nb.index in free:
                ext += 1
    return (sa, ext)


def test_app_runs_on_native_store(tmp_path):
    from gpu_docker_api_tpu.server.app import App
    from gpu_docker_api_tpu.topology import make_topology
    import http.client, json

    a = App(state_dir=str(tmp_path / "s"), backend="mock", addr="127.0.0.1:0",
            topology=make_topology("v5p-8"), api_key="", cpu_cores=8,
            store_engine="native")
    a.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", a.server.port, timeout=10)
        conn.request("POST", "/api/v1/replicaSet",
                     json.dumps({"imageName": "i", "replicaSetName": "n",
                                 "tpuCount": 2}),
                     {"Content-Type": "application/json"})
        out = json.loads(conn.getresponse().read())
        conn.close()
        assert out["code"] == 200
        assert len(out["data"]["tpuChips"]) == 2
    finally:
        a.stop()
