import threading
import time

import pytest

from gpu_docker_api_tpu.store import MVCCStore, StateClient
from gpu_docker_api_tpu.version import MergeMap, VersionMap
from gpu_docker_api_tpu.workqueue import Call, DelKey, PutKeyValue, WorkQueue


def test_version_map_bump_and_persist(client):
    vm = VersionMap("containerVersionMap", client)
    assert vm.get("foo") is None
    assert vm.bump("foo") == 1
    assert vm.bump("foo") == 2
    assert vm.bump("bar") == 1
    vm.rollback_bump("foo", 1)
    assert vm.get("foo") == 1
    vm.rollback_bump("bar", 0)
    assert not vm.exist("bar")
    # reload from store sees the same state
    vm2 = VersionMap("containerVersionMap", client)
    assert vm2.items() == {"foo": 1}


def test_version_map_concurrent_bumps(client):
    vm = VersionMap("containerVersionMap", client)
    out = []
    lock = threading.Lock()

    def w():
        for _ in range(100):
            v = vm.bump("rs")
            with lock:
                out.append(v)

    ts = [threading.Thread(target=w) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert sorted(out) == list(range(1, 801))  # no duplicate versions minted


def test_merge_map(client):
    mm = MergeMap(client)
    mm.set("rs-1", "/merges/rs/rs-1")
    mm.set("rs-2", "/merges/rs/rs-2")
    mm.set("other-1", "/merges/other/other-1")
    gone = mm.remove_replicaset("rs")
    assert sorted(gone) == ["/merges/rs/rs-1", "/merges/rs/rs-2"]
    assert mm.items() == {"other-1": "/merges/other/other-1"}
    mm2 = MergeMap(client)
    assert mm2.items() == {"other-1": "/merges/other/other-1"}


def test_workqueue_applies_in_order(client):
    wq = WorkQueue(client)
    wq.start()
    for i in range(20):
        wq.submit(PutKeyValue("containers", "k", f"v{i}"))
    assert wq.join()
    assert client.get_value("containers", "k") == "v19"
    wq.submit(DelKey("containers", "k"))
    assert wq.join()
    assert client.get("containers", "k") is None
    wq.close()


def test_workqueue_retries_then_succeeds(client):
    fails = {"n": 3}

    def flaky():
        if fails["n"] > 0:
            fails["n"] -= 1
            raise OSError("transient")
        client.put("containers", "done", "yes")

    wq = WorkQueue(client, base_backoff=0.01)
    wq.start()
    wq.submit(Call(flaky))
    deadline = 100
    while client.get("containers", "done") is None and deadline:
        time.sleep(0.05)
        deadline -= 1
    assert client.get_value("containers", "done") == "yes"
    wq.close()


def test_workqueue_drops_after_max_retries(client):
    def always_fails():
        raise OSError("permanent")

    wq = WorkQueue(client, max_retries=2, base_backoff=0.001)
    wq.start()
    wq.submit(Call(always_fails, "doomed"))
    deadline = 100
    while not wq.dropped and deadline:
        time.sleep(0.05)
        deadline -= 1
    assert len(wq.dropped) == 1
    wq.close()


def test_workqueue_rejects_after_close(client):
    wq = WorkQueue(client)
    wq.start()
    wq.close()
    with pytest.raises(RuntimeError):
        wq.submit(PutKeyValue("a", "b", "c"))


def test_version_map_via_workqueue(tmp_path):
    store = MVCCStore()
    client = StateClient(store)
    wq = WorkQueue(client)
    wq.start()
    vm = VersionMap("volumeVersionMap", client, wq)
    vm.bump("vol")
    vm.bump("vol")
    assert wq.join()
    vm2 = VersionMap("volumeVersionMap", client)
    assert vm2.get("vol") == 2
    wq.close()


def test_workqueue_retry_preserves_key_order(client):
    """A transiently-failing write must not be overtaken by a later write."""
    fails = {"n": 2}
    applied = []

    def first():
        if fails["n"] > 0:
            fails["n"] -= 1
            raise OSError("transient")
        applied.append("old")
        client.put("containers", "ordered", "old")

    def second():
        applied.append("new")
        client.put("containers", "ordered", "new")

    wq = WorkQueue(client, base_backoff=0.01)
    wq.start()
    wq.submit(Call(first))
    wq.submit(Call(second))
    assert wq.join(10)
    assert applied == ["old", "new"]
    assert client.get_value("containers", "ordered") == "new"
    wq.close()


class _RecordingClient:
    """StateClient wrapper counting the ops that actually hit the store."""

    def __init__(self, inner):
        self.inner = inner
        self.ops: list[tuple] = []

    def put(self, resource, name, value):
        self.ops.append(("put", resource, name, value))
        return self.inner.put(resource, name, value)

    def delete(self, resource, name):
        self.ops.append(("del", resource, name))
        return self.inner.delete(resource, name)


def test_coalesce_consecutive_puts_same_key(client):
    """Tentpole: a queued burst of same-key puts hits the store ONCE, with
    the latest value; distinct keys keep their relative order."""
    rec = _RecordingClient(client)
    wq = WorkQueue(rec)
    for i in range(20):
        wq.submit(PutKeyValue("containers", "hot", f"v{i}"))
    wq.submit(PutKeyValue("containers", "other", "x"))
    wq.start()          # drainer sees the whole burst at once
    assert wq.join()
    puts = [op for op in rec.ops if op[0] == "put"]
    assert puts == [("put", "containers", "hot", "v19"),
                    ("put", "containers", "other", "x")]
    assert client.get_value("containers", "hot") == "v19"
    assert wq.coalesced_count() == 19
    wq.close()


def test_coalesce_del_is_a_barrier(client):
    """put -> del -> put must apply as THREE ops in order: collapsing the
    puts around the barrier would end the run with the key deleted (or
    resurrect a deleted value)."""
    rec = _RecordingClient(client)
    wq = WorkQueue(rec)
    wq.submit(PutKeyValue("containers", "k", "v1"))
    wq.submit(DelKey("containers", "k"))
    wq.submit(PutKeyValue("containers", "k", "v2"))
    wq.start()
    assert wq.join()
    assert rec.ops == [("put", "containers", "k", "v1"),
                       ("del", "containers", "k"),
                       ("put", "containers", "k", "v2")]
    assert client.get_value("containers", "k") == "v2"
    assert wq.coalesced_count() == 0
    wq.close()


def test_coalesce_call_is_a_barrier(client):
    """Call closures fence coalescing the same way DelKey does — a
    persistence closure may read keys written before it."""
    rec = _RecordingClient(client)
    wq = WorkQueue(rec)
    seen = {}
    wq.submit(PutKeyValue("containers", "k", "v1"))
    wq.submit(Call(lambda: seen.update(
        at_call=client.get_value("containers", "k")), "probe"))
    wq.submit(PutKeyValue("containers", "k", "v2"))
    wq.start()
    assert wq.join()
    assert seen["at_call"] == "v1"      # the barrier saw the FIRST write
    assert client.get_value("containers", "k") == "v2"
    wq.close()


def test_coalesce_deferred_value_resolved_on_drainer(client):
    """PutKeyValue.value may be a callable (deferred serialization): the
    drainer resolves it, and coalescing keeps only the newest snapshot."""
    wq = WorkQueue(client)
    resolved = []

    def snap(i):
        def go():
            resolved.append(i)
            return f"snapshot-{i}"
        return go

    for i in range(5):
        wq.submit(PutKeyValue("tpus", "statusMap", snap(i)))
    wq.start()
    assert wq.join()
    assert client.get_value("tpus", "statusMap") == "snapshot-4"
    assert resolved == [4]              # superseded snapshots never serialized
    wq.close()


def test_coalesced_drop_dead_letters_survivor(client):
    """Dead-letter interaction: when the coalesced survivor exhausts its
    retries, the LATEST message lands in dropped (the superseded ones are
    moot), join() still completes, and replay_dropped() re-queues it."""
    class Failing:
        def __init__(self, inner):
            self.inner = inner
            self.healthy = False

        def put(self, resource, name, value):
            if not self.healthy:
                raise OSError("store down")
            return self.inner.put(resource, name, value)

        def delete(self, resource, name):
            return self.inner.delete(resource, name)

    failing = Failing(client)
    wq = WorkQueue(failing, max_retries=1, base_backoff=0.001)
    for i in range(8):
        wq.submit(PutKeyValue("containers", "dl", f"v{i}"))
    wq.start()
    assert wq.join(10)                  # drop still completes the batch
    assert wq.coalesced_count() == 7
    assert len(wq.dropped) == 1
    assert wq.dropped[0].value == "v7"  # the survivor IS the newest value
    failing.healthy = True
    assert wq.replay_dropped() == 1
    assert wq.join(10)
    assert client.get_value("containers", "dl") == "v7"
    assert wq.dropped_count() == 0
    wq.close()


def test_merge_map_prefix_no_cross_replicaset(client):
    mm = MergeMap(client)
    mm.set("app-1", "/m/app/app-1")
    mm.set("app-1-1", "/m/app-1/app-1-1")  # replicaSet literally named "app-1"
    gone = mm.remove_replicaset("app")
    assert gone == ["/m/app/app-1"]
    assert "app-1-1" in mm.items()
