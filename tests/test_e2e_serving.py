"""End-to-end train -> serve through the control plane: a training
replicaSet checkpoints onto a volume; a serving replicaSet binds the SAME
volume, loads the checkpoint, and answers generation requests on the port
the scheduler granted. The full lifecycle a user of the reference would
expect — except the workloads are first-class here instead of opaque
containers."""

import json
import os
import sys
import time

import pytest

from gpu_docker_api_tpu.server.app import App
from gpu_docker_api_tpu.topology import make_topology

# slow tier: long-compile / multi-process e2e — quick CI runs
# -m 'not slow' (<3 min); the full suite stays the default
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def app(tmp_path):
    a = App(state_dir=str(tmp_path / "state"), backend="process",
            addr="127.0.0.1:0", port_range=(45200, 45300),
            topology=make_topology("v5p-8"), api_key="", cpu_cores=8)
    a.start()
    yield a
    a.stop()


def call(app, method, path, body=None):
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", app.server.port,
                                      timeout=30)
    conn.request(method, path, json.dumps(body) if body is not None else None,
                 {"Content-Type": "application/json"})
    resp = json.loads(conn.getresponse().read())
    conn.close()
    return resp


def _http(port, method, path, body=None, timeout=120):
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request(method, path, json.dumps(body) if body is not None else None,
                 {"Content-Type": "application/json"})
    out = json.loads(conn.getresponse().read())
    conn.close()
    return out


@pytest.mark.slow
def test_train_then_serve_from_checkpoint(app, tmp_path):
    cache = str(tmp_path / "jax-cache")
    env = [
        f"PYTHONPATH={REPO}",
        "JAX_PLATFORMS=cpu", "JAX_PLATFORM_NAME=cpu",
        "PALLAS_AXON_POOL_IPS=",
        f"JAX_COMPILATION_CACHE_DIR={cache}",
    ]

    # 1. volume for the model artifacts
    vol = call(app, "POST", "/api/v1/volumes",
               {"name": "model", "size": "2GB"})["data"]

    # 2. short training job writes a checkpoint onto the volume
    resp = call(app, "POST", "/api/v1/replicaSet", {
        "imageName": "python", "replicaSetName": "trainjob", "tpuCount": 0,
        "env": env,
        "cmd": [sys.executable, "-m",
                "gpu_docker_api_tpu.workloads.train_llama",
                "--config", "tiny", "--steps", "4", "--checkpoint-every", "4",
                "--batch", "2", "--seq", "32", "--workdir", "root/foo-tmp"],
        "binds": [{"src": vol["mountpoint"], "dest": "/root/foo-tmp"}]})
    assert resp["code"] == 200, resp
    ckpt_dir = os.path.join(vol["mountpoint"], "checkpoints")
    deadline = time.time() + 300
    while time.time() < deadline:
        if os.path.isdir(ckpt_dir) and any(
                not n.startswith(".") for n in os.listdir(ckpt_dir)):
            # a non-temp checkpoint step dir exists
            # orbax temp dirs: <step>.orbax-checkpoint-tmp-<timestamp>
            if any(os.path.isdir(os.path.join(ckpt_dir, n))
                   and ".orbax-checkpoint-tmp" not in n
                   for n in os.listdir(ckpt_dir)):
                break
        time.sleep(0.5)
    else:
        pytest.fail("training never wrote a checkpoint")
    call(app, "DELETE", "/api/v1/replicaSet/trainjob")

    # 3. serving replicaSet binds the same volume and loads the checkpoint
    resp = call(app, "POST", "/api/v1/replicaSet", {
        "imageName": "python", "replicaSetName": "llm", "tpuCount": 0,
        "containerPorts": ["8000"], "env": env,
        "cmd": [sys.executable, "-m", "gpu_docker_api_tpu.workloads.serve",
                "--config", "tiny", "--host", "127.0.0.1",
                "--checkpoint", "root/foo-tmp/checkpoints"],
        "binds": [{"src": vol["mountpoint"], "dest": "/root/foo-tmp"}]})
    assert resp["code"] == 200, resp
    port = list(resp["data"]["portBindings"].values())[0]

    deadline = time.time() + 300
    health = None
    while time.time() < deadline:
        try:
            health = _http(port, "GET", "/healthz", timeout=3)
            break
        except OSError:
            time.sleep(1)
    assert health and health["code"] == 200, health
    assert health["data"]["model"] == "llama/tiny"

    # 4. greedy generation is deterministic: the served model is REAL
    req = {"tokens": [[5, 9, 2, 7]], "max_new": 4}
    a = _http(port, "POST", "/generate", req)
    b = _http(port, "POST", "/generate", req)
    assert a["code"] == 200, a
    assert a["data"]["tokens"] == b["data"]["tokens"]
    toks = a["data"]["tokens"][0]
    assert len(toks) == 4 and all(0 <= t < 256 for t in toks)

    call(app, "DELETE", "/api/v1/replicaSet/llm")
