"""Failure-path and resource-ownership regression tests (code-review round 3
findings: stale releases, double-frees, cache/store coherence on unwind)."""

import pytest

from gpu_docker_api_tpu import xerrors
from gpu_docker_api_tpu.backend import MockBackend
from gpu_docker_api_tpu.dtos import ContainerRun, MemoryPatch, PatchRequest, TpuPatch
from gpu_docker_api_tpu.schedulers import CpuScheduler, PortScheduler, TpuScheduler
from gpu_docker_api_tpu.services import ReplicaSetService, VolumeService
from gpu_docker_api_tpu.store import MVCCStore, StateClient
from gpu_docker_api_tpu.topology import make_topology
from gpu_docker_api_tpu.version import MergeMap, VersionMap
from gpu_docker_api_tpu.workqueue import WorkQueue


class FlakyBackend(MockBackend):
    """Mock backend with one-shot failure injection."""

    def __init__(self, state_dir):
        super().__init__(state_dir)
        self.fail_next_start: bool = False
        self.fail_start_of: str = ""

    def start(self, name):
        if self.fail_next_start or (self.fail_start_of and name == self.fail_start_of):
            self.fail_next_start = False
            self.fail_start_of = ""
            raise RuntimeError("injected start failure")
        return super().start(name)


@pytest.fixture()
def world(tmp_path):
    store = MVCCStore()
    client = StateClient(store)
    wq = WorkQueue(client)
    wq.start()
    backend = FlakyBackend(str(tmp_path / "state"))
    tpu = TpuScheduler(client, wq, topology=make_topology("v4-32"))
    cpu = CpuScheduler(client, wq, core_count=16)
    ports = PortScheduler(client, wq, port_range=(42000, 42100), seed=11)
    rs = ReplicaSetService(backend, client, wq, tpu, cpu, ports,
                           VersionMap("containerVersionMap", client, wq),
                           MergeMap(client, wq))
    vol = VolumeService(backend, client, wq,
                        VersionMap("volumeVersionMap", client, wq))
    yield rs, vol, backend, tpu, cpu, ports, wq, client
    wq.close()


def _run(rs, name="a", tpus=2, ports=1):
    return rs.run_container(ContainerRun(
        imageName="img", replicaSetName=name, tpuCount=tpus,
        containerPorts=["8888"] if ports else []))


# finding 1+7: failed rolling replace must fully revert latest pointer,
# version counter, and the new version's port grant

def test_failed_replace_reverts_world(world):
    rs, _, backend, tpu, cpu, ports, wq, client = world
    _run(rs, "a", tpus=1)
    ports_before = len(ports.get_status()["usedPortSet"])
    backend.fail_start_of = "a-2"
    with pytest.raises(RuntimeError):
        rs.patch_container("a", PatchRequest(tpuPatch=TpuPatch(4)))
    # old container restarted and still addressable
    assert backend.inspect("a-1").running
    info = rs.get_container_info("a")
    assert info["version"] == 1 and info["containerName"] == "a-1"
    # resources: only the original grant remains held
    assert tpu.get_status()["freeCount"] == 15
    assert len(ports.get_status()["usedPortSet"]) == ports_before
    # next mutation mints version 2, not 3
    resp = rs.patch_container("a", PatchRequest(memoryPatch=MemoryPatch("2GB")))
    assert resp["version"] == 2
    # history has no phantom entry for the failed attempt
    hist = rs.get_container_history("a")
    assert [h["version"] for h in hist] == [2, 1]


def test_failed_first_start_does_not_brick_the_name(world):
    """Review finding: create succeeded, start failed — the created
    container must be removed, or every retry collides with the leftover
    and the name is unusable until a reboot's reconcile."""
    rs, _, backend, tpu, cpu, ports, wq, client = world
    backend.fail_next_start = True
    with pytest.raises(RuntimeError):
        _run(rs, "a", tpus=1)
    assert not backend.inspect("a-1").exists
    assert tpu.get_status()["freeCount"] == 16
    # the name is immediately reusable
    resp = _run(rs, "a", tpus=1)
    assert resp["name"] == "a-1"
    assert backend.inspect("a-1").running


# finding 2: double-stop must not free chips now owned by another replicaSet

def test_double_stop_cannot_free_others_chips(world):
    rs, _, backend, tpu, *_ = world
    r_a = _run(rs, "a", tpus=4, ports=0)
    rs.stop_container("a")          # frees a's 4 chips
    r_b = _run(rs, "b", tpus=4, ports=0)   # b may get the same chips
    rs.stop_container("a")          # second stop — must be a no-op
    status = tpu.get_status()
    owned_b = [c["index"] for c in status["chips"] if c["owner"] == "b"]
    assert sorted(owned_b) == sorted(r_b["tpuChips"])
    assert status["freeCount"] == 12


# finding 3: in-place reuse — during patch the old grant never transits the
# free pool, and unwind never clobbers another owner

def test_patch_reuse_keeps_ownership(world):
    rs, _, backend, tpu, *_ = world
    _run(rs, "a", tpus=4, ports=0)
    _run(rs, "b", tpus=8, ports=0)   # only 4 chips left free
    # shrink a 4 -> 2: must reuse a's own chips, not fail or steal
    resp = rs.patch_container("a", PatchRequest(tpuPatch=TpuPatch(2)))
    assert len(resp["tpuChips"]) == 2
    status = tpu.get_status()
    owners = {c["index"]: c["owner"] for c in status["chips"]}
    assert all(owners[i] == "a" for i in resp["tpuChips"])
    assert status["freeCount"] == 6  # 16 - 8(b) - 2(a)


def test_patch_shortage_unwind_leaves_other_owner_intact(world):
    rs, _, backend, tpu, *_ = world
    _run(rs, "a", tpus=2, ports=0)
    _run(rs, "b", tpus=12, ports=0)
    with pytest.raises(xerrors.TpuNotEnoughError):
        rs.patch_container("a", PatchRequest(tpuPatch=TpuPatch(8)))
    status = tpu.get_status()
    assert status["freeCount"] == 2
    assert sum(1 for c in status["chips"] if c["owner"] == "b") == 12
    assert backend.inspect("a-1").running


# finding 4: stop -> restart must not free the new version's (or another
# replicaSet's) re-picked port numbers

def test_stop_restart_port_not_stolen(world):
    rs, _, backend, tpu, cpu, ports, *_ = world
    _run(rs, "a", tpus=0, ports=1)
    rs.stop_container("a")
    assert ports.get_status()["usedPortSet"] == []
    resp = rs.restart_container("a")
    new_port = resp["portBindings"]["8888"]
    assert ports.get_status()["usedPortSet"] == [new_port]  # still held


# finding 5: restart-of-stopped shortage must not free stale chip lists

def test_restart_shortage_no_stale_free(world):
    rs, _, backend, tpu, *_ = world
    _run(rs, "a", tpus=4, ports=0)
    rs.stop_container("a")
    r_b = _run(rs, "b", tpus=14, ports=0)  # occupies most chips incl a's old
    with pytest.raises(xerrors.TpuNotEnoughError):
        rs.restart_container("a")
    status = tpu.get_status()
    assert sum(1 for c in status["chips"] if c["owner"] == "b") == 14
    assert status["freeCount"] == 2


# finding 6: deleting a replicaSet whose workload exited on its own still
# releases its grants

def test_delete_exited_container_releases_resources(world):
    rs, _, backend, tpu, cpu, ports, *_ = world
    _run(rs, "a", tpus=4)
    # simulate workload exiting by itself (not via stop_container)
    backend.stop("a-1")
    assert not backend.inspect("a-1").running
    rs.delete_container("a")
    assert tpu.get_status()["freeCount"] == 16
    assert ports.get_status()["usedPortSet"] == []


# finding 8: volume migration failure leaves reads pointing at the live old
# volume and no phantom history entry

def test_volume_migration_failure_coherent(world, monkeypatch):
    _, vol, backend, *_ = world
    v = vol.create_volume("vol", "1GB")
    import gpu_docker_api_tpu.services.volume as volmod

    def boom(src, dest):
        raise OSError("injected migration failure")

    monkeypatch.setattr(volmod, "move_dir_contents", boom)
    with pytest.raises(OSError):
        vol.patch_volume_size("vol", "2GB")
    info = vol.get_volume_info("vol")
    assert info["volumeName"] == "vol-1"
    assert info["mountpoint"]  # the old volume is alive and inspectable
    hist = vol.get_volume_history("vol")
    assert [h["version"] for h in hist] == [1]
    # a later patch works and mints version 2
    monkeypatch.undo()
    out = vol.patch_volume_size("vol", "2GB")
    assert out["name"] == "vol-2"
