"""KV-aware serving data plane sweep (`kvroute` marker; make
verify-kvroute).

Four layers:

- kvaffinity primitives: incremental chunk hashing, Bloom sketch
  membership with the consecutive-run rule, the queue-dominates scoring
  contract, sketch hex wire format — both ends of every sketch exchange
  (shm cells, response headers) must agree bit-for-bit;
- the replica-side prefix index (batching.PrefixTrie) and the mock
  model's full serving contract (sketch/occupancy headers, single-take
  /kv export, handoff import, queue-wait EWMA) — the surfaces the bench
  and the e2e cases drive;
- router policy: the in-process Gateway and the worker tier's
  WorkerRouter both order candidates by kvaffinity.score — warm wins a
  queue tie, a visibly shorter queue always wins, TDAPI_GW_AFFINITY=0
  restores pure least-queued — and the worker side does it from the shm
  kv cells ONLY (pinned by the daemon-SIGKILL case: routing and
  affinity continue with no daemon process at all);
- prefill/decode disaggregation e2e over real mock replicas: the
  two-phase handoff returns a byte-compatible single reply, the export
  is single-take, and the kvhandoff.after_prefill crashpoint leaks
  neither claims nor KV (TTL purge), after which the same request
  completes whole.
"""

from __future__ import annotations

import http.client
import json
import os
import subprocess
import sys
import time

import pytest

from gpu_docker_api_tpu import faults, kvaffinity
from gpu_docker_api_tpu.batching import PrefixTrie
from gpu_docker_api_tpu.faults import InjectedCrash
from gpu_docker_api_tpu.gateway import (
    READY, Gateway, GatewayConfig, Replica,
)

pytestmark = pytest.mark.kvroute

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

try:
    from gpu_docker_api_tpu.server import workers
    _HAVE_WORKERS = workers.available()
except Exception:  # noqa: BLE001 — no native core on this platform
    workers = None
    _HAVE_WORKERS = False

needs_workers = pytest.mark.skipif(
    not _HAVE_WORKERS,
    reason="worker tier unavailable (no Linux SO_REUSEPORT / native core)")

OK = b'{"code":200,"msg":"ok","data":{}}'


@pytest.fixture(autouse=True)
def _disarmed():
    faults.disarm_all()
    yield
    faults.disarm_all()


# ------------------------------------------------- kvaffinity primitives

def test_chunk_hashes_prefix_property():
    toks = list(range(200))
    hs = kvaffinity.chunk_hashes(toks)
    assert len(hs) == 6                       # 200 // 32 complete levels
    # hashes are a pure function of the PREFIX: extending the prompt
    # never changes earlier levels (incremental FNV, one pass)
    assert kvaffinity.chunk_hashes(toks[:64]) == hs[:2]
    # a partial trailing chunk is never hashed (can't be block-resident)
    assert kvaffinity.chunk_hashes(toks[:63]) == hs[:1]
    assert kvaffinity.chunk_hashes(list(range(31))) == []
    assert (len(kvaffinity.chunk_hashes(list(range(1000))))
            == kvaffinity.MAX_LEVELS)


def test_hit_requires_consecutive_levels():
    toks = list(range(128))                   # 4 levels
    hs = kvaffinity.chunk_hashes(toks)
    sk = kvaffinity.build_sketch(hs[:2])
    assert kvaffinity.hit_tokens(sk, hs) == 2 * kvaffinity.CHUNK_TOKENS
    # a deeper level WITHOUT its ancestors is a false positive by
    # construction — the run must start at level 0
    assert kvaffinity.hit_tokens(kvaffinity.build_sketch(hs[2:]), hs) == 0
    assert kvaffinity.hit_tokens(None, hs) == 0
    assert kvaffinity.hit_tokens(kvaffinity.build_sketch(hs), []) == 0


def test_score_queue_strictly_dominates_hit():
    deepest = kvaffinity.MAX_LEVELS * kvaffinity.CHUNK_TOKENS
    # one unit of queue depth outweighs the deepest possible hit:
    # affinity refines least-queued order, it never overrides it
    assert kvaffinity.score(deepest, 1) > kvaffinity.score(0, 0)
    # at equal depth the deeper hit wins (lower score)
    assert kvaffinity.score(64, 2) < kvaffinity.score(0, 2)


def test_sketch_hex_roundtrip_and_signed64():
    words = [0x8000000000000001, 0, (1 << 64) - 1, 0x0123456789ABCDEF]
    text = kvaffinity.encode_sketch_hex(words)
    assert len(text) == kvaffinity.SKETCH_WORDS * 16
    assert kvaffinity.decode_sketch_hex(text) == words
    assert kvaffinity.decode_sketch_hex("") is None
    assert kvaffinity.decode_sketch_hex(text[:-1]) is None
    assert kvaffinity.decode_sketch_hex("zz" * 32) is None
    for w in words:       # int64 shm-cell reinterpretation round-trips
        assert kvaffinity.signed64(w) & ((1 << 64) - 1) == w


def test_kvroute_catalog_registration():
    """Every kvroute event op / metric family is in the obs/names.py
    catalog (the tdlint untraced-op contract)."""
    from gpu_docker_api_tpu.obs.names import EVENT_OPS, METRIC_NAMES
    assert {"gateway.kv_handoff", "router.affinity_hit"} <= EVENT_OPS
    assert {"tdapi_gw_affinity_hits_total",
            "tdapi_gw_affinity_tokens_total",
            "tdapi_kv_prefix_blocks",
            "tdapi_kv_prefix_handoffs_total"} <= METRIC_NAMES


# ------------------------------------------------ replica-side prefix trie

def test_prefix_trie_sharing_lru_and_leaf_only_eviction():
    t = PrefixTrie(4)
    a = list(range(8))
    assert t.insert(a, [10, 11]) == [10, 11]
    b = a[:4] + [99, 98, 97, 96]
    # the shared first block is NOT re-referenced: two prompts sharing a
    # prefix share the physical block
    assert t.insert(b, [10, 12]) == [12]
    assert len(t) == 3 and t.leaf_count == 2
    blocks, matched = t.lookup(a + [5])
    assert blocks == [10, 11] and matched == 8
    # the lookup refreshed a's path, so LRU eviction drops b's leaf —
    # and ONLY a leaf (the shared interior block backs both prefixes)
    assert t.evict_lru() == [12]
    assert t.evict_lru() == [11]
    assert t.clear() == [10]


# ---------------------------------------------------- mock serving contract

def _spawn_mock(workdir, *args):
    """A real mock_model subprocess (its own cwd: READY_MARKER and
    weights land there); returns (proc, port) once it serves."""
    env = dict(os.environ, PORT="0", PYTHONPATH=REPO)
    proc = subprocess.Popen(
        [sys.executable, "-u", "-m",
         "gpu_docker_api_tpu.workloads.mock_model",
         "--host", "127.0.0.1", *args],
        cwd=str(workdir), env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    port = None
    deadline = time.time() + 20
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        if "serving on" in line:
            port = int(line.rsplit(":", 1)[1])
            break
    assert port, "mock model never came up"
    return proc, port


def _post(port, data, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("POST", "/generate", json.dumps(data).encode(),
                     {"Content-Type": "application/json",
                      **(headers or {})})
        r = conn.getresponse()
        return r.status, r.getheaders(), json.loads(r.read())
    finally:
        conn.close()


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        return r.status, r.getheaders(), json.loads(r.read())
    finally:
        conn.close()


def _prefix_cache(port) -> dict:
    _, _, hz = _get(port, "/healthz")
    return hz["data"]["batching"]["prefixCache"]


def test_mock_kv_contract_sketch_export_ewma(tmp_path):
    proc, port = _spawn_mock(tmp_path, "--decode-ms", "1")
    try:
        toks = list(range(64))
        status, hdrs, out = _post(
            port, {"tokens": [toks], "max_new": 8},
            {"X-TDAPI-Phase": "prefill", "X-TDAPI-KV-Key": "k1"})
        assert status == 200
        row = out["data"]["tokens"][0]
        assert row == toks + [0]          # prefill phase forces max_new=1
        h = {k.lower(): v for k, v in hdrs}
        assert kvaffinity.decode_sketch_hex(h["x-tdapi-kv-sketch"]) \
            is not None
        assert int(h["x-tdapi-kv-occ"]) >= 1
        # the export is the PROMPT KV, and it is single-take
        st1, _, kv = _get(port, "/kv?key=k1")
        assert st1 == 200 and kv["data"]["tokens"] == toks
        st2, _, kv2 = _get(port, "/kv?key=k1")
        assert st2 == 404 and kv2["code"] == 404
        # healthz: smoothed queue wait + the prefix-cache block
        _, _, hz = _get(port, "/healthz")
        b = hz["data"]["batching"]
        assert b["queueWait"]["ewmaMs"] is not None
        assert b["prefixCache"]["entries"] >= 1
        assert b["prefixCache"]["kvFetches"] == 1
    finally:
        proc.kill()
        proc.wait(10)


# ------------------------------------------------ in-process router policy

def _bare_gateway(transport, **cfg_kw) -> Gateway:
    kw = dict(name="g", image="img", deadlineMs=3000, maxQueue=8)
    kw.update(cfg_kw)
    return Gateway(GatewayConfig(**kw), services=None, intents=None,
                   transport=transport)


def _ready_replica(name, idx, port, slots=2) -> Replica:
    r = Replica(name, idx)
    r.state = READY
    r.slots = slots
    r.host_port = port
    return r


def test_gateway_pick_prefers_warm_on_queue_tie_and_folds_meta():
    toks = list(range(64))
    sketch = kvaffinity.build_sketch(kvaffinity.chunk_hashes(toks))
    meta = {"x-tdapi-kv-sketch": kvaffinity.encode_sketch_hex(sketch),
            "x-tdapi-kv-occ": "5"}
    seen = []

    def transport(port, method, path, body, timeout):
        seen.append(port)
        return 200, OK, meta

    gw = _bare_gateway(transport)
    gw.replicas = {"a": _ready_replica("a", 0, 1001),
                   "b": _ready_replica("b", 1, 1002)}
    gw.replicas["b"].kv_sketch = sketch
    status, _ = gw.forward(
        json.dumps({"tokens": [toks], "max_new": 4}).encode())
    assert status == 200 and seen == [1002]       # warm replica won the tie
    assert gw.affinity_hits == 1 and gw.affinity_tokens == 64
    # the response's advertised sketch/occupancy folded into the handle
    assert gw.replicas["b"].kv_occ == 5
    assert gw.replicas["b"].kv_sketch == sketch


def test_gateway_affinity_never_overrides_shorter_queue():
    toks = list(range(64))
    sketch = kvaffinity.build_sketch(kvaffinity.chunk_hashes(toks))
    seen = []

    def transport(port, method, path, body, timeout):
        seen.append(port)
        return 200, OK

    gw = _bare_gateway(transport)
    gw.replicas = {"a": _ready_replica("a", 0, 1001),
                   "b": _ready_replica("b", 1, 1002)}
    gw.replicas["b"].kv_sketch = sketch
    gw.replicas["b"].inflight = 1                 # warm but visibly busier
    status, _ = gw.forward(
        json.dumps({"tokens": [toks], "max_new": 4}).encode())
    assert status == 200 and seen == [1001]       # queue depth dominates
    assert gw.affinity_hits == 0


def test_gateway_affinity_env_disable(monkeypatch):
    monkeypatch.setenv("TDAPI_GW_AFFINITY", "0")
    toks = list(range(64))
    sketch = kvaffinity.build_sketch(kvaffinity.chunk_hashes(toks))
    seen = []

    def transport(port, method, path, body, timeout):
        seen.append(port)
        return 200, OK

    gw = _bare_gateway(transport)                 # toggle read at init
    gw.replicas = {"a": _ready_replica("a", 0, 1001),
                   "b": _ready_replica("b", 1, 1002)}
    gw.replicas["b"].kv_sketch = sketch
    status, _ = gw.forward(
        json.dumps({"tokens": [toks], "max_new": 4}).encode())
    assert status == 200 and seen == [1001]       # pure least-queued order
    assert gw.affinity_hits == 0


def test_pool_policy_validation_roles_and_scale_parity():
    cfg = GatewayConfig(name="g", image="img", poolPolicy="bogus")
    with pytest.raises(ValueError):
        cfg.validate()
    GatewayConfig(name="g", image="img",
                  poolPolicy="disaggregated").validate()
    # roles derive from idx PARITY (crash-recoverable: adopt-by-name
    # needs no stored role field)
    assert Replica("gr0", 0).role == "prefill"
    assert Replica("gr1", 1).role == "decode"
    # pool-aware autoscaling grows the smaller pool, on its idx stride
    gw = _bare_gateway(None, poolPolicy="disaggregated")
    gw.replicas = {"gr0": _ready_replica("gr0", 0, 1001),
                   "gr1": _ready_replica("gr1", 1, 1002),
                   "gr2": _ready_replica("gr2", 2, 1003)}
    assert gw._scale_parity() == 1                # decode pool is smaller
    assert gw._next_idx(1) == 3
    assert _bare_gateway(None)._scale_parity() is None


# ----------------------------------------------- worker-tier router policy

@pytest.fixture()
def state():
    st = workers.SharedRouterState(create=True)
    yield st
    st.close(unlink=True)


def publish(st, replicas, max_queue=8, deadline_ms=3000, name="g"):
    st.publish([{"name": name, "maxQueue": max_queue,
                 "deadlineMs": deadline_ms, "replicas": replicas}])


def rep(port, slots=4, ready=True):
    return {"port": port, "slots": slots, "ready": ready}


@needs_workers
def test_kv_cells_roundtrip_and_torn_read(state):
    words = [0x8000000000000001, 0x123456789ABCDEF0, (1 << 64) - 1, 0]
    state.publish_replica_kv(0, 3, 42, words)
    assert state.read_replica_kv(0, 3) == (42, words)
    assert state.read_replica_kv(0, 2) is None    # nothing advertised
    # a writer killed mid-publish parks the cell gen odd: ONE read
    # attempt, None, never a spin — and the next publish heals it
    state.publish_replica_kv(0, 0, 5, words)
    off = workers._rep_kv_off(0, 0)
    gen = state.load(off)
    state.store(off, gen + 1)
    assert state.read_replica_kv(0, 0) is None
    state.store(off, gen + 2)
    assert state.read_replica_kv(0, 0) == (5, words)


@needs_workers
def test_worker_scored_pick_prefers_warm_on_equal_queue(state):
    toks = list(range(64))
    body = json.dumps({"tokens": [toks], "max_new": 4}).encode()
    publish(state, [rep(1001), rep(1002)])
    state.publish_replica_kv(
        0, 1, 2, kvaffinity.build_sketch(kvaffinity.chunk_hashes(toks)))
    seen = []

    def transport(port, method, path, body, timeout):
        seen.append(port)
        return 200, OK

    r = workers.WorkerRouter(state, 0, transport=transport)
    status, _ = r.forward("g", body)
    assert status == 200 and seen == [1002]
    c = state.gateway_counters(0)
    assert c["affinityHits"] == 1 and c["affinityTokens"] == 64


@needs_workers
def test_worker_scored_pick_queue_depth_dominates(state):
    toks = list(range(64))
    body = json.dumps({"tokens": [toks], "max_new": 4}).encode()
    publish(state, [rep(1001), rep(1002)])
    state.publish_replica_kv(
        0, 1, 2, kvaffinity.build_sketch(kvaffinity.chunk_hashes(toks)))
    state.add(workers._rep_cnt_off(0, 1), 1)      # warm replica busier
    seen = []
    r = workers.WorkerRouter(
        state, 0,
        transport=lambda port, *a: seen.append(port) or (200, OK))
    status, _ = r.forward("g", body)
    assert status == 200 and seen == [1001]
    assert state.gateway_counters(0)["affinityHits"] == 0


@needs_workers
def test_worker_affinity_env_disable(state, monkeypatch):
    monkeypatch.setenv("TDAPI_GW_AFFINITY", "0")
    toks = list(range(64))
    publish(state, [rep(1001), rep(1002)])
    state.publish_replica_kv(
        0, 1, 2, kvaffinity.build_sketch(kvaffinity.chunk_hashes(toks)))
    seen = []
    r = workers.WorkerRouter(
        state, 0,
        transport=lambda port, *a: seen.append(port) or (200, OK))
    status, _ = r.forward(
        "g", json.dumps({"tokens": [toks], "max_new": 4}).encode())
    assert status == 200 and seen == [1001]       # pure least-queued order
    assert state.gateway_counters(0)["affinityHits"] == 0


@needs_workers
def test_worker_folds_advertised_sketch_into_cells(state):
    toks = list(range(64))
    sketch = kvaffinity.build_sketch(kvaffinity.chunk_hashes(toks))
    publish(state, [rep(1001), rep(1002)])
    calls = []

    def transport(port, method, path, body, timeout):
        calls.append(port)
        if port == 1001 and body == b"{}":
            # the warmup request fails over to 1002, whose response
            # advertises its KV state (the 4-tuple kv element)
            raise ConnectionRefusedError("warmup: 1001 down")
        if port == 1002:
            return 200, OK, 1.5, (7, sketch)
        return 200, OK

    r = workers.WorkerRouter(state, 0, transport=transport)
    status, _ = r.forward("g", b"{}")    # retries onto 1002 -> kv folds
    assert status == 200 and calls == [1001, 1002]
    assert state.read_replica_kv(0, 1) == (7, sketch)
    assert state.read_replica_kv(0, 0) is None
    # the published cells steer the next prompt-bearing request
    status, _ = r.forward(
        "g", json.dumps({"tokens": [toks], "max_new": 4}).encode())
    assert status == 200 and calls[-1] == 1002
    assert state.gateway_counters(0)["affinityHits"] == 1


_CHILD = (
    "import time\n"
    "from gpu_docker_api_tpu.server import workers\n"
    "from gpu_docker_api_tpu import kvaffinity\n"
    "st = workers.SharedRouterState(create=True)\n"
    "st.publish([{'name': 'g', 'maxQueue': 8, 'deadlineMs': 3000,\n"
    "             'replicas': [\n"
    "                 {'port': 1001, 'slots': 4, 'ready': True},\n"
    "                 {'port': 1002, 'slots': 4, 'ready': True}]}])\n"
    "toks = list(range(64))\n"
    "st.publish_replica_kv(0, 1, 2,\n"
    "    kvaffinity.build_sketch(kvaffinity.chunk_hashes(toks)))\n"
    "print(st.name, flush=True)\n"
    "time.sleep(60)\n")


@needs_workers
def test_affinity_routes_from_shm_after_daemon_sigkill():
    """The zero-daemon-round-trips pin: a 'daemon' process publishes the
    roster + kv sketches and is SIGKILLed; the worker router keeps
    forwarding AND keeps applying affinity from the shm cells alone."""
    proc = subprocess.Popen(
        [sys.executable, "-u", "-c", _CHILD], cwd=REPO,
        env=dict(os.environ, PYTHONPATH=REPO),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    name = proc.stdout.readline().strip()
    assert name, "publisher never came up"
    st = workers.SharedRouterState(name=name)
    try:
        proc.kill()
        proc.wait(10)
        seen = []
        r = workers.WorkerRouter(
            st, 0,
            transport=lambda port, *a: seen.append(port) or (200, OK))
        body = json.dumps(
            {"tokens": [list(range(64))], "max_new": 4}).encode()
        for _ in range(3):
            status, _ = r.forward("g", body)
            assert status == 200
        assert seen == [1002, 1002, 1002]
        assert st.gateway_counters(0)["affinityHits"] == 3
    finally:
        proc.kill()
        st.created = True          # the creator died; this side unlinks
        st.close(unlink=True)


# ------------------------------------- disaggregation e2e over real mocks

@pytest.fixture()
def mock_pair(tmp_path):
    procs = []

    def spawn(sub):
        d = tmp_path / sub
        d.mkdir()
        p, port = _spawn_mock(d, "--decode-ms", "2", "--kv-ttl", "1.0")
        procs.append(p)
        return port

    yield spawn("pre"), spawn("dec")
    for p in procs:
        p.kill()
        p.wait(10)


def _disagg_gateway(pre_port, dec_port) -> Gateway:
    gw = _bare_gateway(None, poolPolicy="disaggregated", deadlineMs=8000)
    gw.replicas = {"gr0": _ready_replica("gr0", 0, pre_port, slots=4),
                   "gr1": _ready_replica("gr1", 1, dec_port, slots=4)}
    return gw


def test_disagg_handoff_e2e(mock_pair):
    pre_port, dec_port = mock_pair
    gw = _disagg_gateway(pre_port, dec_port)
    toks = list(range(96))
    status, payload = gw.forward(
        json.dumps({"tokens": [toks], "max_new": 4}).encode())
    assert status == 200
    row = json.loads(payload)["data"]["tokens"][0]
    # byte-compatible with a single-shot reply: prompt + max_new tokens
    assert row[:96] == toks and len(row) == 100
    assert gw.kv_handoffs == 1
    assert all(r.inflight == 0 for r in gw.replicas.values())
    pre_pc, dec_pc = _prefix_cache(pre_port), _prefix_cache(dec_port)
    assert pre_pc["kvFetches"] == 1 and pre_pc["kvExports"] == 0
    assert dec_pc["handoffsIn"] == 1
    # a short prompt stays below the bar: whole request, shared path
    status, _ = gw.forward(
        json.dumps({"tokens": [toks[:32]], "max_new": 2}).encode())
    assert status == 200 and gw.kv_handoffs == 1


def test_crash_mid_handoff_releases_claims_and_leaks_no_kv(mock_pair):
    """kvhandoff.after_prefill: the daemon dies with the prompt KV
    exported and the decode phase never dispatched. Both claims release
    on the unwind, the orphaned export TTL-purges (zero leaked KV), and
    the same request then completes whole."""
    pre_port, dec_port = mock_pair
    gw = _disagg_gateway(pre_port, dec_port)
    body = json.dumps({"tokens": [list(range(96))],
                       "max_new": 4}).encode()
    faults.arm("kvhandoff.after_prefill")
    with pytest.raises(InjectedCrash):
        gw.forward(body)
    faults.disarm_all()
    assert all(r.inflight == 0 for r in gw.replicas.values())
    assert _prefix_cache(pre_port)["kvExports"] == 1   # orphaned export
    deadline = time.time() + 8
    left = 1
    while time.time() < deadline and left:
        time.sleep(0.2)
        left = _prefix_cache(pre_port)["kvExports"]
    assert left == 0, "orphaned KV export never TTL-purged"
    status, _ = gw.forward(body)
    assert status == 200 and gw.kv_handoffs == 1
