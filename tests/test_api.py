"""REST API integration tests: full stack over HTTP against the mock backend
(the reference's only test story was manual API testing against `-tags mock`;
this automates it — SURVEY §4)."""

import http.client
import json

import pytest

from gpu_docker_api_tpu.server.app import App
from gpu_docker_api_tpu.topology import make_topology


@pytest.fixture()
def app(tmp_path):
    a = App(state_dir=str(tmp_path / "state"), backend="mock",
            addr="127.0.0.1:0", port_range=(43000, 43100),
            topology=make_topology("v4-32"), api_key="", cpu_cores=16)
    a.start()
    yield a
    a.stop()


def call(app, method, path, body=None, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", app.server.port, timeout=10)
    payload = json.dumps(body) if body is not None else None
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    conn.request(method, path, payload, hdrs)
    resp = conn.getresponse()
    raw = resp.read()
    conn.close()
    return resp.status, json.loads(raw) if raw else None


def test_ping(app):
    status, body = call(app, "GET", "/ping")
    assert status == 200
    assert body["code"] == 200
    assert body["data"]["status"] == "pong"


def test_run_patch_rollback_flow(app):
    # run with 1 chip
    status, body = call(app, "POST", "/api/v1/replicaSet", {
        "imageName": "ubuntu:22.04", "replicaSetName": "train",
        "tpuCount": 1, "cpuCount": 2, "memory": "8GB",
        "containerPorts": ["8888"]})
    assert body["code"] == 200, body
    assert body["data"]["name"] == "train-1"
    assert len(body["data"]["tpuChips"]) == 1

    # patch 1 -> 4 chips (BASELINE config 3 control-plane path)
    _, body = call(app, "PATCH", "/api/v1/replicaSet/train",
                   {"tpuPatch": {"tpuCount": 4}})
    assert body["code"] == 200, body
    assert body["data"]["name"] == "train-2"
    assert len(body["data"]["tpuChips"]) == 4

    # history shows both versions
    _, body = call(app, "GET", "/api/v1/replicaSet/train/history")
    assert [h["version"] for h in body["data"]["history"]] == [2, 1]

    # rollback to v1 = forward-write v3
    _, body = call(app, "PATCH", "/api/v1/replicaSet/train/rollback", {"version": 1})
    assert body["code"] == 200, body
    assert body["data"]["version"] == 3
    assert len(body["data"]["tpuChips"]) == 1

    # info reflects v3
    _, body = call(app, "GET", "/api/v1/replicaSet/train")
    assert body["data"]["info"]["version"] == 3
    assert body["data"]["info"]["running"] is True


def test_validation_codes(app):
    cases = [
        ({"replicaSetName": "x"}, 1001),                       # no image
        ({"imageName": "img"}, 1002),                          # no name
        ({"imageName": "img", "replicaSetName": "a-b"}, 1003), # dash
        ({"imageName": "img", "replicaSetName": "x", "tpuCount": -1}, 1012),
        ({"imageName": "img", "replicaSetName": "x", "cpuCount": -1}, 1024),
        ({"imageName": "img", "replicaSetName": "x", "memory": "8XB"}, 1025),
    ]
    for body, code in cases:
        _, resp = call(app, "POST", "/api/v1/replicaSet", body)
        assert resp["code"] == code, (body, resp)


def test_run_duplicate_and_shortage_codes(app):
    call(app, "POST", "/api/v1/replicaSet",
         {"imageName": "i", "replicaSetName": "dup"})
    _, resp = call(app, "POST", "/api/v1/replicaSet",
                   {"imageName": "i", "replicaSetName": "dup"})
    assert resp["code"] == 1008
    _, resp = call(app, "POST", "/api/v1/replicaSet",
                   {"imageName": "i", "replicaSetName": "big", "tpuCount": 99})
    assert resp["code"] == 1013


def test_gpu_count_alias(app):
    # reference clients send gpuCount; accepted as tpuCount
    _, resp = call(app, "POST", "/api/v1/replicaSet",
                   {"imageName": "i", "replicaSetName": "legacy", "gpuCount": 2})
    assert resp["code"] == 200
    assert len(resp["data"]["tpuChips"]) == 2


def test_lifecycle_endpoints(app):
    call(app, "POST", "/api/v1/replicaSet",
         {"imageName": "i", "replicaSetName": "lc", "tpuCount": 2})
    _, resp = call(app, "PATCH", "/api/v1/replicaSet/lc/pause")
    assert resp["code"] == 200
    _, resp = call(app, "PATCH", "/api/v1/replicaSet/lc/continue")
    assert resp["code"] == 200
    _, resp = call(app, "PATCH", "/api/v1/replicaSet/lc/stop")
    assert resp["code"] == 200
    _, resp = call(app, "GET", "/api/v1/resources/tpus")
    assert resp["data"]["tpus"]["freeCount"] == 16  # released
    _, resp = call(app, "PATCH", "/api/v1/replicaSet/lc/restart")
    assert resp["code"] == 200
    assert resp["data"]["name"] == "lc-2"
    _, resp = call(app, "DELETE", "/api/v1/replicaSet/lc")
    assert resp["code"] == 200
    _, resp = call(app, "GET", "/api/v1/replicaSet/lc")
    assert resp["code"] == 1016


def test_execute_and_commit_endpoints(app):
    call(app, "POST", "/api/v1/replicaSet",
         {"imageName": "i", "replicaSetName": "ex"})
    _, resp = call(app, "POST", "/api/v1/replicaSet/ex/execute",
                   {"cmd": ["echo", "hello"]})
    assert resp["code"] == 200
    assert "echo hello" in resp["data"]["output"]
    _, resp = call(app, "POST", "/api/v1/replicaSet/ex/commit",
                   {"newImageName": "snap:v1"})
    assert resp["code"] == 200
    assert resp["data"]["imageId"].startswith("sha256:")


def test_volume_endpoints(app):
    _, resp = call(app, "POST", "/api/v1/volumes", {"name": "vol", "size": "1GB"})
    assert resp["code"] == 200
    assert resp["data"]["name"] == "vol-1"
    _, resp = call(app, "POST", "/api/v1/volumes", {"name": "bad-name", "size": "1GB"})
    assert resp["code"] == 1108
    _, resp = call(app, "POST", "/api/v1/volumes", {"name": "/abs", "size": "1GB"})
    assert resp["code"] == 1109
    _, resp = call(app, "POST", "/api/v1/volumes", {"name": "vol2", "size": "9QB"})
    assert resp["code"] == 1106
    _, resp = call(app, "PATCH", "/api/v1/volumes/vol/size", {"size": "2GB"})
    assert resp["code"] == 200
    assert resp["data"]["name"] == "vol-2"
    _, resp = call(app, "PATCH", "/api/v1/volumes/vol/size", {"size": "2GB"})
    assert resp["code"] == 1105
    _, resp = call(app, "GET", "/api/v1/volumes/vol")
    assert resp["data"]["info"]["volumeName"] == "vol-2"
    _, resp = call(app, "GET", "/api/v1/volumes/vol/history")
    assert [h["version"] for h in resp["data"]["history"]] == [2, 1]
    _, resp = call(app, "DELETE", "/api/v1/volumes/vol")
    assert resp["code"] == 200
    _, resp = call(app, "GET", "/api/v1/volumes/vol")
    assert resp["code"] == 1110


def test_resources_endpoints(app):
    _, resp = call(app, "GET", "/api/v1/resources/tpus")
    tpus = resp["data"]["tpus"]
    assert tpus["topology"]["acceleratorType"] == "v4-32"
    assert len(tpus["chips"]) == 16
    _, resp = call(app, "GET", "/api/v1/resources/gpus")  # legacy alias
    assert resp["data"]["tpus"]["freeCount"] == 16
    _, resp = call(app, "GET", "/api/v1/resources/cpus")
    assert resp["data"]["cpus"]["totalCount"] > 0
    _, resp = call(app, "GET", "/api/v1/resources/ports")
    assert resp["data"]["ports"]["range"] == [43000, 43100]


def test_unknown_route_404(app):
    status, body = call(app, "GET", "/api/v1/nope")
    assert status == 404


def test_invalid_json_body(app):
    conn = http.client.HTTPConnection("127.0.0.1", app.server.port, timeout=10)
    conn.request("POST", "/api/v1/replicaSet", "{not json",
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    body = json.loads(resp.read())
    conn.close()
    assert body["code"] == 1000


def test_cors_preflight(app):
    conn = http.client.HTTPConnection("127.0.0.1", app.server.port, timeout=10)
    conn.request("OPTIONS", "/api/v1/replicaSet", headers={"Origin": "http://x"})
    resp = conn.getresponse()
    resp.read()
    assert resp.status == 204
    assert resp.getheader("Access-Control-Allow-Origin") == "http://x"
    conn.close()


def test_auth_when_key_set(tmp_path):
    a = App(state_dir=str(tmp_path / "s2"), backend="mock", addr="127.0.0.1:0",
            topology=make_topology("v5p-8"), api_key="secret")
    a.start()
    try:
        _, resp = call(a, "GET", "/api/v1/resources/tpus")
        assert resp["code"] == 403
        _, resp = call(a, "GET", "/api/v1/resources/tpus",
                       headers={"Authorization": "Bearer secret"})
        assert resp["code"] == 200
    finally:
        a.stop()


def test_crash_resume(tmp_path):
    """Reference §3.4: state recovery = read store, else probe. Kill the app,
    boot a new one on the same state dir, everything survives."""
    state = str(tmp_path / "s3")
    a = App(state_dir=state, backend="mock", addr="127.0.0.1:0",
            topology=make_topology("v4-32"), api_key="")
    a.start()
    call(a, "POST", "/api/v1/replicaSet",
         {"imageName": "i", "replicaSetName": "persist", "tpuCount": 4})
    call(a, "PATCH", "/api/v1/replicaSet/persist", {"tpuPatch": {"tpuCount": 2}})
    a.stop()

    b = App(state_dir=state, backend="mock", addr="127.0.0.1:0", api_key="")
    b.start()
    try:
        _, resp = call(b, "GET", "/api/v1/resources/tpus")
        st = resp["data"]["tpus"]
        assert st["topology"]["acceleratorType"] == "v4-32"  # from store
        assert st["freeCount"] == 14                         # 2 chips still held
        _, resp = call(b, "GET", "/api/v1/replicaSet/persist/history")
        assert [h["version"] for h in resp["data"]["history"]] == [2, 1]
        # version counter continues: next mutation is v3
        _, resp = call(b, "PATCH", "/api/v1/replicaSet/persist",
                       {"memoryPatch": {"memory": "1GB"}})
        assert resp["data"]["version"] == 3
    finally:
        b.stop()


def test_events_endpoint(app):
    call(app, "POST", "/api/v1/replicaSet",
         {"imageName": "i", "replicaSetName": "evt", "tpuCount": 1})
    call(app, "PATCH", "/api/v1/replicaSet/evt", {"tpuPatch": {"tpuCount": 2}})
    _, resp = call(app, "GET", "/api/v1/events")
    evts = resp["data"]["events"]
    assert len(evts) >= 2
    ops = [e["op"] for e in evts]
    assert any(op.startswith("POST /api/v1/replicaSet") for op in ops)
    assert any(op.startswith("PATCH") for op in ops)
    # target filter narrows to the named replicaSet's ops
    _, resp = call(app, "GET", "/api/v1/events?target=evt")
    evts_t = resp["data"]["events"]
    assert evts_t and all(e["target"] == "evt" for e in evts_t)
    evts = evts_t
    # the patch's rolling replace emits an internal replace.copied event
    # (no requestId — it is not an HTTP request) with the copy/downtime record
    copied = [e for e in evts if e["op"] == "replace.copied"]
    assert copied and all(e["downtimeMs"] >= 0 for e in copied)
    http_evts = [e for e in evts if " /" in e["op"]]
    assert http_evts
    assert all(e["durationMs"] >= 0 and e["requestId"] for e in http_evts)
    assert all(e["code"] == 200 for e in evts)
    # events.jsonl persisted on disk
    import os
    assert os.path.exists(os.path.join(app.state_dir, "events.jsonl"))


# --------------------------------------------------- metrics + openapi

def _call_raw(app, path):
    conn = http.client.HTTPConnection("127.0.0.1", app.server.port, timeout=10)
    conn.request("GET", path)
    resp = conn.getresponse()
    raw = resp.read()
    ctype = resp.getheader("Content-Type")
    conn.close()
    return resp.status, ctype, raw


def test_metrics_prometheus_text(app):
    call(app, "POST", "/api/v1/replicaSet",
         {"imageName": "img", "replicaSetName": "m1", "tpuCount": 2,
          "cpuCount": 1})
    status, ctype, raw = _call_raw(app, "/metrics")
    assert status == 200
    assert ctype.startswith("text/plain")
    text = raw.decode()
    assert 'tdapi_tpu_chips{state="used"} 2' in text
    assert 'tdapi_cpu_cores{state="used"} 1' in text
    assert "tdapi_replicasets 1" in text
    assert "tdapi_workqueue_pending" in text


def test_openapi_served(app):
    status, ctype, raw = _call_raw(app, "/openapi.json")
    assert status == 200
    spec = json.loads(raw)
    assert "/api/v1/replicaSet" in spec["paths"]
    assert "openapi" in spec


def test_store_maintenance_bounds_wal_across_restart(tmp_path):
    """VERDICT r1 missing #5: the App must invoke store maintenance so the
    WAL stays bounded over the service lifetime, while container history
    survives compaction + restart."""
    import os

    state = str(tmp_path / "maint")
    a = App(state_dir=state, backend="mock", addr="127.0.0.1:0",
            port_range=(43200, 43300), topology=make_topology("v4-8"),
            api_key="", cpu_cores=16, store_maint_records=50)
    a.start()
    try:
        status, body = call(a, "POST", "/api/v1/replicaSet",
                            {"imageName": "ubuntu:22.04",
                             "replicaSetName": "churn", "tpuCount": 1})
        assert body["code"] == 200
        # hammer mutations: each patch rolls a new version (store churn)
        for i in range(12):
            status, body = call(a, "PATCH", "/api/v1/replicaSet/churn",
                                {"cpuPatch": {"cpuCount": 1 + (i % 2)}})
            assert body["code"] == 200
        a.wq.join()
        # trigger: hammering crossed the 50-record threshold; wait for the
        # janitor (2s poll), or force one pass to keep the test fast
        stats = a.maintain_store()
        assert stats["wal_records"] < 300
        wal = os.path.join(state, "state.wal")
        with open(wal) as f:
            assert sum(1 for _ in f) < 300
        status, body = call(a, "GET", "/api/v1/replicaSet/churn/history")
        hist_before = body["data"]["history"]
        assert len(hist_before) == 13            # run + 12 patches
    finally:
        a.stop()

    # restart on the rewritten WAL: history + latest state intact
    b = App(state_dir=state, backend="mock", addr="127.0.0.1:0",
            port_range=(43200, 43300), api_key="", cpu_cores=16,
            store_maint_records=50)
    b.start()
    try:
        status, body = call(b, "GET", "/api/v1/replicaSet/churn/history")
        assert body["code"] == 200
        assert len(body["data"]["history"]) == 13
        status, body = call(b, "GET", "/api/v1/replicaSet/churn")
        assert body["data"]["info"]["version"] == 13
    finally:
        b.stop()
