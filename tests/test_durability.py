"""Durable state plane sweep (`durability` marker, `make
verify-durability`).

Five layers, cheapest first:

- UNIT: walio frame/scan/scrub classify every WAL shape — clean v1,
  torn tail (truncate + continue), mid-log corruption (typed refusal
  pointing at the scrub tool), v0 legacy.
- SWEEP: kill-at-any-point (live torn_tail disk fault at every append
  index) plus offline torn-tail/bitflip damage — replay must land on
  the SAME observable state in BOTH engines, byte-identical WALs.
- BACKUP: point-in-time snapshot/restore round-trips preserve exact
  revision history (cr/ver counters, tombstones) within and ACROSS
  engines, via the `store backup|restore|scrub` CLI too.
- FAULTS: ENOSPC latches the store read-only (memory-ahead-of-disk),
  surfaces as 503 + Retry-After + a `store.read_only` event at the app
  layer, and heals through the timed re-probe.
- REPLICATION: a StandbyReplicator tails a live daemon gap-free,
  resyncs from one atomic snapshot after a WatchCompacted, and the
  promote model's R2 checker is proven live on its seeded mutant; the
  acceptance e2e (SIGKILL the primary, standby promotes behind the
  fencing epoch with zero acked-revision loss) closes the file.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from gpu_docker_api_tpu import faults
from gpu_docker_api_tpu.faults import InjectedCrash
from gpu_docker_api_tpu.replication import StandbyReplicator, resource_key
from gpu_docker_api_tpu.server.app import App
from gpu_docker_api_tpu.store import (
    StoreReadOnlyError, WalCorruptError, native_available, open_store,
    walio,
)
from gpu_docker_api_tpu.topology import make_topology
from tools.tdcheck import models
from tools.tdcheck.sched import InvariantViolation

from conftest import wait_for

pytestmark = pytest.mark.durability

ENGINES = ["python", "native"] if native_available() else ["python"]
BOTH = pytest.mark.parametrize("engine", ENGINES)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.disarm_all()
    faults.disarm_disk_faults()
    yield
    faults.disarm_all()
    faults.disarm_disk_faults()


def observable(s):
    return {
        "rev": s.revision,
        "range": [(kv.key, kv.value, kv.create_revision, kv.mod_revision,
                   kv.version) for kv in s.range("/")],
    }


# ------------------------------------------------------------ walio unit

def test_frame_roundtrip():
    payload = b'{"op":"put","k":"/a","v":"x","r":1}'
    line = walio.frame(payload)
    assert line.endswith(b"\n")
    assert walio.parse_frame(line) == payload


def test_parse_frame_rejects_damage():
    line = walio.frame(b'{"op":"put"}')
    assert walio.parse_frame(line[:-5]) is None          # torn
    flipped = line[:15] + bytes([line[15] ^ 0x01]) + line[16:]
    assert walio.parse_frame(flipped) is None            # crc mismatch


def _v1_wal(path, payloads):
    with open(path, "wb") as f:
        f.write(walio.MAGIC)
        for p in payloads:
            f.write(walio.frame(p))


def test_scan_clean_torn_and_midlog(tmp_path):
    p = str(tmp_path / "w.wal")
    recs = [b'{"op":"put","k":"/a","v":"1","r":1}',
            b'{"op":"put","k":"/b","v":"2","r":2}',
            b'{"op":"put","k":"/a","v":"3","r":3}']
    _v1_wal(p, recs)
    s = walio.scan(p)
    assert (s.fmt, len(s.payloads), s.truncate_to, s.corrupt_at) == \
        (1, 3, None, None)

    # torn tail: bad frames only at the end -> truncate point, records
    # before it all served
    size = os.path.getsize(p)
    with open(p, "ab") as f:
        f.write(walio.frame(recs[0])[:10])
    s = walio.scan(p)
    assert len(s.payloads) == 3 and s.truncate_to == size
    assert s.corrupt_at is None

    # mid-log: a valid frame AFTER the bad one makes it corruption, not
    # a crash artifact — typed refusal pointing at the scrub tool. The
    # bad frame must be newline-terminated (a bitflip, not a tear) or
    # the scanner correctly merges it with what follows as one torn line
    good = walio.frame(recs[1])
    bad = good[:15] + bytes([good[15] ^ 0x01]) + good[16:]
    with open(p, "wb") as f:
        f.write(walio.MAGIC)
        for r in recs:
            f.write(walio.frame(r))
        f.write(bad)
        f.write(walio.frame(recs[1]))
    s = walio.scan(p)
    assert s.corrupt_at == size
    with pytest.raises(WalCorruptError) as ei:
        open_store(wal_path=p, engine="python")
    assert "scrub" in str(ei.value)


def test_scan_v0_legacy(tmp_path):
    p = str(tmp_path / "v0.wal")
    with open(p, "w") as f:
        f.write('{"op": "put", "k": "/a", "v": "x", "r": 1}\n')
    s = walio.scan(p)
    assert s.fmt == 0 and len(s.payloads) == 1


def test_scrub_reports(tmp_path):
    p = str(tmp_path / "w.wal")
    _v1_wal(p, [b'{"op":"put","k":"/a","v":"1","r":1}'])
    rep = walio.scrub(p)
    assert rep["ok"] and rep["format"] == 1 and rep["records"] == 1
    # damage to the FINAL record is indistinguishable from a crash
    # mid-write: scrub reports it as a (recoverable) torn tail
    faults.corrupt_wal(p, "bitflip", line_at=1.0)
    rep = walio.scrub(p)
    assert rep["ok"] and "tornTailAt" in rep and rep["records"] == 0


# --------------------------------------- kill / corruption replay sweeps

N_OPS = 8


def _mutate(s, i):
    if i % 4 == 3:
        s.delete(f"/k{(i - 1) % 3}")
    else:
        s.put(f"/k{i % 3}", f"v{i}")


def _replay_both(tmp_path, src_path, tag):
    """Replay one damaged-then-healed WAL in both engines; identical
    observable state and identical post-replay WAL bytes."""
    outs = {}
    for engine in ENGINES:
        p = str(tmp_path / f"replay-{tag}-{engine}.wal")
        with open(src_path, "rb") as f:
            data = f.read()
        with open(p, "wb") as f:
            f.write(data)
        s = open_store(wal_path=p, engine=engine)
        outs[engine] = (observable(s), open(p, "rb").read())
        s.close()
    first = outs[ENGINES[0]]
    for engine in ENGINES[1:]:
        assert outs[engine] == first, f"engine divergence at {tag}"
    return first[0]


def test_kill_at_any_append_replays_identically(tmp_path):
    """Live torn_tail at every append index: the writer dies mid-write,
    replay truncates the torn frame and keeps the prefix — in both
    engines, landing on the same state."""
    for kill_at in range(N_OPS):
        p = str(tmp_path / f"kill{kill_at}.wal")
        s = open_store(wal_path=p, engine="python")
        faults.arm_disk_fault(f"kill{kill_at}.wal:torn_tail:{kill_at}")
        try:
            with pytest.raises(InjectedCrash):
                for i in range(N_OPS):
                    _mutate(s, i)
                raise AssertionError("disk fault never fired")
        finally:
            faults.disarm_disk_faults()
        # abandon without close (the crash); replay both engines
        state = _replay_both(tmp_path, p, f"kill{kill_at}")
        assert state["rev"] <= kill_at  # torn record never acked


@BOTH
@pytest.mark.parametrize("mode", ["torn_tail", "bitflip"])
def test_offline_tail_damage_truncates_both_engines(tmp_path, engine,
                                                    mode):
    p = str(tmp_path / "w.wal")
    s = open_store(wal_path=p, engine=engine)
    for i in range(N_OPS):
        _mutate(s, i)
    undamaged = observable(s)
    s.close()
    faults.corrupt_wal(p, mode, line_at=1.0)
    state = _replay_both(tmp_path, p, f"{engine}-{mode}")
    assert state["rev"] == undamaged["rev"] - 1


@BOTH
def test_midlog_bitflip_refused_both_engines(tmp_path, engine):
    p = str(tmp_path / "w.wal")
    s = open_store(wal_path=p, engine=engine)
    for i in range(N_OPS):
        _mutate(s, i)
    s.close()
    faults.corrupt_wal(p, "bitflip", line_at=0.4)
    for eng in ENGINES:
        with pytest.raises(WalCorruptError):
            open_store(wal_path=p, engine=eng)
    assert not walio.scrub(p)["ok"]


@BOTH
def test_v0_wal_replays_and_maintain_upgrades(tmp_path, engine):
    p = str(tmp_path / "v0.wal")
    with open(p, "w") as f:
        f.write('{"op": "put", "k": "/a", "v": "x", "r": 1}\n')
        f.write('{"op": "put", "k": "/b", "v": "y", "r": 2}\n')
        f.write('{"op": "del", "k": "/a", "r": 3}\n')
    s = open_store(wal_path=p, engine=engine)
    assert s.wal_format == 0
    assert s.revision == 3 and s.get("/a") is None
    s.put("/c", "z")                  # appended in v0 (no mixed files)
    s.maintain()                      # every rewrite upgrades to v1
    assert s.wal_format == 1
    state = observable(s)
    s.close()
    assert open(p, "rb").read().startswith(walio.MAGIC)
    s2 = open_store(wal_path=p, engine=engine)
    assert observable(s2) == state
    s2.close()


# ------------------------------------------------- backup/restore + CLI

def _seed(s):
    s.put("/a", "1")
    s.put("/b", "2")
    s.put("/a", "3")
    s.delete("/b")
    s.put("/c", "4")
    return s.revision             # 5


@BOTH
def test_backup_restore_roundtrip(tmp_path, engine):
    p = str(tmp_path / "src.wal")
    s = open_store(wal_path=p, engine=engine)
    rev = _seed(s)
    want = observable(s)
    out = s.backup(str(tmp_path / "bk.wal"))
    assert out["revision"] == rev
    s.close()
    # restore = open the backup file as a WAL, in EITHER engine
    for eng in ENGINES:
        r = open_store(wal_path=str(tmp_path / "bk.wal"), engine=eng)
        got = observable(r)
        assert got == want, f"restore diverged in {eng}"
        # tombstone replayed: /b deleted but its revision retained
        assert r.get("/b") is None
        r.close()


@BOTH
def test_backup_point_in_time_and_validation(tmp_path, engine):
    s = open_store(wal_path=str(tmp_path / "src.wal"), engine=engine)
    _seed(s)
    s.backup(str(tmp_path / "bk3.wal"), revision=3)
    with pytest.raises(ValueError):
        s.backup(str(tmp_path / "bad.wal"), revision=99)
    s.close()
    r = open_store(wal_path=str(tmp_path / "bk3.wal"), engine="python")
    assert r.revision == 3
    assert r.get("/a").value == "3" and r.get("/b").value == "2"
    assert r.get("/c") is None
    # lifetime counters preserved exactly, not re-minted
    assert r.get("/a").create_revision == 1 and r.get("/a").version == 2
    r.close()


def test_store_cli_backup_restore_scrub(tmp_path):
    sd = tmp_path / "sd"
    s = open_store(wal_path=str(sd / "state.wal"), engine="python")
    rev = _seed(s)
    want = observable(s)
    s.close()

    def cli(*a):
        return subprocess.run(
            [sys.executable, "-m", "gpu_docker_api_tpu.cli", "store", *a],
            capture_output=True, text=True, cwd="/root/repo")

    r = cli("scrub", str(sd / "state.wal"))
    assert r.returncode == 0 and json.loads(r.stdout)["ok"]
    r = cli("backup", "-s", str(sd), "-o", str(tmp_path / "bk.wal"))
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout)["revision"] == rev
    r = cli("restore", "-s", str(tmp_path / "sd2"),
            "--from", str(tmp_path / "bk.wal"))
    assert r.returncode == 0, r.stderr
    # refuses to clobber without --force
    r = cli("restore", "-s", str(tmp_path / "sd2"),
            "--from", str(tmp_path / "bk.wal"))
    assert r.returncode == 1 and "--force" in r.stderr
    # refuses a corrupt backup outright
    faults.corrupt_wal(str(tmp_path / "bk.wal"), "bitflip", line_at=0.4)
    r = cli("restore", "-s", str(tmp_path / "sd3"),
            "--from", str(tmp_path / "bk.wal"), "--force")
    assert r.returncode == 1 and "corrupt" in r.stderr
    s2 = open_store(wal_path=str(tmp_path / "sd2" / "state.wal"),
                    engine="python")
    assert observable(s2) == want
    s2.close()


# ------------------------------------------------ put_at / delete_at

@BOTH
def test_put_at_delete_at_idempotent(tmp_path, engine):
    s = open_store(wal_path=str(tmp_path / "r.wal"), engine=engine)
    assert s.put_at("/a", "x", 5, create_revision=5, version=1)
    assert s.revision == 5
    # replay below the head is a no-op (the replicator's crash-replay
    # guarantee), not a new revision
    assert not s.put_at("/a", "x", 5)
    assert not s.put_at("/a", "stale", 4)
    assert s.revision == 5 and s.get("/a").value == "x"
    assert s.delete_at("/a", 7)
    assert s.revision == 7 and s.get("/a") is None
    assert not s.delete_at("/a", 7)
    # counters pinned exactly on a fresh key
    assert s.put_at("/b", "y", 9, create_revision=2, version=6)
    kv = s.get("/b")
    assert (kv.create_revision, kv.version) == (2, 6)
    s.close()


# ----------------------------------------------- ENOSPC -> read-only 503

def make_app(tmp_path, **kw):
    a = App(state_dir=str(tmp_path / "state"), backend="mock",
            addr="127.0.0.1:0", port_range=(43600, 43700),
            topology=make_topology("v4-32"), api_key="", cpu_cores=16,
            store_engine="python", **kw)
    a.start()
    return a


def _post(app, path, body):
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", app.server.port,
                                      timeout=10)
    conn.request("POST", path, json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    out = json.loads(resp.read())
    headers = dict(resp.getheaders())
    conn.close()
    return resp.status, out, headers


def test_enospc_latches_read_only_503_then_heals(tmp_path, monkeypatch):
    monkeypatch.setattr(
        "gpu_docker_api_tpu.store.mvcc.MVCCStore.READ_ONLY_PROBE_S", 0.2)
    app = make_app(tmp_path)
    try:
        status, out, _ = _post(app, "/api/v1/volumes",
                               {"name": "vol", "size": "1GB"})
        assert status == 200 and out["code"] == 200
        faults.arm_disk_fault("state.wal:enospc")
        status, out, headers = _post(app, "/api/v1/volumes",
                                     {"name": "ro", "size": "1GB"})
        assert status == 503, out
        assert int(headers["Retry-After"]) >= 1
        assert "ENOSPC" in out["data"]["reason"] or \
            "No space" in out["data"]["reason"]
        assert any(e["op"] == "store.read_only"
                   for e in app.events.recent(50))
        # latched: the next mutation is denied without touching disk
        status, _, _ = _post(app, "/api/v1/volumes",
                             {"name": "ro2", "size": "1GB"})
        assert status == 503
        # the disk recovers; the timed re-probe heals the latch
        faults.disarm_disk_faults()
        time.sleep(0.25)
        status, out, _ = _post(app, "/api/v1/volumes",
                               {"name": "ok", "size": "1GB"})
        assert status == 200 and out["code"] == 200, out
        assert app.store.read_only is None
    finally:
        faults.disarm_disk_faults()
        app.stop()


# ----------------------------------------------------------- replication

def test_replicator_tails_live_daemon(tmp_path):
    a = make_app(tmp_path)
    b_dir = tmp_path / "replB"
    try:
        _post(a, "/api/v1/volumes", {"name": "vol", "size": "1GB"})
        r = StandbyReplicator(f"127.0.0.1:{a.server.port}", str(b_dir),
                              engine="python")
        pk = a.store.get(resource_key("volumes", "vol"))
        r.start()
        try:
            wait_for(lambda: r.horizon >= pk.mod_revision,
                     msg="replica caught up")
        finally:
            r.stop()
        kv = r.store.get(resource_key("volumes", "vol"))
        # stop() closed the replica store; reopen to assert durability
        r2 = StandbyReplicator(f"127.0.0.1:{a.server.port}", str(b_dir),
                               engine="python")
        kv = r2.get_record("volumes", "vol")
        assert kv is not None and kv.mod_revision == pk.mod_revision
        assert kv.value == pk.value
        assert r2.horizon >= pk.mod_revision
        st = r2.describe()
        assert st["peer"].endswith(str(a.server.port))
        r2.store.close()
    finally:
        a.stop()


def test_replicator_gap_forces_full_resync(tmp_path):
    a = make_app(tmp_path)
    try:
        _post(a, "/api/v1/volumes", {"name": "vol", "size": "1GB"})
        r = StandbyReplicator(f"127.0.0.1:{a.server.port}",
                              str(tmp_path / "replB"), engine="python")
        # a horizon AHEAD of the peer's head is a foreign revision
        # space — the watch answers WatchCompacted, the replicator must
        # resync from one atomic snapshot, not stream garbage
        r.horizon = a.store.revision + 1000
        r.run_once()
        assert r.resyncs_total == 1
        kv = r.get_record("volumes", "vol")
        pk = a.store.get(resource_key("volumes", "vol"))
        assert kv is not None and kv.mod_revision == pk.mod_revision
        assert kv.create_revision == pk.create_revision
        assert kv.version == pk.version
        r.store.close()
    finally:
        a.stop()


# ------------------------------------------------------ promote-on-loss

def test_promote_model_r2_mutant_is_caught():
    """The R1 mutant is proven by `make lint`'s CLI gate; the R2 mutant
    (promote after a LOST steal) is proven here, mirroring the lease
    model's NoExpiry split."""
    with pytest.raises(InvariantViolation) as ei:
        models.sweep_promote(max_schedules=800,
                             member_cls=models.BrokenPromoteMember)
    assert "R2" in str(ei.value.message)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_sigkill_primary_standby_promotes(tmp_path):
    """The acceptance scenario: daemon A (a fleet member) owns a
    replicaSet and dies by SIGKILL; daemon B — arbiter host, standby
    replicator tailing A — must steal the orphan grant behind a fresh
    fencing epoch AND install A's replicated record, losing no
    acknowledged revision at-or-below the replicated horizon."""
    ttl = 1.0
    port_a = free_port()
    b = make_app(tmp_path, fleet_member="b", fleet_ttl=ttl,
                 repl_peer=f"127.0.0.1:{port_a}")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("APIKEY", None)
    alog = open(tmp_path / "a.log", "wb")
    proc = subprocess.Popen(
        [sys.executable, "-m", "gpu_docker_api_tpu.cli",
         "-a", f"127.0.0.1:{port_a}", "-s", str(tmp_path / "a"),
         "-b", "mock", "-t", "v4-32", "-p", "43600-43700",
         "--health-interval", "0", "--warm-pool", "0", "--cpu-cores", "16",
         "--fleet-member", "a",
         "--fleet-host", f"127.0.0.1:{b.server.port}",
         "--fleet-ttl", str(ttl)],
        env=env, stdout=alog, stderr=alog, cwd="/root/repo")
    try:
        import http.client

        from gpu_docker_api_tpu.federation import HashRing

        def ping_a():
            try:
                conn = http.client.HTTPConnection("127.0.0.1", port_a,
                                                  timeout=2)
                conn.request("GET", "/ping")
                ok = conn.getresponse().status == 200
                conn.close()
                return ok
            except OSError:
                return False
        wait_for(ping_a, timeout=60, msg="daemon a serving")
        wait_for(lambda: {m["member"]
                          for m in b.fleet.arbiter.members()} == {"a", "b"},
                 timeout=15, msg="a joined the fleet")

        # a replicaSet name A's ring slice owns
        i = 0
        while HashRing.owner_of(f"containers/rs{i}", {"a", "b"}) != "a":
            i += 1
        name = f"rs{i}"
        conn = http.client.HTTPConnection("127.0.0.1", port_a, timeout=10)
        conn.request("POST", "/api/v1/replicaSet", json.dumps({
            "imageName": "ubuntu:22.04", "replicaSetName": name,
            "tpuCount": 1, "cpuCount": 1, "memory": "1GB"}),
            {"Content-Type": "application/json"})
        out = json.loads(conn.getresponse().read())
        conn.close()
        assert out["code"] == 200, out

        # the write is acked to the client; B's replica must catch up
        # to it before we murder A (the warm standby steady state)
        wait_for(lambda: b.replicator is not None
                 and b.replicator.get_record("containers", name)
                 is not None,
                 timeout=20, msg="replica caught the acked record")
        replica_kv = b.replicator.get_record("containers", name)
        assert b.replicator.horizon >= replica_kv.mod_revision
        grant_before = {g["name"]: g for g in b.fleet.arbiter.grants()}
        assert grant_before[name]["holder"] == "a"

        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)

        # B's heartbeat sweep: steal behind a fresh epoch, promote the
        # replicated record, adopt
        wait_for(lambda: ("containers", name) in b.fleet.member.owned,
                 timeout=15 * ttl, msg="standby takeover")
        grants = {g["name"]: g for g in b.fleet.arbiter.grants()}
        assert grants[name]["holder"] == "b"
        assert grants[name]["epoch"] == grant_before[name]["epoch"] + 1
        # zero acked-revision loss: the promoted record carries A's
        # last replicated state of the acked write
        kv = b.store.get(resource_key("containers", name))
        assert kv is not None, "promoted record missing"
        assert kv.value == replica_kv.value
        ops = [e["op"] for e in b.events.recent(200)]
        assert "fed.promote" in ops and "fed.takeover" in ops
        # promoted exactly once: one lineage (R2 in the live plane)
        assert ops.count("fed.promote") == 1
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        alog.close()
        b.stop()
