"""tdcheck sweep (`tdcheck` marker, `make verify-tdcheck`).

Four layers:

- EXHAUSTIVE: the 2-writer/1-reader seqlock model and the 2-worker
  claim/reconcile model are swept COMPLETELY within their context
  bounds (the frontier empties below the schedule cap — asserted), the
  WAL twin likewise, with a crash injected at every yield point.
- LIVENESS: every invariant checker fires on its seeded-broken mutant
  twin (a checker that can't fail its mutant proves nothing), and on
  the emulated PRE-FIX publish epoch arithmetic — the bug tdcheck's
  kill sweep originally caught in `SharedRouterState.publish`.
- DETERMINISM: the same seed replays the same schedules bit-for-bit
  (digest over every explored schedule), and a failure's reported
  schedule reproduces the identical violation via ReplayStrategy.
- CROSS-VALIDATION: the WAL twin's W1 invariant (Commit returned =>
  record durable) is re-checked against the REAL C++ core by a
  subprocess SIGKILLed mid-commit-stream.
"""

from __future__ import annotations

import os
import random
import signal
import subprocess
import sys
import time

import pytest

from gpu_docker_api_tpu.server import workers
from tools.tdcheck.instrument import BrokenSeqlockState, InstrumentedState
from tools.tdcheck.models import (
    BrokenClaimRouter, BrokenWalTwin, SeqlockModel, WalModel, run_model,
    sweep_claim, sweep_seqlock, sweep_wal,
)
from tools.tdcheck.sched import InvariantViolation, ReplayStrategy

pytestmark = [pytest.mark.tdcheck]

needs_shm = pytest.mark.skipif(
    not workers.available(),
    reason="worker tier unavailable (no Linux SO_REUSEPORT / native core)")

#: well above every model's full tree — the sweep tests assert the
#: frontier emptied BELOW this, i.e. the exploration was exhaustive
CAP = 30000


# ------------------------------------------------------------ exhaustive

@needs_shm
def test_seqlock_model_swept_exhaustively():
    """Both passes (torn sweep at preemption bound 2; kill+heal sweep
    with a SIGKILL at every writer yield point) terminate with the
    frontier empty: every schedule within the bounds was explored and
    every invariant held on all of them."""
    stats = sweep_seqlock(max_schedules=CAP)
    assert 0 < stats["schedules"] < CAP, "cap hit: sweep not exhaustive"
    assert stats["killed_runs"] > 100   # the kill sweep really injected


@needs_shm
def test_claim_model_swept_exhaustively():
    stats = sweep_claim(max_schedules=CAP)
    assert 0 < stats["schedules"] < CAP, "cap hit: sweep not exhaustive"
    assert stats["killed_runs"] > 100


def test_wal_model_swept_exhaustively():
    stats = sweep_wal(max_schedules=CAP)
    assert 0 < stats["schedules"] < CAP, "cap hit: sweep not exhaustive"
    assert stats["killed_runs"] > 100   # crash-at-every-yield-point


# -------------------------------------------------------------- liveness

@needs_shm
def test_seqlock_checker_live_on_mutant():
    """The torn-roster checker must catch a publish that forgets the
    odd-epoch store (config bytes landing under a read-admissible
    epoch) — and the failure must carry a replayable schedule."""
    with pytest.raises(InvariantViolation) as ei:
        sweep_seqlock(state_cls=BrokenSeqlockState, max_schedules=CAP)
    v = ei.value
    assert "torn roster" in str(v)
    assert v.schedule, "failure report lost its schedule"
    assert "replay schedule:" in v.format()
    # the report names the PASS it came from — replaying a torn-pass
    # schedule against the kill-variant model (extra heal process)
    # would desynchronize, so the variant must travel with the schedule
    assert v.variant == "torn"
    assert "--variant torn" in v.format()
    with pytest.raises(InvariantViolation) as ei2:
        run_model(lambda s: SeqlockModel(s, heal=False,
                                         state_cls=BrokenSeqlockState),
                  ReplayStrategy(v.schedule), kills=0, preemptions=2)
    assert ei2.value.message == v.message


@needs_shm
def test_claim_checker_live_on_mutant():
    """The accounting checker must catch the ledger-before-fetch_add
    ordering: a kill in the reversed window makes reconcile free
    capacity that was never claimed."""
    with pytest.raises(InvariantViolation) as ei:
        sweep_claim(router_cls=BrokenClaimRouter, max_schedules=CAP)
    assert "ledger ran AHEAD" in str(ei.value)
    assert ei.value.schedule


def test_wal_checker_live_on_mutant():
    """The durability checker must catch a leader that reads its
    durable horizon AFTER the file write (acking records appended
    mid-flush that were never written)."""
    with pytest.raises(InvariantViolation) as ei:
        sweep_wal(twin_cls=BrokenWalTwin, max_schedules=CAP)
    assert "not in the flushed stream" in str(ei.value)
    assert ei.value.schedule


class PreFixSeqlockState(InstrumentedState):
    """Emulates the PRE-FIX publish epoch arithmetic (epoch+1 / epoch+2
    regardless of crash parity): storing the reentry-normalized odd
    value over an identical current epoch becomes value+1 — exactly the
    old `epoch + 1` behaviour, which flipped a crashed-odd epoch EVEN
    mid-write and re-parked it odd at the close."""

    def store(self, off: int, v: int) -> None:
        if (off == workers.HDR_OFF_EPOCH
                and v == self.lib.shm_load(self.base + off)):
            super().store(off, v + 1)
        else:
            super().store(off, v)


@needs_shm
def test_kill_sweep_catches_prefix_publish_bug():
    """Regression proof for the workers.py fix this PR ships: with the
    old epoch arithmetic, a writer SIGKILLed inside the window either
    wedges readers past the heal republish or hands them a torn roster.
    The kill+heal sweep must refuse it."""
    with pytest.raises(InvariantViolation) as ei:
        sweep_seqlock(state_cls=PreFixSeqlockState, max_schedules=CAP)
    msg = str(ei.value)
    assert "wedged" in msg or "torn roster" in msg


# ----------------------------------------------------------- determinism

def test_exhaustive_sweep_deterministic():
    a = sweep_wal(max_schedules=400)
    b = sweep_wal(max_schedules=400)
    assert a["digest"] == b["digest"]
    assert a["schedules"] == b["schedules"]


@needs_shm
def test_random_mode_deterministic_under_seed():
    a = sweep_claim(mode="random", max_schedules=40, seed=7)
    b = sweep_claim(mode="random", max_schedules=40, seed=7)
    c = sweep_claim(mode="random", max_schedules=40, seed=8)
    assert a["digest"] == b["digest"]
    assert c["digest"] != a["digest"]


@needs_shm
def test_random_mode_failure_reports_its_seed():
    """A failing random draw must name the one seed that reproduces it
    alone (draw i runs under seed+i). The claim mutant trips random
    mode within a couple dozen draws (measured: seed 22 from base 11);
    the WAL mutant notably does NOT within 20k random draws — the
    exhaustive pass is what finds it, which is the point of having
    both modes."""
    with pytest.raises(InvariantViolation) as ei:
        sweep_claim(router_cls=BrokenClaimRouter, mode="random",
                    max_schedules=500, seed=11)
    assert ei.value.seed is not None and ei.value.seed >= 11
    assert "seed:" in ei.value.format()


def test_failure_schedule_replays_identical_violation():
    with pytest.raises(InvariantViolation) as ei:
        sweep_wal(twin_cls=BrokenWalTwin, max_schedules=CAP)
    first = ei.value
    with pytest.raises(InvariantViolation) as ei2:
        run_model(lambda s: WalModel(s, twin_cls=BrokenWalTwin),
                  ReplayStrategy(first.schedule), kills=1, crash_all=True)
    assert ei2.value.message == first.message


# ------------------------------------------------------------ CLI wiring

def test_cli_sweep_and_mutant_gate():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-m", "tools.tdcheck", "--model", "wal",
         "--schedules", "300"],
        capture_output=True, text=True, timeout=120, env=env)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "all invariants held" in out.stdout
    out = subprocess.run(
        [sys.executable, "-m", "tools.tdcheck", "--model", "wal",
         "--prove-mutants", "--schedules", "2000"],
        capture_output=True, text=True, timeout=120, env=env)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "checker LIVE" in out.stdout


# ----------------------------------------- real-core cross-validation

def test_wal_twin_invariant_on_real_core_kill_sweep(tmp_path):
    """W1 against the REAL C++ group commit: a child streams one line
    per ACKED put (native engine, fsync on); the parent SIGKILLs it
    mid-stream at a seeded random moment. Every complete acked line
    must replay from the WAL — the twin's invariant, cross-validated
    where SIGKILL is real and the flush is a real fsync."""
    from gpu_docker_api_tpu.store import native_available, open_store
    if not native_available():
        pytest.skip("native core not built")
    wal = str(tmp_path / "kill.wal")
    child = (
        "import sys, threading\n"
        f"sys.path.insert(0, {os.getcwd()!r})\n"
        "from gpu_docker_api_tpu.store.native import NativeMVCCStore\n"
        f"s = NativeMVCCStore(wal_path={wal!r}, fsync=True)\n"
        "lock = threading.Lock()\n"
        "def w(i):\n"
        "    for j in range(400):\n"
        "        k = f'/ck/{i}-{j}'\n"
        "        s.put(k, 'v')\n"
        "        with lock:\n"
        "            print(k, flush=True)\n"
        "ts = [threading.Thread(target=w, args=(i,)) for i in range(3)]\n"
        "[t.start() for t in ts]\n"
        "[t.join() for t in ts]\n"
    )
    proc = subprocess.Popen([sys.executable, "-c", child],
                            stdout=subprocess.PIPE, text=True)
    rng = random.Random(1234)
    time.sleep(0.05 + rng.random() * 0.4)     # mid-stream, seeded
    proc.send_signal(signal.SIGKILL)
    out, _ = proc.communicate(timeout=60)
    lines = out.splitlines()
    if lines and not out.endswith("\n"):
        lines = lines[:-1]                     # torn final stdout line
    acked = [ln.strip() for ln in lines if ln.startswith("/ck/")]
    assert acked, "child was killed before any ack — widen the window"
    s2 = open_store(wal_path=wal, engine="native")
    try:
        for k in acked:
            assert s2.get(k) is not None and s2.get(k).value == "v", \
                f"acked {k} lost by SIGKILL — W1 violated on the real core"
    finally:
        s2.close()
