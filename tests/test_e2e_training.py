"""Flagship end-to-end: a resumable Llama training replicaSet driven
entirely through the REST API, patched and rolled back MID-RUN with
checkpoint continuity (the BASELINE config-5 scenario, scaled to CI:
tiny model, CPU devices, process substrate — the control-plane mechanics
are identical on a TPU slice)."""

import json
import os
import sys
import time

import pytest

from gpu_docker_api_tpu.server.app import App
from gpu_docker_api_tpu.topology import make_topology

# slow tier: long-compile / multi-process e2e — quick CI runs
# -m 'not slow' (<3 min); the full suite stays the default
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def app(tmp_path):
    a = App(state_dir=str(tmp_path / "state"), backend="process",
            addr="127.0.0.1:0", port_range=(45000, 45100),
            topology=make_topology("v5p-8"), api_key="", cpu_cores=8)
    a.start()
    yield a
    a.stop()


def call(app, method, path, body=None):
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", app.server.port, timeout=30)
    conn.request(method, path, json.dumps(body) if body is not None else None,
                 {"Content-Type": "application/json"})
    resp = json.loads(conn.getresponse().read())
    conn.close()
    return resp


def _read_metrics(path):
    recs = []
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                try:
                    recs.append(json.loads(line))
                except json.JSONDecodeError:
                    pass
    return recs


def _wait_metrics(path, pred, timeout=180):
    deadline = time.time() + timeout
    while time.time() < deadline:
        recs = _read_metrics(path)
        if pred(recs):
            return recs
        time.sleep(0.25)
    raise TimeoutError(f"metrics predicate not met at {path}")


def test_training_replicaset_patch_and_rollback_resumes(app, tmp_path):
    cache = str(tmp_path / "jax-cache")
    # 1. a volume carries the durable training state (checkpoints + metrics)
    vol = call(app, "POST", "/api/v1/volumes",
               {"name": "jobdata", "size": "2GB"})["data"]
    mountpoint = vol["mountpoint"]

    env = [
        f"PYTHONPATH={REPO}",
        "JAX_PLATFORMS=cpu", "JAX_PLATFORM_NAME=cpu",
        f"JAX_COMPILATION_CACHE_DIR={cache}",
    ]
    cmd = [sys.executable, "-m", "gpu_docker_api_tpu.workloads.train_llama",
           "--config", "tiny", "--steps", "400", "--checkpoint-every", "5",
           "--batch", "2", "--seq", "32",
           "--workdir", "root/foo-tmp"]

    # 2. launch the training replicaSet with 1 chip
    resp = call(app, "POST", "/api/v1/replicaSet", {
        "imageName": "python", "replicaSetName": "train", "tpuCount": 1,
        "env": env, "cmd": cmd,
        "binds": [{"src": mountpoint, "dest": "/root/foo-tmp"}]})
    assert resp["code"] == 200, resp

    metrics = os.path.join(mountpoint, "metrics.jsonl")
    _wait_metrics(metrics, lambda rs: any(r.get("checkpoint") for r in rs))

    # 3. patch 1 -> 4 chips MID-RUN (rolling replacement kills the process,
    #    starts a new one; durable state lives on the volume)
    resp = call(app, "PATCH", "/api/v1/replicaSet/train",
                {"tpuPatch": {"tpuCount": 4}})
    assert resp["code"] == 200, resp
    assert len(resp["data"]["tpuChips"]) == 4

    # the post-patch process RESUMED: wait for a record written by the NEW
    # generation (step strictly past everything the pre-patch process
    # logged), not for stale pre-patch rows
    pre_patch_step = _max_step(_read_metrics(metrics))
    recs = _wait_metrics(
        metrics, lambda rs: _max_step(rs) > pre_patch_step)
    ckpts = [r["checkpoint"] for r in recs if "checkpoint" in r]
    assert ckpts == sorted(ckpts), "checkpoint steps must be monotonic"
    assert min(r["step"] for r in recs if "step" in r) == 1, \
        "sanity: generation 1 started at step 1"

    # 4. rollback to version 1 — again a rolling replacement; training
    #    must resume, not restart
    pre_rollback_step = _max_step(recs)
    resp = call(app, "PATCH", "/api/v1/replicaSet/train/rollback",
                {"version": 1})
    assert resp["code"] == 200, resp
    assert resp["data"]["version"] == 3
    assert len(resp["data"]["tpuChips"]) == 1  # back to v1's chip count

    recs = _wait_metrics(
        metrics, lambda rs: _max_step(rs) > pre_rollback_step)
    assert _max_step(recs) > pre_rollback_step

    # 5. hygiene: exactly one container alive, resources consistent
    info = call(app, "GET", "/api/v1/replicaSet/train")["data"]["info"]
    assert info["version"] == 3 and info["running"]
    tpus = call(app, "GET", "/api/v1/resources/tpus")["data"]["tpus"]
    assert tpus["freeCount"] == 3  # 4-chip slice, 1 held
    call(app, "DELETE", "/api/v1/replicaSet/train")


def _max_step(recs) -> int:
    return max((r["step"] for r in recs if "step" in r), default=0)
