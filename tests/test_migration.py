"""Zero-loss training migration sweep: the workload quiesce protocol.

The backend quiesce contract (backend/base.py Backend.quiesce) lets a
rolling replace checkpoint a training workload at its EXACT current step
before stopping it, so drain/patch/rollback become loss-curve-continuous
operations. This suite covers the control-plane half on the mock substrate
(ordering, fallback on timeout/error, drain response fields, crash
recovery), the process-backend signal/ack mechanics with real host
processes, and — in the slow tier — the end-to-end acceptance: a
mid-training 1->4 chip patch whose metrics step sequence is GAPLESS with
quiesce enabled, and degrades to at most --checkpoint-every replayed steps
when the quiesce times out.

`make verify-migrate` runs exactly this marker.
"""

import json
import os
import sys
import time

import pytest

from gpu_docker_api_tpu import faults
from gpu_docker_api_tpu.backend import GuardedBackend, MockBackend
from gpu_docker_api_tpu.dtos import ContainerRun, PatchRequest, TpuPatch
from gpu_docker_api_tpu.faults import InjectedCrash
from gpu_docker_api_tpu.server.app import App
from gpu_docker_api_tpu.topology import make_topology

pytestmark = pytest.mark.migrate

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarmed():
    faults.disarm_all()
    faults.disarm_faults()
    yield
    faults.disarm_all()
    faults.disarm_faults()


def make_app(tmp_path, backend=None):
    return App(state_dir=str(tmp_path / "state"),
               backend=backend if backend is not None else "mock",
               addr="127.0.0.1:0", port_range=(47000, 47100),
               topology=make_topology("v4-32"), api_key="", cpu_cores=8,
               store_maint_records=0)


def run_train(app, name="train", tpus=2, quiesce=True):
    env = ["TDAPI_QUIESCE=1"] if quiesce else []
    return app.replicasets.run_container(ContainerRun(
        imageName="img", replicaSetName=name, tpuCount=tpus, env=env))


def patch_tpus(app, name="train", count=4):
    return app.replicasets.patch_container(
        name, PatchRequest(tpuPatch=TpuPatch(tpuCount=count)))


def last_copied_event(app):
    evts = [e for e in app.events.recent(limit=50)
            if e["op"] == "replace.copied"]
    assert evts, "no replace.copied event recorded"
    return evts[-1]


# ----------------------------------------------- control plane (mock)

def test_patch_quiesces_optin_workload_before_stop(tmp_path):
    app = make_app(tmp_path)
    run_train(app)
    patch_tpus(app)
    # the mock only acks a quiesce while the container RUNS, so a recorded
    # quiesce proves the signal went out before the stop
    assert app.backend.quiesce_log == ["train-1"]
    evt = last_copied_event(app)
    assert evt["quiesced"] is True
    assert evt["quiesceStep"] == 7          # the mock's injected ack step
    assert app.backend.inspect("train-2").running


def test_patch_without_optin_never_signals(tmp_path):
    """A workload without a SIGUSR1 handler would die on the signal — the
    control plane must only quiesce containers whose spec opted in."""
    app = make_app(tmp_path)
    run_train(app, quiesce=False)
    patch_tpus(app)
    assert app.backend.quiesce_log == []
    assert last_copied_event(app)["quiesced"] is False


def test_global_kill_switch_disables_quiesce(tmp_path, monkeypatch):
    monkeypatch.setenv("TDAPI_QUIESCE", "0")
    app = make_app(tmp_path)
    run_train(app)
    patch_tpus(app)
    assert app.backend.quiesce_log == []


def test_quiesce_timeout_falls_back_to_plain_stop(tmp_path):
    """A quiesce that never acks must not wedge the replace: the patch
    still completes through today's stop path."""
    app = make_app(tmp_path)
    run_train(app)
    app.backend.set_quiesce("timeout")
    out = patch_tpus(app)
    assert len(out["tpuChips"]) == 4
    assert last_copied_event(app)["quiesced"] is False
    assert app.backend.inspect("train-2").running


def test_quiesce_error_falls_back_to_plain_stop(tmp_path):
    app = make_app(tmp_path)
    run_train(app)
    app.backend.set_quiesce("error")
    out = patch_tpus(app)
    assert len(out["tpuChips"]) == 4
    assert last_copied_event(app)["quiesced"] is False


def test_drain_reports_per_set_quiesce_fields(tmp_path):
    """POST /tpus/drain answers quiesced/stepsLost per migrated set:
    0 lost steps for the quiesced workload, null (bounded by the
    workload's checkpoint cadence) for the plain-stopped one."""
    app = make_app(tmp_path)
    a = run_train(app, name="qtrain", tpus=2, quiesce=True)
    b = run_train(app, name="plain", tpus=2, quiesce=False)
    app.tpu.cordon([a["tpuChips"][0], b["tpuChips"][0]])
    result = app.replicasets.drain_cordoned()
    by_name = {d["name"]: d for d in result["drained"]}
    assert set(by_name) == {"qtrain", "plain"}
    assert by_name["qtrain"]["quiesced"] is True
    assert by_name["qtrain"]["stepsLost"] == 0
    assert by_name["plain"]["quiesced"] is False
    assert by_name["plain"]["stepsLost"] is None
    assert result["failed"] == {}


def test_quiesced_intent_step_recorded(tmp_path):
    """The 'quiesced' marker rides the journal (informational, lazy): a
    synchronous later step persists it, so post-crash forensics show
    whether the checkpoint was parked."""
    app = make_app(tmp_path)
    run_train(app)
    faults.arm("replace.after_copy")    # dies AFTER the sync 'copied' write
    with pytest.raises(InjectedCrash):
        patch_tpus(app)
    rec = app.intents.open_intents()[0]
    assert rec.has_step("quiesced")
    assert rec.step_meta("quiesced") == {"ok": True, "step": 7}


def test_crash_at_after_quiesce_reconciles_like_interrupted_replace(tmp_path):
    """Daemon death right after the quiesce settles: the new version was
    already persisted, so the reconciler rolls FORWARD — and the parked
    checkpoint state (the ack/marker files living in the writable layer)
    is carried into the surviving container by the idempotent layer
    sync. No grant leaks, fixpoint reconcile."""
    app = make_app(tmp_path)
    run_train(app)
    faults.arm("replace.after_quiesce")
    with pytest.raises(InjectedCrash):
        patch_tpus(app)
    # abandon like a daemon death (same protocol as test_crash_recovery)
    faults.disarm_all()
    app.wq.close()
    app.store.close()
    app.events.close()
    app2 = make_app(tmp_path, backend=app.backend)
    assert app2.intents.open_intents() == []
    info_kv = app2.client.get("containers", "train")
    from gpu_docker_api_tpu.dtos import StoredContainerInfo
    stored = StoredContainerInfo.deserialize(info_kv.value)
    assert stored.version == 2
    state = app2.backend.inspect("train-2")
    assert state.running
    # the quiesce ack traveled with the layer: same checkpoint, same step
    assert os.path.exists(os.path.join(state.upper_dir, ".quiesced"))
    rerun = app2.reconciler.run()
    assert rerun["actions"] == 0, rerun


def test_guard_grants_quiesce_its_own_timeout(tmp_path):
    """The guard's generic per-op deadline must not cut a healthy quiesce
    that legitimately waits on a checkpoint longer than the deadline."""

    from gpu_docker_api_tpu.dtos import ContainerSpec

    class SlowQuiesce(MockBackend):
        def quiesce(self, name, timeout=30.0):
            time.sleep(0.2)
            return super().quiesce(name, timeout)

    backend = GuardedBackend(SlowQuiesce(str(tmp_path / "b")),
                             deadline=0.05, retries=0)
    backend.create("w-1", ContainerSpec(image="img"))
    backend.start("w-1")
    assert backend.quiesce("w-1", timeout=1.0) is True


def test_purge_incomplete_checkpoints(tmp_path):
    """A stop that lands mid-orbax-save leaves an uncommitted
    `*.orbax-checkpoint-tmp-*` dir; the resume path must sweep it before
    opening a CheckpointManager (train.py purge_incomplete_checkpoints)."""
    from gpu_docker_api_tpu.train import purge_incomplete_checkpoints
    ckpt = tmp_path / "checkpoints"
    (ckpt / "7").mkdir(parents=True)
    (ckpt / "14.orbax-checkpoint-tmp-6").mkdir()
    (ckpt / "14.orbax-checkpoint-tmp-6" / "shard").write_text("torn")
    assert purge_incomplete_checkpoints(str(ckpt)) == 1
    assert sorted(os.listdir(ckpt)) == ["7"]
    # idempotent, and tolerant of a missing dir
    assert purge_incomplete_checkpoints(str(ckpt)) == 0
    assert purge_incomplete_checkpoints(str(tmp_path / "nope")) == 0


# ------------------------------------------- process backend mechanics

QUIESCE_SCRIPT = r"""
import json, os, signal, time
def _on(signum, frame):
    root = os.environ["CONTAINER_ROOT"]
    tmp = os.path.join(root, ".quiesced.tmp")
    with open(tmp, "w") as f:
        json.dump({"step": 5}, f)
    os.replace(tmp, os.path.join(root, ".quiesced"))
signal.signal(signal.SIGUSR1, _on)
open(os.path.join(os.environ["CONTAINER_ROOT"], "ready"), "w").close()
while True:
    time.sleep(0.05)
"""

# handlers installed, then readiness marker — the tests must not signal a
# child whose interpreter is still booting (default disposition would win)
READY_LINE = ('import os\n'
              'open(os.path.join(os.environ["CONTAINER_ROOT"], "ready"),'
              ' "w").close()\n')


def _process_backend(tmp_path):
    from gpu_docker_api_tpu.backend import ProcessBackend
    return ProcessBackend(str(tmp_path / "backend"))


def _spawn(backend, cmd, name="w-1"):
    from gpu_docker_api_tpu.dtos import ContainerSpec
    backend.create(name, ContainerSpec(image="", cmd=cmd))
    backend.start(name)
    ready = os.path.join(backend.inspect(name).upper_dir, "ready")
    deadline = time.time() + 10
    while time.time() < deadline and not os.path.exists(ready):
        time.sleep(0.02)
    assert os.path.exists(ready) and backend.inspect(name).running


def test_process_quiesce_acks_handled_signal(tmp_path):
    backend = _process_backend(tmp_path)
    try:
        _spawn(backend, [sys.executable, "-c", QUIESCE_SCRIPT])
        assert backend.quiesce("w-1", timeout=10.0) is True
        state = backend.inspect("w-1")
        with open(os.path.join(state.upper_dir, ".quiesced")) as f:
            assert json.load(f)["step"] == 5
        # the parked process is still stoppable the ordinary way
        backend.stop("w-1", timeout=5)
        assert not backend.inspect("w-1").running
    finally:
        backend.close()


def test_process_quiesce_unhandled_signal_reads_false(tmp_path):
    """A workload without a handler dies on SIGUSR1 (default disposition):
    quiesce reports False promptly instead of burning the whole timeout,
    and the stop path still converges."""
    backend = _process_backend(tmp_path)
    try:
        _spawn(backend, [sys.executable, "-c", READY_LINE +
                         "import time\nwhile True: time.sleep(0.05)"])
        t0 = time.time()
        assert backend.quiesce("w-1", timeout=10.0) is False
        assert time.time() - t0 < 5.0
        backend.stop("w-1", timeout=5)
    finally:
        backend.close()


def test_process_quiesce_ignores_stale_ack(tmp_path):
    """An ack left by a previous generation (or cloned in by the replace
    layer copy) must not satisfy a fresh quiesce wait."""
    backend = _process_backend(tmp_path)
    try:
        _spawn(backend, [sys.executable, "-c", READY_LINE +
                         "import time\nwhile True: time.sleep(0.05)"])
        state = backend.inspect("w-1")
        with open(os.path.join(state.upper_dir, ".quiesced"), "w") as f:
            json.dump({"step": 1}, f)
        # no handler: the process dies on the signal — the stale ack was
        # removed before signaling, so this must NOT read as quiesced
        assert backend.quiesce("w-1", timeout=10.0) is False
    finally:
        backend.close()


def test_process_stop_kill_escalation_is_observable(tmp_path):
    """Satellite: SIGTERM->SIGKILL escalation is logged, counted
    (stop_kills feeds tdapi_backend_stop_kills), and emitted as a
    backend.stop_killed event."""
    from gpu_docker_api_tpu.events import EventLog
    backend = _process_backend(tmp_path)
    backend.events = EventLog(str(tmp_path / "ev"))
    try:
        # ignore SIGTERM: stop() must escalate
        _spawn(backend, [sys.executable, "-c",
                         "import signal, time\n"
                         "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
                         + READY_LINE +
                         "while True: time.sleep(0.05)"])
        assert backend.stop_kills == 0
        backend.stop("w-1", timeout=0.3)
        assert not backend.inspect("w-1").running
        assert backend.stop_kills == 1
        ops = [e["op"] for e in backend.events.recent()]
        assert "backend.stop_killed" in ops
    finally:
        backend.events.close()
        backend.close()


def test_stop_kills_gauge_exported(tmp_path):
    import http.client
    app = make_app(tmp_path)
    app.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", app.server.port,
                                          timeout=10)
        conn.request("GET", "/metrics")
        text = conn.getresponse().read().decode()
        conn.close()
        assert "tdapi_backend_stop_kills 0" in text
    finally:
        app.stop()


# ------------------------------------------------ end-to-end (slow tier)

def _call(app, method, path, body=None):
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", app.server.port,
                                      timeout=30)
    conn.request(method, path,
                 json.dumps(body) if body is not None else None,
                 {"Content-Type": "application/json"})
    resp = json.loads(conn.getresponse().read())
    conn.close()
    return resp


def _read_metrics(path):
    recs = []
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                try:
                    recs.append(json.loads(line))
                except json.JSONDecodeError:
                    pass
    return recs


def _wait_metrics(path, pred, timeout=240):
    deadline = time.time() + timeout
    while time.time() < deadline:
        recs = _read_metrics(path)
        if pred(recs):
            return recs
        time.sleep(0.25)
    raise TimeoutError(f"metrics predicate not met at {path}")


def _steps(recs):
    return [r["step"] for r in recs if "step" in r]


@pytest.fixture()
def served_app(tmp_path):
    a = App(state_dir=str(tmp_path / "state"), backend="process",
            addr="127.0.0.1:0", port_range=(47200, 47300),
            topology=make_topology("v5p-8"), api_key="", cpu_cores=8)
    a.start()
    yield a
    a.stop()


def _launch_training(app, tmp_path, quiesce_env="1", steps=60,
                     checkpoint_every=7):
    vol = _call(app, "POST", "/api/v1/volumes",
                {"name": "jobdata", "size": "2GB"})["data"]
    mountpoint = vol["mountpoint"]
    env = [
        f"PYTHONPATH={REPO}",
        "JAX_PLATFORMS=cpu", "JAX_PLATFORM_NAME=cpu",
        # pin ONE virtual device (overrides the pytest harness's
        # inherited 8-device XLA_FLAGS): the migration mechanics under
        # test are device-count-independent, and the tp=8 virtual mesh
        # intermittently trips XLA:CPU heap corruption in subprocesses
        "XLA_FLAGS=--xla_force_host_platform_device_count=1",
        # persistent compile cache OFF (empty value also blocks the
        # daemon's auto-injection): this jax build intermittently heap-
        # corrupts (glibc 'corrupted double-linked list') when a resumed
        # process reads a warm shared cache — an environment bug, and
        # determinism matters more here than the ~seconds of tiny-model
        # recompile per generation
        "JAX_COMPILATION_CACHE_DIR=",
        f"TDAPI_QUIESCE={quiesce_env}",
    ]
    # relative --workdir: resolved against the container rootfs, where the
    # bind is materialized as a symlink onto the volume mountpoint
    cmd = [sys.executable, "-m", "gpu_docker_api_tpu.workloads.train_llama",
           "--config", "tiny", "--steps", str(steps),
           "--checkpoint-every", str(checkpoint_every),
           "--batch", "2", "--seq", "32", "--workdir", "root/foo-tmp"]
    resp = _call(app, "POST", "/api/v1/replicaSet", {
        "imageName": "python", "replicaSetName": "train", "tpuCount": 1,
        "env": env, "cmd": cmd,
        "binds": [{"src": mountpoint, "dest": "/root/foo-tmp"}]})
    assert resp["code"] == 200, resp
    return os.path.join(mountpoint, "metrics.jsonl")


@pytest.mark.slow
def test_e2e_mid_training_patch_loses_zero_steps(served_app, tmp_path):
    """Acceptance: a 1->4 chip patch mid-training with quiesce enabled is
    loss-curve-continuous — the metrics step sequence is GAPLESS across
    the migration (each record exactly one step after the previous; no
    replay, no hole)."""
    app = served_app
    metrics = _launch_training(app, tmp_path, quiesce_env="1")
    _wait_metrics(metrics, lambda rs: max(_steps(rs), default=0) >= 10)

    resp = _call(app, "PATCH", "/api/v1/replicaSet/train",
                 {"tpuPatch": {"tpuCount": 4}})
    assert resp["code"] == 200, resp
    assert len(resp["data"]["tpuChips"]) == 4

    pre = max(_steps(_read_metrics(metrics)))
    recs = _wait_metrics(metrics,
                         lambda rs: max(_steps(rs), default=0) > pre)
    seq = _steps(recs)
    # zero loss: strictly consecutive across the whole run, generations
    # included — no replayed step, no gap
    assert seq == list(range(1, len(seq) + 1)), seq
    # the quiesce checkpoint marker landed in the metrics stream
    assert any(r.get("quiesced") for r in recs if "checkpoint" in r)
    # and the control plane recorded the quiesced replace
    evts = _call(app, "GET", "/api/v1/events?limit=200")["data"]["events"]
    copied = [e for e in evts if e["op"] == "replace.copied"]
    assert copied and copied[-1]["quiesced"] is True
    _call(app, "DELETE", "/api/v1/replicaSet/train")


@pytest.mark.slow
def test_e2e_quiesce_timeout_degrades_to_bounded_replay(served_app,
                                                        tmp_path,
                                                        monkeypatch):
    """Acceptance: with the quiesce window collapsed to ~zero the patch
    falls back to the plain stop and the run degrades CLEANLY — at most
    --checkpoint-every steps replay, each generation stays monotonic."""
    checkpoint_every = 7
    monkeypatch.setenv("TDAPI_QUIESCE_TIMEOUT", "0.01")
    app = served_app
    metrics = _launch_training(app, tmp_path, quiesce_env="1",
                               checkpoint_every=checkpoint_every)
    # past the first periodic checkpoint, so the fallback has a resume point
    _wait_metrics(
        metrics,
        lambda rs: any("checkpoint" in r for r in rs)
        and max(_steps(rs), default=0) >= checkpoint_every + 2)

    pre = max(_steps(_read_metrics(metrics)))
    resp = _call(app, "PATCH", "/api/v1/replicaSet/train",
                 {"tpuPatch": {"tpuCount": 4}})
    assert resp["code"] == 200, resp

    recs = _wait_metrics(metrics,
                         lambda rs: max(_steps(rs), default=0) > pre)
    seq = _steps(recs)
    # find the generation boundary (step value that fails to increase)
    breaks = [i for i in range(1, len(seq)) if seq[i] <= seq[i - 1]]
    assert len(breaks) <= 1, seq
    if breaks:
        i = breaks[0]
        replayed = seq[i - 1] - (seq[i] - 1)
        assert 0 < replayed <= checkpoint_every, seq
        # each generation individually gapless
        assert seq[:i] == list(range(1, i + 1)), seq
        assert seq[i:] == list(range(seq[i], seq[i] + len(seq) - i)), seq
    else:
        # the workload may still have parked in time (the signal went out
        # before the timeout verdict) — that is zero loss, which trivially
        # satisfies the <= checkpoint-every bound
        assert seq == list(range(1, len(seq) + 1)), seq
    _call(app, "DELETE", "/api/v1/replicaSet/train")
