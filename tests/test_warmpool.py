"""Warm worker pool (backend/warmpool.py): fast workload start for the
process substrate. Tests use preimport="json" — the mechanism is identical
to the production preimport="jax" but costs milliseconds, per the suite's
fake-substrate strategy (SURVEY §4)."""

import os
import subprocess
import sys
import time

import pytest

from conftest import wait_for
from gpu_docker_api_tpu.backend.process import ProcessBackend
from gpu_docker_api_tpu.backend.warmpool import WarmPool
from gpu_docker_api_tpu.dtos import ContainerSpec


def test_supports_classification():
    py = sys.executable
    assert WarmPool.supports([py, "-c", "pass"])
    assert WarmPool.supports([py, "-u", "-c", "pass"])
    assert WarmPool.supports([py, "-m", "json.tool"])
    assert WarmPool.supports([py, "script.py", "arg"])
    assert WarmPool.supports(["python3", "-c", "pass"])
    assert not WarmPool.supports([])
    assert not WarmPool.supports(["sleep", "5"])
    assert not WarmPool.supports([py])                  # bare REPL
    assert not WarmPool.supports([py, "-c"])            # missing code
    assert not WarmPool.supports([py, "-X", "dev", "x.py"])  # unknown flag
    # PYTHON* env is consumed at interpreter startup — warm can't honor it
    assert not WarmPool.supports([py, "-c", "pass"], ["PYTHONPATH=/x"])
    assert not WarmPool.supports([py, "-c", "pass"], ["PYTHONHASHSEED=0"])
    assert WarmPool.supports([py, "-c", "pass"], ["FOO=bar", "PY=1"])
    # import-baked JAX env can't be re-pointed post-import — must cold-spawn
    assert not WarmPool.supports([py, "-c", "pass"],
                                 ["JAX_DEFAULT_DTYPE_BITS=32"])
    # re-pointable JAX env is fine (the worker routes it via jax.config)
    assert WarmPool.supports([py, "-c", "pass"], ["JAX_ENABLE_X64=1"])
    assert WarmPool.supports([py, "-c", "pass"], ["XLA_FLAGS=--xla_foo"])


@pytest.mark.slow
def test_warm_worker_repoints_jax_env(tmp_path):
    """A warm worker that already imported jax must honor a job's JAX_*
    env through jax.config (ADVICE r2: JAX_ENABLE_X64 et al. were silently
    ignored before)."""
    b = ProcessBackend(str(tmp_path / "b"), warm_pool=1,
                       warm_preimport="jax")
    try:
        wait_for(lambda: len(b._pool._idle) >= 1, timeout=60,
                 msg="jax warm worker")
        pool_pids = {w.pid for w in b._pool._idle}
        st = _run(b, "cx", (
            "import os, json, jax, jax.numpy as jnp\n"
            "rec = {'pid': os.getpid(),\n"
            "       'x64': str(jnp.arange(3.0).dtype)}\n"
            "open('marker.json', 'w').write(json.dumps(rec))\n"
        ), env=["JAX_ENABLE_X64=true", "JAX_PLATFORMS=cpu"])
        marker = os.path.join(st.upper_dir, "marker.json")
        import json as _json
        rec = {}

        def parsed():
            nonlocal rec
            try:
                rec = _json.loads(open(marker).read())
                return True
            except (OSError, ValueError):
                return False    # not yet written / mid-write

        wait_for(parsed, timeout=60, msg="marker")
        assert rec["pid"] in pool_pids      # ran warm, not cold-spawned
        assert rec["x64"] == "float64"      # x64 re-pointed post-import
    finally:
        b.close()


@pytest.fixture()
def warm_backend(tmp_path):
    b = ProcessBackend(str(tmp_path / "b"), warm_pool=1,
                       warm_preimport="json")
    yield b
    b.close()


def _run(b, name, code, env=None, cpuset=""):
    spec = ContainerSpec(image="", cmd=[sys.executable, "-c", code],
                         env=env or [], cpuset=cpuset)
    b.create(name, spec)
    b.start(name)
    return b.inspect(name)


def test_warm_start_runs_in_pool_worker(warm_backend, tmp_path):
    b = warm_backend
    # the idle worker (spawned at pool init) is who must run the job
    pool_pids = {w.pid for w in b._pool._idle}
    st = _run(b, "c1", (
        "import os, sys, json\n"
        "rec = {'pid': os.getpid(), 'cwd': os.getcwd(),\n"
        "       'argv': sys.argv, 'foo': os.environ.get('FOO'),\n"
        "       'root': os.environ.get('CONTAINER_ROOT'),\n"
        "       'stdin': sys.stdin.read(),\n"
        "       'json_warm': 'json' in sys.modules}\n"
        "open('marker.json', 'w').write(json.dumps(rec))\n"
        "print('hello-from-warm')\n"
    ), env=["FOO=bar"])
    assert st.running
    marker = os.path.join(st.upper_dir, "marker.json")
    wait_for(lambda: os.path.exists(marker), msg="marker")
    import json as _json
    rec = _json.loads(open(marker).read())
    assert rec["pid"] in pool_pids            # absorbed by the warm worker
    assert rec["cwd"] == os.path.realpath(st.upper_dir) or \
        rec["cwd"] == st.upper_dir
    assert rec["argv"][0] == "-c"
    assert rec["foo"] == "bar"                # spec env applied
    assert rec["root"] == st.upper_dir        # grant env applied
    assert rec["stdin"] == ""                 # stdin is EOF, not a hang
    # stdout lands in the container log
    wait_for(lambda: os.path.exists(b._get("c1").log_path), msg="log")
    wait_for(lambda: "hello-from-warm" in open(b._get("c1").log_path).read(),
             msg="log content")


def test_warm_worker_is_stoppable_and_exit_code_seen(warm_backend):
    b = warm_backend
    st = _run(b, "c2", "import time\ntime.sleep(60)\n")
    assert st.running
    b.stop("c2", timeout=5)
    st = b.inspect("c2")
    assert not st.running
    # a failing job surfaces its exit code through the same Popen
    _run(b, "c3", "import sys\nsys.exit(7)\n")
    wait_for(lambda: not b.inspect("c3").running, msg="c3 exit")
    assert b.inspect("c3").exit_code == 7


def test_pool_refills_after_take(warm_backend):
    b = warm_backend
    _run(b, "c4", "pass")
    wait_for(lambda: len(b._pool._idle) >= 1, msg="pool refill")


def test_dead_worker_falls_back_to_cold_spawn(warm_backend, tmp_path):
    b = warm_backend
    wait_for(lambda: len(b._pool._idle) >= 1, msg="initial worker")
    for w in list(b._pool._idle):
        w.kill()
        w.wait(timeout=5)
    st = _run(b, "c5", (
        "open('cold.txt', 'w').write('ran')\n"
    ))
    marker = os.path.join(st.upper_dir, "cold.txt")
    wait_for(lambda: os.path.exists(marker), msg="cold marker")
    # a popped-dead worker must be REPLACED, not shrink the pool forever
    wait_for(lambda: len(b._pool._idle) >= 1, msg="refill after dead worker")


def test_pythonpath_env_bypasses_pool(warm_backend):
    """PYTHONPATH is read at interpreter startup: the job must cold-spawn
    (where it works), never run on a warm worker (where it can't)."""
    b = warm_backend
    pool_pids = {w.pid for w in b._pool._idle}
    st = _run(b, "c7", (
        "import os, sys\n"
        "ok = '/warm-test-libs' in sys.path\n"
        "open('pp.txt', 'w').write(f'{os.getpid()} {ok}')\n"
    ), env=["PYTHONPATH=/warm-test-libs"])
    marker = os.path.join(st.upper_dir, "pp.txt")
    wait_for(lambda: os.path.exists(marker), msg="pp marker")
    pid, ok = open(marker).read().split()
    assert int(pid) not in pool_pids
    assert ok == "True"                        # the var actually took effect


def test_non_python_cmd_bypasses_pool(warm_backend):
    b = warm_backend
    spec = ContainerSpec(image="", cmd=["sleep", "30"])
    b.create("c6", spec)
    b.start("c6")
    assert b.inspect("c6").running
    b.stop("c6", timeout=5)


def test_pool_gives_up_after_consecutive_spawn_failures(monkeypatch):
    """Satellite: a broken spawn path (e.g. a preimport that can't even
    exec) must back off and eventually disable the pool instead of
    spinning a hot respawn loop."""
    pool = WarmPool(size=0, preimport="json", give_up_after=3,
                    backoff_base=0.001, backoff_cap=0.01)
    spawns = []
    monkeypatch.setattr(pool, "_spawn",
                        lambda: spawns.append(1) or None)
    for _ in range(10):
        pool._add_worker()
    st = pool.stats()
    assert st["gaveUp"] is True
    assert st["consecFailures"] >= 3
    # once given up, no further spawn attempts happen at all
    assert len(spawns) == 3
    pool._refill_async()               # must not resurrect the loop
    time.sleep(0.05)
    assert len(spawns) == 3
    assert pool.take() is None
    pool.close()


def test_pool_dead_idle_workers_count_toward_give_up(tmp_path):
    """Workers that die between spawn and take (broken preimport) are
    consecutive-failure evidence; a LIVE take resets the streak."""
    pool = WarmPool(size=2, preimport="json", give_up_after=50)
    wait_for(lambda: pool.stats()["idle"] == 2, msg="two workers")
    for w in list(pool._idle):
        w.kill()
        w.wait(timeout=5)
    assert pool.take() is None                  # both popped dead
    assert pool.stats()["consecFailures"] == 2
    wait_for(lambda: pool.stats()["idle"] >= 1, msg="refill")
    w = pool.take()
    assert w is not None                        # live take...
    assert pool.stats()["consecFailures"] == 0  # ...resets the streak
    from gpu_docker_api_tpu.backend.warmpool import _reap
    _reap(w)
    pool.close()


def test_pool_close_reaps_workers(tmp_path):
    b = ProcessBackend(str(tmp_path / "b2"), warm_pool=2,
                       warm_preimport="json")
    wait_for(lambda: len(b._pool._idle) == 2, msg="two workers")
    workers = list(b._pool._idle)
    b.close()
    for w in workers:
        assert w.poll() is not None           # exited (EOF on stdin)
    assert b._pool.take() is None             # closed pool hands out nothing
