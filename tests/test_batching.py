"""Continuous batching (batching.py): the slot cache with per-row lengths
must reproduce each request's solo greedy stream exactly, under staggered
admission and slot reuse."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gpu_docker_api_tpu.batching import (
    init_slot_cache, slot_decode, slot_prefill,
)
from gpu_docker_api_tpu.infer import generate
from gpu_docker_api_tpu.models.llama import LlamaConfig, init_params


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    return cfg, params


def solo(params, cfg, prompt_row, n):
    return np.asarray(generate(params, prompt_row[None, :], cfg,
                               max_new=n))[0]


def test_two_slots_match_solo_streams(setup):
    """Different prompts, different lengths, decoded in lock-step — each
    row must equal its per-request greedy stream."""
    cfg, params = setup
    p0 = jax.random.randint(jax.random.key(1), (6,), 0, cfg.vocab_size)
    p1 = jax.random.randint(jax.random.key(2), (9,), 0, cfg.vocab_size)
    want0, want1 = solo(params, cfg, p0, 5), solo(params, cfg, p1, 5)

    cache = init_slot_cache(cfg, slots=2, max_len=32)
    l0, cache = slot_prefill(params, p0[None], cache, 0, cfg)
    l1, cache = slot_prefill(params, p1[None], cache, 1, cfg)
    toks = jnp.array([jnp.argmax(l0[0]), jnp.argmax(l1[0])], jnp.int32)
    streams = [[int(toks[0])], [int(toks[1])]]
    active = jnp.array([True, True])
    for _ in range(4):
        logits, cache = slot_decode(params, toks, cache, active, cfg)
        toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        streams[0].append(int(toks[0]))
        streams[1].append(int(toks[1]))
    np.testing.assert_array_equal(streams[0], want0)
    np.testing.assert_array_equal(streams[1], want1)


def test_staggered_admission_does_not_disturb_running_slot(setup):
    """Admit a second request mid-decode: the first row's stream must be
    IDENTICAL to an uninterrupted run (continuous batching's contract)."""
    cfg, params = setup
    p0 = jax.random.randint(jax.random.key(3), (5,), 0, cfg.vocab_size)
    p1 = jax.random.randint(jax.random.key(4), (7,), 0, cfg.vocab_size)
    want0, want1 = solo(params, cfg, p0, 6), solo(params, cfg, p1, 3)

    cache = init_slot_cache(cfg, slots=2, max_len=32)
    l0, cache = slot_prefill(params, p0[None], cache, 0, cfg)
    t0 = jnp.argmax(l0[0]).astype(jnp.int32)
    s0 = [int(t0)]
    toks = jnp.array([t0, 0], jnp.int32)
    # two steps with only slot 0 active
    for _ in range(2):
        logits, cache = slot_decode(params, toks, cache,
                                    jnp.array([True, False]), cfg)
        nxt = jnp.argmax(logits[0]).astype(jnp.int32)
        s0.append(int(nxt))
        toks = jnp.array([nxt, 0], jnp.int32)
    # slot 1 joins
    l1, cache = slot_prefill(params, p1[None], cache, 1, cfg)
    t1 = jnp.argmax(l1[0]).astype(jnp.int32)
    s1 = [int(t1)]
    toks = jnp.array([toks[0], t1], jnp.int32)
    for _ in range(3):
        logits, cache = slot_decode(params, toks, cache,
                                    jnp.array([True, True]), cfg)
        toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        s0.append(int(toks[0]))
        if len(s1) < 3:
            s1.append(int(toks[1]))
    np.testing.assert_array_equal(s0, want0)
    np.testing.assert_array_equal(s1, want1)


def test_slot_reuse_after_finish(setup):
    """A finished slot re-prefilled with a NEW prompt must produce that
    prompt's solo stream — stale KV beyond the new length is dead."""
    cfg, params = setup
    p_old = jax.random.randint(jax.random.key(5), (10,), 0, cfg.vocab_size)
    p_new = jax.random.randint(jax.random.key(6), (4,), 0, cfg.vocab_size)
    want = solo(params, cfg, p_new, 4)

    cache = init_slot_cache(cfg, slots=1, max_len=32)
    l, cache = slot_prefill(params, p_old[None], cache, 0, cfg)
    toks = jnp.argmax(l, axis=-1).astype(jnp.int32)
    for _ in range(3):                      # leave stale entries behind
        logits, cache = slot_decode(params, toks, cache,
                                    jnp.array([True]), cfg)
        toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    l, cache = slot_prefill(params, p_new[None], cache, 0, cfg)
    stream = [int(jnp.argmax(l[0]))]
    toks = jnp.argmax(l, axis=-1).astype(jnp.int32)
    for _ in range(3):
        logits, cache = slot_decode(params, toks, cache,
                                    jnp.array([True]), cfg)
        toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        stream.append(int(toks[0]))
    np.testing.assert_array_equal(stream, want)


def test_inactive_rows_do_not_advance(setup):
    cfg, params = setup
    cache = init_slot_cache(cfg, slots=2, max_len=16)
    p = jax.random.randint(jax.random.key(7), (3,), 0, cfg.vocab_size)
    _, cache = slot_prefill(params, p[None], cache, 0, cfg)
    lens_before = np.asarray(cache["lengths"])
    _, cache = slot_decode(params, jnp.zeros(2, jnp.int32), cache,
                           jnp.array([True, False]), cfg)
    lens = np.asarray(cache["lengths"])
    assert lens[0] == lens_before[0] + 1
    assert lens[1] == 0


@pytest.mark.slow
def test_kv_quant_slot_cache_matches_generate():
    """The int8 slot cache (batcher x kv-quant — VERDICT r2 hole #3) must
    reproduce infer.generate's kv_quant greedy stream exactly: identical
    quantization math, slot layout is just a batched view."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gpu_docker_api_tpu.batching import (
        init_slot_cache, slot_decode, slot_prefill,
    )
    from gpu_docker_api_tpu.infer import generate
    from gpu_docker_api_tpu.models.llama import LlamaConfig, init_params

    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    prompt = jnp.array([[5, 9, 2, 7, 11, 3]], jnp.int32)
    max_new = 8

    want = np.asarray(
        generate(params, prompt, cfg, max_new, kv_quant=True))[0].tolist()

    cache = init_slot_cache(cfg, slots=2, max_len=32, quantized=True)
    assert cache["k"].dtype == jnp.int8 and "ks" in cache
    logits, cache = slot_prefill(params, prompt, cache, jnp.int32(1), cfg)
    toks = [int(jnp.argmax(logits[0]))]
    active = jnp.array([False, True])
    while len(toks) < max_new:
        step = jnp.array([0, toks[-1]], jnp.int32)
        logits, cache = slot_decode(params, step, cache, active, cfg)
        toks.append(int(jnp.argmax(logits[1])))
    assert toks == want


@pytest.mark.slow
def test_kv_quant_slot_cache_independent_rows():
    """Two quantized slots decode independently (no cross-row scale
    bleed): each matches its own single-row run."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gpu_docker_api_tpu.batching import (
        init_slot_cache, slot_decode, slot_prefill,
    )
    from gpu_docker_api_tpu.infer import generate
    from gpu_docker_api_tpu.models.llama import LlamaConfig, init_params

    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    prompts = [jnp.array([[4, 8, 15]], jnp.int32),
               jnp.array([[16, 23, 42, 108, 7]], jnp.int32)]
    max_new = 6
    wants = [np.asarray(generate(params, p, cfg, max_new,
                                 kv_quant=True))[0].tolist()
             for p in prompts]

    cache = init_slot_cache(cfg, slots=2, max_len=32, quantized=True)
    streams = []
    lg0, cache = slot_prefill(params, prompts[0], cache, jnp.int32(0), cfg)
    lg1, cache = slot_prefill(params, prompts[1], cache, jnp.int32(1), cfg)
    streams = [[int(jnp.argmax(lg0[0]))], [int(jnp.argmax(lg1[0]))]]
    active = jnp.array([True, True])
    while len(streams[0]) < max_new:
        step = jnp.array([streams[0][-1], streams[1][-1]], jnp.int32)
        logits, cache = slot_decode(params, step, cache, active, cfg)
        streams[0].append(int(jnp.argmax(logits[0])))
        streams[1].append(int(jnp.argmax(logits[1])))
    assert streams[0] == wants[0]
    assert streams[1] == wants[1]
