"""Substrate fault tolerance sweep: transient faults, breaker, cordon/drain.

Where tests/test_crash_recovery.py kills the CONTROL PLANE at every step
boundary, this suite makes the SUBSTRATE misbehave under a live control
plane: every mutating endpoint is driven with each transient fault mode
(error_once / error_n / latency / hang — faults.py) armed on each backend
op it crosses, through a GuardedBackend with test-scale deadlines and
retries. Invariants after every case:

- the op either succeeded after retry or failed clean,
- zero leaked TPU/CPU/port grants (bitmaps == non-released stored specs),
- a fresh reconcile pass is a no-op.

Plus: breaker open => mutating routes answer HTTP 503 + Retry-After while
reads serve from the store; breaker open -> half-open -> closed recovery;
health monitor auto-cordon; cordon + drain leaves no spec on a cordoned
chip with the rolling replacement in history.
"""

import http.client
import json
import time

import pytest

from gpu_docker_api_tpu import faults, xerrors
from gpu_docker_api_tpu.backend import GuardedBackend, MockBackend
from gpu_docker_api_tpu.backend.guard import CLOSED, OPEN
from gpu_docker_api_tpu.dtos import (
    ContainerRun, PatchRequest, StoredContainerInfo, TpuPatch,
)
from gpu_docker_api_tpu.health import HealthMonitor
from gpu_docker_api_tpu.server.app import App
from gpu_docker_api_tpu.topology import make_topology

pytestmark = pytest.mark.faults

N_CHIPS = 16      # v4-32 single host
N_CORES = 16

# test-scale guard: deadline far under the hang fault's sleep, fast retries
DEADLINE = 0.4
RETRIES = 2
HANG = 1.2        # > DEADLINE: first attempt must be cut by the deadline


@pytest.fixture(autouse=True)
def _disarmed():
    faults.disarm_faults()
    yield
    faults.disarm_faults()


def make_app(tmp_path, breaker_threshold=50, breaker_cooldown=30.0):
    backend = GuardedBackend(
        MockBackend(str(tmp_path / "backend")),
        deadline=DEADLINE, retries=RETRIES, backoff_base=0.01,
        backoff_cap=0.05, breaker_threshold=breaker_threshold,
        breaker_cooldown=breaker_cooldown)
    return App(state_dir=str(tmp_path / "state"), backend=backend,
               addr="127.0.0.1:0", port_range=(46000, 46100),
               topology=make_topology("v4-32"), api_key="",
               cpu_cores=N_CORES, store_maint_records=0)


def run_demo(app, name="demo", tpus=2, env=()):
    return app.replicasets.run_container(ContainerRun(
        imageName="img", replicaSetName=name, tpuCount=tpus, cpuCount=2,
        containerPorts=["8888"], env=list(env)))


# ------------------------------------------------------------ invariants

def stored_containers(app):
    app.wq.join()
    return {kv.key.rsplit("/", 1)[1]: StoredContainerInfo.deserialize(kv.value)
            for kv in app.client.range("containers")}


def assert_no_leaks(app):
    """Scheduler bitmaps hold exactly the grants of non-released stored
    records, no intent is left open, and reconcile reaches a fixpoint.

    The first reconcile pass may legitimately clean BACKEND-side debris a
    services layer deliberately tolerated (a failed post-commit remove
    leaves an orphan container/volume for exactly this pass) — but it must
    never need to fix a grant: resource accounting has to be exact the
    moment the op returns, not one reconcile later."""
    stored = stored_containers(app)
    exp_tpu, exp_cpu, exp_ports = {}, {}, {}
    for name, info in stored.items():
        if info.resourcesReleased:
            continue
        for c in info.spec.tpu_chips:
            exp_tpu[c] = name
        for c in app.cpu._cores(info.spec.cpuset):
            exp_cpu[c] = name
        for p in info.spec.port_bindings.values():
            exp_ports[int(p)] = name
    assert {i: o for i, o in app.tpu.status.items()
            if o not in (None, "")} == exp_tpu
    assert {i: o for i, o in app.cpu.status.items()
            if o not in (None, "")} == exp_cpu
    assert dict(app.ports.used) == exp_ports
    assert app.intents.open_intents() == []
    settle = app.reconciler.run()
    assert sum(settle["grantsFreed"].values()) == 0, settle
    assert sum(settle["grantsRemarked"].values()) == 0, settle
    rerun = app.reconciler.run()
    assert rerun["actions"] == 0, f"re-reconcile not a no-op: {rerun}"
    return stored


# ------------------------------------------------------- sweep scenarios

def mut_run(app):
    run_demo(app, name="fresh")


def mut_patch(app):
    app.replicasets.patch_container(
        "demo", PatchRequest(tpuPatch=TpuPatch(tpuCount=4)))


def mut_rollback(app):
    app.replicasets.patch_container(
        "demo", PatchRequest(tpuPatch=TpuPatch(tpuCount=4)))
    app.replicasets.rollback_container("demo", 1)


def mut_stop(app):
    app.replicasets.stop_container("demo")


def mut_restart(app):
    app.replicasets.restart_container("demo")


def mut_pause(app):
    app.replicasets.pause_container("demo")


def mut_continue(app):
    app.replicasets.startup_container("demo")


def mut_delete(app):
    app.replicasets.delete_container("demo")


def mut_vol_create(app):
    app.volumes.create_volume("vol", "16MB")


def mut_vol_patch(app):
    app.volumes.create_volume("vol", "16MB")
    app.volumes.patch_volume_size("vol", "32MB")


def mut_vol_delete(app):
    app.volumes.create_volume("vol", "16MB")
    app.volumes.delete_volume("vol")


def mut_patch_quiesce(app):
    """Quiesce-enabled replace: the spec opts in (TDAPI_QUIESCE=1), so the
    patch crosses the backend quiesce op before stopping the old version."""
    run_demo(app, env=["TDAPI_QUIESCE=1"])
    app.replicasets.patch_container(
        "demo", PatchRequest(tpuPatch=TpuPatch(tpuCount=4)))


# every mutating endpoint x the backend ops it crosses. `swallowed` marks
# ops whose failure the services layer deliberately tolerates (post-commit
# cleanup — the endpoint still succeeds; the reconciler's orphan sweep is
# the designed janitor). Every pair below is actually crossed by its
# endpoint, so an armed fault that never fires would mean the table rotted.
SWEEP = [
    ("run", mut_run, "create", False),
    ("run", mut_run, "start", False),
    ("patch", mut_patch, "create", False),
    ("patch", mut_patch, "start", False),
    ("patch", mut_patch, "stop", False),
    ("patch", mut_patch, "remove", True),     # old-version removal is logged
    ("rollback", mut_rollback, "stop", False),
    ("stop", mut_stop, "stop", False),
    ("restart", mut_restart, "create", False),
    ("restart", mut_restart, "start", False),
    ("pause", mut_pause, "pause", False),
    ("continue", mut_continue, "restart_inplace", False),
    ("delete", mut_delete, "remove", False),
    # quiesce is strictly best-effort: its failure falls back to the plain
    # stop and the replace still succeeds, so every mode is "swallowed"
    ("patchq", mut_patch_quiesce, "quiesce", True),
    ("vol.create", mut_vol_create, "volume_create", False),
    ("vol.patch", mut_vol_patch, "volume_create", False),
    ("vol.delete", mut_vol_delete, "volume_remove", True),  # logged, swept
]

MODES = ["error_once", f"error_n:{RETRIES + 2}", "latency:0.02",
         f"hang:{HANG}"]


@pytest.mark.parametrize("mode", MODES)
@pytest.mark.parametrize("endpoint,mutate,op,swallowed",
                         [(e, m, o, s) for e, m, o, s in SWEEP],
                         ids=[f"{e}-{o}" for e, _, o, _ in SWEEP])
def test_transient_fault_sweep(endpoint, mutate, op, swallowed, mode,
                               tmp_path):
    """Under every fault mode, every mutating endpoint either converges
    (bounded-retry win) or fails clean with zero leaked grants and a
    fixpoint reconcile."""
    app = make_app(tmp_path)
    if endpoint not in ("run", "patchq", "vol.create", "vol.patch",
                        "vol.delete"):
        run_demo(app)
    faults.arm_fault(f"{op}:{mode}")
    mode_name = mode.partition(":")[0]
    try:
        mutate(app)
        outcome = "ok"
    except (OSError, xerrors.XError, RuntimeError) as e:
        outcome = f"failed: {e}"
    finally:
        faults.disarm_faults()
    from gpu_docker_api_tpu.backend.guard import NON_IDEMPOTENT
    if mode_name == "hang" and op in NON_IDEMPOTENT:
        # a timed-out create/commit may have half-applied: NOT retried —
        # must fail clean instead of risking a double-apply
        assert outcome != "ok", f"{endpoint}/{op}/{mode} unexpectedly passed"
    elif mode_name in ("error_once", "latency", "hang"):
        # retries must absorb a once-off error, a slow call, and one hang
        assert outcome == "ok", f"{endpoint}/{op}/{mode}: {outcome}"
    elif not swallowed:
        # more consecutive errors than the retry budget: must fail clean
        assert outcome != "ok", f"{endpoint}/{op}/{mode} unexpectedly passed"
    assert_no_leaks(app)


def test_error_n_exhausts_then_recovers(tmp_path):
    """After a clean failure, the same mutation succeeds once the fault
    clears — nothing about the failed attempt poisoned the name."""
    app = make_app(tmp_path)
    faults.arm_fault(f"create:error_n:{RETRIES + 2}")
    with pytest.raises(OSError):
        run_demo(app)
    faults.disarm_faults()
    assert_no_leaks(app)
    out = run_demo(app)
    assert out["name"] == "demo-1"
    assert_no_leaks(app)


# --------------------------------------------------------- breaker + HTTP

def call(app, method, path, body=None):
    conn = http.client.HTTPConnection("127.0.0.1", app.server.port,
                                      timeout=10)
    payload = json.dumps(body) if body is not None else None
    conn.request(method, path, payload, {"Content-Type": "application/json"})
    resp = conn.getresponse()
    raw = resp.read()
    headers = dict(resp.getheaders())
    conn.close()
    return resp.status, headers, json.loads(raw) if raw else None


MUTATING_ROUTES = [
    ("POST", "/api/v1/replicaSet",
     {"imageName": "i", "replicaSetName": "x"}),
    ("PATCH", "/api/v1/replicaSet/demo", {"tpuPatch": {"tpuCount": 2}}),
    ("PATCH", "/api/v1/replicaSet/demo/rollback", {"version": 1}),
    ("PATCH", "/api/v1/replicaSet/demo/stop", None),
    ("PATCH", "/api/v1/replicaSet/demo/restart", None),
    ("PATCH", "/api/v1/replicaSet/demo/pause", None),
    ("PATCH", "/api/v1/replicaSet/demo/continue", None),
    ("POST", "/api/v1/replicaSet/demo/execute", {"cmd": ["ls"]}),
    ("POST", "/api/v1/replicaSet/demo/commit", {"newImageName": "img2"}),
    ("DELETE", "/api/v1/replicaSet/demo", None),
    ("POST", "/api/v1/volumes", {"name": "v", "size": "16MB"}),
    ("PATCH", "/api/v1/volumes/vol/size", {"size": "32MB"}),
    ("DELETE", "/api/v1/volumes/vol", None),
    ("POST", "/api/v1/tpus/drain", None),
]


def test_breaker_open_503_and_degraded_reads(tmp_path):
    """Breaker forced open: every mutating route answers HTTP 503 with
    Retry-After (envelope code 503) while info/history/resource reads keep
    serving from the MVCC store."""
    app = make_app(tmp_path)
    app.volumes.create_volume("vol", "16MB")
    run_demo(app)
    # v2 with 4 chips, so the rollback body (version 1) is a real rollback
    # and the patch body (tpuCount 2) is a real change under the breaker
    out = app.replicasets.patch_container(
        "demo", PatchRequest(tpuPatch=TpuPatch(tpuCount=4)))
    # a cordoned chip inside demo's grant makes /tpus/drain attempt a real
    # migration — which must 503, not log a per-replicaSet failure
    app.tpu.cordon([out["tpuChips"][0]])
    app.start()
    try:
        app.backend.breaker.force_open(cooldown=60)
        for method, path, body in MUTATING_ROUTES:
            status, headers, out = call(app, method, path, body)
            assert status == 503, (method, path, status, out)
            assert int(headers["Retry-After"]) >= 1, (method, path)
            assert out["code"] == 503, (method, path, out)
        # reads: answered from the store, degraded where live state is gone
        status, _, out = call(app, "GET", "/api/v1/replicaSet/demo")
        assert status == 200 and out["code"] == 200
        assert out["data"]["info"]["degraded"] is True
        assert out["data"]["info"]["running"] is None
        assert out["data"]["info"]["spec"]["tpu_chips"]
        status, _, out = call(app, "GET", "/api/v1/replicaSet/demo/history")
        assert status == 200 and out["code"] == 200 and out["data"]["history"]
        status, _, out = call(app, "GET", "/api/v1/volumes/vol")
        assert status == 200 and out["code"] == 200
        assert out["data"]["info"]["degraded"] is True
        status, _, out = call(app, "GET", "/api/v1/resources/tpus")
        assert status == 200 and out["data"]["tpus"]["freeCount"] == N_CHIPS - 4
        status, _, out = call(app, "GET", "/api/v1/healthz")
        assert status == 200 and out["data"]["status"] == "degraded"
        assert out["data"]["breaker"]["state"] == "open"
        status, _, _ = call(app, "GET", "/api/v1/events")
        assert status == 200
        app.backend.breaker.force_close()
        assert_no_leaks(app)
    finally:
        app.backend.breaker.force_close()
        app.stop()


def test_breaker_opens_on_failures_and_recovers_via_probe(tmp_path):
    """Consecutive transient failures open the breaker; after the cooldown
    a half-open trial succeeds and closes it — and the transitions are
    emitted as events."""
    app = make_app(tmp_path, breaker_threshold=2, breaker_cooldown=0.15)
    backend = app.backend
    # two post-retry failures: error_n outlasting the retry budget, twice
    for _ in range(2):
        faults.arm_fault(f"inspect:error_n:{RETRIES + 1}")
        with pytest.raises(OSError):
            backend.inspect("ghost")
        faults.disarm_faults()
    assert backend.breaker.describe()["state"] == OPEN
    # while open: refused fast with a retry hint
    with pytest.raises(xerrors.BackendUnavailableError) as ei:
        backend.inspect("ghost")
    assert ei.value.retry_after > 0
    # cooldown elapses -> one probe call is admitted and closes the breaker
    time.sleep(0.2)
    state = backend.inspect("ghost")
    assert not state.exists
    assert backend.breaker.describe()["state"] == CLOSED
    ops = [e["op"] for e in app.events.recent()]
    assert "breaker.open" in ops and "breaker.closed" in ops
    assert_no_leaks(app)


def test_breaker_halfopen_failure_reopens(tmp_path):
    app = make_app(tmp_path, breaker_threshold=1, breaker_cooldown=0.1)
    backend = app.backend
    faults.arm_fault(f"inspect:error_n:{2 * (RETRIES + 1)}")
    with pytest.raises(OSError):
        backend.inspect("ghost")
    assert backend.breaker.describe()["state"] == OPEN
    time.sleep(0.15)
    with pytest.raises(OSError):        # the half-open trial fails too
        backend.inspect("ghost")
    assert backend.breaker.describe()["state"] == OPEN
    faults.disarm_faults()
    time.sleep(0.15)
    backend.inspect("ghost")
    assert backend.breaker.describe()["state"] == CLOSED


# ------------------------------------------------------------- health

def test_health_monitor_auto_cordons_missing_chip(tmp_path):
    app = make_app(tmp_path)
    inner = app.backend.inner
    dead = app.tpu.topology.chips[3]
    inner.set_chip_health(dead.device_path, False)
    mon = HealthMonitor(inner, app.tpu, events=app.events, interval=0,
                        fail_threshold=2)
    rep = mon.probe_once()
    assert rep["status"] == "degraded"
    assert dead.index not in app.tpu.cordoned      # below threshold
    rep = mon.probe_once()
    assert dead.index in app.tpu.cordoned          # score hit threshold
    assert rep["chips"][dead.index]["cordoned"]
    assert "health.cordon" in [e["op"] for e in app.events.recent()]
    # recovery clears the score but NOT the cordon (explicit uncordon only)
    inner.set_chip_health(dead.device_path, True)
    rep = mon.probe_once()
    assert rep["chips"][dead.index]["failureScore"] == 0
    assert dead.index in app.tpu.cordoned


def test_health_monitor_flap_scores_chips(tmp_path):
    app = make_app(tmp_path)
    run_demo(app)
    inner = app.backend.inner
    info = stored_containers(app)["demo"]
    inner.set_flap_count(info.containerName, 5)
    mon = HealthMonitor(inner, app.tpu, interval=0, fail_threshold=3,
                        flap_threshold=3, auto_cordon=False)
    rep = mon.probe_once()
    assert rep["flapping"] == {info.containerName: 5}
    for c in info.spec.tpu_chips:
        assert rep["chips"][c]["failureScore"] == 1
    assert rep["status"] == "degraded"


def test_healthz_probes_fresh_when_prober_off(tmp_path):
    """With the background prober off, EVERY /healthz (not just the
    first) must run a fresh probe cycle — a chip dying between two
    requests shows up in the second."""
    app = make_app(tmp_path)        # health_interval 0: prober not running
    app.start()
    try:
        _, _, out = call(app, "GET", "/api/v1/healthz")
        assert out["data"]["status"] == "ok"
        dead = app.tpu.topology.chips[1]
        app.backend.inner.set_chip_health(dead.device_path, False)
        _, _, out = call(app, "GET", "/api/v1/healthz")   # no ?probe
        assert out["data"]["status"] == "degraded"
        assert out["data"]["health"]["chips"][1]["failureScore"] >= 1
    finally:
        app.stop()


def test_substrate_unreachable_reported(tmp_path):
    app = make_app(tmp_path)
    app.backend.inner.set_ping(False)
    app.start()
    try:
        status, _, out = call(app, "GET", "/api/v1/healthz?probe")
        assert out["data"]["status"] == "degraded"
        assert out["data"]["health"]["substrate"]["reachable"] is False
    finally:
        app.stop()


# -------------------------------------------------------- cordon / drain

def test_cordon_drain_end_to_end(tmp_path):
    """Acceptance: after cordon + drain of a chip held by a running
    replicaSet, /resources/tpus shows it cordoned, no stored spec
    references it, and the version history shows the rolling
    replacement."""
    app = make_app(tmp_path)
    out = run_demo(app, tpus=4)
    victim = out["tpuChips"][0]
    app.start()
    try:
        status, _, body = call(app, "POST", f"/api/v1/tpus/{victim}/cordon")
        assert body["code"] == 200 and victim in body["data"]["cordoned"]
        status, _, body = call(app, "POST", "/api/v1/tpus/drain")
        assert body["code"] == 200, body
        drained = body["data"]["drain"]["drained"]
        assert [d["name"] for d in drained] == ["demo"]
        assert victim in drained[0]["fromChips"]
        assert victim not in drained[0]["toChips"]
        # chip shows cordoned on the resource read; capacity excludes it
        status, _, body = call(app, "GET", "/api/v1/resources/tpus")
        chips = body["data"]["tpus"]["chips"]
        assert chips[victim]["cordoned"] and not chips[victim]["used"]
        assert body["data"]["tpus"]["freeCount"] == N_CHIPS - 4 - 1
        # no stored spec references the cordoned chip
        for info in stored_containers(app).values():
            assert victim not in info.spec.tpu_chips
        # history shows the replacement (v2 off, v1 on the cordoned chip)
        status, _, body = call(app, "GET", "/api/v1/replicaSet/demo/history")
        hist = body["data"]["history"]
        assert hist[0]["version"] == 2
        assert victim not in hist[0]["status"]["spec"]["tpu_chips"]
        assert victim in hist[1]["status"]["spec"]["tpu_chips"]
        # uncordon returns the chip to the pool
        status, _, body = call(app, "POST",
                               f"/api/v1/tpus/{victim}/uncordon")
        assert body["data"]["cordoned"] == []
        assert_no_leaks(app)
    finally:
        app.stop()


def test_drain_insufficient_capacity_fails_clean(tmp_path):
    """Draining more chips than the healthy pool can absorb reports the
    failure per replicaSet and leaves the workload running on its old
    grant — degraded but alive beats dead."""
    app = make_app(tmp_path)
    run_demo(app, tpus=N_CHIPS)         # the whole mesh: no spare chip
    victim = 0
    app.tpu.cordon([victim])
    result = app.replicasets.drain_cordoned()
    assert "demo" in result["failed"]
    assert result["drained"] == []
    info = stored_containers(app)["demo"]
    assert victim in info.spec.tpu_chips       # still on the old grant
    assert_no_leaks(app)


def test_drain_skips_stopped_replicasets(tmp_path):
    app = make_app(tmp_path)
    out = run_demo(app)
    app.replicasets.stop_container("demo")
    app.tpu.cordon([out["tpuChips"][0]])
    result = app.replicasets.drain_cordoned()
    assert result["skipped"] == ["demo"]
    assert result["drained"] == [] and result["failed"] == {}
    assert_no_leaks(app)


def test_drain_repost_after_partial_failure_is_idempotent(tmp_path):
    """Re-POSTing /tpus/drain after a partial failure (some sets in
    `failed`) is idempotent: already-migrated sets are skipped (they no
    longer hold cordoned chips), the failed ones are retried, and no
    grant leaks across either attempt."""
    app = make_app(tmp_path)
    run_demo(app, name="aaa")
    run_demo(app, name="bbb")
    stored = stored_containers(app)
    bad = {stored["aaa"].spec.tpu_chips[0], stored["bbb"].spec.tpu_chips[0]}
    app.tpu.cordon(sorted(bad))
    app.start()
    try:
        # fail exactly the FIRST migration (drain scans names sorted):
        # error_n outlasts the guard's retry budget once, then runs dry
        faults.arm_fault(f"create:error_n:{RETRIES + 1}")
        status, _, body = call(app, "POST", "/api/v1/tpus/drain")
        faults.disarm_faults()
        first = body["data"]["drain"]
        assert "aaa" in first["failed"]
        assert [d["name"] for d in first["drained"]] == ["bbb"]
        assert_no_leaks(app)
        bbb_version = stored_containers(app)["bbb"].version
        # the retry migrates the failed set and leaves the migrated one
        # alone — no second rolling replace, no version churn
        status, _, body = call(app, "POST", "/api/v1/tpus/drain")
        second = body["data"]["drain"]
        assert [d["name"] for d in second["drained"]] == ["aaa"]
        assert second["failed"] == {}
        stored = stored_containers(app)
        assert stored["bbb"].version == bbb_version
        for info in stored.values():
            assert not set(info.spec.tpu_chips) & bad
        # a third drain is a full no-op
        status, _, body = call(app, "POST", "/api/v1/tpus/drain")
        third = body["data"]["drain"]
        assert third["drained"] == [] and third["failed"] == {}
        assert_no_leaks(app)
    finally:
        faults.disarm_faults()
        app.stop()


def test_crash_mid_drain_reconciles(tmp_path):
    """A drain is an intent-journaled replace: a daemon death mid-drain
    must reconcile at boot exactly like any interrupted replace."""
    from gpu_docker_api_tpu.faults import InjectedCrash

    app = make_app(tmp_path)
    out = run_demo(app)
    victim = out["tpuChips"][0]
    app.tpu.cordon([victim])
    faults.arm("replace.after_stop_old")
    try:
        with pytest.raises(InjectedCrash):
            app.replicasets.drain_cordoned()
    finally:
        faults.disarm_all()
    # abandon like a crash (same protocol as test_crash_recovery.crash)
    app.wq.close()
    app.store.close()
    app.events.close()
    app2 = App(state_dir=str(tmp_path / "state"), backend=app.backend,
               addr="127.0.0.1:0", port_range=(46000, 46100),
               topology=make_topology("v4-32"), api_key="",
               cpu_cores=N_CORES, store_maint_records=0)
    stored = assert_no_leaks(app2)
    # rolled forward: the new version is live and off the cordoned chip
    info = stored["demo"]
    assert info.version == 2
    assert victim not in info.spec.tpu_chips
    assert victim in app2.tpu.cordoned          # cordon survived the crash
    assert app2.backend.inspect(info.containerName).running


def test_fault_gate_env_var(tmp_path, monkeypatch):
    """TDAPI_FAULTS arms faults against a live daemon, mirroring
    TDAPI_CRASHPOINTS for crashpoints."""
    monkeypatch.setenv(faults.FAULTS_ENV_VAR,
                       f"create:error_n:{RETRIES + 2}")
    app = make_app(tmp_path)
    with pytest.raises(OSError):
        run_demo(app)
    monkeypatch.delenv(faults.FAULTS_ENV_VAR)
    faults.disarm_faults()
    assert_no_leaks(app)
