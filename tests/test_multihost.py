"""Multi-host execution e2e: the control plane's env contract actually
assembles a live multi-process JAX cluster (SURVEY §5.8 — round 1 shipped
the contract but never RAN a multi-host path).

Flow: a replicaSet grant spanning two TPU-VM workers -> GET info exposes
the per-worker env -> two REAL processes are launched with exactly that
env (the operator's per-worker launcher role) -> each joins the cluster
via distributed.maybe_initialize_from_env -> together they run a sharded
train step over the GLOBAL 8-device mesh and agree on the loss.

CPU stands in for the chips (4 virtual devices per process); the contract
path exercised — TPU_WORKER_ID/HOSTNAMES/PROCESS_PORT -> jax.distributed —
is the same one a real TPU pod slice uses.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

from gpu_docker_api_tpu.distributed import cluster_spec_from_env

# slow tier: long-compile / multi-process e2e — quick CI runs
# -m 'not slow' (<3 min); the full suite stays the default
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER_SCRIPT = r"""
import json, os, sys
from gpu_docker_api_tpu.distributed import maybe_initialize_from_env

spec = maybe_initialize_from_env()
assert spec is not None, "contract should describe a 2-process cluster"

import jax
import jax.numpy as jnp

assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()
assert jax.local_device_count() == 4

from gpu_docker_api_tpu.models.llama import LlamaConfig
from gpu_docker_api_tpu.parallel.mesh import MeshPlan
from gpu_docker_api_tpu.train import Trainer

cfg = LlamaConfig.tiny()
trainer = Trainer.create(cfg, MeshPlan.auto(8, tp=2))
state = trainer.init(jax.random.key(0))
tokens = jax.random.randint(jax.random.key(1), (8, 32), 0, cfg.vocab_size,
                            jnp.int32)
tokens = trainer.shard_batch(tokens)
state, metrics = trainer.step(state, tokens)
loss = float(metrics["loss"])

rec = {"rank": spec["process_id"], "loss": loss,
       "devices": jax.device_count(), "processes": jax.process_count()}
out = sys.argv[1]
open(out, "w").write(json.dumps(rec))
print("worker done", rec, flush=True)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_spec_parsing_single_worker_is_noop():
    assert cluster_spec_from_env({"TPU_WORKER_HOSTNAMES": "localhost"}) is None
    assert cluster_spec_from_env({}) is None


def test_spec_parsing_derives_coordinator():
    spec = cluster_spec_from_env({
        "TPU_WORKER_HOSTNAMES": "worker-0,worker-1",
        "TPU_WORKER_ID": "1",
        "TPU_PROCESS_PORT": "8476",
    })
    assert spec == {"coordinator": "worker-0:9487",
                    "num_processes": 2, "process_id": 1}
    # operator override wins
    spec = cluster_spec_from_env({
        "TPU_WORKER_HOSTNAMES": "a,b",
        "TPU_WORKER_ID": "0",
        "JAX_COORDINATOR_ADDRESS": "10.0.0.5:1234",
    })
    assert spec["coordinator"] == "10.0.0.5:1234"


@pytest.mark.slow
def test_two_worker_cluster_from_replicaset_env(tmp_path):
    from gpu_docker_api_tpu.server.app import App
    from gpu_docker_api_tpu.topology import make_topology

    app = App(state_dir=str(tmp_path / "state"), backend="mock",
              addr="127.0.0.1:0", topology=make_topology("v5p-16"),
              api_key="")
    app.start()
    try:
        import http.client

        def call(method, path, body=None):
            conn = http.client.HTTPConnection("127.0.0.1", app.server.port,
                                              timeout=30)
            conn.request(method, path,
                         json.dumps(body) if body is not None else None,
                         {"Content-Type": "application/json"})
            out = json.loads(conn.getresponse().read())
            conn.close()
            assert out["code"] == 200, out
            return out["data"]

        call("POST", "/api/v1/replicaSet", {
            "imageName": "x", "replicaSetName": "pod", "tpuCount": 8})
        info = call("GET", "/api/v1/replicaSet/pod")["info"]
        multihost = info["multihost"]
        assert sorted(multihost) == ["0", "1"]
        for w, env in multihost.items():
            assert env["TPU_WORKER_ID"] in ("0", "1")
            assert env["TPU_WORKER_HOSTNAMES"] == "worker-0,worker-1"
            assert "TPU_PROCESS_ADDRESSES" in env
    finally:
        app.stop()

    # launch one REAL process per worker with the granted env (the
    # operator's per-worker launcher); CPU stands in for the chips
    script = tmp_path / "worker.py"
    script.write_text(WORKER_SCRIPT)
    port = _free_port()
    procs = []
    for w, contract in sorted(multihost.items()):
        env = dict(os.environ)
        env.update(contract)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        })
        out = tmp_path / f"out-{w}.json"
        procs.append((w, out, subprocess.Popen(
            [sys.executable, str(script), str(out)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)))

    results = {}
    for w, out, p in procs:
        stdout, _ = p.communicate(timeout=420)
        assert p.returncode == 0, stdout.decode(errors="replace")[-3000:]
        results[w] = json.loads(out.read_text())

    assert results["0"]["processes"] == 2 and results["1"]["processes"] == 2
    assert results["0"]["devices"] == 8
    # both processes computed the SAME global training step
    assert results["0"]["loss"] == pytest.approx(results["1"]["loss"])
    assert results["0"]["rank"] == 0 and results["1"]["rank"] == 1


def test_spec_parsing_bad_rank_raises():
    """A malformed rank on a multi-worker contract must fail loudly — a
    silent single-process fallback would leave the rest of the cluster
    blocked in initialize() waiting for this worker."""
    with pytest.raises(ValueError, match="TPU_WORKER_ID"):
        cluster_spec_from_env({
            "TPU_WORKER_HOSTNAMES": "worker-0,worker-1",
            "TPU_WORKER_ID": "worker-1",
        })
