"""lockwatch unit tests: the wrapped primitives, the lock-order graph, a
real two-thread A->B/B->A cycle, held-across-backend detection with the
name-lock exemption, and the install()/uninstall() threading seam.

Tests that EXPECT findings run against a private LockWatcher (or swap the
module global for one), so a TDAPI_LOCKWATCH=1 session's graph never
inherits a deliberate violation."""

import threading

import pytest

from gpu_docker_api_tpu.analysis import lockwatch
from gpu_docker_api_tpu.analysis.lockwatch import (
    LockWatcher, _WatchedCondition, _WatchedLock,
)


# ------------------------------------------------------------ primitives

def test_watched_lock_contract():
    w = LockWatcher()
    lk = w.make_lock(site="L")
    assert not lk.locked()
    with lk:
        assert lk.locked()
        assert not lk.acquire(blocking=False)   # it's a real Lock under
    assert not lk.locked()
    assert w.acquires == 1
    assert w.report()["lockSites"] == {"L": 1}


def test_watched_rlock_reentrancy_no_self_edge():
    w = LockWatcher()
    rl = w.make_rlock(site="R")
    with rl:
        with rl:                                 # reentrant: no R->R edge
            pass
    assert w.report()["edges"] == []
    assert w.report()["cycles"] == []


def test_out_of_lifo_release_keeps_stack_honest():
    w = LockWatcher()
    a, b = w.make_lock(site="A"), w.make_lock(site="B")
    a.acquire()
    b.acquire()
    a.release()                 # non-LIFO: legal
    w.note_backend_op("stop")   # only B still held
    b.release()
    found = w.report()["heldAcrossBackend"]
    assert [f["lock"] for f in found] == ["B"]


def test_condition_wrapper_wait_notify():
    w = LockWatcher()
    cond = w.make_condition(site="C")
    hits = []

    def waiter():
        with cond:
            while not hits:
                cond.wait(timeout=5)
            hits.append("woke")

    t = threading.Thread(target=waiter)
    t.start()
    import time
    time.sleep(0.05)
    with cond:
        hits.append("sent")
        cond.notify_all()
    t.join(timeout=5)
    assert not t.is_alive() and hits == ["sent", "woke"]


def test_condition_over_watched_lock_shares_the_real_lock():
    w = LockWatcher()
    lk = w.make_rlock(site="L")
    cond = w.make_condition(lock=lk, site="C")
    with lk:
        # the condition is bound to the SAME underlying primitive: its
        # non-blocking acquire from another thread must fail
        grabbed = []
        t = threading.Thread(
            target=lambda: grabbed.append(cond._inner.acquire(False)))
        t.start()
        t.join()
        assert grabbed == [False]


# ------------------------------------------------------- lock-order graph

def test_nested_acquire_records_edge():
    w = LockWatcher()
    a, b = w.make_lock(site="A"), w.make_lock(site="B")
    with a:
        with b:
            pass
    rep = w.report()
    assert rep["edges"] == [{"from": "A", "to": "B", "count": 1}]
    assert rep["cycles"] == []
    w.assert_clean()            # one direction only: no hazard


def test_real_two_thread_abba_cycle_detected():
    """Two threads take {A then B} and {B then A}, interleaved so the run
    itself never deadlocks — lockwatch must still flag the cycle."""
    w = LockWatcher()
    a, b = w.make_lock(site="A"), w.make_lock(site="B")
    t1_done = threading.Event()

    def t1():
        with a:
            with b:
                pass
        t1_done.set()

    def t2():
        t1_done.wait(5)         # sequenced: real threads, no deadlock
        with b:
            with a:
                pass

    th1, th2 = threading.Thread(target=t1), threading.Thread(target=t2)
    th1.start()
    th2.start()
    th1.join(5)
    th2.join(5)
    rep = w.report()
    assert rep["cycles"] == [{"sites": ["A", "B"]}]
    assert {(e["from"], e["to"]) for e in rep["cycleEdges"]} == {
        ("A", "B"), ("B", "A")}
    with pytest.raises(AssertionError, match="lock-order cycle"):
        w.assert_clean()


def test_three_site_cycle_detected():
    w = LockWatcher()
    a, b, c = (w.make_lock(site=s) for s in "ABC")
    for first, second in ((a, b), (b, c), (c, a)):
        with first:
            with second:
                pass
    assert w.report()["cycles"] == [{"sites": ["A", "B", "C"]}]


def test_same_site_peer_instances_skip_edge():
    w = LockWatcher()
    a1 = w.make_lock(site="base.py:65")
    a2 = w.make_lock(site="base.py:65")
    with a1:
        with a2:
            pass
    assert w.report()["edges"] == []    # documented granularity limit


# --------------------------------------------------- held-across-backend

def test_lock_held_across_backend_op_flagged():
    w = LockWatcher()
    lk = w.make_lock(site="sched")
    with lk:
        w.note_backend_op("create")
    found = w.report()["heldAcrossBackend"]
    assert [(f["lock"], f["op"]) for f in found] == [("sched", "create")]
    with pytest.raises(AssertionError, match="held across backend"):
        w.assert_clean()


def test_no_finding_when_nothing_held():
    w = LockWatcher()
    lk = w.make_lock(site="sched")
    with lk:
        pass
    w.note_backend_op("create")
    assert w.report()["heldAcrossBackend"] == []
    w.assert_clean()


def test_name_lock_exemptions():
    w = LockWatcher()
    # creation-time exemption (IO_EXEMPT_FUNCS path sets exempt=True)
    name_lock = w.make_lock(site="replicaset.py:173", exempt=True)
    with name_lock:
        w.note_backend_op("stop")
    assert w.report()["heldAcrossBackend"] == []
    # post-hoc allowlist by site
    other = w.make_lock(site="special")
    w.exempt_io("special")
    with other:
        w.note_backend_op("stop")
    assert w.report()["heldAcrossBackend"] == []
    assert "special" in w.report()["exemptSites"]


def test_guard_seam_reports_callers_held_locks(tmp_path, monkeypatch):
    """GuardedBackend._guard calls lockwatch.note_backend_op on the
    CALLER's thread — a watched lock held over a guarded op is caught
    end-to-end (the fixed health.py probe was exactly this bug class)."""
    from gpu_docker_api_tpu.backend import MockBackend
    from gpu_docker_api_tpu.backend.guard import GuardedBackend
    w = LockWatcher()
    monkeypatch.setattr(lockwatch, "_watcher", w)
    gb = GuardedBackend(MockBackend(str(tmp_path / "state")))
    lk = w.make_lock(site="monitor._lock")
    with lk:
        gb.ping()               # unguarded health hook: no finding
        gb.list_names()         # guarded op: finding
    found = w.report()["heldAcrossBackend"]
    assert ("monitor._lock", "list_names") in [
        (f["lock"], f["op"]) for f in found]
    assert all(f["op"] != "ping" for f in found)


# ------------------------------------------------------ install seam

def test_install_patches_package_lock_creation_only():
    was_installed = lockwatch.installed()
    w = lockwatch.install()
    try:
        # a lock created HERE (tests/, outside the package) stays real
        ours = threading.Lock()
        assert not isinstance(ours, _WatchedLock)
        # a lock created inside the package is watched, keyed by site
        from gpu_docker_api_tpu.schedulers import TpuScheduler
        from gpu_docker_api_tpu.topology import make_topology
        s = TpuScheduler(topology=make_topology("v4-8"))
        assert isinstance(s._lock, _WatchedLock)
        assert "schedulers/base.py" in s._lock._site
        site_count = w.report()["lockSites"][s._lock._site]
        assert site_count >= 1
        # conditions created in-package are watched too
        from gpu_docker_api_tpu.regulator import ChipRegulator
        r = ChipRegulator(chip=0)
        assert isinstance(r._cond, _WatchedCondition)
    finally:
        if not was_installed:
            lockwatch.uninstall()


def test_reset_clears_in_place_so_existing_locks_stay_watched(monkeypatch):
    """reset() must clear the SAME watcher instance: already-created locks
    hold a reference to it, so a swap-for-fresh would silently route their
    edges into a graph nobody reports."""
    w = LockWatcher()
    monkeypatch.setattr(lockwatch, "_watcher", w)
    a, b = w.make_lock(site="A"), w.make_lock(site="B")
    with a:
        with b:
            pass
    assert len(w.report()["edges"]) == 1
    lockwatch.reset()
    assert w.report()["edges"] == []
    assert w.report()["acquires"] == 0
    # phase 2 on the SAME pre-existing locks: the inverse order now forms
    # a cycle that must land in the REPORTED graph
    with b:
        with a:
            pass
    with a:
        with b:
            pass
    assert lockwatch.report()["cycles"] == [{"sites": ["A", "B"]}]


def test_uninstall_restores_and_watched_locks_survive():
    was_installed = lockwatch.installed()
    if was_installed:
        pytest.skip("session-armed lockwatch stays installed")
    lockwatch.install()
    from gpu_docker_api_tpu.schedulers import CpuScheduler
    s = CpuScheduler(core_count=4)
    lockwatch.uninstall()
    assert not lockwatch.installed()
    assert threading.Lock is lockwatch._REAL_LOCK
    # the orphaned wrapper keeps functioning
    grant = s.apply(2, "o")
    s.restore(grant, "o")
    assert lockwatch.report() == {}
    lockwatch.assert_clean()    # no-op when not installed
    lockwatch.note_backend_op("stop")   # fast no-op path
