"""Multi-process SO_REUSEPORT data-plane sweep (`workers` marker).

Three layers:

- POLICY PARITY: the worker router (server/workers.py WorkerRouter) is
  the in-process Gateway router's policy re-run over shared-memory state;
  the same scenarios the `gateway` suite pins on Gateway — slot caps,
  least-queued split, queue-bound shed, priority barge, deadline — are
  driven against WorkerRouter with an injected transport and must yield
  identical outcomes;
- E2E over real SO_REUSEPORT worker processes: kernel-balanced accepts,
  shed codes on the wire (429 + Retry-After, 504), the App wiring
  (TDAPI_GW_WORKERS -> tier, /healthz workers block, graceful stop);
- CRASH: SIGKILL a worker mid-request — the kernel stops routing to its
  closed socket, the watchdog respawns it, and the shared-memory claim
  reconcile returns the dead worker's slots with zero double-admits (the
  replica-side concurrent-request high-water mark never exceeds slots).
"""

from __future__ import annotations

import http.client
import http.server
import json
import threading
import time

import pytest

from gpu_docker_api_tpu import xerrors

workers = pytest.importorskip("gpu_docker_api_tpu.server.workers")

pytestmark = [
    pytest.mark.workers,
    pytest.mark.skipif(not workers.available(),
                       reason="worker tier unavailable "
                              "(no Linux SO_REUSEPORT / native core)"),
]


# ---------------------------------------------------------------- harness

@pytest.fixture()
def state():
    st = workers.SharedRouterState(create=True)
    yield st
    st.close(unlink=True)


def publish(st, replicas, max_queue=4, deadline_ms=3000, name="g"):
    st.publish([{"name": name, "maxQueue": max_queue,
                 "deadlineMs": deadline_ms, "replicas": replicas}])


def rep(port, slots=2, ready=True):
    return {"port": port, "slots": slots, "ready": ready}


class StubReplica:
    """Minimal replica-contract HTTP server with hold/concurrency probes:
    the policy assertions need to see in-replica concurrency, which is
    exactly what the slot cap bounds."""

    def __init__(self):
        outer = self
        self.hold = threading.Event()
        self.hold.set()                      # set = answer immediately
        self.lock = threading.Lock()
        self.inflight = 0
        self.peak = 0
        self.served = 0

        class H(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            disable_nagle_algorithm = True   # keep-alive + small bodies

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0) or 0)
                self.rfile.read(n)
                with outer.lock:
                    outer.inflight += 1
                    outer.peak = max(outer.peak, outer.inflight)
                try:
                    outer.hold.wait(10)
                    body = b'{"code":200,"msg":"ok","data":{}}'
                    try:
                        self.send_response(200)
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    except OSError:
                        pass      # client (a killed worker) went away
                finally:
                    with outer.lock:
                        outer.inflight -= 1
                        outer.served += 1

            def log_message(self, *a):
                pass

        self.srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.srv.server_address[1]
        threading.Thread(target=self.srv.serve_forever, daemon=True).start()

    def close(self):
        self.srv.shutdown()
        self.srv.server_close()


class FakeManager:
    """Just enough GatewayManager for a WorkerTier: router_states() is
    the publish payload; get() backs the wake-hint relay."""

    def __init__(self, states):
        self.states = states
        self.on_change = None
        self.waked = []

    def router_states(self):
        return list(self.states)

    def get(self, name):
        class _G:
            def note_external_demand(inner):
                self.waked.append(name)
        return _G()


def data_call(port, name="g", body=b"{}", headers=None, timeout=10):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", f"/api/v1/gateways/{name}/generate", body,
                     {"Content-Type": "application/json", **(headers or {})})
        resp = conn.getresponse()
        return resp.status, resp.getheaders(), json.loads(resp.read())
    finally:
        conn.close()


# ---------------------------------------------- policy parity (in-process)

def test_worker_router_slot_cap_and_least_queued(state):
    """Identical outcome to the gateway suite's slot-cap case: per-replica
    inflight never exceeds advertised slots, load splits least-queued."""
    seen = []
    hold = threading.Event()

    def transport(port, method, path, body, timeout):
        seen.append(port)
        hold.wait(2)
        return 200, b'{"code":200,"msg":"ok","data":{}}'

    publish(state, [rep(1001, slots=2), rep(1002, slots=2)], max_queue=32)
    r = workers.WorkerRouter(state, 0, transport=transport)
    done = []
    threads = [threading.Thread(target=lambda: done.append(
        r.forward("g", b"{}"))) for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.3)
    c = state.gateway_counters(0)
    assert c["inflight"][:2] == [2, 2]
    extra = threading.Thread(target=lambda: done.append(
        r.forward("g", b"{}")))
    extra.start()
    time.sleep(0.2)
    c = state.gateway_counters(0)
    assert c["inflight"][:2] == [2, 2]       # the 5th PARKED, cap held
    assert c["queued"] == 1
    hold.set()
    for t in threads + [extra]:
        t.join(5)
    assert len(done) == 5
    assert sorted(seen[:4]) == [1001, 1001, 1002, 1002]


def test_worker_router_queue_bound_sheds(state):
    hold = threading.Event()

    def transport(port, method, path, body, timeout):
        hold.wait(3)
        return 200, b'{"code":200,"msg":"ok","data":{}}'

    publish(state, [rep(1001, slots=1)], max_queue=2)
    r = workers.WorkerRouter(state, 0, transport=transport)
    threads = [threading.Thread(target=lambda: r.forward("g", b"{}"))
               for _ in range(3)]           # 1 in flight + 2 queued = full
    for t in threads:
        t.start()
    time.sleep(0.2)
    with pytest.raises(xerrors.GatewayShedError):
        r.forward("g", b"{}")
    assert state.gateway_counters(0)["shedTotal"] == 1
    hold.set()
    for t in threads:
        t.join(5)


def test_worker_router_priority_barges(state):
    """X-TDAPI-Priority high admits ahead of every parked best-effort
    request — the strict-priority FIFO contract, same as in-process."""
    order = []
    hold = threading.Event()

    def transport(port, method, path, body, timeout):
        order.append(bytes(body))
        if body == b"first":
            hold.wait(3)
        return 200, b'{"code":200,"msg":"ok","data":{}}'

    publish(state, [rep(1001, slots=1)], max_queue=16, deadline_ms=5000)
    r = workers.WorkerRouter(state, 0, transport=transport)
    threads = [threading.Thread(target=r.forward, args=("g", b"first"))]
    threads[0].start()
    time.sleep(0.1)
    for i in range(3):
        t = threading.Thread(target=r.forward, args=("g", b"low%d" % i))
        t.start()
        threads.append(t)
        time.sleep(0.05)
    t = threading.Thread(target=r.forward, args=("g", b"hi"),
                         kwargs={"priority": "high"})
    t.start()
    threads.append(t)
    time.sleep(0.15)
    hold.set()
    for t in threads:
        t.join(5)
    assert order[0] == b"first"
    assert order[1] == b"hi", order
    assert sorted(order[2:]) == [b"low0", b"low1", b"low2"]


def test_worker_router_deadline_504(state):
    publish(state, [], max_queue=8, deadline_ms=150)
    r = workers.WorkerRouter(state, 0,
                             transport=lambda *a: (200, b"{}"))
    t0 = time.monotonic()
    with pytest.raises(xerrors.GatewayDeadlineError):
        r.forward("g", b"{}")
    assert 0.1 <= time.monotonic() - t0 < 1.5


def test_worker_router_retries_dead_replica(state):
    calls = []

    def transport(port, method, path, body, timeout):
        calls.append(port)
        if port == 1001:
            raise ConnectionRefusedError("replica gone")
        return 200, b'{"code":200,"msg":"ok","data":{"ok":true}}'

    publish(state, [rep(1001, slots=4), rep(1002, slots=4)],
            deadline_ms=2000)
    r = workers.WorkerRouter(state, 0, transport=transport)
    status, payload = r.forward("g", b"{}")
    assert status == 200 and b'"ok"' in payload
    assert 1002 in calls
    # the error landed on the shared error counter (daemon-visible)
    g = 0
    assert state.load(workers._rep_cnt_off(g, 0) + 8) >= 1


def test_slot_reassignment_resets_counters(state):
    """A gateway deleted mid-request whose segment slot is reused by a
    NEW gateway must not bequeath phantom inflight: the publisher bumps
    the gen word AND zeroes the slot's counters + claim cells, and the
    old claim's release skips itself on the gen mismatch."""
    hold = threading.Event()

    def transport(port, method, path, body, timeout):
        hold.wait(5)
        return 200, b'{"code":200,"msg":"ok","data":{}}'

    publish(state, [rep(1001, slots=2)], name="old")
    r = workers.WorkerRouter(state, 0, transport=transport)
    t = threading.Thread(target=lambda: r.forward("old", b"{}"))
    t.start()
    deadline = time.time() + 5
    while time.time() < deadline and \
            state.gateway_counters(0)["inflight"][0] == 0:
        time.sleep(0.01)
    assert state.gateway_counters(0)["inflight"][0] == 1
    # delete "old" (its slot's name clears), then create "new" — the
    # publisher reuses the freed slot 0, which must change identity
    state.publish([])
    gen0 = state.load(workers._gw_cnt_off(0))
    publish(state, [rep(2002, slots=4)], name="new")
    assert state.load(workers._gw_cnt_off(0)) == gen0 + 1
    c = state.gateway_counters(0)
    assert sum(c["inflight"]) == 0 and c["queued"] == 0
    assert state.load(workers._wk_claim_off(0, 0, 0)) == 0
    # the old claim releases against the new tenant: gen mismatch ->
    # skipped, nothing goes negative or phantom
    hold.set()
    t.join(5)
    c = state.gateway_counters(0)
    assert sum(c["inflight"]) == 0 and c["queued"] == 0


def test_seqlock_readers_never_see_torn_roster(state):
    """Concurrent publishes vs readers: every read parses as ONE of the
    published rosters, never a mix (the seqlock contract)."""
    a = [{"name": "alpha", "maxQueue": 4, "deadlineMs": 1000,
          "replicas": [rep(1, 1), rep(2, 2)]}]
    b = [{"name": "alpha", "maxQueue": 9, "deadlineMs": 9000,
          "replicas": [rep(9, 9)]}]
    stop = threading.Event()
    bad = []

    def reader():
        while not stop.is_set():
            _, roster = state.read_roster()
            gw = roster.get("alpha")
            if gw is None:
                continue
            shape = (gw["maxQueue"], gw["deadlineMs"],
                     tuple((r["port"], r["slots"]) for r in gw["replicas"]))
            if shape not in ((4, 1000, ((1, 1), (2, 2))),
                             (9, 9000, ((9, 9),))):
                bad.append(shape)

    threads = [threading.Thread(target=reader) for _ in range(2)]
    for t in threads:
        t.start()
    for i in range(200):
        state.publish(a if i % 2 == 0 else b)
    stop.set()
    for t in threads:
        t.join(5)
    assert not bad, bad[:3]


def test_writer_killed_mid_publish_heals_on_republish(state):
    """A publisher SIGKILLed inside the seqlock window parks the epoch
    odd. Readers must bound their retry (paced by the backoff sleep,
    refusing the in-progress window rather than parsing through it) and
    the daemon's 250ms heal republish must recover them — i.e. publish
    is re-enterable from a crashed-odd epoch. Pinned by tdcheck's
    seqlock kill sweep (tools/tdcheck); this is the deterministic
    single-schedule twin."""
    publish(state, [rep(1001, slots=1)])
    epoch = state.load(workers.HDR_OFF_EPOCH)
    assert epoch % 2 == 0
    # park the epoch odd, exactly as a kill inside the window would
    state.store(workers.HDR_OFF_EPOCH, epoch + 1)
    sleeps = []
    orig_sleep = workers.time.sleep

    def counting_sleep(s):
        sleeps.append(s)
        orig_sleep(s)

    workers.time.sleep = counting_sleep
    got = []
    t = threading.Thread(target=lambda: got.append(state.read_roster()))
    t.start()
    try:
        # orig_sleep: workers.time IS the global time module, so the
        # test's own pacing must not feed the counter it asserts on
        orig_sleep(0.25)
        # the reader is retrying, paced — not parsing the torn window,
        # not busy-spinning
        assert not got, "reader parsed a roster through an odd epoch"
        assert sleeps, "reader busy-spins instead of pacing its retry"
        # the heal: republish onto the crashed-odd epoch
        publish(state, [rep(2002, slots=4)], max_queue=7)
        t.join(5)
        assert got, "reader wedged after the heal republish"
    finally:
        workers.time.sleep = orig_sleep
        t.join(1)
    _, roster = got[0]
    gw = roster["g"]
    # the recovered read is CONSISTENT: entirely the healed roster
    assert gw["maxQueue"] == 7
    assert gw["replicas"][0]["port"] == 2002
    # and the heal left the epoch even — the next reader needs no retry
    assert state.load(workers.HDR_OFF_EPOCH) % 2 == 0


# ------------------------------------------------- e2e over SO_REUSEPORT

@pytest.fixture()
def stub():
    s = StubReplica()
    yield s
    s.close()


def test_tier_e2e_kernel_balanced_and_shed_codes(stub):
    """Two real worker processes on one port: requests serve through
    either, queue-full sheds HTTP 429 + Retry-After on the wire, and the
    worker /healthz answers."""
    mgr = FakeManager([{"name": "g", "maxQueue": 1, "deadlineMs": 3000,
                        "replicas": [rep(stub.port, slots=2)]}])
    tier = workers.WorkerTier(mgr, n=2)
    tier.start()
    try:
        deadline = time.time() + 15
        while time.time() < deadline:
            try:
                status, _, out = data_call(tier.port)
                if out.get("code") == 200:
                    break
            except OSError:
                time.sleep(0.05)
        assert out["code"] == 200, out
        for _ in range(10):
            _, _, out = data_call(tier.port)
            assert out["code"] == 200
        # saturate: hold the replica, fill both slots + the 1-queue
        stub.hold.clear()
        parked = [threading.Thread(target=data_call, args=(tier.port,))
                  for _ in range(3)]
        for t in parked:
            t.start()
        time.sleep(0.4)
        status, headers, out = data_call(tier.port)
        assert out["code"] == 429 and status == 429
        assert any(k.lower() == "retry-after" for k, _ in headers)
        stub.hold.set()
        for t in parked:
            t.join(10)
        assert stub.peak <= 2, f"slot cap violated: peak {stub.peak}"
        # worker healthz
        conn = http.client.HTTPConnection("127.0.0.1", tier.port,
                                          timeout=5)
        conn.request("GET", "/api/v1/healthz")
        hz = json.loads(conn.getresponse().read())
        conn.close()
        assert hz["data"]["gateways"] == ["g"]
    finally:
        tier.stop()


def test_tier_worker_kill_mid_request_reconciles(stub):
    """SIGKILL the ONLY worker while it holds the replica's single slot:
    the watchdog respawns it and reconciles the orphaned claim, so the
    slot is usable again — and the replica never saw over-cap admits."""
    mgr = FakeManager([{"name": "g", "maxQueue": 8, "deadlineMs": 4000,
                        "replicas": [rep(stub.port, slots=1)]}])
    tier = workers.WorkerTier(mgr, n=1)
    tier.start()
    try:
        deadline = time.time() + 15
        while time.time() < deadline:
            try:
                _, _, out = data_call(tier.port)
                if out.get("code") == 200:
                    break
            except OSError:
                time.sleep(0.05)
        assert out["code"] == 200
        # a request that will HOLD the slot, then SIGKILL its worker
        stub.hold.clear()
        t = threading.Thread(target=lambda: data_call(tier.port,
                                                      timeout=3))
        t.start()
        deadline = time.time() + 5
        while time.time() < deadline and stub.inflight == 0:
            time.sleep(0.02)
        assert stub.inflight == 1
        assert state_inflight(tier) == 1
        tier.procs[0].kill()
        t.join(10)
        stub.hold.set()
        # respawn + reconcile: claim subtracted, slot free again
        deadline = time.time() + 10
        while time.time() < deadline:
            if tier.respawns >= 1 and state_inflight(tier) == 0:
                break
            time.sleep(0.05)
        assert tier.respawns >= 1
        assert state_inflight(tier) == 0, "orphaned claim never reconciled"
        assert tier.reclaimed_claims >= 1
        # the respawned worker serves with the FULL slot again
        deadline = time.time() + 10
        out = {}
        while time.time() < deadline:
            try:
                _, _, out = data_call(tier.port)
                if out.get("code") == 200:
                    break
            except OSError:
                time.sleep(0.05)
        assert out.get("code") == 200, out
        assert stub.peak <= 1, f"double admit: replica saw {stub.peak}"
    finally:
        tier.stop()


def state_inflight(tier) -> int:
    return sum(tier.state.gateway_counters(0)["inflight"])


def test_tier_graceful_drain_completes_inflight(stub):
    """stop() SIGTERMs workers, which drain: a request in flight when the
    tier stops still gets its 200."""
    mgr = FakeManager([{"name": "g", "maxQueue": 8, "deadlineMs": 8000,
                        "replicas": [rep(stub.port, slots=2)]}])
    tier = workers.WorkerTier(mgr, n=1)
    tier.start()
    deadline = time.time() + 15
    while time.time() < deadline:
        try:
            _, _, out = data_call(tier.port)
            if out.get("code") == 200:
                break
        except OSError:
            time.sleep(0.05)
    assert out["code"] == 200
    stub.hold.clear()
    results = []

    def slow():
        try:
            results.append(data_call(tier.port, timeout=15)[2]["code"])
        except Exception as e:  # noqa: BLE001
            results.append(repr(e))

    t = threading.Thread(target=slow)
    t.start()
    deadline = time.time() + 5
    while time.time() < deadline and stub.inflight == 0:
        time.sleep(0.02)
    releaser = threading.Timer(0.5, stub.hold.set)
    releaser.start()
    tier.stop(drain_timeout=10)
    t.join(15)
    assert results == [200], results


# ----------------------------------------------------- App-level wiring

def test_app_wires_worker_tier_and_wakes(tmp_path, stub):
    """TDAPI_GW_WORKERS via App arg: the tier starts with the App,
    /healthz reports it, the data port serves a REAL gateway's roster
    (replica port patched onto the stub), and App.stop() drains it."""
    from gpu_docker_api_tpu.gateway import READY, GatewayConfig
    from gpu_docker_api_tpu.server.app import App
    from gpu_docker_api_tpu.topology import make_topology

    app = App(state_dir=str(tmp_path / "state"), backend="mock",
              addr="127.0.0.1:0", port_range=(47000, 47100),
              topology=make_topology("v5p-8"), api_key="", cpu_cores=8,
              store_maint_records=0, gw_workers=2)
    app.start()
    try:
        assert app.workers is not None
        app.gateways.create(GatewayConfig(
            name="gw", image="img", cmd=["serve"],
            minReplicas=1, maxReplicas=2, readiness="running",
            scaleDownIdleS=3600, deadlineMs=4000, maxQueue=16))
        gw = app.gateways.get("gw")
        deadline = time.time() + 10
        while time.time() < deadline and not any(
                r.state is READY for r in gw.replicas.values()):
            time.sleep(0.05)
        # the mock substrate's replica isn't a real server: point the
        # roster at the stub and republish
        with gw._cond:
            for r in gw.replicas.values():
                r.host_port = stub.port
        app.workers.poke()
        deadline = time.time() + 10
        out = {}
        while time.time() < deadline:
            try:
                _, _, out = data_call(app.workers.port, name="gw")
                if out.get("code") == 200:
                    break
            except OSError:
                pass
            time.sleep(0.05)
        assert out.get("code") == 200, out
        # healthz reports the tier, with data-plane counters
        conn = http.client.HTTPConnection("127.0.0.1", app.server.port,
                                          timeout=10)
        conn.request("GET", "/api/v1/healthz")
        hz = json.loads(conn.getresponse().read())["data"]
        conn.close()
        assert hz["workers"]["count"] == 2
        assert hz["workers"]["port"] == app.workers.port
        assert hz["workers"]["gateways"]["gw"]["requestsTotal"] >= 1
    finally:
        app.stop()
    assert app.workers.state is None        # segment closed + unlinked
