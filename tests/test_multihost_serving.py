"""Multi-host SERVING + spanning patch/rollback e2e (VERDICT r3 weak #6).

Round 3 proved the env contract forms a live 2-process TRAINING cluster
(test_multihost.py); this file closes the serving half and the worker-set-
change half:

1. serving: a spanning grant's env launches serve.py on every worker; the
   processes form one mesh, rank 0 owns the HTTP endpoint, and every
   request runs as ONE lock-step sharded generate across both processes —
   the reply must equal the single-process greedy stream bit-for-bit.
2. spanning patch/rollback: a training replicaSet's grant is patched to a
   DIFFERENT worker set (2 -> 4 workers) and rolled back (4 -> 2); after
   each change the new cluster re-forms at the new process count and
   RESUMES from the orbax checkpoint (abstract-template restore reshards
   onto the new mesh).

CPU stands in for the chips (virtual devices per process); the contract
path — TPU_WORKER_* env -> jax.distributed -> global mesh — is the same
one a real TPU pod slice uses.
"""

import http.client
import json
import os
import socket
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SERVE_SCRIPT = r"""
import sys
from gpu_docker_api_tpu.workloads.serve import main
sys.exit(main(["--family", "llama", "--config", "tiny",
               "--tp", "2", "--host", "127.0.0.1", "--port", sys.argv[1]]))
"""

TRAIN_ARGS = ["--family", "llama", "--config", "tiny", "--batch", "8",
              "--seq", "32", "--tp", "2", "--checkpoint-every", "1"]

TRAIN_SCRIPT = r"""
import sys
from gpu_docker_api_tpu.workloads.train_llama import main
sys.exit(main(sys.argv[1:]))
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _call(port, method, path, body=None, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request(method, path,
                 json.dumps(body) if body is not None else None,
                 {"Content-Type": "application/json"})
    out = json.loads(conn.getresponse().read())
    conn.close()
    assert out["code"] == 200, out
    return out["data"]


def _launch_workers(multihost, tmp_path, script, script_args,
                    devices_per_proc, coord_port, tag):
    """One REAL process per granted worker, with the granted env — the
    operator's per-worker launcher role (same harness as
    test_multihost.py)."""
    script_path = tmp_path / f"{tag}.py"
    script_path.write_text(script)
    procs = []
    for w, contract in sorted(multihost.items()):
        env = dict(os.environ)
        env.update(contract)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "XLA_FLAGS":
                f"--xla_force_host_platform_device_count={devices_per_proc}",
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{coord_port}",
            "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""),
        })
        log = open(tmp_path / f"{tag}-{w}.log", "wb")
        procs.append((w, log, subprocess.Popen(
            [sys.executable, str(script_path), *script_args], env=env,
            stdout=log, stderr=subprocess.STDOUT)))
    return procs


def _wait_all(procs, timeout=420):
    for w, log, p in procs:
        p.wait(timeout=timeout)
        log.close()
        out = open(log.name, "rb").read().decode(errors="replace")
        assert p.returncode == 0, f"worker {w}: {out[-3000:]}"


def _kill_all(procs):
    for _, log, p in procs:
        if p.poll() is None:
            p.kill()
            p.wait(timeout=30)
        log.close()


def _wait_healthz(serve_port, procs, timeout=300):
    """Poll rank 0's /healthz until it answers (surfacing worker logs if
    any process dies first). Returns the health data."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            return _call(serve_port, "GET", "/healthz", timeout=5)
        except (ConnectionError, OSError, AssertionError):
            if any(p.poll() is not None for _, _, p in procs):
                _wait_all(procs, timeout=5)   # surfaces worker logs
            time.sleep(0.5)
    raise AssertionError("rank 0 endpoint never came up")


def _reference_streams(prompts, max_new):
    """Single-process greedy streams for the same init seed — the
    bit-equality oracle for every serving test."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from gpu_docker_api_tpu.infer import generate
    from gpu_docker_api_tpu.models.llama import LlamaConfig
    from gpu_docker_api_tpu.parallel.mesh import MeshPlan
    from gpu_docker_api_tpu.train import Trainer

    cfg = LlamaConfig.tiny()
    trainer = Trainer.create(cfg, MeshPlan(), devices=jax.devices()[:1])
    params = trainer.init(jax.random.key(0))["params"]
    return [np.asarray(generate(
        params, jnp.asarray([p], jnp.int32), cfg,
        max_new))[0].tolist() for p in prompts]


def _spanning_grant(app_port, name, tpu_count):
    _call(app_port, "POST", "/api/v1/replicaSet", {
        "imageName": "x", "replicaSetName": name, "tpuCount": tpu_count})
    return _call(app_port, "GET",
                 f"/api/v1/replicaSet/{name}")["info"]["multihost"]


@pytest.fixture()
def app(tmp_path):
    from gpu_docker_api_tpu.server.app import App
    from gpu_docker_api_tpu.topology import make_topology

    a = App(state_dir=str(tmp_path / "state"), backend="mock",
            addr="127.0.0.1:0", topology=make_topology("v5p-32"),
            api_key="")
    a.start()
    yield a
    a.stop()


def test_multihost_serving_lock_step(app, tmp_path):
    """Two processes serve ONE tiny llama over a tp=2 global mesh; the
    REST reply equals the single-process greedy stream exactly."""
    multihost = _spanning_grant(app.server.port, "servepod", 8)
    assert sorted(multihost) == ["0", "1"]

    serve_port = _free_port()
    procs = _launch_workers(multihost, tmp_path, SERVE_SCRIPT,
                            [str(serve_port)], devices_per_proc=4,
                            coord_port=_free_port(), tag="serve")
    try:
        health = _wait_healthz(serve_port, procs)
        assert health["model"] == "llama/tiny"

        prompt = [3, 7, 1, 9, 4, 2]
        got = _call(serve_port, "POST", "/generate",
                    {"tokens": [prompt], "max_new": 8},
                    timeout=120)["tokens"]
        (want,) = _reference_streams([prompt], 8)
        assert got == [want]

        # second request exercises the engine loop (not just one round)
        got2 = _call(serve_port, "POST", "/generate",
                     {"tokens": [prompt], "max_new": 8},
                     timeout=120)["tokens"]
        assert got2 == [want]
    finally:
        _kill_all(procs)


BATCH_SERVE_SCRIPT = r"""
import sys
from gpu_docker_api_tpu.workloads.serve import main
sys.exit(main(["--family", "llama", "--config", "tiny",
               "--tp", "2", "--batch-slots", "4", "--decode-chunk", "8",
               "--batch-prefill-chunk", "4",
               "--host", "127.0.0.1", "--port", sys.argv[1]]))
"""


def test_multihost_batched_serving_concurrent_streams(app, tmp_path):
    """Lock-step CONTINUOUS BATCHING across two processes (VERDICT r4
    next #6): rank 0 broadcasts each tick's admissions and every rank
    runs the identical slot-step. Four concurrent streams must each be
    bit-equal to the single-process greedy stream, and their aggregate
    wall time must beat the SAME engine serving the same four requests
    one at a time (single-flight) by > 1.5x — batching shares decode
    steps; serialization pays them per stream."""
    import time as _time
    from concurrent.futures import ThreadPoolExecutor

    multihost = _spanning_grant(app.server.port, "batchpod", 8)
    serve_port = _free_port()
    procs = _launch_workers(multihost, tmp_path, BATCH_SERVE_SCRIPT,
                            [str(serve_port)], devices_per_proc=4,
                            coord_port=_free_port(), tag="bserve")
    try:
        health = _wait_healthz(serve_port, procs)
        assert health["batching"]["slots"] == 4

        prompts = [[3, 7, 1, 9, 4, 2], [5, 1, 8, 2, 6, 4],
                   [2, 2, 6, 4, 1, 1, 3, 5, 9], [9, 8, 7, 6, 5, 4]]
        max_new = 24
        want = _reference_streams(prompts, max_new)

        def ask(p):
            return _call(serve_port, "POST", "/generate",
                         {"tokens": [p], "max_new": max_new},
                         timeout=240)["tokens"][0]

        # warm-up: compile every program (per-length prefill + chunked
        # decode) so neither timed phase pays XLA compiles
        for p in prompts:
            assert ask(p) == want[prompts.index(p)]

        # single-flight baseline: same engine, one request in flight
        t0 = _time.perf_counter()
        seq = [ask(p) for p in prompts]
        t_seq = _time.perf_counter() - t0

        # concurrent: all four share decode steps
        ex = ThreadPoolExecutor(4)
        try:
            t0 = _time.perf_counter()
            futs = [ex.submit(ask, p) for p in prompts]
            conc = [f.result(timeout=240) for f in futs]
            t_conc = _time.perf_counter() - t0
        finally:
            ex.shutdown(wait=True)

        for got, w in zip(seq, want):
            assert got == w
        for got, w in zip(conc, want):
            assert got == w
        speedup = t_seq / t_conc
        assert speedup > 1.5, (
            f"aggregate concurrent speedup {speedup:.2f}x "
            f"(seq {t_seq:.2f}s, conc {t_conc:.2f}s)")
    finally:
        _kill_all(procs)


PAGED_SERVE_SCRIPT = r"""
import sys
from gpu_docker_api_tpu.workloads.serve import main
sys.exit(main(["--family", "llama", "--config", "tiny",
               "--tp", "2", "--batch-slots", "4", "--batch-max-len", "64",
               "--decode-chunk", "8", "--batch-prefill-chunk", "4",
               "--kv-block", "8", "--kv-pool", "14", "--kv-quant",
               "--prefix-cache", "2",
               "--host", "127.0.0.1", "--port"] + sys.argv[1:]))
"""


def _reference_paged_batcher_streams(prompts, max_new):
    """Single-process batcher with the IDENTICAL composition flags — the
    bit-equality oracle for the multihost paged test (sequential submits:
    block placement differs, values must not)."""
    import jax
    import jax.numpy as jnp
    from gpu_docker_api_tpu.models.llama import LlamaConfig
    from gpu_docker_api_tpu.parallel.mesh import MeshPlan
    from gpu_docker_api_tpu.train import Trainer
    from gpu_docker_api_tpu.workloads.serve import _Batcher

    cfg = LlamaConfig.tiny()
    trainer = Trainer.create(cfg, MeshPlan(), devices=jax.devices()[:1])
    params = trainer.init(jax.random.key(0))["params"]
    b = _Batcher(cfg, params, slots=4, max_len=64, prefill_chunk=4,
                 prefix_cache=2, kv_quant=True, kv_block=8,
                 kv_pool_blocks=14, decode_chunk=8)
    try:
        return [b.submit(jnp.asarray(p, jnp.int32), max_new)
                for p in prompts]
    finally:
        b.close()


@pytest.mark.parametrize("shard_kv", [False, True],
                         ids=["replicated", "shard-kv"])
def test_multihost_paged_prefix_kv8_lock_step(app, tmp_path, shard_kv):
    """The single-host serving compositions ride the lock-step batcher
    (round-5 closure of the 'dense only' scope note): paged KV with a
    pool SMALL enough to force head-of-line parking, in-flight prefix
    sharing + the prefix store, and int8 KV — across two real
    processes, in BOTH cache layouts (the default replicated pool and
    --shard-kv's tp-sharded one; the oracle batcher runs unsharded
    single-process either way, so equality also pins that sharding
    never changes a stream). Every rank replays the same
    admission/parking/share decisions from the broadcast pending list,
    so each stream must be bit-equal to an identically-configured
    single-process batcher."""
    from concurrent.futures import ThreadPoolExecutor

    multihost = _spanning_grant(app.server.port,
                                f"pagedpod{int(shard_kv)}", 8)
    serve_port = _free_port()
    procs = _launch_workers(
        multihost, tmp_path, PAGED_SERVE_SCRIPT,
        [str(serve_port)] + (["--shard-kv"] if shard_kv else []),
        devices_per_proc=4, coord_port=_free_port(),
        tag=f"pserve{int(shard_kv)}")
    try:
        health = _wait_healthz(serve_port, procs)
        assert health["batching"]["paged"] == {
            "blockSize": 8, "poolBlocks": 14, "freeBlocks": 13}

        # 9-token common prefix (one full 8-token block usable) +
        # distinct tails; 12 + 24 tokens = 5 blocks/request unshared, so
        # a 13-free-block pool forces at least one concurrent request to
        # park until an earlier stream frees its blocks
        base = [5, 3, 8, 1, 9, 2, 7, 4, 6]
        prompts = [base + t for t in
                   ([11, 12, 13], [11, 14, 15], [16, 17, 18], [19, 20, 21])]
        max_new = 24
        want = _reference_paged_batcher_streams(prompts, max_new)

        def ask(p):
            return _call(serve_port, "POST", "/generate",
                         {"tokens": [p], "max_new": max_new},
                         timeout=240)["tokens"][0]

        ex = ThreadPoolExecutor(4)
        try:
            futs = [ex.submit(ask, p) for p in prompts]
            got = [f.result(timeout=240) for f in futs]
        finally:
            ex.shutdown(wait=True)
        for g, w in zip(got, want):
            assert g == w

        # the composition actually engaged: blocks were shared (in-flight
        # donors and/or the prefix store), and the pool drained back —
        # only stored prefixes still hold references
        health = _call(serve_port, "GET", "/healthz")
        assert health["batching"]["prefixHits"] >= 1
        paged = health["batching"]["paged"]
        assert paged["freeBlocks"] >= 11    # <= 2 stored 1-block prefixes

        # a second pass over one prompt must hit the prefix STORE (its
        # full first block re-enters the new page table zero-copy) and
        # stay bit-equal
        assert ask(prompts[0]) == want[0]
        health = _call(serve_port, "GET", "/healthz")
        assert health["batching"]["prefixHits"] >= 2
    finally:
        _kill_all(procs)


def test_multihost_batched_rank_death_fails_fast(app, tmp_path):
    """Failure detection for the lock-step batched engine (SURVEY §5.3
    on the round-5 surface): SIGKILL a follower mid-serve. Measured
    semantics this test pins: rank 0's next collective errors on the
    broken connection (no heartbeat wait), _fail_all releases every
    waiter — so clients see an error in seconds, never a hang — and
    rank 0 then EXITS nonzero (the jax.distributed shutdown barrier
    holds it for the ~60s heartbeat timeout first), so a pod-level
    supervisor observes the death and can restart the pod."""
    multihost = _spanning_grant(app.server.port, "crashpod", 8)
    serve_port = _free_port()
    procs = _launch_workers(multihost, tmp_path, BATCH_SERVE_SCRIPT,
                            [str(serve_port)], devices_per_proc=4,
                            coord_port=_free_port(), tag="crash")
    try:
        _wait_healthz(serve_port, procs)
        ok = _call(serve_port, "POST", "/generate",
                   {"tokens": [[3, 7, 1]], "max_new": 4},
                   timeout=240)["tokens"]
        assert len(ok[0]) == 4

        by_id = {w: p for w, _, p in procs}
        by_id["1"].kill()

        # a request against the dead pod must FAIL (error envelope or
        # dropped connection), and must do so fast — a hang here means
        # a waiter parked on an event nobody will set
        t0 = time.time()
        served = None
        try:
            # raw call (no envelope assert): a 500 envelope also counts
            # as the failure surfacing
            conn = http.client.HTTPConnection("127.0.0.1", serve_port,
                                              timeout=60)
            conn.request("POST", "/generate", json.dumps(
                {"tokens": [[3, 7, 1]], "max_new": 4}),
                {"Content-Type": "application/json"})
            body = json.loads(conn.getresponse().read())
            conn.close()
            if body.get("code") == 200:
                served = body
        except (ConnectionError, OSError, http.client.HTTPException,
                json.JSONDecodeError):
            pass
        assert served is None, f"request served by a dead pod: {served}"
        assert time.time() - t0 < 45, "post-death request hung"

        # rank 0 exits NONZERO once the distributed shutdown resolves
        rc = by_id["0"].wait(timeout=180)
        assert rc != 0, "rank 0 exited 0 after losing a follower"
    finally:
        _kill_all(procs)


SHARDKV_SERVE_SCRIPT = r"""
import sys
from gpu_docker_api_tpu.workloads.serve import main
sys.exit(main(["--family", "llama", "--config", "tiny",
               "--tp", "2", "--batch-slots", "3", "--batch-max-len", "64",
               "--decode-chunk", "4", "--shard-kv",
               "--host", "127.0.0.1", "--port", sys.argv[1]]))
"""


def test_multihost_sharded_kv_lock_step(app, tmp_path):
    """--shard-kv: the slot cache's K/V shard over tp on the kv-head
    axis instead of replicating (per-rank cache HBM / tp). Attention
    runs each rank's own heads (q is already head-sharded by the
    megatron wq), so streams must stay bit-equal to the single-process
    dense engine; the dryrun's S4 plan pins the HLO communication
    shape, this test pins the live 2-process engine."""
    from concurrent.futures import ThreadPoolExecutor

    multihost = _spanning_grant(app.server.port, "skvpod", 8)
    serve_port = _free_port()
    procs = _launch_workers(multihost, tmp_path, SHARDKV_SERVE_SCRIPT,
                            [str(serve_port)], devices_per_proc=4,
                            coord_port=_free_port(), tag="kserve")
    try:
        health = _wait_healthz(serve_port, procs)
        assert health["batching"]["slots"] == 3

        prompts = [[3, 7, 1, 9, 4, 2], [5, 1, 8, 2, 6, 4, 9, 9],
                   [2, 2, 6, 4, 1, 1, 3]]
        max_new = 16
        want = _reference_streams(prompts, max_new)

        def ask(p):
            return _call(serve_port, "POST", "/generate",
                         {"tokens": [p], "max_new": max_new},
                         timeout=240)["tokens"][0]

        ex = ThreadPoolExecutor(3)
        try:
            futs = [ex.submit(ask, p) for p in prompts]
            got = [f.result(timeout=240) for f in futs]
        finally:
            ex.shutdown(wait=True)
        for g, w in zip(got, want):
            assert g == w
    finally:
        _kill_all(procs)


SPEC_SERVE_SCRIPT = r"""
import sys
from gpu_docker_api_tpu.workloads.serve import main
sys.exit(main(["--family", "llama", "--config", "tiny",
               "--tp", "2", "--batch-slots", "3", "--batch-max-len", "64",
               "--batch-prefill-chunk", "4",
               "--draft-config", "tiny", "--gamma", "3",
               "--kv-block", "8", "--kv-quant", "--shard-kv",
               "--host", "127.0.0.1", "--port", sys.argv[1]]))
"""


def test_multihost_speculative_paged_lock_step(app, tmp_path):
    """Speculative decoding INSIDE the lock-step batcher, over the paged
    int8 TP-SHARDED target cache (--shard-kv: the full composition
    stack), across two real processes: every rank runs the same draft
    rounds + shared sharded verify, and the accept/rollback decisions
    replay identically from SPMD device results. Greedy spec is
    bit-exact by construction, so the oracle is the single-process
    NON-speculative unsharded batcher with the same cache flags —
    equality proves the whole multihost spec stack emits exactly the
    target-only streams. The fresh-init draft uses a different key than
    the target (worst-case proposals), so rejection/rollback paths
    really run."""
    from concurrent.futures import ThreadPoolExecutor

    import jax
    import jax.numpy as jnp
    from gpu_docker_api_tpu.models.llama import LlamaConfig
    from gpu_docker_api_tpu.parallel.mesh import MeshPlan
    from gpu_docker_api_tpu.train import Trainer
    from gpu_docker_api_tpu.workloads.serve import _Batcher

    multihost = _spanning_grant(app.server.port, "specpod", 8)
    serve_port = _free_port()
    procs = _launch_workers(multihost, tmp_path, SPEC_SERVE_SCRIPT,
                            [str(serve_port)], devices_per_proc=4,
                            coord_port=_free_port(), tag="sserve")
    try:
        health = _wait_healthz(serve_port, procs)
        assert health["batching"]["speculative"]["gamma"] == 3

        prompts = [[3, 7, 1, 9, 4, 2], [5, 1, 8, 2, 6, 4, 9, 9],
                   [2, 2, 6, 4, 1, 1, 3]]
        max_new = 20

        cfg = LlamaConfig.tiny()
        trainer = Trainer.create(cfg, MeshPlan(), devices=jax.devices()[:1])
        params = trainer.init(jax.random.key(0))["params"]
        oracle = _Batcher(cfg, params, slots=3, max_len=64,
                          prefill_chunk=4, kv_quant=True, kv_block=8)
        try:
            want = [oracle.submit(jnp.asarray(p, jnp.int32), max_new)
                    for p in prompts]
        finally:
            oracle.close()

        def ask(p):
            return _call(serve_port, "POST", "/generate",
                         {"tokens": [p], "max_new": max_new},
                         timeout=240)["tokens"][0]

        ex = ThreadPoolExecutor(3)
        try:
            futs = [ex.submit(ask, p) for p in prompts]
            got = [f.result(timeout=240) for f in futs]
        finally:
            ex.shutdown(wait=True)
        for g, w in zip(got, want):
            assert g == w

        spec = _call(serve_port, "GET", "/healthz")["batching"]["speculative"]
        assert spec["rounds"] > 0 and spec["emitted"] > 0
        # a key(1) draft against a key(0) target proposes near-noise:
        # some proposals must have been rejected (rollback paths ran)
        assert spec["accepted"] < spec["proposed"]
    finally:
        _kill_all(procs)


def test_spanning_patch_and_rollback_cluster_reforms(app, tmp_path):
    """Patch 8 -> 16 chips (2 -> 4 workers), then roll back: after each
    worker-set change the relaunched cluster resumes training from the
    checkpoint at the NEW process count (orbax abstract-template restore
    reshards onto the new mesh)."""
    workdir = tmp_path / "work"
    workdir.mkdir()
    args = TRAIN_ARGS + ["--workdir", str(workdir)]

    multihost = _spanning_grant(app.server.port, "pod", 8)
    assert len(multihost) == 2
    procs = _launch_workers(multihost, tmp_path, TRAIN_SCRIPT,
                            args + ["--steps", "2"], devices_per_proc=4,
                            coord_port=_free_port(), tag="t1")
    _wait_all(procs)

    # PATCH to 16 chips: the new version's grant spans 4 workers
    patched = _call(app.server.port, "PATCH", "/api/v1/replicaSet/pod",
                    {"tpuPatch": {"tpuCount": 16}})
    assert patched["version"] == 2 and len(patched["tpuChips"]) == 16
    multihost4 = _call(app.server.port, "GET",
                       "/api/v1/replicaSet/pod")["info"]["multihost"]
    assert len(multihost4) == 4

    procs = _launch_workers(multihost4, tmp_path, TRAIN_SCRIPT,
                            args + ["--steps", "4"], devices_per_proc=2,
                            coord_port=_free_port(), tag="t2")
    _wait_all(procs)
    log2 = (tmp_path / "t2-0.log").read_bytes().decode(errors="replace")
    assert "resumed from checkpoint step 2" in log2

    # ROLLBACK to version 1: grant shrinks back to the 2-worker spec
    rolled = _call(app.server.port, "PATCH",
                   "/api/v1/replicaSet/pod/rollback", {"version": 1})
    assert len(rolled["tpuChips"]) == 8
    multihost2 = _call(app.server.port, "GET",
                       "/api/v1/replicaSet/pod")["info"]["multihost"]
    assert len(multihost2) == 2

    procs = _launch_workers(multihost2, tmp_path, TRAIN_SCRIPT,
                            args + ["--steps", "6"], devices_per_proc=4,
                            coord_port=_free_port(), tag="t3")
    _wait_all(procs)
    log3 = (tmp_path / "t3-0.log").read_bytes().decode(errors="replace")
    assert "resumed from checkpoint step 4" in log3

    # the metrics stream is continuous across all three cluster shapes
    # (every rank appends to the shared workdir, so steps appear once per
    # process — the SET must be exactly the 6 steps, no gap, no restart)
    steps = [json.loads(line).get("step")
             for line in (workdir / "metrics.jsonl").read_text()
             .strip().splitlines()]
    steps = [s for s in steps if s is not None]
    assert set(steps) == {1, 2, 3, 4, 5, 6}
